"""Content-addressed compile store (perf/compile_store.py): fence
semantics, corruption quarantine, crash consistency under kill -9
mid-``put`` (the checkpoint sweep idiom), and the compile-cache
routing that hands the store's fenced xla/ plane to JAX (ISSUE 18
satellite — the zero-cold-start substrate the serving fleet rides)."""
import json
import os
import subprocess
import sys
import time
import zlib
from pathlib import Path

from deeplearning4j_tpu.perf.compile_store import (CompileStore,
                                                   CORRUPT_DIR,
                                                   ENTRY_SUFFIX,
                                                   MAGIC,
                                                   from_env,
                                                   program_fingerprint)

REPO = Path(__file__).resolve().parent.parent


# =========================================================================
# fingerprint + round trip
# =========================================================================

def test_fingerprint_stable_and_order_insensitive():
    a = program_fingerprint(buckets=[8, 16], block=8, spec_k=2)
    b = program_fingerprint(spec_k=2, block=8, buckets=[8, 16])
    assert a == b and len(a) == 64
    assert a != program_fingerprint(buckets=[8, 32], block=8, spec_k=2)


def test_put_get_roundtrip_and_counters(tmp_path):
    store = CompileStore(tmp_path, jaxlib="1.0", topology="cpu")
    fp = program_fingerprint(model="m", buckets=[8])
    assert store.get(fp) is None                      # cold miss
    path = store.put(fp, b"payload-bytes")
    assert path.is_file() and path.suffix == ENTRY_SUFFIX
    assert store.get(fp) == b"payload-bytes"
    # overwrite publishes atomically over the old entry
    store.put(fp, b"v2")
    assert store.get(fp) == b"v2"
    c = store.counters()
    assert c["puts"] == 2 and c["hits"] == 2
    assert c["misses"] == 1 and c["quarantined"] == 0
    stats = store.stats()
    assert stats["objects"] == 1 and stats["fence"] == store.fence


def test_fence_mismatch_is_miss_not_damage(tmp_path):
    """A different jaxlib/topology reads a disjoint keyspace, and even
    a same-key entry whose header names another universe is a miss
    left IN PLACE — never quarantined (it is not damage)."""
    fp = program_fingerprint(model="m")
    old = CompileStore(tmp_path, jaxlib="0.4.36", topology="cpu")
    old.put(fp, b"old-binary-artifact")
    new = CompileStore(tmp_path, jaxlib="0.5.0", topology="cpu")
    assert new.fence != old.fence
    assert new.get(fp) is None                        # disjoint key
    assert old.get(fp) == b"old-binary-artifact"      # untouched
    # force a same-path fence-field mismatch: copy the old entry to
    # the new fence's path for this key
    new.entry_path(fp).write_bytes(old.entry_path(fp).read_bytes())
    assert new.get(fp) is None
    assert new.counters()["quarantined"] == 0
    assert new.entry_path(fp).is_file()               # left in place


def _corrupt(path: Path, mutate):
    path.write_bytes(mutate(path.read_bytes()))


def test_corrupt_entries_quarantined_then_recompile_path(tmp_path):
    """Every damage class (bad magic, truncated header, unparseable
    header, payload crc/size mismatch) is quarantined to
    ``<fence>/corrupt/`` and reported as a miss; a fresh ``put``
    (the recompile fallback) restores service on the same key."""
    store = CompileStore(tmp_path, jaxlib="1.0", topology="cpu")
    cases = [
        ("magic", lambda b: b"XXXX" + b[4:]),
        ("trunc", lambda b: b[:len(MAGIC) + 3]),
        ("header", lambda b: b.replace(MAGIC, MAGIC + b"not json", 1)),
        ("crc", lambda b: b[:-2] + bytes([b[-2] ^ 0xFF]) + b[-1:]),
    ]
    for i, (name, mutate) in enumerate(cases):
        fp = program_fingerprint(case=name)
        store.put(fp, b"payload-%d" % i + b"x" * 64)
        _corrupt(store.entry_path(fp), mutate)
        assert store.get(fp) is None, name
        assert not store.entry_path(fp).exists(), name
        # recompile fallback: the key serves again
        store.put(fp, b"recompiled")
        assert store.get(fp) == b"recompiled", name
    assert store.counters()["quarantined"] == len(cases)
    quarantined = list((store.fence_dir / CORRUPT_DIR).iterdir())
    assert len(quarantined) == len(cases)             # evidence kept


def test_quarantine_never_clobbers_prior_evidence(tmp_path):
    store = CompileStore(tmp_path, jaxlib="1.0", topology="cpu")
    fp = program_fingerprint(case="twice")
    for _ in range(2):
        store.put(fp, b"p" * 32)
        _corrupt(store.entry_path(fp), lambda b: b"XXXX" + b[4:])
        assert store.get(fp) is None
    names = [p.name for p in (store.fence_dir / CORRUPT_DIR).iterdir()]
    assert len(names) == 2 and len(set(names)) == 2


# =========================================================================
# crash consistency: kill -9 mid-put leaves old-or-absent, never torn
# =========================================================================

_KILL9_CHILD = r"""
import sys
sys.path.insert(0, %(repo)r)
from deeplearning4j_tpu.perf.compile_store import CompileStore
store = CompileStore(%(root)r, jaxlib="1.0", topology="cpu")
fp = %(fp)r
print("READY", flush=True)
i = 0
while True:                       # publish continuously until killed
    i += 1
    # generation-stamped payload, fat enough to widen the write window
    store.put(fp, (b"gen-%%08d|" %% i) + bytes([i %% 251]) * 65536)
    print("PUT %%d" %% i, flush=True)
"""


def test_kill9_mid_put_leaves_old_or_absent(tmp_path):
    """Acceptance: SIGKILL at ANY point during ``put`` leaves the
    entry old-or-absent — a subsequent ``get`` returns a complete
    generation's payload or a miss, and never quarantines (atomic
    publish means no torn entry ever lands at the final path)."""
    fp = program_fingerprint(sweep="kill9")
    for delay in (0.002, 0.01, 0.03):
        root = tmp_path / f"run_{int(delay * 1000)}"
        child = subprocess.Popen(
            [sys.executable, "-c", _KILL9_CHILD % {
                "repo": str(REPO), "root": str(root), "fp": fp}],
            stdout=subprocess.PIPE, text=True,
            env=dict(os.environ, JAX_PLATFORMS="cpu"))
        puts = 0
        for line in child.stdout:
            if line.startswith("PUT"):
                puts += 1
                if puts >= 2:
                    break
        time.sleep(delay)         # land the kill mid-put-cycle
        child.kill()              # SIGKILL: no cleanup code runs
        child.wait(timeout=60)
        child.stdout.close()
        store = CompileStore(root, jaxlib="1.0", topology="cpu")
        got = store.get(fp)
        if got is not None:
            assert got.startswith(b"gen-") and len(got) == 65549, \
                f"kill@{delay}: torn payload"
            gen = int(got[4:12])
            assert got[13:] == bytes([gen % 251]) * 65536, \
                f"kill@{delay}: cross-generation tear"
        assert store.counters()["quarantined"] == 0, \
            f"kill@{delay}: atomic publish still landed a torn entry"


# =========================================================================
# env gating + compile-cache routing (subprocess: configure mutates
# process-global jax cache config)
# =========================================================================

def test_from_env_gating(tmp_path, monkeypatch):
    for off in ("", "0", "off", "none", "false", "disabled"):
        monkeypatch.setenv("DL4J_TPU_COMPILE_STORE", off)
        assert from_env() is None
    monkeypatch.delenv("DL4J_TPU_COMPILE_STORE", raising=False)
    assert from_env() is None
    monkeypatch.setenv("DL4J_TPU_COMPILE_STORE", str(tmp_path / "s"))
    store = from_env()
    assert store is not None
    assert store.root == tmp_path / "s"


_ROUTING_CHILD = r"""
import json, sys
sys.path.insert(0, %(repo)r)
import jax
jax.config.update("jax_platforms", "cpu")
from deeplearning4j_tpu.perf import compile_cache
d = compile_cache.configure_from_env()
store = compile_cache.active_store()
print(json.dumps({
    "dir": d,
    "has_store": store is not None,
    "xla_dir": str(store.xla_dir) if store else None,
    "fence_in_stats": compile_cache.cache_stats().get("store_fence"),
    "jax_dir": jax.config.jax_compilation_cache_dir,
}))
"""


def test_compile_store_routes_persistent_cache(tmp_path):
    """DL4J_TPU_COMPILE_STORE supersedes the flat cache dir: the
    fenced xla/ plane becomes JAX's compilation cache dir (explicit
    opt-in, so it applies on CPU too)."""
    r = subprocess.run(
        [sys.executable, "-c", _ROUTING_CHILD % {"repo": str(REPO)}],
        capture_output=True, text=True, timeout=120,
        env=dict(os.environ, JAX_PLATFORMS="cpu",
                 DL4J_TPU_COMPILE_STORE=str(tmp_path / "store")))
    assert r.returncode == 0, r.stderr
    out = json.loads(r.stdout.strip().splitlines()[-1])
    assert out["has_store"] is True
    assert out["dir"] == out["xla_dir"] == out["jax_dir"]
    assert str(tmp_path / "store") in out["dir"]
    assert out["fence_in_stats"]


def test_compile_store_off_keeps_cpu_cache_disabled(tmp_path):
    """Without the store (and without DL4J_TPU_COMPILE_CACHE), a plain
    CPU process keeps the persistent cache off — the jaxlib-0.4.x
    deserialization segfault gate stays intact."""
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    env.pop("DL4J_TPU_COMPILE_STORE", None)
    env.pop("DL4J_TPU_COMPILE_CACHE", None)
    r = subprocess.run(
        [sys.executable, "-c", _ROUTING_CHILD % {"repo": str(REPO)}],
        capture_output=True, text=True, timeout=120, env=env)
    assert r.returncode == 0, r.stderr
    out = json.loads(r.stdout.strip().splitlines()[-1])
    assert out["dir"] is None and out["has_store"] is False
