"""Image ETL tests (reference: datavec-data-image TestImageRecordReader
/ TestImageTransform — same shapes/label semantics, synthetic fixture
images)."""
import numpy as np
import pytest

from deeplearning4j_tpu.data import (CropImageTransform,
                                     FlipImageTransform,
                                     ImageRecordReader,
                                     NativeImageLoader,
                                     PipelineImageTransform,
                                     ResizeImageTransform,
                                     RotateImageTransform)
from deeplearning4j_tpu.data.records import RecordReaderDataSetIterator


@pytest.fixture(scope="module")
def image_root(tmp_path_factory):
    """root/<label>/*.png fixture: 2 classes x 3 images, distinct
    constant colors."""
    import cv2
    root = tmp_path_factory.mktemp("imgs")
    for label, color in [("cats", (255, 0, 0)), ("dogs", (0, 0, 255))]:
        d = root / label
        d.mkdir()
        for i in range(3):
            img = np.full((12 + i, 10 + i, 3),
                          color, np.uint8)  # varied sizes → resize path
            cv2.imwrite(str(d / f"{i}.png"), img)
    return str(root)


def test_native_image_loader(image_root):
    ld = NativeImageLoader(8, 8, 3)
    import os
    f = os.path.join(image_root, "cats", "0.png")
    x = ld.load(f)
    assert x.shape == (8, 8, 3) and x.dtype == np.float32
    # cats are written as BGR (255,0,0) → loader returns RGB
    assert x[..., 2].mean() > 200 and x[..., 0].mean() < 50
    m = ld.as_matrix(f)
    assert m.shape == (1, 8, 8, 3)
    nchw = NativeImageLoader(8, 8, 3, channels_first=True).load(f)
    assert nchw.shape == (3, 8, 8)


def test_native_image_loader_grayscale(image_root):
    import os
    ld = NativeImageLoader(6, 6, 1)
    x = ld.load(os.path.join(image_root, "dogs", "1.png"))
    assert x.shape == (6, 6, 1)


def test_image_record_reader_labels_and_batches(image_root):
    rr = ImageRecordReader(8, 8, 3).initialize(image_root)
    assert rr.labels == ["cats", "dogs"]
    recs = list(rr)
    assert len(recs) == 6
    assert recs[0][0].shape == (8, 8, 3)
    it = RecordReaderDataSetIterator(
        ImageRecordReader(8, 8, 3).initialize(image_root),
        batch_size=4, label_index=1, num_classes=2)
    batches = list(it)
    assert batches[0].features.shape == (4, 8, 8, 3)
    assert batches[0].labels.shape == (4, 2)
    total = sum(b.features.shape[0] for b in batches)
    assert total == 6


def test_transforms_shapes_and_determinism():
    rng1 = np.random.default_rng(5)
    rng2 = np.random.default_rng(5)
    img = np.arange(20 * 16 * 3, dtype=np.uint8).reshape(20, 16, 3)
    assert ResizeImageTransform(8, 10).transform(img).shape == (10, 8, 3)
    assert FlipImageTransform(1).transform(img).shape == (20, 16, 3)
    np.testing.assert_array_equal(
        FlipImageTransform(1).transform(
            FlipImageTransform(1).transform(img)), img)
    r1 = RotateImageTransform(30).transform(img, rng1)
    r2 = RotateImageTransform(30).transform(img, rng2)
    np.testing.assert_array_equal(r1, r2)   # same rng stream
    c = CropImageTransform(4).transform(img, rng1)
    assert c.shape[0] >= 12 and c.shape[1] >= 8


def test_pipeline_transform(image_root):
    rng = np.random.default_rng(0)
    img = np.full((16, 16, 3), 128, np.uint8)
    pipe = PipelineImageTransform([
        (FlipImageTransform(1), 0.5),
        ResizeImageTransform(8, 8),
    ])
    out = pipe.transform(img, rng)
    assert out.shape == (8, 8, 3)


def test_image_reader_with_augmentation(image_root):
    rr = ImageRecordReader(
        8, 8, 3,
        transform=PipelineImageTransform(
            [(FlipImageTransform(1), 1.0),
             (RotateImageTransform(15), 0.5)])).initialize(image_root)
    recs = list(rr)
    assert all(r[0].shape == (8, 8, 3) for r in recs)


def test_image_record_reader_parallel_workers(tmp_path):
    """workers>1 decodes over a thread pool with ORDERED yield: no
    transform → byte-identical to the sequential path; with a random
    transform → deterministic per (seed, epoch, index) regardless of
    thread timing, and re-iterating gives a FRESH epoch of augments."""
    import cv2
    rng = np.random.default_rng(0)
    for i in range(12):
        d = tmp_path / f"c{i % 3}"
        d.mkdir(exist_ok=True)
        cv2.imwrite(str(d / f"{i:03d}.png"),
                    rng.integers(0, 255, (40, 40, 3), dtype=np.uint8))

    from deeplearning4j_tpu.data.image import (FlipImageTransform,
                                               ImageRecordReader)
    seq = ImageRecordReader(32, 32, 3).initialize(str(tmp_path))
    par = ImageRecordReader(32, 32, 3,
                            workers=3).initialize(str(tmp_path))
    a = list(seq)
    b = list(par)
    assert len(a) == len(b) == 12
    for (xa, la), (xb, lb) in zip(a, b):
        assert la == lb
        np.testing.assert_array_equal(xa, xb)

    aug = ImageRecordReader(32, 32, 3, workers=3, seed=7,
                            transform=FlipImageTransform()) \
        .initialize(str(tmp_path))
    e0 = [x for x, _ in aug]
    aug2 = ImageRecordReader(32, 32, 3, workers=3, seed=7,
                             transform=FlipImageTransform()) \
        .initialize(str(tmp_path))
    e0b = [x for x, _ in aug2]
    for xa, xb in zip(e0, e0b):       # same seed+epoch → identical
        np.testing.assert_array_equal(xa, xb)
    e1 = [x for x, _ in aug2]         # next epoch → fresh augments
    assert any(not np.array_equal(xa, xb) for xa, xb in zip(e0b, e1))
