"""Orbax-backed sharded checkpointing on the 8-device CPU mesh:
save/restore of a TP-sharded pytree preserves values AND shardings;
keep-last-K; resume into a live network. (SURVEY §5 checkpoint/resume —
the scale path next to the zip ModelSerializer.)
"""
import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from deeplearning4j_tpu.serialization import ShardedCheckpointer

pytestmark = pytest.mark.skipif(
    len(jax.devices()) < 8, reason="needs 8 virtual devices")


def test_sharded_roundtrip_preserves_sharding(tmp_path):
    mesh = Mesh(np.array(jax.devices()).reshape(4, 2), ("data", "model"))
    sh = NamedSharding(mesh, P(None, "model"))
    w = jax.device_put(
        jnp.arange(16 * 8, dtype=jnp.float32).reshape(16, 8), sh)
    tree = {"params": {"w": w, "b": jnp.ones((8,))},
            "opt_state": {"m": jnp.zeros((16, 8))},
            "state": {}, "meta": {"iteration": 7, "epoch": 1}}
    with ShardedCheckpointer(tmp_path / "ckpt", async_save=False) as ck:
        ck.save(0, tree=tree, wait=True)
        target = jax.tree.map(
            lambda a: jax.ShapeDtypeStruct(a.shape, a.dtype,
                                           sharding=a.sharding)
            if hasattr(a, "sharding") else a, tree)
        got = ck.restore(0, target=target)
    np.testing.assert_array_equal(np.asarray(got["params"]["w"]),
                                  np.asarray(w))
    assert got["params"]["w"].sharding.is_equivalent_to(sh, 2)
    assert int(np.asarray(got["meta"]["iteration"])) == 7


def test_keep_last_k(tmp_path):
    tree = {"x": jnp.ones((4,))}
    with ShardedCheckpointer(tmp_path / "ck", keep_last=2,
                             async_save=False) as ck:
        for s in range(5):
            ck.save(s, tree=tree, wait=True)
        assert ck.all_steps() == [3, 4]
        assert ck.latest_step() == 4


def test_resume_into_network(tmp_path):
    from deeplearning4j_tpu.nn import (MultiLayerNetwork,
                                       NeuralNetConfiguration)
    from deeplearning4j_tpu.nn.config import InputType
    from deeplearning4j_tpu.nn.layers import DenseLayer, OutputLayer
    from deeplearning4j_tpu.nn import updaters as upd

    def make():
        conf = (NeuralNetConfiguration.builder().seed(3)
                .updater(upd.Adam(learning_rate=0.05)).list()
                .layer(DenseLayer(n_out=8, activation="tanh"))
                .layer(OutputLayer(n_out=2, activation="softmax",
                                   loss="mcxent"))
                .set_input_type(InputType.feed_forward(4)).build())
        return MultiLayerNetwork(conf).init()

    rng = np.random.default_rng(0)
    x = rng.standard_normal((64, 4)).astype(np.float32)
    y = np.eye(2, dtype=np.float32)[(x.sum(1) > 0).astype(int)]
    a = make()
    for _ in range(5):
        a.fit(x, y)
    with ShardedCheckpointer(tmp_path / "net", async_save=False) as ck:
        ck.save(a.iteration, a, wait=True)
        b = ck.restore(net=make())
    assert b.iteration == a.iteration
    np.testing.assert_allclose(np.asarray(b.output(x)),
                               np.asarray(a.output(x)), rtol=1e-6)
    # training continues identically from the restored state
    a.fit(x, y)
    b.fit(x, y)
    np.testing.assert_allclose(np.asarray(b.output(x)),
                               np.asarray(a.output(x)), rtol=1e-5)


def test_sharded_checkpoint_listener(tmp_path):
    from deeplearning4j_tpu.nn import (MultiLayerNetwork,
                                       NeuralNetConfiguration)
    from deeplearning4j_tpu.nn.config import InputType
    from deeplearning4j_tpu.nn.layers import DenseLayer, OutputLayer
    from deeplearning4j_tpu.nn import updaters as upd
    from deeplearning4j_tpu.train.listeners import CheckpointListener

    conf = (NeuralNetConfiguration.builder().seed(3)
            .updater(upd.Sgd(learning_rate=0.1)).list()
            .layer(DenseLayer(n_out=4, activation="tanh"))
            .layer(OutputLayer(n_out=2, activation="softmax",
                               loss="mcxent"))
            .set_input_type(InputType.feed_forward(4)).build())
    net = MultiLayerNetwork(conf).init()
    lst = CheckpointListener(tmp_path / "sh", save_every_n_iterations=2,
                             keep_last=2, sharded=True)
    net.listeners.append(lst)
    rng = np.random.default_rng(0)
    x = rng.standard_normal((32, 4)).astype(np.float32)
    y = np.eye(2, dtype=np.float32)[(x.sum(1) > 0).astype(int)]
    for _ in range(6):
        net.fit(x, y)
    lst._ck.wait_until_finished()
    assert lst._ck.all_steps() == [4, 6]
    restored = lst._ck.restore(6, net=MultiLayerNetwork(conf).init())
    np.testing.assert_allclose(np.asarray(restored.output(x)),
                               np.asarray(net.output(x)), rtol=1e-6)


def test_listener_iter_and_epoch_saves_no_step_collision(tmp_path):
    from deeplearning4j_tpu.nn import (MultiLayerNetwork,
                                       NeuralNetConfiguration)
    from deeplearning4j_tpu.nn.config import InputType
    from deeplearning4j_tpu.nn.layers import DenseLayer, OutputLayer
    from deeplearning4j_tpu.nn import updaters as upd
    from deeplearning4j_tpu.data import DataSet, ListDataSetIterator
    from deeplearning4j_tpu.train.listeners import CheckpointListener

    conf = (NeuralNetConfiguration.builder().seed(3)
            .updater(upd.Sgd(learning_rate=0.1)).list()
            .layer(DenseLayer(n_out=4, activation="tanh"))
            .layer(OutputLayer(n_out=2, activation="softmax",
                               loss="mcxent"))
            .set_input_type(InputType.feed_forward(4)).build())
    net = MultiLayerNetwork(conf).init()
    # every 2 iters AND every epoch; 4 batches/epoch → epoch-end save
    # lands on an iteration already saved (would collide without dedup)
    lst = CheckpointListener(tmp_path / "both",
                             save_every_n_iterations=2,
                             save_every_n_epochs=1, keep_last=10,
                             sharded=True)
    net.listeners.append(lst)
    rng = np.random.default_rng(0)
    data = [DataSet(rng.standard_normal((8, 4)).astype(np.float32),
                    np.eye(2, dtype=np.float32)[rng.integers(0, 2, 8)])
            for _ in range(4)]
    net.fit(ListDataSetIterator(data), epochs=2)   # no crash = no collision
    lst.flush()
    assert lst._ck.all_steps() == [2, 4, 6, 8]
