"""Orbax-backed sharded checkpointing on the 8-device CPU mesh:
save/restore of a TP-sharded pytree preserves values AND shardings;
keep-last-K; resume into a live network; elastic resharded restore
(a ZeRO checkpoint written at N devices restored onto M≠N — the
forced-8-CPU-device reshard fence of ISSUE 7). (SURVEY §5
checkpoint/resume — the scale path next to the zip ModelSerializer.)
"""
import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from deeplearning4j_tpu.parallel._compat import supports_psum_scatter
from deeplearning4j_tpu.serialization import ShardedCheckpointer

pytestmark = pytest.mark.skipif(
    len(jax.devices()) < 8, reason="needs 8 virtual devices")

needs_scatter = pytest.mark.skipif(
    not supports_psum_scatter(),
    reason="jax runtime has no psum_scatter/all_gather")


def test_sharded_roundtrip_preserves_sharding(tmp_path):
    mesh = Mesh(np.array(jax.devices()).reshape(4, 2), ("data", "model"))
    sh = NamedSharding(mesh, P(None, "model"))
    w = jax.device_put(
        jnp.arange(16 * 8, dtype=jnp.float32).reshape(16, 8), sh)
    tree = {"params": {"w": w, "b": jnp.ones((8,))},
            "opt_state": {"m": jnp.zeros((16, 8))},
            "state": {}, "meta": {"iteration": 7, "epoch": 1}}
    with ShardedCheckpointer(tmp_path / "ckpt", async_save=False) as ck:
        ck.save(0, tree=tree, wait=True)
        target = jax.tree.map(
            lambda a: jax.ShapeDtypeStruct(a.shape, a.dtype,
                                           sharding=a.sharding)
            if hasattr(a, "sharding") else a, tree)
        got = ck.restore(0, target=target)
    np.testing.assert_array_equal(np.asarray(got["params"]["w"]),
                                  np.asarray(w))
    assert got["params"]["w"].sharding.is_equivalent_to(sh, 2)
    assert int(np.asarray(got["meta"]["iteration"])) == 7


def test_keep_last_k(tmp_path):
    tree = {"x": jnp.ones((4,))}
    with ShardedCheckpointer(tmp_path / "ck", keep_last=2,
                             async_save=False) as ck:
        for s in range(5):
            ck.save(s, tree=tree, wait=True)
        assert ck.all_steps() == [3, 4]
        assert ck.latest_step() == 4


def test_resume_into_network(tmp_path):
    from deeplearning4j_tpu.nn import (MultiLayerNetwork,
                                       NeuralNetConfiguration)
    from deeplearning4j_tpu.nn.config import InputType
    from deeplearning4j_tpu.nn.layers import DenseLayer, OutputLayer
    from deeplearning4j_tpu.nn import updaters as upd

    def make():
        conf = (NeuralNetConfiguration.builder().seed(3)
                .updater(upd.Adam(learning_rate=0.05)).list()
                .layer(DenseLayer(n_out=8, activation="tanh"))
                .layer(OutputLayer(n_out=2, activation="softmax",
                                   loss="mcxent"))
                .set_input_type(InputType.feed_forward(4)).build())
        return MultiLayerNetwork(conf).init()

    rng = np.random.default_rng(0)
    x = rng.standard_normal((64, 4)).astype(np.float32)
    y = np.eye(2, dtype=np.float32)[(x.sum(1) > 0).astype(int)]
    a = make()
    for _ in range(5):
        a.fit(x, y)
    with ShardedCheckpointer(tmp_path / "net", async_save=False) as ck:
        ck.save(a.iteration, a, wait=True)
        b = ck.restore(net=make())
    assert b.iteration == a.iteration
    np.testing.assert_allclose(np.asarray(b.output(x)),
                               np.asarray(a.output(x)), rtol=1e-6)
    # training continues identically from the restored state
    a.fit(x, y)
    b.fit(x, y)
    np.testing.assert_allclose(np.asarray(b.output(x)),
                               np.asarray(a.output(x)), rtol=1e-5)


def test_sharded_checkpoint_listener(tmp_path):
    from deeplearning4j_tpu.nn import (MultiLayerNetwork,
                                       NeuralNetConfiguration)
    from deeplearning4j_tpu.nn.config import InputType
    from deeplearning4j_tpu.nn.layers import DenseLayer, OutputLayer
    from deeplearning4j_tpu.nn import updaters as upd
    from deeplearning4j_tpu.train.listeners import CheckpointListener

    conf = (NeuralNetConfiguration.builder().seed(3)
            .updater(upd.Sgd(learning_rate=0.1)).list()
            .layer(DenseLayer(n_out=4, activation="tanh"))
            .layer(OutputLayer(n_out=2, activation="softmax",
                               loss="mcxent"))
            .set_input_type(InputType.feed_forward(4)).build())
    net = MultiLayerNetwork(conf).init()
    lst = CheckpointListener(tmp_path / "sh", save_every_n_iterations=2,
                             keep_last=2, sharded=True)
    net.listeners.append(lst)
    rng = np.random.default_rng(0)
    x = rng.standard_normal((32, 4)).astype(np.float32)
    y = np.eye(2, dtype=np.float32)[(x.sum(1) > 0).astype(int)]
    for _ in range(6):
        net.fit(x, y)
    lst._ck.wait_until_finished()
    assert lst._ck.all_steps() == [4, 6]
    restored = lst._ck.restore(6, net=MultiLayerNetwork(conf).init())
    np.testing.assert_allclose(np.asarray(restored.output(x)),
                               np.asarray(net.output(x)), rtol=1e-6)


# =========================================================================
# elastic resharded restore (ISSUE 7): save at N, restore at M != N
# =========================================================================

def _zero_wrapper(n, seed=3, feats=6, classes=3, hidden=13):
    """A sharded-update wrapper over the first n of the 8 forced CPU
    devices; hidden=13 makes most flat leaves pad differently under
    8 vs 4 shards (the repad path is actually exercised)."""
    from deeplearning4j_tpu.nn import (MultiLayerNetwork,
                                       NeuralNetConfiguration)
    from deeplearning4j_tpu.nn.config import InputType
    from deeplearning4j_tpu.nn.layers import DenseLayer, OutputLayer
    from deeplearning4j_tpu.nn import updaters as upd
    from deeplearning4j_tpu.parallel.wrapper import ParallelWrapper
    conf = (NeuralNetConfiguration.builder().seed(seed)
            .updater(upd.Adam(learning_rate=5e-3)).list()
            .layer(DenseLayer(n_out=hidden, activation="tanh"))
            .layer(OutputLayer(n_out=classes, activation="softmax",
                               loss="mcxent"))
            .set_input_type(InputType.feed_forward(feats)).build())
    net = MultiLayerNetwork(conf).init()
    return net, ParallelWrapper(net, workers=n, sharded_update=True,
                                prefetch_buffer=0)


def _fit_steps(wrapper, steps=4, batch=16, feats=6, classes=3, seed=0):
    from deeplearning4j_tpu.data import DataSet, ListDataSetIterator
    rng = np.random.RandomState(seed)
    x = rng.randn(batch * steps, feats).astype(np.float32)
    y = np.eye(classes, dtype=np.float32)[
        rng.randint(0, classes, batch * steps)]
    wrapper.fit(ListDataSetIterator(DataSet(x, y), batch_size=batch),
                epochs=1)


def _host_flat_opt(wrapper):
    """The wrapper's live optimizer state as full host-side flat
    leaves (np.asarray of a P('data') global array materializes the
    whole leaf)."""
    return [np.asarray(l)
            for l in jax.tree_util.tree_leaves(wrapper._dp_state)]


@needs_scatter
def test_reshard_fence_8_to_4_and_back(tmp_path):
    """Acceptance fence: opt/param state saved at N=8 restores onto
    M=4 (and 4→8) with the gathered flat leaves bit-identical to the
    source checkpoint, in this forced-8-CPU-device process."""
    from deeplearning4j_tpu.parallel.zero import repad_flat_leaves
    net8, w8 = _zero_wrapper(8)
    _fit_steps(w8)
    src_flat = _host_flat_opt(w8)
    src_params = [np.asarray(l)
                  for l in jax.tree_util.tree_leaves(net8.params)]
    with ShardedCheckpointer(tmp_path / "ck", async_save=False) as ck:
        ck.save_wrapper(net8.iteration, w8, wait=True)
        assert ck.world_manifest(net8.iteration)["n_shards"] == 8

        # N=8 -> M=4
        net4, w4 = _zero_wrapper(4)
        ck.restore_wrapper(w4)
        assert net4.iteration == net8.iteration
        assert net4.epoch == net8.epoch
        for a, b in zip(jax.tree_util.tree_leaves(net4.params),
                        src_params):
            assert np.array_equal(np.asarray(a), b)
        # gather M=4 shards, re-pad onto the source layout: bit-equal
        flat4 = _host_flat_opt(w4)
        back = repad_flat_leaves(flat4, src_flat)
        for a, b in zip(back, src_flat):
            assert a.dtype == b.dtype and np.array_equal(a, b)

        # M=4 -> N=8 (continue training at 4, save, restore at 8)
        _fit_steps(w4, seed=1)
        ck.save_wrapper(net4.iteration, w4, wait=True)
        assert ck.world_manifest(net4.iteration)["n_shards"] == 4
        src4_flat = _host_flat_opt(w4)
        net8b, w8b = _zero_wrapper(8)
        ck.restore_wrapper(w8b, step=net4.iteration)
        assert net8b.iteration == net4.iteration
        flat8b = _host_flat_opt(w8b)
        back4 = repad_flat_leaves(flat8b, src4_flat)
        for a, b in zip(back4, src4_flat):
            assert np.array_equal(a, b)
        # and the resharded state actually trains (shards are live,
        # not just storage): one more step must not diverge from the
        # same step taken at the source scale... world size differs,
        # so just assert it steps cleanly and stays finite
        _fit_steps(w8b, steps=1, seed=2)
        assert np.isfinite(net8b.score_)


@needs_scatter
def test_same_topology_restore_stays_fast_path(tmp_path):
    """n_src == wrapper.n keeps the sharded-target restore (shards
    land on their devices; nothing gathers): the restored opt leaves
    carry P('data') shardings."""
    net8, w8 = _zero_wrapper(8)
    _fit_steps(w8)
    with ShardedCheckpointer(tmp_path / "ck", async_save=False) as ck:
        ck.save_wrapper(net8.iteration, w8, wait=True)
        net8b, w8b = _zero_wrapper(8)
        ck.restore_wrapper(w8b)
    from deeplearning4j_tpu.parallel.zero import sharded_leaf
    for leaf in jax.tree_util.tree_leaves(w8b._dp_state):
        if sharded_leaf(leaf, 8):
            assert len(leaf.sharding.device_set) == 8
    for a, b in zip(_host_flat_opt(w8b), _host_flat_opt(w8)):
        assert np.array_equal(a, b)


@needs_scatter
def test_reshard_refused_without_opt_in(tmp_path):
    net8, w8 = _zero_wrapper(8)
    _fit_steps(w8)
    with ShardedCheckpointer(tmp_path / "ck", async_save=False) as ck:
        ck.save_wrapper(net8.iteration, w8, wait=True)
        _, w4 = _zero_wrapper(4)
        with pytest.raises(ValueError, match="reshard"):
            ck.restore_wrapper(w4, reshard=False)


@needs_scatter
def test_layout_mismatch_fails_fast_without_quarantine(tmp_path):
    """Restoring a checkpoint dir written by a DIFFERENT net is a
    configuration error: the strict zero-pad invariant raises
    LayoutMismatch and restore_latest_valid must NOT walk the chain
    quarantining every (valid) step."""
    from deeplearning4j_tpu.parallel.zero import LayoutMismatch
    net8, w8 = _zero_wrapper(8, hidden=13)
    _fit_steps(w8)
    with ShardedCheckpointer(tmp_path / "ck", async_save=False) as ck:
        ck.save_wrapper(net8.iteration, w8, wait=True)
        # same leaf COUNT, different layer width -> flat sizes clash
        _, w4 = _zero_wrapper(4, hidden=9)
        with pytest.raises(LayoutMismatch):
            ck.restore_latest_valid(wrapper=w4)
        assert ck.all_steps() == [net8.iteration]   # nothing moved
        assert not (tmp_path / "ck" / "corrupt").exists()


@needs_scatter
def test_restore_degradation_order_quarantines_then_reshards(tmp_path):
    """Satellite: newest checkpoint written at N=8 is CORRUPT →
    restore_latest_valid onto M=4 quarantines it (with its world
    manifest) and the next-newest valid step still reshards."""
    from deeplearning4j_tpu.obs import metrics
    net8, w8 = _zero_wrapper(8)
    _fit_steps(w8)
    good_step = net8.iteration
    good_params = [np.asarray(l)
                   for l in jax.tree_util.tree_leaves(net8.params)]
    ck = ShardedCheckpointer(tmp_path / "ck", keep_last=5,
                             async_save=False)
    ck.save_wrapper(good_step, w8, wait=True)
    _fit_steps(w8, seed=1)
    bad_step = net8.iteration
    ck.save_wrapper(bad_step, w8, wait=True)
    # rot the newest step dir (truncate every tensorstore file)
    for f in (tmp_path / "ck" / str(bad_step)).rglob("*"):
        if f.is_file():
            f.write_bytes(f.read_bytes()[:3])
    q0 = metrics.CKPT_QUARANTINED._children[()].get()
    net4, w4 = _zero_wrapper(4)
    ck.restore_latest_valid(wrapper=w4)
    assert net4.iteration == good_step      # fell back, resharded
    for a, b in zip(jax.tree_util.tree_leaves(net4.params),
                    good_params):
        assert np.array_equal(np.asarray(a), b)
    assert metrics.CKPT_QUARANTINED._children[()].get() == q0 + 1
    assert (tmp_path / "ck" / "corrupt" / str(bad_step)).exists()
    # the corrupt step's world manifest moved with it
    assert not (tmp_path / "ck" / f"world_{bad_step}.json").exists()
    assert (tmp_path / "ck" / "corrupt"
            / f"world_{bad_step}.json").exists()
    assert ck.all_steps() == [good_step]
    ck.close()


def test_listener_iter_and_epoch_saves_no_step_collision(tmp_path):
    from deeplearning4j_tpu.nn import (MultiLayerNetwork,
                                       NeuralNetConfiguration)
    from deeplearning4j_tpu.nn.config import InputType
    from deeplearning4j_tpu.nn.layers import DenseLayer, OutputLayer
    from deeplearning4j_tpu.nn import updaters as upd
    from deeplearning4j_tpu.data import DataSet, ListDataSetIterator
    from deeplearning4j_tpu.train.listeners import CheckpointListener

    conf = (NeuralNetConfiguration.builder().seed(3)
            .updater(upd.Sgd(learning_rate=0.1)).list()
            .layer(DenseLayer(n_out=4, activation="tanh"))
            .layer(OutputLayer(n_out=2, activation="softmax",
                               loss="mcxent"))
            .set_input_type(InputType.feed_forward(4)).build())
    net = MultiLayerNetwork(conf).init()
    # every 2 iters AND every epoch; 4 batches/epoch → epoch-end save
    # lands on an iteration already saved (would collide without dedup)
    lst = CheckpointListener(tmp_path / "both",
                             save_every_n_iterations=2,
                             save_every_n_epochs=1, keep_last=10,
                             sharded=True)
    net.listeners.append(lst)
    rng = np.random.default_rng(0)
    data = [DataSet(rng.standard_normal((8, 4)).astype(np.float32),
                    np.eye(2, dtype=np.float32)[rng.integers(0, 2, 8)])
            for _ in range(4)]
    net.fit(ListDataSetIterator(data), epochs=2)   # no crash = no collision
    lst.flush()
    assert lst._ck.all_steps() == [2, 4, 6, 8]
