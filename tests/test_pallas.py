"""Pallas kernel tests (interpret mode on CPU — same code path that
compiles with Mosaic on TPU). Reference coverage: libnd4j
encode_threshold/decode_threshold ops and the attention platform-helper
dispatch (SURVEY §2.1 platform helpers, §3.5 gradient compression)."""
import jax
import jax.numpy as jnp
import numpy as np
import pytest

from deeplearning4j_tpu.ops import pallas_kernels as pk
from deeplearning4j_tpu.nn.layers.attention import scaled_dot_attention


# ---------------------------------------------------------------------------
# flash attention
# ---------------------------------------------------------------------------
# The interpret-mode flash tests became RUNNABLE on this old-jaxlib CI
# env with ISSUE 15's jax.typeof/vma compat fix (they AttributeError'd
# before). The deep backward/variant sweeps cost seconds each in
# interpret mode, and tier-1's 870 s wall-clock budget was already ~96%
# utilised — so the quick parity core stays tier-1 and the heavy
# variants ride the slow lane (still run at round end).
_SLOW = pytest.mark.slow


@pytest.mark.parametrize("causal", [pytest.param(False, marks=_SLOW),
                                    True])
@pytest.mark.parametrize("t", [64, 200])
def test_flash_matches_reference(rng, causal, t):
    B, H, D = 2, 2, 32
    q, k, v = (jnp.asarray(rng.standard_normal((B, t, H, D)),
                           jnp.float32) for _ in range(3))
    ref = scaled_dot_attention(q, k, v, causal=causal)
    out = pk.flash_attention(q, k, v, causal=causal,
                             block_q=64, block_k=64)
    assert float(jnp.max(jnp.abs(ref - out))) < 2e-5


def test_flash_gradients_match_reference(rng):
    B, T, H, D = 1, 96, 2, 16
    q, k, v = (jnp.asarray(rng.standard_normal((B, T, H, D)),
                           jnp.float32) for _ in range(3))

    def loss(fn):
        return lambda q, k, v: jnp.sum(fn(q, k, v, causal=True) ** 2)

    g1 = jax.grad(loss(lambda *a, **kw: pk.flash_attention(
        *a, block_q=32, block_k=32, **kw)), argnums=(0, 1, 2))(q, k, v)
    g2 = jax.grad(loss(scaled_dot_attention), argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(g1, g2):
        assert float(jnp.max(jnp.abs(a - b))) < 5e-5


@pytest.mark.parametrize("causal", [pytest.param(False, marks=_SLOW),
                                    True])
@pytest.mark.parametrize("t", [64, 200, 130])
def test_flash_backward_matches_reference(rng, causal, t):
    """The Pallas dQ/dKV kernels (FlashAttention-2 recompute style)
    must agree with autodiff through the einsum reference — including
    ragged lengths that exercise the padded-block masking."""
    B, H, D = 2, 2, 16
    q, k, v = (jnp.asarray(rng.standard_normal((B, t, H, D)),
                           jnp.float32) for _ in range(3))
    co = jnp.asarray(rng.standard_normal((B, t, H, D)), jnp.float32)

    def loss(fn):
        return lambda q, k, v: jnp.sum(fn(q, k, v, causal=causal) * co)

    g1 = jax.grad(loss(lambda *a, **kw: pk.flash_attention(
        *a, block_q=64, block_k=64, **kw)), argnums=(0, 1, 2))(q, k, v)
    g2 = jax.grad(loss(scaled_dot_attention),
                  argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(g1, g2):
        assert float(jnp.max(jnp.abs(a - b))) < 5e-5


@_SLOW
@pytest.mark.parametrize("causal", [False, True])
def test_flash_backward_split_fallback(monkeypatch, rng, causal):
    """Very long sequences fall back from the fused single-pass
    backward to the split dq / dkv kernels (full-length dq scratch
    would exceed VMEM). Force the threshold to 0 so the split path
    stays covered at test sizes."""
    monkeypatch.setattr(pk, "_FUSED_BWD_DQ_VMEM", 0)
    B, H, D, t = 2, 2, 16, 130
    q, k, v = (jnp.asarray(rng.standard_normal((B, t, H, D)),
                           jnp.float32) for _ in range(3))
    co = jnp.asarray(rng.standard_normal((B, t, H, D)), jnp.float32)

    def loss(fn):
        return lambda q, k, v: jnp.sum(fn(q, k, v, causal=causal) * co)

    g1 = jax.grad(loss(lambda *a, **kw: pk.flash_attention(
        *a, block_q=64, block_k=64, **kw)), argnums=(0, 1, 2))(q, k, v)
    g2 = jax.grad(loss(scaled_dot_attention),
                  argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(g1, g2):
        assert float(jnp.max(jnp.abs(a - b))) < 5e-5


@_SLOW
def test_flash_backward_finite_difference(rng):
    """Directional finite-difference check straight through the Pallas
    custom_vjp (float64-free: central difference in f32 with a loose
    tolerance)."""
    B, T, H, D = 1, 40, 1, 8
    q, k, v = (jnp.asarray(rng.standard_normal((B, T, H, D)),
                           jnp.float32) * 0.5 for _ in range(3))

    def f(q, k, v):
        return jnp.sum(pk.flash_attention(
            q, k, v, causal=True, block_q=32, block_k=32) ** 2)

    grads = jax.grad(f, argnums=(0, 1, 2))(q, k, v)
    key = jax.random.PRNGKey(0)
    eps = 1e-2
    for idx, g in enumerate(grads):
        d = jax.random.normal(key, g.shape, jnp.float32)
        d = d / jnp.linalg.norm(d.reshape(-1))
        args = [q, k, v]
        ap = list(args); ap[idx] = args[idx] + eps * d
        am = list(args); am[idx] = args[idx] - eps * d
        fd = (f(*ap) - f(*am)) / (2 * eps)
        an = jnp.vdot(g, d)
        assert abs(float(fd - an)) < 5e-2 * max(1.0, abs(float(an)))


@_SLOW
def test_flash_backward_bf16(rng):
    """bf16 inputs keep f32 accumulation in the backward kernels."""
    B, T, H, D = 1, 64, 2, 16
    qf, kf, vf = (jnp.asarray(rng.standard_normal((B, T, H, D)),
                              jnp.float32) for _ in range(3))
    q, k, v = (x.astype(jnp.bfloat16) for x in (qf, kf, vf))

    def loss(fn, *a):
        return jnp.sum(fn(*a, causal=False).astype(jnp.float32) ** 2)

    g1 = jax.grad(lambda *a: loss(lambda q, k, v, causal: pk.
                  flash_attention(q, k, v, causal, block_q=32,
                                  block_k=32), *a),
                  argnums=(0, 1, 2))(q, k, v)
    g2 = jax.grad(lambda *a: loss(
        lambda q, k, v, causal: scaled_dot_attention(
            q, k, v, causal=causal), *a), argnums=(0, 1, 2))(qf, kf, vf)
    for a, b in zip(g1, g2):
        err = float(jnp.max(jnp.abs(a.astype(jnp.float32) - b)))
        assert err < 0.15, err   # bf16 rounding, not accumulation error


@pytest.mark.parametrize("causal", [pytest.param(False, marks=_SLOW),
                                    True])
def test_flash_masked_matches_einsum(rng, causal):
    """Per-example key masks through the Pallas kernel (VERDICT r2 #3):
    padded-batch sequences must match the masked einsum reference —
    forward AND backward, causal and not."""
    B, T, H, D = 3, 96, 2, 16
    q, k, v = (jnp.asarray(rng.standard_normal((B, T, H, D)),
                           jnp.float32) for _ in range(3))
    # ragged lengths incl. one full-length row
    lens = jnp.asarray([96, 40, 77])
    mask = (jnp.arange(T)[None, :] < lens[:, None]).astype(jnp.float32)
    co = jnp.asarray(rng.standard_normal((B, T, H, D)), jnp.float32)

    def loss(fn):
        return lambda q, k, v: jnp.sum(fn(q, k, v) * co)

    flash = lambda q, k, v: pk.flash_attention(
        q, k, v, causal=causal, mask=mask, block_q=32, block_k=32)
    ref = lambda q, k, v: scaled_dot_attention(
        q, k, v, mask=mask, causal=causal)
    # only compare valid query rows (masked-out queries differ: flash
    # emits zeros there, einsum emits a uniform average — both are
    # discarded by downstream masking)
    valid = mask[:, :, None, None]
    outf, outr = flash(q, k, v) * valid, ref(q, k, v) * valid
    assert float(jnp.max(jnp.abs(outf - outr))) < 2e-5
    g1 = jax.grad(loss(lambda *a: flash(*a) * valid),
                  argnums=(0, 1, 2))(q, k, v)
    g2 = jax.grad(loss(lambda *a: ref(*a) * valid),
                  argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(g1, g2):
        assert float(jnp.max(jnp.abs(a - b))) < 5e-5


@_SLOW
@pytest.mark.parametrize("causal", [False, True])
def test_flash_gqa_matches_repeat(rng, causal):
    """Native GQA (kv BlockSpec index map b // groups) must equal
    attention with kv heads explicitly broadcast — fwd AND bwd,
    with a key mask."""
    B, T, H, HKV, D = 2, 96, 4, 2, 16
    q = jnp.asarray(rng.standard_normal((B, T, H, D)), jnp.float32)
    k = jnp.asarray(rng.standard_normal((B, T, HKV, D)), jnp.float32)
    v = jnp.asarray(rng.standard_normal((B, T, HKV, D)), jnp.float32)
    mask = (jnp.arange(T)[None, :]
            < jnp.asarray([[96], [70]])).astype(jnp.float32)
    co = jnp.asarray(rng.standard_normal((B, T, H, D)), jnp.float32)
    rep = lambda x: jnp.repeat(x, H // HKV, axis=2)

    gqa = lambda q, k, v: pk.flash_attention(
        q, k, v, causal=causal, mask=mask, block_q=32, block_k=32)
    full = lambda q, k, v: pk.flash_attention(
        q, rep(k), rep(v), causal=causal, mask=mask,
        block_q=32, block_k=32)
    np.testing.assert_allclose(np.asarray(gqa(q, k, v)),
                               np.asarray(full(q, k, v)),
                               rtol=1e-5, atol=1e-6)
    g1 = jax.grad(lambda q, k, v: jnp.sum(gqa(q, k, v) * co),
                  argnums=(0, 1, 2))(q, k, v)
    g2 = jax.grad(lambda q, k, v: jnp.sum(full(q, k, v) * co),
                  argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(g1, g2):
        assert float(jnp.max(jnp.abs(a - b))) < 5e-5


def test_flash_block_offsets_compose(rng):
    """flash_block_fwd/_merge semantics (the ring-attention surface):
    two half-sequence KV blocks with dynamic global offsets, merged by
    log-sum-exp combination, must equal full causal attention."""
    from deeplearning4j_tpu.parallel.ring_attention import _merge_blocks
    bh, t, d = 2, 64, 16
    q, k, v = (jnp.asarray(rng.standard_normal((bh, t, d)), jnp.float32)
               for _ in range(3))
    half = t // 2
    out = jnp.zeros((bh, half, d), jnp.float32)
    lse = jnp.full((bh, half, 1), -jnp.inf, jnp.float32)
    # queries are the SECOND half (global offset `half`)
    qh = q[:, half:]
    for blk in range(2):
        offs = jnp.asarray([half, blk * half], jnp.int32)
        o_b, lse_b = pk.flash_block_fwd(
            qh, k[:, blk * half:(blk + 1) * half],
            v[:, blk * half:(blk + 1) * half], None, offs, True,
            block_q=32, block_k=32)
        out, lse = _merge_blocks(out, lse, o_b, lse_b)
    want = pk._reference_scan(q, k, v, causal=True, block=32)[:, half:]
    assert float(jnp.max(jnp.abs(out - want))) < 2e-5


def test_flash_block_bwd_composes(rng):
    """flash_block_bwd with global lse: summing per-block dq and
    per-block dk/dv must equal autodiff through full attention."""
    bh, t, d = 2, 64, 16
    q, k, v = (jnp.asarray(rng.standard_normal((bh, t, d)), jnp.float32)
               for _ in range(3))
    co = jnp.asarray(rng.standard_normal((bh, t, d)), jnp.float32)
    out, lse = pk._flash_fwd(q, k, v, None, None, True, 32, 32,
                             return_lse=True)
    half = t // 2
    dq = jnp.zeros_like(q)
    dks, dvs = [], []
    for blk in range(2):
        sl = slice(blk * half, (blk + 1) * half)
        offs = jnp.asarray([0, blk * half], jnp.int32)
        dq_b, dk_b, dv_b = pk.flash_block_bwd(
            q, k[:, sl], v[:, sl], out, lse, co, None, offs, True,
            block_q=32, block_k=32)
        dq = dq + dq_b
        dks.append(dk_b)
        dvs.append(dv_b)
    dk = jnp.concatenate(dks, axis=1)
    dv = jnp.concatenate(dvs, axis=1)
    want = jax.grad(
        lambda q, k, v: jnp.sum(_dense_causal(q, k, v) * co),
        argnums=(0, 1, 2))(q, k, v)
    for a, b in zip((dq, dk, dv), want):
        assert float(jnp.max(jnp.abs(a - b))) < 5e-5


@_SLOW
def test_flash_block_bwd_kv_longer_than_q(rng):
    """Rectangular kv>q: dk/dv must come back at the KV length, not
    truncated to the q length (regression: dk[:, :t] slice bug)."""
    bh, tq, tk, d = 2, 32, 64, 16
    q = jnp.asarray(rng.standard_normal((bh, tq, d)), jnp.float32)
    k = jnp.asarray(rng.standard_normal((bh, tk, d)), jnp.float32)
    v = jnp.asarray(rng.standard_normal((bh, tk, d)), jnp.float32)
    co = jnp.asarray(rng.standard_normal((bh, tq, d)), jnp.float32)
    out, lse = pk._flash_fwd(q, k, v, None, None, False, 32, 32,
                             return_lse=True)
    dq, dk, dv = pk.flash_block_bwd(q, k, v, out, lse, co,
                                    block_q=32, block_k=32)
    assert dk.shape == k.shape and dv.shape == v.shape

    def ref(q, k, v):
        s = jnp.einsum("bqd,bkd->bqk", q, k) / np.sqrt(d)
        return jnp.einsum("bqk,bkd->bqd", jax.nn.softmax(s, -1), v)

    want = jax.grad(lambda q, k, v: jnp.sum(ref(q, k, v) * co),
                    argnums=(0, 1, 2))(q, k, v)
    for a, b in zip((dq, dk, dv), want):
        assert float(jnp.max(jnp.abs(a - b))) < 5e-5


def _dense_causal(q, k, v):
    d = q.shape[-1]
    s = jnp.einsum("bqd,bkd->bqk", q, k) / np.sqrt(d)
    t = q.shape[1]
    s = jnp.where(jnp.tril(jnp.ones((t, t), bool))[None], s, -jnp.inf)
    return jnp.einsum("bqk,bkd->bqd", jax.nn.softmax(s, -1), v)


def test_reference_scan_matches_full_attention(rng):
    # the O(T)-memory backward path is itself correct
    bh, t, d = 3, 130, 16
    q, k, v = (jnp.asarray(rng.standard_normal((bh, t, d)), jnp.float32)
               for _ in range(3))
    got = pk._reference_scan(q, k, v, causal=True, block=64)
    s = jnp.einsum("bqd,bkd->bqk", q, k) / np.sqrt(d)
    s = jnp.where(jnp.tril(jnp.ones((t, t), bool))[None], s, -jnp.inf)
    want = jnp.einsum("bqk,bkd->bqd", jax.nn.softmax(s, -1), v)
    assert float(jnp.max(jnp.abs(got - want))) < 2e-5


# ---------------------------------------------------------------------------
# threshold codec
# ---------------------------------------------------------------------------
def test_threshold_codec_roundtrip(rng):
    g = jnp.asarray(rng.standard_normal(10_001), jnp.float32) * 0.01
    tau = 0.012
    packed, resid = pk.threshold_encode(g, tau)
    dense = pk.threshold_decode(packed, tau, g.size)
    expect = jnp.where(g > tau, tau, jnp.where(g < -tau, -tau, 0.0))
    assert np.allclose(dense, expect)
    assert np.allclose(resid, g - expect, atol=1e-7)
    # 2 bits per element on the wire
    assert packed.size * 4 <= g.size / 2


def test_threshold_codec_2d_shape(rng):
    g = jnp.asarray(rng.standard_normal((37, 53)), jnp.float32) * 0.1
    packed, resid = pk.threshold_encode(g, 0.05)
    dense = pk.threshold_decode(packed, 0.05, g.size, g.shape)
    assert dense.shape == g.shape and resid.shape == g.shape
    assert np.allclose(dense + resid, g, atol=1e-6)


def test_packed_exchange_multidevice(rng):
    """exchange_packed inside shard_map over the 8-device CPU mesh:
    identical result on every device, equals the mean of the decoded
    local updates (reference fan-out semantics)."""
    from jax.sharding import Mesh, PartitionSpec as P
    from jax import shard_map
    from deeplearning4j_tpu.parallel.compression import \
        EncodedGradientsAccumulator

    devs = np.array(jax.devices()[:8])
    mesh = Mesh(devs, ("data",))
    acc = EncodedGradientsAccumulator()
    grads = {"w": jnp.asarray(
        rng.standard_normal((8, 64)), jnp.float32) * 0.01}
    state = acc.init_state({"w": grads["w"][0]})

    def f(g, st):
        return acc.exchange_packed(g, st, axis_name="data")

    out, new_state = jax.jit(shard_map(
        f, mesh=mesh,
        in_specs=(P("data"), P()),
        out_specs=(P("data"), P()),
        check_vma=False))(grads, state)
    # every device got the same averaged update
    got = out["w"]                       # [8, 64] — one row per device
    assert np.allclose(got, got[0:1], atol=1e-6)
    tau = float(state["tau"])
    expect = np.mean([np.where(g > tau, tau,
                               np.where(g < -tau, -tau, 0.0))
                      for g in np.asarray(grads["w"])], axis=0)
    assert np.allclose(got[0], expect, atol=1e-6)


def test_attention_dispatch_uses_einsum_on_cpu(rng):
    # on CPU the helper dispatch must stay on the einsum path (float64
    # gradcheck support) — just exercises the guard
    q = jnp.asarray(rng.standard_normal((1, 1100, 1, 8)), jnp.float32)
    out = scaled_dot_attention(q, q, q)
    assert out.shape == q.shape


def test_flash_dispatch_gate(monkeypatch, rng):
    """Routing gate (VERDICT r3 #6): the flash path is chosen on the
    KEY length — cross-attention (Tq != Tk) and short-query/long-key
    shapes qualify; the threshold comes from DL4J_TPU_FLASH_MIN_T."""
    from deeplearning4j_tpu.nn.layers.attention import _use_flash
    monkeypatch.setattr(jax, "default_backend", lambda: "tpu")
    q_tiny = jnp.zeros((1, 8, 2, 16), jnp.float32)
    q_cross = jnp.zeros((1, 256, 2, 16), jnp.float32)
    q_long = jnp.zeros((1, 2048, 2, 16), jnp.float32)
    k_long = jnp.zeros((1, 2048, 2, 16), jnp.float32)
    k_short = jnp.zeros((1, 64, 2, 16), jnp.float32)
    assert _use_flash(q_long, k_long)           # self, long
    assert _use_flash(q_cross, k_long)          # cross, Tq != Tk
    # tiny Tq (scan-step query, learned-query pooling): einsum — the
    # kernel would pad Tq to a 128-row block per launch
    assert not _use_flash(q_tiny, k_long)
    assert not _use_flash(q_long, k_short)      # long q, short keys
    # causal Tq > Tk: the paths define keyless leading rows
    # differently — must stay einsum
    q_xl = jnp.zeros((1, 4096, 2, 16), jnp.float32)
    assert not _use_flash(q_xl, k_long, causal=True)
    assert _use_flash(q_xl, k_long)             # non-causal is fine
    with jax.enable_x64(True):
        assert not _use_flash(jnp.zeros((1, 2048, 2, 16), jnp.float64),
                              k_long)
    monkeypatch.setenv("DL4J_TPU_FLASH_MIN_T", "32")
    assert _use_flash(q_cross, k_short)         # threshold is a flag
    monkeypatch.setattr(jax, "default_backend", lambda: "cpu")
    assert not _use_flash(q_long, k_long)


def test_flash_dispatch_routes_cross_attention(monkeypatch, rng):
    """scaled_dot_attention actually hands Tq != Tk (and masked
    Ulysses-style full-T masked shapes) to the kernel when the gate
    passes — the pre-round-4 gate required Tq == Tk."""
    import deeplearning4j_tpu.ops.pallas_kernels as pk_mod
    calls = []
    monkeypatch.setattr(jax, "default_backend", lambda: "tpu")
    monkeypatch.setattr(
        pk_mod, "flash_attention",
        lambda q, k, v, causal=False, mask=None, **kw:
            calls.append((q.shape[1], k.shape[1], mask is not None))
            or jnp.zeros(q.shape, q.dtype))
    q = jnp.zeros((1, 256, 2, 16), jnp.float32)
    k = jnp.zeros((1, 2048, 2, 16), jnp.float32)
    mask = jnp.ones((1, 2048), jnp.float32)
    scaled_dot_attention(q, k, k, causal=True)            # cross
    scaled_dot_attention(k, k, k, mask=mask)              # masked full-T
    assert calls == [(256, 2048, False), (2048, 2048, True)]


@pytest.mark.parametrize("causal", [pytest.param(False, marks=_SLOW),
                                    True])
def test_flash_cross_attention_matches_einsum(rng, causal):
    """Tq != Tk through the kernel: end-aligned causal diagonal
    (tril(.., Tk - Tq)) and key masks must match the dense path,
    fwd and bwd."""
    B, TQ, TK, H, D = 2, 32, 96, 2, 16
    q = jnp.asarray(rng.standard_normal((B, TQ, H, D)), jnp.float32)
    k = jnp.asarray(rng.standard_normal((B, TK, H, D)), jnp.float32)
    v = jnp.asarray(rng.standard_normal((B, TK, H, D)), jnp.float32)
    mask = (jnp.arange(TK)[None, :]
            < jnp.asarray([[96], [61]])).astype(jnp.float32)
    co = jnp.asarray(rng.standard_normal((B, TQ, H, D)), jnp.float32)
    flash = lambda q, k, v: pk.flash_attention(
        q, k, v, causal=causal, mask=mask, block_q=32, block_k=32)
    ref = lambda q, k, v: scaled_dot_attention(
        q, k, v, mask=mask, causal=causal)
    np.testing.assert_allclose(np.asarray(flash(q, k, v)),
                               np.asarray(ref(q, k, v)),
                               rtol=1e-5, atol=2e-5)
    g1 = jax.grad(lambda *a: jnp.sum(flash(*a) * co),
                  argnums=(0, 1, 2))(q, k, v)
    g2 = jax.grad(lambda *a: jnp.sum(ref(*a) * co),
                  argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(g1, g2):
        assert float(jnp.max(jnp.abs(a - b))) < 5e-5
