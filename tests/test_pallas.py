"""Pallas kernel tests (interpret mode on CPU — same code path that
compiles with Mosaic on TPU). Reference coverage: libnd4j
encode_threshold/decode_threshold ops and the attention platform-helper
dispatch (SURVEY §2.1 platform helpers, §3.5 gradient compression)."""
import jax
import jax.numpy as jnp
import numpy as np
import pytest

from deeplearning4j_tpu.ops import pallas_kernels as pk
from deeplearning4j_tpu.nn.layers.attention import scaled_dot_attention


# ---------------------------------------------------------------------------
# flash attention
# ---------------------------------------------------------------------------
@pytest.mark.parametrize("causal", [False, True])
@pytest.mark.parametrize("t", [64, 200])
def test_flash_matches_reference(rng, causal, t):
    B, H, D = 2, 2, 32
    q, k, v = (jnp.asarray(rng.standard_normal((B, t, H, D)),
                           jnp.float32) for _ in range(3))
    ref = scaled_dot_attention(q, k, v, causal=causal)
    out = pk.flash_attention(q, k, v, causal=causal,
                             block_q=64, block_k=64)
    assert float(jnp.max(jnp.abs(ref - out))) < 2e-5


def test_flash_gradients_match_reference(rng):
    B, T, H, D = 1, 96, 2, 16
    q, k, v = (jnp.asarray(rng.standard_normal((B, T, H, D)),
                           jnp.float32) for _ in range(3))

    def loss(fn):
        return lambda q, k, v: jnp.sum(fn(q, k, v, causal=True) ** 2)

    g1 = jax.grad(loss(lambda *a, **kw: pk.flash_attention(
        *a, block_q=32, block_k=32, **kw)), argnums=(0, 1, 2))(q, k, v)
    g2 = jax.grad(loss(scaled_dot_attention), argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(g1, g2):
        assert float(jnp.max(jnp.abs(a - b))) < 5e-5


@pytest.mark.parametrize("causal", [False, True])
@pytest.mark.parametrize("t", [64, 200, 130])
def test_flash_backward_matches_reference(rng, causal, t):
    """The Pallas dQ/dKV kernels (FlashAttention-2 recompute style)
    must agree with autodiff through the einsum reference — including
    ragged lengths that exercise the padded-block masking."""
    B, H, D = 2, 2, 16
    q, k, v = (jnp.asarray(rng.standard_normal((B, t, H, D)),
                           jnp.float32) for _ in range(3))
    co = jnp.asarray(rng.standard_normal((B, t, H, D)), jnp.float32)

    def loss(fn):
        return lambda q, k, v: jnp.sum(fn(q, k, v, causal=causal) * co)

    g1 = jax.grad(loss(lambda *a, **kw: pk.flash_attention(
        *a, block_q=64, block_k=64, **kw)), argnums=(0, 1, 2))(q, k, v)
    g2 = jax.grad(loss(scaled_dot_attention),
                  argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(g1, g2):
        assert float(jnp.max(jnp.abs(a - b))) < 5e-5


def test_flash_backward_finite_difference(rng):
    """Directional finite-difference check straight through the Pallas
    custom_vjp (float64-free: central difference in f32 with a loose
    tolerance)."""
    B, T, H, D = 1, 40, 1, 8
    q, k, v = (jnp.asarray(rng.standard_normal((B, T, H, D)),
                           jnp.float32) * 0.5 for _ in range(3))

    def f(q, k, v):
        return jnp.sum(pk.flash_attention(
            q, k, v, causal=True, block_q=32, block_k=32) ** 2)

    grads = jax.grad(f, argnums=(0, 1, 2))(q, k, v)
    key = jax.random.PRNGKey(0)
    eps = 1e-2
    for idx, g in enumerate(grads):
        d = jax.random.normal(key, g.shape, jnp.float32)
        d = d / jnp.linalg.norm(d.reshape(-1))
        args = [q, k, v]
        ap = list(args); ap[idx] = args[idx] + eps * d
        am = list(args); am[idx] = args[idx] - eps * d
        fd = (f(*ap) - f(*am)) / (2 * eps)
        an = jnp.vdot(g, d)
        assert abs(float(fd - an)) < 5e-2 * max(1.0, abs(float(an)))


def test_flash_backward_bf16(rng):
    """bf16 inputs keep f32 accumulation in the backward kernels."""
    B, T, H, D = 1, 64, 2, 16
    qf, kf, vf = (jnp.asarray(rng.standard_normal((B, T, H, D)),
                              jnp.float32) for _ in range(3))
    q, k, v = (x.astype(jnp.bfloat16) for x in (qf, kf, vf))

    def loss(fn, *a):
        return jnp.sum(fn(*a, causal=False).astype(jnp.float32) ** 2)

    g1 = jax.grad(lambda *a: loss(lambda q, k, v, causal: pk.
                  flash_attention(q, k, v, causal, 32, 32), *a),
                  argnums=(0, 1, 2))(q, k, v)
    g2 = jax.grad(lambda *a: loss(
        lambda q, k, v, causal: scaled_dot_attention(
            q, k, v, causal=causal), *a), argnums=(0, 1, 2))(qf, kf, vf)
    for a, b in zip(g1, g2):
        err = float(jnp.max(jnp.abs(a.astype(jnp.float32) - b)))
        assert err < 0.15, err   # bf16 rounding, not accumulation error


def test_reference_scan_matches_full_attention(rng):
    # the O(T)-memory backward path is itself correct
    bh, t, d = 3, 130, 16
    q, k, v = (jnp.asarray(rng.standard_normal((bh, t, d)), jnp.float32)
               for _ in range(3))
    got = pk._reference_scan(q, k, v, causal=True, block=64)
    s = jnp.einsum("bqd,bkd->bqk", q, k) / np.sqrt(d)
    s = jnp.where(jnp.tril(jnp.ones((t, t), bool))[None], s, -jnp.inf)
    want = jnp.einsum("bqk,bkd->bqd", jax.nn.softmax(s, -1), v)
    assert float(jnp.max(jnp.abs(got - want))) < 2e-5


# ---------------------------------------------------------------------------
# threshold codec
# ---------------------------------------------------------------------------
def test_threshold_codec_roundtrip(rng):
    g = jnp.asarray(rng.standard_normal(10_001), jnp.float32) * 0.01
    tau = 0.012
    packed, resid = pk.threshold_encode(g, tau)
    dense = pk.threshold_decode(packed, tau, g.size)
    expect = jnp.where(g > tau, tau, jnp.where(g < -tau, -tau, 0.0))
    assert np.allclose(dense, expect)
    assert np.allclose(resid, g - expect, atol=1e-7)
    # 2 bits per element on the wire
    assert packed.size * 4 <= g.size / 2


def test_threshold_codec_2d_shape(rng):
    g = jnp.asarray(rng.standard_normal((37, 53)), jnp.float32) * 0.1
    packed, resid = pk.threshold_encode(g, 0.05)
    dense = pk.threshold_decode(packed, 0.05, g.size, g.shape)
    assert dense.shape == g.shape and resid.shape == g.shape
    assert np.allclose(dense + resid, g, atol=1e-6)


def test_packed_exchange_multidevice(rng):
    """exchange_packed inside shard_map over the 8-device CPU mesh:
    identical result on every device, equals the mean of the decoded
    local updates (reference fan-out semantics)."""
    from jax.sharding import Mesh, PartitionSpec as P
    from jax import shard_map
    from deeplearning4j_tpu.parallel.compression import \
        EncodedGradientsAccumulator

    devs = np.array(jax.devices()[:8])
    mesh = Mesh(devs, ("data",))
    acc = EncodedGradientsAccumulator()
    grads = {"w": jnp.asarray(
        rng.standard_normal((8, 64)), jnp.float32) * 0.01}
    state = acc.init_state({"w": grads["w"][0]})

    def f(g, st):
        return acc.exchange_packed(g, st, axis_name="data")

    out, new_state = jax.jit(shard_map(
        f, mesh=mesh,
        in_specs=(P("data"), P()),
        out_specs=(P("data"), P()),
        check_vma=False))(grads, state)
    # every device got the same averaged update
    got = out["w"]                       # [8, 64] — one row per device
    assert np.allclose(got, got[0:1], atol=1e-6)
    tau = float(state["tau"])
    expect = np.mean([np.where(g > tau, tau,
                               np.where(g < -tau, -tau, 0.0))
                      for g in np.asarray(grads["w"])], axis=0)
    assert np.allclose(got[0], expect, atol=1e-6)


def test_attention_dispatch_uses_einsum_on_cpu(rng):
    # on CPU the helper dispatch must stay on the einsum path (float64
    # gradcheck support) — just exercises the guard
    q = jnp.asarray(rng.standard_normal((1, 1100, 1, 8)), jnp.float32)
    out = scaled_dot_attention(q, q, q)
    assert out.shape == q.shape
