"""Arbiter hyperparameter optimization tests. Reference analog:
arbiter's TestRandomSearch / TestGridSearch / optimization runner
tests."""
import numpy as np
import pytest

from deeplearning4j_tpu.arbiter import (ContinuousParameterSpace,
                                        DiscreteParameterSpace,
                                        GridSearchGenerator,
                                        IntegerParameterSpace,
                                        OptimizationRunner,
                                        RandomSearchGenerator)


def test_parameter_spaces():
    rng = np.random.default_rng(0)
    c = ContinuousParameterSpace(0.1, 10.0, log=True)
    vals = [c.sample(rng) for _ in range(200)]
    assert all(0.1 <= v <= 10.0 for v in vals)
    # log-uniform: median near geometric mean, not arithmetic middle
    assert 0.5 < float(np.median(vals)) < 2.0
    g = c.grid(3)
    assert pytest.approx(g[1], rel=1e-6) == 1.0
    i = IntegerParameterSpace(2, 5)
    assert set(i.grid(4)) == {2, 3, 4, 5}
    assert all(2 <= i.sample(rng) <= 5 for _ in range(50))
    d = DiscreteParameterSpace(["a", "b"])
    assert d.grid(99) == ["a", "b"]


def test_grid_generator_enumerates_product():
    gen = GridSearchGenerator({
        "lr": DiscreteParameterSpace([0.1, 0.01]),
        "units": IntegerParameterSpace(8, 16),
    }, points_per_dim=2)
    combos = list(gen)
    assert len(combos) == 4
    assert {c["lr"] for c in combos} == {0.1, 0.01}


def test_runner_finds_minimum():
    # quadratic bowl: best candidate is the closest sample to x=3
    gen = RandomSearchGenerator(
        {"x": ContinuousParameterSpace(0.0, 10.0)}, seed=1)

    def score(c):
        return (c["x"] - 3.0) ** 2, None

    runner = OptimizationRunner(gen, score, max_candidates=40)
    best = runner.execute()
    assert abs(best.params["x"] - 3.0) < 0.5
    assert len(runner.results) == 40
    assert best.score == runner.best().score


def test_runner_trains_real_models():
    """End-to-end: arbiter searches hidden size + lr for a real net."""
    from deeplearning4j_tpu.nn import (MultiLayerNetwork,
                                       NeuralNetConfiguration)
    from deeplearning4j_tpu.nn.config import InputType
    from deeplearning4j_tpu.nn.layers import DenseLayer, OutputLayer
    from deeplearning4j_tpu.nn import updaters as upd

    rng = np.random.default_rng(0)
    x = rng.standard_normal((256, 4)).astype(np.float32)
    y = np.eye(2, dtype=np.float32)[(x.sum(1) > 0).astype(int)]

    def build_and_score(c):
        conf = (NeuralNetConfiguration.builder().seed(7)
                .updater(upd.Adam(learning_rate=c["lr"])).list()
                .layer(DenseLayer(n_out=c["units"], activation="tanh"))
                .layer(OutputLayer(n_out=2, activation="softmax",
                                   loss="mcxent"))
                .set_input_type(InputType.feed_forward(4)).build())
        net = MultiLayerNetwork(conf).init()
        for _ in range(15):
            net.fit(x, y)
        return net.score(), net

    runner = OptimizationRunner(
        RandomSearchGenerator({
            "lr": ContinuousParameterSpace(1e-4, 0.1, log=True),
            "units": DiscreteParameterSpace([4, 16]),
        }, seed=3),
        build_and_score, max_candidates=4, keep_models=True)
    best = runner.execute()
    assert best.score < 0.6
    assert best.model is not None
    assert best.seconds > 0


def test_runner_nan_scores_and_reentry():
    calls = []

    def score(c):
        calls.append(c["x"])
        # first candidate diverges
        return (float("nan") if len(calls) == 1
                else (c["x"] - 3.0) ** 2), None

    gen = RandomSearchGenerator(
        {"x": ContinuousParameterSpace(0.0, 10.0)}, seed=1)
    runner = OptimizationRunner(gen, score, max_candidates=10)
    best = runner.execute()
    assert not np.isnan(best.score)
    # re-entrant execute: results reset, same reproducible candidates
    n1 = len(runner.results)
    first_run_xs = [r.params["x"] for r in runner.results]
    calls.clear()
    runner.execute()
    assert len(runner.results) == n1
    assert [r.params["x"] for r in runner.results] == first_run_xs


def test_space_validation():
    with pytest.raises(ValueError):
        ContinuousParameterSpace(0.0, 1.0, log=True)
    with pytest.raises(ValueError):
        ContinuousParameterSpace(2.0, 1.0)
    with pytest.raises(ValueError):
        DiscreteParameterSpace([])
