"""Evaluation merge semantics (reference
``org.nd4j.evaluation.IEvaluation#merge``): evaluating shards
separately and merging must equal evaluating all data at once — the
reduction contract distributed evaluation
(``SparkDl4jMultiLayer#doEvaluation``) relies on."""
import numpy as np
import pytest

from deeplearning4j_tpu.eval_.evaluation import (
    Evaluation, EvaluationBinary, EvaluationCalibration,
    RegressionEvaluation, ROC, ROCBinary, ROCMultiClass)


@pytest.fixture
def cls_data(rng):
    n, c = 120, 4
    y = np.eye(c, dtype=np.float32)[rng.integers(0, c, n)]
    p = rng.random((n, c)).astype(np.float32)
    p /= p.sum(1, keepdims=True)
    return y, p


def _shards(y, p, k=3):
    idx = np.array_split(np.arange(len(y)), k)
    return [(y[i], p[i]) for i in idx]


def test_evaluation_merge_equals_full(cls_data):
    y, p = cls_data
    full = Evaluation()
    full.eval(y, p)
    merged = Evaluation()
    for ys, ps in _shards(y, p):
        e = Evaluation()
        e.eval(ys, ps)
        merged.merge(e)
    np.testing.assert_array_equal(merged.confusion, full.confusion)
    assert merged.count == full.count
    assert merged.accuracy() == full.accuracy()
    assert merged.f1() == full.f1()


def test_evaluation_merge_into_empty(cls_data):
    y, p = cls_data
    e = Evaluation()
    e.eval(y, p)
    empty = Evaluation()
    empty.merge(e)
    assert empty.accuracy() == e.accuracy()
    # and merging an empty one changes nothing
    e2 = Evaluation()
    e2.eval(y, p)
    e2.merge(Evaluation())
    assert e2.count == e.count


def test_evaluation_merge_class_mismatch_raises(cls_data):
    y, p = cls_data
    a = Evaluation()
    a.eval(y, p)
    b = Evaluation()
    b.eval(np.eye(3, dtype=np.float32)[[0, 1, 2]],
           np.eye(3, dtype=np.float32)[[0, 2, 1]])
    with pytest.raises(ValueError):
        a.merge(b)


def test_evaluation_merge_pinned_classes_empty_shard_raises(cls_data):
    """A pinned n_classes must be honoured even before any data lands
    on this shard (e.g. evaluate(num_classes=...) on a process whose
    shard was empty) — silent adoption of the other's count hides a
    config mismatch (ADVICE r3)."""
    y, p = cls_data
    other = Evaluation()
    other.eval(y, p)                       # n_classes from data
    pinned = Evaluation(n_classes=other.n_classes + 2)
    with pytest.raises(ValueError):
        pinned.merge(other)
    # same pin, matching count: merge proceeds
    ok = Evaluation(n_classes=other.n_classes)
    ok.merge(other)
    assert ok.accuracy() == other.accuracy()
    # direction-independent: data.merge(pinned-but-empty) raises too
    with pytest.raises(ValueError):
        other.merge(Evaluation(n_classes=other.n_classes + 2))
    # an empty accumulator ADOPTS a pin from an empty shard, so the
    # pin still gates later merges (tree-reduce order independence)
    acc = Evaluation()
    acc.merge(Evaluation(n_classes=other.n_classes + 2))
    assert acc.n_classes == other.n_classes + 2
    with pytest.raises(ValueError):
        acc.merge(other)


def test_evaluation_binary_merge(rng):
    y = (rng.random((80, 3)) > 0.5).astype(np.float32)
    p = rng.random((80, 3)).astype(np.float32)
    full = EvaluationBinary()
    full.eval(y, p)
    merged = EvaluationBinary()
    for ys, ps in _shards(y, p):
        e = EvaluationBinary()
        e.eval(ys, ps)
        merged.merge(e)
    for i in range(3):
        assert merged.f1(i) == full.f1(i)
        assert merged.accuracy(i) == full.accuracy(i)


def test_roc_merge(rng):
    y = (rng.random(200) > 0.5).astype(np.float32)
    p = rng.random(200).astype(np.float32)
    full = ROC()
    full.eval(y, p)
    merged = ROC()
    for ys, ps in _shards(y, p):
        r = ROC()
        r.eval(ys, ps)
        merged.merge(r)
    assert merged.calculate_auc() == pytest.approx(
        full.calculate_auc(), abs=1e-12)
    assert merged.calculate_auprc() == pytest.approx(
        full.calculate_auprc(), abs=1e-12)


def test_roc_multiclass_and_binary_merge(cls_data):
    y, p = cls_data
    for cls in (ROCMultiClass, ROCBinary):
        full = cls()
        full.eval(y, p)
        merged = cls()
        for ys, ps in _shards(y, p):
            r = cls()
            r.eval(ys, ps)
            merged.merge(r)
        assert merged.average_auc() == pytest.approx(
            full.average_auc(), abs=1e-12)


def test_calibration_merge(cls_data):
    y, p = cls_data
    full = EvaluationCalibration()
    full.eval(y, p)
    merged = EvaluationCalibration()
    for ys, ps in _shards(y, p):
        e = EvaluationCalibration()
        e.eval(ys, ps)
        merged.merge(e)
    assert merged.expected_calibration_error() == pytest.approx(
        full.expected_calibration_error(), abs=1e-12)


def test_regression_merge(rng):
    y = rng.standard_normal((90, 2))
    p = y + 0.1 * rng.standard_normal((90, 2))
    full = RegressionEvaluation()
    full.eval(y, p)
    merged = RegressionEvaluation()
    for ys, ps in _shards(y, p):
        e = RegressionEvaluation()
        e.eval(ys, ps)
        merged.merge(e)
    for col in range(2):
        assert merged.mean_squared_error(col) == pytest.approx(
            full.mean_squared_error(col), rel=1e-12)
        assert merged.r_squared(col) == pytest.approx(
            full.r_squared(col), rel=1e-12)
        assert merged.pearson_correlation(col) == pytest.approx(
            full.pearson_correlation(col), rel=1e-12)


def test_merge_across_processes_single_process(cls_data):
    """Single-process: merge_across_processes is the identity (the
    2-process path is exercised by tests/test_multiprocess.py)."""
    from deeplearning4j_tpu.parallel.master import merge_across_processes
    y, p = cls_data
    e = Evaluation()
    e.eval(y, p)
    out = merge_across_processes(e)
    assert out is e
    outs = merge_across_processes([e, e])
    assert outs == [e, e]
