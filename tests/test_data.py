"""Data pipeline tests. Reference analogs: CSVRecordReaderTest,
TestTransformProcess (datavec), NormalizerStandardizeTest (nd4j).
"""
import numpy as np
import pytest

from deeplearning4j_tpu.data import (AsyncDataSetIterator, DataSet,
                                     ListDataSetIterator,
                                     NormalizerMinMaxScaler,
                                     NormalizerStandardize,
                                     ImagePreProcessingScaler)
from deeplearning4j_tpu.data.records import (
    CSVRecordReader, CSVSequenceRecordReader, CollectionRecordReader,
    LineRecordReader, RecordReaderDataSetIterator, RegexLineRecordReader)
from deeplearning4j_tpu.data.transform import Schema, TransformProcess


CSV = "1.0,2.0,cat,0\n3.5,4.0,dog,1\n5.0,6.5,cat,0\n"


def test_csv_record_reader_parses():
    rr = CSVRecordReader(CSV)
    recs = list(rr)
    assert recs[0] == [1.0, 2.0, "cat", 0]
    assert recs[1][3] == 1


def test_csv_reader_from_file(tmp_path):
    p = tmp_path / "d.csv"
    p.write_text("h1,h2\n1,2\n3,4\n")
    rr = CSVRecordReader(p, skip_lines=1)
    assert list(rr) == [[1, 2], [3, 4]]


def test_line_and_regex_readers():
    assert list(LineRecordReader("a\nb"))[1] == ["b"]
    rr = RegexLineRecordReader("2024-01-01 INFO hello\n"
                               "2024-01-02 WARN bye",
                               r"(\S+) (\S+) (.*)")
    recs = list(rr)
    assert recs[0] == ["2024-01-01", "INFO", "hello"]
    assert recs[1][1] == "WARN"


def test_sequence_reader():
    seqs = list(CSVSequenceRecordReader(["1,2\n3,4", "5,6"]))
    assert seqs[0] == [[1, 2], [3, 4]]
    assert seqs[1] == [[5, 6]]


def test_record_reader_dataset_iterator_classification():
    rr = CollectionRecordReader([[0.1, 0.2, 0], [0.3, 0.4, 1],
                                 [0.5, 0.6, 2]])
    it = RecordReaderDataSetIterator(rr, batch_size=2, label_index=2,
                                     num_classes=3)
    batches = list(it)
    assert batches[0].features.shape == (2, 2)
    np.testing.assert_allclose(batches[0].labels,
                               [[1, 0, 0], [0, 1, 0]])
    assert batches[1].features.shape == (1, 2)


def test_record_reader_dataset_iterator_regression():
    rr = CollectionRecordReader([[0.1, 0.2, 1.5], [0.3, 0.4, 2.5]])
    it = RecordReaderDataSetIterator(rr, batch_size=2, label_index=2,
                                     regression=True)
    b = next(iter(it))
    np.testing.assert_allclose(b.labels, [[1.5], [2.5]])


def test_transform_process():
    schema = (Schema.builder()
              .add_column_double("a")
              .add_column_double("b")
              .add_column_categorical("animal", ["cat", "dog"])
              .add_column_integer("label")
              .build())
    tp = (TransformProcess.builder(schema)
          .categorical_to_one_hot("animal")
          .double_math_op("a", "multiply", 2.0)
          .double_column_math_op("ab", "add", "a", "b")
          .filter_by(lambda row: row["label"] == 0)
          .build())
    rows = tp.execute(list(CSVRecordReader(CSV)))
    # label==1 row filtered out
    assert len(rows) == 2
    # a doubled; one-hot expanded; ab appended
    assert rows[0] == [2.0, 2.0, 1, 0, 0, 4.0]
    fs = tp.final_schema()
    assert fs.names() == ["a", "b", "animal[cat]", "animal[dog]",
                          "label", "ab"]


def test_transform_normalize_and_remove():
    schema = (Schema.builder().add_column_double("x")
              .add_column_string("junk").build())
    tp = (TransformProcess.builder(schema)
          .remove_columns("junk")
          .normalize("x", "minmax", 0.0, 10.0)
          .build())
    rows = tp.execute([[5.0, "z"], [10.0, "y"]])
    np.testing.assert_allclose([r[0] for r in rows], [0.5, 1.0])


def test_normalizer_standardize_fit_transform_revert():
    rng = np.random.default_rng(0)
    x = rng.normal(5.0, 3.0, (500, 4)).astype(np.float32)
    ds = DataSet(x, np.zeros((500, 1)))
    n = NormalizerStandardize().fit(ds)
    t = n.transform(x)
    np.testing.assert_allclose(t.mean(0), 0, atol=1e-4)
    np.testing.assert_allclose(t.std(0), 1, atol=1e-3)
    np.testing.assert_allclose(n.revert(t), x, rtol=1e-4)
    # streaming fit over batches gives same stats
    n2 = NormalizerStandardize().fit(
        iter(ListDataSetIterator(ds, batch_size=100)))
    np.testing.assert_allclose(n.mean, n2.mean, rtol=1e-5)


def test_normalizer_minmax_and_image():
    x = np.array([[0.0, 5.0], [10.0, 15.0]], np.float32)
    n = NormalizerMinMaxScaler().fit(DataSet(x, x))
    t = n.transform(x)
    assert t.min() == 0 and t.max() == 1
    np.testing.assert_allclose(n.revert(t), x)
    img = ImagePreProcessingScaler()
    np.testing.assert_allclose(
        img.transform(np.array([0, 255], np.uint8)), [0.0, 1.0])


def test_normalizer_serialization_roundtrip():
    from deeplearning4j_tpu.data.normalizers import normalizer_from_state
    x = np.random.default_rng(1).normal(size=(50, 3)).astype(np.float32)
    n = NormalizerStandardize().fit(DataSet(x, x))
    n2 = normalizer_from_state(n.state_dict())
    np.testing.assert_allclose(n.transform(x), n2.transform(x))


def test_async_iterator_matches_sync():
    ds = DataSet(np.arange(40, dtype=np.float32).reshape(10, 4),
                 np.zeros((10, 2), np.float32))
    base = ListDataSetIterator(ds, batch_size=3)
    sync = [b.features.sum() for b in base]
    async_it = AsyncDataSetIterator(ListDataSetIterator(ds, batch_size=3))
    asy = [b.features.sum() for b in async_it]
    assert sync == asy


def test_async_iterator_propagates_errors():
    class Bad(ListDataSetIterator):
        def __iter__(self):
            yield DataSet(np.ones((2, 2)), np.ones((2, 1)))
            raise RuntimeError("boom")
    with pytest.raises(RuntimeError, match="boom"):
        list(AsyncDataSetIterator(Bad(None)))


def test_dataset_ops():
    ds = DataSet(np.arange(20).reshape(10, 2), np.arange(10))
    tr, te = ds.split_test_and_train(8)
    assert tr.num_examples() == 8 and te.num_examples() == 2
    sh = ds.shuffle(0)
    assert sorted(sh.labels.tolist()) == list(range(10))
    m = DataSet.merge([tr, te])
    assert m.num_examples() == 10


def test_tf_data_adapter():
    tf = pytest.importorskip("tensorflow")
    from deeplearning4j_tpu.data import TfDataSetIterator
    import numpy as np
    x = np.arange(40, dtype=np.float32).reshape(10, 4)
    y = np.eye(2, dtype=np.float32)[np.arange(10) % 2]
    ds = tf.data.Dataset.from_tensor_slices((x, y))
    it = TfDataSetIterator(ds, batch_size=4)   # adapter applies .batch(4)
    assert len(it) == 3
    batches = list(it)
    assert [b.features.shape[0] for b in batches] == [4, 4, 2]
    np.testing.assert_array_equal(batches[0].features, x[:4])
    # epochs restart cleanly; trains through the normal fit loop
    from deeplearning4j_tpu.nn import MultiLayerNetwork, \
        NeuralNetConfiguration
    from deeplearning4j_tpu.nn.config import InputType
    from deeplearning4j_tpu.nn.layers import DenseLayer, OutputLayer
    conf = (NeuralNetConfiguration.builder().seed(1).list()
            .layer(DenseLayer(n_out=8, activation="relu"))
            .layer(OutputLayer(n_out=2, activation="softmax",
                               loss="mcxent"))
            .set_input_type(InputType.feed_forward(4)).build())
    net = MultiLayerNetwork(conf).init()
    net.fit(it, epochs=2)
    assert net.iteration == 6


def test_tf_data_adapter_unlabeled_and_prebatched():
    tf = pytest.importorskip("tensorflow")
    from deeplearning4j_tpu.data import TfDataSetIterator
    import numpy as np
    x = np.arange(12, dtype=np.float32).reshape(6, 2)
    # pre-batched dataset consumed as-is (batch_size=None)
    pre = tf.data.Dataset.from_tensor_slices(x).batch(3)
    batches = list(TfDataSetIterator(pre))
    assert [b.features.shape for b in batches] == [(3, 2), (3, 2)]
    # unlabeled elements keep labels None (not an object array)
    assert all(b.labels is None for b in batches)


def test_reducer_group_by():
    from deeplearning4j_tpu.data.transform import Reducer, Schema
    schema = (Schema.builder()
              .add_column_string("city")
              .add_column_double("temp")
              .add_column_double("sales").build())
    records = [["nyc", 10.0, 1.0], ["sf", 20.0, 2.0],
               ["nyc", 30.0, 3.0], ["sf", 16.0, 4.0],
               ["nyc", 20.0, 5.0]]
    red = (Reducer.Builder("city")
           .mean_columns("temp").sum_columns("sales").build())
    out = red.reduce(schema, records)
    assert out == [["nyc", 20.0, 9.0], ["sf", 18.0, 6.0]]
    os_ = red.output_schema(schema)
    assert os_.names() == ["city", "temp", "sales"]
    # count/stdev/count_unique ops
    red2 = (Reducer.Builder("city").count_columns("temp")
            .count_unique_columns("sales").build())
    out2 = red2.reduce(schema, records)
    assert out2 == [["nyc", 3, 3], ["sf", 2, 2]]


def test_join_types():
    from deeplearning4j_tpu.data.transform import Join, Schema
    left = (Schema.builder().add_column_integer("id")
            .add_column_string("name").build())
    right = (Schema.builder().add_column_integer("id")
             .add_column_double("score").build())
    L = [[1, "a"], [2, "b"], [3, "c"]]
    R = [[2, 20.0], [3, 30.0], [4, 40.0]]

    def mk(t):
        return (Join.Builder(t).set_schemas(left, right)
                .set_keys("id").build())
    assert mk(Join.INNER).execute(L, R) == [[2, "b", 20.0],
                                            [3, "c", 30.0]]
    assert mk(Join.LEFT_OUTER).execute(L, R) == [
        [1, "a", None], [2, "b", 20.0], [3, "c", 30.0]]
    ro = mk(Join.RIGHT_OUTER).execute(L, R)
    assert [2, "b", 20.0] in ro and [4, None, 40.0] in ro
    fo = mk(Join.FULL_OUTER).execute(L, R)
    assert [1, "a", None] in fo and [4, None, 40.0] in fo
    assert mk(Join.INNER).output_schema().names() == ["id", "name",
                                                      "score"]


def test_reducer_schema_and_join_validation():
    from deeplearning4j_tpu.data.transform import Join, Reducer, Schema
    schema = (Schema.builder().add_column_string("k")
              .add_column_string("label")
              .add_column_double("v").build())
    red = Reducer.Builder("k").mean_columns("v").build()  # label: first
    os_ = red.output_schema(schema)
    # value-preserving default op keeps the string type
    assert os_.type_of("label") == "string"
    assert os_.type_of("v") == "double"
    out = red.reduce(schema, [["a", "x", 1.0], ["a", "y", 3.0]])
    assert out == [["a", "x", 2.0]]
    # stdev is correct (ddof=1)
    red2 = Reducer.Builder("k").stdev_columns("v").build()
    out2 = red2.reduce(schema, [["a", "x", 1.0], ["a", "y", 3.0]])
    assert abs(out2[0][2] - 2 ** 0.5) < 1e-9
    import pytest as _pytest
    with _pytest.raises(ValueError):
        Join.Builder("left_outer")


def test_dataset_fetchers_synthetic():
    from deeplearning4j_tpu.data import (Cifar10DataSetIterator,
                                         EmnistDataSetIterator,
                                         IrisDataSetIterator,
                                         SvhnDataSetIterator)
    em = EmnistDataSetIterator("LETTERS", batch_size=32, n_examples=128)
    b = next(iter(em))
    assert b.features.shape == (32, 28, 28, 1)
    assert b.labels.shape == (32, 26) and em.synthetic
    # deterministic across constructions
    em2 = EmnistDataSetIterator("LETTERS", batch_size=32, n_examples=128)
    np.testing.assert_array_equal(next(iter(em2)).features, b.features)
    with pytest.raises(ValueError):
        EmnistDataSetIterator("NOPE")

    cf = Cifar10DataSetIterator(batch_size=16, n_examples=64)
    bc = next(iter(cf))
    assert bc.features.shape == (16, 32, 32, 3)
    assert bc.labels.shape == (16, 10)
    sv = SvhnDataSetIterator(batch_size=8, n_examples=32)
    assert next(iter(sv)).features.shape == (8, 32, 32, 3)

    ir = IrisDataSetIterator(batch_size=150)
    bi = next(iter(ir))
    assert bi.features.shape == (150, 4) and bi.labels.shape == (150, 3)
    # separable enough to learn quickly
    from deeplearning4j_tpu.nn import MultiLayerNetwork, \
        NeuralNetConfiguration
    from deeplearning4j_tpu.nn.config import InputType
    from deeplearning4j_tpu.nn.layers import DenseLayer, OutputLayer
    from deeplearning4j_tpu.nn import updaters as upd
    conf = (NeuralNetConfiguration.builder().seed(2)
            .updater(upd.Adam(learning_rate=0.05)).list()
            .layer(DenseLayer(n_out=16, activation="tanh"))
            .layer(OutputLayer(n_out=3, activation="softmax",
                               loss="mcxent"))
            .set_input_type(InputType.feed_forward(4)).build())
    net = MultiLayerNetwork(conf).init()
    net.fit(ir, epochs=60)
    ev = net.evaluate(ir)
    assert ev.accuracy() > 0.9, ev.accuracy()


def test_async_iterator_tracks_etl_wait():
    """Reference PerformanceListener ETL-wait metric: the async wrapper
    accumulates consumer block time."""
    import time as _t
    from deeplearning4j_tpu.data import AsyncDataSetIterator, DataSet

    class SlowBase:
        batch_size = 4

        def __iter__(self):
            for _ in range(3):
                _t.sleep(0.02)
                yield DataSet(np.zeros((4, 2), np.float32),
                              np.zeros((4, 2), np.float32))

    it = AsyncDataSetIterator(SlowBase(), queue_size=1)
    n = sum(1 for _ in it)
    assert n == 3
    assert it.etl_wait_seconds > 0.01


def test_performance_listener_reports_etl(capsys):
    from deeplearning4j_tpu.train.listeners import PerformanceListener
    from deeplearning4j_tpu.data import AsyncDataSetIterator

    class _B:
        batch_size = 1

        def __iter__(self):
            return iter([])

    it = AsyncDataSetIterator(_B())
    it.etl_wait_seconds = 0.5
    msgs = []
    pl = PerformanceListener(frequency=1, report=msgs.append,
                             iterator=it)

    class FakeNet:
        def score(self):
            return 1.0
    pl.iteration_done(FakeNet(), 1, 0)
    pl.iteration_done(FakeNet(), 2, 0)
    assert any("ETL wait" in m for m in msgs)


def test_bucketed_sequence_iterator():
    """Variable-T batches snap to bucket lengths (bounded retraces),
    masks keep semantics exact."""
    from deeplearning4j_tpu.data import (BucketedSequenceIterator,
                                         DataSet, ListDataSetIterator)
    batches = []
    for t in (5, 17, 33, 300):
        batches.append(DataSet(np.ones((2, t, 3), np.float32),
                               np.ones((2, t, 4), np.float32)))
    it = BucketedSequenceIterator(ListDataSetIterator(batches),
                                  buckets=(16, 32, 64))
    out = list(it)
    assert [d.features.shape[1] for d in out] == [16, 32, 64, 300]
    # padded region masked out, real region mask 1
    d0 = out[0]
    assert d0.features_mask.shape == (2, 16)
    assert d0.features_mask[:, :5].all()
    assert not d0.features_mask[:, 5:].any()
    assert d0.labels.shape == (2, 16, 4)
    assert d0.labels_mask[:, 5:].sum() == 0
    # pre-masked input: original mask preserved under padding
    masked = DataSet(np.ones((1, 10, 3), np.float32),
                     np.ones((1, 10, 4), np.float32),
                     features_mask=np.concatenate(
                         [np.ones((1, 7)), np.zeros((1, 3))], 1))
    out2 = list(BucketedSequenceIterator(
        ListDataSetIterator([masked]), buckets=(16,)))[0]
    assert out2.features_mask[0, :7].all()
    assert not out2.features_mask[0, 7:].any()


def test_bucketing_preserves_per_sequence_label_mask():
    """Regression: 2D (per-sequence) labels keep their mask unpadded."""
    from deeplearning4j_tpu.data import (BucketedSequenceIterator,
                                         DataSet, ListDataSetIterator)
    ds = DataSet(np.ones((2, 5, 3), np.float32),
                 np.ones((2, 4), np.float32),          # per-sequence
                 labels_mask=np.ones((2, 1), np.float32))
    out = list(BucketedSequenceIterator(
        ListDataSetIterator([ds]), buckets=(8,)))[0]
    assert out.features.shape == (2, 8, 3)
    assert out.labels.shape == (2, 4)                  # untouched
    assert out.labels_mask.shape == (2, 1)             # untouched
