"""Regression gate: importing ANY deeplearning4j_tpu submodule must not
initialise a jax backend or create device arrays.

VERDICT r3 Missing #3: module-level ``jnp.asarray`` in
``autodiff/ops_registry_ext.py`` initialised the accelerator backend at
import, hanging SameDiff and the TF/ONNX importers whenever the axon
tunnel was down. The reference's backend initialises on first use,
never at class-load (SURVEY §3.1 — upstream
``org.nd4j.linalg.factory.Nd4j`` static init defers native backend
selection to the first array op). This test fences the whole class of
bug: every submodule is imported in a cpu-forced subprocess and the jax
backend cache must stay empty afterwards.

Module enumeration is filesystem-based on purpose: ``pkgutil``'s
walkers import package ``__init__``s in THIS process (no cpu override —
a regression would hang collection) and swallow ImportErrors.
"""
import pathlib
import subprocess
import sys

import deeplearning4j_tpu

PKG_ROOT = pathlib.Path(deeplearning4j_tpu.__file__).parent


def _all_submodules():
    """Every importable module in the package, from the filesystem —
    nothing is imported here."""
    names = ["deeplearning4j_tpu"]
    for py in sorted(PKG_ROOT.rglob("*.py")):
        rel = py.relative_to(PKG_ROOT)
        parts = ("deeplearning4j_tpu",) + rel.with_suffix("").parts
        if parts[-1] == "__init__":
            parts = parts[:-1]
        names.append(".".join(parts))
    return sorted(set(names))


_CHECK = r"""
import jax
jax.config.update("jax_platforms", "cpu")  # sitecustomize forces axon
import importlib, sys
# Direct (non-getattr) access: if a jax upgrade moves this private
# cache the test must fail loudly, not pass vacuously.
from jax._src.xla_bridge import _backends

offenders = []
for name in sys.argv[1:]:
    importlib.import_module(name)
    # NB: jax.live_arrays() itself initialises a backend, so the only
    # safe detector is the backend cache (a device array cannot exist
    # without a backend entry).
    if _backends:
        offenders.append((name, list(_backends)))
        break  # first offender poisons the rest; report and stop
if offenders:
    print("BACKEND_TOUCHED_AT_IMPORT", offenders)
    raise SystemExit(1)
print("CLEAN", len(sys.argv) - 1)
"""


def test_no_submodule_initialises_backend_at_import():
    mods = _all_submodules()
    assert len(mods) > 60, f"submodule walk looks broken: {len(mods)}"
    r = subprocess.run(
        [sys.executable, "-c", _CHECK, *mods],
        capture_output=True, text=True, timeout=600,
        cwd=str(PKG_ROOT.parent),
    )
    assert r.returncode == 0, (
        f"a submodule touched the backend at import:\n{r.stdout}\n{r.stderr}"
    )
    assert "CLEAN" in r.stdout
