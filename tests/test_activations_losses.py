"""Activation/loss tests w/ finite-difference gradient checks.

Reference analogs: ActivationFunctionTests, LossFunctionGradientCheck
(deeplearning4j-core gradientcheck suite).
"""
import jax
import jax.numpy as jnp
import numpy as np
import pytest

from deeplearning4j_tpu.ops import activations, losses
from deeplearning4j_tpu.utils import check_gradients


def test_activation_registry_resolves_all():
    for name in activations.names():
        fn = activations.get(name)
        out = fn(jnp.linspace(-2, 2, 8))
        assert out.shape == (8,)


def test_activation_known_values():
    x = jnp.array([-1.0, 0.0, 2.0])
    np.testing.assert_allclose(activations.get("relu")(x), [0, 0, 2])
    np.testing.assert_allclose(activations.get("sigmoid")(x),
                               1 / (1 + np.exp([1.0, 0.0, -2.0])), rtol=1e-6)
    np.testing.assert_allclose(
        activations.get("softmax")(x).sum(), 1.0, rtol=1e-6)
    np.testing.assert_allclose(activations.get("hardtanh")(x), [-1, 0, 1])
    np.testing.assert_allclose(activations.get("cube")(x), [-1, 0, 8])


def test_unknown_activation_raises():
    with pytest.raises(ValueError):
        activations.get("nope")


@pytest.mark.parametrize("name", ["mse", "mae", "mcxent", "xent", "hinge",
                                  "squared_hinge", "kl_divergence",
                                  "poisson", "cosine_proximity"])
def test_loss_gradients_finite_difference(name, rng):
    n, k = 3, 4
    preds = jnp.asarray(rng.uniform(0.05, 0.95, (n, k)))
    if name in ("mcxent", "kl_divergence"):
        lab = rng.uniform(size=(n, k))
        labels = jnp.asarray(lab / lab.sum(-1, keepdims=True))
        preds = preds / preds.sum(-1, keepdims=True)
    elif name in ("xent",):
        labels = jnp.asarray(rng.integers(0, 2, (n, k)).astype(float))
    elif name in ("hinge", "squared_hinge"):
        labels = jnp.asarray(rng.choice([-1.0, 1.0], (n, k)))
        preds = jnp.asarray(rng.normal(size=(n, k)))
    else:
        labels = jnp.asarray(rng.normal(size=(n, k)))
        if name == "poisson":
            labels = jnp.abs(labels)
    fn = losses.get(name)
    check_gradients(lambda p, l: fn(l, p), preds, labels)


def test_mcxent_from_logits_matches_softmax_path(rng):
    logits = jnp.asarray(rng.normal(size=(5, 7)))
    lab = jax.nn.one_hot(jnp.asarray(rng.integers(0, 7, 5)), 7)
    a = losses.mcxent(lab, jax.nn.softmax(logits), from_logits=False)
    b = losses.mcxent(lab, logits, from_logits=True)
    np.testing.assert_allclose(a, b, rtol=1e-5)


def test_sparse_mcxent_matches_dense(rng):
    logits = jnp.asarray(rng.normal(size=(5, 7)))
    idx = jnp.asarray(rng.integers(0, 7, 5))
    dense = losses.mcxent(jax.nn.one_hot(idx, 7), logits, from_logits=True)
    sparse = losses.sparse_mcxent(idx, logits, from_logits=True)
    np.testing.assert_allclose(dense, sparse, rtol=1e-6)


def test_binary_xent_logits_stable():
    big = jnp.array([[100.0, -100.0]])
    lab = jnp.array([[1.0, 0.0]])
    val = losses.binary_xent(lab, big, from_logits=True)
    assert jnp.isfinite(val) and val < 1e-3


def test_loss_masking():
    labels = jnp.ones((2, 3, 4))
    preds = jnp.zeros((2, 3, 4))
    mask = jnp.array([[1.0, 1.0, 0.0], [1.0, 0.0, 0.0]])  # [B,T]
    full = losses.mse(labels, preds)
    masked = losses.mse(labels, preds, mask=mask)
    assert full > 0 and masked > 0
    # all-ones mask must be identical to no mask (reference semantics)
    np.testing.assert_allclose(
        losses.mse(labels, preds, mask=jnp.ones((2, 3))), full, rtol=1e-6)
    # masked steps contribute 0: (2 active + 1 active) steps * 4 feats / 2
    np.testing.assert_allclose(masked, (2 * 4 + 1 * 4) / 2, rtol=1e-6)
    # all-masked timesteps contribute nothing
    zero_mask = jnp.zeros((2, 3))
    assert losses.mse(labels, preds, mask=zero_mask) == 0


def test_ndarray_unhashable_and_eval_shape():
    import jax
    from deeplearning4j_tpu import Nd4j
    a = Nd4j.create([1.0])
    with pytest.raises(TypeError):
        hash(a)
    out = jax.eval_shape(lambda d: d["w"].add(1.0),
                         {"w": Nd4j.create([1.0, 2.0])})
    assert out.shape == (2,)


def test_fmeasure_mask_and_default_dtype_guard():
    from deeplearning4j_tpu import dtypes
    labels = jnp.array([[1.0, 0.0], [1.0, 1.0]])
    preds = jnp.array([[0.9, 0.1], [0.2, 0.8]])
    m = jnp.array([[1.0, 1.0], [0.0, 0.0]])
    masked = losses.fmeasure(labels, preds, mask=m)
    only_first = losses.fmeasure(labels[:1], preds[:1])
    np.testing.assert_allclose(masked, only_first, rtol=1e-6)
    with pytest.raises(ValueError):
        dtypes.set_default_dtype("int32")


def test_score_array_per_example(rng):
    labels = jnp.asarray(rng.normal(size=(6, 3)))
    preds = jnp.asarray(rng.normal(size=(6, 3)))
    per = losses.score_array("mse", labels, preds)
    assert per.shape == (6,)
    np.testing.assert_allclose(per.mean(), losses.mse(labels, preds),
                               rtol=1e-5)


def test_ctc_loss_runs(rng):
    logits = jnp.asarray(rng.normal(size=(2, 10, 6)))
    labels = jnp.asarray(rng.integers(1, 6, (2, 4)))
    val = losses.ctc_loss(labels, logits,
                          jnp.array([4, 3]), jnp.array([10, 8]))
    assert jnp.isfinite(val)


def test_wants_f32_logits_gate():
    """Single source of truth for the half-precision loss cast: only
    fused losses that declare handles_low_precision_logits skip the
    f32 upcast (round-4 review: the gate was copy-pasted at 3 sites
    and the tBPTT one missed)."""
    from deeplearning4j_tpu.ops import losses as L
    assert not L.wants_f32_logits(L.get("sparse_mcxent"), fused=True)
    assert L.wants_f32_logits(L.get("sparse_mcxent"), fused=False)
    assert L.wants_f32_logits(L.get("mcxent"), fused=True)
    assert L.wants_f32_logits(lambda y, p, mask=None: 0.0, fused=True)


def test_sparse_mcxent_bf16_logits_match_f32():
    """The logsumexp-formulated from-logits path accepts bf16 logits
    (f32 accumulation inside): loss within bf16 rounding of the f32
    reference, gradients finite."""
    import jax
    import jax.numpy as jnp
    from deeplearning4j_tpu.ops import losses as L
    rng = np.random.default_rng(0)
    logits = rng.standard_normal((4, 16, 512)).astype(np.float32) * 3
    labels = rng.integers(0, 512, (4, 16)).astype(np.int32)
    fn = L.get("sparse_mcxent")
    f32 = float(fn(labels, jnp.asarray(logits), from_logits=True))
    bf16 = float(fn(labels, jnp.asarray(logits, jnp.bfloat16),
                    from_logits=True))
    assert abs(f32 - bf16) < 0.03 * abs(f32) + 1e-3
    g = jax.grad(lambda x: fn(labels, x, from_logits=True))(
        jnp.asarray(logits, jnp.bfloat16))
    assert bool(jnp.isfinite(g.astype(jnp.float32)).all())
