"""OpValidation — every registered samediff op is validated, coverage-
gated like the reference's ``org.nd4j.autodiff.opvalidation`` framework
(SURVEY §4: "coverage-tracked so unvalidated ops fail CI"): forward
executed (finite + shape), float ops finite-difference gradient-checked
in float64, and — where a trusted producer exists — compared against
numpy goldens.
"""
import numpy as np
import pytest
import jax
import jax.numpy as jnp

from deeplearning4j_tpu.autodiff.ops_registry import OPS, get_op

R = np.random.default_rng(7)


def A(*shape, pos=False, lo=None, hi=None, dtype=np.float64):
    a = R.standard_normal(shape)
    if pos:
        a = np.abs(a) + 0.5
    if lo is not None:
        a = np.clip(a, lo, hi)
    return a.astype(dtype)


# name -> (args, kwargs, flags). flags: g=gradcheck, golden=np callable
CASES = {}


def case(name, *args, g=True, golden=None, **kwargs):
    CASES.setdefault(name, []).append((list(args), kwargs, g, golden))


# --- elementwise unary ------------------------------------------------------
for n, gold, dom in [
    ("abs", np.abs, {}), ("exp", np.exp, {}), ("neg", lambda a: -a, {}),
    ("log", np.log, {"pos": True}), ("log1p", np.log1p, {"pos": True}),
    ("sqrt", np.sqrt, {"pos": True}), ("square", np.square, {}),
    ("reciprocal", lambda a: 1 / a, {"pos": True}),
    ("sin", np.sin, {}), ("cos", np.cos, {}), ("tan", np.tan, {}),
    ("asin", np.arcsin, {"lo": -0.9, "hi": 0.9}),
    ("acos", np.arccos, {"lo": -0.9, "hi": 0.9}),
    ("atan", np.arctan, {}), ("sinh", np.sinh, {}),
    ("cosh", np.cosh, {}), ("tanh", np.tanh, {}),
    ("expm1", np.expm1, {}), ("log2", np.log2, {"pos": True}),
    ("log10", np.log10, {"pos": True}), ("cbrt", np.cbrt, {"pos": True}),
    ("asinh", np.arcsinh, {}),
    ("acosh", lambda a: np.arccosh(a + 1.5), None),
    ("atanh", np.arctanh, {"lo": -0.9, "hi": 0.9}),
    ("cube", lambda a: a ** 3, {}),
]:
    if n == "acosh":
        case(n, A(3, 4, pos=True) + 1.5, golden=np.arccosh)
    else:
        case(n, A(3, 4, **dom), golden=gold)

for n in ["sigmoid", "softplus", "softsign", "swish", "gelu", "elu",
          "selu", "relu", "relu6", "hard_sigmoid", "hard_tanh",
          "log_sigmoid", "mish", "erf", "erfc", "lgamma", "digamma",
          "rsqrt", "rect_tanh"]:
    case(n, A(3, 4, pos=(n in ("lgamma", "digamma", "rsqrt"))),
         g=(n not in ("relu", "relu6", "hard_tanh", "rect_tanh")))
case("leaky_relu", A(3, 4), alpha=0.1)
case("prelu", A(3, 4), A(4, pos=True))
case("mish", A(3, 4))

# non-differentiable unaries: forward only
case("sign", A(3, 4), g=False, golden=np.sign)
case("floor", A(3, 4), g=False, golden=np.floor)
case("ceil", A(3, 4), g=False, golden=np.ceil)
case("round", A(3, 4), g=False, golden=np.round)
case("step", A(3, 4), g=False, cutoff=0.0)
case("is_nan", A(3, 4), g=False, golden=np.isnan)
case("is_inf", A(3, 4), g=False, golden=np.isinf)
case("zero_fraction", np.array([[0.0, 1.0], [2.0, 0.0]]), g=False)
case("clip_by_value", A(3, 4), g=False, min=-0.5, max=0.5,
     golden=lambda a: np.clip(a, -0.5, 0.5))

# --- binary -----------------------------------------------------------------
for n, gold in [("add", np.add), ("sub", np.subtract),
                ("mul", np.multiply), ("maximum", np.maximum),
                ("minimum", np.minimum), ("atan2", np.arctan2),
                ("hypot", np.hypot), ("logaddexp", np.logaddexp),
                ("squared_difference", lambda a, b: (a - b) ** 2)]:
    case(n, A(3, 4), A(3, 4), golden=gold)
case("div", A(3, 4), A(3, 4, pos=True), golden=np.divide)
case("rsub", A(3, 4), A(3, 4), golden=lambda a, b: b - a)
case("rdiv", A(3, 4, pos=True), A(3, 4), golden=lambda a, b: b / a)
case("pow", A(3, 4, pos=True), A(3, 4), golden=np.power)
case("floormod", A(3, 4), A(3, 4, pos=True), g=False, golden=np.mod)
case("xlogy", A(3, 4, pos=True), A(3, 4, pos=True))
for n in ["eq", "neq", "gt", "gte", "lt", "lte"]:
    case(n, A(3, 4), A(3, 4), g=False)
b1, b2 = A(3, 4) > 0, A(3, 4) > 0
case("logical_and", b1, b2, g=False, golden=np.logical_and)
case("logical_or", b1, b2, g=False, golden=np.logical_or)
case("logical_not", b1, g=False, golden=np.logical_not)
case("where", b1, A(3, 4), A(3, 4), g=False)

# --- matmul / linalg --------------------------------------------------------
case("matmul", A(3, 4), A(4, 5), golden=np.matmul)
case("matmul", A(3, 4), A(5, 4), transpose_b=True)
case("reshape_dynamic", A(2, 6), np.array([3, 4], np.int32), g=False,
     golden=lambda a, s: np.reshape(a, [3, 4]))
case("reshape_sym", A(2, 6), A(3, 9), entries=[[0, 0], -1], g=False,
     golden=lambda a, s: np.reshape(a, [3, -1]))
case("einsum", A(3, 4), A(4, 5), equation="ij,jk->ik",
     golden=lambda a, b: np.einsum("ij,jk->ik", a, b))
case("einsum", A(2, 3, 4), A(2, 4, 5), equation="bij,bjk->bik",
     golden=lambda a, b: np.einsum("bij,bjk->bik", a, b))
case("dot", A(4), A(4), golden=np.dot)
case("tensordot", A(3, 4), A(4, 5), axes=1)
case("linear", A(5, 3), A(3, 2), A(2))
case("bias_add", A(5, 3), A(3))
spd = (lambda m: m @ m.T + 3 * np.eye(4))(A(4, 4))
case("cholesky", spd, g=False, golden=np.linalg.cholesky)
case("matrix_inverse", spd, golden=np.linalg.inv)
case("matrix_determinant", spd, golden=np.linalg.det)
case("log_matrix_determinant", spd,
     golden=lambda a: np.linalg.slogdet(a)[1])
case("solve", spd, A(4, 2), golden=np.linalg.solve)
case("triangular_solve", np.linalg.cholesky(spd), A(4, 2), g=False,
     lower=True)
case("qr", A(4, 3), g=False)
case("svd", A(4, 3), g=False)
case("lstsq", A(5, 3), A(5, 2), g=False)
case("eye", g=False, n=3, m=4, golden=None)
case("trace", A(4, 4), golden=np.trace)
case("diag", A(4), g=False, golden=np.diag)
case("diag_part", A(4, 4), g=False, golden=np.diagonal)
case("triu", A(4, 4), g=False, golden=np.triu)
case("tril", A(4, 4), g=False, golden=np.tril)
case("cross", A(3), A(3), golden=np.cross)
case("kron", A(2, 2), A(3, 3), g=False, golden=np.kron)
case("outer", A(3), A(4), golden=np.outer)

# --- reductions -------------------------------------------------------------
for n, gold in [("sum", np.sum), ("mean", np.mean), ("max", np.max),
                ("min", np.min), ("prod", np.prod), ("std", np.std),
                ("variance", np.var)]:
    case(n, A(3, 4, pos=(n == "prod")), axis=1,
         g=(n not in ("max", "min")),
         golden=lambda a, _g=gold: _g(a, axis=1))
case("sum", A(3, 4), axis=None, golden=np.sum)
case("norm1", A(3, 4), axis=1,
     golden=lambda a: np.abs(a).sum(1))
case("norm2", A(3, 4), axis=1,
     golden=lambda a: np.sqrt((a ** 2).sum(1)))
case("norm_max", A(3, 4), axis=1, g=False,
     golden=lambda a: np.abs(a).max(1))
for n in ["amax", "amin", "amean"]:
    case(n, A(3, 4), axis=1, g=False)
case("count_nonzero", np.array([[1.0, 0.0], [2.0, 3.0]]), g=False,
     axis=1)
case("entropy", np.array([0.2, 0.3, 0.5]))
case("log_entropy", np.array([0.2, 0.3, 0.5]))
case("moments", A(3, 4), axis=0)
case("argmax", A(3, 4), g=False, axis=1,
     golden=lambda a: np.argmax(a, 1))
case("argmin", A(3, 4), g=False, axis=1,
     golden=lambda a: np.argmin(a, 1))
case("cumsum", A(3, 4), axis=1, golden=lambda a: np.cumsum(a, 1))
case("cumprod", A(3, 4), axis=1, golden=lambda a: np.cumprod(a, 1))
case("logsumexp", A(3, 4), axis=1)

# --- distances --------------------------------------------------------------
case("euclidean_distance", A(5), A(5),
     golden=lambda a, b: np.linalg.norm(a - b))
case("manhattan_distance", A(5), A(5),
     golden=lambda a, b: np.abs(a - b).sum())
case("cosine_similarity", A(5), A(5))
case("cosine_distance", A(5), A(5))
case("hamming_distance", np.array([1.0, 2, 3]), np.array([1.0, 0, 3]),
     g=False)
case("jaccard_distance", A(5, pos=True), A(5, pos=True), g=False)
case("dot_product", A(5), A(5), golden=lambda a, b: a @ b)

# --- shape ops --------------------------------------------------------------
case("reshape", A(3, 4), g=False, shape=(4, 3))
case("transpose", A(3, 4), g=False, golden=np.transpose)
case("permute", A(2, 3, 4), g=False, axes=(2, 0, 1))
case("expand_dims", A(3, 4), g=False, axis=1)
case("squeeze", A(3, 1, 4), g=False, axis=1)
case("concat", A(2, 3), A(2, 3), g=False, axis=0)
case("stack", A(2, 3), A(2, 3), g=False, axis=0)
case("unstack", A(3, 4), g=False, axis=0, num=3)
case("split", A(4, 6), g=False, num=2, axis=1)
case("tile", A(2, 3), g=False, reps=(2, 2))
case("gather", A(5, 3), np.array([0, 2, 4]), g=False, axis=0)
case("gather_nd", A(4, 5), np.array([[0, 1], [2, 3]]), g=False)
case("take_along_axis", A(3, 4), np.array([[0], [1], [2]]), g=False,
     axis=1)
case("slice", A(5, 6), g=False, begin=(1, 2), size=(2, 3))
case("strided_slice", A(6, 6), g=False, begin=(0, 1), end=(4, 5),
     strides=(2, 1))
case("getitem", A(5, 6), g=False,
     spec=[{"t": "int", "v": 2},
           {"t": "slice", "start": 1, "stop": 4, "step": 1}])
case("cast", A(3, 4), g=False, dtype="float32")
case("shape_of", A(3, 4), g=False)
case("one_hot", np.array([0, 2, 1]), g=False, depth=3)
case("reverse", A(3, 4), g=False, axis=1,
     golden=lambda a: np.flip(a, 1))
case("pad", A(2, 3), g=False, paddings=((1, 1), (0, 2)),
     golden=lambda a: np.pad(a, ((1, 1), (0, 2))))
case("roll", A(3, 4), g=False, shift=2, axis=1,
     golden=lambda a: np.roll(a, 2, 1))
case("linspace", g=False, start=0.0, stop=1.0, num=5)
case("arange", g=False, start=0, stop=10, step=2)
case("meshgrid", A(3), A(4), g=False)
case("full_like", A(2, 2), g=False, value=7.0)
case("zeros_like", A(2, 2), g=False, golden=np.zeros_like)
case("ones_like", A(2, 2), g=False, golden=np.ones_like)

# --- sorting / search -------------------------------------------------------
case("sort", A(4, 5), g=False, axis=1, golden=lambda a: np.sort(a, 1))
case("sort", A(4, 5), g=False, axis=1, descending=True)
case("argsort", A(4, 5), g=False, axis=1)
case("top_k", A(4, 6), g=False, k=2)
case("in_top_k", A(4, 6), np.array([1, 2, 3, 0]), g=False, k=3)
case("searchsorted", np.sort(A(8)), A(3), g=False)

# --- scatter / segment ------------------------------------------------------
case("scatter_update", A(5, 3), np.array([0, 2]), A(2, 3), g=False)
case("scatter_add", A(5, 3), np.array([0, 2]), A(2, 3), g=False)
case("scatter_sub", A(5, 3), np.array([0, 2]), A(2, 3), g=False)
case("scatter_mul", A(5, 3), np.array([0, 2]), A(2, 3), g=False)
case("scatter_max", A(5, 3), np.array([0, 2]), A(2, 3), g=False)
case("scatter_min", A(5, 3), np.array([0, 2]), A(2, 3), g=False)
seg_ids = np.array([0, 0, 1, 2, 2])
case("segment_sum", A(5, 3), seg_ids, g=False, num_segments=3)
case("segment_max", A(5, 3), seg_ids, g=False, num_segments=3)
case("segment_min", A(5, 3), seg_ids, g=False, num_segments=3)
case("segment_mean", A(5, 3), seg_ids, g=False, num_segments=3)

# --- nn / conv / pool / attention ------------------------------------------
case("softmax", A(3, 5), axis=-1)
case("log_softmax", A(3, 5), axis=-1)
case("layer_norm", A(4, 6), A(6, pos=True), A(6))
case("batch_norm", A(4, 6), A(6), A(6, pos=True), A(6, pos=True), A(6))
case("dropout", A(4, 6), g=False, rate=0.5, seed=0, deterministic=True)
case("conv2d", A(1, 8, 8, 2), A(3, 3, 2, 4), strides=(1, 1),
     padding="SAME")
case("depthwise_conv2d", A(1, 8, 8, 2), A(3, 3, 2, 2), g=False,
     strides=(1, 1), padding="SAME")
case("max_pooling2d", A(1, 8, 8, 2), g=False, kernel=(2, 2),
     strides=(2, 2))
case("avg_pooling2d", A(1, 8, 8, 2), kernel=(2, 2), strides=(2, 2))
case("dot_product_attention", A(2, 4, 8), A(2, 6, 8), A(2, 6, 8))
case("resize_bilinear", A(1, 4, 4, 2), g=False, size=(8, 8))
case("resize_nearest", A(1, 4, 4, 2), g=False, size=(8, 8))
case("space_to_depth", A(1, 4, 4, 3), g=False, block_size=2)
case("depth_to_space", A(1, 2, 2, 12), g=False, block_size=2)

# --- losses -----------------------------------------------------------------
lbl5 = np.eye(5)[R.integers(0, 5, 4)].astype(np.float64)
case("loss_mse", lbl5, A(4, 5))
case("loss_mae", lbl5, A(4, 5))
case("loss_softmax_cross_entropy", lbl5, A(4, 5))
case("loss_sparse_softmax_cross_entropy",
     R.integers(0, 5, 4).astype(np.float64), A(4, 5), g=False)
case("loss_sigmoid_cross_entropy", (A(4, 5) > 0).astype(np.float64),
     A(4, 5))
case("loss_log", (A(4, 5) > 0).astype(np.float64),
     A(4, 5, lo=0.05, hi=0.95))
case("loss_huber", lbl5, A(4, 5))
case("loss_cosine_distance", lbl5, A(4, 5))
case("ctc_loss", np.array([[1, 2], [2, 1]], np.float64),
     A(2, 6, 4), np.array([2.0, 2.0]), np.array([6.0, 5.0]), g=False)

# --- random -----------------------------------------------------------------
case("random_normal", g=False, shape=(3, 4), seed=1)
case("random_uniform", g=False, shape=(3, 4), seed=1, minval=2.0,
     maxval=3.0)
case("random_bernoulli", g=False, shape=(100,), seed=1, p=0.3)

# ===========================================================================
# extended surface (ops_registry_ext) — every op needs a case (gate below)
# ===========================================================================
I32 = np.array([[12, 5], [-7, 3]], np.int32)

# math / transforms
case("rint", A(3, 4), g=False, golden=np.rint)
case("trunc", A(3, 4), g=False, golden=np.trunc)
case("mod", A(3, 4), A(3, 4, pos=True), g=False, golden=np.mod)
case("truncatediv", A(3, 4), A(3, 4, pos=True), g=False)
case("truncatemod", A(3, 4), A(3, 4, pos=True), g=False,
     golden=np.fmod)
case("divide_no_nan", A(2, 2), np.array([[0.0, 1], [2, 0]]), g=False)
case("igamma", A(3, pos=True), A(3, pos=True), g=False)
case("igammac", A(3, pos=True), A(3, pos=True), g=False)
case("betainc", A(3, pos=True), A(3, pos=True),
     np.array([0.2, 0.5, 0.8]), g=False)
case("polygamma", np.array([1.0, 2.0]), A(2, pos=True), g=False)
case("zeta", np.array([2.0, 3.0]), np.array([1.0, 1.5]), g=False)
case("erfinv", np.array([-0.5, 0.0, 0.5]))
case("precise_gelu", A(3, 4))
case("identity", A(3, 4), golden=lambda a: a)
case("assign", A(3, 4), A(3, 4), g=False, golden=lambda a, b: b)
case("assign_add", A(3, 4), A(3, 4), golden=np.add)
case("assign_sub", A(3, 4), A(3, 4), golden=np.subtract)
case("stop_gradient", A(3, 4), g=False, golden=lambda a: a)
case("thresholdedrelu", A(3, 4), g=False, theta=0.5)
case("mergeadd", A(3), A(3), A(3), golden=lambda a, b, c: a + b + c)
case("mergeavg", A(3), A(3), golden=lambda a, b: (a + b) / 2)
case("mergemax", A(3), A(3), g=False, golden=np.maximum)
case("mergemaxindex", A(3), A(3), g=False)
case("check_numerics", A(3, 4), g=False, golden=lambda a: a)
case("standardize", A(4, 6), axis=-1)
case("clip_by_norm", A(3, 4), clip_norm=1.0)
case("clip_by_avg_norm", A(3, 4), clip_norm=1.0)
case("clip_by_global_norm", A(3), A(3), g=False, clip_norm=1.0)
case("axpy", A(3, 4), A(3, 4), alpha=2.0,
     golden=lambda x, y: 2.0 * x + y)
case("realdiv", A(3, 4), A(3, 4, pos=True), golden=np.divide)
case("floordiv", A(3, 4), A(3, 4, pos=True), g=False,
     golden=np.floor_divide)
case("select", A(3, 4) > 0, A(3, 4), A(3, 4), g=False)
case("choose", A(8), g=False, condition="gt", value=0.0)
case("boolean_mask", A(5), np.array([1, 0, 1, 1, 0], bool), g=False)

# bitwise
case("bitwise_and", I32, I32 + 1, g=False, golden=np.bitwise_and)
case("bitwise_or", I32, I32 + 1, g=False, golden=np.bitwise_or)
case("bitwise_xor", I32, I32 + 1, g=False, golden=np.bitwise_xor)
case("toggle_bits", I32, g=False, golden=np.bitwise_not)
case("shift_bits", I32, np.int32(2), g=False)
case("rshift_bits", I32, np.int32(2), g=False)
case("cyclic_shift_bits", I32, np.int32(3), g=False)
case("cyclic_rshift_bits", I32, np.int32(3), g=False)
case("bitcast", np.array([1.0, 2.0], np.float32), g=False,
     dtype="int32")
case("compare_and_bitpack", A(2, 8), g=False, threshold=0.0)
case("bits_hamming_distance", I32, I32 + 1, g=False)

# reductions / index
case("all", np.array([[1.0, 0], [1, 1]]), g=False, axis=1)
case("any", np.array([[1.0, 0], [0, 0]]), g=False, axis=1)
case("asum", A(3, 4), axis=1, g=False,
     golden=lambda a: np.abs(a).sum(1))
case("sqnorm", A(3, 4), axis=1, golden=lambda a: (a ** 2).sum(1))
case("count_zero", np.array([[0.0, 1], [0, 0]]), g=False, axis=1)
case("reduce_dot", A(3, 4), A(3, 4), axis=1,
     golden=lambda a, b: (a * b).sum(1))
case("percentile", A(20), g=False, q=50)
case("median", A(21), g=False, golden=np.median)
case("iamax", A(6), g=False, golden=lambda a: np.argmax(np.abs(a)))
case("iamin", A(6), g=False, golden=lambda a: np.argmin(np.abs(a)))
case("first_index", A(8), g=False, condition="gt", value=0.0)
case("last_index", A(8), g=False, condition="gt", value=0.0)
case("match_condition", A(8), g=False, condition="lt", value=0.0)
case("match_condition_transform", A(8), g=False, condition="lt",
     value=0.0)
case("norm", A(3, 4), g=False, ord=2, axis=1)
case("histogram", A(30), g=False, nbins=5)
case("histogram_fixed_width", A(30), g=False, range=(-2.0, 2.0),
     nbins=5)
case("bincount", np.array([0, 1, 1, 3], np.int32), g=False, length=4,
     golden=lambda a: np.bincount(a, minlength=4))

# shape / gather-scatter
case("broadcast_to", A(4), g=False, shape=(3, 4))
case("flatten", A(3, 4), g=False, golden=np.ravel)
case("rank", A(3, 4), g=False)
case("size", A(3, 4), g=False)
case("size_at", A(3, 4), g=False, dim=1)
case("repeat", A(3), g=False, repeats=2, axis=0,
     golden=lambda a: np.repeat(a, 2, 0))
case("fill", g=False, shape=(2, 3), value=7.0)
case("ones", g=False, shape=(2, 3), golden=None)
case("zeros", g=False, shape=(2, 3))
case("empty", g=False, shape=(2, 3))
case("tri", g=False, n=4)
case("logspace", g=False, start=0.0, stop=2.0, num=5)
case("invert_permutation", np.array([2, 0, 1], np.int32), g=False)
case("matrix_diag", A(4), g=False)
case("matrix_diag_part", A(4, 4), g=False,
     golden=lambda a: np.diagonal(a, axis1=-2, axis2=-1))
case("matrix_set_diag", A(4, 4), A(4), g=False)
case("matrix_band_part", A(4, 4), g=False, num_lower=1, num_upper=1)
case("matrix_power", A(3, 3), g=False, n=2)
case("reverse_sequence", A(2, 5, 3), np.array([3, 5], np.int32),
     g=False)
case("sequence_mask", np.array([1, 3], np.int32), g=False, maxlen=4)
case("confusion_matrix", np.array([0, 1, 1], np.int32),
     np.array([0, 1, 0], np.int32), g=False, num_classes=2)
case("unique", np.array([3.0, 1, 3, 2]), g=False, size=3)
case("unique_with_counts", np.array([3.0, 1, 3, 2]), g=False, size=3)
case("listdiff", np.array([1.0, 2, 3, 4]), np.array([2.0, 4]),
     g=False)
case("dynamic_partition", A(6), np.array([0, 1, 0, 1, 0, 1]),
     g=False, num_partitions=2)
case("dynamic_stitch", np.array([0, 2], np.int32),
     np.array([1, 3], np.int32), A(2), A(2), g=False)
case("scatter_nd", np.array([[0], [2]], np.int32), A(2), g=False,
     shape=(5,))
case("scatter_nd_add", A(5), np.array([[0], [2]], np.int32), A(2),
     g=False)
case("scatter_nd_sub", A(5), np.array([[0], [2]], np.int32), A(2),
     g=False)
case("scatter_nd_update", A(5), np.array([[0], [2]], np.int32), A(2),
     g=False)
case("scatter_div", A(5, 3), np.array([0, 2]), A(2, 3, pos=True),
     g=False)
case("segment_prod", A(5, pos=True), seg_ids, g=False, num_segments=3)
for _n in ["unsorted_segment_sum", "unsorted_segment_max",
           "unsorted_segment_min", "unsorted_segment_prod",
           "unsorted_segment_mean", "unsorted_segment_sqrt_n"]:
    case(_n, A(5, pos=True), np.array([0, 1, 0, 2, 1]), g=False,
         num_segments=3)
case("nth_element", A(4, 6), g=False, n=2)
case("batch_to_space", A(4, 2, 2, 1), g=False, block_size=2,
     crops=[[0, 0], [0, 0]])
case("space_to_batch", A(1, 4, 4, 1), g=False, block_size=2,
     paddings=[[0, 0], [0, 0]])
case("batch_to_space_nd", A(4, 2, 2, 1), g=False, block_shape=[2, 2],
     crops=[[0, 0], [0, 0]])
case("space_to_batch_nd", A(1, 4, 4, 1), g=False, block_shape=[2, 2],
     paddings=[[0, 0], [0, 0]])
case("mirror_pad", A(3, 4), g=False, paddings=((1, 1), (1, 1)),
     golden=lambda a: np.pad(a, ((1, 1), (1, 1)), mode="reflect"))
case("split_v", A(8), g=False, sizes=[3, 5])
case("cumsum_exclusive", A(5), axis=0)
case("rot90", A(1, 3, 3, 2), g=False, k=1)
case("flip_left_right", A(1, 3, 3, 2), g=False)
case("flip_up_down", A(1, 3, 3, 2), g=False)

# nn / conv / pool / recurrent
case("conv1d", A(1, 8, 2), A(3, 2, 4), stride=1, padding="SAME")
case("conv3d", A(1, 4, 4, 4, 2), A(2, 2, 2, 2, 3), g=False,
     padding="VALID")
case("deconv2d", A(1, 4, 4, 2), A(2, 2, 2, 3), g=False,
     strides=(2, 2))
case("deconv3d", A(1, 2, 2, 2, 2), A(2, 2, 2, 2, 3), g=False,
     strides=(2, 2, 2))
case("sconv2d", A(1, 6, 6, 2), A(3, 3, 2, 2), A(1, 1, 4, 5), g=False)
case("max_pooling3d", A(1, 4, 4, 4, 2), g=False)
case("avg_pooling3d", A(1, 4, 4, 4, 2), g=False)
case("pnormpool2d", A(1, 4, 4, 2, pos=True), g=False, pnorm=2)
case("max_pool_with_argmax", A(1, 4, 4, 2), g=False)
case("im2col", A(1, 5, 5, 2), g=False, kernel=(3, 3))
case("col2im", A(1, 3, 3, 18), g=False, input_shape=(1, 5, 5, 2),
     kernel=(3, 3))
case("extract_image_patches", A(1, 5, 5, 2), g=False, kernel=(3, 3))
case("lrn", A(1, 4, 4, 8), depth=3)
case("fused_batch_norm", A(2, 4, 4, 3), A(3, pos=True), A(3), g=False)
case("xw_plus_b", A(5, 3), A(3, 2), A(2))
case("relu_layer", A(5, 3), A(3, 2), A(2), g=False)
case("embedding_lookup", A(10, 4), np.array([0, 3, 7], np.int32),
     g=False)
case("upsampling2d", A(1, 3, 3, 2), g=False, factor=2)
case("upsampling3d", A(1, 2, 2, 2, 2), g=False, factor=2)
case("dilation2d", A(1, 5, 5, 2), A(2, 2, 2), g=False)
case("multi_head_dot_product_attention", A(2, 4, 8), A(2, 6, 8),
     A(2, 6, 8), A(8, 8), A(8, 8), A(8, 8), A(8, 8), g=False,
     num_heads=2)
case("lstm_cell", A(2, 3), A(2, 4), A(2, 4), A(3, 16), A(4, 16),
     A(16), g=False)
case("gru_cell", A(2, 3), A(2, 4), A(3, 12), A(4, 12), A(12), g=False)
case("sru_cell", A(2, 4), A(2, 4), A(4, 12), A(8), g=False)
case("lstm_layer", A(3, 2, 3), np.zeros((2, 4)), np.zeros((2, 4)),
     A(3, 16), A(4, 16), A(16), g=False)
case("lstmBlock", A(3, 2, 3), np.zeros((2, 4)), np.zeros((2, 4)),
     A(3, 16), A(4, 16), A(16), g=False)
case("gru", A(3, 2, 3), np.zeros((2, 4)), A(3, 12), A(4, 12), A(12),
     g=False)
case("sru", A(3, 2, 4), np.zeros((2, 4)), A(4, 12), A(8), g=False)
case("static_bidirectional_rnn", A(3, 2, 3), np.zeros((2, 4)),
     np.zeros((2, 4)), np.zeros((2, 4)), np.zeros((2, 4)),
     A(3, 16), A(4, 16), A(16), A(3, 16), A(4, 16), A(16), g=False)
case("ctc_greedy_decoder", A(2, 5, 4), np.array([5, 4], np.int32),
     g=False)

# updater ops (functional: (grad, state...) -> (update, state'...))
_g4 = A(4)
_z4 = np.zeros(4)
case("sgd_updater", _g4, g=False, lr=0.1,
     golden=lambda g: 0.1 * g)
case("adam_updater", _g4, _z4, _z4, g=False, lr=0.1)
case("ada_max_updater", _g4, _z4, _z4, g=False, lr=0.1)
case("nadam_updater", _g4, _z4, _z4, g=False, lr=0.1)
case("ams_grad_updater", _g4, _z4, _z4, _z4, g=False, lr=0.1)
case("ada_delta_updater", _g4, _z4, _z4, g=False)
case("ada_grad_updater", _g4, _z4, g=False, lr=0.1)
case("rms_prop_updater", _g4, _z4, g=False, lr=0.1)
case("nesterovs_updater", _g4, _z4, g=False, lr=0.1)
case("ada_belief_updater", _g4, _z4, _z4, g=False, lr=0.1)

# losses / moments
_bl = (A(4, 5) > 0).astype(np.float64)
case("absolute_difference_loss", lbl5, A(4, 5))
case("l2_loss", A(4, 5), golden=lambda a: (a ** 2).sum() / 2)
case("log_poisson_loss", np.abs(A(4)) + 0.5, A(4))
case("mean_pairwssqerr_loss", lbl5, A(4, 5), g=False)
case("weighted_cross_entropy_with_logits", _bl, A(4, 5),
     pos_weight=2.0)
case("hinge_loss", _bl, A(4, 5), g=False)
case("softmax_cross_entropy_with_logits", lbl5, A(4, 5))
case("sigmoid_cross_entropy_with_logits", _bl, A(4, 5))
case("sufficient_statistics", A(3, 4), g=False, axis=[0])
case("normalize_moments", np.array(12.0), A(4), A(4, pos=True) + 4,
     g=False)
case("weighted_moments", A(3, 4), np.abs(A(3, 4)) + 0.1, g=False,
     axis=(0,))

# image
case("resize_bicubic", A(1, 4, 4, 2), g=False, size=(8, 8))
case("resize_area", A(1, 4, 4, 2), g=False, size=(2, 2))
case("image_resize", A(1, 4, 4, 2), g=False, size=(8, 8),
     method="bilinear")
_img = np.abs(A(2, 4, 4, 3)) % 1.0
case("rgb_to_grs", _img, g=False)
case("rgb_to_hsv", _img, g=False)
case("hsv_to_rgb", _img, g=False)
case("rgb_to_yuv", _img)
case("yuv_to_rgb", _img)
case("rgb_to_yiq", _img)
case("yiq_to_rgb", _img)
case("rgb_to_bgr", _img, g=False, golden=lambda a: a[..., ::-1])
case("adjust_contrast", _img, g=False, factor=1.5)
case("adjust_hue", _img, g=False, delta=0.1)
case("adjust_saturation", _img, g=False, factor=1.2)
_boxes = np.array([[0, 0, 1, 1], [0, 0, 0.9, 0.9], [0.5, 0.5, 1, 1]],
                  np.float64)
case("non_max_suppression", _boxes, np.array([0.9, 0.8, 0.7]),
     g=False, max_output_size=2)
case("non_max_suppression_overlaps", np.eye(3), np.array([0.9, 0.8,
                                                          0.7]),
     g=False, max_output_size=2)
case("crop_and_resize", _img, np.array([[0.0, 0.0, 1.0, 1.0]]),
     np.array([0], np.int32), g=False, crop_size=(2, 2))
case("draw_bounding_boxes", _img,
     np.tile(_boxes[None, :1], (2, 1, 1)), g=False)

# random
case("random_exponential", g=False, shape=(10,), seed=1)
case("random_gamma", g=False, shape=(10,), seed=1, alpha=2.0)
case("random_poisson", g=False, shape=(10,), seed=1, lam=3.0)
case("random_shuffle", A(8), g=False, seed=1)
case("random_multinomial", A(2, 5), g=False, num_samples=4, seed=1)
case("truncated_normal", g=False, shape=(10,), seed=1)
case("log_normal", g=False, shape=(10,), seed=1)
case("alpha_dropout", A(4, 5), g=False, rate=0.5, seed=0,
     deterministic=True)
case("dropout_inverted", A(4, 5), g=False, rate=0.5, seed=0,
     deterministic=True)
case("random_crop", A(6, 6, 2), g=False, size=(3, 3, 2), seed=1)

# linalg extras
case("lu", spd, g=False)
case("self_adjoint_eig", spd, g=False)
case("batched_gemm", A(2, 3, 4), A(2, 4, 5), golden=np.matmul)
case("gemm", A(3, 4), A(4, 5), A(3, 5), g=False, alpha=2.0, beta=0.5)
case("tensormmul", A(3, 4), A(4, 5), g=False, axes=1)

# compression codec
_sg = A(16)
case("encode_threshold", _sg, g=False, threshold=0.5)
case("decode_threshold", np.sign(_sg), g=False, threshold=0.5)
case("encode_bitmap", np.sign(_sg), g=False)
# bitmaps are packed uint8 words (8 elements/byte): 0b0101, 0b1010
case("decode_bitmap", np.array([5, 0], np.uint8),
     np.array([10, 0], np.uint8), g=False, size=16)

# casts
for _cn in ["to_float32", "to_float16", "to_bfloat16", "to_double",
            "to_int32", "to_int64", "to_uint8"]:
    case(_cn, np.abs(A(3, 4)), g=False)

# batch 3: native declarable-name aliases (same args as their targets)
for _an in ["greater", "greater_equal", "less", "less_equal", "equals",
            "not_equals"]:
    case(_an, A(3, 4), A(3, 4), g=False)
for _an in ["reduce_mean", "reduce_sum", "reduce_max", "reduce_min",
            "reduce_variance", "reduce_stdev", "reduce_logsumexp",
            "reduce_norm1", "reduce_norm2", "reduce_norm_max",
            "reduce_sqnorm"]:
    case(_an, A(3, 4), g=False, axis=1)
case("reduce_prod", A(3, 4, pos=True), g=False, axis=1)
case("maxpool2d", A(1, 4, 4, 2), g=False)
case("avgpool2d", A(1, 4, 4, 2), g=False)
case("maxpool3dnew", A(1, 4, 4, 4, 2), g=False)
case("avgpool3dnew", A(1, 4, 4, 4, 2), g=False)
case("conv3dnew", A(1, 4, 4, 4, 2), A(2, 2, 2, 2, 3), g=False,
     padding="VALID")
case("batchnorm", A(4, 6), A(6), A(6, pos=True), A(6, pos=True), A(6),
     g=False)
case("zeros_as", A(2, 2), g=False, golden=np.zeros_like)
case("ones_as", A(2, 2), g=False, golden=np.ones_like)
case("lin_space", g=False, start=0.0, stop=1.0, num=5)
case("range", g=False, start=0, stop=6, step=2)
case("randomuniform", g=False, shape=(3,), seed=1)
case("onehot", np.array([0, 2, 1]), g=False, depth=3)
case("reversev2", A(3, 4), g=False, axis=1)
case("logdet", spd, g=False)
case("det", spd, g=False, golden=np.linalg.det)
case("solve_ls", A(5, 3), A(5, 2), g=False)
case("batch_matmul", A(2, 3, 4), A(2, 4, 5), g=False,
     golden=np.matmul)
case("resize_neighbor", A(1, 4, 4, 2), g=False, size=(8, 8))
case("resize_linear", A(1, 4, 4, 2), g=False, size=(8, 8))
case("adjust_contrast_v2", _img, g=False, factor=1.5)
case("apply_gradient_descent", _g4, g=False, lr=0.1)
case("huber_loss", lbl5, A(4, 5), g=False)
case("log_loss", (A(4, 5) > 0).astype(np.float64),
     A(4, 5, lo=0.05, hi=0.95), g=False)
case("mean_sqerr_loss", lbl5, A(4, 5), g=False)
case("cosine_distance_loss", lbl5, A(4, 5), g=False)
case("softmax_cross_entropy_loss", lbl5, A(4, 5), g=False)
case("sparse_softmax_cross_entropy_loss",
     R.integers(0, 5, 4).astype(np.float64), A(4, 5), g=False)
case("sigm_cross_entropy_loss", _bl, A(4, 5), g=False)

# batch 3: new implementations
case("is_finite", A(3, 4), g=False, golden=np.isfinite)
case("is_numeric_tensor", A(3, 4), g=False)
case("equals_with_eps", A(3, 4), A(3, 4), g=False, eps=1e-5)
case("where_np", A(3, 4) > 0, A(3, 4), A(3, 4), g=False)
case("Assert", np.array([True, True]), g=False)
case("set_seed", g=False, seed=42)
case("get_seed", g=False)
case("fake_quant_with_min_max_args", A(3, 4), g=False, min=-3.0,
     max=3.0)
case("fake_quant_with_min_max_vars", A(3, 4), np.array(-3.0),
     np.array(3.0), g=False)
case("fake_quant_with_min_max_vars_per_channel", A(3, 4),
     np.full(4, -3.0), np.full(4, 3.0), g=False)
case("static_rnn", A(3, 2, 3), np.zeros((2, 4)), A(3, 4), A(4, 4),
     A(4), g=False)
case("dynamic_rnn", A(3, 2, 3), np.zeros((2, 4)), A(3, 4), A(4, 4),
     A(4), np.array([2, 3]), g=False)
case("dynamic_bidirectional_rnn", A(3, 2, 3), np.zeros((2, 4)),
     np.zeros((2, 4)), A(3, 4), A(4, 4), A(4), A(3, 4), A(4, 4),
     A(4), np.array([2, 3]), g=False)
case("ctc_beam", A(1, 4, 3), np.array([4], np.int32), g=False,
     beam_width=3)

# batch 4: list ops, embeddings training, final aliases
case("create_list", g=False)
case("size_list", (A(3), A(3)), g=False)
case("read_list", (A(3), A(3)), g=False, idx=1)
case("stack_list", (A(3), A(3)), g=False)
case("unstack_list", A(3, 4), g=False)
case("gather_list", (A(3), A(3), A(3)), np.array([2, 0]), g=False)
case("scatter_list", A(3, 4), np.array([2, 0, 1]), g=False)
case("split_list", A(8), g=False, sizes=[3, 5])
case("write_list", (A(3),), A(3), g=False, idx=1)
_emb0 = np.abs(A(10, 4)) * 0.1
case("skipgram", _emb0, _emb0, np.array([1, 2]), np.array([3, 4]),
     np.array([[5, 6], [7, 8]]), g=False)
case("cbow", _emb0, _emb0, np.array([[1, 2], [3, 4]]),
     np.array([5, 6]), np.array([[7, 8], [0, 9]]), g=False)
case("eig", A(3, 3), g=False)
case("hashcode", A(3, 3), g=False)
case("random_flip_left_right", _img, g=False, seed=0)
case("random_flip_up_down", _img, g=False, seed=0)
case("per_image_standardization", _img, g=False)
case("subtract", A(3, 4), A(3, 4), g=False, golden=np.subtract)
case("multiply", A(3, 4), A(3, 4), g=False, golden=np.multiply)
case("divide", A(3, 4), A(3, 4, pos=True), g=False, golden=np.divide)
case("fmod", A(3, 4), A(3, 4, pos=True), g=False, golden=np.fmod)
case("scatter_upd", A(5, 3), np.array([0, 2]), A(2, 3), g=False)
case("parallel_stack", A(2, 3), A(2, 3), g=False, axis=0)
case("lup", spd, g=False)
case("clipbyvalue", A(3, 4), g=False, min=-0.5, max=0.5)
case("clipbynorm", A(3, 4), g=False, clip_norm=1.0)
case("clipbyavgnorm", A(3, 4), g=False, clip_norm=1.0)
case("clipbyglobalnorm", A(3), A(3), g=False, clip_norm=1.0)
case("lstmCell", A(2, 3), A(2, 4), A(2, 4), A(3, 16), A(4, 16),
     A(16), g=False)
case("gruCell", A(2, 3), A(2, 4), A(3, 12), A(4, 12), A(12), g=False)
case("sruCell", A(2, 4), A(2, 4), A(4, 12), A(8), g=False)
case("lstmLayer", A(3, 2, 3), np.zeros((2, 4)), np.zeros((2, 4)),
     A(3, 16), A(4, 16), A(16), g=False)
case("dot_product_attention_v2", A(2, 4, 8), A(2, 6, 8), A(2, 6, 8),
     g=False)


def test_every_op_has_validation_case():
    """The coverage gate: adding an op without a validation case fails
    CI (reference OpValidation coverage tracking)."""
    missing = sorted(set(OPS) - set(CASES))
    assert not missing, f"ops without validation cases: {missing}"
    unknown = sorted(set(CASES) - set(OPS))
    assert not unknown, f"cases for unregistered ops: {unknown}"


def test_ctc_loss_matches_brute_force():
    """CTC nll vs explicit enumeration of all T-length paths that
    collapse (dedup + blank-strip) to the label."""
    import itertools
    T, C, blank = 4, 3, 0
    logits = np.asarray(A(1, T, C))
    label = [1, 2]

    def collapse(path):
        out, prev = [], None
        for p in path:
            if p != prev and p != blank:
                out.append(p)
            prev = p
        return out

    logp = np.asarray(jax.nn.log_softmax(jnp.asarray(logits), -1))[0]
    tot = -np.inf
    for path in itertools.product(range(C), repeat=T):
        if collapse(path) == label:
            tot = np.logaddexp(tot, sum(logp[t, p]
                                        for t, p in enumerate(path)))
    want = -tot
    got = float(get_op("ctc_loss")(
        jnp.asarray([label]), jnp.asarray(logits),
        jnp.asarray([2.0]), jnp.asarray([float(T)])))
    np.testing.assert_allclose(got, want, rtol=1e-6)


def test_ctc_loss_empty_label():
    """label_length 0 → nll of the all-blank path exactly."""
    T, C = 3, 3
    logits = np.asarray(A(1, T, C))
    logp = np.asarray(jax.nn.log_softmax(jnp.asarray(logits), -1))[0]
    want = -logp[:, 0].sum()
    got = float(get_op("ctc_loss")(
        jnp.asarray([[0, 0]]), jnp.asarray(logits),
        jnp.asarray([0.0]), jnp.asarray([float(T)])))
    np.testing.assert_allclose(got, want, rtol=1e-6)


def test_ctc_loss_respects_logit_lengths():
    """Padded time steps beyond logit_length must not change the nll."""
    T, C = 5, 3
    logits = np.asarray(A(1, T, C))
    base = float(get_op("ctc_loss")(
        jnp.asarray([[1, 2]]), jnp.asarray(logits[:, :4]),
        jnp.asarray([2.0]), jnp.asarray([4.0])))
    padded = logits.copy()
    padded[:, 4:] = R.standard_normal((1, 1, C)) * 50  # garbage pad
    got = float(get_op("ctc_loss")(
        jnp.asarray([[1, 2]]), jnp.asarray(padded),
        jnp.asarray([2.0]), jnp.asarray([4.0])))
    np.testing.assert_allclose(got, base, rtol=1e-6)


def _leaves(out):
    return [o for o in jax.tree.leaves(out)
            if jnp.issubdtype(jnp.asarray(o).dtype, jnp.inexact)]


@pytest.mark.parametrize("name", sorted(CASES))
def test_op_forward_and_grad(name):
    fn = get_op(name)
    for args, kwargs, grad, golden in CASES[name]:
        with jax.enable_x64(True):
            jargs = [jnp.asarray(a) for a in args]
            out = fn(*jargs, **kwargs)
            for leaf in jax.tree.leaves(out):
                assert np.isfinite(
                    np.asarray(leaf, dtype=np.float64)).all() or \
                    not jnp.issubdtype(jnp.asarray(leaf).dtype,
                                       jnp.inexact), \
                    f"{name}: non-finite output"
            if golden is not None:
                want = golden(*[np.asarray(a) for a in args])
                np.testing.assert_allclose(
                    np.asarray(jax.tree.leaves(out)[0]), want,
                    rtol=1e-6, atol=1e-8, err_msg=name)
            if grad:
                def scalar(*fa):
                    o = fn(*fa, **kwargs)
                    return sum(jnp.sum(l) for l in _leaves(o))
                g = jax.grad(scalar, argnums=tuple(range(len(jargs))))(
                    *jargs)
                eps = 1e-6
                for ai, ga in enumerate(g):
                    flat = np.asarray(args[ai], np.float64).ravel()
                    # probe a few indices
                    for idx in range(0, flat.size,
                                     max(1, flat.size // 3)):
                        fp = flat.copy(); fp[idx] += eps
                        fm = flat.copy(); fm[idx] -= eps
                        sh = np.asarray(args[ai]).shape
                        ap = [jnp.asarray(fp.reshape(sh))
                              if j == ai else jargs[j]
                              for j in range(len(jargs))]
                        am = [jnp.asarray(fm.reshape(sh))
                              if j == ai else jargs[j]
                              for j in range(len(jargs))]
                        fd = (float(scalar(*ap)) - float(scalar(*am))) \
                            / (2 * eps)
                        an = float(np.asarray(ga).ravel()[idx])
                        assert abs(fd - an) <= 1e-4 * max(
                            1.0, abs(fd), abs(an)), \
                            f"{name} arg{ai}[{idx}]: fd={fd} grad={an}"
