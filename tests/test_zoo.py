"""Zoo architecture tests — tiny inputs, forward-shape + one train
step (reference: ``deeplearning4j-zoo`` TestInstantiation suites, which
also instantiate each model and run a forward pass).
"""
import numpy as np
import pytest

from deeplearning4j_tpu import zoo


def _fwd(net, shape):
    x = np.random.default_rng(0).normal(size=shape).astype(np.float32)
    return net.output(x)


@pytest.mark.parametrize("cls,in_shape,classes", [
    (zoo.AlexNet, (64, 64, 3), 10),
    (zoo.VGG16, (32, 32, 3), 10),
    (zoo.VGG19, (32, 32, 3), 10),
])
def test_sequential_zoo_forward(cls, in_shape, classes):
    net = cls(num_classes=classes, input_shape=in_shape).init()
    out = _fwd(net, (2,) + in_shape)
    assert out.shape == (2, classes)
    np.testing.assert_allclose(out.sum(1), 1.0, rtol=1e-4)


@pytest.mark.parametrize("cls,in_shape,classes", [
    (zoo.SqueezeNet, (64, 64, 3), 10),
    (zoo.Xception, (71, 71, 3), 10),
])
def test_graph_zoo_forward(cls, in_shape, classes):
    net = cls(num_classes=classes, input_shape=in_shape).init()
    x = np.random.default_rng(0).normal(
        size=(2,) + in_shape).astype(np.float32)
    out = net.output(x)
    out = out[0] if isinstance(out, (list, tuple)) else out
    assert out.shape == (2, classes)
    np.testing.assert_allclose(np.asarray(out).sum(1), 1.0, rtol=1e-4)


def test_darknet19_forward():
    net = zoo.Darknet19(num_classes=10, input_shape=(64, 64, 3)).init()
    out = _fwd(net, (2, 64, 64, 3))
    assert out.shape == (2, 10)


def test_inception_resnet_v1_small():
    net = zoo.InceptionResNetV1(num_classes=8, input_shape=(80, 80, 3),
                                n35=1, n17=1, n8=1,
                                embedding_size=32).init()
    out = net.output(np.random.default_rng(0).normal(
        size=(1, 80, 80, 3)).astype(np.float32))
    out = out[0] if isinstance(out, (list, tuple)) else out
    assert np.asarray(out).shape == (1, 8)


def test_nasnet_small():
    net = zoo.NASNet(num_classes=6, input_shape=(32, 32, 3),
                     penultimate_filters=96, n_cells=1).init()
    out = net.output(np.random.default_rng(0).normal(
        size=(1, 32, 32, 3)).astype(np.float32))
    out = out[0] if isinstance(out, (list, tuple)) else out
    assert np.asarray(out).shape == (1, 6)


def test_unet_forward_shape():
    net = zoo.UNet(n_channels_out=1, input_shape=(32, 32, 3),
                   base_filters=8, depth=2).init()
    out = net.output(np.random.default_rng(0).normal(
        size=(1, 32, 32, 3)).astype(np.float32))
    out = out[0] if isinstance(out, (list, tuple)) else out
    out = np.asarray(out)
    assert out.shape == (1, 32, 32, 1)
    assert (out >= 0).all() and (out <= 1).all()      # sigmoid mask


def test_tiny_yolo_forward_and_loss_step():
    C, A = 3, 5
    net = zoo.TinyYOLO(num_classes=C, input_shape=(64, 64, 3)).init()
    x = np.random.default_rng(0).normal(
        size=(2, 64, 64, 3)).astype(np.float32)
    out = net.output(x)
    gh = gw = 64 // 32       # 5 stride-2 pools
    assert out.shape == (2, gh, gw, A * (5 + C))

    # labels: one object in cell (0,1) of each image
    labels = np.zeros((2, gh, gw, 4 + C), np.float32)
    labels[:, 0, 1, 0:4] = [1.5, 0.5, 1.2, 2.0]   # cx, cy, w, h
    labels[:, 0, 1, 4] = 1.0                       # class 0
    from deeplearning4j_tpu.data import DataSet, ListDataSetIterator
    it = ListDataSetIterator(DataSet(x, labels), batch_size=2)
    net.fit(it, epochs=1)
    assert np.isfinite(net.score())


def test_yolo2_output_layer_decode():
    from deeplearning4j_tpu.nn.layers import Yolo2OutputLayer
    lay = Yolo2OutputLayer(anchors=[[1., 1.], [2., 2.]], num_classes=2)
    x = np.zeros((1, 4, 4, 2 * 7), np.float32)
    p = lay.activate_predictions(x)
    assert p["xy"].shape == (1, 4, 4, 2, 2)
    # sigmoid(0)=0.5 + cell offset
    np.testing.assert_allclose(np.asarray(p["xy"])[0, 0, 0, 0], [0.5, 0.5])
    np.testing.assert_allclose(np.asarray(p["xy"])[0, 2, 3, 0], [3.5, 2.5])
    np.testing.assert_allclose(np.asarray(p["wh"])[0, 0, 0, 1], [2., 2.])
    np.testing.assert_allclose(np.asarray(p["cls"]).sum(-1), 1.0,
                               rtol=1e-5)
