"""Zoo architecture tests — tiny inputs, forward-shape + one train
step (reference: ``deeplearning4j-zoo`` TestInstantiation suites, which
also instantiate each model and run a forward pass).
"""
import numpy as np
import pytest

from deeplearning4j_tpu import zoo


def _fwd(net, shape):
    x = np.random.default_rng(0).normal(size=shape).astype(np.float32)
    return net.output(x)


@pytest.mark.parametrize("cls,in_shape,classes", [
    (zoo.AlexNet, (64, 64, 3), 10),
    (zoo.VGG16, (32, 32, 3), 10),
    (zoo.VGG19, (32, 32, 3), 10),
])
def test_sequential_zoo_forward(cls, in_shape, classes):
    net = cls(num_classes=classes, input_shape=in_shape).init()
    out = _fwd(net, (2,) + in_shape)
    assert out.shape == (2, classes)
    np.testing.assert_allclose(out.sum(1), 1.0, rtol=1e-4)


@pytest.mark.parametrize("cls,in_shape,classes", [
    (zoo.SqueezeNet, (64, 64, 3), 10),
    (zoo.Xception, (71, 71, 3), 10),
])
def test_graph_zoo_forward(cls, in_shape, classes):
    net = cls(num_classes=classes, input_shape=in_shape).init()
    x = np.random.default_rng(0).normal(
        size=(2,) + in_shape).astype(np.float32)
    out = net.output(x)
    out = out[0] if isinstance(out, (list, tuple)) else out
    assert out.shape == (2, classes)
    np.testing.assert_allclose(np.asarray(out).sum(1), 1.0, rtol=1e-4)


def test_darknet19_forward():
    net = zoo.Darknet19(num_classes=10, input_shape=(64, 64, 3)).init()
    out = _fwd(net, (2, 64, 64, 3))
    assert out.shape == (2, 10)


def test_inception_resnet_v1_small():
    net = zoo.InceptionResNetV1(num_classes=8, input_shape=(80, 80, 3),
                                n35=1, n17=1, n8=1,
                                embedding_size=32).init()
    out = net.output(np.random.default_rng(0).normal(
        size=(1, 80, 80, 3)).astype(np.float32))
    out = out[0] if isinstance(out, (list, tuple)) else out
    assert np.asarray(out).shape == (1, 8)


def test_nasnet_small():
    net = zoo.NASNet(num_classes=6, input_shape=(32, 32, 3),
                     penultimate_filters=96, n_cells=1).init()
    out = net.output(np.random.default_rng(0).normal(
        size=(1, 32, 32, 3)).astype(np.float32))
    out = out[0] if isinstance(out, (list, tuple)) else out
    assert np.asarray(out).shape == (1, 6)


def test_unet_forward_shape():
    net = zoo.UNet(n_channels_out=1, input_shape=(32, 32, 3),
                   base_filters=8, depth=2).init()
    out = net.output(np.random.default_rng(0).normal(
        size=(1, 32, 32, 3)).astype(np.float32))
    out = out[0] if isinstance(out, (list, tuple)) else out
    out = np.asarray(out)
    assert out.shape == (1, 32, 32, 1)
    assert (out >= 0).all() and (out <= 1).all()      # sigmoid mask


def test_tiny_yolo_forward_and_loss_step():
    C, A = 3, 5
    net = zoo.TinyYOLO(num_classes=C, input_shape=(64, 64, 3)).init()
    x = np.random.default_rng(0).normal(
        size=(2, 64, 64, 3)).astype(np.float32)
    out = net.output(x)
    gh = gw = 64 // 32       # 5 stride-2 pools
    assert out.shape == (2, gh, gw, A * (5 + C))

    # labels: one object in cell (0,1) of each image
    labels = np.zeros((2, gh, gw, 4 + C), np.float32)
    labels[:, 0, 1, 0:4] = [1.5, 0.5, 1.2, 2.0]   # cx, cy, w, h
    labels[:, 0, 1, 4] = 1.0                       # class 0
    from deeplearning4j_tpu.data import DataSet, ListDataSetIterator
    it = ListDataSetIterator(DataSet(x, labels), batch_size=2)
    net.fit(it, epochs=1)
    assert np.isfinite(net.score())


def test_yolo2_output_layer_decode():
    from deeplearning4j_tpu.nn.layers import Yolo2OutputLayer
    lay = Yolo2OutputLayer(anchors=[[1., 1.], [2., 2.]], num_classes=2)
    x = np.zeros((1, 4, 4, 2 * 7), np.float32)
    p = lay.activate_predictions(x)
    assert p["xy"].shape == (1, 4, 4, 2, 2)
    # sigmoid(0)=0.5 + cell offset
    np.testing.assert_allclose(np.asarray(p["xy"])[0, 0, 0, 0], [0.5, 0.5])
    np.testing.assert_allclose(np.asarray(p["xy"])[0, 2, 3, 0], [3.5, 2.5])
    np.testing.assert_allclose(np.asarray(p["wh"])[0, 0, 0, 1], [2., 2.])
    np.testing.assert_allclose(np.asarray(p["cls"]).sum(-1), 1.0,
                               rtol=1e-5)


# ---------------------------------------------------------------------------
# BERT (BASELINE config #4 — native model instead of TF-imported graph)
# ---------------------------------------------------------------------------
def test_bert_tiny_classifier_learns():
    from deeplearning4j_tpu.zoo import BertTiny
    T, B = 16, 8
    net = BertTiny(max_len=T).init_classifier(num_classes=2, seq_len=T)
    rng = np.random.default_rng(0)
    tok = rng.integers(0, 1000, (B, T))
    seg = np.zeros((B, T), np.int64)
    y = np.eye(2, dtype=np.float32)[(tok[:, 0] < 500).astype(int)]
    for _ in range(60):
        net.fit([tok, seg], [y])
    assert net.score() < 0.3
    out = net.output(tok, seg)[0]
    assert out.shape == (B, 2)
    assert np.allclose(np.sum(np.asarray(out), -1), 1, atol=1e-3)


def test_bert_mlm_head_shapes_and_step():
    from deeplearning4j_tpu.zoo import BertTiny
    T, B, V = 12, 4, 1000
    net = BertTiny(max_len=T).init_mlm(seq_len=T)
    rng = np.random.default_rng(1)
    tok = rng.integers(0, V, (B, T))
    seg = np.zeros((B, T), np.int64)
    net.fit([tok, seg], [np.eye(V, dtype=np.float32)[tok]])
    assert np.isfinite(net.score())
    out = net.output(tok, seg)[0]
    assert out.shape == (B, T, V)


def test_bert_base_is_bert_base_sized():
    from deeplearning4j_tpu.zoo import BertBase
    conf = BertBase(max_len=128).conf_classifier(num_classes=2,
                                                seq_len=128)
    # config JSON round-trips (model format parity with the reference's
    # Jackson config beans)
    from deeplearning4j_tpu.nn.graph import ComputationGraphConfiguration
    conf2 = ComputationGraphConfiguration.from_json(conf.to_json())
    assert len(conf2.nodes) == len(conf.nodes)


def test_bert_mlm_labels_mask_scopes_loss():
    """labels_mask restricts the MLM loss to masked positions (graph
    fit mask threading)."""
    from deeplearning4j_tpu.zoo import BertTiny
    T, B, V = 12, 4, 1000
    net = BertTiny(max_len=T, dropout=0.0).init_mlm(seq_len=T)
    rng = np.random.default_rng(3)
    tok = rng.integers(0, V, (B, T))
    seg = np.zeros((B, T), np.int64)
    y = np.eye(V, dtype=np.float32)[tok]
    lmask = np.zeros((B, T), np.float32)
    lmask[:, :2] = 1          # only 2/12 positions scored
    net.fit([tok, seg], [y], labels_masks=[lmask])
    s_masked = net.score()
    net2 = BertTiny(max_len=T, dropout=0.0).init_mlm(seq_len=T)
    net2.fit([tok, seg], [y])
    s_full = net2.score()
    assert np.isfinite(s_masked) and np.isfinite(s_full)
    assert s_masked != s_full


def test_facenet_nn4_small2():
    net = zoo.FaceNetNN4Small2(num_classes=6, input_shape=(64, 64, 3),
                               embedding_size=32).init()
    out = net.output(np.random.default_rng(0).normal(
        size=(1, 64, 64, 3)).astype(np.float32))
    out = out[0] if isinstance(out, (list, tuple)) else out
    assert np.asarray(out).shape == (1, 6)
    # embedding activations are L2-normalized before the loss head
    x = np.random.default_rng(1).normal(
        size=(2, 64, 64, 3)).astype(np.float32)
    acts, _ = net._forward(net.params, net.state, {"input": x},
                           train=False, rng=None)
    emb = np.asarray(acts["embeddings"])
    assert emb.shape == (2, 32)
    np.testing.assert_allclose(np.linalg.norm(emb, axis=1), 1.0,
                               rtol=1e-4)
