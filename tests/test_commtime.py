"""Communication observatory (obs/commtime.py — ARCHITECTURE.md §19).

Fences: the per-device ring wire model is exact, replica-group/pair
parsing handles the literal and iota HLO forms, the collective walker
joins ``dl4j.*`` scopes through the scope map and never double-counts
async ``-done`` halves, the static wire ledger reproduces the PR 5
byte model on the ZeRO sharded step (reduce-scatter ≈ grad/N shard
under ``zero.reduce_scatter``, all-gather ≈ param bytes under
``zero.all_gather``) across DP / ZeRO / ZeRO-overlap / DP×TP / SP /
EP, the comm-view roofline math is exact, a collective-dominated
scope flips ``gap_report``'s bound axis to ``"wire"`` and is never a
Pallas candidate, the capture pipeline publishes the
``dl4j_tpu_comm_*`` gauges, and — the PR 2 contract —
``DL4J_TPU_COMMTIME`` unset means zero profiler sessions and zero
captures through the fit loops (counter-asserted).
"""
import importlib.util
import sys
from pathlib import Path

import numpy as np
import pytest

REPO = Path(__file__).resolve().parent.parent
sys.path.insert(0, str(REPO))

import jax  # noqa: E402
import jax.numpy as jnp  # noqa: E402
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P  # noqa: E402

from conftest import requires_modern_jax  # noqa: E402
from deeplearning4j_tpu.nn import (MultiLayerNetwork,  # noqa: E402
                                   NeuralNetConfiguration)
from deeplearning4j_tpu.nn.config import InputType  # noqa: E402
from deeplearning4j_tpu.nn.layers import (ConvolutionLayer,  # noqa: E402
                                          DenseLayer, OutputLayer,
                                          SubsamplingLayer)
from deeplearning4j_tpu.nn import updaters as upd  # noqa: E402
from deeplearning4j_tpu.obs import commtime, devtime  # noqa: E402
from deeplearning4j_tpu.obs import metrics as obs_metrics  # noqa: E402
from deeplearning4j_tpu.parallel import ParallelWrapper  # noqa: E402
from deeplearning4j_tpu.parallel.mesh import make_mesh  # noqa: E402

needs_mesh = pytest.mark.skipif(
    len(jax.devices()) < 8,
    reason="wire-ledger gates pin an 8-device mesh "
           "(--xla_force_host_platform_device_count=8)")


@pytest.fixture(autouse=True)
def _clean_commtime():
    commtime.disable()
    commtime.reset_counters()
    yield
    commtime.disable()
    commtime.reset_counters()


def _param_bytes(tree):
    return sum(int(np.prod(p.shape)) * p.dtype.itemsize
               for p in jax.tree_util.tree_leaves(tree))


def _mlp_wrapper(sharded_update=True, gather_overlap=False):
    """Tiny ZeRO-able DP MLP on the 8-device mesh — the ledger-gate
    donor (same geometry as the probe the assertion bands were pinned
    against: params 32·64+64 + 64·16+16 = 3152 f32)."""
    conf = (NeuralNetConfiguration.builder().seed(5)
            .updater(upd.Adam(learning_rate=1e-3)).list()
            .layer(DenseLayer(n_out=64, activation="relu"))
            .layer(OutputLayer(n_out=16, activation="softmax",
                               loss="mcxent"))
            .set_input_type(InputType.feed_forward(32)).build())
    net = MultiLayerNetwork(conf).init()
    w = ParallelWrapper(net, workers=8, sharded_update=sharded_update,
                        gather_overlap=gather_overlap)
    w._prepare()
    dshard = NamedSharding(w.mesh, P("data"))
    x = jax.device_put(jnp.zeros((64, 32), jnp.float32), dshard)
    y = jax.device_put(jnp.zeros((64, 16), jnp.float32), dshard)
    rng = jax.random.PRNGKey(0)
    if gather_overlap:
        args = (w._pshard, w._dp_state, net.state, x, y, rng)
    elif sharded_update:
        args = (net.params, w._dp_state, net.state, x, y, rng)
    else:
        args = (net.params, net.opt_state, net.state, x, y, rng)
    return net, w, args


def _smoke_net():
    conf = (NeuralNetConfiguration.builder().seed(3)
            .updater(upd.Adam(learning_rate=1e-3)).list()
            .layer(ConvolutionLayer(n_out=4, kernel_size=(3, 3),
                                    activation="relu"))
            .layer(SubsamplingLayer(kernel_size=(2, 2),
                                    stride=(2, 2)))
            .layer(DenseLayer(n_out=16, activation="tanh"))
            .layer(OutputLayer(n_out=3, activation="softmax",
                               loss="mcxent"))
            .set_input_type(InputType.convolutional(8, 8, 1)).build())
    net = MultiLayerNetwork(conf).init()
    rng = np.random.default_rng(0)
    x = rng.standard_normal((8, 8, 8, 1)).astype(np.float32)
    y = np.eye(3, dtype=np.float32)[rng.integers(0, 3, 8)]
    return net, x, y


# -------------------------------------------------------------------------
# ring wire model + HLO attribute parsing
# -------------------------------------------------------------------------

def test_ring_wire_bytes_model():
    # all-reduce = reduce-scatter + all-gather over the ring
    assert commtime.ring_wire_bytes("all-reduce", 1024, 8) \
        == 2 * 1024 * 7 / 8
    # all-gather result is the FULL tensor; each device sends a shard
    assert commtime.ring_wire_bytes("all-gather", 800, 8) == 700.0
    # reduce-scatter result is the SHARD
    assert commtime.ring_wire_bytes("reduce-scatter", 128, 8) \
        == 128 * 7
    assert commtime.ring_wire_bytes("collective-permute", 4096, 8) \
        == 4096.0
    assert commtime.ring_wire_bytes("all-to-all", 800, 8) \
        == 800 * 7 / 8
    # a two-device all-reduce ring moves exactly the tensor bytes
    assert commtime.ring_wire_bytes("all-reduce", 2048, 2) == 2048.0
    # one-device groups move nothing
    for k in ("all-reduce", "all-gather", "reduce-scatter",
              "collective-permute", "all-to-all"):
        assert commtime.ring_wire_bytes(k, 1e9, 1) == 0.0


def test_parse_replica_groups_literal_iota_and_absent():
    lit = commtime.parse_replica_groups(
        "f32[8]{0} all-reduce(%g), replica_groups={{0,1,2,3},{4,5,6,7}},"
        " to_apply=%add")
    assert lit == frozenset({frozenset({0, 1, 2, 3}),
                             frozenset({4, 5, 6, 7})})
    # iota form with a transpose: [4,2]<=[2,4]T(1,0) strides the axis
    iota = commtime.parse_replica_groups(
        "replica_groups=[4,2]<=[2,4]T(1,0)")
    assert iota == frozenset({frozenset({0, 4}), frozenset({1, 5}),
                              frozenset({2, 6}), frozenset({3, 7})})
    plain = commtime.parse_replica_groups("replica_groups=[2,4]<=[8]")
    assert plain == frozenset({frozenset({0, 1, 2, 3}),
                               frozenset({4, 5, 6, 7})})
    # absent/empty groups: None (one group of every device)
    assert commtime.parse_replica_groups(
        "all-reduce(%g), to_apply=%add") is None


def test_parse_source_target_pairs():
    pairs = commtime.parse_source_target_pairs(
        "collective-permute(%kv), "
        "source_target_pairs={{0,1},{1,2},{2,3},{3,0}}")
    assert pairs == [(0, 1), (1, 2), (2, 3), (3, 0)]
    assert commtime.parse_source_target_pairs(
        "all-reduce(%g), to_apply=%add") is None


# -------------------------------------------------------------------------
# the collective walker on synthetic HLO (scope join, async halves,
# while-body trips, group-sized rings)
# -------------------------------------------------------------------------

_SYNTH_HLO = """\
HloModule synth_step, entry_computation_layout={(f32[256]{0})->f32[256]{0}}

%add (a: f32[], b: f32[]) -> f32[] {
  %a = f32[] parameter(0)
  %b = f32[] parameter(1)
  ROOT %sum = f32[] add(%a, %b)
}

ENTRY %main (p0: f32[256]) -> f32[256] {
  %p0 = f32[256]{0} parameter(0)
  %all-reduce.1 = f32[256]{0} all-reduce(%p0), replica_groups={{0,1,2,3},{4,5,6,7}}, to_apply=%add, metadata={op_name="jit(step)/jit(main)/dl4j.zero.grad_sync/psum"}
  %all-gather-start.1 = f32[2048]{0} all-gather-start(%all-reduce.1), replica_groups=[1,8]<=[8], dimensions={0}, metadata={op_name="jit(step)/dl4j.zero.all_gather/all_gather"}
  %all-gather-done.1 = f32[2048]{0} all-gather-done(%all-gather-start.1)
  %collective-permute.1 = f32[256]{0} collective-permute(%p0), source_target_pairs={{0,1},{1,2},{2,3},{3,0},{4,5},{5,6},{6,7},{7,4}}, metadata={op_name="jit(step)/while/body/dl4j.sp.ring_attention/ppermute"}
  ROOT %anon = f32[256]{0} all-reduce(%collective-permute.1), to_apply=%add
}
"""


def test_collective_records_synthetic_hlo():
    recs = commtime.collective_records(_SYNTH_HLO, n_devices=8)
    assert [r["kind"] for r in recs] == [
        "all-reduce", "all-gather", "collective-permute", "all-reduce"]
    ar, ag, cp, anon = recs
    assert ar["module"] == "synth_step"
    assert ar["scope"] == "zero.grad_sync"
    assert ar["tensor_bytes"] == 256 * 4
    # ring sized by the PARSED groups: two 4-rings, not the 8 mesh
    assert ar["group_size"] == 4
    assert ar["replica_groups"] == frozenset(
        {frozenset({0, 1, 2, 3}), frozenset({4, 5, 6, 7})})
    assert ar["wire_bytes"] == pytest.approx(2 * 1024 * 3 / 4)
    # the async -start half IS the op; the -done half never counts
    assert ag["op"] == "all-gather-start.1"
    assert ag["scope"] == "zero.all_gather"
    assert ag["tensor_bytes"] == 2048 * 4
    assert ag["group_size"] == 8
    assert ag["wire_bytes"] == pytest.approx(2048 * 4 / 8 * 7)
    assert not any(r["op"].startswith("all-gather-done") for r in recs)
    # while-body permute: one neighbor hop per ring trip
    assert cp["scope"] == "sp.ring_attention"
    assert cp["in_while"] is True and cp["trips"] == 8
    assert cp["source_target_pairs"][:2] == [(0, 1), (1, 2)]
    assert cp["wire_bytes"] == pytest.approx(1024 * 8)
    # no groups + no scope: n_devices ring, anonymous record
    assert anon["scope"] is None
    assert anon["group_size"] == 8 and anon["trips"] == 1
    assert anon["backward"] is False


def test_collective_records_uniform_ring_override():
    # the legacy collective_volume knob: every ring sized to the mesh
    recs = commtime.collective_records(_SYNTH_HLO, uniform_ring=8)
    assert recs[0]["group_size"] == 8
    assert recs[0]["wire_bytes"] == pytest.approx(2 * 1024 * 7 / 8)


class _FakeCompiled:
    def __init__(self, text):
        self._text = text

    def as_text(self):
        return self._text


def test_wire_ledger_aggregates_scopes_and_kinds():
    led = commtime.wire_ledger([_FakeCompiled(_SYNTH_HLO), None],
                               n_devices=8)
    assert led["programs"] == 1          # None executables filtered
    assert led["n_devices"] == 8
    assert set(led["by_scope"]) == {"zero.grad_sync",
                                    "zero.all_gather",
                                    "sp.ring_attention",
                                    "op:all-reduce"}
    assert led["by_kind"]["all-reduce"]["count"] == 2
    assert led["by_kind"]["all-gather"]["count"] == 1
    assert led["wire_bytes"] == pytest.approx(
        sum(r["wire_bytes"] for r in led["records"]))
    # tensor-byte rollup multiplies the while-body trip count
    assert led["by_scope"]["sp.ring_attention"]["tensor_bytes"] \
        == pytest.approx(1024 * 8)
    assert led["by_scope"]["zero.grad_sync"]["kinds"] \
        == {"all-reduce": 1}


# -------------------------------------------------------------------------
# compiled programs: every parallelism mode's ledger
# -------------------------------------------------------------------------

@needs_mesh
def test_dp_dense_wrapper_allreduce_wire():
    net, w, args = _mlp_wrapper(sharded_update=False)
    compiled = w._step.lower(*args).compile()
    led = commtime.wire_ledger([compiled], n_devices=8)
    # dense DP syncs grads with all-reduce ONLY — a reduce-scatter
    # here would mean the replicated baseline silently went ZeRO
    assert set(led["by_kind"]) == {"all-reduce"}
    want = 2 * _param_bytes(net.params) * 7 / 8
    assert want * 0.98 < led["wire_bytes"] < want * 1.06


@needs_mesh
def test_zero_ledger_scope_attribution_matches_byte_model():
    net, w, args = _mlp_wrapper(sharded_update=True)
    compiled = w._step.lower(*args).compile()
    led = commtime.wire_ledger([compiled], n_devices=8)
    by = led["by_scope"]
    p = _param_bytes(net.params)
    # PR 5 byte model through the scope join: reduce-scatter results
    # ≈ grad/8 shards, all-gather results ≈ full params — both ride
    # the same (N/n)·(n−1) ring wire
    shard_wire = p / 8 * 7
    rs, ag = by["zero.reduce_scatter"], by["zero.all_gather"]
    assert shard_wire * 0.95 < rs["wire_bytes"] < shard_wire * 1.2
    assert shard_wire * 0.95 < ag["wire_bytes"] < shard_wire * 1.2
    assert p / 8 * 0.95 < rs["tensor_bytes"] < p / 8 * 1.2
    assert p * 0.95 < ag["tensor_bytes"] < p * 1.2
    assert set(rs["kinds"]) == {"reduce-scatter"}
    assert set(ag["kinds"]) == {"all-gather"}
    # the loss pmean is the only anonymous collective left (the
    # in-repo emitters are scoped — lint rule 11's fence)
    assert [k for k in by if k.startswith("op:")] == ["op:all-reduce"]


@needs_mesh
def test_zero_gather_overlap_keeps_scope_attribution():
    net, w, args = _mlp_wrapper(sharded_update=True,
                                gather_overlap=True)
    compiled = w._step.lower(*args).compile()
    led = commtime.wire_ledger([compiled], n_devices=8)
    by = led["by_scope"]
    # the overlap step carries flat 1/N shards and gathers params up
    # front — same scopes, same byte model as the non-overlap path
    p = _param_bytes(net.params)
    shard_wire = p / 8 * 7
    assert shard_wire * 0.9 < by["zero.all_gather"]["wire_bytes"] \
        < shard_wire * 1.3
    assert shard_wire * 0.9 < by["zero.reduce_scatter"]["wire_bytes"] \
        < shard_wire * 1.3
    assert led["by_kind"]["all-gather"]["count"] >= 1
    assert led["by_kind"]["reduce-scatter"]["count"] >= 1


@needs_mesh
def test_dp_tp_rings_sized_per_parsed_group():
    mesh = Mesh(np.array(jax.devices()).reshape(4, 2),
                ("data", "tensor"))
    d, h = 64, 256
    params = {"W1": jnp.zeros((d, h), jnp.float32),
              "W2": jnp.zeros((h, d), jnp.float32)}
    shard = {"W1": NamedSharding(mesh, P(None, "tensor")),
             "W2": NamedSharding(mesh, P("tensor", None))}
    x = jnp.zeros((32, d), jnp.float32)

    def fwd(p, x):
        hdn = jax.nn.relu(x @ p["W1"])
        return jnp.sum((hdn @ p["W2"]) ** 2)

    step = jax.jit(lambda p, x: jax.value_and_grad(fwd)(p, x),
                   in_shardings=(shard, NamedSharding(mesh,
                                                      P("data"))))
    compiled = step.lower(jax.device_put(params, shard), x).compile()
    recs = commtime.collective_records(compiled.as_text())
    assert recs and all(r["kind"] == "all-reduce" for r in recs)
    # tensor-axis activation psum rings over 2, data-axis grad sync
    # over 4 — NEVER a flat 8-ring on this 4×2 mesh
    sizes = sorted({r["group_size"] for r in recs})
    assert sizes == [2, 4]
    for r in recs:
        assert r["wire_bytes"] == pytest.approx(
            commtime.ring_wire_bytes("all-reduce", r["tensor_bytes"],
                                     r["group_size"]))
    # the 2-ring moves exactly the activation-grad tensor bytes
    two = [r for r in recs if r["group_size"] == 2]
    assert two and all(r["wire_bytes"] == pytest.approx(
        r["tensor_bytes"]) for r in two)


@needs_mesh
def test_ep_moe_rings_span_expert_axis():
    from deeplearning4j_tpu.parallel.moe import MixtureOfExperts
    mesh = make_mesh({"expert": 8})
    moe = MixtureOfExperts(d_model=8, d_hidden=16, num_experts=8,
                           top_k=2)
    params = moe.shard(moe.init(), mesh, axis="expert")
    x = jax.random.normal(jax.random.PRNGKey(1), (4, 8, 8))

    @jax.jit
    def step(p, x):
        def loss(p):
            out, aux = moe.apply(p, x)
            return jnp.mean(jnp.square(out)) + 0.01 * aux
        return jax.value_and_grad(loss)(p)

    compiled = step.lower(params, x).compile()
    recs = commtime.collective_records(compiled.as_text())
    assert recs
    for r in recs:
        # GSPMD lowers the expert mixing to all-reduce over the FULL
        # expert axis; each record's wire obeys the ring model
        assert r["kind"] == "all-reduce" and r["group_size"] == 8
        assert r["wire_bytes"] == pytest.approx(
            2 * r["tensor_bytes"] * 7 / 8)
    led = commtime.wire_ledger([compiled], n_devices=8)
    assert led["wire_bytes"] == pytest.approx(
        sum(r["wire_bytes"] for r in recs))


@requires_modern_jax
@needs_mesh
def test_sp_ring_attention_permute_trips():
    from deeplearning4j_tpu.parallel.ring_attention import \
        ring_self_attention
    mesh = make_mesh({"seq": 8})
    q = jnp.zeros((1, 1024, 4, 32), jnp.bfloat16)

    def loss(q):
        return jnp.sum(
            ring_self_attention(q, q, q, mesh, causal=True)
            .astype(jnp.float32) ** 2)

    compiled = jax.jit(jax.value_and_grad(loss)).lower(q).compile()
    recs = commtime.collective_records(compiled.as_text())
    perms = [r for r in recs if r["kind"] == "collective-permute"]
    assert perms, "ring attention emitted no collective-permute"
    # the fori_loop KV rotation pays one hop per ring trip
    looped = [r for r in perms if r["in_while"]]
    assert looped
    for r in looped:
        assert r["trips"] == r["group_size"]
        assert r["wire_bytes"] == pytest.approx(
            r["tensor_bytes"] * r["trips"])


# -------------------------------------------------------------------------
# comm-view roofline math + the gap report's wire axis
# -------------------------------------------------------------------------

def test_comm_view_roofline_math():
    att = {
        "total_device_ms": 10.0, "device_steps": 2, "planes": 1,
        "modules": {},
        "scopes": {
            "zero.reduce_scatter": {
                "device_ms": 6.0, "comm_ms": 4.0,
                "kinds": {"reduce-scatter-start": 2,
                          "reduce-scatter-done": 2}},
            "layer_0.Dense": {
                "device_ms": 4.0, "comm_ms": 0.0,
                "kinds": {"dot": 3}},
        }}
    ledger = {
        "wire_bytes": 22064.0,
        "by_scope": {
            "zero.reduce_scatter": {"wire_bytes": 11032.0,
                                    "tensor_bytes": 1576.0,
                                    "kinds": {"reduce-scatter": 2}},
            "ghost.ledger_only": {"wire_bytes": 1.0,
                                  "tensor_bytes": 1.0, "kinds": {}},
        }}
    view = commtime.comm_view(att, ledger=ledger, peak_ici=100e9)
    # a scope with no collective time, no collective kinds, and no
    # ledger row is dropped; a ledger row with no runtime scope never
    # invents device time
    assert set(view["scopes"]) == {"zero.reduce_scatter"}
    r = view["scopes"]["zero.reduce_scatter"]
    assert r["collective_ms"] == 4.0
    assert r["share"] == pytest.approx(0.4)
    # async halves roll up to ONE base kind (the -done half dropped)
    assert r["kinds"] == {"reduce-scatter": 2}
    assert r["wire_bound"] is True       # 4.0 > 0.5 · 6.0
    assert r["wire_bytes_per_step"] == 11032.0
    # achieved GB/s = wire/step · steps / collective seconds
    want_gbs = 11032.0 * 2 / (4.0 / 1e3) / 1e9
    assert r["achieved_gbs"] == pytest.approx(want_gbs, rel=1e-3)
    # published value is rounded to 6 decimals
    assert r["link_utilization"] == pytest.approx(
        want_gbs * 1e9 / 100e9, abs=1e-6)
    assert view["collective_ms"] == pytest.approx(4.0)
    assert view["comm_share"] == pytest.approx(0.4)
    assert view["by_kind"] == {"reduce-scatter": 2}
    assert view["wire_bound_scopes"] == ["zero.reduce_scatter"]
    assert view["peak_ici_gbs"] == pytest.approx(100.0)
    assert view["wire_bytes_per_step"] == 22064.0
    # XLA:CPU captures time host thunks, not ICI — flagged as such
    assert view["estimate_only"] is True


def test_comm_view_steps_fall_back_to_module_executions():
    att = {"total_device_ms": 1.0, "device_steps": 0, "planes": 1,
           "modules": {"jit_step": {"executions": 5}},
           "scopes": {"s": {"device_ms": 1.0, "comm_ms": 1.0,
                            "kinds": {"all-reduce": 1}}}}
    ledger = {"wire_bytes": 100.0,
              "by_scope": {"s": {"wire_bytes": 100.0,
                                 "tensor_bytes": 50.0, "kinds": {}}}}
    view = commtime.comm_view(att, ledger=ledger, peak_ici=1e9)
    # 100 B/step · 5 executions / 1 ms
    assert view["scopes"]["s"]["achieved_gbs"] == pytest.approx(
        100.0 * 5 / (1.0 / 1e3) / 1e9, rel=1e-3)


def test_gap_report_wire_bound_axis():
    cap = {"scopes": {
        "zero.all_gather": {
            "device_ms": 8.0, "share": 0.5, "ops": 4, "fusions": 0,
            "backward_ms": 0.0, "comm_ms": 6.0, "custom_call_ms": 0.0,
            "flops": 1e9, "bytes": 1e8, "kinds": {"all-gather": 4},
            "roofline": {"utilization": 0.05, "bound": "memory"}},
        "layer_0.Dense": {
            "device_ms": 8.0, "share": 0.5, "ops": 4, "fusions": 1,
            "backward_ms": 2.0, "comm_ms": 0.5, "custom_call_ms": 0.0,
            "flops": 1e9, "bytes": 1e8, "kinds": {"dot": 2},
            "roofline": {"utilization": 0.05, "bound": "memory"}},
    }}
    gaps = devtime.gap_report(cap, top=10)
    assert [tuple(g) for g in gaps] == [devtime.GAP_KEYS] * 2
    by = {g["scope"]: g for g in gaps}
    # collective-dominated: the interconnect is the ceiling — bound
    # flips to "wire" and no kernel can close it
    assert by["zero.all_gather"]["bound"] == "wire"
    assert by["zero.all_gather"]["comm_ms"] == 6.0
    assert by["zero.all_gather"]["pallas_candidate"] is False
    # the compute twin below the roofline stays a candidate
    assert by["layer_0.Dense"]["bound"] == "memory"
    assert by["layer_0.Dense"]["pallas_candidate"] is True


# -------------------------------------------------------------------------
# capture pipeline + metric surface + the off-path fence
# -------------------------------------------------------------------------

def _threaded_runner(compiled, args):
    """One-step runner that threads the carried state through — the
    step donates argnums (0, 1, 2), so re-calling with the original
    arrays would hit deleted buffers."""
    carried = list(args[:3])
    rest = args[3:]

    def run_once():
        p, s, st, loss = compiled(carried[0], carried[1], carried[2],
                                  *rest)
        carried[0], carried[1], carried[2] = p, s, st
        jax.block_until_ready(loss)

    return run_once


@needs_mesh
def test_capture_attributes_and_publishes_zero_scopes():
    net, w, args = _mlp_wrapper(sharded_update=True)
    compiled = w._step.lower(*args).compile()
    run_once = _threaded_runner(compiled, args)
    run_once()                       # settle OUTSIDE any window
    assert commtime.captures() == 0
    assert commtime.profiler_sessions() == 0

    rep = commtime.capture(run_once, executables=[compiled])
    assert commtime.captures() == 1
    assert commtime.profiler_sessions() == 1
    assert rep["label"] == "on_demand" and rep["capture_wall_s"] > 0
    assert commtime.last_report() is rep
    view = rep["comm"]
    assert view["collective_ms"] > 0
    assert view["estimate_only"] is True         # CPU capture
    assert {"reduce-scatter", "all-gather"} <= set(view["by_kind"])
    sc = view["scopes"]
    assert "zero.reduce_scatter" in sc and "zero.all_gather" in sc
    p = _param_bytes(net.params)
    rs = sc["zero.reduce_scatter"]
    assert rs["collective_ms"] > 0
    assert p / 8 * 7 * 0.95 < rs["wire_bytes_per_step"] \
        < p / 8 * 7 * 1.2
    assert "achieved_gbs" in rs and "link_utilization" in rs
    assert rep["ledger"]["programs"] == 1

    # the standing-registry surface: scrape shows THIS capture
    fams = obs_metrics.parse_exposition(obs_metrics.exposition())
    assert fams[("dl4j_tpu_comm_captures_total", ())] >= 1.0
    wire_scopes = {dict(labels)["scope"]
                   for (name, labels) in fams
                   if name == "dl4j_tpu_comm_scope_wire_bytes_per_step"}
    assert {"zero.reduce_scatter", "zero.all_gather"} <= wire_scopes
    op_kinds = {dict(labels)["kind"]
                for (name, labels) in fams
                if name == "dl4j_tpu_comm_op_count"}
    assert {"reduce-scatter", "all-gather"} <= op_kinds
    share = {dict(labels)["scope"]: v for (name, labels), v
             in fams.items()
             if name == "dl4j_tpu_comm_scope_step_share"}
    assert 0.0 < share["zero.reduce_scatter"] <= 1.0


@needs_mesh
def test_xprof_summary_comm_mode(tmp_path):
    net, w, args = _mlp_wrapper(sharded_update=True)
    compiled = w._step.lower(*args).compile()
    run_once = _threaded_runner(compiled, args)
    run_once()
    commtime.capture(run_once, executables=[compiled],
                     keep_dir=str(tmp_path))
    spec = importlib.util.spec_from_file_location(
        "xprof_summary", REPO / "tools" / "xprof_summary.py")
    xp = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(xp)
    out = xp.summarize_comm(str(tmp_path))
    # offline twin of tpu_watch --comm: per-scope collective table
    # from the kept xplane session. XLA:CPU event names carry no
    # op_name metadata, so the maps=None join lands in the per-kind
    # buckets — on a TPU capture the dl4j.* scopes appear instead
    assert "collective" in out
    assert "op:reduce-scatter" in out and "op:all-gather" in out
    assert "| scope | collective ms |" in out
    assert "estimate-only" in out        # non-TPU capture is flagged
    assert "wire-bound scopes:" in out


def test_off_path_fence_counters_zero(monkeypatch):
    monkeypatch.delenv("DL4J_TPU_COMMTIME", raising=False)
    net, x, y = _smoke_net()
    for _ in range(3):
        net.fit(x, y)
    # the PR 2 bar: env unset — the fit-loop hooks are one
    # module-global branch, zero profiler sessions, zero captures
    assert commtime.captures() == 0
    assert commtime.profiler_sessions() == 0
    ov = commtime.measure_capture_overhead(step_seconds=0.01,
                                           iters=20000)
    assert ov["monitor_enabled"] is False
    assert ov["off_path_cost_us"] < 50.0
    assert ov["off_path_pct_of_step"] < 1.0
    # the probe restored the counters it touched
    assert commtime.captures() == 0
    assert commtime.profiler_sessions() == 0


def test_cadence_monitor_and_refence():
    net, x, y = _smoke_net()
    net.fit(x, y)                    # compile outside any window
    assert commtime.profiler_sessions() == 0
    commtime.configure(every=2, steps=2)
    for _ in range(4):
        net.fit(x, y)
    commtime.disable()
    assert commtime.captures() >= 1
    assert commtime.profiler_sessions() >= 1
    rep = commtime.last_report()
    assert rep is not None and rep["label"] == "cadence"
    assert rep["comm"]["total_device_ms"] > 0
    # monitor off again: further fits never touch the profiler
    n = commtime.captures()
    s = commtime.profiler_sessions()
    for _ in range(2):
        net.fit(x, y)
    assert commtime.captures() == n
    assert commtime.profiler_sessions() == s


@needs_mesh
def test_comm_report_gates_byte_model():
    rep = commtime.comm_report(n_devices=8, hidden=32, features=16,
                               classes=4)
    assert not rep.get("skipped"), rep
    gates = rep["gates"]
    # the bench.py "comm" section's acceptance: reduce-scatter tensor
    # bytes ≈ grad/8 shard, all-gather tensor bytes ≈ full params
    assert gates["reduce_scatter_tensor_over_grad_shard"] \
        == pytest.approx(1.0, rel=0.2)
    assert gates["all_gather_tensor_over_params"] \
        == pytest.approx(1.0, rel=0.2)
    assert rep["wire_bytes_per_step"] > 0
    assert rep["off_path"]["off_path_cost_us"] < 50.0
