"""Numerics observatory (obs/numerics.py, ARCHITECTURE.md §11):
off-path zero-cost fence, in-step per-layer health, NaN attribution,
replica divergence, and the resilience restore path end-to-end."""
import numpy as np
import pytest

from deeplearning4j_tpu.data import DataSet, ListDataSetIterator
from deeplearning4j_tpu.nn import (MultiLayerNetwork,
                                   NeuralNetConfiguration)
from deeplearning4j_tpu.nn.config import InputType
from deeplearning4j_tpu.nn.layers import DenseLayer, OutputLayer
from deeplearning4j_tpu.nn import updaters as upd
from deeplearning4j_tpu.obs import numerics
from deeplearning4j_tpu.obs.numerics import NonFiniteError
from deeplearning4j_tpu.resilience import faults

N_IN, HIDDEN, CLASSES = 6, 10, 3


def _mk_net(seed=7):
    conf = (NeuralNetConfiguration.builder().seed(seed)
            .updater(upd.Adam(learning_rate=1e-2)).list()
            .layer(DenseLayer(n_out=HIDDEN, activation="relu"))
            .layer(DenseLayer(n_out=HIDDEN, activation="relu"))
            .layer(OutputLayer(n_out=CLASSES, activation="softmax",
                               loss="mcxent"))
            .set_input_type(InputType.feed_forward(N_IN)).build())
    return MultiLayerNetwork(conf).init()


def _data(rng, n=32):
    x = rng.normal(size=(n, N_IN)).astype(np.float32)
    y = np.eye(CLASSES, dtype=np.float32)[
        rng.integers(0, CLASSES, n)]
    return x, y


def _poison(net, layer="layer_1"):
    net.params[layer]["W"] = np.asarray(
        net.params[layer]["W"]) * 0 + np.inf


@pytest.fixture(autouse=True)
def _clean():
    numerics.reset_counters()
    yield
    numerics.reset_counters()
    faults.reset()


# --- off-path fence ---------------------------------------------------------

def test_off_path_is_byte_identical_and_transfer_free(rng):
    """Acceptance fence: with no monitor (and with one whose cadence
    never fires) the default compiled step's outputs are byte-identical
    and the numerics counters prove zero diag dispatches and zero
    diag device→host transfers."""
    import jax
    x, y = _data(rng)
    a, b = _mk_net(), _mk_net()
    b.monitor_numerics(every=10 ** 9)   # attached, never due
    for _ in range(3):
        a.fit(x, y)
        b.fit(x, y)
    jax.tree.map(
        lambda u, v: np.testing.assert_array_equal(
            np.asarray(u), np.asarray(v)), a.params, b.params)
    assert numerics.diag_dispatches() == 0
    assert numerics.host_pulls() == 0
    assert b._diag_step_fn is None      # diag program never even built


# --- in-step health ---------------------------------------------------------

def test_diag_step_reports_per_layer_health(rng):
    x, y = _data(rng)
    net = _mk_net()
    net.monitor_numerics(every=1, histograms=True)
    for _ in range(2):
        net.fit(x, y)
    num = net.last_numerics
    assert num["iteration"] == net.iteration == 2
    layers = {"layer_0", "layer_1", "layer_2"}
    for key in ("grad_norm", "update_norm", "param_norm",
                "update_ratio", "act_absmax"):
        assert set(num[key]) == layers, key
        assert all(v > 0 for v in num[key].values()), key
    assert all(v == 0 for v in num["grad_nonfinite"].values())
    # log2 sketches: fixed bins, populated for real updates
    assert len(num["update_hist"]["layer_0"]) == numerics.HIST_BINS
    assert sum(num["update_hist"]["layer_0"]) > 0
    assert numerics.diag_dispatches() == 2
    assert numerics.host_pulls() == 2   # ONE pull per diag step


def test_diag_step_update_matches_plain_step(rng):
    """The diagnostic step is the same update plus aux outputs — the
    trained params must match the plain step's."""
    x, y = _data(rng)
    a, b = _mk_net(), _mk_net()
    b.monitor_numerics(every=1)
    for _ in range(3):
        a.fit(x, y)
        b.fit(x, y)
    import jax
    jax.tree.map(
        lambda u, v: np.testing.assert_allclose(
            np.asarray(u), np.asarray(v), rtol=1e-6, atol=1e-7),
        a.params, b.params)


def test_metrics_families_and_trace_counter_tracks(rng):
    from deeplearning4j_tpu.obs import metrics, trace
    x, y = _data(rng)
    net = _mk_net()
    net.monitor_numerics(every=1)
    trace.enable()                      # ring-only
    try:
        net.fit(x, y)
        evs = trace.events()
    finally:
        trace.reset()
    counters = [e for e in evs if e.get("ph") == "C"]
    assert any(e["name"] == "numerics/grad_norm" and
               "layer_0" in e["args"] for e in counters)
    assert any(e["name"] == "numerics/update_ratio"
               for e in counters)
    text = metrics.exposition()
    fams = metrics.parse_exposition(text)   # must stay well-formed
    assert ("dl4j_tpu_numerics_grad_norm",
            (("layer", "layer_0"),)) in fams
    assert ("dl4j_tpu_numerics_update_ratio",
            (("layer", "layer_2"),)) in fams


# --- NaN attribution --------------------------------------------------------

def test_nan_attribution_names_poisoned_layer(rng):
    x, y = _data(rng)
    net = _mk_net()
    net.monitor_numerics(every=1)
    net.fit(x, y)
    _poison(net, "layer_1")
    with pytest.raises(NonFiniteError) as ei:
        net.fit(x, y)
    e = ei.value
    assert e.layer == "layer_1"         # forward origin, not layer_2
    assert e.kind == "activations"
    assert e.iteration == 2
    assert "non-finite" in str(e)
    num = net.last_numerics
    assert num["nonfinite"] == {"layer": "layer_1",
                                "kind": "activations"}
    # downstream of the origin is poisoned too — attribution picked
    # the FIRST forward-order layer, which is the point
    assert num["act_nonfinite"]["layer_2"] > 0
    assert num["act_nonfinite"]["layer_0"] == 0


def test_nonfinite_score_escalates_sparse_cadence(rng):
    """At a sparse cadence a NaN between diagnostic steps still gets
    attributed: the non-finite score forces the NEXT step to run as a
    diagnostic one."""
    x, y = _data(rng)
    net = _mk_net()
    net.monitor_numerics(every=1000)    # effectively never due
    net.fit(x, y)
    _poison(net, "layer_0")
    # plain step: loss goes non-finite, note_score arms escalation
    net.fit(x, y)
    assert net._numerics.force
    with pytest.raises(NonFiniteError) as ei:
        net.fit(x, y)
    assert ei.value.layer == "layer_0"
    assert numerics.diag_dispatches() == 1   # only the escalated step


def test_graph_diag_and_attribution(rng):
    from deeplearning4j_tpu.nn.graph import ComputationGraph, GraphBuilder
    g = (GraphBuilder()
         .add_inputs("in")
         .add_layer("h1", DenseLayer(n_out=HIDDEN, activation="relu"),
                    "in")
         .add_layer("h2", DenseLayer(n_out=HIDDEN, activation="relu"),
                    "h1")
         .add_layer("out", OutputLayer(n_out=CLASSES,
                                       activation="softmax",
                                       loss="mcxent"), "h2")
         .set_outputs("out")
         .set_input_types(**{"in": InputType.feed_forward(N_IN)}))
    net = ComputationGraph(g.build()).init()
    net.monitor_numerics(every=1)
    x, y = _data(rng)
    net.fit(x, y)
    num = net.last_numerics
    assert set(num["grad_norm"]) == {"h1", "h2", "out"}
    assert all(v > 0 for v in num["grad_norm"].values())
    net.params["h2"]["W"] = np.asarray(
        net.params["h2"]["W"]) * 0 + np.inf
    with pytest.raises(NonFiniteError) as ei:
        net.fit(x, y)
    assert ei.value.layer == "h2" and ei.value.kind == "activations"


# --- ParallelWrapper SPMD path ----------------------------------------------

def test_wrapper_sync_diag_reports_replica_divergence(rng):
    from deeplearning4j_tpu.parallel.wrapper import ParallelWrapper
    x, y = _data(rng, n=32)
    net = _mk_net(seed=3)
    net.monitor_numerics(every=1)
    pw = ParallelWrapper(net, workers=2, mode=ParallelWrapper.SYNC)
    it = ListDataSetIterator(DataSet(x, y), batch_size=16)
    pw.fit(it, epochs=1)
    num = net.last_numerics
    assert num["entry"] == "ParallelWrapper"
    assert set(num["replica_divergence"]) == {"layer_0", "layer_1",
                                              "layer_2"}
    # the two replicas saw different shards — their local grad norms
    # must differ (the signal the fused global-grad step cannot see)
    assert max(num["replica_divergence"].values()) > 0
    assert all(v >= 0 for v in num["replica_divergence"].values())
    assert all(v > 0 for v in num["grad_norm"].values())


# --- resilience restore path ------------------------------------------------

class _Poisoner:
    """Listener that poisons one layer's params at a given iteration
    (persistently: also after a restore rewinds past it)."""

    def __init__(self, at_iteration, layer="layer_1", once=False):
        self.at = at_iteration
        self.layer = layer
        self.once = once
        self.fired = 0

    def iteration_done(self, net, iteration, epoch):
        if iteration >= self.at and not (self.once and self.fired):
            self.fired += 1
            _poison(net, self.layer)

    def on_epoch_start(self, net):
        pass

    def on_epoch_end(self, net):
        pass


def test_trainer_restores_once_then_continues_after_poison(rng,
                                                           tmp_path):
    """One-shot poison: NonFiniteError attributes the layer, the
    trainer restores the newest valid checkpoint (PR 3 deterministic
    semantics) and training completes."""
    from deeplearning4j_tpu.train import FaultTolerantTrainer
    x, y = _data(rng, n=48)
    net = _mk_net(seed=11)
    net.monitor_numerics(every=1)
    net.listeners.append(_Poisoner(at_iteration=5, once=True))
    trainer = FaultTolerantTrainer(net, tmp_path,
                                   save_every_n_iterations=2)
    it = ListDataSetIterator(DataSet(x, y), batch_size=16)
    trainer.fit(it, epochs=4)
    assert trainer.restarts == 1
    assert np.isfinite(net.score_)
    assert net.epoch == 4               # full run completed
    assert all(np.isfinite(np.asarray(l)).all()
               for l in __import__("jax").tree.leaves(net.params))


def test_trainer_reraises_on_second_nonfinite(rng, tmp_path):
    """Persistent poison: ONE restore, then the NonFiniteError
    re-raises loudly with the attribution intact."""
    from deeplearning4j_tpu.train import FaultTolerantTrainer
    x, y = _data(rng, n=48)
    net = _mk_net(seed=11)
    net.monitor_numerics(every=1)
    net.listeners.append(_Poisoner(at_iteration=5))
    trainer = FaultTolerantTrainer(net, tmp_path,
                                   save_every_n_iterations=2)
    it = ListDataSetIterator(DataSet(x, y), batch_size=16)
    with pytest.raises(NonFiniteError) as ei:
        trainer.fit(it, epochs=4)
    assert ei.value.layer == "layer_1"
    assert trainer.restarts == 2        # restore, recur, re-raise


def test_fault_plan_injects_nonfinite_and_trainer_recovers(
        rng, tmp_path, monkeypatch):
    """DL4J_TPU_FAULT_PLAN step-site rule firing the structured
    sentinel: classified deterministic, one restore, run completes."""
    from deeplearning4j_tpu.train import FaultTolerantTrainer
    monkeypatch.setenv("DL4J_TPU_FAULT_PLAN",
                       "step:error=NonFiniteError:nth=4:max=1")
    faults.configure_from_env()
    try:
        x, y = _data(rng, n=48)
        net = _mk_net(seed=2)
        trainer = FaultTolerantTrainer(net, tmp_path,
                                       save_every_n_iterations=2)
        it = ListDataSetIterator(DataSet(x, y), batch_size=16)
        trainer.fit(it, epochs=3)
        assert trainer.restarts == 1
        assert net.epoch == 3
        st = faults.stats()
        assert sum(s["fires"] for s in st.values()) == 1
    finally:
        faults.reset()


# --- warmup + listener integration ------------------------------------------

def test_warmup_covers_diag_step(rng):
    from deeplearning4j_tpu.perf import sentry
    from deeplearning4j_tpu.perf.warmup import WarmupSpec
    x, y = _data(rng, n=8)
    net = _mk_net()
    net.monitor_numerics(every=1)
    rep = net.warmup([WarmupSpec(features=(8, N_IN),
                                 labels=(8, CLASSES))])
    assert rep["compiled"] >= 3         # train + DIAG + output
    before = sentry.total_traces()
    net.fit(x, y)                       # first step IS a diag step
    assert numerics.diag_dispatches() == 1
    assert sentry.total_traces() == before   # zero new traces


def test_stats_listener_consumes_in_step_numerics(rng):
    from deeplearning4j_tpu.train import InMemoryStatsStorage, StatsListener
    x, y = _data(rng, n=64)
    storage = InMemoryStatsStorage()
    net = _mk_net()
    listener = StatsListener(storage, frequency=1, session_id="nx",
                             collect_histograms=True)
    net.set_listeners(listener)
    net.fit(ListDataSetIterator(DataSet(x, y), batch_size=32),
            epochs=2)
    # the listener attached a record-aligned, non-raising monitor
    assert net._numerics is not None
    assert net._numerics.every == 1
    assert not net._numerics.raise_on_nonfinite
    recs = storage.get_records("nx")
    assert all("param_norms" in r for r in recs)
    last = recs[-1]
    for key in ("grad_norms", "update_norms", "update_ratios",
                "activation_stats"):
        assert set(last[key]) == set(net.params), key
    assert all(v > 0 for v in last["grad_norms"].values())
    h = last["update_histograms"]["layer_0"]
    assert sum(h["counts"]) > 0 and h["min"] < h["max"]
    # the old host-side previous-params copy is gone for good
    assert not hasattr(listener, "_prev_params")
