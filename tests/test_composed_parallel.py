"""Composed DP × SP × TP: the causal LM trained on ONE
{"data": 2, "seq": 2, "tensor": 2} mesh (8 virtual devices) — DP
gradient reduction + ring/zigzag sequence-parallel attention +
Megatron col→row tensor-parallel weights in a single jitted step —
must EXACT-MATCH the single-device step (VERDICT r4 Missing #1).

Reference analog: SharedTrainingMaster running a ParallelWrapper per
executor (multi-node × multi-device composition, SURVEY §3.5); the
TPU rebuild composes via one multi-axis mesh instead (SURVEY §2.5).
"""
import jax
import jax.numpy as jnp
import numpy as np
import pytest

from conftest import requires_modern_jax

pytestmark = pytest.mark.skipif(
    len(jax.devices()) < 8, reason="needs 8 virtual devices")

VOCAB, HID, LAYERS, HEADS, T, B = 64, 32, 2, 2, 32, 4


def _net(sp=None, seed=5):
    from deeplearning4j_tpu.zoo import CausalTransformerLM
    model = CausalTransformerLM(
        vocab_size=VOCAB, hidden=HID, n_layers=LAYERS, n_heads=HEADS,
        max_len=T, ffn_mult=2.0, tie_embeddings=True, seed=seed,
        sequence_parallel=sp)
    return model, model.init(seq_len=T)


def _batch():
    rng = np.random.default_rng(0)
    x = jnp.asarray(rng.integers(0, VOCAB, (B, T)), jnp.int32)
    y = jnp.asarray(rng.integers(0, VOCAB, (B, T)), jnp.int32)
    return x, y


def _run_steps(net, x, y, n=2):
    step = net._make_train_step()
    params, opt, state = net.params, net.opt_state, net.state
    key = jax.random.PRNGKey(0)
    losses = []
    for _ in range(n):
        params, opt, state, loss = step(params, opt, state, x, y,
                                        None, None, key)
        losses.append(float(loss))
    return losses, params

@pytest.mark.parametrize("sp_mode", ["ring", "zigzag_ring"])
@requires_modern_jax
def test_composed_dp_sp_tp_matches_single_device(sp_mode):
    """Two train steps on the composed mesh == two single-device
    steps: same losses, same updated params (every leaf)."""
    from deeplearning4j_tpu.parallel import (
        composed_context, composed_data_sharding, make_mesh,
        shard_lm_for_composed)

    x, y = _batch()
    # reference: same init, no context → local attention, one device
    _, ref_net = _net(sp=sp_mode)
    ref_losses, ref_params = _run_steps(ref_net, x, y)

    _, net = _net(sp=sp_mode)
    mesh = make_mesh({"data": 2, "seq": 2, "tensor": 2})
    shard_lm_for_composed(net, mesh, tensor_axis="tensor")
    ds = composed_data_sharding(mesh)
    xs, ys = jax.device_put(x, ds), jax.device_put(y, ds)
    with composed_context(mesh):
        losses, params = _run_steps(net, xs, ys)

    np.testing.assert_allclose(losses, ref_losses, rtol=2e-5)
    for (ka, a), (kb, b) in zip(
            jax.tree_util.tree_leaves_with_path(params),
            jax.tree_util.tree_leaves_with_path(ref_params)):
        assert str(ka) == str(kb)
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=5e-4, atol=5e-5,
            err_msg=str(ka))


def test_composed_params_actually_sharded():
    """The TP placement is real: col/row weights land with a 'tensor'
    dimension in their sharding, batch rides 'data' — not a silent
    full replication (the canary class the volume gates exist for)."""
    from deeplearning4j_tpu.parallel import (make_mesh,
                                             shard_lm_for_composed)
    _, net = _net(sp="ring")
    mesh = make_mesh({"data": 2, "seq": 2, "tensor": 2})
    shard_lm_for_composed(net, mesh)
    found_col = found_row = False
    for path, leaf in jax.tree_util.tree_leaves_with_path(net.params):
        spec = leaf.sharding.spec
        names = [p.key for p in path if hasattr(p, "key")]
        if names[-1] in ("Wq", "Wk", "Wv", "Wg", "Wu"):
            assert spec == ("tensor",) or spec[1] == "tensor", (
                names, spec)
            found_col = True
        if names[-1] in ("Wo", "Wd"):
            assert spec[0] == "tensor", (names, spec)
            found_row = True
    assert found_col and found_row


@requires_modern_jax
def test_composed_gqa_matches_single_device():
    """Composed mesh with grouped-query attention: kv heads (2) shard
    over 'tensor' alongside the query heads (4) — the ring carries the
    SMALL kv per shard. One train step must match the single-device
    step. (Masked ring attention under a composed mesh is covered at
    the layer level by test_composed_dp_sp_tp_matches_single_device's
    zigzag variant machinery + tests/test_parallel.py's masked rings —
    the LM's fit path itself doesn't thread key masks.)"""
    from deeplearning4j_tpu.parallel import (
        composed_context, composed_data_sharding, make_mesh,
        shard_lm_for_composed)
    from deeplearning4j_tpu.zoo import CausalTransformerLM

    def build():
        model = CausalTransformerLM(
            vocab_size=VOCAB, hidden=HID, n_layers=2, n_heads=4,
            n_kv_heads=2, max_len=T, ffn_mult=2.0,
            tie_embeddings=True, seed=9, sequence_parallel="ring")
        return model.init(seq_len=T)

    x, y = _batch()
    ref_losses, ref_params = _run_steps(build(), x, y, n=1)

    net = build()
    mesh = make_mesh({"data": 2, "seq": 2, "tensor": 2})
    shard_lm_for_composed(net, mesh)
    ds = composed_data_sharding(mesh)
    xs, ys = jax.device_put(x, ds), jax.device_put(y, ds)
    with composed_context(mesh):
        losses, params = _run_steps(net, xs, ys, n=1)

    np.testing.assert_allclose(losses, ref_losses, rtol=2e-5)
    for (ka, a), (kb, b) in zip(
            jax.tree_util.tree_leaves_with_path(params),
            jax.tree_util.tree_leaves_with_path(ref_params)):
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=5e-4, atol=5e-5,
            err_msg=str(ka))
