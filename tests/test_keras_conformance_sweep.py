"""Keras conformance sweep (reference: KerasModelEndToEndTest — ~60
end-to-end .h5 models imported and compared against Keras-produced
activations, SURVEY §4).

Like the TF (306 graphs) and ONNX (113 graphs) sweeps, cases are
*generated*: per-mapper Keras models are built in-process with the
installed Keras, saved, imported, and the forward pass must match the
Keras prediction within tolerance. A final coverage gate compares
``keras_import.MAPPED_LAYER_CLASSES`` against the classes the sweep
actually exercised and fails on any unswept mapper.
"""
import numpy as np
import pytest

keras = pytest.importorskip("keras")

from deeplearning4j_tpu.modelimport import KerasModelImport  # noqa: E402
from deeplearning4j_tpu.modelimport.keras_import import (  # noqa: E402
    MAPPED_LAYER_CLASSES)

L = keras.layers
RNG = np.random.default_rng(2026)

#: Keras classes observed across all swept model configs
SWEPT = set()
#: how many sweep models actually ran this session (the coverage gate
#: only judges a COMPLETE sweep — pytest -k subsets skip it)
RAN = []

#: mapped classes that CANNOT be swept against installed Keras 3
#: (removed upstream) — still importable from legacy h5 archives and
#: covered by the hand-written crafted-archive tests
EXEMPT = {
    "ThresholdedReLU",       # removed in Keras 3
    "LocallyConnected1D",    # removed in Keras 3
    "LocallyConnected2D",    # removed in Keras 3
}

#: pure aliases that resolve through the same mapper branch as the
#: canonical class name (legacy Keras-1 spellings)
ALIASES = {"Convolution1D", "Convolution2D", "Convolution3D",
           "Convolution2DTranspose"}


def _record(model):
    """Walk the serialized config and record every layer class seen."""
    def walk(node):
        if isinstance(node, dict):
            cn = node.get("class_name")
            if cn:
                SWEPT.add(cn)
            for v in node.values():
                walk(v)
        elif isinstance(node, (list, tuple)):
            for v in node:
                walk(v)
    walk(model.get_config())
    SWEPT.add("InputLayer")      # implicit in every built model


def _run(model, x, tmp_path, rtol=1e-4, atol=1e-5):
    _record(model)
    RAN.append(1)
    path = str(tmp_path / "m.h5")
    model.save(path)
    net = KerasModelImport.import_model(path)
    want = np.asarray(model(x if not isinstance(x, list) else
                            [np.asarray(v) for v in x], training=False))
    got = net.output(*x) if isinstance(x, list) else net.output(x)
    if isinstance(got, (list, tuple)):
        got = got[0]
    np.testing.assert_allclose(np.asarray(got), want, rtol=rtol,
                               atol=atol)


def _x(*shape):
    return RNG.normal(size=shape).astype(np.float32)


# ---------------------------------------------------------------------------
# case table: (id, builder) — builder returns (keras model, input)
# ---------------------------------------------------------------------------
def _seq(input_shape, *layers):
    return keras.Sequential([L.Input(input_shape), *layers])


CASES = [
    # dense family
    ("dense_relu", lambda: (_seq((7,), L.Dense(5, activation="relu"),
                                 L.Dense(3)), _x(4, 7))),
    ("dense_nobias_softmax", lambda: (_seq(
        (6,), L.Dense(4, use_bias=False, activation="softmax")),
        _x(3, 6))),
    ("conv_flatten_dense", lambda: (_seq(
        (8, 8, 2), L.Conv2D(3, 3), L.Flatten(), L.Dense(4)),
        _x(2, 8, 8, 2))),
    # conv2d family
    ("conv2d_same", lambda: (_seq(
        (12, 12, 3), L.Conv2D(6, 3, padding="same", activation="relu")),
        _x(2, 12, 12, 3))),
    ("conv2d_valid_strides", lambda: (_seq(
        (13, 13, 2), L.Conv2D(4, 3, strides=2, padding="valid")),
        _x(2, 13, 13, 2))),
    ("conv2d_dilated", lambda: (_seq(
        (14, 14, 2), L.Conv2D(4, 3, dilation_rate=2)), _x(1, 14, 14, 2))),
    ("conv1d", lambda: (_seq(
        (11, 4), L.Conv1D(6, 3, padding="same", activation="tanh")),
        _x(2, 11, 4))),
    ("conv1d_strides", lambda: (_seq(
        (12, 3), L.Conv1D(5, 3, strides=2, padding="valid")),
        _x(2, 12, 3))),
    ("conv2dtranspose_same", lambda: (_seq(
        (8, 8, 3), L.Conv2DTranspose(5, 3, padding="same")),
        _x(2, 8, 8, 3))),
    ("conv2dtranspose_strides", lambda: (_seq(
        (7, 7, 2), L.Conv2DTranspose(4, 3, strides=2, padding="valid")),
        _x(1, 7, 7, 2))),
    ("conv3d", lambda: (_seq(
        (6, 6, 6, 2), L.Conv3D(3, 2, activation="relu")),
        _x(1, 6, 6, 6, 2))),
    ("depthwise_m1", lambda: (_seq(
        (10, 10, 3), L.DepthwiseConv2D(3, padding="same")),
        _x(2, 10, 10, 3))),
    ("depthwise_m2_strides", lambda: (_seq(
        (11, 11, 2), L.DepthwiseConv2D(3, strides=2,
                                       depth_multiplier=2)),
        _x(2, 11, 11, 2))),
    # SeparableConv edge configs (VERDICT r2 #8)
    ("separable_basic", lambda: (_seq(
        (10, 10, 3), L.SeparableConv2D(5, 3)), _x(2, 10, 10, 3))),
    ("separable_m2_same", lambda: (_seq(
        (9, 9, 2), L.SeparableConv2D(4, 3, depth_multiplier=2,
                                     padding="same",
                                     activation="relu")),
        _x(2, 9, 9, 2))),
    ("separable_strides_nobias", lambda: (_seq(
        (12, 12, 3), L.SeparableConv2D(6, 3, strides=2,
                                       use_bias=False)),
        _x(1, 12, 12, 3))),
    # pooling
    ("maxpool2d", lambda: (_seq(
        (10, 10, 2), L.MaxPooling2D(2)), _x(2, 10, 10, 2))),
    ("avgpool2d_pad", lambda: (_seq(
        (9, 9, 2), L.AveragePooling2D(2, padding="same")),
        _x(2, 9, 9, 2))),
    ("maxpool1d", lambda: (_seq((12, 3), L.MaxPooling1D(2)),
                           _x(2, 12, 3))),
    ("avgpool1d_stride3", lambda: (_seq(
        (12, 3), L.AveragePooling1D(2, strides=3)), _x(2, 12, 3))),
    ("maxpool3d", lambda: (_seq(
        (6, 6, 6, 2), L.MaxPooling3D(2)), _x(1, 6, 6, 6, 2))),
    ("avgpool3d", lambda: (_seq(
        (6, 6, 6, 2), L.AveragePooling3D(2)), _x(1, 6, 6, 6, 2))),
    ("globalmax2d", lambda: (_seq(
        (8, 8, 3), L.GlobalMaxPooling2D()), _x(2, 8, 8, 3))),
    ("globalavg2d", lambda: (_seq(
        (8, 8, 3), L.GlobalAveragePooling2D()), _x(2, 8, 8, 3))),
    ("globalmax1d", lambda: (_seq((9, 4), L.GlobalMaxPooling1D()),
                             _x(2, 9, 4))),
    ("globalavg1d", lambda: (_seq((9, 4), L.GlobalAveragePooling1D()),
                             _x(2, 9, 4))),
    # norm
    ("batchnorm_conv", lambda: (_seq(
        (8, 8, 3), L.Conv2D(4, 3), L.BatchNormalization()),
        _x(2, 8, 8, 3))),
    ("batchnorm_dense_nocenter", lambda: (_seq(
        (6,), L.Dense(5), L.BatchNormalization(center=False)),
        _x(3, 6))),
    ("layernorm", lambda: (_seq(
        (7,), L.Dense(6), L.LayerNormalization()), _x(3, 7))),
    # dropout family (identity at inference — import must still map)
    ("dropouts", lambda: (_seq(
        (6,), L.Dense(5), L.Dropout(0.3), L.GaussianNoise(0.1),
        L.GaussianDropout(0.2), L.AlphaDropout(0.1)), _x(3, 6))),
    ("spatial_dropouts", lambda: (_seq(
        (8, 8, 2), L.SpatialDropout2D(0.2), L.Conv2D(3, 3)),
        _x(2, 8, 8, 2))),
    ("spatial_dropout1d", lambda: (_seq(
        (9, 3), L.SpatialDropout1D(0.2), L.Conv1D(3, 3)), _x(2, 9, 3))),
    ("spatial_dropout3d", lambda: (_seq(
        (5, 5, 5, 2), L.SpatialDropout3D(0.2), L.Conv3D(2, 2)),
        _x(1, 5, 5, 5, 2))),
    # activations
    ("activation_layer", lambda: (_seq(
        (6,), L.Dense(4), L.Activation("tanh")), _x(2, 6))),
    ("relu_layer_max", lambda: (_seq(
        (6,), L.Dense(4), L.ReLU(max_value=1.0)), _x(2, 6))),
    ("relu_layer_slope", lambda: (_seq(
        (6,), L.Dense(4), L.ReLU(negative_slope=0.2)), _x(2, 6))),
    ("leaky_relu", lambda: (_seq(
        (6,), L.Dense(4), L.LeakyReLU(negative_slope=0.1)), _x(2, 6))),
    ("prelu", lambda: (_seq((6,), L.Dense(4), L.PReLU()), _x(2, 6))),
    ("elu_softmax", lambda: (_seq(
        (6,), L.Dense(4), L.ELU(), L.Dense(3), L.Softmax()), _x(2, 6))),
    # shape ops
    ("zeropad2d_crop2d", lambda: (_seq(
        (8, 8, 2), L.ZeroPadding2D(((1, 2), (0, 1))),
        L.Cropping2D(((1, 0), (2, 1)))), _x(2, 8, 8, 2))),
    ("zeropad1d_crop1d", lambda: (_seq(
        (9, 3), L.ZeroPadding1D(2), L.Cropping1D((1, 2))), _x(2, 9, 3))),
    ("zeropad3d_crop3d", lambda: (_seq(
        (5, 5, 5, 2), L.ZeroPadding3D(1), L.Cropping3D(1)),
        _x(1, 5, 5, 5, 2))),
    ("upsampling2d", lambda: (_seq(
        (5, 5, 2), L.UpSampling2D(2)), _x(2, 5, 5, 2))),
    ("upsampling1d", lambda: (_seq((6, 3), L.UpSampling1D(2)),
                              _x(2, 6, 3))),
    ("upsampling3d", lambda: (_seq(
        (4, 4, 4, 2), L.UpSampling3D(2)), _x(1, 4, 4, 4, 2))),
    ("repeat_vector", lambda: (_seq(
        (5,), L.Dense(4), L.RepeatVector(3)), _x(2, 5))),
    # recurrent
    ("lstm_seq", lambda: (_seq(
        (8, 4), L.LSTM(5, return_sequences=True)), _x(2, 8, 4))),
    ("lstm_last", lambda: (_seq((8, 4), L.LSTM(5)), _x(2, 8, 4))),
    ("gru_reset_after", lambda: (_seq(
        (8, 4), L.GRU(5, reset_after=True)), _x(2, 8, 4))),
    ("gru_no_reset_after", lambda: (_seq(
        (8, 4), L.GRU(5, reset_after=False, return_sequences=True)),
        _x(2, 8, 4))),
    ("simplernn", lambda: (_seq(
        (7, 3), L.SimpleRNN(4, return_sequences=True)), _x(2, 7, 3))),
    ("bidirectional_concat", lambda: (_seq(
        (8, 4), L.Bidirectional(L.LSTM(3, return_sequences=True))),
        _x(2, 8, 4))),
    ("bidirectional_sum_last", lambda: (_seq(
        (8, 4), L.Bidirectional(L.LSTM(3), merge_mode="sum")),
        _x(2, 8, 4))),
    ("timedistributed_dense", lambda: (_seq(
        (6, 4), L.TimeDistributed(L.Dense(3))), _x(2, 6, 4))),
    ("masking_lstm", lambda: (_seq(
        (6, 3), L.Masking(), L.LSTM(4, return_sequences=True)),
        _x(2, 6, 3))),
    # ConvLSTM2D (VERDICT r2 #8 named mapper)
    ("convlstm2d_last", lambda: (_seq(
        (4, 8, 8, 2), L.ConvLSTM2D(3, 3, padding="same")),
        _x(2, 4, 8, 8, 2))),
    ("convlstm2d_seq_valid", lambda: (_seq(
        (3, 9, 9, 2), L.ConvLSTM2D(4, 3, strides=2,
                                   return_sequences=True)),
        _x(1, 3, 9, 9, 2))),
    # embedding
    ("embedding", lambda: (
        _seq((5,), L.Embedding(11, 6), L.LSTM(4)),
        RNG.integers(0, 11, (3, 5)).astype(np.float32))),
]


@pytest.mark.parametrize("case_id,builder", CASES,
                         ids=[c[0] for c in CASES])
def test_keras_conformance(case_id, builder, tmp_path):
    model, x = builder()
    tol = {"convlstm2d_last": (5e-4, 5e-5),
           "convlstm2d_seq_valid": (5e-4, 5e-5),
           "lstm_seq": (2e-4, 2e-5), "lstm_last": (2e-4, 2e-5),
           "bidirectional_concat": (2e-4, 2e-5),
           "bidirectional_sum_last": (2e-4, 2e-5)}.get(
        case_id, (1e-4, 1e-5))
    _run(model, x, tmp_path, rtol=tol[0], atol=tol[1])


def test_functional_merge_layers(tmp_path):
    """Add/Subtract/Multiply/Average/Maximum/Concatenate through the
    functional-model vertex map."""
    a = L.Input((6,), name="a")
    b = L.Input((6,), name="b")
    da = L.Dense(5, activation="tanh")(a)
    db = L.Dense(5, activation="tanh")(b)
    merged = [L.Add()([da, db]), L.Subtract()([da, db]),
              L.Multiply()([da, db]), L.Average()([da, db]),
              L.Maximum()([da, db])]
    out = L.Concatenate()(merged)
    out = L.Dense(3)(out)
    model = keras.Model([a, b], out)
    xa, xb = _x(3, 6), _x(3, 6)
    _run(model, [xa, xb], tmp_path)


def test_keras_sweep_coverage_gate():
    """Every mapped Keras class must be exercised by the sweep (or be
    explicitly exempt with a reason) — mapped-vs-swept gate mirroring
    the TF/ONNX sweeps."""
    assert len(CASES) >= 40, "sweep shrank below the 40-model floor"
    if len(RAN) < len(CASES) + 1:      # CASES + the functional model
        pytest.skip("coverage gate judges only a complete sweep run")
    unswept = MAPPED_LAYER_CLASSES - SWEPT - EXEMPT - ALIASES
    assert not unswept, (
        f"mapped Keras classes never swept: {sorted(unswept)} — add a "
        "generated case or an explicit exemption with a reason")
    stale = (EXEMPT | ALIASES) - MAPPED_LAYER_CLASSES
    assert not stale, f"exempt/alias entries not in mapper: {stale}"
