"""Causal transformer LM (decoder-only): GQA/RoPE attention pieces,
training convergence, and KV-cached generation consistency with the
training-time forward (the transformer analog of the reference's
``rnnTimeStep`` stored-state tests)."""
import jax
import jax.numpy as jnp
import numpy as np
import pytest

from conftest import requires_modern_jax

from deeplearning4j_tpu.nn.layers.attention import (
    MultiHeadAttention, repeat_kv_heads, rotary_embedding,
    scaled_dot_attention)
from deeplearning4j_tpu.zoo import GPTNano

def test_rope_relative_position_invariance(rng):
    """RoPE scores depend only on RELATIVE position: applying a common
    position offset to q and k must not change q·kᵀ."""
    b, t, h, d = 1, 6, 2, 16
    q = jnp.asarray(rng.standard_normal((b, t, h, d)), jnp.float32)
    k = jnp.asarray(rng.standard_normal((b, t, h, d)), jnp.float32)

    def scores(off):
        qr = rotary_embedding(q, offset=off)
        kr = rotary_embedding(k, offset=off)
        return jnp.einsum("bqhd,bkhd->bhqk", qr, kr)

    np.testing.assert_allclose(np.asarray(scores(0)),
                               np.asarray(scores(17)),
                               rtol=1e-4, atol=1e-5)
    # ...and a shift of k only DOES change them (sanity)
    shifted = jnp.einsum("bqhd,bkhd->bhqk", rotary_embedding(q),
                         rotary_embedding(k, offset=3))
    assert float(jnp.max(jnp.abs(shifted - scores(0)))) > 1e-3


def test_gqa_matches_explicit_repeat(rng):
    """n_kv_heads attention == attention with kv heads explicitly
    broadcast (the GQA contract)."""
    layer = MultiHeadAttention(n_in=16, n_out=16, n_heads=4,
                               n_kv_heads=2, causal=True)
    params, _, _ = layer.init(jax.random.PRNGKey(0), (8, 16))
    x = jnp.asarray(rng.standard_normal((2, 8, 16)), jnp.float32)
    out, _ = layer.apply(params, {}, x)

    q = (x @ params["Wq"]).reshape(2, 8, 4, 4)
    k = repeat_kv_heads((x @ params["Wk"]).reshape(2, 8, 2, 4), 4)
    v = repeat_kv_heads((x @ params["Wv"]).reshape(2, 8, 2, 4), 4)
    want = scaled_dot_attention(q, k, v, causal=True).reshape(2, 8, 16)
    want = want @ params["Wo"] + params["bo"]
    np.testing.assert_allclose(np.asarray(out), np.asarray(want),
                               rtol=1e-5, atol=1e-6)


def test_gqa_param_shapes():
    layer = MultiHeadAttention(n_in=32, n_out=32, n_heads=8,
                               n_kv_heads=2)
    params, _, _ = layer.init(jax.random.PRNGKey(0), (4, 32))
    assert params["Wq"].shape == (32, 32)
    assert params["Wk"].shape == (32, 8)      # 2 kv heads × head_dim 4
    assert params["Wv"].shape == (32, 8)
    with pytest.raises(ValueError, match="n_kv_heads"):
        MultiHeadAttention(n_in=32, n_out=32, n_heads=8,
                           n_kv_heads=3).init(jax.random.PRNGKey(0),
                                              (4, 32))


@pytest.fixture(scope="module")
def toy_lm():
    """GPTNano trained on a deterministic repeating token pattern."""
    model = GPTNano(vocab_size=16, max_len=64, seed=5)
    net = model.init(seq_len=24)
    period = 5
    tokens = np.arange(24 + 1) % period + 1          # 1..5 repeating
    x = np.tile(tokens[:24], (8, 1)).astype(np.int32)
    y = np.tile(tokens[1:25], (8, 1)).astype(np.int32)
    s0 = None
    for _ in range(60):
        net.fit(x, y)
        s0 = s0 if s0 is not None else net.score()
    return model, net, s0, period


def test_lm_trains(toy_lm):
    model, net, s0, _ = toy_lm
    assert net.score() < s0 * 0.2, (net.score(), s0)


def test_generate_matches_training_forward(toy_lm):
    """The KV-cached decode must agree with the training-time forward:
    the first generated token equals argmax of net.output at the
    prompt's last position."""
    model, net, _, period = toy_lm
    prompt = (np.arange(9) % period + 1)[None, :].astype(np.int32)
    out = model.generate(net, prompt, n_new=6)
    probs = np.asarray(net.output(prompt))           # [1, 9, V]
    assert out[0, 9] == int(np.argmax(probs[0, -1]))


def test_generate_continues_pattern(toy_lm):
    model, net, _, period = toy_lm
    prompt = (np.arange(10) % period + 1)[None, :].astype(np.int32)
    out = model.generate(net, prompt, n_new=8)
    np.testing.assert_array_equal(out[0, :10], prompt[0])  # unchanged
    want = (np.arange(10, 18) % period + 1)
    np.testing.assert_array_equal(out[0, 10:], want)


def test_remat_same_loss_and_gradients():
    """remat=True must be numerically identical to remat=False (only
    memory behavior differs): same loss, same post-step params."""
    def build(remat):
        m = GPTNano(vocab_size=16, max_len=32, seed=5, remat=remat)
        return m.init(seq_len=12)

    tokens = np.arange(13) % 5 + 1
    x = np.tile(tokens[:12], (4, 1)).astype(np.int32)
    y = np.tile(tokens[1:13], (4, 1)).astype(np.int32)
    nets = [build(False), build(True)]
    for net in nets:
        net.fit(x, y)
    assert nets[0].score() == pytest.approx(nets[1].score(), rel=1e-6)
    a = jax.tree.leaves(nets[0].params)[0]
    b = jax.tree.leaves(nets[1].params)[0]
    np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=1e-6)


def test_generate_n_new_zero_returns_prompt(toy_lm):
    """n_new=0 must hand the prompt back untouched (regression: the
    final-slot write used to clobber the last prompt token)."""
    model, net, _, _ = toy_lm
    prompt = np.asarray([[1, 2, 3, 4, 5]], np.int32)
    out = model.generate(net, prompt, n_new=0)
    np.testing.assert_array_equal(out, prompt)


def test_generate_uses_current_params(toy_lm):
    """Params are a jit argument, not a closure capture: decoding after
    further training must reflect the NEW params through the cached
    compiled scan."""
    model, net, _, period = toy_lm
    prompt = (np.arange(9) % period + 1)[None, :].astype(np.int32)
    model.generate(net, prompt, n_new=2)      # populate the jit cache
    old = {k: jax.tree.map(np.array, v) for k, v in net.params.items()}
    x = np.tile((np.arange(25) % period + 1)[:24], (8, 1)).astype(np.int32)
    y = np.tile((np.arange(25) % period + 1)[1:25], (8, 1)).astype(np.int32)
    net.fit(x, y)                              # params change
    out2 = model.generate(net, prompt, n_new=2)
    probs = np.asarray(net.output(prompt))
    assert out2[0, 9] == int(np.argmax(probs[0, -1]))
    net.params = old                           # restore for other tests


@requires_modern_jax
def test_ring_attention_gqa_matches_dense():
    """GQA through the distributed ring: kv with fewer heads must
    equal dense attention with kv heads broadcast (only the small kv
    travels the ring)."""
    from deeplearning4j_tpu.parallel import make_mesh, \
        ring_self_attention
    mesh = make_mesh({"seq": 8})
    b, t, h, hkv, d = 1, 32, 4, 2, 8
    kq, kk, kv = jax.random.split(jax.random.PRNGKey(12), 3)
    q = jax.random.normal(kq, (b, t, h, d))
    k = jax.random.normal(kk, (b, t, hkv, d))
    v = jax.random.normal(kv, (b, t, hkv, d))
    ring = ring_self_attention(q, k, v, mesh, causal=True)
    want = scaled_dot_attention(q, repeat_kv_heads(k, h),
                                repeat_kv_heads(v, h), causal=True)
    np.testing.assert_allclose(np.asarray(ring), np.asarray(want),
                               rtol=2e-4, atol=2e-5)
    g = jax.grad(lambda k: jnp.sum(
        ring_self_attention(q, k, v, mesh, causal=True) ** 2))(k)
    gw = jax.grad(lambda k: jnp.sum(scaled_dot_attention(
        q, repeat_kv_heads(k, h), repeat_kv_heads(v, h),
        causal=True) ** 2))(k)
    np.testing.assert_allclose(np.asarray(g), np.asarray(gw),
                               rtol=2e-4, atol=2e-5)


@requires_modern_jax
def test_lm_trains_sequence_parallel():
    """The flagship long-context combination: the causal LM trains
    with ring sequence parallelism purely via the layer API."""
    from deeplearning4j_tpu.parallel import (distributed_context,
                                             make_mesh)
    model = GPTNano(vocab_size=16, max_len=64, seed=5,
                    sequence_parallel="ring")
    net = model.init(seq_len=16)
    tokens = np.arange(17) % 5 + 1
    x = np.tile(tokens[:16], (4, 1)).astype(np.int32)
    y = np.tile(tokens[1:17], (4, 1)).astype(np.int32)
    with distributed_context(make_mesh({"seq": 8})):
        s0 = None
        for _ in range(10):
            net.fit(x, y)
            s0 = s0 if s0 is not None else net.score()
    assert np.isfinite(net.score()) and net.score() < s0


def test_generate_batched_and_sampled(toy_lm):
    model, net, _, period = toy_lm
    prompts = np.stack([(np.arange(8) % period + 1),
                        (np.arange(1, 9) % period + 1)]).astype(np.int32)
    out = model.generate(net, prompts, n_new=4)
    assert out.shape == (2, 12)
    # temperature sampling stays in-vocab and is reproducible per key
    s1 = model.generate(net, prompts, n_new=4, temperature=0.8,
                        rng=jax.random.PRNGKey(7))
    s2 = model.generate(net, prompts, n_new=4, temperature=0.8,
                        rng=jax.random.PRNGKey(7))
    np.testing.assert_array_equal(s1, s2)
    assert s1.min() >= 0 and s1.max() < 16


def _sequence_logprob(net, seq, t0):
    """Σ log p(token_i | tokens_<i) over the generated region under the
    training-time forward — the objective beam search maximises."""
    probs = np.asarray(net.output(seq[:, :-1]))     # [B, T-1, V]
    lp = 0.0
    for i in range(t0 - 1, seq.shape[1] - 1):
        lp += float(np.log(probs[0, i, seq[0, i + 1]] + 1e-30))
    return lp


def test_beam_search_matches_greedy_at_one_beam(toy_lm):
    model, net, _, period = toy_lm
    prompt = (np.arange(9) % period + 1)[None, :].astype(np.int32)
    greedy = model.generate(net, prompt, n_new=6)
    beam1 = model.generate_beam(net, prompt, n_new=6, beams=1)
    np.testing.assert_array_equal(greedy, beam1)


def test_beam_search_exact_at_full_width():
    """With beams == vocab_size and n_new == 2, beam search IS
    exhaustive (step 1 keeps every first token, step 2 maximises over
    all V² continuations) — so its result must equal the brute-force
    argmax over every 2-token continuation, and its logprob must be
    >= greedy's. Uses an UNDERTRAINED model so greedy is suboptimal-
    prone."""
    V = 16
    model = GPTNano(vocab_size=V, max_len=64, seed=13)
    net = model.init(seq_len=20)
    rng = np.random.default_rng(3)
    net.fit(rng.integers(1, V, (8, 20)).astype(np.int32),
            rng.integers(1, V, (8, 20)).astype(np.int32))
    prompt = np.asarray([[1, 2, 3, 4, 5, 6]], np.int32)
    t0 = prompt.shape[1]
    beam = model.generate_beam(net, prompt, n_new=2, beams=V)

    # brute force: total logprob of every (t1, t2) continuation
    cands = np.asarray([[a, c] for a in range(V) for c in range(V)],
                       np.int32)
    seqs = np.concatenate(
        [np.tile(prompt, (V * V, 1)), cands], axis=1)
    probs = np.asarray(net.output(seqs[:, :-1]))   # [V², t0+1, V]
    lp = (np.log(probs[np.arange(V * V), t0 - 1, cands[:, 0]] + 1e-30)
          + np.log(probs[np.arange(V * V), t0, cands[:, 1]] + 1e-30))
    best = cands[int(np.argmax(lp))]
    np.testing.assert_array_equal(beam[0, t0:], best)
    greedy = model.generate(net, prompt, n_new=2)
    assert _sequence_logprob(net, beam, t0) >= \
        _sequence_logprob(net, greedy, t0) - 1e-5


def test_beam_search_batched_and_guards(toy_lm):
    model, net, _, period = toy_lm
    prompts = np.stack([(np.arange(8) % period + 1),
                        (np.arange(2, 10) % period + 1)]).astype(np.int32)
    out = model.generate_beam(net, prompts, n_new=4, beams=3)
    assert out.shape == (2, 12)
    np.testing.assert_array_equal(out[:, :8], prompts)   # prompts kept
    # the sharply-trained toy model: beam == greedy continuation
    greedy = model.generate(net, prompts, n_new=4)
    np.testing.assert_array_equal(out, greedy)
    np.testing.assert_array_equal(
        model.generate_beam(net, prompts, n_new=0, beams=3), prompts)
    with pytest.raises(ValueError, match="beams"):
        model.generate_beam(net, prompts, n_new=2, beams=99)


def test_generate_top_k_top_p(toy_lm):
    """top_k=1 sampling collapses to greedy regardless of temperature
    or seed; top_p in-vocab and reproducible; filters compose."""
    model, net, _, period = toy_lm
    prompt = (np.arange(8) % period + 1)[None, :].astype(np.int32)
    greedy = model.generate(net, prompt, n_new=5)
    k1 = model.generate(net, prompt, n_new=5, temperature=2.0,
                        top_k=1, rng=jax.random.PRNGKey(0))
    np.testing.assert_array_equal(greedy, k1)
    # a sharply-trained model puts ~all mass on one token: tiny top_p
    # also reproduces greedy
    p_small = model.generate(net, prompt, n_new=5, temperature=1.0,
                             top_p=0.5, rng=jax.random.PRNGKey(1))
    np.testing.assert_array_equal(greedy, p_small)
    both = model.generate(net, prompt, n_new=5, temperature=0.9,
                          top_k=3, top_p=0.9,
                          rng=jax.random.PRNGKey(2))
    assert both.min() >= 0 and both.max() < 16


def test_prefill_bucket_reuse_and_padding(toy_lm):
    """Prompt lengths sharing a power-of-two bucket reuse ONE compiled
    decode (prompt padded, true length traced), and padding never
    leaks into outputs: every prompt length continues the pattern
    exactly (VERDICT r3 Missing #2 + Next #10 serving cache)."""
    model, net, _, period = toy_lm
    model._gen_cache = {}
    outs = {}
    for t0 in (9, 12, 16):                      # bucket(9|12|16) == 16
        prompt = (np.arange(t0) % period + 1)[None, :].astype(np.int32)
        outs[t0] = model.generate(net, prompt, n_new=4)
    assert len(model._gen_cache) == 1, list(model._gen_cache)
    for t0, out in outs.items():
        want = (np.arange(t0, t0 + 4) % period + 1)
        np.testing.assert_array_equal(out[0, t0:], want)
    # a different bucket compiles separately
    prompt = (np.arange(20) % period + 1)[None, :].astype(np.int32)
    model.generate(net, prompt, n_new=4)
    assert len(model._gen_cache) == 2


def test_beam_prefill_bucket_reuse(toy_lm):
    model, net, _, period = toy_lm
    model._gen_cache = {}
    for t0 in (9, 13):
        prompt = (np.arange(t0) % period + 1)[None, :].astype(np.int32)
        out = model.generate_beam(net, prompt, n_new=3, beams=2)
        want = (np.arange(t0, t0 + 3) % period + 1)
        np.testing.assert_array_equal(out[0, t0:], want)
    assert len(model._gen_cache) == 1, list(model._gen_cache)


def test_generate_top_k_validation(toy_lm):
    model, net, _, _ = toy_lm
    prompt = np.ones((1, 4), np.int32)
    with pytest.raises(ValueError, match="top_k"):
        model.generate(net, prompt, n_new=2, temperature=1.0, top_k=0)
    with pytest.raises(ValueError, match="top_k"):
        model.generate(net, prompt, n_new=2, temperature=1.0,
                       top_k=model.vocab_size + 1)


def test_generate_default_rng_varies_across_calls(toy_lm):
    """Sampled calls WITHOUT an explicit rng must not all replay the
    same stream (ADVICE r3: fixed PRNGKey(0) default)."""
    model, net, _, _ = toy_lm
    prompt = np.ones((4, 4), np.int32)
    a = model.generate(net, prompt, n_new=8, temperature=3.0)
    b = model.generate(net, prompt, n_new=8, temperature=3.0)
    assert not np.array_equal(a, b)


def test_generate_top_p_validation(toy_lm):
    model, net, _, _ = toy_lm
    prompt = np.ones((1, 4), np.int32)
    for bad in (0.0, -0.2, 1.5):
        with pytest.raises(ValueError, match="top_p"):
            model.generate(net, prompt, n_new=2, temperature=1.0,
                           top_p=bad)


def test_tied_embeddings_lm():
    """tie_embeddings=True: the head W is GONE from the master params
    (the tie rebuilds it from the embedding in every forward), the
    model trains (gradients reach the embedding from both uses), KV-
    cached decode matches the training forward, and the zip round-trip
    preserves the tie."""
    model = GPTNano(vocab_size=16, max_len=64, seed=5,
                    tie_embeddings=True)
    net = model.init(seq_len=24)
    head = f"layer_{model.n_layers + 2}"
    assert "W" not in net.params[head]          # not a master param
    assert "b" in net.params[head]
    period = 5
    tokens = np.arange(24 + 1) % period + 1
    x = np.tile(tokens[:24], (8, 1)).astype(np.int32)
    y = np.tile(tokens[1:25], (8, 1)).astype(np.int32)
    emb0 = np.asarray(net.params["layer_0"]["W"]).copy()
    s0 = None
    for _ in range(60):
        net.fit(x, y)
        s0 = s0 if s0 is not None else net.score()
    assert net.score() < s0 * 0.25, (net.score(), s0)
    assert not np.allclose(np.asarray(net.params["layer_0"]["W"]),
                           emb0)                # embedding trained
    prompt = (np.arange(9) % period + 1)[None, :].astype(np.int32)
    out = model.generate(net, prompt, n_new=6)
    probs = np.asarray(net.output(prompt))
    assert out[0, 9] == int(np.argmax(probs[0, -1]))
    # serialization round-trip keeps the tie (no head W reappears)
    import tempfile, os
    from deeplearning4j_tpu.serialization import ModelSerializer
    with tempfile.TemporaryDirectory() as td:
        p = os.path.join(td, "tied.zip")
        ModelSerializer.write_model(net, p)
        net2 = ModelSerializer.restore_multi_layer_network(p)
        assert "W" not in net2.params[head]
        np.testing.assert_allclose(np.asarray(net2.output(prompt)),
                                   probs, rtol=1e-5, atol=1e-6)


def test_tie_weights_mln_generic():
    """Network-level tie_weights on a plain autoencoder-style MLP:
    decoder W = encoder W^T, gradients flow to the single master."""
    import jax
    from deeplearning4j_tpu.nn import (MultiLayerNetwork,
                                       NeuralNetConfiguration)
    from deeplearning4j_tpu.nn.config import InputType
    from deeplearning4j_tpu.nn.layers import DenseLayer, OutputLayer
    from deeplearning4j_tpu.nn import updaters as upd
    conf = (NeuralNetConfiguration.builder().seed(3)
            .updater(upd.Adam(learning_rate=0.01)).list()
            .layer(DenseLayer(n_out=6, activation="tanh"))
            .layer(OutputLayer(n_out=10, activation="identity",
                               loss="mse"))
            .tie_weights(1, "W", 0, "W", transpose=True)
            .set_input_type(InputType.feed_forward(10)).build())
    net = MultiLayerNetwork(conf).init()
    assert "W" not in net.params["layer_1"]
    rng = np.random.default_rng(0)
    x = rng.standard_normal((32, 10)).astype(np.float32)
    net.fit(x, x)
    s0 = net.score()
    for _ in range(40):
        net.fit(x, x)
    assert net.score() < s0 * 0.7
    # conf JSON round-trip carries the tie
    from deeplearning4j_tpu.nn.config import MultiLayerConfiguration
    rt = MultiLayerConfiguration.from_json(conf.to_json())
    assert rt.tied_weights == [[1, "W", 0, "W", True]]


def test_tie_weights_shape_mismatch_raises():
    from deeplearning4j_tpu.nn import (MultiLayerNetwork,
                                       NeuralNetConfiguration)
    from deeplearning4j_tpu.nn.config import InputType
    from deeplearning4j_tpu.nn.layers import DenseLayer, OutputLayer
    conf = (NeuralNetConfiguration.builder().list()
            .layer(DenseLayer(n_out=6))
            .layer(OutputLayer(n_out=9, loss="mse"))   # 9 != 10
            .tie_weights(1, "W", 0, "W", transpose=True)
            .set_input_type(InputType.feed_forward(10)).build())
    with pytest.raises(ValueError, match="tie_weights"):
        MultiLayerNetwork(conf).init()


def test_tied_weights_direct_param_apis():
    """feed_forward / activate_selected_layers read self.params
    directly — they must see materialised tied weights, not KeyError
    (round-4 review finding)."""
    from deeplearning4j_tpu.nn import (MultiLayerNetwork,
                                       NeuralNetConfiguration)
    from deeplearning4j_tpu.nn.config import InputType
    from deeplearning4j_tpu.nn.layers import DenseLayer, OutputLayer
    conf = (NeuralNetConfiguration.builder().seed(3).list()
            .layer(DenseLayer(n_out=6, activation="tanh"))
            .layer(OutputLayer(n_out=10, activation="identity",
                               loss="mse"))
            .tie_weights(1, "W", 0, "W", transpose=True)
            .set_input_type(InputType.feed_forward(10)).build())
    net = MultiLayerNetwork(conf).init()
    x = np.random.default_rng(0).standard_normal((4, 10)) \
        .astype(np.float32)
    acts = net.feed_forward(x)
    assert len(acts) == 3
    np.testing.assert_allclose(np.asarray(acts[-1]),
                               np.asarray(net.output(x)),
                               rtol=1e-5, atol=1e-6)
    mid = net.activate_selected_layers(0, 0, x)
    np.testing.assert_allclose(np.asarray(mid), np.asarray(acts[1]),
                               rtol=1e-6, atol=1e-7)


def test_tied_weights_transfer_learning():
    """Ties reindex onto the transfer-learning tail; a tie crossing
    the frozen/unfrozen split is rejected with a clear error."""
    from deeplearning4j_tpu.nn import (MultiLayerNetwork,
                                       NeuralNetConfiguration,
                                       TransferLearningHelper)
    from deeplearning4j_tpu.nn.config import InputType
    from deeplearning4j_tpu.nn.layers import DenseLayer, OutputLayer
    from deeplearning4j_tpu.data import DataSet

    def build(tie):
        b = (NeuralNetConfiguration.builder().seed(3).list()
             .layer(DenseLayer(n_out=8, activation="tanh"))
             .layer(DenseLayer(n_out=8, activation="tanh"))
             .layer(OutputLayer(n_out=8, activation="identity",
                                loss="mse")))
        b.tie_weights(*tie)
        return MultiLayerNetwork(
            b.set_input_type(InputType.feed_forward(8)).build()).init()

    rng = np.random.default_rng(1)
    x = rng.standard_normal((16, 8)).astype(np.float32)
    y = rng.standard_normal((16, 8)).astype(np.float32)

    # tie fully inside the tail (layers 1,2 -> tail 0,1): works
    net = build((2, "W", 1, "W", True))
    h = TransferLearningHelper(net, frozen_until=0)
    tail = h.unfrozen_mln()
    assert tail.conf.tied_weights == [[1, "W", 0, "W", True]]
    h.fit_featurized(DataSet(x, y))
    assert np.isfinite(tail.score_)
    feats = h.featurize(DataSet(x, y))       # frozen prefix runs
    assert feats.features.shape == (16, 8)

    # tie crossing the split: rejected
    net2 = build((1, "W", 0, "W", True))
    with pytest.raises(ValueError, match="crosses"):
        TransferLearningHelper(net2, frozen_until=0)


def test_tied_lm_head_swap_transfer():
    """The canonical fine-tune: swap a tied LM's head via
    TransferLearning.Builder — the stale tie must be DROPPED (fresh
    untied head with its own W), not re-materialised over the new
    head (round-4 review repro: broadcast error (2,24,16) vs (7,))."""
    from deeplearning4j_tpu.nn import TransferLearning
    from deeplearning4j_tpu.nn.layers import RnnOutputLayer
    model = GPTNano(vocab_size=16, max_len=64, seed=5,
                    tie_embeddings=True)
    net = model.init(seq_len=24)
    head = f"layer_{model.n_layers + 2}"
    new = (TransferLearning.builder(net)
           .remove_output_layer()
           .add_layer(RnnOutputLayer(n_out=7, activation="softmax",
                                     loss="mcxent"))
           .build())
    assert new.conf.tied_weights == []          # stale tie dropped
    assert "W" in new.params[head]              # fresh untied head
    x = np.random.default_rng(0).integers(0, 16, (2, 24)) \
        .astype(np.int32)
    out = np.asarray(new.output(x))
    assert out.shape == (2, 24, 7)
    # keeping the head keeps the tie (and the W-less param block)
    kept = (TransferLearning.builder(net).build())
    assert kept.conf.tied_weights == net.conf.tied_weights
    assert "W" not in kept.params[head]
    assert np.asarray(kept.output(x)).shape == (2, 24, 16)


def test_int8_serving_matches_f32_greedy():
    """serve_quant="int8" (weight-only per-channel, dequant fused in
    the consuming matmul): greedy decode on a trained toy LM must
    produce the same continuation as full-precision serving, through
    both the tied and untied heads and the beam path."""
    for tied in (False, True):
        model = GPTNano(vocab_size=16, max_len=64, seed=5,
                        tie_embeddings=tied)
        net = model.init(seq_len=24)
        period = 5
        toks = np.arange(25) % period + 1
        x = np.tile(toks[:24], (8, 1)).astype(np.int32)
        y = np.tile(toks[1:25], (8, 1)).astype(np.int32)
        for _ in range(60):
            net.fit(x, y)
        prompt = (np.arange(9) % period + 1)[None, :].astype(np.int32)
        ref = model.generate(net, prompt, n_new=8)
        model_q = GPTNano(vocab_size=16, max_len=64, seed=5,
                          tie_embeddings=tied, serve_quant="int8")
        got = model_q.generate(net, prompt, n_new=8)
        np.testing.assert_array_equal(got, ref)
        beam = model_q.generate_beam(net, prompt, n_new=8, beams=2)
        np.testing.assert_array_equal(beam, ref)   # peaked dist


def test_int8_quantized_weight_roundtrip():
    from deeplearning4j_tpu.zoo.gpt import QuantizedWeight
    rng = np.random.default_rng(0)
    w = jnp.asarray(rng.standard_normal((32, 48)), jnp.float32)
    for axis in (0, 1):
        qw = QuantizedWeight.quantize(w, axis)
        assert qw.w8.dtype == jnp.int8
        deq = qw._dequant(jnp.float32)
        # per-channel max error bounded by scale/2
        err = np.abs(np.asarray(deq - w))
        smax = np.broadcast_to(np.asarray(qw.scale), w.shape)
        assert (err <= smax * 0.5 + 1e-7).all()
        # matmul protocol + transpose flips the channel axis
        x = jnp.asarray(rng.standard_normal((4, 32)), jnp.float32)
        np.testing.assert_allclose(np.asarray(x @ qw),
                                   np.asarray(x @ deq), rtol=1e-6)
        assert qw.T.axis == 1 - axis
        # row gather (embedding use): exact in the default f32
        # act_dtype — a wrong scale row would show immediately
        rows = qw[jnp.asarray([1, 3])]
        np.testing.assert_allclose(
            np.asarray(rows), np.asarray(deq[jnp.asarray([1, 3])]),
            rtol=1e-6, atol=1e-7)


def test_serve_quant_validation():
    with pytest.raises(ValueError, match="serve_quant"):
        GPTNano(serve_quant="int4")


def test_decode_params_cache_invalidation():
    """The serving prepare-cache must see BOTH params-change styles:
    fit() rebinding net.params AND in-place per-layer writes
    (TransferLearningHelper, manual loading) — round-4 review
    finding."""
    model = GPTNano(vocab_size=16, max_len=64, seed=5,
                    compute_dtype="bfloat16")
    net = model.init(seq_len=24)
    prompt = np.asarray([[1, 2, 3, 4, 5]], np.int32)
    out0 = model.generate(net, prompt, n_new=4)
    # in-place write: bias the head so token 9 always wins
    head = f"layer_{model.n_layers + 2}"
    import jax.numpy as jnp
    b = np.zeros(16, np.float32); b[9] = 1e4
    net.params[head] = dict(net.params[head], b=jnp.asarray(b))
    out1 = model.generate(net, prompt, n_new=4)
    assert (out1[0, 5:] == 9).all(), out1
    # and repeated calls against unchanged params hit the cache
    refs, prepared = model._decode_params_cache
    model.generate(net, prompt, n_new=4)
    assert model._decode_params_cache[1] is prepared


def test_head_geometry_quality_parity():
    """The round-5 flagship geometry change (6×d=128 instead of GPT-2's
    12×d=64, BASELINE.md round-5 §3) is a hardware-mapping knob, not a
    capacity change: at fixed hidden width, splitting the same
    projection matrices into fewer/wider vs more/narrower heads keeps
    the param count IDENTICAL and converges equivalently. Train the
    same tiny LM with head_dim=hidden (1 head) and head_dim=hidden/4
    (4 heads) on the same data and assert parity."""
    from deeplearning4j_tpu.zoo import CausalTransformerLM

    rng = np.random.default_rng(7)
    # learnable structure: next token = (token + 1) mod vocab with a
    # few random corruptions, so the loss floor is well below init
    vocab, b, t = 32, 8, 32
    x = rng.integers(0, vocab, (b, t)).astype(np.int32)
    y = (x + 1) % vocab

    finals, counts = [], []
    for heads in (1, 4):
        model = CausalTransformerLM(
            vocab_size=vocab, hidden=32, n_layers=2, n_heads=heads,
            max_len=t, ffn_mult=2.0, tie_embeddings=True, seed=3)
        net = model.init(seq_len=t)
        counts.append(sum(int(np.prod(p.shape))
                          for p in jax.tree.leaves(net.params)))
        step = net._make_train_step()
        params, opt, state = net.params, net.opt_state, net.state
        key = jax.random.PRNGKey(0)
        for _ in range(60):
            params, opt, state, loss = step(params, opt, state,
                                            jnp.asarray(x),
                                            jnp.asarray(y), None,
                                            None, key)
        finals.append(float(loss))

    assert counts[0] == counts[1], counts
    # both learn the structure: per-token loss well under the
    # ln(32) ≈ 3.47 init plateau (the training loss is a SUM over
    # the b·t tokens)...
    per_tok = [f / (b * t) for f in finals]
    assert all(f < 0.8 for f in per_tok), per_tok
    # ...and land in the same loss regime (measured: within 0.1% of
    # each other at 60 steps)
    lo, hi = sorted(finals)
    assert hi < lo * 1.5 + 0.1, finals


def test_int8_kv_cache_decode_matches(toy_lm):
    """cache_quant="int8" (round 5): decode with the int8 KV cache —
    codes + per-(row, head, half, position) scales, dequant factored
    out of the attention einsums so the dots read pure int8 — must
    reproduce the bf16-cache greedy output on a trained model (the
    toy LM's confident next-token structure leaves no headroom for
    quantisation flips), and compose with beam search and int8
    weights."""
    model, net, _, _ = toy_lm
    rng = np.random.default_rng(1)
    prompt = rng.integers(0, model.vocab_size, (2, 16)).astype(np.int32)
    base = model.generate(net, prompt, n_new=16)

    # FRESH instances (and the jit key now carries cache_quant, so
    # even a copied model with the attribute flipped retraces instead
    # of silently reusing the bf16-cache executable)
    qm = GPTNano(vocab_size=16, max_len=64, seed=5,
                 cache_quant="int8")
    got = qm.generate(net, prompt, n_new=16)
    np.testing.assert_array_equal(np.asarray(got), np.asarray(base))

    beam = qm.generate_beam(net, prompt, n_new=8, beams=3)
    assert beam.shape == (2, prompt.shape[1] + 8)

    qboth = GPTNano(vocab_size=16, max_len=64, seed=5,
                    cache_quant="int8", serve_quant="int8")
    both = qboth.generate(net, prompt, n_new=16)
    # int8 weights round the logits; the confident toy still matches
    assert (np.asarray(both) == np.asarray(base)).mean() > 0.9, (
        both, base)


def test_cache_quant_validation():
    from deeplearning4j_tpu.zoo import CausalTransformerLM
    with pytest.raises(ValueError):
        CausalTransformerLM(cache_quant="int4")
