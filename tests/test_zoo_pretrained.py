"""Pretrained-zoo machinery (reference ZooModel.initPretrained +
DL4JResources checksum gate): checked-in goldens restore and reproduce
their minting forward pass; corruption and absence fail loudly."""
import json
import shutil
from pathlib import Path

import numpy as np
import pytest

from deeplearning4j_tpu.zoo import (CausalTransformerLM, LeNet,
                                    SimpleCNN, TextGenerationLSTM)
from deeplearning4j_tpu.zoo.pretrained import (DL4JResources,
                                               export_pretrained,
                                               fetch_pretrained)

GOLDENS = Path(__file__).resolve().parents[1] / "resources" / \
    "pretrained"


@pytest.mark.parametrize("cls", [LeNet, SimpleCNN, TextGenerationLSTM,
                                 CausalTransformerLM])
def test_init_pretrained_matches_golden_forward(cls):
    """load-pretrained → forward == the outputs captured at minting.
    base_dir pinned to the checked-in goldens so an ambient
    DL4J_TPU_RESOURCES cannot redirect the test."""
    net = cls.init_pretrained(base_dir=GOLDENS)
    io = np.load(GOLDENS / cls.model_name() / "default_golden_io.npz")
    got = np.asarray(net.output(io["x"]))
    np.testing.assert_allclose(got, io["y"], rtol=1e-5, atol=1e-6)


def test_pretrained_available():
    assert LeNet.pretrained_available(base_dir=GOLDENS)
    assert not LeNet.pretrained_available("imagenet", base_dir=GOLDENS)


def test_checksum_gate_rejects_corruption(tmp_path):
    src = GOLDENS / "TextGenerationLSTM"
    dst = tmp_path / "TextGenerationLSTM"
    shutil.copytree(src, dst)
    art = dst / "default.zip"
    blob = bytearray(art.read_bytes())
    blob[len(blob) // 2] ^= 0xFF
    art.write_bytes(bytes(blob))
    with pytest.raises(IOError, match="checksum mismatch"):
        fetch_pretrained("TextGenerationLSTM", "default", tmp_path)


def test_missing_weights_error_names_alternatives(tmp_path):
    with pytest.raises(FileNotFoundError, match="no pretrained"):
        fetch_pretrained("LeNet", "default", tmp_path)


def test_export_then_init_pretrained_roundtrip(tmp_path):
    """Publishing side: export into a fresh repository, point the
    resolver at it, restore, compare outputs."""
    rng = np.random.default_rng(3)
    net = LeNet(num_classes=10, seed=5, input_shape=(14, 14, 1)).init()
    x = rng.normal(size=(2, 14, 14, 1)).astype(np.float32)
    want = np.asarray(net.output(x))
    export_pretrained(net, "LeNet", "mytask", tmp_path)
    manifest = json.loads(
        (tmp_path / "LeNet" / "manifest.json").read_text())
    assert manifest["mytask"]["format"] == "multilayer"
    DL4JResources.set_base_directory(str(tmp_path))
    try:
        net2 = LeNet.init_pretrained("mytask")
    finally:
        DL4JResources.set_base_directory(None)
    np.testing.assert_allclose(np.asarray(net2.output(x)), want,
                               rtol=1e-6, atol=1e-7)


def test_http_refused():
    with pytest.raises(RuntimeError, match="no network egress"):
        DL4JResources.resolve("https://dl4jdata.example/model.zip")


def test_file_url_resolves(tmp_path):
    p = DL4JResources.resolve(f"file://{tmp_path}/x.zip")
    assert p == Path(f"{tmp_path}/x.zip")
