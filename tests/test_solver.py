"""Solver + legacy full-batch optimizers (LBFGS, CG, line search).

Reference analog: BackTrackLineSearchTest / TestOptimizers
(deeplearning4j-core, org.deeplearning4j.optimize.solvers).
"""
import jax
import jax.numpy as jnp
import numpy as np
import pytest

from deeplearning4j_tpu.train.solver import (
    Solver, backtrack_line_search, StochasticGradientDescent,
    LineGradientDescent, ConjugateGradient, LBFGS)


def _rosenbrock(p):
    x, y = p["x"], p["y"]
    return jnp.sum(100.0 * (y - x ** 2) ** 2 + (1 - x) ** 2)


def _quadratic(p):
    return jnp.sum(jnp.square(p["w"] - 3.0))


class TestLineSearch:
    def test_armijo_decreases_loss(self):
        params = {"w": jnp.asarray([0.0, 0.0])}
        g = jax.grad(_quadratic)(params)
        d = jax.tree.map(lambda v: -v, g)
        a, f_new = backtrack_line_search(_quadratic, params, d)
        assert float(f_new) < float(_quadratic(params))
        assert float(a) > 0


class TestOptimizers:
    @pytest.mark.parametrize("cls,kw", [
        (StochasticGradientDescent, {"learning_rate": 0.2}),
        (LineGradientDescent, {}),
        (ConjugateGradient, {}),
        (LBFGS, {}),
    ])
    def test_quadratic_converges(self, cls, kw):
        opt = cls(max_iterations=60, **kw)
        p0 = {"w": jnp.zeros(4)}
        f0 = float(_quadratic(p0))
        out = opt.optimize(_quadratic, p0)
        assert np.allclose(np.asarray(out["w"]), 3.0, atol=1e-2), cls
        assert opt.scores_[-1] < f0

    def test_lbfgs_beats_sgd_on_rosenbrock(self):
        p0 = {"x": jnp.asarray([-1.0]), "y": jnp.asarray([1.0])}
        lb = LBFGS(max_iterations=80)
        out = lb.optimize(_rosenbrock, jax.tree.map(jnp.copy, p0))
        sgd = StochasticGradientDescent(learning_rate=1e-3,
                                        max_iterations=80)
        sgd.optimize(_rosenbrock, jax.tree.map(jnp.copy, p0))
        assert lb.scores_[-1] < sgd.scores_[-1]
        assert np.allclose(float(out["x"][0]), 1.0, atol=0.1)

    def test_cg_on_rosenbrock_decreases(self):
        p0 = {"x": jnp.asarray([-1.0]), "y": jnp.asarray([1.0])}
        f0 = float(_rosenbrock(p0))
        cg = ConjugateGradient(max_iterations=50)
        cg.optimize(_rosenbrock, p0)
        assert cg.scores_[-1] < f0 / 10


class TestSolverDriver:
    def _net_and_data(self):
        from deeplearning4j_tpu.nn import (MultiLayerNetwork,
                                           NeuralNetConfiguration)
        from deeplearning4j_tpu.nn.config import InputType
        from deeplearning4j_tpu.nn.layers import DenseLayer, OutputLayer
        from deeplearning4j_tpu.nn import updaters as upd
        from deeplearning4j_tpu.data.dataset import DataSet

        rng = np.random.RandomState(0)
        x = rng.randn(64, 6).astype(np.float32)
        y = np.eye(3, dtype=np.float32)[rng.randint(0, 3, 64)]
        conf = (NeuralNetConfiguration.builder().seed(2)
                .updater(upd.Sgd(learning_rate=0.1)).list()
                .layer(DenseLayer(n_out=12, activation="tanh"))
                .layer(OutputLayer(n_out=3, activation="softmax",
                                   loss="mcxent"))
                .set_input_type(InputType.feed_forward(6)).build())
        return MultiLayerNetwork(conf).init(), DataSet(x, y)

    @pytest.mark.parametrize("algo", ["LBFGS", "CONJUGATE_GRADIENT",
                                      "LINE_GRADIENT_DESCENT"])
    def test_solver_improves_network_score(self, algo):
        net, ds = self._net_and_data()
        s0 = net.score(ds)
        solver = (Solver.builder().model(net).optimization_algo(algo)
                  .max_iterations(25).build())
        final = solver.optimize(ds)
        assert final < s0
        assert net.score(ds) < s0          # params actually updated

    def test_unknown_algo_raises(self):
        net, _ = self._net_and_data()
        with pytest.raises(ValueError):
            Solver(net, algo="NEWTON")
