"""Shared scaffolding for TRUE multi-process (jax.distributed) tests:
launch N worker processes with a coordinator address, collect their
output, and guarantee cleanup — a crashed or hung worker never leaks
past the test (its peer would otherwise block in a collective forever
and keep the coordinator port bound)."""
import os
import subprocess
import sys


def run_two_process_workers(script_path, port, extra_env=None,
                            timeout=300):
    """Launch 2 workers of ``script_path`` (each sees COORD/PROC_ID and
    2 CPU devices), wait for both, and return their outputs. Kills
    both processes on any failure path."""
    procs = []
    try:
        for pid in range(2):
            env = dict(os.environ,
                       COORD=f"127.0.0.1:{port}", NPROC="2",
                       PROC_ID=str(pid),
                       XLA_FLAGS="--xla_force_host_platform_device_count=2",
                       JAX_PLATFORMS="cpu")
            env.update(extra_env or {})      # overrides win
            procs.append(subprocess.Popen(
                [sys.executable, str(script_path)], env=env,
                stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
                text=True))
        outs = []
        for p in procs:
            out, _ = p.communicate(timeout=timeout)
            outs.append(out)
        return procs, outs
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
                p.communicate(timeout=30)


def assert_all_done(procs, outs):
    for pid, (p, out) in enumerate(zip(procs, outs)):
        assert p.returncode == 0, f"proc {pid} failed:\n{out[-3000:]}"
        assert f"proc {pid} DONE" in out
