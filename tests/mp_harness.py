"""Shared scaffolding for TRUE multi-process (jax.distributed) tests:
launch N worker processes with a coordinator address, collect their
output, and guarantee cleanup — a crashed or hung worker never leaks
past the test (its peer would otherwise block in a collective forever
and keep the coordinator port bound).

``kill_after`` staggers host death deterministically from the parent:
``{proc_id: seconds}`` SIGKILLs the given worker that long after
launch — the elastic drills (and any future membership test) get a
real kill -9 mid-run without hand-rolling Popen scaffolding per test.
"""
import os
import subprocess
import sys
import threading


def run_workers(script_path, port, n=2, extra_env=None, timeout=300,
                kill_after=None, devices_per_proc=2,
                per_proc_env=None):
    """Launch ``n`` workers of ``script_path`` (each sees
    COORD/NPROC/PROC_ID and ``devices_per_proc`` forced CPU devices),
    wait for all, and return ``(procs, outs)``. Kills every process on
    any failure path.

    ``kill_after={proc_id: seconds}``: a timer per entry SIGKILLs that
    worker after the delay — the deterministic host-death hook for
    elastic/membership drills. A killed worker's output is whatever it
    flushed before dying; its returncode is ``-SIGKILL``.

    ``per_proc_env={proc_id: {...}}``: per-worker overrides on top of
    ``extra_env`` (e.g. a fault plan armed on ONE host of a fleet).
    """
    procs = []
    timers = []
    try:
        for pid in range(n):
            env = dict(os.environ,
                       COORD=f"127.0.0.1:{port}", NPROC=str(n),
                       PROC_ID=str(pid),
                       XLA_FLAGS="--xla_force_host_platform_device_"
                                 f"count={devices_per_proc}",
                       JAX_PLATFORMS="cpu")
            env.update(extra_env or {})      # overrides win
            env.update((per_proc_env or {}).get(pid, {}))
            procs.append(subprocess.Popen(
                [sys.executable, str(script_path)], env=env,
                stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
                text=True))
        for pid, delay in (kill_after or {}).items():
            t = threading.Timer(float(delay), procs[int(pid)].kill)
            t.daemon = True
            t.start()
            timers.append(t)
        outs = []
        for p in procs:
            out, _ = p.communicate(timeout=timeout)
            outs.append(out)
        return procs, outs
    finally:
        for t in timers:
            t.cancel()
        for p in procs:
            if p.poll() is None:
                p.kill()
                p.communicate(timeout=30)


def run_two_process_workers(script_path, port, extra_env=None,
                            timeout=300):
    """Back-compat wrapper: the original 2-worker launcher."""
    return run_workers(script_path, port, n=2, extra_env=extra_env,
                       timeout=timeout)


def assert_all_done(procs, outs):
    for pid, (p, out) in enumerate(zip(procs, outs)):
        assert p.returncode == 0, f"proc {pid} failed:\n{out[-3000:]}"
        assert f"proc {pid} DONE" in out
