"""Fleet observability plane (ARCHITECTURE.md §14, obs/fleet.py):
per-host telemetry snapshots on the elastic file plane, fleet-level
exposition aggregation with host=/mesh_epoch= labels, collective-skew
straggler attribution, and the crash flight recorder — plus the
heartbeat-plane unification (lease ages and worker beats share ONE
staleness table on /healthz) and the off-path zero-publish fence.
"""
import json
import os
import sys
import time
from pathlib import Path

import numpy as np
import pytest

from deeplearning4j_tpu import obs
from deeplearning4j_tpu.data.dataset import DataSet
from deeplearning4j_tpu.data.iterators import ListDataSetIterator
from deeplearning4j_tpu.nn import (MultiLayerNetwork,
                                   NeuralNetConfiguration)
from deeplearning4j_tpu.nn.config import InputType
from deeplearning4j_tpu.nn.layers import DenseLayer, OutputLayer
from deeplearning4j_tpu.nn import updaters as upd
from deeplearning4j_tpu.obs import fleet, health, metrics
from deeplearning4j_tpu.resilience import elastic, faults

REPO = Path(__file__).resolve().parents[1]


@pytest.fixture(autouse=True)
def _clean_planes():
    faults.reset()
    yield
    faults.reset()


def _mlp(seed=11, n_in=8, n_out=3, hidden=16):
    conf = (NeuralNetConfiguration.builder().seed(seed)
            .updater(upd.Adam(learning_rate=5e-3)).list()
            .layer(DenseLayer(n_out=hidden, activation="tanh"))
            .layer(OutputLayer(n_out=n_out, activation="softmax",
                               loss="mcxent"))
            .set_input_type(InputType.feed_forward(n_in)).build())
    return MultiLayerNetwork(conf).init()


def _iter(n=32, batch=8, seed=5, n_in=8, n_out=3):
    rng = np.random.RandomState(seed)
    x = rng.randn(n, n_in).astype(np.float32)
    y = np.eye(n_out, dtype=np.float32)[rng.randint(0, n_out, n)]
    return ListDataSetIterator(DataSet(x, y), batch_size=batch)


def _clockpair(start=1000.0):
    t = [start]
    return t, (lambda: t[0])


# =========================================================================
# telemetry publishing: atomic, versioned, cadence-gated
# =========================================================================

def test_snapshot_publish_versioned_and_parseable(tmp_path):
    ft = fleet.FleetTelemetry(tmp_path, "h0", every_s=0.0)
    base = time.time()
    ft.note_enter(3, t=base)
    ft.record_step(3, mesh_epoch=2, t_exit=base + 0.01, loss=0.75)
    snap = json.loads((tmp_path / "telemetry" / "h0.json").read_text())
    assert snap["version"] == fleet.SNAPSHOT_VERSION
    assert snap["host"] == "h0" and snap["pid"] == os.getpid()
    assert snap["step"] == 3 and snap["mesh_epoch"] == 2
    (b,) = snap["barriers"]
    assert b[0] == 3 and b[2] - b[1] == pytest.approx(0.01, abs=1e-6)
    # the embedded exposition is valid Prometheus text
    fams = metrics.parse_exposition(snap["exposition"])
    assert any(k[0].startswith("dl4j_tpu_") for k in fams)
    # round trip through the reader (version-compatible)
    assert "h0" in fleet.read_snapshots(tmp_path)


def test_publish_cadence_is_gated_by_clock(tmp_path):
    t, clock = _clockpair()
    ft = fleet.FleetTelemetry(tmp_path, "h0", every_s=10.0,
                              clock=clock)
    p0 = fleet.publishes()
    ft.record_step(0)               # first record always publishes
    for i in range(1, 6):
        t[0] += 1.0
        ft.record_step(i)           # inside the cadence window
    assert fleet.publishes() == p0 + 1
    t[0] += 10.0
    ft.record_step(6)               # window elapsed
    assert fleet.publishes() == p0 + 2
    # step/barriers in the published file reflect the LAST publish
    snap = json.loads((tmp_path / "telemetry" / "h0.json").read_text())
    assert snap["step"] == 6


def test_incompatible_snapshot_version_skipped(tmp_path):
    ft = fleet.FleetTelemetry(tmp_path, "ok", every_s=0.0)
    ft.record_step(1)
    bad = tmp_path / "telemetry" / "zombie.json"
    bad.write_text(json.dumps({"version": 999, "host": "zombie",
                               "step": 9}))
    (tmp_path / "telemetry" / "torn.json").write_text("{not json")
    snaps = fleet.read_snapshots(tmp_path)
    assert set(snaps) == {"ok"}     # incompatible + torn both skipped


# =========================================================================
# aggregation: fleet exposition with host=/mesh_epoch= labels
# =========================================================================

def test_aggregate_exposition_carries_host_and_epoch_labels(tmp_path):
    base = time.time()
    for i, host in enumerate(("h0", "h1")):
        ft = fleet.FleetTelemetry(tmp_path, host, every_s=0.0)
        ft.record_step(5, mesh_epoch=3, t_enter=base + 0.01 * i,
                       t_exit=base + 0.02, loss=0.5)
    view = fleet.aggregate(tmp_path)
    assert set(view.table()) == {"h0", "h1"}
    text = view.exposition()
    fams = metrics.parse_exposition(text)      # raises on malformed
    hosts = {dict(labels).get("host") for (_n, labels) in fams}
    assert {"h0", "h1"} <= hosts
    # every MERGED per-host sample carries the mesh_epoch label (the
    # aggregator's own families — skew, ages — are per-host only)
    assert all(dict(labels).get("mesh_epoch") == "3"
               for (name, labels) in fams
               if dict(labels).get("host") in ("h0", "h1")
               and name not in fleet.AGGREGATE_FAMILIES)
    assert any(name == "dl4j_tpu_fleet_snapshots_published_total"
               and dict(labels).get("mesh_epoch") == "3"
               for (name, labels) in fams)
    assert fams[("dl4j_tpu_fleet_hosts", ())] == 2.0
    # TYPE lines come from the FAMILIES registry, including the
    # aggregator-computed families
    assert "# TYPE dl4j_tpu_collective_skew_seconds gauge" in text
    assert "# TYPE dl4j_tpu_fleet_snapshots_published_total counter" \
        in text


def test_skew_report_names_last_in_host(tmp_path):
    base = time.time()
    for host, late in (("h0", 0.0), ("h1", 0.04), ("h2", 0.002)):
        ft = fleet.FleetTelemetry(tmp_path, host, every_s=0.0)
        for step in (4, 5):
            ft.record_step(step, t_enter=base + step + late,
                           t_exit=base + step + late + 0.01)
    rep = fleet.aggregate(tmp_path).skew_report()
    assert rep["step"] == 5 and rep["missing"] == []
    assert rep["straggler"] == "h1"
    assert rep["skew_s"]["h1"] == pytest.approx(0.04, abs=1e-5)
    assert rep["skew_s"]["h0"] == 0.0
    # the per-step series names the last-in host step by step
    assert [s[0] for s in rep["series"]] == [4, 5]
    assert all(s[2] == "h1" for s in rep["series"])


def test_skew_names_lease_dead_host_as_final_step_straggler(tmp_path):
    """A host whose LEASE evidence says it is gone (lease older than
    its own window) is the straggler — entry times alone cannot tell
    the corpse from peers wedged waiting on it."""
    t, clock = _clockpair()
    co = {h: elastic.MembershipCoordinator(tmp_path, h, lease_secs=5.0,
                                           clock=clock)
          for h in ("h0", "h1", "h2")}
    fts = {h: fleet.FleetTelemetry(tmp_path, h, every_s=0.0,
                                   clock=clock)
           for h in ("h0", "h1", "h2")}
    for h in fts:
        co[h].renew()
        fts[h].record_step(7, t_enter=t[0], t_exit=t[0])
    # h2 dies; its lease outlives its window while the survivors
    # renew and enter step 8
    t[0] += 6.0
    for h in ("h0", "h1"):
        co[h].renew()
        fts[h].record_step(8, t_enter=t[0], t_exit=t[0])
    rep = fleet.aggregate(tmp_path, now=t[0]).skew_report()
    assert rep["step"] == 8
    assert rep["dead"] == ["h2"]
    assert rep["missing"] == ["h2"]
    assert rep["straggler"] == "h2"
    assert rep["skew_s"]["h2"] >= 0.0


def test_skew_no_phantom_straggler_on_staggered_cadence(tmp_path):
    """The healthy-fleet case: every lease live but snapshots lag one
    another by up to the publish cadence (step time ≪ cadence). The
    host with the staler snapshot must NOT be called missing or
    straggler — attribution anchors on the newest COMMON step."""
    t, clock = _clockpair()
    for h in ("h0", "h1"):
        co = elastic.MembershipCoordinator(tmp_path, h, lease_secs=30.0,
                                           clock=clock)
        co.renew()
    ft0 = fleet.FleetTelemetry(tmp_path, "h0", every_s=0.0, clock=clock)
    ft1 = fleet.FleetTelemetry(tmp_path, "h1", every_s=0.0, clock=clock)
    # h1's snapshot stops at step 10; h0's is ~1s fresher (step 13),
    # entering each step 1ms after h1 — the real skew is 1ms
    for s in range(8, 11):
        ft1.record_step(s, t_enter=1000.0 + s * 0.05,
                        t_exit=1000.0 + s * 0.05 + 0.01)
    for s in range(8, 14):
        ft0.record_step(s, t_enter=1000.0 + s * 0.05 + 0.001,
                        t_exit=1000.0 + s * 0.05 + 0.011)
    t[0] += 1.0
    rep = fleet.aggregate(tmp_path, now=t[0]).skew_report()
    assert rep["dead"] == [] and rep["missing"] == []
    assert rep["step"] == 10            # newest step BOTH published
    assert rep["straggler"] == "h0"     # the genuine 1ms last-in
    assert rep["max_skew_s"] == pytest.approx(0.001, abs=1e-5)


# =========================================================================
# crash flight recorder
# =========================================================================

def test_flight_recorder_ring_bounded_and_bundle_versioned(tmp_path):
    from deeplearning4j_tpu.obs.numerics import NonFiniteError
    ft = fleet.FleetTelemetry(tmp_path, "h0", every_s=1e9, ring=8)
    for i in range(50):
        ft.record_step(i, mesh_epoch=1, loss=1.0 / (i + 1))
    ft.event("mesh_epoch_commit", epoch=2)
    d0 = fleet.dumps()
    path = ft.dump(NonFiniteError(layer="dense_1", kind="gradients",
                                  iteration=49))
    assert fleet.dumps() == d0 + 1
    bundle = json.loads(Path(path).read_text())
    assert bundle["version"] == fleet.BUNDLE_VERSION
    assert bundle["host"] == "h0" and bundle["step"] == 49
    assert bundle["cause"] == "NonFiniteError"
    assert bundle["origin"] == {"layer": "dense_1",
                                "kind": "gradients", "iteration": 49}
    # bounded black box: ring + the epoch event, last-N only
    assert len(bundle["ring"]) == 8
    assert bundle["ring"][-1]["event"] == "mesh_epoch_commit"
    assert bundle["ring"][-2]["step"] == 49
    # the bundle carries the obs report tail and the fleet skew view
    assert "metrics" in bundle["report"]
    assert bundle["fleet"]["skew"]["step"] == 49


def test_leader_eviction_bundle_snapshots_dead_host(tmp_path):
    t, clock = _clockpair()
    dead = fleet.FleetTelemetry(tmp_path, "h9", every_s=0.0,
                                clock=clock)
    dead.record_step(12, mesh_epoch=1, loss=0.3)
    path = fleet.record_eviction(tmp_path, "h9", by="h0", now=t[0] + 6)
    bundle = json.loads(Path(path).read_text())
    assert bundle["cause"] == "Evicted" and bundle["host"] == "h9"
    assert bundle["recorded_by"] == "h0"
    assert bundle["final_telemetry"]["step"] == 12
    # the adjudicated skew view rides the eviction bundle
    assert bundle["fleet"]["skew"]["step"] == 12
    # the corpse's live snapshot retired from the fleet view, its
    # eviction visible to the watcher
    assert "h9" not in fleet.read_snapshots(tmp_path)
    view = fleet.aggregate(tmp_path)
    assert view.evicted() == ["h9"]
    # a host that never published: no-op, no bundle
    assert fleet.record_eviction(tmp_path, "ghost", by="h0") is None


def test_graceful_departure_retires_snapshot_not_straggler(tmp_path):
    """A host that LEAVES cleanly (SIGTERM path) retires its own
    snapshot into a departed bundle — without this, its lease-less
    stale snapshot would read as a corpse and be named straggler
    forever, masking any real one."""
    t, clock = _clockpair()
    co = {h: elastic.MembershipCoordinator(tmp_path, h, lease_secs=5.0,
                                           clock=clock)
          for h in ("h0", "h1", "h2")}
    for h, late in (("h0", 0.0), ("h1", 0.01), ("h2", 0.0)):
        co[h].renew()
        ft = fleet.FleetTelemetry(tmp_path, h, every_s=0.0,
                                  clock=clock)
        ft.record_step(3, t_enter=t[0] + late, t_exit=t[0] + late)
    co["h2"].leave()
    assert "h2" not in fleet.read_snapshots(tmp_path)
    bundles = list((tmp_path / "postmortem").glob("h2.departed.*.json"))
    assert len(bundles) == 1
    bundle = json.loads(bundles[0].read_text())
    assert bundle["cause"] == "Departed" and bundle["host"] == "h2"
    assert bundle["final_telemetry"]["step"] == 3
    # an hour later the fleet view names the REAL straggler, not the
    # long-departed host
    t[0] += 3600.0
    for h in ("h0", "h1"):
        co[h].renew()
    rep = fleet.aggregate(tmp_path, now=t[0]).skew_report()
    assert rep["dead"] == []
    assert rep["straggler"] == "h1"


def test_evicted_dump_does_not_resurrect_retired_snapshot(tmp_path):
    """An evicted host's own dump (republish=False) must not rewrite
    the telemetry file the leader's eviction bundle just retired —
    that lease-less snapshot would read as a corpse forever."""
    ft = fleet.FleetTelemetry(tmp_path, "hX", every_s=0.0)
    ft.record_step(5, mesh_epoch=1)
    fleet.record_eviction(tmp_path, "hX", by="h0")
    assert "hX" not in fleet.read_snapshots(tmp_path)
    path = ft.dump(RuntimeError("evicted straggler"), republish=False)
    assert path and Path(path).is_file()          # the bundle exists
    assert "hX" not in fleet.read_snapshots(tmp_path)   # still gone


def test_skew_disjoint_windows_name_no_straggler(tmp_path):
    """Steps much faster than the cadence: the hosts' barrier windows
    don't overlap, nobody is dead — a lone entrant at the newest step
    must NOT be named straggler (that would flag the FASTEST host)."""
    ft0 = fleet.FleetTelemetry(tmp_path, "h0", every_s=0.0)
    ft1 = fleet.FleetTelemetry(tmp_path, "h1", every_s=0.0)
    for s in range(100, 116):
        ft0.record_step(s, t_enter=1000.0 + s, t_exit=1000.0 + s)
    for s in range(40, 56):
        ft1.record_step(s, t_enter=1000.0 + s, t_exit=1000.0 + s)
    rep = fleet.aggregate(tmp_path, now=1200.0).skew_report()
    assert rep["dead"] == [] and rep["missing"] == []
    assert rep["straggler"] is None
    # and the exposition still parses with no straggler flagged
    text = fleet.aggregate(tmp_path, now=1200.0).exposition()
    fams = metrics.parse_exposition(text)
    flagged = [k for k, v in fams.items()
               if k[0] == "dl4j_tpu_collective_straggler" and v == 1.0]
    assert flagged == []


def test_dump_fleet_view_stays_in_injected_clock_domain(tmp_path):
    """dump() aggregates with the publisher's own clock — mixing a
    fake clock's stamps with wall time would make every age
    astronomically stale and every host read dead."""
    t, clock = _clockpair()
    co = elastic.MembershipCoordinator(tmp_path, "h0", lease_secs=5.0,
                                       clock=clock)
    co.renew()
    ft = fleet.FleetTelemetry(tmp_path, "h0", every_s=0.0, clock=clock)
    ft.record_step(2, t_enter=t[0], t_exit=t[0])
    bundle = json.loads(Path(ft.dump("probe")).read_text())
    assert bundle["fleet"]["skew"]["dead"] == []
    assert bundle["fleet"]["hosts"]["h0"]["age_s"] < 10.0


def test_coordinator_eviction_writes_leader_bundle(tmp_path):
    """The wired path: MembershipCoordinator.evict_expired — the
    winner of the lease race snapshots the dead host's telemetry."""
    t, clock = _clockpair()
    a = elastic.MembershipCoordinator(tmp_path, "a", lease_secs=5.0,
                                      clock=clock)
    b = elastic.MembershipCoordinator(tmp_path, "b", lease_secs=5.0,
                                      clock=clock)
    a.renew()
    b.renew()
    ftb = fleet.FleetTelemetry(tmp_path, "b", every_s=0.0, clock=clock)
    ftb.record_step(4, mesh_epoch=1)
    t[0] += 6.0                     # b's lease expires
    a.renew()
    assert a.evict_expired() == ["b"]
    bundles = list((tmp_path / "postmortem").glob("b.evicted.*.json"))
    assert len(bundles) == 1
    bundle = json.loads(bundles[0].read_text())
    assert bundle["host"] == "b" and bundle["recorded_by"] == "a"
    assert bundle["final_telemetry"]["step"] == 4


# =========================================================================
# elastic hooks: barrier stamps through ElasticContext + trainer dump
# =========================================================================

def test_elastic_context_stamps_barriers_and_publishes(tmp_path):
    t, clock = _clockpair()
    co = elastic.MembershipCoordinator(tmp_path, "a", lease_secs=5.0,
                                       clock=clock, port_base=31000)
    co.renew()
    ft = fleet.FleetTelemetry(tmp_path, "a", every_s=0.0, clock=clock)
    ctx = elastic.ElasticContext(co, {"epoch": 0, "members": ["a"],
                                      "port": 1}, fleet=ft)
    ctx.pre_step(0)                 # barrier entry at t=1000
    t[0] += 0.5
    ctx.post_step(0, 0.25)          # barrier exit at t=1000.5
    snap = json.loads((tmp_path / "telemetry" / "a.json").read_text())
    (b,) = snap["barriers"]
    assert b == [0, 1000.0, 1000.5]
    assert snap["mesh_epoch"] == 0
    # a context with NO fleet plane: both hooks are one branch
    ctx2 = elastic.ElasticContext(co, {"epoch": 0, "members": ["a"],
                                       "port": 1})
    p0 = fleet.publishes()
    ctx2.pre_step(1)
    ctx2.post_step(1, 0.1)
    assert fleet.publishes() == p0


def test_elastic_trainer_dumps_flight_bundle_on_nonfinite(tmp_path):
    """A deterministic failure (the numerics sentinel) surfaces AND
    leaves the postmortem bundle behind — the black box survives the
    failure it explains."""
    from deeplearning4j_tpu.obs.numerics import NonFiniteError
    co = elastic.MembershipCoordinator(tmp_path / "el", "solo",
                                       lease_secs=5.0,
                                       port_base=31800)
    tr = elastic.ElasticTrainer(
        _mlp, tmp_path / "ck", coordinator=co, sharded_update=False,
        save_every=0, fleet_telemetry=True)
    with faults.active("worker_step:error=NonFiniteError:nth=2"):
        with pytest.raises(NonFiniteError):
            tr.fit(_iter(), epochs=1, expected=1)
    co.stop_auto_renew()
    bundles = list((tmp_path / "el" / "postmortem").glob("*.json"))
    assert len(bundles) == 1
    bundle = json.loads(bundles[0].read_text())
    assert bundle["cause"] == "NonFiniteError"
    assert bundle["host"] == "solo"
    # the ring captured the step that preceded the failure
    steps = [r["step"] for r in bundle["ring"] if "step" in r]
    assert steps and steps[-1] >= 0


# =========================================================================
# heartbeat-plane unification: one staleness table
# =========================================================================

def test_healthz_names_stale_hosts_and_workers_from_one_table():
    health.reset()
    try:
        health.heartbeat("w-live")
        health.heartbeat("w-stuck", t=obs.now() - 100)   # > default 30
        # a host 10s silent under a 5s lease: stale by ITS window even
        # though the generic worker default (30s) would say ok — the
        # unified table renders the coordinator's verdict
        health.observe_age("host:hX", 10.0, stale_after=5.0)
        chk = health.check()
        assert chk["host:hX"]["stale"] is True
        assert chk["w-live"]["stale"] is False
        body = metrics.MetricsServer(port=0).healthz()
        assert body["status"] == "stale_workers"
        assert body["stale_workers"] == ["host:hX", "w-stuck"]
        assert body["stale_hosts"] == ["hX"]
    finally:
        health.reset()


def test_observe_age_threshold_cleared_on_retire():
    health.reset()
    try:
        health.observe_age("host:gone", 1.0, stale_after=5.0)
        health.retire("host:gone")
        assert health.check() == {}
        # re-registering without an override falls back to the default
        health.heartbeat("host:gone", t=obs.now() - 10.0)
        assert health.check(stale_after=30.0)["host:gone"][
            "stale"] is False
    finally:
        health.reset()


# =========================================================================
# the off path: zero publishes, zero dumps, one branch
# =========================================================================

def test_off_path_zero_publish_counter_fence():
    """Training with NO fleet plane installed must never touch the
    publisher or the recorder — the PR 2/4 off-path contract."""
    from deeplearning4j_tpu.parallel.wrapper import ParallelWrapper
    p0, d0 = fleet.publishes(), fleet.dumps()
    fam0 = fleet.FLEET_PUBLISHES._children[()].get()
    net = _mlp()
    ParallelWrapper(net, workers=2, prefetch_buffer=0).fit(
        _iter(n=16, batch=8), epochs=1)
    net2 = _mlp()
    net2.fit(_iter(n=16, batch=8), epochs=1)
    assert fleet.publishes() == p0
    assert fleet.dumps() == d0
    assert fleet.FLEET_PUBLISHES._children[()].get() == fam0


def test_measure_publish_overhead_scrubs_probe_counters():
    p0 = fleet.publishes()
    fam0 = fleet.FLEET_PUBLISHES._children[()].get()
    rec = fleet.measure_publish_overhead(step_seconds=0.05, iters=200)
    assert rec["publishes"] >= 1            # the probe did publish...
    assert fleet.publishes() == p0          # ...and scrubbed itself
    assert fleet.FLEET_PUBLISHES._children[()].get() == fam0
    assert rec["off_path_cost_us"] < rec["on_path_record_us"] + 1e3
    assert rec["overhead_pct_of_step"] is not None


# =========================================================================
# tpu_watch --fleet-dir: table + skew sparkline + alarms
# =========================================================================

def test_tpu_watch_fleet_dir_renders_view(tmp_path, monkeypatch):
    from deeplearning4j_tpu.obs import numerics
    sys.path.insert(0, str(REPO / "tools"))
    import tpu_watch
    monkeypatch.setattr(tpu_watch, "LOG", tmp_path / "log.jsonl")
    eldir = tmp_path / "el"
    base = time.time()
    nf = numerics.NONFINITE.labels(layer="dense_0", kind="gradients")
    nf.inc()
    try:
        for host, late in (("h0", 0.0), ("h1", 0.03)):
            ft = fleet.FleetTelemetry(eldir, host, every_s=0.0)
            ft.record_step(9, mesh_epoch=2, t_enter=base + late,
                           t_exit=base + late + 0.01, loss=0.4)
        dead = fleet.FleetTelemetry(eldir, "h2", every_s=0.0)
        dead.record_step(7, mesh_epoch=1)
        fleet.record_eviction(eldir, "h2", by="h0")
        tpu_watch._scrape_telemetry(None, None, None,
                                    fleet_dir=str(eldir))
    finally:
        # scrub the synthetic non-finite sample from the live registry
        with numerics.NONFINITE._lock:
            numerics.NONFINITE._children.pop(
                ("dense_0", "gradients"), None)
    recs = [json.loads(ln) for ln in
            (tmp_path / "log.jsonl").read_text().splitlines()]
    (rec,) = [r for r in recs if r["event"] == "fleet"]
    assert set(rec["hosts"]) == {"h0", "h1"}
    assert rec["hosts"]["h0"]["step"] == 9
    assert rec["hosts"]["h0"]["mesh_epoch"] == 2
    assert rec["skew"]["straggler"] == "h1"
    assert rec["skew"]["max_skew_s"] == pytest.approx(0.03, abs=1e-4)
    assert rec["skew"]["sparkline"]
    assert rec["skew"]["series"][-1][2] == "h1"   # last-in, by step
    assert rec["alarms"]["EVICTED"] == ["h2"]
    assert any("dense_0/gradients" in k
               for k in rec["alarms"]["NONFINITE"])


# =========================================================================
# FAMILIES registry sanity (the in-process complement to lint rule 6)
# =========================================================================

def test_every_live_family_is_declared_in_families_table():
    reg_names = set(metrics.REGISTRY._metrics)
    for name, kind, _doc, _samples in metrics.REGISTRY._collected():
        reg_names.add(name)
    undeclared = {n for n in reg_names if n.startswith("dl4j_tpu_")} \
        - set(metrics.FAMILIES)
    assert not undeclared, undeclared


# =========================================================================
# the 3-host drill: publish → aggregate → kill → postmortem
# =========================================================================

FLEET_WORKER = r"""
import json, os, signal, sys, time
sys.path.insert(0, __REPO__)
from deeplearning4j_tpu.obs import fleet, metrics
from deeplearning4j_tpu.resilience import elastic

pid = os.environ["PROC_ID"]
host = "h" + pid
d = os.environ["ELASTIC_DIR"]
lease = float(os.environ["LEASE_S"])
STEPS = int(os.environ["STEPS"])
KILL_AT = int(os.environ["KILL_AT"])
victim = os.environ.get("KILL_HOST", "") == pid

co = elastic.MembershipCoordinator(d, host, lease_secs=lease,
                                   port_base=31900)
co.renew()
ft = fleet.FleetTelemetry(d, host, every_s=0.0)
for i in range(STEPS):
    t0 = time.time()
    metrics.STEPS.labels(entry="fleet_drill").inc()
    time.sleep(0.02)
    ft.record_step(i, mesh_epoch=1, t_enter=t0, loss=1.0 / (i + 1))
    co.maybe_renew()
    if pid == "0" and i == KILL_AT // 2:
        # all three hosts live: the aggregate view must carry every
        # host's samples and parse as valid exposition
        deadline = time.time() + 20
        while len(fleet.read_snapshots(d)) < 3 and \
                time.time() < deadline:
            time.sleep(0.05)
        view = fleet.aggregate(d)
        fams = metrics.parse_exposition(view.exposition())
        hosts = sorted({dict(l).get("host") for _n, l in fams
                        if dict(l).get("host")})
        print("AGG hosts=%d names=%s" % (len(view.table()),
                                         ",".join(hosts)), flush=True)
    if victim and i == KILL_AT:
        os.kill(os.getpid(), signal.SIGKILL)

# survivors: let the victim's lease expire (renewing our own), name
# the straggler from the aggregate, then evict — the winner of the
# lease race snapshots the corpse's final telemetry into the bundle.
# h1 waits for h0's straggler verdict before evicting, so the corpse's
# snapshot is still live when the skew report ranks it
marker = os.path.join(d, "straggler.done")
for _ in range(int(lease / 0.2) + 4):
    co.renew()
    time.sleep(0.2)
if pid == "0":
    rep = fleet.aggregate(d).skew_report()
    print("STRAGGLER=%s missing=%s" % (rep["straggler"],
                                       ",".join(rep["missing"])),
          flush=True)
    with open(marker, "w") as f:
        f.write("done")
else:
    deadline = time.time() + 30
    while not os.path.exists(marker) and time.time() < deadline:
        co.renew()
        time.sleep(0.1)
deadline = time.time() + 30
bundle = None
while time.time() < deadline:
    co.renew()
    co.evict_expired()
    found = list((__import__("pathlib").Path(d) / "postmortem")
                 .glob("h*.evicted.*.json")) \
        if os.path.isdir(os.path.join(d, "postmortem")) else []
    if found:
        bundle = found[0]
        break
    time.sleep(0.2)
print("proc %s DONE bundle=%s" % (pid, bundle), flush=True)
"""


@pytest.mark.skipif(os.environ.get("DL4J_TPU_SKIP_MP") == "1",
                    reason="multi-process test disabled")
def test_three_hosts_publish_aggregate_and_postmortem(tmp_path):
    """ISSUE 12 satellite: 3 hosts publish, the aggregate exposition
    carries host= labels and parses; SIGKILL one host → the skew view
    names it the straggler, and the surviving leader's postmortem
    bundle exists, parses, and names the dead host and its last
    step."""
    sys.path.insert(0, str(REPO / "tests"))
    from mp_harness import run_workers

    script = tmp_path / "fleet_worker.py"
    script.write_text(FLEET_WORKER.replace("__REPO__",
                                           repr(str(REPO))))
    eldir = tmp_path / "elastic"
    kill_at = 12
    env = {"ELASTIC_DIR": str(eldir), "LEASE_S": "1.5",
           "STEPS": "24", "KILL_AT": str(kill_at), "KILL_HOST": "2"}
    procs, outs = run_workers(script, port=29990, n=3, timeout=180,
                              kill_after={2: 60.0}, extra_env=env)
    assert procs[2].returncode == -9, outs[2][-2000:]
    for i in (0, 1):
        assert procs[i].returncode == 0, outs[i][-2000:]
        assert f"proc {i} DONE" in outs[i]
    # all three hosts were aggregated while alive
    assert "AGG hosts=3 names=h0,h1,h2" in outs[0]
    # the corpse named as straggler (missing from the newest step,
    # ranked by lease age)
    assert "STRAGGLER=h2" in outs[0] and "missing=h2" in outs[0]
    # the leader bundle: exists, parses, names the dead host and its
    # last published step
    bundles = list((eldir / "postmortem").glob("h2.evicted.*.json"))
    assert len(bundles) == 1
    bundle = json.loads(bundles[0].read_text())
    assert bundle["host"] == "h2" and bundle["cause"] == "Evicted"
    assert bundle["final_telemetry"]["step"] == kill_at
    assert bundle["final_telemetry"]["version"] == \
        fleet.SNAPSHOT_VERSION
    # eviction-time adjudication: the corpse — lease-less while its
    # snapshot was still live — is the final-step straggler
    assert bundle["fleet"]["skew"]["straggler"] == "h2"
    assert "h2" in bundle["fleet"]["skew"]["missing"]
    # post-eviction fleet view: survivors only, eviction visible
    view = fleet.aggregate(eldir)
    assert set(view.table()) == {"h0", "h1"}
    assert view.evicted() == ["h2"]
    metrics.parse_exposition(view.exposition())
