"""Data-parallel training over all local devices — the reference's
ParallelWrapper / Spark training-master flow (SURVEY §3.5) as one SPMD
program. Run with virtual devices to see 8-way DP on a laptop:

    XLA_FLAGS=--xla_force_host_platform_device_count=8 \
    JAX_PLATFORMS=cpu python examples/distributed_data_parallel.py

Multi-host: call initialize_distributed() on every process (see
parallel/mesh.py) and feed per-process shards — same code.
"""
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))


def main():
    import jax
    import numpy as np
    from deeplearning4j_tpu.data import DataSet, ListDataSetIterator
    from deeplearning4j_tpu.nn import (MultiLayerNetwork,
                                       NeuralNetConfiguration)
    from deeplearning4j_tpu.nn.config import InputType
    from deeplearning4j_tpu.nn.layers import DenseLayer, OutputLayer
    from deeplearning4j_tpu.nn import updaters as upd
    from deeplearning4j_tpu.parallel import (
        ParallelWrapper, ParameterAveragingTrainingMaster,
        SparkDl4jMultiLayer)

    n = len(jax.devices())
    print(f"{n} device(s): {jax.devices()[0].platform}")

    conf = (NeuralNetConfiguration.builder().seed(42)
            .updater(upd.Adam(learning_rate=0.02)).list()
            .layer(DenseLayer(n_out=32, activation="relu"))
            .layer(OutputLayer(n_out=2, activation="softmax",
                               loss="mcxent"))
            .set_input_type(InputType.feed_forward(8)).build())

    rng = np.random.default_rng(0)
    x = rng.standard_normal((1024, 8)).astype(np.float32)
    y = np.eye(2, dtype=np.float32)[(x.sum(1) > 0).astype(int)]
    data = [DataSet(x[i:i + 64], y[i:i + 64])
            for i in range(0, 1024, 64)]

    # 1) ParallelWrapper SYNC mode: sharded batch, XLA allreduce
    net = MultiLayerNetwork(conf).init()
    wrapper = (ParallelWrapper.builder(net).workers(n)
               .prefetch_buffer(2).build())
    wrapper.fit(ListDataSetIterator(data), epochs=4)
    print(f"ParallelWrapper SYNC: score {net.score():.4f}")

    # 2) Spark-facade with parameter averaging (reference
    #    ParameterAveragingTrainingMaster semantics)
    net2 = MultiLayerNetwork(conf).init()
    master = (ParameterAveragingTrainingMaster.Builder(64)
              .averaging_frequency(4).build())
    SparkDl4jMultiLayer(net2, master).fit(
        ListDataSetIterator(data), epochs=4)
    print(f"ParameterAveraging master: score {net2.score():.4f}")


if __name__ == "__main__":
    main()
