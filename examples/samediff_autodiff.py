"""SameDiff-style graph building + autodiff — the reference's
SameDiff quickstart: define variables/ops, train, save/load.

    python examples/samediff_autodiff.py
"""
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))


def main():
    import numpy as np
    from deeplearning4j_tpu.autodiff.samediff import (SameDiff,
                                                      TrainingConfig)
    from deeplearning4j_tpu.data import DataSet, ListDataSetIterator
    from deeplearning4j_tpu.nn import updaters as upd

    rng = np.random.default_rng(0)
    X = rng.standard_normal((256, 4)).astype(np.float32)
    w_true = rng.standard_normal((4, 1)).astype(np.float32)
    Y = X @ w_true + 0.05 * rng.standard_normal((256, 1)).astype(
        np.float32)

    sd = SameDiff.create()
    x = sd.placeholder("x", np.float32, -1, 4)
    y = sd.placeholder("y", np.float32, -1, 1)
    w = sd.var("w", np.zeros((4, 1), np.float32))
    b = sd.var("b", np.zeros((1,), np.float32))
    pred = x.mmul(w).add(b, name="pred")
    sd.loss.mse(y, pred, name="loss")
    sd.set_loss_variables("loss")
    sd.set_training_config(TrainingConfig(
        updater=upd.Sgd(learning_rate=0.1),
        data_set_feature_mapping=["x"], data_set_label_mapping=["y"]))

    it = ListDataSetIterator(DataSet(X, Y), batch_size=64)
    losses = sd.fit(it, epochs=60)
    print(f"final loss: {losses[-1]:.5f}")
    w_err = float(np.abs(np.asarray(sd.get_variable("w").get_arr())
                         - w_true).max())
    print(f"max |w - w_true|: {w_err:.4f}")

    import tempfile
    path = os.path.join(tempfile.mkdtemp(), "samediff_example.sdz")
    sd.save(path)
    sd2 = SameDiff.load(path)
    p2 = sd2.get_variable("pred").eval({"x": X[:4]})
    print("restored pred shape:", np.asarray(p2).shape)


if __name__ == "__main__":
    main()
