"""Masked-LM pretraining from raw text: wordpiece vocab → BertIterator
(15% masking, 80/10/10 corruption) → BertTiny MLM head — the upstream
``BertIterator`` UNSUPERVISED-task flow, whole step jitted.

    python examples/bert_pretrain_mlm.py
"""
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

FAST = os.environ.get("DL4J_TPU_EXAMPLE_FAST") == "1"


def main():
    import jax

    if os.environ.get("DL4J_TPU_EXAMPLE_TPU") != "1":
        jax.config.update("jax_platforms", "cpu")
    import numpy as np

    from deeplearning4j_tpu.nlp import (BertIterator,
                                        BertWordPieceTokenizer)
    from deeplearning4j_tpu.nn import updaters as upd
    from deeplearning4j_tpu.zoo import BertTiny

    corpus = ["the quick brown fox jumps over the lazy dog",
              "pack my box with five dozen liquor jugs",
              "how vexingly quick daft zebras jump",
              "the five boxing wizards jump quickly",
              "sphinx of black quartz judge my vow"] * 8
    vocab = BertWordPieceTokenizer.build_vocab(corpus)
    tok = BertWordPieceTokenizer(vocab)
    print(f"wordpiece vocab: {len(vocab)} pieces")

    net = BertTiny(vocab_size=len(vocab), max_len=32,
                   updater=upd.Adam(learning_rate=1e-3),
                   seed=11).init_mlm(seq_len=16)
    it = BertIterator(tok, corpus, batch_size=8, seq_len=16,
                      task="mask_lm", seed=1)
    epochs = 2 if FAST else 12
    s0 = None
    for e in range(epochs):
        net.fit(it)
        it.reset()                 # fresh masking every epoch
        s0 = s0 if s0 is not None else net.score()
    print(f"MLM loss {s0:.3f} -> {net.score():.3f} "
          f"after {epochs} epochs (decreasing: {net.score() < s0})")

    # probe: mask one token and ask the model to fill it
    ids, segs, _ = it._encode_fixed("the quick brown fox")
    masked = list(ids)
    pos = 3                        # position of "brown"
    masked[pos] = vocab["[MASK]"]
    probs = np.asarray(net.output(
        np.asarray([masked], np.int32),
        np.asarray([segs], np.int32))[0])
    inv = {i: w for w, i in vocab.items()}
    top = np.argsort(-probs[0, pos])[:3]
    print("fill-in-the-blank 'the quick [MASK] fox' →",
          [inv[int(t)] for t in top])


if __name__ == "__main__":
    main()
