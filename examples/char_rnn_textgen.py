"""Character-level text generation with GravesLSTM + truncated BPTT —
the reference's GravesLSTMCharModellingExample (BASELINE config #3).

    python examples/char_rnn_textgen.py
"""
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

import jax

# force CPU BEFORE any device query — sitecustomize routes to the axon
# TPU tunnel otherwise, which serializes tiny examples (and hangs when
# the tunnel is down); opt into TPU with DL4J_TPU_EXAMPLE_TPU=1
if os.environ.get("DL4J_TPU_EXAMPLE_TPU") != "1":
    jax.config.update("jax_platforms", "cpu")

FAST = os.environ.get("DL4J_TPU_EXAMPLE_FAST") == "1"

CORPUS = (
    "the quick brown fox jumps over the lazy dog. "
    "pack my box with five dozen liquor jugs. "
    "how vexingly quick daft zebras jump! "
    "sphinx of black quartz, judge my vow. "
) * 40


def main():
    import numpy as np
    from deeplearning4j_tpu.zoo.textgen_lstm import TextGenerationLSTM

    chars = sorted(set(CORPUS))
    idx = {c: i for i, c in enumerate(chars)}
    data = np.asarray([idx[c] for c in CORPUS], np.int32)

    seq, batch = 50, 16
    model = TextGenerationLSTM(vocab_size=len(chars),
                               hidden=64 if FAST else 256,
                               layers=2, tbptt=25)
    net = model.init()

    def batches(n):
        rng = np.random.default_rng(0)
        for _ in range(n):
            starts = rng.integers(0, data.size - seq - 1, batch)
            ids = np.stack([data[s:s + seq] for s in starts])
            nxt = np.stack([data[s + 1:s + seq + 1] for s in starts])
            x = np.eye(len(chars), dtype=np.float32)[ids]
            y = np.eye(len(chars), dtype=np.float32)[nxt]
            yield x, y

    steps = 30 if FAST else 300
    for i, (x, y) in enumerate(batches(steps)):
        net.fit(x, y)
        if (i + 1) % max(1, steps // 5) == 0:
            print(f"step {i+1}/{steps}  loss {net.score():.3f}")

    # sample: greedy generation char by char via stored-state stepping
    # (reference rnnTimeStep API — state carried inside the net)
    seed = "the "
    out = list(seed)
    net.rnn_clear_previous_state()
    x = np.eye(len(chars), dtype=np.float32)[[idx[c] for c in seed]][None]
    for _ in range(80):
        y = net.rnn_time_step(x)
        nxt = int(np.asarray(y)[0, -1].argmax())
        out.append(chars[nxt])
        x = np.eye(len(chars), dtype=np.float32)[[nxt]][None]
    print("generated:", "".join(out))


if __name__ == "__main__":
    main()
