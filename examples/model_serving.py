"""Model serving with dynamic batching — many concurrent clients, one
device: requests are queued, concatenated up to a batch limit, run as
one jitted forward, and scattered back to their callers (reference:
ParallelInference BATCHED mode + BatchedInferenceObservable,
SURVEY §3.3).

    python examples/model_serving.py
"""
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

import jax

# force CPU BEFORE any device query — sitecustomize routes to the axon
# TPU tunnel otherwise, which serializes tiny examples (and hangs when
# the tunnel is down); opt into TPU with DL4J_TPU_EXAMPLE_TPU=1
if os.environ.get("DL4J_TPU_EXAMPLE_TPU") != "1":
    jax.config.update("jax_platforms", "cpu")

FAST = os.environ.get("DL4J_TPU_EXAMPLE_FAST") == "1"


def main():
    import threading
    import time

    import numpy as np
    from deeplearning4j_tpu.nn import (MultiLayerNetwork,
                                       NeuralNetConfiguration)
    from deeplearning4j_tpu.nn.config import InputType
    from deeplearning4j_tpu.nn.layers import DenseLayer, OutputLayer
    from deeplearning4j_tpu.nn import updaters as upd
    from deeplearning4j_tpu.parallel import ParallelInference

    conf = (NeuralNetConfiguration.builder().seed(0)
            .updater(upd.Adam(learning_rate=1e-3)).list()
            .layer(DenseLayer(n_out=64, activation="relu"))
            .layer(DenseLayer(n_out=32, activation="relu"))
            .layer(OutputLayer(n_out=4, activation="softmax",
                               loss="mcxent"))
            .set_input_type(InputType.feed_forward(16)).build())
    net = MultiLayerNetwork(conf).init()

    server = ParallelInference(net, mode=ParallelInference.BATCHED,
                               batch_limit=32)
    rng = np.random.default_rng(0)
    n_clients = 8 if FAST else 32
    per_client = 4 if FAST else 16
    latencies = []
    lock = threading.Lock()

    def client(cid):
        for _ in range(per_client):
            x = rng.standard_normal((1, 16)).astype(np.float32)
            t0 = time.perf_counter()
            out = server.output(x)
            dt = time.perf_counter() - t0
            assert out.shape == (1, 4)
            assert abs(float(out.sum()) - 1.0) < 1e-4
            with lock:
                latencies.append(dt)

    threads = [threading.Thread(target=client, args=(i,))
               for i in range(n_clients)]
    t0 = time.perf_counter()
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    wall = time.perf_counter() - t0
    n = n_clients * per_client
    lat = sorted(latencies)
    print(f"served {n} single-example requests from {n_clients} "
          f"concurrent clients in {wall:.2f}s "
          f"({n / wall:.0f} req/s through dynamic batching)")
    print(f"latency p50 {lat[len(lat) // 2] * 1e3:.1f} ms, "
          f"p95 {lat[int(len(lat) * 0.95)] * 1e3:.1f} ms")
    server.shutdown()


if __name__ == "__main__":
    main()
