"""Decoder-only causal LM: train on a toy corpus, decode with the
KV-cached scan, and (optionally) train sequence-parallel over a mesh —
the modern-LM family the reference lacks (its LM story is char-RNN +
imported BERT).

    XLA_FLAGS=--xla_force_host_platform_device_count=8 \
        python examples/causal_lm.py
"""
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

os.environ.setdefault("XLA_FLAGS",
                      "--xla_force_host_platform_device_count=8")
if "xla_force_host_platform_device_count" not in \
        os.environ["XLA_FLAGS"]:
    os.environ["XLA_FLAGS"] += \
        " --xla_force_host_platform_device_count=8"

FAST = os.environ.get("DL4J_TPU_EXAMPLE_FAST") == "1"


def main():
    import jax

    if os.environ.get("DL4J_TPU_EXAMPLE_TPU") != "1":
        jax.config.update("jax_platforms", "cpu")
    import numpy as np

    from deeplearning4j_tpu.zoo import GPTNano

    # toy corpus: learn to continue a repeating token melody
    # (t divisible by the mesh size so the ring-SP section shards)
    period, t = 7, 32
    model = GPTNano(vocab_size=32, max_len=64, seed=11)
    net = model.init(seq_len=t)
    tokens = np.arange(t + 1) % period + 1
    x = np.tile(tokens[:t], (8, 1)).astype(np.int32)
    y = np.tile(tokens[1:t + 1], (8, 1)).astype(np.int32)
    steps = 15 if FAST else 80
    for i in range(steps):
        net.fit(x, y)
    print(f"trained {steps} steps, loss {net.score():.4f}")

    prompt = (np.arange(10) % period + 1)[None, :].astype(np.int32)
    out = model.generate(net, prompt, n_new=10)
    print("prompt       :", prompt[0].tolist())
    print("continuation :", out[0, 10:].tolist())
    want = (np.arange(10, 20) % period + 1).tolist()
    print("expected     :", want,
          "MATCH" if out[0, 10:].tolist() == want else "(still learning)")

    # production serving recipe (round 4): tied embeddings train the
    # GPT-2 way; bf16 + weight-only int8 serving halve-then-halve the
    # per-token HBM traffic — greedy outputs stay identical
    tied = GPTNano(vocab_size=32, max_len=64, seed=11,
                   tie_embeddings=True, compute_dtype="bfloat16")
    tnet = tied.init(seq_len=t)
    for _ in range(steps):
        tnet.fit(x, y)
    full_out = tied.generate(tnet, prompt, n_new=10)
    server = GPTNano(vocab_size=32, max_len=64, seed=11,
                     tie_embeddings=True, compute_dtype="bfloat16",
                     serve_quant="int8")
    q_out = server.generate(tnet, prompt, n_new=10)
    print("int8-served  :", q_out[0, 10:].tolist(),
          "MATCH" if q_out.tolist() == full_out.tolist()
          else "DIVERGED from full precision!")

    # the same config trains sequence-parallel — layer API only
    from deeplearning4j_tpu.parallel import (distributed_context,
                                             make_mesh)
    sp = GPTNano(vocab_size=32, max_len=64, seed=11,
                 sequence_parallel="ring")
    spnet = sp.init(seq_len=t)
    with distributed_context(make_mesh(
            {"seq": min(8, len(jax.devices()))})):
        for _ in range(3 if FAST else 10):
            spnet.fit(x, y)
    print(f"sequence-parallel ring training: loss {spnet.score():.4f}")


if __name__ == "__main__":
    main()
