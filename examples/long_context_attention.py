"""Long-context attention: two sequence-parallel strategies over a
device mesh (beyond-reference capability; the reference's longest-
sequence story is truncated BPTT).

- ring attention: KV blocks rotate around the ICI ring (ppermute),
  O(T/N) memory per device — use for extreme lengths / masks.
- Ulysses: all_to_all trades the sequence axis for the head axis, two
  collectives per call — use when heads >= mesh size.

    XLA_FLAGS=--xla_force_host_platform_device_count=8 \
        python examples/long_context_attention.py
"""
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

os.environ.setdefault("XLA_FLAGS",
                      "--xla_force_host_platform_device_count=8")
if "xla_force_host_platform_device_count" not in \
        os.environ["XLA_FLAGS"]:
    os.environ["XLA_FLAGS"] += \
        " --xla_force_host_platform_device_count=8"

FAST = os.environ.get("DL4J_TPU_EXAMPLE_FAST") == "1"


def main():
    import jax

    # force CPU BEFORE any device query — sitecustomize routes to the
    # axon TPU tunnel otherwise, which can hang; opt into TPU with
    # DL4J_TPU_EXAMPLE_TPU=1
    if os.environ.get("DL4J_TPU_EXAMPLE_TPU") != "1":
        jax.config.update("jax_platforms", "cpu")
    import jax.numpy as jnp
    import numpy as np

    from deeplearning4j_tpu.nn.layers.attention import \
        scaled_dot_attention
    from deeplearning4j_tpu.parallel import (make_mesh,
                                             ring_self_attention,
                                             ulysses_self_attention)

    n = min(8, len(jax.devices()))
    mesh = make_mesh({"seq": n})
    b, t, h, d = 2, (8 * n if FAST else 64 * n), 8, 32
    key = jax.random.PRNGKey(0)
    kq, kk, kv = jax.random.split(key, 3)
    q = jax.random.normal(kq, (b, t, h, d))
    k = jax.random.normal(kk, (b, t, h, d))
    v = jax.random.normal(kv, (b, t, h, d))

    full = scaled_dot_attention(q, k, v)
    ring = ring_self_attention(q, k, v, mesh)
    uly = ulysses_self_attention(q, k, v, mesh)
    err_r = float(jnp.max(jnp.abs(full - ring)))
    err_u = float(jnp.max(jnp.abs(full - uly)))
    print(f"T={t} over {n} devices: ring err {err_r:.2e}, "
          f"ulysses err {err_u:.2e} (both vs single-device attention)")

    # gradients flow through both collective patterns
    g = jax.grad(lambda q: jnp.sum(
        ring_self_attention(q, k, v, mesh) ** 2))(q)
    gu = jax.grad(lambda q: jnp.sum(
        ulysses_self_attention(q, k, v, mesh) ** 2))(q)
    assert np.isfinite(np.asarray(g)).all()
    assert np.isfinite(np.asarray(gu)).all()
    print("gradients finite through ppermute ring and all_to_all swap")

    # the flagship workload: CAUSAL-LM training step with sequence-
    # parallel ring attention — per ring step the flash kernel masks
    # above the (globally-offset) diagonal and skips dead blocks
    full_c = scaled_dot_attention(q, k, v, causal=True)
    ring_c = ring_self_attention(q, k, v, mesh, causal=True)
    err_c = float(jnp.max(jnp.abs(full_c - ring_c)))

    import optax
    wq = jax.random.normal(jax.random.PRNGKey(1), (d, d)) * 0.05

    def lm_loss(wq, x):
        qp = jnp.einsum("bthd,de->bthe", x, wq)
        out = ring_self_attention(qp, x, x, mesh, causal=True)
        # next-position prediction surrogate on the sharded axis
        return jnp.mean((out[:, :-1] - x[:, 1:]) ** 2)

    opt = optax.adam(1e-2)
    state = opt.init(wq)
    losses = []
    for _ in range(3):
        loss, grad = jax.value_and_grad(lm_loss)(wq, q)
        upd, state = opt.update(grad, state, wq)
        wq = optax.apply_updates(wq, upd)
        losses.append(float(loss))
    print(f"causal ring err {err_c:.2e}; causal-LM train losses "
          f"{['%.4f' % l for l in losses]} (decreasing: "
          f"{losses[-1] < losses[0]})")


if __name__ == "__main__":
    main()
