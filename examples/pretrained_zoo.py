"""Pretrained model zoo — restore checksum-verified weights, predict,
fine-tune, and publish your own (reference: ZooModel.initPretrained +
DL4JResources; dl4j-examples' pretrained VGG16 flow).

    python examples/pretrained_zoo.py
"""
import os
import sys
import tempfile

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

FAST = os.environ.get("DL4J_TPU_EXAMPLE_FAST") == "1"


def main():
    import jax

    # force CPU BEFORE any device query — sitecustomize routes to the
    # axon TPU tunnel otherwise, which can hang; opt into TPU with
    # DL4J_TPU_EXAMPLE_TPU=1
    if os.environ.get("DL4J_TPU_EXAMPLE_TPU") != "1":
        jax.config.update("jax_platforms", "cpu")
    import numpy as np
    from deeplearning4j_tpu.data import DataSet, ListDataSetIterator
    from deeplearning4j_tpu.zoo import LeNet, export_pretrained

    # 1. restore the checked-in pretrained weights (sha256-verified)
    assert LeNet.pretrained_available()
    net = LeNet.init_pretrained()
    rng = np.random.default_rng(0)
    x = rng.normal(size=(4, 14, 14, 1)).astype(np.float32)
    probs = np.asarray(net.output(x))
    print(f"pretrained LeNet: predicted classes {probs.argmax(1)}")

    # 2. fine-tune on new data
    y = np.eye(10, dtype=np.float32)[rng.integers(0, 10, 64)]
    xt = rng.normal(size=(64, 14, 14, 1)).astype(np.float32)
    it = ListDataSetIterator(DataSet(xt, y), batch_size=32)
    for _ in range(1 if FAST else 5):
        net.fit(it)
    print(f"fine-tuned score: {net.score():.3f}")

    # 3. publish to your own weight repository (manifest + checksum)
    with tempfile.TemporaryDirectory() as repo:
        artifact = export_pretrained(net, "LeNet", "mytask", repo)
        print(f"published {artifact.name} "
              f"({artifact.stat().st_size // 1024} kB) with manifest")
        restored = LeNet.init_pretrained("mytask", base_dir=repo)
        assert np.allclose(np.asarray(restored.output(x)),
                           np.asarray(net.output(x)), atol=1e-6)
        print("round-trip restore matches")


if __name__ == "__main__":
    main()
