"""Hyperparameter search with the Arbiter analog — random search over
learning rate / width / updater for a classifier (reference:
arbiter's OptimizationRunner + ParameterSpace over a
MultiLayerConfiguration, SURVEY §2 arbiter row).

    python examples/hyperparameter_search.py
"""
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

import jax

# force CPU BEFORE any device query — sitecustomize routes to the axon
# TPU tunnel otherwise, which serializes tiny examples (and hangs when
# the tunnel is down); opt into TPU with DL4J_TPU_EXAMPLE_TPU=1
if os.environ.get("DL4J_TPU_EXAMPLE_TPU") != "1":
    jax.config.update("jax_platforms", "cpu")

FAST = os.environ.get("DL4J_TPU_EXAMPLE_FAST") == "1"


def main():
    import numpy as np
    from deeplearning4j_tpu.arbiter import (
        ContinuousParameterSpace, DiscreteParameterSpace,
        IntegerParameterSpace, OptimizationRunner, RandomSearchGenerator)
    from deeplearning4j_tpu.data.dataset import DataSet
    from deeplearning4j_tpu.data.iterators import ListDataSetIterator
    from deeplearning4j_tpu.nn import (MultiLayerNetwork,
                                       NeuralNetConfiguration)
    from deeplearning4j_tpu.nn.config import InputType
    from deeplearning4j_tpu.nn.layers import DenseLayer, OutputLayer
    from deeplearning4j_tpu.nn import updaters as upd

    rng = np.random.RandomState(0)
    x = rng.randn(256, 10).astype(np.float32)
    w_true = rng.randn(10, 3)
    y = np.eye(3, dtype=np.float32)[np.argmax(x @ w_true, axis=1)]
    train, test = DataSet(x[:192], y[:192]), DataSet(x[192:], y[192:])

    space = {
        "lr": ContinuousParameterSpace(1e-4, 1e-1, log=True),
        "hidden": IntegerParameterSpace(8, 64),
        "updater": DiscreteParameterSpace(["adam", "rmsprop"]),
    }

    def build_and_score(cand):
        u = (upd.Adam(learning_rate=cand["lr"])
             if cand["updater"] == "adam"
             else upd.RmsProp(learning_rate=cand["lr"]))
        conf = (NeuralNetConfiguration.builder().seed(7).updater(u)
                .list()
                .layer(DenseLayer(n_out=cand["hidden"],
                                  activation="relu"))
                .layer(OutputLayer(n_out=3, activation="softmax",
                                   loss="mcxent"))
                .set_input_type(InputType.feed_forward(10)).build())
        net = MultiLayerNetwork(conf).init()
        net.fit(ListDataSetIterator([train], batch_size=192),
                epochs=5 if FAST else 40)
        return net.score(test), net

    runner = OptimizationRunner(
        RandomSearchGenerator(space, seed=1),
        build_and_score,
        max_candidates=3 if FAST else 12)
    best = runner.execute()
    print(f"evaluated {len(runner.results)} candidates")
    for r in sorted(runner.results, key=lambda r: r.score)[:3]:
        print(f"  score {r.score:.4f}  <- {r.params}")
    print(f"best: {best.params} (test loss {best.score:.4f})")


if __name__ == "__main__":
    main()
