"""The five parallelism modes on one virtual 8-device mesh:
data parallel (ParallelWrapper), tensor parallel (sharded matmuls),
sequence parallel (ring attention), pipeline parallel (GPipe), and
expert parallel (MoE) — the TPU-native answers to the reference's
ParallelWrapper / SharedTrainingMaster stack (SURVEY §2.5), with TP/SP/
PP/EP as new capabilities the reference lacks.

    XLA_FLAGS=--xla_force_host_platform_device_count=8 \
    JAX_PLATFORMS=cpu python examples/parallelism_modes.py
"""
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

os.environ.setdefault("XLA_FLAGS",
                      "--xla_force_host_platform_device_count=8")


def main():
    import jax
    import jax.numpy as jnp
    import numpy as np
    import optax

    from deeplearning4j_tpu.data import DataSet, ListDataSetIterator
    from deeplearning4j_tpu.nn import (MultiLayerNetwork,
                                       NeuralNetConfiguration)
    from deeplearning4j_tpu.nn.config import InputType
    from deeplearning4j_tpu.nn.layers import DenseLayer, OutputLayer
    from deeplearning4j_tpu.nn import updaters as upd
    from deeplearning4j_tpu.parallel import (
        ParallelWrapper, make_mesh, MixtureOfExperts,
        pipeline_train_step, make_mlp_stage)
    from deeplearning4j_tpu.parallel.ring_attention import \
        ring_self_attention

    n = jax.device_count()
    print(f"devices: {n} ({jax.devices()[0].platform})")
    if n < 2:
        print("single device: modes below still compile as 1-way "
              "meshes (run with the XLA_FLAGS above for 8-way)")
    rng = np.random.default_rng(0)

    # ---- 1. Data parallel: replica-per-device SPMD step --------------
    conf = (NeuralNetConfiguration.builder().seed(1)
            .updater(upd.Adam(learning_rate=1e-2)).list()
            .layer(DenseLayer(n_out=32, activation="relu"))
            .layer(OutputLayer(n_out=4, activation="softmax",
                               loss="mcxent"))
            .set_input_type(InputType.feed_forward(16)).build())
    net = MultiLayerNetwork(conf).init()
    ds = DataSet(rng.normal(size=(16 * n, 16)).astype(np.float32),
                 np.eye(4, dtype=np.float32)[
                     rng.integers(0, 4, 16 * n)])
    ParallelWrapper.builder(net).workers(n).build().fit(
        ListDataSetIterator(ds, batch_size=16 * n), epochs=3)
    print(f"1. DP   ParallelWrapper score: {net.score():.4f}")

    # ---- 2. Tensor parallel: column/row-sharded MLP ------------------
    from jax.sharding import NamedSharding, PartitionSpec as P
    mesh = (make_mesh({"data": 2, "model": n // 2}) if n % 2 == 0
            else make_mesh({"data": 1, "model": n}))
    W1 = jax.device_put(
        jnp.asarray(rng.normal(size=(16, 64)).astype(np.float32)) * 0.1,
        NamedSharding(mesh, P(None, "model")))
    W2 = jax.device_put(
        jnp.asarray(rng.normal(size=(64, 4)).astype(np.float32)) * 0.1,
        NamedSharding(mesh, P("model", None)))
    x = jax.device_put(jnp.asarray(ds.features[:32]),
                       NamedSharding(mesh, P("data", None)))

    @jax.jit
    def tp_fwd(W1, W2, x):
        return jax.nn.relu(x @ W1) @ W2          # SPMD inserts psum

    print(f"2. TP   sharded MLP out: {tp_fwd(W1, W2, x).shape}")

    # ---- 3. Sequence parallel: ring attention over an ICI ring -------
    smesh = make_mesh({"seq": n})
    q = jnp.asarray(rng.normal(size=(2, 8 * n, 2, 16)), jnp.float32)
    out = jax.jit(lambda q: ring_self_attention(q, q, q, smesh))(q)
    print(f"3. SP   ring attention out: {out.shape} (seq sharded {n}x)")

    # ---- 4. Pipeline parallel: GPipe microbatches --------------------
    pmesh = make_mesh({"stage": n})
    params = {"W": jnp.asarray(rng.normal(size=(n, 16, 16)) * 0.1,
                               jnp.float32),
              "b": jnp.zeros((n, 16))}
    step, opt = pipeline_train_step(
        make_mlp_stage(), lambda o, t: jnp.mean(jnp.square(o - t)),
        mesh=pmesh, axis="stage", optimizer=optax.adam(1e-2))
    xm = jnp.asarray(rng.normal(size=(4, 4, 16)), jnp.float32)
    ym = jnp.tanh(xm)
    st = opt.init(params)
    for i in range(5):
        params, st, loss = step(params, st, xm, ym)
    print(f"4. PP   gpipe loss after 5 steps: {float(loss):.4f}")

    # ---- 5. Expert parallel: MoE with sharded experts ----------------
    emesh = make_mesh({"expert": n})
    moe = MixtureOfExperts(d_model=16, d_hidden=32, num_experts=n,
                           top_k=min(2, n))
    p = moe.shard(moe.init(), emesh, axis="expert")
    xe = jnp.asarray(rng.normal(size=(4, 2 * n, 16)), jnp.float32)
    out, aux = jax.jit(moe.apply)(p, xe)
    print(f"5. EP   moe out: {out.shape}, load-balance aux: "
          f"{float(aux):.3f}")
    print("all five parallelism modes ran on one mesh family")


if __name__ == "__main__":
    main()
