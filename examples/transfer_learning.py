"""Transfer learning — freeze a pretrained feature extractor, replace
the head, fine-tune on a new task (reference:
TransferLearning.Builder + FineTuneConfiguration +
TransferLearningHelper featurization, SURVEY §2.3).

    python examples/transfer_learning.py
"""
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

import jax

# force CPU BEFORE any device query — sitecustomize routes to the axon
# TPU tunnel otherwise, which serializes tiny examples (and hangs when
# the tunnel is down); opt into TPU with DL4J_TPU_EXAMPLE_TPU=1
if os.environ.get("DL4J_TPU_EXAMPLE_TPU") != "1":
    jax.config.update("jax_platforms", "cpu")

FAST = os.environ.get("DL4J_TPU_EXAMPLE_FAST") == "1"


def main():
    import numpy as np
    from deeplearning4j_tpu.data.dataset import DataSet
    from deeplearning4j_tpu.data.iterators import ListDataSetIterator
    from deeplearning4j_tpu.nn import (MultiLayerNetwork,
                                       NeuralNetConfiguration)
    from deeplearning4j_tpu.nn.config import InputType
    from deeplearning4j_tpu.nn.layers import DenseLayer, OutputLayer
    from deeplearning4j_tpu.nn import updaters as upd
    from deeplearning4j_tpu.nn.transferlearning import (
        FineTuneConfiguration, TransferLearning, TransferLearningHelper)

    rng = np.random.RandomState(0)
    epochs = 4 if FAST else 30

    # --- 1. "pretrain" a base model on task A (4-way) ------------------
    xa = rng.randn(256, 12).astype(np.float32)
    wa = rng.randn(12, 4)
    ya = np.eye(4, dtype=np.float32)[np.argmax(xa @ wa, axis=1)]
    conf = (NeuralNetConfiguration.builder().seed(1)
            .updater(upd.Adam(learning_rate=5e-3)).list()
            .layer(DenseLayer(n_out=32, activation="relu"))
            .layer(DenseLayer(n_out=16, activation="relu"))
            .layer(OutputLayer(n_out=4, activation="softmax",
                               loss="mcxent"))
            .set_input_type(InputType.feed_forward(12)).build())
    base = MultiLayerNetwork(conf).init()
    base.fit(ListDataSetIterator([DataSet(xa, ya)], batch_size=256),
             epochs=epochs)
    print(f"base model task-A loss: {base.score(DataSet(xa, ya)):.4f}")

    # --- 2. freeze features, new 2-way head, fine-tune on task B --------
    xb = rng.randn(128, 12).astype(np.float32)
    yb = np.eye(2, dtype=np.float32)[(xb @ wa[:, 0] > 0).astype(int)]
    ft = (TransferLearning.builder(base)
          .fine_tune_configuration(FineTuneConfiguration(
              updater=upd.Adam(learning_rate=1e-3)))
          .set_feature_extractor(1)           # freeze layers 0..1
          .remove_output_layer()
          .add_layer(OutputLayer(n_out=2, activation="softmax",
                                 loss="mcxent"))
          .build())
    # snapshot to host BEFORE fit: the jitted step donates param buffers
    frozen_before = np.asarray(ft.params["layer_0"]["W"]).copy()
    ft.fit(ListDataSetIterator([DataSet(xb, yb)], batch_size=128),
           epochs=epochs)
    drift = float(np.abs(np.asarray(ft.params["layer_0"]["W"])
                         - frozen_before).max())
    print(f"fine-tuned task-B loss: {ft.score(DataSet(xb, yb)):.4f} "
          f"(frozen-layer drift: {drift:.2e})")

    # --- 3. featurization path (TransferLearningHelper) ----------------
    helper = TransferLearningHelper(base, frozen_until=1)
    feats = helper.featurize(DataSet(xb, yb))
    print(f"featurized activations: {np.asarray(feats.features).shape} "
          "(train a head on these without re-running the frozen trunk)")


if __name__ == "__main__":
    main()
