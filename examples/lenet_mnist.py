"""LeNet on MNIST — the reference's LeNetMnistExample
(dl4j-examples): config builder -> fit -> Evaluation -> save/load.
Runs on CPU or TPU; uses the synthetic MNIST fallback without data.

    python examples/lenet_mnist.py
"""
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

import jax

# force CPU BEFORE any device query — sitecustomize routes to the axon
# TPU tunnel otherwise, which serializes tiny examples (and hangs when
# the tunnel is down); opt into TPU with DL4J_TPU_EXAMPLE_TPU=1
if os.environ.get("DL4J_TPU_EXAMPLE_TPU") != "1":
    jax.config.update("jax_platforms", "cpu")

FAST = os.environ.get("DL4J_TPU_EXAMPLE_FAST") == "1"


def main():
    import numpy as np
    from deeplearning4j_tpu.data.mnist import MnistDataSetIterator
    from deeplearning4j_tpu.serialization import ModelSerializer
    from deeplearning4j_tpu.zoo import LeNet

    n_train = 1024 if FAST else 16384
    train_it = MnistDataSetIterator(batch_size=64, train=True,
                                    n_examples=n_train)
    test_it = MnistDataSetIterator(batch_size=256, train=False,
                                   n_examples=n_train // 4)

    net = LeNet(num_classes=10, seed=123).init()
    print(f"LeNet: {net.num_params():,} params "
          f"(synthetic MNIST: {train_it.synthetic})")
    net.fit(train_it, epochs=1 if FAST else 3, steps_per_loop=4)
    ev = net.evaluate(test_it)
    print(ev.stats())

    import tempfile
    path = os.path.join(tempfile.mkdtemp(), "lenet_example.zip")
    ModelSerializer.write_model(net, path)
    net2 = ModelSerializer.restore_multi_layer_network(path)
    x = next(iter(test_it)).features[:4]
    assert np.allclose(np.asarray(net.output(x)),
                       np.asarray(net2.output(x)))
    print(f"saved + restored OK -> {path}")


if __name__ == "__main__":
    main()
