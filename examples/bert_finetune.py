"""BERT fine-tune for sequence classification (BASELINE config #4) —
a tiny BERT trained on a synthetic keyword-sentiment task.

    python examples/bert_finetune.py
"""
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

import jax

# force CPU BEFORE any device query — sitecustomize routes to the axon
# TPU tunnel otherwise, which serializes tiny examples (and hangs when
# the tunnel is down); opt into TPU with DL4J_TPU_EXAMPLE_TPU=1
if os.environ.get("DL4J_TPU_EXAMPLE_TPU") != "1":
    jax.config.update("jax_platforms", "cpu")

FAST = os.environ.get("DL4J_TPU_EXAMPLE_FAST") == "1"


def main():
    import numpy as np
    from deeplearning4j_tpu.zoo.bert import Bert
    from deeplearning4j_tpu.nn import updaters as upd
    from deeplearning4j_tpu.eval_.evaluation import Evaluation

    vocab, seq_len, batch = 1000, 32, 32
    GOOD, BAD = 7, 13          # sentiment carrier tokens
    bert = Bert(vocab_size=vocab, hidden=64, n_layers=2, n_heads=4,
                max_len=seq_len, dropout=0.1,
                updater=upd.Adam(learning_rate=1e-3))
    net = bert.init_classifier(num_classes=2, seq_len=seq_len)
    print(f"tiny BERT: {net.num_params():,} params")

    rng = np.random.default_rng(0)

    def make_batch():
        ids = rng.integers(20, vocab, (batch, seq_len))
        labels = rng.integers(0, 2, batch)
        pos = rng.integers(1, seq_len, batch)
        ids[np.arange(batch), pos] = np.where(labels == 1, GOOD, BAD)
        segs = np.zeros((batch, seq_len), np.int32)
        y = np.eye(2, dtype=np.float32)[labels]
        return ids, segs, y

    steps = 20 if FAST else 200
    for i in range(steps):
        ids, segs, y = make_batch()
        net.fit([ids, segs], [y])
        if (i + 1) % max(1, steps // 5) == 0:
            print(f"step {i+1}/{steps}  loss {net.score():.3f}")

    ids, segs, y = make_batch()
    preds = np.asarray(net.output(ids, segs)[0])
    ev = Evaluation(2)
    ev.eval(y, preds)
    print(f"held-out accuracy: {ev.accuracy():.3f}")


if __name__ == "__main__":
    main()
