"""Model-parallel serving: a network whose parameters exceed one
chip's HBM served across a mesh with per-layer NamedSharding
(SURVEY §2.5 "shard large models with pjit"; the reference's
ParallelInference is replica-only).

    XLA_FLAGS=--xla_force_host_platform_device_count=8 \
        python examples/sharded_serving.py
"""
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

os.environ.setdefault("XLA_FLAGS",
                      "--xla_force_host_platform_device_count=8")
if "xla_force_host_platform_device_count" not in \
        os.environ["XLA_FLAGS"]:
    os.environ["XLA_FLAGS"] += \
        " --xla_force_host_platform_device_count=8"

FAST = os.environ.get("DL4J_TPU_EXAMPLE_FAST") == "1"


def main():
    import jax

    # force CPU BEFORE any device query — sitecustomize routes to the
    # axon TPU tunnel otherwise, which can hang; opt into TPU with
    # DL4J_TPU_EXAMPLE_TPU=1
    if os.environ.get("DL4J_TPU_EXAMPLE_TPU") != "1":
        jax.config.update("jax_platforms", "cpu")
    import numpy as np

    from deeplearning4j_tpu.nn import (MultiLayerNetwork,
                                       NeuralNetConfiguration)
    from deeplearning4j_tpu.nn.config import InputType
    from deeplearning4j_tpu.nn.layers import DenseLayer, OutputLayer
    from deeplearning4j_tpu.nn import updaters as upd
    from deeplearning4j_tpu.parallel import (ParallelInference,
                                             make_mesh)

    hidden = 256 if FAST else 2048
    conf = (NeuralNetConfiguration.builder().seed(7)
            .updater(upd.Sgd(learning_rate=1e-2)).list()
            .layer(DenseLayer(n_out=hidden, activation="relu"))
            .layer(DenseLayer(n_out=hidden, activation="relu"))
            .layer(OutputLayer(n_out=16, activation="softmax",
                               loss="mcxent"))
            .set_input_type(InputType.feed_forward(64)).build())
    net = MultiLayerNetwork(conf).init()
    total = sum(l.size * l.dtype.itemsize
                for l in jax.tree_util.tree_leaves(net.params))

    n = min(8, len(jax.devices()))
    mesh = make_mesh({"model": n})
    pi = ParallelInference(net, mesh=mesh, shard_params=True)
    local = sum(l.addressable_shards[0].data.size
                * l.addressable_shards[0].data.dtype.itemsize
                for l in jax.tree_util.tree_leaves(net.params))
    print(f"params {total/1e6:.1f} MB total -> {local/1e6:.1f} MB "
          f"per device over {n} devices")

    x = np.random.default_rng(0).normal(size=(4, 64)).astype(np.float32)
    try:
        out = pi.output(x)
    finally:
        pi.shutdown()
    print(f"served batch through the sharded mesh: probs sum "
          f"{out.sum(1).round(3)}")


if __name__ == "__main__":
    main()
