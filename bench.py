"""Benchmark — BASELINE.md config #2: ResNet-50 training throughput
(images/sec/chip), the headline metric ("north star: match nd4j-cuda
on A100").

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.

Robustness contract (VERDICT r2 #1a): the TPU backend behind the axon
tunnel can hang indefinitely (even ``jax.devices()`` blocks when the
tunnel is down).  An infra outage must never read as ``rc:1`` /
``parsed:null`` — so this script:

  1. probes the backend in a SUBPROCESS with a <=120s timeout
     (device query + tiny matmul + host transfer, the full round trip);
  2. runs the actual benchmark in a SUBPROCESS with a bounded timeout
     (first XLA compile of ResNet-50 is slow, so the budget is generous);
  3. on any probe/bench failure or timeout emits one parseable line
     ``{"metric": ..., "skipped": true, "reason": ...}`` and exits 0.

Protocol (BASELINE.md): steady-state throughput — warmup (compile +
20 steps) excluded, median of 3 run-length-differenced estimates
(T(60 steps) − T(20 steps): one device→host sync through the axon
tunnel costs ~100–150 ms, so differencing cancels the constant
sync/dispatch floor while keeping every real per-step cost), synthetic
ImageNet-shaped data (224x224x3, 1000 classes) so storage never
bounds the number.
Whole-graph jitted train step, bf16 compute / fp32 master params on
TPU (the reference's cuDNN path is fp32 with per-op JNI dispatch —
SURVEY §3.2).

``vs_baseline``: the reference publishes no numbers (BASELINE.md
"none published"). Denominator: 2500 images/sec — A100-class ResNet-50
fp16 training throughput (NGC/MLPerf-era single-GPU ballpark), the
"match nd4j-cuda on A100" bar from BASELINE.json's north star.
"""
import json
import os
import subprocess
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

from deeplearning4j_tpu.utils.backend_probe import (  # noqa: E402
    apply_platform_override, probe_backend)

A100_CLASS_RESNET50_IMAGES_PER_SEC = 2500.0

METRIC = "resnet50_train_images_per_sec_per_chip"

BENCH_TIMEOUT_S = 1800


def _skip(reason):
    print(json.dumps({
        "metric": METRIC,
        "value": None,
        "unit": "images/sec",
        "vs_baseline": None,
        "skipped": True,
        "reason": reason,
    }))
    sys.exit(0)


def _run_bench_child():
    """Run the benchmark body in a subprocess with a watchdog timeout.

    Even after a successful probe the tunnel can drop mid-run; the
    child is killed on timeout and a structured skip is emitted.
    """
    try:
        proc = subprocess.run(
            [sys.executable, os.path.abspath(__file__), "--child"],
            capture_output=True, text=True, timeout=BENCH_TIMEOUT_S)
    except subprocess.TimeoutExpired:
        _skip(f"bench timed out after {BENCH_TIMEOUT_S}s "
              "(tunnel dropped mid-run?)")
    parsed = None
    for line in proc.stdout.splitlines():
        line = line.strip()
        if line.startswith("{"):
            try:
                parsed = json.loads(line)
            except ValueError:
                pass
    if proc.returncode != 0 or parsed is None:
        tail = (proc.stderr or proc.stdout or "").strip().splitlines()[-3:]
        _skip("bench child failed rc=%d: %s"
              % (proc.returncode, " | ".join(tail)))
    # ZeRO-DP sharded weight update (parallel/zero.py): before/after
    # row — replicated vs sharded SYNC step time and per-device
    # optimizer-state bytes on an 8-virtual-device mesh. Runs in its
    # own forced-CPU subprocess so a tunnel outage (or a 1-device box)
    # never blanks the headline number.
    from deeplearning4j_tpu.parallel import zero
    parsed["zero_dp"] = zero.subprocess_report()
    # continuous-batching serving gateway (serving/): the smoke trace
    # row — continuous vs request-at-a-time tokens/sec, p99 TTFT,
    # shed rate, retraces-after-warmup. Own forced-CPU subprocess for
    # the same reason as zero_dp.
    from deeplearning4j_tpu.serving import loadgen
    parsed["serving"] = loadgen.subprocess_report()
    # fused-primitive kernel library (ops/fused_norms.py): per-kernel
    # interpret-parity status + fallback timings. Forced-CPU
    # subprocess like zero_dp — parity is the contract the same
    # Mosaic-lowered code honors on TPU.
    from deeplearning4j_tpu.ops import fused_norms
    parsed["fused_kernels"] = fused_norms.subprocess_report()
    # communication observatory (obs/commtime.py): the ZeRO sharded
    # step's per-scope wire ledger gated against the PR 5 HLO byte
    # model (reduce-scatter ≈ grad_bytes/N, all-gather ≈ param
    # bytes), plus the off-path fence numbers. Own forced-CPU
    # subprocess like zero_dp.
    from deeplearning4j_tpu.obs import commtime
    parsed["comm"] = commtime.subprocess_report()
    print(json.dumps(parsed))


def bench_body():
    import numpy as np
    import jax
    apply_platform_override()
    import jax.numpy as jnp

    from deeplearning4j_tpu.zoo import ResNet50
    from deeplearning4j_tpu.nn import updaters as upd

    on_tpu = jax.devices()[0].platform in ("tpu", "axon")
    # the CPU path only validates wiring (the number is labeled with
    # its platform); this box has ONE core and XLA-CPU ResNet steps
    # run ~seconds each, so keep the CPU shapes tiny
    batch = 256 if on_tpu else 4
    size = 224 if on_tpu else 32

    net = ResNet50(num_classes=1000, seed=123,
                   input_shape=(size, size, 3),
                   updater=upd.Nesterovs(learning_rate=0.1, momentum=0.9),
                   compute_dtype="bfloat16" if on_tpu else None).init()

    rng = np.random.default_rng(0)
    x = jnp.asarray(rng.standard_normal((batch, size, size, 3)),
                    jnp.float32)
    y = jnp.asarray(np.eye(1000, dtype=np.float32)[
        rng.integers(0, 1000, batch)])

    # scanned device loop (steps_per_loop): K train steps per dispatched
    # executable — the idiomatic TPU training loop (host/dispatch latency
    # amortised; the reference instead pays a JNI crossing PER OP).
    k_inner = 4
    assert 20 % k_inner == 0, "warmup/timed step counts must divide k_inner"
    loop = net._make_train_loop()
    params, opt_state, state = net.params, net.opt_state, net.state
    base = jax.random.PRNGKey(0)
    x_stack = {"input": jnp.stack([x] * k_inner)}
    y_stack = [jnp.stack([y] * k_inner)]
    rngs = jnp.stack([jax.random.fold_in(base, i) for i in range(k_inner)])

    # warmup: compile + 20 steps (BASELINE.md protocol). Sync via a
    # scalar host transfer: the loss is data-dependent on the whole
    # step chain, and (unlike block_until_ready) a device->host copy
    # is a true barrier on every platform including the axon TPU tunnel.
    def sync(tree):
        # scalar host transfer of a param leaf: data-dependent on the
        # final optimizer update, so the whole chain must be done
        float(jax.tree.leaves(tree)[0].ravel()[0])

    for _ in range((20 if on_tpu else 4) // k_inner):
        params, opt_state, state, _ = loop(params, opt_state, state,
                                           x_stack, y_stack, {}, {},
                                           rngs)
    sync(params)

    def run_steps(n_steps):
        nonlocal params, opt_state, state
        assert n_steps % k_inner == 0
        t0 = time.perf_counter()
        for _ in range(n_steps // k_inner):
            params, opt_state, state, _ = loop(
                params, opt_state, state, x_stack, y_stack, {}, {},
                rngs)
        sync(params)
        return time.perf_counter() - t0

    def timed_run(n_lo=None, n_hi=None):
        # run-length differencing: one sync through the axon tunnel
        # costs ~100-150 ms (round-5 measurement), so T(n)/n would
        # overstate the step time by the amortised floor; timing n_lo
        # and n_hi steps and differencing cancels the constant
        # sync/dispatch floor while keeping every real per-step cost.
        # The CPU path only validates wiring — keep it short there.
        if n_lo is None:
            n_lo, n_hi = (20, 60) if on_tpu else (4, 8)
        dt = run_steps(n_hi) - run_steps(n_lo)
        return ((n_hi - n_lo) * batch / dt if dt > 0
                else n_hi * batch / run_steps(n_hi))

    runs = sorted(timed_run() for _ in range(3))
    images_per_sec = runs[1]  # median of 3 paired estimates

    # compile subsystem (perf/): wall-time XLA spent compiling this
    # run's entry points and whether the persistent cache paid for any
    # of it — a second bench run against a warm DL4J_TPU_COMPILE_CACHE
    # should show persistent_hits == compile_requests
    from deeplearning4j_tpu.perf import compile_report
    compile_rec = compile_report()

    # telemetry spine (obs/): the instrumentation rides every step, so
    # its tracing-OFF cost must be provably negligible — measured here
    # against this run's real step time (acceptance: < 1%)
    from deeplearning4j_tpu import obs
    obs_rec = obs.overhead_report(step_seconds=batch / images_per_sec)
    obs_rec["step_summary"] = obs.metrics.step_summary()

    # numerics observatory (obs/numerics.py): diagnostics-on vs -off
    # step time on this run's model — the in-step per-layer stats must
    # cost a small, measured fraction of the step (acceptance: <= 5%
    # on the smoke model), with scalars-only host traffic at cadence.
    # NB: reuses the live post-timing (params, opt_state, state) — the
    # scanned loop donated net's original buffers.
    numerics_rec = obs.numerics.measure_diag_overhead(
        net, params, opt_state, state, ({"input": x}, [y], {}, {}),
        jax.random.fold_in(jax.random.PRNGKey(0), 0),
        k=4 if on_tpu else 2)

    # fleet observability plane (obs/fleet.py): publish-cadence cost
    # against this run's real step — the off path (no plane) must be
    # ~0 (one branch, the PR 2 bar) and the on path < 1% of step time
    # at the default 1 Hz cadence
    fleet_rec = obs.fleet.measure_publish_overhead(
        step_seconds=batch / images_per_sec)

    # device-time observatory (obs/devtime.py): the fit-loop hook's
    # off-path cost against this run's real step (DL4J_TPU_DEVTIME
    # unset must be one branch — the PR 2 bar), plus capture counters
    devtime_rec = obs.devtime.measure_capture_overhead(
        step_seconds=batch / images_per_sec)

    print(json.dumps({
        "metric": METRIC,
        "value": round(images_per_sec, 1),
        "unit": "images/sec",
        "vs_baseline": round(
            images_per_sec / A100_CLASS_RESNET50_IMAGES_PER_SEC, 3),
        # BASELINE.md protocol: state batch/shape/platform with every
        # number; vs_baseline is only apples-to-apples on TPU
        "batch": batch,
        "image_size": size,
        "compute_dtype": "bfloat16" if on_tpu else "float32",
        "platform": jax.devices()[0].platform,
        "compile": compile_rec,
        "obs": obs_rec,
        "numerics": numerics_rec,
        "fleet_obs": fleet_rec,
        "devtime": devtime_rec,
    }), flush=True)


def main():
    if "--child" in sys.argv:
        bench_body()
        return
    ok, detail = probe_backend()
    if not ok:
        _skip(detail)
    _run_bench_child()


if __name__ == "__main__":
    main()
