"""Benchmark — BASELINE.md config #1 (LeNet MNIST throughput).

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.

Protocol (BASELINE.md): steady-state throughput, warmup excluded,
median of 3 runs. Runs on whatever the default JAX platform is (the
real TPU chip under the driver; CPU in dev).

``vs_baseline``: the reference publishes no numbers (BASELINE.md).
We use the conventional figure for DL4J's CPU LeNet MNIST training
(~2,500 images/sec, dl4j-examples era hardware) as the denominator so
the ratio is meaningful until real reference measurements exist.
"""
import json
import time

import numpy as np

REFERENCE_LENET_IMAGES_PER_SEC = 2500.0  # nominal DL4J CPU baseline


def main():
    import jax
    import jax.numpy as jnp

    from deeplearning4j_tpu.zoo import LeNet
    from deeplearning4j_tpu.data.mnist import MnistDataSetIterator

    batch = 512
    net = LeNet(num_classes=10, seed=123).init()

    it = MnistDataSetIterator(batch_size=batch, train=True,
                              n_examples=batch * 4)
    batches = [(jnp.asarray(ds.features), jnp.asarray(ds.labels))
               for ds in it]

    step = net._make_train_step()
    if net._train_step_fn is None:
        net._train_step_fn = step

    params, opt_state, state = net.params, net.opt_state, net.state
    rng = jax.random.PRNGKey(0)

    # warmup: compile + 20 steps (BASELINE.md protocol)
    for i in range(20):
        x, y = batches[i % len(batches)]
        params, opt_state, state, loss = step(params, opt_state, state,
                                              x, y, None, None, rng)
    jax.block_until_ready(params)

    def timed_run(n_steps=30):
        t0 = time.perf_counter()
        nonlocal params, opt_state, state
        for i in range(n_steps):
            x, y = batches[i % len(batches)]
            params, opt_state, state, loss = step(
                params, opt_state, state, x, y, None, None, rng)
        jax.block_until_ready(params)
        dt = time.perf_counter() - t0
        return n_steps * batch / dt

    runs = sorted(timed_run() for _ in range(3))
    images_per_sec = runs[1]  # median of 3

    print(json.dumps({
        "metric": "lenet_mnist_train_images_per_sec",
        "value": round(images_per_sec, 1),
        "unit": "images/sec",
        "vs_baseline": round(
            images_per_sec / REFERENCE_LENET_IMAGES_PER_SEC, 3),
    }))


if __name__ == "__main__":
    main()
