"""Hyperparameter optimization — reference: the ``arbiter/`` module
present in most fork vintages (SURVEY §0 note):
``org.deeplearning4j.arbiter.optimize``'s ParameterSpace hierarchy,
CandidateGenerator (random/grid search), and OptimizationRunner with
score functions and termination conditions.

TPU-native notes: candidates are independent full training runs; run
them sequentially on one chip (each already saturates it) or fan out
one candidate per slice in multi-host settings. The config-bean design
makes a candidate just a dict of sampled values applied to a
model-builder callable.
"""
from __future__ import annotations

import math
import time
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional, Sequence

import numpy as np


# ---------------------------------------------------------------------------
# parameter spaces (reference org.deeplearning4j.arbiter.optimize.parameter)
# ---------------------------------------------------------------------------
class ParameterSpace:
    def sample(self, rng) -> Any:
        raise NotImplementedError

    def grid(self, n: int) -> List[Any]:
        raise NotImplementedError


@dataclass
class ContinuousParameterSpace(ParameterSpace):
    """Uniform (or log-uniform) float range."""
    min: float = 0.0
    max: float = 1.0
    log: bool = False

    def __post_init__(self):
        if self.min >= self.max:
            raise ValueError(f"min {self.min} >= max {self.max}")
        if self.log and self.min <= 0:
            raise ValueError(
                f"log-uniform space needs min > 0, got {self.min}")

    def sample(self, rng):
        if self.log:
            lo, hi = math.log(self.min), math.log(self.max)
            return float(math.exp(rng.uniform(lo, hi)))
        return float(rng.uniform(self.min, self.max))

    def grid(self, n):
        if self.log:
            return [float(v) for v in np.exp(np.linspace(
                math.log(self.min), math.log(self.max), n))]
        return [float(v) for v in np.linspace(self.min, self.max, n)]


@dataclass
class IntegerParameterSpace(ParameterSpace):
    min: int = 0
    max: int = 10

    def __post_init__(self):
        # min == max is a valid degenerate space (pins the parameter)
        if self.min > self.max:
            raise ValueError(f"min {self.min} > max {self.max}")

    def sample(self, rng):
        return int(rng.integers(self.min, self.max + 1))

    def grid(self, n):
        return sorted({int(round(v)) for v in
                       np.linspace(self.min, self.max, n)})


@dataclass
class DiscreteParameterSpace(ParameterSpace):
    values: Sequence[Any] = field(default_factory=list)

    def __post_init__(self):
        if not self.values:
            raise ValueError("DiscreteParameterSpace needs values")

    def sample(self, rng):
        return self.values[int(rng.integers(0, len(self.values)))]

    def grid(self, n):
        return list(self.values)


# ---------------------------------------------------------------------------
# candidate generators (reference CandidateGenerator)
# ---------------------------------------------------------------------------
class RandomSearchGenerator:
    def __init__(self, space: Dict[str, ParameterSpace], seed: int = 0):
        self.space = space
        self.seed = seed

    def __iter__(self):
        # fresh stream per iteration: the same generator object yields
        # the same reproducible candidate sequence every run
        rng = np.random.default_rng(self.seed)
        while True:
            yield {k: s.sample(rng) for k, s in self.space.items()}


class GridSearchGenerator:
    def __init__(self, space: Dict[str, ParameterSpace],
                 points_per_dim: int = 3):
        self.space = space
        self.n = points_per_dim

    def __iter__(self):
        import itertools
        keys = list(self.space)
        axes = [self.space[k].grid(self.n) for k in keys]
        for combo in itertools.product(*axes):
            yield dict(zip(keys, combo))


# ---------------------------------------------------------------------------
# runner (reference OptimizationRunner + scoring + termination)
# ---------------------------------------------------------------------------
@dataclass
class CandidateResult:
    index: int
    params: Dict[str, Any]
    score: float
    model: Any = None
    seconds: float = 0.0


class OptimizationRunner:
    """Evaluate candidates from a generator with a user model-builder
    and score function; keep the best.

    ``build_and_score(candidate_params) -> (score, model)`` — lower is
    better by default (set ``maximize=True`` for accuracy-style
    scores). Termination: ``max_candidates`` and/or
    ``max_minutes`` (reference MaxCandidatesCondition /
    TimeoutTerminationCondition).
    """

    def __init__(self, generator, build_and_score: Callable,
                 max_candidates: int = 10,
                 max_minutes: Optional[float] = None,
                 maximize: bool = False,
                 keep_models: bool = False):
        self.generator = generator
        self.build_and_score = build_and_score
        self.max_candidates = max_candidates
        self.max_minutes = max_minutes
        self.maximize = maximize
        self.keep_models = keep_models
        self.results: List[CandidateResult] = []

    def execute(self) -> CandidateResult:
        t0 = time.monotonic()
        self.results = []                  # re-entrant: fresh run
        for i, cand in enumerate(self.generator):
            if i >= self.max_candidates:
                break
            if self.max_minutes is not None and \
                    (time.monotonic() - t0) / 60.0 > self.max_minutes:
                break
            tc = time.monotonic()
            score, model = self.build_and_score(cand)
            self.results.append(CandidateResult(
                i, dict(cand), float(score),
                model if self.keep_models else None,
                time.monotonic() - tc))
        return self.best()

    def best(self) -> CandidateResult:
        finite = [r for r in self.results if not math.isnan(r.score)]
        if not finite:
            raise RuntimeError("no finite-score candidates")
        key = (lambda r: -r.score) if self.maximize else \
            (lambda r: r.score)
        return min(finite, key=key)
