"""Brute-force k-NN on device (reference:
``nearestneighbor-core`` ``NearestNeighbor`` exact search). One jitted
matmul-based distance kernel — on TPU this beats tree traversal for
most corpus sizes (the trees exist for CPU-side parity and huge
corpora).
"""
from __future__ import annotations

from typing import List, Tuple

import numpy as np


class BruteForceNearestNeighbors:
    def __init__(self, points: np.ndarray, distance: str = "euclidean"):
        import jax
        import jax.numpy as jnp

        self.distance = distance
        self._points = jnp.asarray(np.asarray(points, np.float32))

        def query(points, q, k):
            if distance == "euclidean":
                d2 = (jnp.sum(points * points, 1)
                      - 2.0 * points @ q + q @ q)
                d = jnp.sqrt(jnp.maximum(d2, 0.0))
            elif distance == "cosine":
                pn = jnp.linalg.norm(points, axis=1)
                d = 1.0 - (points @ q) / jnp.maximum(
                    pn * jnp.linalg.norm(q), 1e-12)
            elif distance == "manhattan":
                d = jnp.sum(jnp.abs(points - q), axis=1)
            else:
                raise ValueError(f"unknown metric {distance!r}")
            neg, idx = jax.lax.top_k(-d, k)
            return idx, -neg

        self._query = jax.jit(query, static_argnums=(2,))

    def knn(self, q: np.ndarray, k: int) -> Tuple[List[int], List[float]]:
        idx, d = self._query(self._points,
                             np.asarray(q, np.float32), int(k))
        return list(np.asarray(idx)), list(np.asarray(d))
