"""K-Means clustering — reference:
``org.deeplearning4j.clustering.kmeans.KMeansClustering`` (module
deeplearning4j-nearestneighbor-parent/nearestneighbor-core) with its
ClusterSet/Point API.

TPU-native design: Lloyd iterations are ONE jitted step — the
[N, K] distance computation is a single batched matmul
(||x||² - 2x·c + ||c||²) on the MXU, assignment is an argmin, and the
centroid update is an unsorted segment mean; iterations run under
``lax.scan`` with static iteration count (distanceFunction/maxIterations
mirror the reference's setup(k, maxIter, distance))."""
from __future__ import annotations

from dataclasses import dataclass, field
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np


def _pairwise_sq_dists(x, c):
    # ||x-c||² via the matmul identity: lands on the MXU instead of an
    # [N,K,D] broadcast that would be HBM-bound
    x2 = jnp.sum(jnp.square(x), axis=1, keepdims=True)
    c2 = jnp.sum(jnp.square(c), axis=1)
    return x2 - 2.0 * (x @ c.T) + c2[None, :]


def _cosine_dists(x, c, eps=1e-9):
    xn = x / jnp.maximum(jnp.linalg.norm(x, axis=1, keepdims=True), eps)
    cn = c / jnp.maximum(jnp.linalg.norm(c, axis=1, keepdims=True), eps)
    return 1.0 - xn @ cn.T


def _manhattan_dists(x, c):
    return jnp.sum(jnp.abs(x[:, None, :] - c[None, :, :]), axis=-1)


_DISTANCES = {"euclidean": _pairwise_sq_dists,
              "cosinedistance": _cosine_dists,
              "cosine": _cosine_dists,
              "manhattan": _manhattan_dists}


@dataclass
class KMeansClustering:
    """``KMeansClustering.setup(k, maxIter, distance)`` equivalent."""
    k: int = 8
    max_iterations: int = 100
    distance: str = "euclidean"
    seed: int = 0
    #: k-means++ style init (reference uses random point selection)
    init: str = "kmeans++"
    centers_: Optional[np.ndarray] = field(default=None, repr=False)

    @staticmethod
    def setup(k: int, max_iterations: int,
              distance: str = "euclidean", **kw) -> "KMeansClustering":
        return KMeansClustering(k=k, max_iterations=max_iterations,
                                distance=distance, **kw)

    def _init_centers(self, x: jnp.ndarray) -> jnp.ndarray:
        key = jax.random.PRNGKey(self.seed)
        n = x.shape[0]
        if self.init != "kmeans++":
            idx = jax.random.choice(key, n, (self.k,), replace=False)
            return x[idx]
        dist_fn = _DISTANCES[self.distance.lower()]
        centers = [x[int(jax.random.randint(key, (), 0, n))]]
        for _ in range(1, self.k):
            key, sub = jax.random.split(key)
            d = jnp.min(dist_fn(x, jnp.stack(centers)), axis=1)
            p = jnp.maximum(d, 0)
            p = p / jnp.maximum(jnp.sum(p), 1e-12)
            centers.append(x[int(jax.random.choice(sub, n, p=p))])
        return jnp.stack(centers)

    def apply_to(self, points) -> "ClusterSet":
        """Run Lloyd iterations (reference applyTo(points))."""
        x = jnp.asarray(np.asarray(points, np.float32))
        dist_fn = _DISTANCES[self.distance.lower()]
        c0 = self._init_centers(x)
        k = self.k

        @jax.jit
        def run(x, c0):
            def step(c, _):
                assign = jnp.argmin(dist_fn(x, c), axis=1)
                ssum = jax.ops.segment_sum(x, assign, k)
                cnt = jax.ops.segment_sum(jnp.ones((x.shape[0], 1)),
                                          assign, k)
                new_c = jnp.where(cnt > 0, ssum / jnp.maximum(cnt, 1), c)
                return new_c, None
            c, _ = jax.lax.scan(step, c0, None,
                                length=self.max_iterations)
            assign = jnp.argmin(dist_fn(x, c), axis=1)
            return c, assign

        c, assign = run(x, c0)
        self.centers_ = np.asarray(c)
        return ClusterSet(np.asarray(c), np.asarray(assign),
                          np.asarray(x), self.distance)

    def predict(self, points) -> np.ndarray:
        if self.centers_ is None:
            raise RuntimeError("call apply_to() first")
        x = jnp.asarray(np.asarray(points, np.float32))
        dist_fn = _DISTANCES[self.distance.lower()]
        return np.asarray(jnp.argmin(
            dist_fn(x, jnp.asarray(self.centers_)), axis=1))


@dataclass
class ClusterSet:
    """Result container (reference ClusterSet/Cluster/PointClassification).
    """
    centers: np.ndarray
    assignments: np.ndarray
    points: np.ndarray
    distance: str = "euclidean"

    def get_clusters(self):
        return [self.points[self.assignments == i]
                for i in range(len(self.centers))]

    def center_of(self, cluster_idx: int) -> np.ndarray:
        return self.centers[cluster_idx]

    def inertia(self) -> float:
        d = _DISTANCES[self.distance.lower()](
            jnp.asarray(self.points), jnp.asarray(self.centers))
        return float(jnp.sum(jnp.min(d, axis=1)))
