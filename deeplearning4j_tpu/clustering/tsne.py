"""t-SNE — reference: ``org.deeplearning4j.plot.BarnesHutTsne``
(module deeplearning4j-manifold/deeplearning4j-tsne) with its
``.Builder`` (perplexity, theta, learningRate, maxIter) and
``fit(INDArray)`` API.

TPU-native design: instead of the reference's Barnes-Hut quadtree
(a pointer-chasing O(N log N) CPU structure), the pairwise affinity and
gradient computations are EXACT dense [N,N] matmuls — O(N²) FLOPs that
land on the MXU, where for the N ≤ ~50k regime t-SNE is used in this is
faster than tree traversal on accelerators. The perplexity search is a
vectorized bisection over all rows at once; the descent loop (momentum +
gains + early exaggeration, matching the reference's schedule) is one
``lax.scan``."""
from __future__ import annotations

from dataclasses import dataclass
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np


def _conditional_probs(d2, perplexity, iters=50):
    """Row-wise bisection for beta = 1/(2σ²) hitting target perplexity."""
    n = d2.shape[0]
    log_u = jnp.log(perplexity)
    mask = 1.0 - jnp.eye(n)

    def entropy_and_p(beta):
        p = jnp.exp(-d2 * beta[:, None]) * mask
        psum = jnp.maximum(jnp.sum(p, axis=1, keepdims=True), 1e-12)
        p = p / psum
        h = -jnp.sum(jnp.where(p > 1e-12, p * jnp.log(p), 0.0), axis=1)
        return h, p

    def body(state, _):
        beta, lo, hi = state
        h, _ = entropy_and_p(beta)
        too_high = h > log_u             # entropy too high → raise beta
        lo = jnp.where(too_high, beta, lo)
        hi = jnp.where(too_high, hi, beta)
        beta = jnp.where(jnp.isinf(hi), beta * 2,
                         (lo + hi) / 2.0)
        return (beta, lo, hi), None

    beta0 = jnp.ones(d2.shape[0])
    lo0 = jnp.zeros_like(beta0)
    hi0 = jnp.full_like(beta0, jnp.inf)
    (beta, _, _), _ = jax.lax.scan(body, (beta0, lo0, hi0), None,
                                   length=iters)
    _, p = entropy_and_p(beta)
    return p


@dataclass
class BarnesHutTsne:
    """Builder-compatible t-SNE (exact dense mode — see module doc).
    ``theta`` is accepted for API parity; the dense MXU path ignores it.
    """
    n_components: int = 2
    perplexity: float = 30.0
    theta: float = 0.5
    #: None → auto: max(N / early_exaggeration / 4, 50) — keeps the
    #: exaggerated phase stable across dataset sizes
    learning_rate: Optional[float] = None
    max_iter: int = 500
    momentum: float = 0.8
    early_exaggeration: float = 12.0
    stop_lying_iteration: int = 250
    seed: int = 0
    embedding_: Optional[np.ndarray] = None

    class Builder:
        def __init__(self):
            self._kw = {}

        def perplexity(self, v):
            self._kw["perplexity"] = v
            return self

        def theta(self, v):
            self._kw["theta"] = v
            return self

        def learning_rate(self, v):
            self._kw["learning_rate"] = v
            return self

        def set_max_iter(self, v):
            self._kw["max_iter"] = v
            return self

        def number_of_dimensions(self, v):
            self._kw["n_components"] = v
            return self

        def seed(self, v):
            self._kw["seed"] = v
            return self

        def build(self):
            return BarnesHutTsne(**self._kw)

    @staticmethod
    def builder() -> "BarnesHutTsne.Builder":
        return BarnesHutTsne.Builder()

    def fit(self, x) -> np.ndarray:
        """fit(INDArray)-equivalent; returns and stores the embedding."""
        x = jnp.asarray(np.asarray(x, np.float32))
        n = x.shape[0]
        # symmetric input affinities
        x2 = jnp.sum(jnp.square(x), axis=1)
        d2 = jnp.maximum(x2[:, None] - 2 * (x @ x.T) + x2[None, :], 0.0)
        p = _conditional_probs(d2, self.perplexity)
        p = (p + p.T) / (2.0 * n)
        p = jnp.maximum(p, 1e-12)

        key = jax.random.PRNGKey(self.seed)
        y0 = 1e-4 * jax.random.normal(key, (n, self.n_components))

        lr = (self.learning_rate if self.learning_rate is not None
              else max(n / self.early_exaggeration / 4.0, 50.0))
        mom = self.momentum
        lie = self.early_exaggeration
        stop_lie = min(self.stop_lying_iteration, self.max_iter)
        eye = jnp.eye(n)

        def grad_kl(y, p_eff):
            y2 = jnp.sum(jnp.square(y), axis=1)
            num = 1.0 / (1.0 + jnp.maximum(
                y2[:, None] - 2 * (y @ y.T) + y2[None, :], 0.0))
            num = num * (1.0 - eye)
            q = jnp.maximum(num / jnp.sum(num), 1e-12)
            w = (p_eff - q) * num
            # 4 * sum_j w_ij (y_i - y_j): row-sum trick keeps it matmuls
            return 4.0 * (jnp.sum(w, axis=1, keepdims=True) * y - w @ y)

        def step(state, i):
            y, vel, gains = state
            p_eff = jnp.where(i < stop_lie, p * lie, p)
            g = grad_kl(y, p_eff)
            same_sign = jnp.sign(g) == jnp.sign(vel)
            gains = jnp.maximum(
                jnp.where(same_sign, gains * 0.8, gains + 0.2), 0.01)
            vel = mom * vel - lr * gains * g
            y = y + vel
            y = y - jnp.mean(y, axis=0, keepdims=True)
            return (y, vel, gains), None

        @jax.jit
        def run(y0):
            init = (y0, jnp.zeros_like(y0), jnp.ones_like(y0))
            (y, _, _), _ = jax.lax.scan(step, init,
                                        jnp.arange(self.max_iter))
            return y

        y = run(y0)
        self.embedding_ = np.asarray(y)
        return self.embedding_

    def get_data(self) -> np.ndarray:
        if self.embedding_ is None:
            raise RuntimeError("call fit() first")
        return self.embedding_
