"""KD-tree (reference: ``org.deeplearning4j.clustering.kdtree.KDTree`` —
axis-cycling split, nn/knn queries, euclidean metric).
"""
from __future__ import annotations

import heapq
from typing import List, Optional, Tuple

import numpy as np


class _KDNode:
    __slots__ = ("index", "axis", "left", "right")

    def __init__(self, index, axis):
        self.index = index
        self.axis = axis
        self.left: Optional["_KDNode"] = None
        self.right: Optional["_KDNode"] = None


class KDTree:
    def __init__(self, points: np.ndarray):
        self.items = np.asarray(points, np.float32)
        self.dims = self.items.shape[1]
        self.root = self._build(list(range(len(self.items))), 0)

    def _build(self, idx: List[int], depth: int) -> Optional[_KDNode]:
        if not idx:
            return None
        axis = depth % self.dims
        idx.sort(key=lambda i: self.items[i, axis])
        mid = len(idx) // 2
        node = _KDNode(idx[mid], axis)
        node.left = self._build(idx[:mid], depth + 1)
        node.right = self._build(idx[mid + 1:], depth + 1)
        return node

    def nn(self, q: np.ndarray) -> Tuple[int, float]:
        idx, dist = self.knn(q, 1)
        return idx[0], dist[0]

    def knn(self, q: np.ndarray, k: int) -> Tuple[List[int], List[float]]:
        q = np.asarray(q, np.float32)
        heap: List[Tuple[float, int]] = []

        def visit(node: Optional[_KDNode]):
            if node is None:
                return
            p = self.items[node.index]
            d = float(np.linalg.norm(p - q))
            if len(heap) < k:
                heapq.heappush(heap, (-d, node.index))
            elif d < -heap[0][0]:
                heapq.heapreplace(heap, (-d, node.index))
            diff = q[node.axis] - p[node.axis]
            near, far = (node.left, node.right) if diff <= 0 \
                else (node.right, node.left)
            visit(near)
            if len(heap) < k or abs(diff) < -heap[0][0]:
                visit(far)

        visit(self.root)
        pairs = sorted((-nd, i) for nd, i in heap)
        return [i for _, i in pairs], [d for d, _ in pairs]
