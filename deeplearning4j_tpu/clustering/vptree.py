"""Vantage-point tree (reference:
``org.deeplearning4j.clustering.vptree.VPTree`` — metric-space
nearest-neighbor search with euclidean/cosine/manhattan distances,
``search(target, k)`` API).
"""
from __future__ import annotations

import heapq
from typing import List, Optional, Tuple

import numpy as np


def _distances(metric: str, data: np.ndarray, q: np.ndarray) -> np.ndarray:
    if metric == "euclidean":
        return np.linalg.norm(data - q, axis=-1)
    if metric == "manhattan":
        return np.abs(data - q).sum(axis=-1)
    if metric == "cosine":
        dn = np.linalg.norm(data, axis=-1) * np.linalg.norm(q)
        return 1.0 - (data @ q) / np.maximum(dn, 1e-12)
    raise ValueError(f"unknown distance metric {metric!r}")


class _Node:
    __slots__ = ("index", "threshold", "inside", "outside")

    def __init__(self, index: int):
        self.index = index
        self.threshold = 0.0
        self.inside: Optional["_Node"] = None
        self.outside: Optional["_Node"] = None


class VPTree:
    """Reference: VPTree(INDArray, String distance). O(log n) expected
    search in metric spaces where KD-trees degrade (high dims)."""

    def __init__(self, points: np.ndarray, distance: str = "euclidean",
                 seed: int = 123):
        self.items = np.asarray(points, np.float32)
        self.distance = distance
        self._rng = np.random.default_rng(seed)
        idx = list(range(len(self.items)))
        self.root = self._build(idx)

    def _dist_one(self, i: int, q: np.ndarray) -> float:
        return float(_distances(self.distance, self.items[i][None], q)[0])

    def _build(self, idx: List[int]) -> Optional[_Node]:
        if not idx:
            return None
        vp = idx[self._rng.integers(len(idx))]
        rest = [i for i in idx if i != vp]
        node = _Node(vp)
        if not rest:
            return node
        d = _distances(self.distance, self.items[rest], self.items[vp])
        node.threshold = float(np.median(d))
        inside = [rest[i] for i in range(len(rest))
                  if d[i] <= node.threshold]
        outside = [rest[i] for i in range(len(rest))
                   if d[i] > node.threshold]
        node.inside = self._build(inside)
        node.outside = self._build(outside)
        return node

    def search(self, target: np.ndarray, k: int
               ) -> Tuple[List[int], List[float]]:
        """k nearest (indices, distances) — reference
        VPTree.search(target, k, results, distances)."""
        q = np.asarray(target, np.float32)
        heap: List[Tuple[float, int]] = []    # max-heap via negation
        tau = [np.inf]

        def visit(node: Optional[_Node]):
            if node is None:
                return
            d = self._dist_one(node.index, q)
            if d < tau[0] or len(heap) < k:
                heapq.heappush(heap, (-d, node.index))
                if len(heap) > k:
                    heapq.heappop(heap)
                if len(heap) == k:
                    tau[0] = -heap[0][0]
            if d <= node.threshold:
                visit(node.inside)
                if d + tau[0] > node.threshold:
                    visit(node.outside)
            else:
                visit(node.outside)
                if d - tau[0] <= node.threshold:
                    visit(node.inside)

        visit(self.root)
        pairs = sorted((-nd, i) for nd, i in heap)
        return [i for _, i in pairs], [d for d, _ in pairs]
