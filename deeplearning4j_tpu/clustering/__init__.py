"""Nearest-neighbor search (reference:
``deeplearning4j-nearestneighbor-parent`` —
``org.deeplearning4j.clustering.vptree.VPTree``,
``kdtree.KDTree``).
"""
from deeplearning4j_tpu.clustering.vptree import VPTree
from deeplearning4j_tpu.clustering.kdtree import KDTree
from deeplearning4j_tpu.clustering.knn import BruteForceNearestNeighbors
from deeplearning4j_tpu.clustering.kmeans import KMeansClustering, ClusterSet
from deeplearning4j_tpu.clustering.tsne import BarnesHutTsne

__all__ = ["VPTree", "KDTree", "BruteForceNearestNeighbors",
           "KMeansClustering", "ClusterSet", "BarnesHutTsne"]
