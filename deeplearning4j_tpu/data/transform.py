"""Declarative column transforms — reference: datavec-api
``org.datavec.api.transform.TransformProcess`` + ``schema.Schema``
(+LocalTransformExecutor): typed column schema, chained transforms,
filters, categorical↔integer/one-hot conversion, normalization steps,
reducers — executed locally (the reference's Spark executor maps to the
same pure-python pipeline over any iterable; scale-out belongs to the
data-loading host layer, not the device path).
"""
from __future__ import annotations

import math
from typing import Any, Callable, Dict, List, Optional, Sequence

import numpy as np


class Schema:
    """Typed column schema (reference transform.schema.Schema)."""

    def __init__(self):
        self.columns: List[tuple] = []  # (name, type, meta)

    class Builder:
        def __init__(self):
            self._s = Schema()

        def add_column_double(self, name):
            self._s.columns.append((name, "double", None))
            return self

        def add_column_integer(self, name):
            self._s.columns.append((name, "integer", None))
            return self

        def add_column_long(self, name):
            self._s.columns.append((name, "long", None))
            return self

        def add_column_string(self, name):
            self._s.columns.append((name, "string", None))
            return self

        def add_column_categorical(self, name, categories: Sequence[str]):
            self._s.columns.append((name, "categorical",
                                    list(categories)))
            return self

        def add_column_time(self, name):
            self._s.columns.append((name, "time", None))
            return self

        def build(self):
            return self._s

    @staticmethod
    def builder() -> "Schema.Builder":
        return Schema.Builder()

    def names(self) -> List[str]:
        return [c[0] for c in self.columns]

    def index_of(self, name: str) -> int:
        return self.names().index(name)

    def type_of(self, name: str) -> str:
        return self.columns[self.index_of(name)][1]

    def categories_of(self, name: str):
        return self.columns[self.index_of(name)][2]

    def copy(self) -> "Schema":
        s = Schema()
        s.columns = list(self.columns)
        return s


class TransformProcess:
    """Chained schema-aware record transforms (reference
    TransformProcess + .Builder). ``execute`` maps any iterable of
    records; the final schema is available for downstream vectorization.
    """

    def __init__(self, initial_schema: Schema, steps: List[tuple]):
        self.initial_schema = initial_schema
        self.steps = steps

    class Builder:
        def __init__(self, schema: Schema):
            self._schema = schema
            self._steps: List[tuple] = []

        # -- transforms (reference names) -------------------------------
        def remove_columns(self, *names):
            self._steps.append(("remove", names))
            return self

        def remove_all_columns_except_for(self, *names):
            self._steps.append(("keep", names))
            return self

        def rename_column(self, old, new):
            self._steps.append(("rename", (old, new)))
            return self

        def categorical_to_integer(self, *names):
            self._steps.append(("cat2int", names))
            return self

        def categorical_to_one_hot(self, *names):
            self._steps.append(("cat2onehot", names))
            return self

        def integer_to_categorical(self, name, categories):
            self._steps.append(("int2cat", (name, list(categories))))
            return self

        def string_to_categorical(self, name, categories):
            self._steps.append(("str2cat", (name, list(categories))))
            return self

        def double_math_op(self, name, op: str, value: float):
            self._steps.append(("math", (name, op, value)))
            return self

        def double_column_math_op(self, new_name, op, *names):
            self._steps.append(("colmath", (new_name, op, names)))
            return self

        def normalize(self, name, kind: str, stat1: float, stat2: float):
            """kind: 'minmax' (stat1=min, stat2=max) or 'standardize'
            (stat1=mean, stat2=std)."""
            self._steps.append(("normalize", (name, kind, stat1, stat2)))
            return self

        def filter_by(self, predicate: Callable[[Dict[str, Any]], bool]):
            """Keep records where predicate(row_dict) is True (reference
            FilterInvalidValues / ConditionFilter, inverted sense)."""
            self._steps.append(("filter", predicate))
            return self

        def transform_column(self, name,
                             fn: Callable[[Any], Any]):
            self._steps.append(("apply", (name, fn)))
            return self

        def build(self):
            return TransformProcess(self._schema, self._steps)

    @staticmethod
    def builder(schema: Schema) -> "TransformProcess.Builder":
        return TransformProcess.Builder(schema)

    # -- schema evolution ------------------------------------------------
    def final_schema(self) -> Schema:
        schema = self.initial_schema.copy()
        for kind, arg in self.steps:
            cols = schema.columns
            if kind == "remove":
                schema.columns = [c for c in cols if c[0] not in arg]
            elif kind == "keep":
                schema.columns = [c for c in cols if c[0] in arg]
            elif kind == "rename":
                old, new = arg
                schema.columns = [(new if c[0] == old else c[0], c[1],
                                   c[2]) for c in cols]
            elif kind == "cat2int":
                schema.columns = [
                    (c[0], "integer" if c[0] in arg else c[1],
                     None if c[0] in arg else c[2]) for c in cols]
            elif kind == "cat2onehot":
                out = []
                for c in cols:
                    if c[0] in arg:
                        for cat in c[2]:
                            out.append((f"{c[0]}[{cat}]", "integer",
                                        None))
                    else:
                        out.append(c)
                schema.columns = out
            elif kind in ("int2cat", "str2cat"):
                name, cats = arg
                schema.columns = [
                    (c[0], "categorical" if c[0] == name else c[1],
                     cats if c[0] == name else c[2]) for c in cols]
            elif kind == "colmath":
                new_name, _, _ = arg
                schema.columns = cols + [(new_name, "double", None)]
        return schema

    # -- execution -------------------------------------------------------
    def execute(self, records) -> List[List[Any]]:
        """Reference: LocalTransformExecutor.execute."""
        schema = self.initial_schema.copy()
        rows = [list(r) for r in records]
        for kind, arg in self.steps:
            names = schema.names()
            if kind == "remove":
                idx = [i for i, n in enumerate(names) if n not in arg]
                rows = [[r[i] for i in idx] for r in rows]
            elif kind == "keep":
                idx = [i for i, n in enumerate(names) if n in arg]
                rows = [[r[i] for i in idx] for r in rows]
            elif kind == "cat2int":
                for nm in arg:
                    i = schema.index_of(nm)
                    cats = schema.categories_of(nm)
                    lut = {c: j for j, c in enumerate(cats)}
                    for r in rows:
                        r[i] = lut[r[i]]
            elif kind == "cat2onehot":
                for nm in arg:
                    i = schema.index_of(nm)
                    cats = schema.categories_of(nm)
                    lut = {c: j for j, c in enumerate(cats)}
                    for r in rows:
                        v = r.pop(i)
                        onehot = [0] * len(cats)
                        onehot[lut[v]] = 1
                        r[i:i] = onehot
            elif kind == "int2cat":
                nm, cats = arg
                i = schema.index_of(nm)
                for r in rows:
                    r[i] = cats[int(r[i])]
            elif kind == "str2cat":
                nm, cats = arg
                i = schema.index_of(nm)
                for r in rows:
                    if r[i] not in cats:
                        raise ValueError(f"value {r[i]!r} not in "
                                         f"categories of {nm}")
            elif kind == "rename":
                pass  # schema-only
            elif kind == "math":
                nm, op, val = arg
                i = schema.index_of(nm)
                fn = {"add": lambda x: x + val,
                      "subtract": lambda x: x - val,
                      "multiply": lambda x: x * val,
                      "divide": lambda x: x / val,
                      "pow": lambda x: x ** val}[op.lower()]
                for r in rows:
                    r[i] = fn(float(r[i]))
            elif kind == "colmath":
                new_name, op, cols_ = arg
                idx = [schema.index_of(n) for n in cols_]
                red = {"add": sum,
                       "multiply": lambda vs: math.prod(vs),
                       "max": max, "min": min}[op.lower()]
                for r in rows:
                    r.append(red([float(r[i]) for i in idx]))
            elif kind == "normalize":
                nm, how, s1, s2 = arg
                i = schema.index_of(nm)
                for r in rows:
                    v = float(r[i])
                    if how == "minmax":
                        r[i] = (v - s1) / max(s2 - s1, 1e-12)
                    else:
                        r[i] = (v - s1) / max(s2, 1e-12)
            elif kind == "filter":
                pred = arg
                rows = [r for r in rows
                        if pred(dict(zip(names, r)))]
            elif kind == "apply":
                nm, fn = arg
                i = schema.index_of(nm)
                for r in rows:
                    r[i] = fn(r[i])
            # evolve schema stepwise (reuse final_schema logic per step)
            schema = TransformProcess(schema, [(kind, arg)]
                                      ).final_schema()
        return rows


def _stdev(v):
    m = sum(v) / len(v)
    return (sum((x - m) ** 2 for x in v) / max(1, len(v) - 1)) ** 0.5


class Reducer:
    """Group-by aggregation over records (reference
    ``org.datavec.api.transform.reduce.Reducer`` + ``IAssociativeReducer``):
    rows sharing the key column values collapse to one row per group,
    non-key columns aggregated by the named op.

    Ops: sum, mean, min, max, count, range, stdev, first, last,
    count_unique.
    """

    _OPS = {
        "sum": lambda v: float(sum(v)),
        "mean": lambda v: float(sum(v)) / len(v),
        "min": lambda v: min(v),
        "max": lambda v: max(v),
        "count": lambda v: len(v),
        "range": lambda v: max(v) - min(v),
        "stdev": _stdev,
        "first": lambda v: v[0],
        "last": lambda v: v[-1],
        "count_unique": lambda v: len(set(v)),
    }

    class Builder:
        def __init__(self, *key_columns: str):
            self._keys = list(key_columns)
            self._ops: Dict[str, str] = {}
            self._default = "first"

        def default_op(self, op: str):
            self._default = op
            return self

        def _add(self, op, names):
            for n in names:
                self._ops[n] = op
            return self

        def sum_columns(self, *names):
            return self._add("sum", names)

        def mean_columns(self, *names):
            return self._add("mean", names)

        def min_columns(self, *names):
            return self._add("min", names)

        def max_columns(self, *names):
            return self._add("max", names)

        def count_columns(self, *names):
            return self._add("count", names)

        def stdev_columns(self, *names):
            return self._add("stdev", names)

        def count_unique_columns(self, *names):
            return self._add("count_unique", names)

        def build(self) -> "Reducer":
            r = Reducer()
            r.keys = self._keys
            r.ops = dict(self._ops)
            r.default = self._default
            return r

    def reduce(self, schema: Schema, records) -> List[List[Any]]:
        names = schema.names()
        kidx = [names.index(k) for k in self.keys]
        vidx = [i for i in range(len(names)) if i not in kidx]
        groups: Dict[tuple, List] = {}
        order: List[tuple] = []
        for r in records:
            key = tuple(r[i] for i in kidx)
            if key not in groups:
                groups[key] = []
                order.append(key)
            groups[key].append(r)
        out = []
        for key in order:
            rows = groups[key]
            row = list(key)
            for i in vidx:
                op = self.ops.get(names[i], self.default)
                row.append(self._OPS[op]([r[i] for r in rows]))
            out.append(row)
        return out

    def output_schema(self, schema: Schema) -> Schema:
        names = schema.names()
        out = Schema()
        cols = []
        for k in self.keys:
            c = schema.columns[names.index(k)]
            cols.append(c)
        for i, n in enumerate(names):
            if n not in self.keys:
                op = self.ops.get(n, self.default)
                if op in ("count", "count_unique"):
                    cols.append((n, "integer", None))
                elif op in ("first", "last", "min", "max"):
                    # value-preserving ops keep the source column type
                    cols.append(schema.columns[i])
                else:
                    cols.append((n, "double", None))
        out.columns = cols
        return out


class Join:
    """Join two record sets on key columns (reference
    ``org.datavec.api.transform.join.Join``): Inner, LeftOuter,
    RightOuter, FullOuter; missing sides fill with None."""

    INNER = "Inner"
    LEFT_OUTER = "LeftOuter"
    RIGHT_OUTER = "RightOuter"
    FULL_OUTER = "FullOuter"

    class Builder:
        def __init__(self, join_type: str = "Inner"):
            valid = (Join.INNER, Join.LEFT_OUTER, Join.RIGHT_OUTER,
                     Join.FULL_OUTER)
            if join_type not in valid:
                raise ValueError(f"join_type {join_type!r} not one of "
                                 f"{valid}")
            self._type = join_type
            self._left = None
            self._right = None
            self._keys = []

        def set_schemas(self, left: Schema, right: Schema):
            self._left, self._right = left, right
            return self

        def set_keys(self, *names: str):
            self._keys = list(names)
            return self

        def build(self) -> "Join":
            j = Join()
            j.join_type = self._type
            j.left_schema = self._left
            j.right_schema = self._right
            j.keys = self._keys
            return j

    def output_schema(self) -> Schema:
        out = Schema()
        out.columns = list(self.left_schema.columns) + [
            c for c in self.right_schema.columns
            if c[0] not in self.keys]
        return out

    def execute(self, left_records, right_records) -> List[List[Any]]:
        ln = self.left_schema.names()
        rn = self.right_schema.names()
        lk = [ln.index(k) for k in self.keys]
        rk = [rn.index(k) for k in self.keys]
        rv = [i for i in range(len(rn)) if i not in rk]
        right_by_key: Dict[tuple, List] = {}
        for r in right_records:
            right_by_key.setdefault(tuple(r[i] for i in rk), []).append(r)
        out = []
        matched_right = set()
        for l in left_records:
            key = tuple(l[i] for i in lk)
            matches = right_by_key.get(key, [])
            if matches:
                matched_right.add(key)
                for r in matches:
                    out.append(list(l) + [r[i] for i in rv])
            elif self.join_type in (self.LEFT_OUTER, self.FULL_OUTER):
                out.append(list(l) + [None] * len(rv))
        if self.join_type in (self.RIGHT_OUTER, self.FULL_OUTER):
            lv = len(ln)
            for key, rows in right_by_key.items():
                if key in matched_right:
                    continue
                for r in rows:
                    row = [None] * lv
                    for li, ri in zip(lk, rk):
                        row[li] = r[ri]
                    out.append(row + [r[i] for i in rv])
        return out
