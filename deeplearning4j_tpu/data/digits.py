"""Real handwritten-digit data, checked into the repo.

Reference analog: ``MnistDataSetIterator``'s role as the canonical
real-image smoke dataset (deeplearning4j-datasets ``MnistDataFetcher``).
This environment has no network egress, so MNIST itself cannot be
downloaded; ``MnistDataSetIterator`` falls back to a *synthetic*
generator and says so (``data/mnist.py``).  To keep at least one REAL
image-classification measurement honest, the UCI Optical Recognition
of Handwritten Digits dataset (1,797 pen-written 8×8 digit images —
real human handwriting, shipped with scikit-learn and re-packed under
``resources/datasets/digits_real.npz``) is bundled here with a
deterministic train/test split.
"""
from __future__ import annotations

from pathlib import Path

import numpy as np

from deeplearning4j_tpu.data.dataset import DataSet
from deeplearning4j_tpu.data.iterators import ListDataSetIterator

_NPZ = Path(__file__).resolve().parents[2] / "resources" / "datasets" / \
    "digits_real.npz"


#: the train/test split is a FIXED property of the dataset — varying it
#: with a user seed would leak test samples into training
_SPLIT_SEED = 7


def load_real_digits(train: bool = True, test_fraction: float = 0.2):
    """Returns ``(features [N,8,8,1] float32 in [0,1], one-hot labels
    [N,10])`` for the deterministic train or test split."""
    with np.load(_NPZ) as z:
        images, labels = z["images"], z["labels"]
    rng = np.random.default_rng(_SPLIT_SEED)
    order = rng.permutation(len(images))
    n_test = int(len(images) * test_fraction)
    idx = order[n_test:] if train else order[:n_test]
    x = (images[idx].astype(np.float32) / 16.0)[..., None]
    y = np.eye(10, dtype=np.float32)[labels[idx]]
    return x, y


class RealDigitsDataSetIterator(ListDataSetIterator):
    """Iterator over the checked-in REAL handwritten digits (the
    network-free stand-in for the reference's MNIST iterator; every
    sample is a genuine human-written digit)."""

    def __init__(self, batch_size: int = 64, train: bool = True,
                 seed: int = 7):
        # seed varies only the epoch shuffle order; the split itself is
        # fixed (see _SPLIT_SEED)
        x, y = load_real_digits(train=train)
        super().__init__(DataSet(x, y), batch_size=batch_size,
                         shuffle=train, seed=seed)
