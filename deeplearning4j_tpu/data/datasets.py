"""Dataset fetcher iterators beyond MNIST — reference:
``org.deeplearning4j.datasets.iterator.impl`` (EmnistDataSetIterator,
CifarDataSetIterator, IrisDataSetIterator, SvhnDataSetIterator;
deeplearning4j-datasets fetchers).

Same loading contract as ``data.mnist``: real files if present under
``~/.deeplearning4j_tpu/<name>/`` (or ``$DL4J_TPU_<NAME>_DIR``),
otherwise a DETERMINISTIC SYNTHETIC set marked ``synthetic=True`` —
separable but not trivial, so models and pipelines exercise end-to-end
without network egress.
"""
from __future__ import annotations

import os
from pathlib import Path
from typing import Optional, Tuple

import numpy as np

from deeplearning4j_tpu.data.dataset import DataSet
from deeplearning4j_tpu.data.iterators import DataSetIterator
from deeplearning4j_tpu.data.mnist import _find_idx, _read_idx


def _synthetic_images(n: int, n_classes: int, hw: int, channels: int,
                      train: bool, seed: int) -> Tuple[np.ndarray,
                                                       np.ndarray]:
    """Class templates (low-frequency patterns per channel) + jitter +
    noise — the mnist.py recipe generalized to any image shape."""
    rng = np.random.default_rng(seed)      # templates shared train/test
    block = max(1, hw // 8)
    grid = -(-hw // block)                 # cover hw, crop the excess
    base = rng.normal(size=(n_classes, grid, grid, channels))
    templates = np.kron(base, np.ones((block, block, 1)))[:, :hw, :hw]
    templates -= templates.min(axis=(1, 2), keepdims=True)
    templates /= templates.max(axis=(1, 2), keepdims=True) + 1e-9

    srng = np.random.default_rng(seed + (1 if train else 2))
    labels = srng.integers(0, n_classes, n)
    imgs = templates[labels]
    shifts = srng.integers(-2, 3, (n, 2))
    out = np.empty((n, hw, hw, channels), np.float32)
    for i in range(n):
        out[i] = np.roll(np.roll(imgs[i], shifts[i, 0], 0),
                         shifts[i, 1], 1)
    out += srng.normal(0, 0.3, out.shape).astype(np.float32)
    return np.clip(out, 0, 1), labels


class _ArrayDataSetIterator(DataSetIterator):
    def __init__(self, x, labels, n_classes, batch_size):
        super().__init__(batch_size)
        self._x = x.astype(np.float32)
        self._y = np.eye(n_classes, dtype=np.float32)[labels]

    def __len__(self):
        return -(-self._x.shape[0] // self.batch_size)

    def __iter__(self):
        b = self.batch_size
        for i in range(0, self._x.shape[0], b):
            yield self._apply_pp(DataSet(self._x[i:i + b],
                                         self._y[i:i + b]))


class EmnistDataSetIterator(_ArrayDataSetIterator):
    """Reference EmnistDataSetIterator. Sets: LETTERS (26), DIGITS (10),
    BALANCED (47), BYCLASS (62) — IDX files under the emnist dir if
    present, synthetic otherwise."""

    SETS = {"LETTERS": 26, "DIGITS": 10, "BALANCED": 47, "BYCLASS": 62}

    @staticmethod
    def _find_emnist(root: Path, set_name: str, train: bool):
        """Standard EMNIST filenames:
        emnist-<set>-{train,test}-images-idx3-ubyte[.gz]."""
        part = "train" if train else "test"
        img = f"emnist-{set_name}-{part}-images-idx3-ubyte"
        lab = f"emnist-{set_name}-{part}-labels-idx1-ubyte"
        for suffix in ("", ".gz"):
            ip, lp = root / (img + suffix), root / (lab + suffix)
            if ip.exists() and lp.exists():
                return ip, lp
        return None

    def __init__(self, dataset: str = "LETTERS", batch_size: int = 64,
                 train: bool = True, seed: int = 123,
                 n_examples: Optional[int] = None,
                 data_dir: Optional[str] = None):
        if dataset.upper() not in self.SETS:
            raise ValueError(f"unknown EMNIST set {dataset!r}; one of "
                             f"{sorted(self.SETS)}")
        n_classes = self.SETS[dataset.upper()]
        root = Path(data_dir or os.environ.get(
            "DL4J_TPU_EMNIST_DIR",
            Path.home() / ".deeplearning4j_tpu" / "emnist"))
        found = (self._find_emnist(root, dataset.lower(), train)
                 or _find_idx(root, train))
        if found:
            imgs = _read_idx(found[0]).astype(np.float32) / 255.0
            labels = _read_idx(found[1]).astype(np.int64)
            # EMNIST LETTERS labels are 1-indexed; re-base to 0
            labels = labels - labels.min()
            x = imgs[..., None]
            self.synthetic = False
        else:
            n = n_examples or (4096 if train else 1024)
            x, labels = _synthetic_images(n, n_classes, 28, 1, train,
                                          seed)
            self.synthetic = True
        if n_examples:
            x, labels = x[:n_examples], labels[:n_examples]
        super().__init__(x, labels, n_classes, batch_size)


class Cifar10DataSetIterator(_ArrayDataSetIterator):
    """Reference CifarDataSetIterator (CIFAR-10): binary batch files
    under the cifar dir if present (data_batch_*.bin / test_batch.bin,
    3072-byte RGB rows), synthetic 32x32x3 otherwise."""

    def __init__(self, batch_size: int = 64, train: bool = True,
                 seed: int = 123, n_examples: Optional[int] = None,
                 data_dir: Optional[str] = None):
        root = Path(data_dir or os.environ.get(
            "DL4J_TPU_CIFAR_DIR",
            Path.home() / ".deeplearning4j_tpu" / "cifar10"))
        files = (sorted(root.glob("data_batch_*.bin")) if train
                 else ([root / "test_batch.bin"]
                       if (root / "test_batch.bin").exists() else []))
        if files:
            xs, ls = [], []
            for f in files:
                raw = np.frombuffer(f.read_bytes(), np.uint8)
                rows = raw.reshape(-1, 3073)
                ls.append(rows[:, 0].astype(np.int64))
                xs.append(rows[:, 1:].reshape(-1, 3, 32, 32)
                          .transpose(0, 2, 3, 1))      # NHWC
            x = np.concatenate(xs).astype(np.float32) / 255.0
            labels = np.concatenate(ls)
            self.synthetic = False
        else:
            n = n_examples or (4096 if train else 1024)
            x, labels = _synthetic_images(n, 10, 32, 3, train, seed)
            self.synthetic = True
        if n_examples:
            x, labels = x[:n_examples], labels[:n_examples]
        super().__init__(x, labels, 10, batch_size)


class SvhnDataSetIterator(Cifar10DataSetIterator):
    """Reference SvhnDataSetIterator — 32x32x3 digits; synthetic unless
    pre-extracted under the svhn dir (same binary layout as cifar)."""

    def __init__(self, batch_size: int = 64, train: bool = True,
                 seed: int = 321, n_examples: Optional[int] = None,
                 data_dir: Optional[str] = None):
        root = data_dir or os.environ.get(
            "DL4J_TPU_SVHN_DIR",
            str(Path.home() / ".deeplearning4j_tpu" / "svhn"))
        super().__init__(batch_size, train, seed, n_examples, root)


class IrisDataSetIterator(_ArrayDataSetIterator):
    """Reference IrisDataSetIterator: 150×4 → 3 classes. Real
    ``iris.data`` CSV if present; otherwise deterministic Gaussian
    clusters with iris-like class statistics."""

    def __init__(self, batch_size: int = 150, n_examples: int = 150,
                 seed: int = 12, data_dir: Optional[str] = None):
        root = Path(data_dir or os.environ.get(
            "DL4J_TPU_IRIS_DIR",
            Path.home() / ".deeplearning4j_tpu" / "iris"))
        csv = root / "iris.data"
        if csv.exists():
            names = {"Iris-setosa": 0, "Iris-versicolor": 1,
                     "Iris-virginica": 2}
            rows = [ln.split(",") for ln in
                    csv.read_text().strip().splitlines() if ln.strip()]
            x = np.asarray([[float(v) for v in r[:4]] for r in rows],
                           np.float32)
            labels = np.asarray([names[r[4].strip()] for r in rows],
                                np.int64)
            self.synthetic = False
        else:
            # class means/scales shaped like the real dataset
            means = np.array([[5.0, 3.4, 1.5, 0.2],
                              [5.9, 2.8, 4.3, 1.3],
                              [6.6, 3.0, 5.6, 2.0]], np.float32)
            scales = np.array([[0.35, 0.38, 0.17, 0.10],
                               [0.52, 0.31, 0.47, 0.20],
                               [0.64, 0.32, 0.55, 0.27]], np.float32)
            rng = np.random.default_rng(seed)
            per = -(-n_examples // 3)           # round up, trim below
            labels = np.repeat(np.arange(3), per)[:n_examples]
            x = (means[labels]
                 + rng.normal(size=(labels.size, 4)).astype(np.float32)
                 * scales[labels])
            # deterministic shuffle: class-sorted batches starve SGD
            perm = rng.permutation(labels.size)
            x, labels = x[perm], labels[perm]
            self.synthetic = True
        super().__init__(x, labels, 3, batch_size)
