"""Normalizers — reference: ``org.nd4j.linalg.dataset.api.preprocessor``:
NormalizerStandardize, NormalizerMinMaxScaler, ImagePreProcessingScaler
(fit / transform / revert + serializable statistics).
"""
from __future__ import annotations

from typing import Optional

import numpy as np

from deeplearning4j_tpu.data.dataset import DataSet


class Normalizer:
    def fit(self, data):
        """Accepts a DataSet or an iterator of DataSets (streaming fit,
        like the reference's fit(DataSetIterator))."""
        raise NotImplementedError

    def transform(self, features: np.ndarray) -> np.ndarray:
        raise NotImplementedError

    def revert(self, features: np.ndarray) -> np.ndarray:
        raise NotImplementedError

    def transform_dataset(self, ds: DataSet) -> DataSet:
        return DataSet(self.transform(ds.features), ds.labels,
                       ds.features_mask, ds.labels_mask)

    # serialization (reference NormalizerSerializer)
    def state_dict(self) -> dict:
        raise NotImplementedError

    def load_state_dict(self, d: dict):
        raise NotImplementedError


def _feature_axes(arr: np.ndarray):
    # statistics per trailing feature/channel axis
    return tuple(range(arr.ndim - 1))


class NormalizerStandardize(Normalizer):
    """Zero-mean unit-variance per feature (reference
    NormalizerStandardize; streaming via Welford-style accumulation)."""

    def __init__(self):
        self.mean: Optional[np.ndarray] = None
        self.std: Optional[np.ndarray] = None

    def fit(self, data):
        if isinstance(data, DataSet):
            datasets = [data]
        else:
            datasets = data
        n = 0
        s = None
        s2 = None
        for ds in datasets:
            f = ds.features.astype(np.float64)
            flat = f.reshape(-1, f.shape[-1])
            if s is None:
                s = flat.sum(axis=0)
                s2 = (flat ** 2).sum(axis=0)
            else:
                s += flat.sum(axis=0)
                s2 += (flat ** 2).sum(axis=0)
            n += flat.shape[0]
        self.mean = (s / n).astype(np.float32)
        var = s2 / n - (s / n) ** 2
        self.std = np.sqrt(np.maximum(var, 1e-12)).astype(np.float32)
        return self

    def transform(self, features):
        return (features - self.mean) / self.std

    def revert(self, features):
        return features * self.std + self.mean

    def state_dict(self):
        return {"type": "standardize", "mean": self.mean.tolist(),
                "std": self.std.tolist()}

    def load_state_dict(self, d):
        self.mean = np.asarray(d["mean"], np.float32)
        self.std = np.asarray(d["std"], np.float32)
        return self


class NormalizerMinMaxScaler(Normalizer):
    """Scale to [lo, hi] per feature (reference NormalizerMinMaxScaler)."""

    def __init__(self, lo: float = 0.0, hi: float = 1.0):
        self.lo, self.hi = lo, hi
        self.min: Optional[np.ndarray] = None
        self.max: Optional[np.ndarray] = None

    def fit(self, data):
        datasets = [data] if isinstance(data, DataSet) else data
        mn = mx = None
        for ds in datasets:
            flat = ds.features.reshape(-1, ds.features.shape[-1])
            m1, m2 = flat.min(axis=0), flat.max(axis=0)
            mn = m1 if mn is None else np.minimum(mn, m1)
            mx = m2 if mx is None else np.maximum(mx, m2)
        self.min, self.max = mn, mx
        return self

    def transform(self, features):
        rng = np.maximum(self.max - self.min, 1e-12)
        unit = (features - self.min) / rng
        return unit * (self.hi - self.lo) + self.lo

    def revert(self, features):
        rng = np.maximum(self.max - self.min, 1e-12)
        return (features - self.lo) / (self.hi - self.lo) * rng + self.min

    def state_dict(self):
        return {"type": "minmax", "lo": self.lo, "hi": self.hi,
                "min": self.min.tolist(), "max": self.max.tolist()}

    def load_state_dict(self, d):
        self.lo, self.hi = d["lo"], d["hi"]
        self.min = np.asarray(d["min"], np.float32)
        self.max = np.asarray(d["max"], np.float32)
        return self


class ImagePreProcessingScaler(Normalizer):
    """uint8 [0,255] → [lo,hi] (reference ImagePreProcessingScaler);
    no fit needed."""

    def __init__(self, lo: float = 0.0, hi: float = 1.0):
        self.lo, self.hi = lo, hi

    def fit(self, data):
        return self

    def transform(self, features):
        return features.astype(np.float32) / 255.0 * (self.hi - self.lo) \
            + self.lo

    def revert(self, features):
        return (features - self.lo) / (self.hi - self.lo) * 255.0

    def state_dict(self):
        return {"type": "image", "lo": self.lo, "hi": self.hi}

    def load_state_dict(self, d):
        self.lo, self.hi = d["lo"], d["hi"]
        return self


def normalizer_from_state(d: dict) -> Normalizer:
    t = d["type"]
    n = {"standardize": NormalizerStandardize,
         "minmax": NormalizerMinMaxScaler,
         "image": ImagePreProcessingScaler}[t]()
    return n.load_state_dict(d)
