"""DataSet containers — reference: ``org.nd4j.linalg.dataset.DataSet`` /
``MultiDataSet`` (features/labels + masks, batching, shuffling, split).

Host-side numpy until the jitted step; device transfer happens at the
jit boundary (one H2D per batch — reference instead pins per-device
buffers via AtomicAllocator).
"""
from __future__ import annotations

from typing import List, Optional, Sequence, Tuple

import numpy as np


class DataSet:
    def __init__(self, features, labels=None, features_mask=None,
                 labels_mask=None):
        self.features = np.asarray(features)
        self.labels = None if labels is None else np.asarray(labels)
        self.features_mask = (None if features_mask is None
                              else np.asarray(features_mask))
        self.labels_mask = (None if labels_mask is None
                            else np.asarray(labels_mask))

    def num_examples(self) -> int:
        return self.features.shape[0]

    def shuffle(self, seed: Optional[int] = None) -> "DataSet":
        rng = np.random.default_rng(seed)
        idx = rng.permutation(self.num_examples())
        return self._take(idx)

    def _take(self, idx) -> "DataSet":
        return DataSet(
            self.features[idx], self.labels[idx],
            None if self.features_mask is None else self.features_mask[idx],
            None if self.labels_mask is None else self.labels_mask[idx])

    def split_test_and_train(self, n_train: int
                             ) -> Tuple["DataSet", "DataSet"]:
        return (self._take(slice(0, n_train)),
                self._take(slice(n_train, None)))

    def batch_by(self, batch_size: int) -> List["DataSet"]:
        return [self._take(slice(i, i + batch_size))
                for i in range(0, self.num_examples(), batch_size)]

    def sample(self, n: int, seed: Optional[int] = None) -> "DataSet":
        rng = np.random.default_rng(seed)
        return self._take(rng.choice(self.num_examples(), n,
                                     replace=False))

    @staticmethod
    def merge(datasets: Sequence["DataSet"]) -> "DataSet":
        return DataSet(
            np.concatenate([d.features for d in datasets]),
            np.concatenate([d.labels for d in datasets]),
            None if datasets[0].features_mask is None else
            np.concatenate([d.features_mask for d in datasets]),
            None if datasets[0].labels_mask is None else
            np.concatenate([d.labels_mask for d in datasets]))

    def __repr__(self):
        return (f"DataSet(features{self.features.shape}, "
                f"labels{self.labels.shape})")


class MultiDataSet:
    """Multiple feature/label arrays (reference
    org.nd4j.linalg.dataset.MultiDataSet) for ComputationGraph."""

    def __init__(self, features: Sequence, labels: Sequence,
                 features_masks=None, labels_masks=None):
        self.features = [np.asarray(f) for f in features]
        self.labels = [np.asarray(l) for l in labels]
        self.features_masks = features_masks
        self.labels_masks = labels_masks

    def num_examples(self) -> int:
        return self.features[0].shape[0]
