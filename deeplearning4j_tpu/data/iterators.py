"""DataSet iterators — reference:
``org.nd4j.linalg.dataset.api.iterator.DataSetIterator`` SPI +
``AsyncDataSetIterator`` (background prefetch thread feeding the train
loop, SURVEY §3.2 fitHelper).
"""
from __future__ import annotations

import queue
import threading
from typing import Iterator, List, Optional

import numpy as np

from deeplearning4j_tpu import obs
from deeplearning4j_tpu.data.dataset import DataSet
from deeplearning4j_tpu.resilience import faults


class DataSetIterator:
    """Base iterator; subclasses implement ``_build()`` returning a list
    of DataSets, or override __iter__ for streaming."""

    def __init__(self, batch_size: int = 32):
        self.batch_size = batch_size
        self.pre_processor = None  # normalizer hook (reference name)

    def reset(self):
        pass

    def set_pre_processor(self, p):
        self.pre_processor = p

    def _apply_pp(self, ds: DataSet) -> DataSet:
        # site: iterator next — every batch any subclass yields passes
        # through here (resilience/faults.py; off path is one branch)
        faults.inject("iterator")
        if self.pre_processor is not None:
            ds = self.pre_processor.transform_dataset(ds)
        return ds

    def __iter__(self) -> Iterator[DataSet]:
        raise NotImplementedError


class ListDataSetIterator(DataSetIterator):
    """Iterates a pre-batched or single DataSet (reference
    ListDataSetIterator)."""

    def __init__(self, data, batch_size: int = 32, shuffle: bool = False,
                 seed: int = 0):
        super().__init__(batch_size)
        self._data = data
        self.shuffle = shuffle
        self.seed = seed
        self._epoch = 0

    def __len__(self):
        if isinstance(self._data, DataSet):
            n = self._data.features.shape[0]
            return -(-n // self.batch_size)
        return len(self._data)

    def __iter__(self):
        data = self._data
        if isinstance(data, DataSet):
            if self.shuffle:
                data = data.shuffle(self.seed + self._epoch)
                self._epoch += 1
            for b in data.batch_by(self.batch_size):
                yield self._apply_pp(b)
        else:
            for b in data:
                yield self._apply_pp(b)


class AsyncDataSetIterator(DataSetIterator):
    """Background-thread prefetch wrapper (reference
    AsyncDataSetIterator): overlaps host ETL with device compute. On TPU
    the jitted step runs async anyway (dispatch returns immediately), so
    a small queue suffices to hide ETL latency."""

    def __init__(self, base, queue_size: int = 4):
        # base may be any iterable of DataSets (list, sharded view, …);
        # batch_size is None when the base doesn't declare one — don't
        # fabricate a number for downstream consumers
        super().__init__(getattr(base, "batch_size", None))
        self.base = base
        self.queue_size = queue_size
        #: cumulative seconds the consumer blocked waiting on ETL
        #: (reference PerformanceListener's ETL-wait metric)
        self.etl_wait_seconds = 0.0

    def __len__(self):
        return len(self.base)

    def reset(self):
        if hasattr(self.base, "reset"):
            self.base.reset()

    def __iter__(self):
        from deeplearning4j_tpu.native import RingQueue

        q = RingQueue(capacity=self.queue_size)
        err: List[BaseException] = []

        def worker():
            obs.trace.set_thread_name("etl-prefetch")
            try:
                for ds in self.base:
                    if not q.put(ds):      # consumer closed early
                        return
            except BaseException as e:  # propagate to consumer
                err.append(e)
            finally:
                q.close()

        t = threading.Thread(target=worker, daemon=True)
        t.start()
        try:
            while True:
                t0 = obs.now()
                try:
                    item = q.get()
                except StopIteration:
                    break
                finally:
                    dt = obs.now() - t0
                    self.etl_wait_seconds += dt
                    obs.metrics.PREFETCH_WAIT.inc(dt)
                    obs.metrics.PREFETCH_DEPTH.set(q.qsize())
                    if obs.trace.enabled():
                        obs.trace.add_span("AsyncDataSetIterator/wait",
                                           t0, t0 + dt)
                yield item
        finally:
            q.close()                      # unblock producer on break
            t.join()
        if err:
            raise err[0]


class IteratorDataSetIterator(DataSetIterator):
    """Wraps any python iterable of (x, y) tuples into DataSet batches."""

    def __init__(self, iterable, batch_size: int = 32):
        super().__init__(batch_size)
        self._iterable = iterable

    def __iter__(self):
        xs, ys = [], []
        for x, y in self._iterable:
            xs.append(x)
            ys.append(y)
            if len(xs) == self.batch_size:
                yield self._apply_pp(DataSet(np.stack(xs), np.stack(ys)))
                xs, ys = [], []
        if xs:
            yield self._apply_pp(DataSet(np.stack(xs), np.stack(ys)))


class TfDataSetIterator(DataSetIterator):
    """Adapter: a ``tf.data.Dataset`` drives the training loop as a
    DataSetIterator (SURVEY §7: RecordReader/TransformProcess API over
    tf.data). Elements may be ``(features, labels)`` tuples or dicts
    with 'features'/'labels' keys; tensors convert to numpy zero-copy
    where tf allows. Re-iterating the dataset is tf.data's reset
    semantics, so epochs restart cleanly (shuffle/reshuffle is the
    dataset's own configuration).

    ``batch_size=None`` (default): the dataset is already batched and
    consumed as-is. A given ``batch_size`` applies
    ``dataset.batch(batch_size)`` — the sibling iterators' contract,
    for per-example datasets.
    """

    def __init__(self, dataset, batch_size: Optional[int] = None):
        super().__init__(batch_size)
        self.dataset = (dataset if batch_size is None
                        else dataset.batch(batch_size))

    def __len__(self):
        n = int(self.dataset.cardinality())
        if n < 0:                            # INFINITE or UNKNOWN
            raise TypeError("tf.data cardinality unknown")
        return n

    def __iter__(self):
        for el in self.dataset.as_numpy_iterator():
            if isinstance(el, dict):
                x, y = el["features"], el.get("labels")
            else:
                x, y = el if isinstance(el, (tuple, list)) else (el, None)
            yield self._apply_pp(DataSet(np.asarray(x),
                                         None if y is None
                                         else np.asarray(y)))


class BucketedSequenceIterator(DataSetIterator):
    """Pads each sequence batch's time axis UP to a fixed bucket length.

    TPU-native necessity with no reference equivalent (SURVEY §7 hard
    part (c)): the reference's eager kernels take any [B,T,F]; here
    every distinct T triggers a retrace+recompile of the jitted train
    step. Snapping T to a small bucket set (e.g. 32/64/128/256) bounds
    the number of compiled programs while masks keep the math exact —
    the standard variable-length recipe for XLA.
    """

    def __init__(self, base, buckets=(32, 64, 128, 256)):
        super().__init__(getattr(base, "batch_size", None))
        self.base = base
        self.buckets = sorted(buckets)

    def reset(self):
        if hasattr(self.base, "reset"):
            self.base.reset()

    def _bucket_for(self, t: int) -> int:
        for b in self.buckets:
            if t <= b:
                return b
        return t                       # beyond the largest: leave as-is

    def __iter__(self):
        from deeplearning4j_tpu.data.dataset import DataSet
        for ds in self.base:
            t = ds.features.shape[1]
            tb = self._bucket_for(t)
            if tb == t:
                yield ds
                continue
            pad = tb - t

            def pad_time(a, pad=pad):
                if a is None:
                    return None
                width = [(0, 0)] * a.ndim
                width[1] = (0, pad)
                return np.pad(np.asarray(a), width)

            fm = ds.features_mask
            if fm is None:            # padding NEEDS a mask to be exact
                fm = np.ones(ds.features.shape[:2], np.float32)
            seq_labels = (ds.labels is not None
                          and ds.labels.ndim >= 3)
            lm = ds.labels_mask
            if lm is None and seq_labels:
                lm = np.ones(ds.labels.shape[:2], np.float32)
            yield DataSet(pad_time(ds.features),
                          pad_time(ds.labels) if seq_labels
                          else ds.labels,
                          features_mask=pad_time(fm),
                          # per-sequence labels keep their mask as-is:
                          # only sequence labels pad along time
                          labels_mask=pad_time(lm) if seq_labels
                          else lm)
