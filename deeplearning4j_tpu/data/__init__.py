"""Data pipeline — reference: ``org.nd4j.linalg.dataset`` (DataSet,
iterators, normalizers) + datavec ETL (``data.records`` / ``transform``).
"""
from deeplearning4j_tpu.data.dataset import DataSet, MultiDataSet
from deeplearning4j_tpu.data.iterators import (
    DataSetIterator, ListDataSetIterator, AsyncDataSetIterator,
)
from deeplearning4j_tpu.data.normalizers import (
    NormalizerStandardize, NormalizerMinMaxScaler,
    ImagePreProcessingScaler,
)

__all__ = [
    "DataSet", "MultiDataSet", "DataSetIterator", "ListDataSetIterator",
    "AsyncDataSetIterator", "NormalizerStandardize",
    "NormalizerMinMaxScaler", "ImagePreProcessingScaler",
]
