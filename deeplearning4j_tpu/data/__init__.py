"""Data pipeline — reference: ``org.nd4j.linalg.dataset`` (DataSet,
iterators, normalizers) + datavec ETL (``data.records`` / ``transform``).
"""
from deeplearning4j_tpu.data.dataset import DataSet, MultiDataSet
from deeplearning4j_tpu.data.iterators import (
    DataSetIterator, ListDataSetIterator, AsyncDataSetIterator,
    TfDataSetIterator, BucketedSequenceIterator,
)
from deeplearning4j_tpu.data.datasets import (
    EmnistDataSetIterator, Cifar10DataSetIterator, SvhnDataSetIterator,
    IrisDataSetIterator,
)
from deeplearning4j_tpu.data.digits import (RealDigitsDataSetIterator,
                                            load_real_digits)
from deeplearning4j_tpu.data.transform_executor import \
    DistributedTransformExecutor
from deeplearning4j_tpu.data.records import (
    RecordReader, CollectionRecordReader, CSVRecordReader,
    LineRecordReader, RegexLineRecordReader, CSVSequenceRecordReader,
    FileRecordReader, JacksonLineRecordReader, SVMLightRecordReader,
    TransformProcessRecordReader, RecordReaderDataSetIterator,
    SequenceRecordReaderDataSetIterator,
)
from deeplearning4j_tpu.data.normalizers import (
    NormalizerStandardize, NormalizerMinMaxScaler,
    ImagePreProcessingScaler,
)
from deeplearning4j_tpu.data.image import (
    ColorConversionTransform, CropImageTransform, EqualizeHistTransform,
    FlipImageTransform, ImageRecordReader, ImageTransform,
    NativeImageLoader, ParentPathLabelGenerator, PipelineImageTransform,
    ResizeImageTransform, RotateImageTransform, ScaleImageTransform,
)

__all__ = [
    "DataSet", "MultiDataSet", "DataSetIterator", "ListDataSetIterator",
    "TfDataSetIterator", "BucketedSequenceIterator", "EmnistDataSetIterator", "Cifar10DataSetIterator", "SvhnDataSetIterator", "IrisDataSetIterator",
    "RealDigitsDataSetIterator", "load_real_digits",
    "DistributedTransformExecutor",
    "AsyncDataSetIterator", "NormalizerStandardize",
    "NormalizerMinMaxScaler", "ImagePreProcessingScaler",
    "NativeImageLoader", "ImageRecordReader", "ParentPathLabelGenerator",
    "ImageTransform", "ResizeImageTransform", "ScaleImageTransform",
    "CropImageTransform", "FlipImageTransform", "RotateImageTransform",
    "ColorConversionTransform", "EqualizeHistTransform",
    "PipelineImageTransform",
    "RecordReader", "CollectionRecordReader", "CSVRecordReader",
    "LineRecordReader", "RegexLineRecordReader", "CSVSequenceRecordReader",
    "FileRecordReader", "JacksonLineRecordReader", "SVMLightRecordReader",
    "TransformProcessRecordReader", "RecordReaderDataSetIterator",
    "SequenceRecordReaderDataSetIterator",
]
