"""Image ETL — loader, record reader, augmentation transforms.

Reference: ``datavec-data-image`` (SURVEY §2.4):
``org.datavec.image.loader.NativeImageLoader`` (JavaCV decode +
resize), ``org.datavec.image.recordreader.ImageRecordReader`` with
``ParentPathLabelGenerator``, and ``org.datavec.image.transform.*``
(Crop/Flip/Rotate/Resize/Scale/ColorConversion/Pipeline image
transforms) — the ImageNet input pipeline.

TPU-native design: decode/augment stay on host (cv2/PIL — exactly the
reference's JavaCV role); the output is NHWC float32 batches, the
layout TPU convolutions prefer (the reference emits NCHW for cuDNN).
Batches then stream through AsyncDataSetIterator's native ring queue to
overlap ETL with device compute.
"""
from __future__ import annotations

import os
from pathlib import Path
from typing import Callable, List, Optional, Sequence, Tuple, Union

import numpy as np

from deeplearning4j_tpu.data.records import RecordReader


def _cv2():
    import cv2
    return cv2


class NativeImageLoader:
    """Decode + resize to fixed [H, W, C] float32 (reference
    NativeImageLoader(height, width, channels); ``channels_first``
    opts into the reference's NCHW layout)."""

    def __init__(self, height: int, width: int, channels: int = 3,
                 channels_first: bool = False):
        self.height, self.width = height, width
        self.channels = channels
        self.channels_first = channels_first

    def _decode(self, src) -> np.ndarray:
        cv2 = _cv2()
        if isinstance(src, (str, os.PathLike)):
            flag = (cv2.IMREAD_GRAYSCALE if self.channels == 1
                    else cv2.IMREAD_COLOR)
            img = cv2.imread(str(src), flag)
            if img is None:
                raise IOError(f"cannot decode image: {src}")
            if self.channels == 3:
                img = cv2.cvtColor(img, cv2.COLOR_BGR2RGB)
        else:
            img = np.asarray(src)
        if img.ndim == 2:
            img = img[..., None]
        if img.shape[-1] != self.channels:
            if self.channels == 1:
                img = cv2.cvtColor(img, cv2.COLOR_RGB2GRAY)[..., None]
            elif self.channels == 3 and img.shape[-1] == 1:
                img = np.repeat(img, 3, axis=-1)
            else:
                raise ValueError(
                    f"cannot convert {img.shape[-1]} channels to "
                    f"{self.channels}")
        return img

    def as_matrix(self, src) -> np.ndarray:
        """One image → [1, H, W, C] (or [1, C, H, W]) float32."""
        x = self.load(src)[None]
        return x

    def load(self, src) -> np.ndarray:
        cv2 = _cv2()
        img = self._decode(src)
        if img.shape[:2] != (self.height, self.width):
            img = cv2.resize(img, (self.width, self.height),
                             interpolation=cv2.INTER_AREA)
            if img.ndim == 2:
                img = img[..., None]
        out = img.astype(np.float32)
        if self.channels_first:
            out = np.transpose(out, (2, 0, 1))
        return out


# ---------------------------------------------------------------------------
# Transforms (reference org.datavec.image.transform.ImageTransform SPI)
# ---------------------------------------------------------------------------

class ImageTransform:
    """Base augmentation op: HWC uint8/float in, HWC out. Random
    transforms draw from the generator passed to ``transform`` so a
    pipeline's sampling is reproducible."""

    def transform(self, img: np.ndarray, rng=None) -> np.ndarray:
        raise NotImplementedError

    def __call__(self, img, rng=None):
        return self.transform(
            img, rng if rng is not None else np.random.default_rng())


class ResizeImageTransform(ImageTransform):
    def __init__(self, width: int, height: int):
        self.width, self.height = width, height

    def transform(self, img, rng=None):
        cv2 = _cv2()
        out = cv2.resize(img, (self.width, self.height),
                         interpolation=cv2.INTER_AREA)
        return out[..., None] if out.ndim == 2 else out


class ScaleImageTransform(ImageTransform):
    """Random uniform rescale by ±delta (reference
    ScaleImageTransform(delta))."""

    def __init__(self, delta: float):
        self.delta = delta

    def transform(self, img, rng=None):
        cv2 = _cv2()
        s = 1.0 + float(rng.uniform(-self.delta, self.delta))
        h, w = img.shape[:2]
        out = cv2.resize(img, (max(1, int(w * s)), max(1, int(h * s))),
                         interpolation=cv2.INTER_LINEAR)
        return out[..., None] if out.ndim == 2 else out


class CropImageTransform(ImageTransform):
    """Random crop up to crop_{top,left,bottom,right} pixels
    (reference CropImageTransform)."""

    def __init__(self, crop: int):
        self.crop = crop

    def transform(self, img, rng=None):
        h, w = img.shape[:2]
        t = int(rng.integers(0, self.crop + 1))
        l = int(rng.integers(0, self.crop + 1))
        b = int(rng.integers(0, self.crop + 1))
        r = int(rng.integers(0, self.crop + 1))
        return img[t:h - b if b else h, l:w - r if r else w]


class FlipImageTransform(ImageTransform):
    """mode: 0 vertical, 1 horizontal, -1 both, None random choice
    (reference FlipImageTransform's OpenCV flip codes)."""

    def __init__(self, mode: Optional[int] = None):
        self.mode = mode

    def transform(self, img, rng=None):
        mode = (self.mode if self.mode is not None
                else int(rng.integers(-1, 2)))
        cv2 = _cv2()
        out = cv2.flip(img, mode)
        return out[..., None] if out.ndim == 2 else out


class RotateImageTransform(ImageTransform):
    """Random rotation in ±angle degrees about the center (reference
    RotateImageTransform)."""

    def __init__(self, angle: float):
        self.angle = angle

    def transform(self, img, rng=None):
        cv2 = _cv2()
        a = float(rng.uniform(-self.angle, self.angle))
        h, w = img.shape[:2]
        m = cv2.getRotationMatrix2D((w / 2, h / 2), a, 1.0)
        out = cv2.warpAffine(img, m, (w, h))
        return out[..., None] if out.ndim == 2 else out


class ColorConversionTransform(ImageTransform):
    """Color-space conversion by cv2 code name, e.g. 'RGB2GRAY',
    'RGB2HSV' (reference ColorConversionTransform wraps cvtColor)."""

    def __init__(self, code: str):
        self.code = code

    def transform(self, img, rng=None):
        cv2 = _cv2()
        out = cv2.cvtColor(img, getattr(cv2, f"COLOR_{self.code}"))
        return out[..., None] if out.ndim == 2 else out


class EqualizeHistTransform(ImageTransform):
    """Histogram equalization per channel (reference
    EqualizeHistTransform)."""

    def transform(self, img, rng=None):
        cv2 = _cv2()
        u8 = img.astype(np.uint8)
        chans = [cv2.equalizeHist(u8[..., c])
                 for c in range(u8.shape[-1])]
        return np.stack(chans, axis=-1)


class PipelineImageTransform(ImageTransform):
    """Sequential pipeline; each stage applies with probability p
    (reference PipelineImageTransform(List<Pair<transform, prob>>))."""

    def __init__(self, steps: Sequence[Union[ImageTransform,
                                             Tuple[ImageTransform,
                                                   float]]],
                 shuffle: bool = False):
        self.steps = [(s, 1.0) if isinstance(s, ImageTransform) else s
                      for s in steps]
        self.shuffle = shuffle

    def transform(self, img, rng=None):
        steps = list(self.steps)
        if self.shuffle:
            rng.shuffle(steps)
        for t, p in steps:
            if p >= 1.0 or rng.random() < p:
                img = t.transform(img, rng)
        return img


# ---------------------------------------------------------------------------
# Record reader
# ---------------------------------------------------------------------------

class ParentPathLabelGenerator:
    """Label = parent directory name (reference
    ParentPathLabelGenerator)."""

    def get_label(self, path: str) -> str:
        return Path(path).parent.name


class ImageRecordReader(RecordReader):
    """Walks a directory tree of images; each record is
    ``[image_array, label_index]`` (reference ImageRecordReader yields
    [NDArrayWritable, IntWritable]). Labels discovered from parent dirs
    (sorted, stable) unless an explicit list is given."""

    EXTS = {".png", ".jpg", ".jpeg", ".bmp", ".ppm", ".pgm"}

    def __init__(self, height: int, width: int, channels: int = 3,
                 label_generator=None,
                 labels: Optional[List[str]] = None,
                 transform: Optional[ImageTransform] = None,
                 channels_first: bool = False, seed: int = 0,
                 workers: int = 0):
        self.loader = NativeImageLoader(height, width, channels,
                                        channels_first)
        self.label_generator = label_generator \
            or ParentPathLabelGenerator()
        self.labels = list(labels) if labels else None
        self.transform = transform
        self.seed = seed
        #: decode/augment parallelism: >1 maps the per-file work over
        #: a thread pool (cv2 releases the GIL, so this scales on
        #: multi-core hosts — the BASELINE.md ETL sizing says ~10
        #: cores feed one v5e chip at full ResNet-50 rate), with
        #: bounded read-ahead and ORDERED yield. Augmentation rng is
        #: per-file (seeded by (seed, epoch, index)) so output is
        #: deterministic regardless of thread timing while each epoch
        #: still draws fresh augments.
        self.workers = workers
        self._rng = np.random.default_rng(seed)
        self._epoch = 0
        self._files: List[str] = []
        self._pool = None          # one executor per reader (lazy)
        self._inflight: set = set()

    def initialize(self, root: str) -> "ImageRecordReader":
        """Scan root/<label>/ for images (reference
        initialize(FileSplit))."""
        files = sorted(
            str(p) for p in Path(root).rglob("*")
            if p.suffix.lower() in self.EXTS)
        if not files:
            raise FileNotFoundError(f"no images under {root}")
        self._files = files
        if self.labels is None:
            self.labels = sorted(
                {self.label_generator.get_label(f) for f in files})
        return self

    def num_labels(self) -> int:
        return len(self.labels or [])

    def _load(self, f: str, rng) -> list:
        """Per-file decode → augment → resize → label (shared by the
        sequential and thread-pool paths)."""
        img = self.loader._decode(f)
        if self.transform is not None:
            img = self.transform.transform(img, rng)
        cv2 = _cv2()
        if img.shape[:2] != (self.loader.height, self.loader.width):
            img = cv2.resize(
                img, (self.loader.width, self.loader.height),
                interpolation=cv2.INTER_AREA)
            if img.ndim == 2:
                img = img[..., None]
        x = img.astype(np.float32)
        if self.loader.channels_first:
            x = np.transpose(x, (2, 0, 1))
        lab = self.labels.index(self.label_generator.get_label(f))
        return [x, lab]

    def _executor(self):
        """ONE pool per reader, not per epoch: a training run iterates
        this reader epochs×, and thread create/teardown per ``__iter__``
        is pure churn (plus a warm pool keeps cv2's per-thread state
        hot). Lazy so workers<=1 readers never spin threads."""
        if self._pool is None:
            from concurrent.futures import ThreadPoolExecutor
            self._pool = ThreadPoolExecutor(self.workers)
        return self._pool

    def __iter__(self):
        if self.workers and self.workers > 1:
            # ordered parallel decode with a bounded in-flight window
            # (2× workers) so memory stays O(workers), not O(dataset).
            # Augment rng is keyed (seed, epoch, index): deterministic
            # under any thread timing, but fresh per epoch like the
            # sequential stream
            from collections import deque

            epoch = self._epoch
            self._epoch += 1

            def task(i, f):
                return self._load(
                    f, np.random.default_rng([self.seed, epoch, i]))

            ex = self._executor()
            window: deque = deque()
            try:
                for i, f in enumerate(self._files):
                    fut = ex.submit(task, i, f)
                    window.append(fut)
                    self._inflight.add(fut)
                    # self-prune on completion (late-bound so close()
                    # can swap the set out from under old epochs): an
                    # abandoned epoch must not pin decoded arrays in
                    # _inflight for the reader's lifetime
                    fut.add_done_callback(
                        lambda f: self._inflight.discard(f))
                    if len(window) >= 2 * self.workers:
                        yield window.popleft().result()
                while window:
                    yield window.popleft().result()
            finally:
                # a consumer abandoning the generator mid-epoch must
                # not leave a dead epoch decoding: cancel what hasn't
                # started (running decodes finish into _inflight and
                # are joined by close()); the pool itself stays up for
                # the next epoch
                for fut in window:
                    fut.cancel()
            return
        for f in self._files:
            yield self._load(f, self._rng)

    def reset(self):
        pass

    def close(self):
        """Join in-flight decode futures and tear the pool down — an
        abandoned partial epoch must not keep worker threads churning
        past the reader's lifetime. Idempotent; the reader is reusable
        after close (the pool respawns lazily)."""
        import concurrent.futures
        # swap first: done-callbacks resolve self._inflight late, so
        # they prune the fresh set; drain the old one with atomic
        # pop()s — a straggler callback may still hold a reference to
        # it, and list(set) can blow up mid-iteration on a concurrent
        # discard
        inflight, self._inflight = self._inflight, set()
        futs = []
        while inflight:
            try:
                futs.append(inflight.pop())
            except KeyError:
                break
        for fut in futs:
            fut.cancel()
        if futs:
            concurrent.futures.wait(futs)
        if self._pool is not None:
            self._pool.shutdown(wait=True)
            self._pool = None

    def __del__(self):
        try:
            self.close()
        except Exception:
            pass


class BatchImageETL:
    """Batched decode-to-device ETL tail (reference NativeImageLoader +
    ImagePreProcessingScaler fused): decoded u8 [N,H,W,C] pixels →
    normalized f32 NHWC with per-image random crop + horizontal flip.
    The per-pixel loop runs in the threaded native runtime
    (native/dl4j_tpu_native.cpp img_batch_normalize_u8) when available,
    with an identical numpy fallback."""

    def __init__(self, out_hw=None, mean=None, std=None,
                 random_crop: bool = False, random_flip: bool = False,
                 seed: int = 0, n_threads: int = 0):
        self.out_hw = out_hw
        self.mean = mean
        self.std = std
        self.random_crop = random_crop
        self.random_flip = random_flip
        self.n_threads = n_threads
        self._rng = np.random.default_rng(seed)

    def __call__(self, batch_u8: np.ndarray,
                 train: bool = True) -> np.ndarray:
        from deeplearning4j_tpu import native
        n, h, w, _ = batch_u8.shape
        oh, ow = self.out_hw or (h, w)
        crops = flips = None
        if train and self.random_crop and (oh < h or ow < w):
            crops = np.stack(
                [self._rng.integers(0, h - oh + 1, n),
                 self._rng.integers(0, w - ow + 1, n)], 1)
        elif oh < h or ow < w:           # eval: center crop
            crops = np.tile([[(h - oh) // 2, (w - ow) // 2]], (n, 1))
        if train and self.random_flip:
            flips = self._rng.integers(0, 2, n).astype(np.uint8)
        return native.img_batch_normalize(
            batch_u8, out_hw=(oh, ow), mean=self.mean, std=self.std,
            crop_offsets=crops, flips=flips, n_threads=self.n_threads)
