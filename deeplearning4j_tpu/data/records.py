"""Record readers — reference: datavec-api
``org.datavec.api.records.reader.RecordReader`` SPI and impls
(CSVRecordReader, LineRecordReader, RegexLineRecordReader,
CSVSequenceRecordReader, CollectionRecordReader) + ``Writable`` types.

Writables collapse to plain Python/numpy values (str/float/int/ndarray);
records are lists of values; sequence records are lists of records.
"""
from __future__ import annotations

import csv
import io
import re
from pathlib import Path
from typing import Any, Iterator, List, Optional, Sequence

import numpy as np


class RecordReader:
    """Iterable over records (list of values)."""

    def __iter__(self) -> Iterator[List[Any]]:
        raise NotImplementedError

    def reset(self):
        pass


def _as_path(path_or_text) -> Optional[Path]:
    """Path if the argument names an existing file, else None (inline
    text). Long/invalid strings (inline CSV blobs) are text, not an
    OSError from os.stat."""
    try:
        p = Path(str(path_or_text))
        return p if p.exists() else None
    except (OSError, ValueError):
        return None


class CollectionRecordReader(RecordReader):
    """In-memory records (reference CollectionRecordReader)."""

    def __init__(self, records: Sequence[Sequence[Any]]):
        self._records = [list(r) for r in records]

    def __iter__(self):
        return iter(self._records)


class CSVRecordReader(RecordReader):
    """CSV file/str reader (reference CSVRecordReader): skip lines,
    custom delimiter, numeric auto-parse."""

    def __init__(self, path_or_text, skip_lines: int = 0,
                 delimiter: str = ",", parse_numbers: bool = True):
        self.path_or_text = path_or_text
        self.skip_lines = skip_lines
        self.delimiter = delimiter
        self.parse_numbers = parse_numbers

    def _lines(self):
        p = _as_path(self.path_or_text)
        if p is not None:
            with open(p, newline="") as f:
                yield from f
        else:
            yield from io.StringIO(str(self.path_or_text))

    @staticmethod
    def _parse(v: str):
        v = v.strip()
        try:
            f = float(v)
            return int(f) if f.is_integer() and "." not in v and \
                "e" not in v.lower() else f
        except ValueError:
            return v

    def __iter__(self):
        reader = csv.reader(self._lines(), delimiter=self.delimiter)
        for i, row in enumerate(reader):
            if i < self.skip_lines or not row:
                continue
            yield ([self._parse(v) for v in row] if self.parse_numbers
                   else [v.strip() for v in row])

    def to_matrix(self):
        """Whole-file numeric fast path → [rows, cols] float32, using
        the native CSV parser (native/dl4j_tpu_native.cpp) when built.
        Returns None if the data isn't purely numeric/rectangular —
        callers then fall back to the row iterator."""
        from deeplearning4j_tpu import native as _native

        p = _as_path(self.path_or_text)
        if p is not None:
            data = p.read_bytes()
        else:
            data = str(self.path_or_text).encode()
        return _native.csv_parse_f32(data, self.delimiter,
                                     self.skip_lines)


class LineRecordReader(RecordReader):
    """One record per line (reference LineRecordReader)."""

    def __init__(self, path_or_text):
        self.path_or_text = path_or_text

    def __iter__(self):
        p = _as_path(self.path_or_text)
        lines = (open(p).read() if p is not None
                 else str(self.path_or_text)).splitlines()
        for line in lines:
            yield [line]


class RegexLineRecordReader(RecordReader):
    """Regex-group splitting per line (reference RegexLineRecordReader)."""

    def __init__(self, path_or_text, regex: str, skip_lines: int = 0):
        self.base = LineRecordReader(path_or_text)
        self.pattern = re.compile(regex)
        self.skip_lines = skip_lines

    def __iter__(self):
        for i, (line,) in enumerate(self.base):
            if i < self.skip_lines:
                continue
            m = self.pattern.match(line)
            if m is None:
                raise ValueError(f"line {i} does not match: {line!r}")
            yield list(m.groups())


class CSVSequenceRecordReader(RecordReader):
    """One sequence per file/blob; steps are CSV rows (reference
    CSVSequenceRecordReader)."""

    def __init__(self, sources: Sequence, skip_lines: int = 0,
                 delimiter: str = ","):
        self.sources = sources
        self.skip_lines = skip_lines
        self.delimiter = delimiter

    def __iter__(self):
        for src in self.sources:
            rr = CSVRecordReader(src, self.skip_lines, self.delimiter)
            yield [rec for rec in rr]


class FileRecordReader(RecordReader):
    """Whole file content per record (reference FileRecordReader)."""

    def __init__(self, paths: Sequence):
        self.paths = list(paths)

    def __iter__(self):
        for p in self.paths:
            yield [Path(p).read_text()]


class JacksonLineRecordReader(RecordReader):
    """One JSON object per line, selected fields in order (reference
    JacksonLineRecordReader over a FieldSelection)."""

    def __init__(self, path_or_text, fields: Sequence[str]):
        self.base = LineRecordReader(path_or_text)
        self.fields = list(fields)

    def __iter__(self):
        import json
        for (line,) in self.base:
            if not line.strip():
                continue
            obj = json.loads(line)
            yield [obj.get(f) for f in self.fields]


class SVMLightRecordReader(RecordReader):
    """SVMLight/LibSVM sparse format ``label idx:val ...`` → dense row +
    label (reference SVMLightRecordReader). 1-based indices by default;
    ``zero_based`` for LibSVM-style 0-based files."""

    def __init__(self, path_or_text, num_features: int,
                 zero_based: bool = False):
        self.path_or_text = path_or_text
        self.num_features = num_features
        self.zero_based = zero_based

    def __iter__(self):
        p = _as_path(self.path_or_text)
        text = open(p).read() if p is not None else str(self.path_or_text)
        for line in text.splitlines():
            line = line.split("#")[0].strip()
            if not line:
                continue
            parts = line.split()
            label = float(parts[0])
            row = np.zeros(self.num_features, np.float32)
            for tok in parts[1:]:
                idx, val = tok.split(":")
                i = int(idx) - (0 if self.zero_based else 1)
                row[i] = float(val)
            yield list(row) + [int(label) if label.is_integer()
                               else label]


class TransformProcessRecordReader(RecordReader):
    """Applies a TransformProcess to each record of an underlying
    reader (reference TransformProcessRecordReader)."""

    def __init__(self, reader: RecordReader, transform_process):
        self.reader = reader
        self.tp = transform_process

    def __iter__(self):
        out = self.tp.execute(list(self.reader))
        return iter(out)

    def reset(self):
        self.reader.reset()


class RecordReaderDataSetIterator:
    """Bridges a RecordReader into DataSet batches (reference
    org.deeplearning4j.datasets.datavec.RecordReaderDataSetIterator):
    label column index + one-hot for classification, or regression mode.
    """

    def __init__(self, reader: RecordReader, batch_size: int,
                 label_index: int, num_classes: Optional[int] = None,
                 regression: bool = False):
        self.reader = reader
        self.batch_size = batch_size
        self.label_index = label_index
        self.num_classes = num_classes
        self.regression = regression
        self.pre_processor = None

    def reset(self):
        self.reader.reset()

    def set_pre_processor(self, p):
        self.pre_processor = p

    def __iter__(self):
        from deeplearning4j_tpu.data.dataset import DataSet
        feats, labels = [], []

        def flush():
            x = np.asarray(feats, np.float32)
            if self.regression:
                y = np.asarray(labels, np.float32).reshape(len(labels),
                                                           -1)
            else:
                y = np.eye(self.num_classes, dtype=np.float32)[
                    np.asarray(labels, np.int64)]
            ds = DataSet(x, y)
            if self.pre_processor is not None:
                ds = self.pre_processor.transform_dataset(ds)
            return ds

        for rec in self.reader:
            lab = rec[self.label_index]
            rest = [v for j, v in enumerate(rec)
                    if j != self.label_index]
            if len(rest) == 1 and isinstance(rest[0], np.ndarray):
                # image-style record: [ndarray, label]
                row = rest[0]
            else:
                row = [float(v) for v in rest]
            feats.append(row)
            labels.append(float(lab) if self.regression else int(lab))
            if len(feats) == self.batch_size:
                yield flush()
                feats, labels = [], []
        if feats:
            yield flush()


class SequenceRecordReaderDataSetIterator:
    """Sequence reader(s) → padded [B, T, F] DataSet batches with masks
    (reference SequenceRecordReaderDataSetIterator with
    AlignmentMode: ``ALIGN_START`` pads at the end (default, as
    upstream), ``ALIGN_END`` right-aligns so the final timestep is
    always real data).

    One reader with ``label_index`` (per-step labels from the same
    rows), or a separate ``labels_reader`` whose sequences align 1:1
    with the feature sequences."""

    def __init__(self, features_reader: RecordReader, batch_size: int,
                 num_classes: Optional[int] = None,
                 labels_reader: Optional[RecordReader] = None,
                 label_index: int = -1, regression: bool = False,
                 alignment_mode: str = "ALIGN_START"):
        self.features_reader = features_reader
        self.labels_reader = labels_reader
        self.batch_size = batch_size
        self.num_classes = num_classes
        self.label_index = label_index
        self.regression = regression
        self.alignment_mode = alignment_mode.upper()
        if self.alignment_mode not in ("ALIGN_START", "ALIGN_END"):
            raise ValueError(
                f"unknown alignment_mode {alignment_mode!r}")
        self.pre_processor = None

    def reset(self):
        self.features_reader.reset()
        if self.labels_reader is not None:
            self.labels_reader.reset()

    def _pairs(self):
        if self.labels_reader is not None:
            for fseq, lseq in zip(self.features_reader,
                                  self.labels_reader):
                feats = [[float(v) for v in step] for step in fseq]
                labs = [step[0] if len(step) == 1 else step
                        for step in lseq]
                yield feats, labs
        else:
            for seq in self.features_reader:
                li = self.label_index % len(seq[0])
                feats = [[float(v) for j, v in enumerate(step)
                          if j != li] for step in seq]
                labs = [step[li] for step in seq]
                yield feats, labs

    def _flush(self, batch):
        from deeplearning4j_tpu.data.dataset import DataSet
        T = max(len(f) for f, _ in batch)
        F = len(batch[0][0][0])
        B = len(batch)
        x = np.zeros((B, T, F), np.float32)
        mask = np.zeros((B, T), np.float32)
        if self.regression:
            ydim = (np.asarray(batch[0][1][0]).size
                    if not np.isscalar(batch[0][1][0]) else 1)
            y = np.zeros((B, T, ydim), np.float32)
        else:
            y = np.zeros((B, T, self.num_classes), np.float32)
        for b, (feats, labs) in enumerate(batch):
            t = len(feats)
            sl = (slice(T - t, T) if self.alignment_mode == "ALIGN_END"
                  else slice(0, t))
            x[b, sl] = np.asarray(feats, np.float32)
            mask[b, sl] = 1.0
            if self.regression:
                y[b, sl] = np.asarray(labs, np.float32).reshape(t, -1)
            else:
                y[b, sl] = np.eye(self.num_classes, dtype=np.float32)[
                    np.asarray(labs, np.int64)]
        ds = DataSet(x, y, features_mask=mask, labels_mask=mask)
        if self.pre_processor is not None:
            ds = self.pre_processor.transform_dataset(ds)
        return ds

    def __iter__(self):
        batch = []
        for pair in self._pairs():
            batch.append(pair)
            if len(batch) == self.batch_size:
                yield self._flush(batch)
                batch = []
        if batch:
            yield self._flush(batch)
