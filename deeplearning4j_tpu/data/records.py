"""Record readers — reference: datavec-api
``org.datavec.api.records.reader.RecordReader`` SPI and impls
(CSVRecordReader, LineRecordReader, RegexLineRecordReader,
CSVSequenceRecordReader, CollectionRecordReader) + ``Writable`` types.

Writables collapse to plain Python/numpy values (str/float/int/ndarray);
records are lists of values; sequence records are lists of records.
"""
from __future__ import annotations

import csv
import io
import re
from pathlib import Path
from typing import Any, Iterator, List, Optional, Sequence

import numpy as np


class RecordReader:
    """Iterable over records (list of values)."""

    def __iter__(self) -> Iterator[List[Any]]:
        raise NotImplementedError

    def reset(self):
        pass


class CollectionRecordReader(RecordReader):
    """In-memory records (reference CollectionRecordReader)."""

    def __init__(self, records: Sequence[Sequence[Any]]):
        self._records = [list(r) for r in records]

    def __iter__(self):
        return iter(self._records)


class CSVRecordReader(RecordReader):
    """CSV file/str reader (reference CSVRecordReader): skip lines,
    custom delimiter, numeric auto-parse."""

    def __init__(self, path_or_text, skip_lines: int = 0,
                 delimiter: str = ",", parse_numbers: bool = True):
        self.path_or_text = path_or_text
        self.skip_lines = skip_lines
        self.delimiter = delimiter
        self.parse_numbers = parse_numbers

    def _lines(self):
        p = Path(str(self.path_or_text))
        if p.exists():
            with open(p, newline="") as f:
                yield from f
        else:
            yield from io.StringIO(str(self.path_or_text))

    @staticmethod
    def _parse(v: str):
        v = v.strip()
        try:
            f = float(v)
            return int(f) if f.is_integer() and "." not in v and \
                "e" not in v.lower() else f
        except ValueError:
            return v

    def __iter__(self):
        reader = csv.reader(self._lines(), delimiter=self.delimiter)
        for i, row in enumerate(reader):
            if i < self.skip_lines or not row:
                continue
            yield ([self._parse(v) for v in row] if self.parse_numbers
                   else [v.strip() for v in row])

    def to_matrix(self):
        """Whole-file numeric fast path → [rows, cols] float32, using
        the native CSV parser (native/dl4j_tpu_native.cpp) when built.
        Returns None if the data isn't purely numeric/rectangular —
        callers then fall back to the row iterator."""
        from deeplearning4j_tpu import native as _native

        p = Path(str(self.path_or_text))
        if p.exists():
            data = p.read_bytes()
        else:
            data = str(self.path_or_text).encode()
        return _native.csv_parse_f32(data, self.delimiter,
                                     self.skip_lines)


class LineRecordReader(RecordReader):
    """One record per line (reference LineRecordReader)."""

    def __init__(self, path_or_text):
        self.path_or_text = path_or_text

    def __iter__(self):
        p = Path(str(self.path_or_text))
        lines = (open(p).read() if p.exists()
                 else str(self.path_or_text)).splitlines()
        for line in lines:
            yield [line]


class RegexLineRecordReader(RecordReader):
    """Regex-group splitting per line (reference RegexLineRecordReader)."""

    def __init__(self, path_or_text, regex: str, skip_lines: int = 0):
        self.base = LineRecordReader(path_or_text)
        self.pattern = re.compile(regex)
        self.skip_lines = skip_lines

    def __iter__(self):
        for i, (line,) in enumerate(self.base):
            if i < self.skip_lines:
                continue
            m = self.pattern.match(line)
            if m is None:
                raise ValueError(f"line {i} does not match: {line!r}")
            yield list(m.groups())


class CSVSequenceRecordReader(RecordReader):
    """One sequence per file/blob; steps are CSV rows (reference
    CSVSequenceRecordReader)."""

    def __init__(self, sources: Sequence, skip_lines: int = 0,
                 delimiter: str = ","):
        self.sources = sources
        self.skip_lines = skip_lines
        self.delimiter = delimiter

    def __iter__(self):
        for src in self.sources:
            rr = CSVRecordReader(src, self.skip_lines, self.delimiter)
            yield [rec for rec in rr]


class RecordReaderDataSetIterator:
    """Bridges a RecordReader into DataSet batches (reference
    org.deeplearning4j.datasets.datavec.RecordReaderDataSetIterator):
    label column index + one-hot for classification, or regression mode.
    """

    def __init__(self, reader: RecordReader, batch_size: int,
                 label_index: int, num_classes: Optional[int] = None,
                 regression: bool = False):
        self.reader = reader
        self.batch_size = batch_size
        self.label_index = label_index
        self.num_classes = num_classes
        self.regression = regression
        self.pre_processor = None

    def reset(self):
        self.reader.reset()

    def set_pre_processor(self, p):
        self.pre_processor = p

    def __iter__(self):
        from deeplearning4j_tpu.data.dataset import DataSet
        feats, labels = [], []

        def flush():
            x = np.asarray(feats, np.float32)
            if self.regression:
                y = np.asarray(labels, np.float32).reshape(len(labels),
                                                           -1)
            else:
                y = np.eye(self.num_classes, dtype=np.float32)[
                    np.asarray(labels, np.int64)]
            ds = DataSet(x, y)
            if self.pre_processor is not None:
                ds = self.pre_processor.transform_dataset(ds)
            return ds

        for rec in self.reader:
            lab = rec[self.label_index]
            rest = [v for j, v in enumerate(rec)
                    if j != self.label_index]
            if len(rest) == 1 and isinstance(rest[0], np.ndarray):
                # image-style record: [ndarray, label]
                row = rest[0]
            else:
                row = [float(v) for v in rest]
            feats.append(row)
            labels.append(float(lab) if self.regression else int(lab))
            if len(feats) == self.batch_size:
                yield flush()
                feats, labels = [], []
        if feats:
            yield flush()
