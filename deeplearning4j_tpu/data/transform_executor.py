"""Distributed TransformProcess execution.

Reference: ``datavec-spark``'s ``SparkTransformExecutor`` — the same
``TransformProcess`` that ``LocalTransformExecutor`` runs in-process is
shipped to a cluster and applied partition-parallel.  The TPU-side
rebuild keeps the exact contract with a *multiprocess local* executor:
records are partitioned, each partition runs ``TransformProcess
.execute`` in a forked worker, and results concatenate in order —
row-independent transforms (every TransformProcess step is per-row;
Reducer/Join are separate classes) make this semantically identical to
the sequential path.

Fork-based workers (the default) inherit the process image, so
transform steps may close over lambdas (``transform_column``) without
being picklable — the same problem the reference solves by requiring
*serializable* transform descriptions, solved the unix way.  Caveat:
``fork`` in a process with live JAX threads is formally unsafe
(CPython warns); the children only run pure-python row transforms and
never touch JAX, but callers who want full safety can pass
``start_method="spawn"`` (requires a picklable TransformProcess, the
reference's own contract).  Any pool failure falls back to sequential
execution, which is always correct.
"""
from __future__ import annotations

import multiprocessing
import os
import threading
from typing import Any, List, Optional

# fork-inherited state: set immediately before the pool is created so
# children see it without pickling (lambdas in transform steps survive).
# _FORK_LOCK serializes set-state→fork so concurrent execute() calls
# from different threads can't snapshot each other's state.
_FORK_STATE: dict = {}
_FORK_LOCK = threading.Lock()


def _run_chunk(bounds):
    lo, hi = bounds
    tp = _FORK_STATE["tp"]
    return tp.execute(_FORK_STATE["records"][lo:hi])


def _run_shipped(tp, chunk):
    return tp.execute(chunk)


class DistributedTransformExecutor:
    """Partition-parallel ``TransformProcess`` execution (reference
    ``SparkTransformExecutor.execute``).

    >>> out = DistributedTransformExecutor(num_workers=4).execute(
    ...     tp, records)            # == tp.execute(records), faster
    """

    def __init__(self, num_workers: Optional[int] = None,
                 min_parallel_records: int = 2048,
                 start_method: str = "fork"):
        self.num_workers = num_workers or max(1, os.cpu_count() or 1)
        self.min_parallel_records = min_parallel_records
        self.start_method = start_method

    def _usable(self) -> bool:
        return (self.start_method
                in multiprocessing.get_all_start_methods()
                and self.num_workers > 1)

    def execute(self, tp, records) -> List[List[Any]]:
        records = list(records)
        n = len(records)
        if n < self.min_parallel_records or not self._usable():
            return tp.execute(records)
        workers = min(self.num_workers, n)
        chunk = -(-n // workers)
        bounds = [(lo, min(lo + chunk, n))
                  for lo in range(0, n, chunk)]
        if self.start_method != "fork":
            # spawn/forkserver children don't inherit state; the
            # TransformProcess must pickle (the reference's own
            # serializable-transform contract).  Check BEFORE paying
            # for a pool so closure-bearing transforms fall back fast.
            import pickle
            try:
                pickle.dumps(tp)
            except Exception:
                return tp.execute(records)
        try:
            ctx = multiprocessing.get_context(self.start_method)
            if self.start_method == "fork":
                # children snapshot _FORK_STATE at Pool() fork time;
                # hold the lock over exactly that window
                with _FORK_LOCK:
                    _FORK_STATE["tp"] = tp
                    _FORK_STATE["records"] = records
                    try:
                        pool = ctx.Pool(processes=len(bounds))
                    finally:
                        _FORK_STATE.clear()
                with pool:
                    parts = pool.map(_run_chunk, bounds)
            else:
                with ctx.Pool(processes=len(bounds)) as pool:
                    parts = pool.starmap(
                        _run_shipped,
                        [(tp, records[lo:hi]) for lo, hi in bounds])
        except Exception:
            return tp.execute(records)   # always-correct fallback
        return [row for part in parts for row in part]
