"""MNIST (and EMNIST-style) dataset iterators.

Reference: ``org.deeplearning4j.datasets.iterator.impl.MnistDataSetIterator``
(deeplearning4j-datasets; fetches IDX files, yields normalized batches).

This environment has no network egress, so loading order is:
 1. real IDX files if present under ``~/.deeplearning4j_tpu/mnist/`` or
    ``$DL4J_TPU_MNIST_DIR`` (same ubyte format the reference fetches);
 2. otherwise a DETERMINISTIC SYNTHETIC digit set: class-dependent
    stroke-like templates + noise, 28×28×1, separable but not trivial —
    good enough to exercise LeNet end-to-end and regression-test
    accuracy. A loud attribute ``synthetic=True`` marks the fallback.
"""
from __future__ import annotations

import gzip
import os
import struct
from pathlib import Path
from typing import Optional, Tuple

import numpy as np

from deeplearning4j_tpu.data.dataset import DataSet
from deeplearning4j_tpu.data.iterators import DataSetIterator


def _read_idx(path: Path) -> np.ndarray:
    op = gzip.open if path.suffix == ".gz" else open
    with op(path, "rb") as f:
        magic = struct.unpack(">I", f.read(4))[0]
        ndim = magic & 0xFF
        dims = struct.unpack(">" + "I" * ndim, f.read(4 * ndim))
        return np.frombuffer(f.read(), np.uint8).reshape(dims)


def _find_idx(root: Path, train: bool) -> Optional[Tuple[Path, Path]]:
    img = "train-images-idx3-ubyte" if train else "t10k-images-idx3-ubyte"
    lab = "train-labels-idx1-ubyte" if train else "t10k-labels-idx1-ubyte"
    for suffix in ("", ".gz"):
        ip, lp = root / (img + suffix), root / (lab + suffix)
        if ip.exists() and lp.exists():
            return ip, lp
    return None


def _synthetic_mnist(n: int, train: bool, seed: int = 7
                     ) -> Tuple[np.ndarray, np.ndarray]:
    """Deterministic digit-like dataset: each class is a fixed random
    low-frequency template; samples = template + jitter + noise."""
    rng = np.random.default_rng(seed)  # templates shared train/test
    base = rng.normal(size=(10, 7, 7))
    templates = np.kron(base, np.ones((4, 4)))  # 28x28 blocky patterns
    templates = (templates - templates.min(axis=(1, 2), keepdims=True))
    templates /= templates.max(axis=(1, 2), keepdims=True) + 1e-9

    srng = np.random.default_rng(seed + (1 if train else 2))
    labels = srng.integers(0, 10, n)
    imgs = templates[labels]
    # per-sample 2-pixel translation jitter + gaussian noise
    shifts = srng.integers(-2, 3, (n, 2))
    out = np.empty((n, 28, 28), np.float32)
    for i in range(n):
        out[i] = np.roll(np.roll(imgs[i], shifts[i, 0], 0),
                         shifts[i, 1], 1)
    out += srng.normal(0, 0.35, out.shape).astype(np.float32)
    out = np.clip(out, 0, 1)
    return (out[..., None] * 255).astype(np.uint8), labels


class MnistDataSetIterator(DataSetIterator):
    """Yields DataSet batches of ([B,28,28,1] float32 in [0,1] — NHWC,
    TPU layout), one-hot labels [B,10].

    Reference ctor parity: MnistDataSetIterator(batch, train, seed).
    """

    def __init__(self, batch_size: int = 64, train: bool = True,
                 seed: int = 123, n_examples: Optional[int] = None,
                 data_dir: Optional[str] = None):
        super().__init__(batch_size)
        self.train = train
        self.seed = seed
        root = Path(data_dir or os.environ.get(
            "DL4J_TPU_MNIST_DIR",
            Path.home() / ".deeplearning4j_tpu" / "mnist"))
        found = _find_idx(root, train) if root.exists() else None
        if found:
            imgs = _read_idx(found[0])
            labels = _read_idx(found[1])
            self.synthetic = False
        else:
            n = n_examples or (10000 if train else 2000)
            imgs, labels = _synthetic_mnist(n, train)
            imgs = imgs[..., 0]
            self.synthetic = True
        if n_examples:
            imgs, labels = imgs[:n_examples], labels[:n_examples]
        feats = (imgs.astype(np.float32) / 255.0)[..., None]
        onehot = np.eye(10, dtype=np.float32)[labels]
        self._ds = DataSet(feats, onehot)
        self._epoch = 0

    def total_examples(self) -> int:
        return self._ds.num_examples()

    def __iter__(self):
        ds = self._ds
        if self.train:
            ds = ds.shuffle(self.seed + self._epoch)
            self._epoch += 1
        for b in ds.batch_by(self.batch_size):
            yield self._apply_pp(b)


class IrisDataSetIterator(DataSetIterator):
    """Fisher's Iris (reference IrisDataSetIterator) — the 150 rows are
    generated from the classic per-class Gaussian statistics when the
    CSV isn't on disk (deterministic)."""

    def __init__(self, batch_size: int = 150, seed: int = 42):
        super().__init__(batch_size)
        rng = np.random.default_rng(seed)
        # (mean, std) per class for sepal-l, sepal-w, petal-l, petal-w
        stats = [((5.01, 3.43, 1.46, 0.25), (0.35, 0.38, 0.17, 0.11)),
                 ((5.94, 2.77, 4.26, 1.33), (0.52, 0.31, 0.47, 0.20)),
                 ((6.59, 2.97, 5.55, 2.03), (0.64, 0.32, 0.55, 0.27))]
        feats, labels = [], []
        for c, (mu, sd) in enumerate(stats):
            feats.append(rng.normal(mu, sd, (50, 4)))
            labels.extend([c] * 50)
        x = np.concatenate(feats).astype(np.float32)
        y = np.eye(3, dtype=np.float32)[np.asarray(labels)]
        idx = rng.permutation(150)
        self._ds = DataSet(x[idx], y[idx])

    def __iter__(self):
        for b in self._ds.batch_by(self.batch_size):
            yield self._apply_pp(b)
