from deeplearning4j_tpu.train.listeners import (
    TrainingListener, ScoreIterationListener, PerformanceListener,
    CheckpointListener, EvaluativeListener, CollectScoresListener,
)
from deeplearning4j_tpu.train.earlystopping import (
    EarlyStoppingConfiguration, EarlyStoppingTrainer, EarlyStoppingResult,
    MaxEpochsTerminationCondition, ScoreImprovementEpochTerminationCondition,
    MaxTimeIterationTerminationCondition, MaxScoreIterationTerminationCondition,
    DataSetLossCalculator, ClassificationScoreCalculator,
    InMemoryModelSaver, LocalFileModelSaver,
)
from deeplearning4j_tpu.train.stats import (
    StatsListener, StatsStorage, InMemoryStatsStorage, FileStatsStorage,
    UIServer,
)
from deeplearning4j_tpu.train.fault_tolerance import (
    FaultTolerantTrainer, resume_or_init, newest_checkpoint,
)
from deeplearning4j_tpu.train.solver import (
    Solver, StochasticGradientDescent, LineGradientDescent,
    ConjugateGradient, LBFGS, backtrack_line_search,
)

__all__ = [
    "FaultTolerantTrainer", "resume_or_init", "newest_checkpoint",
    "Solver", "StochasticGradientDescent", "LineGradientDescent",
    "ConjugateGradient", "LBFGS", "backtrack_line_search",
    "TrainingListener", "ScoreIterationListener", "PerformanceListener",
    "CheckpointListener", "EvaluativeListener", "CollectScoresListener",
    "EarlyStoppingConfiguration", "EarlyStoppingTrainer",
    "EarlyStoppingResult", "MaxEpochsTerminationCondition",
    "ScoreImprovementEpochTerminationCondition",
    "MaxTimeIterationTerminationCondition",
    "MaxScoreIterationTerminationCondition", "DataSetLossCalculator",
    "ClassificationScoreCalculator", "InMemoryModelSaver",
    "LocalFileModelSaver", "StatsListener", "StatsStorage",
    "InMemoryStatsStorage", "FileStatsStorage", "UIServer",
]
