from deeplearning4j_tpu.train.listeners import (
    TrainingListener, ScoreIterationListener, PerformanceListener,
    CheckpointListener, EvaluativeListener,
)

__all__ = ["TrainingListener", "ScoreIterationListener",
           "PerformanceListener", "CheckpointListener",
           "EvaluativeListener"]
