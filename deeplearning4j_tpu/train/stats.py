"""Training stats collection + storage + dashboard.

Reference: ``deeplearning4j-ui-parent`` —
``org.deeplearning4j.ui.model.stats.StatsListener`` (per-iteration score,
param/update histograms & ratios, system metrics) streaming into a
``StatsStorage`` (``InMemoryStatsStorage`` / ``FileStatsStorage``), and
``org.deeplearning4j.ui.api.UIServer`` (``VertxUIServer``) rendering
score charts + layer histograms.

TPU-native redesign: stats records are plain dicts (JSON lines on disk
instead of the reference's custom binary + MapDB); the dashboard is a
dependency-free stdlib ``http.server`` rendering inline SVG — no Vertx,
no build step. Param/update norms are computed with jitted reductions
on-device, only scalars cross to host.
"""
from __future__ import annotations

import datetime
import json
import threading
from pathlib import Path
from typing import Any, Dict, List, Optional

import numpy as np

from deeplearning4j_tpu import obs
from deeplearning4j_tpu.train.listeners import TrainingListener


# --- storage ----------------------------------------------------------------

class StatsStorage:
    """Reference: org.deeplearning4j.api.storage.StatsStorage."""

    def put_record(self, session_id: str, record: Dict[str, Any]) -> None:
        raise NotImplementedError

    def list_session_ids(self) -> List[str]:
        raise NotImplementedError

    def get_records(self, session_id: str) -> List[Dict[str, Any]]:
        raise NotImplementedError


class InMemoryStatsStorage(StatsStorage):
    def __init__(self):
        self._data: Dict[str, List[Dict[str, Any]]] = {}
        self._lock = threading.Lock()

    def put_record(self, session_id, record):
        with self._lock:
            self._data.setdefault(session_id, []).append(record)

    def list_session_ids(self):
        return list(self._data)

    def get_records(self, session_id):
        return list(self._data.get(session_id, []))


class FileStatsStorage(StatsStorage):
    """JSON-lines per session (reference FileStatsStorage/MapDB)."""

    def __init__(self, path: str):
        self.dir = Path(path)
        self.dir.mkdir(parents=True, exist_ok=True)

    def _file(self, sid):
        return self.dir / f"{sid}.jsonl"

    def put_record(self, session_id, record):
        with open(self._file(session_id), "a") as f:
            f.write(json.dumps(record) + "\n")

    def list_session_ids(self):
        return [p.stem for p in self.dir.glob("*.jsonl")]

    def get_records(self, session_id):
        p = self._file(session_id)
        if not p.exists():
            return []
        with open(p) as f:
            return [json.loads(line) for line in f if line.strip()]


# --- listener ---------------------------------------------------------------

def _rss_mb() -> Optional[float]:
    """Host resident set size in MB (reference StatsListener system
    metrics: JVM/offheap memory → host RSS here)."""
    try:
        with open("/proc/self/status") as f:
            for line in f:
                if line.startswith("VmRSS:"):
                    return float(line.split()[1]) / 1024.0
    except OSError:
        pass
    try:                  # no /proc (macOS): peak RSS from getrusage —
        import resource   # bytes on darwin, kilobytes elsewhere
        import sys as _sys
        rss = resource.getrusage(resource.RUSAGE_SELF).ru_maxrss
        return rss / (1024.0 ** 2 if _sys.platform == "darwin"
                      else 1024.0)
    except Exception:
        return None


class StatsListener(TrainingListener):
    """Streams per-iteration stats into a StatsStorage (reference
    StatsListener; update:param ratios are the reference's headline
    training-health diagnostic).

    Collected per record: score, per-layer param/gradient/update norms
    and update:param ratios, optional per-layer parameter AND update
    histograms, optional activation histograms (extra forward on a
    held sample batch — the in-step activation stats from the numerics
    observatory cover the training pass itself), and system metrics
    (host RSS, wall step time, ETL wait read off an
    ``AsyncDataSetIterator`` when one is provided).

    Per-layer training health comes from the numerics observatory
    (``obs/numerics.py``): the listener attaches a cadence-aligned
    monitor to the net on first sight (``use_numerics``), and every
    record reads the per-layer scalars the diagnostic step already
    produced ON DEVICE — no previous-params tree copy, no per-layer
    host reduction loop (both of which this listener used to do, at
    the cost of pinning a second full param set between records).
    Only per-layer scalars live between records.

    ``use_numerics=False`` (or a net without ``monitor_numerics``)
    records score/param-norms/system metrics only — update:param
    ratios, grad norms and update histograms REQUIRE the in-step
    observatory; the host-side previous-params diff that used to
    approximate them is deliberately gone (lint rule 3). Note the
    cadence trade: a monitor at ``every <= steps_per_loop`` makes
    diag-due groups run per-batch instead of as one scanned
    executable (warned once at runtime).
    """

    def __init__(self, storage: StatsStorage, frequency: int = 1,
                 session_id: Optional[str] = None,
                 collect_histograms: bool = False,
                 activation_sample=None, iterator=None,
                 use_numerics: bool = True):
        self.storage = storage
        self.frequency = max(1, frequency)
        self.session_id = session_id or (
            "session_"
            + datetime.datetime.now().strftime("%Y%m%d_%H%M%S"))
        self.collect_histograms = collect_histograms
        self.activation_sample = activation_sample
        self.iterator = iterator
        self.use_numerics = use_numerics
        self._t0 = obs.now()    # the obs clock is the one step clock
        self._last_rec: Optional[tuple] = None   # (time, iteration)
        self._last_etl = 0.0
        self._prev_compile: Optional[tuple] = None

    def iteration_done(self, net, iteration, epoch):
        if self.use_numerics and getattr(net, "_numerics", None) \
                is None and hasattr(net, "monitor_numerics"):
            # first sight of the net: attach a record-aligned monitor
            # (diag iterations land exactly on this listener's
            # recording iterations). raise_on_nonfinite stays off —
            # the listener's job is to RECORD divergence, not to turn
            # every monitored run into a raising one (attach an
            # explicit monitor_numerics() for the resilience path).
            net.monitor_numerics(every=self.frequency,
                                 histograms=self.collect_histograms,
                                 raise_on_nonfinite=False)
        if iteration % self.frequency:
            return
        now = obs.now()
        # per-iteration averages over the recording interval, so step
        # time and ETL wait stay comparable at any frequency
        step_ms = None
        iters = self.frequency
        if self._last_rec is not None:
            t_prev, it_prev = self._last_rec
            iters = max(1, iteration - it_prev)
            step_ms = (now - t_prev) * 1e3 / iters
        self._last_rec = (now, iteration)
        # in-step numerics from the diagnostic step that produced THIS
        # iteration (stale records from an off-cadence monitor are
        # never misattributed)
        num = getattr(net, "last_numerics", None)
        if num is not None and num.get("iteration") != iteration:
            num = None
        rec: Dict[str, Any] = {
            "iteration": iteration,
            "epoch": epoch,
            "time": now - self._t0,
            "score": float(net.score_)
            if np.isfinite(net.score_) else None,
            # fallback (first record / numerics off): ONE jitted fused
            # reduction in obs/numerics.py, scalars to host
            "param_norms": (dict(num["param_norm"]) if num
                            else obs.numerics.tree_norms(net.params)),
        }
        sys_rec: Dict[str, Any] = {"mem_rss_mb": _rss_mb(),
                                   "step_time_ms": step_ms}
        etl = getattr(self.iterator, "etl_wait_seconds", None)
        if etl is not None:
            sys_rec["etl_wait_ms"] = (etl - self._last_etl) * 1e3 / iters
            self._last_etl = etl
        rec["sys"] = sys_rec
        rec["compile"] = self._compile_rec()
        # telemetry spine: compact merged snapshot (tracing state,
        # per-entry step means, stale workers) — obs.report() scalars,
        # never the full metric family dump
        rec["obs"] = obs.summary()
        if num is not None:
            rec["update_ratios"] = dict(num["update_ratio"])
            rec["grad_norms"] = dict(num["grad_norm"])
            rec["update_norms"] = dict(num["update_norm"])
            rec["activation_stats"] = {
                l: {"mean": num["act_mean"][l],
                    "std": num["act_std"][l],
                    "absmax": num["act_absmax"][l]}
                for l in num["act_mean"]}
            if "replica_divergence" in num:
                rec["replica_divergence"] = dict(
                    num["replica_divergence"])
            if "nonfinite" in num:
                rec["nonfinite"] = dict(num["nonfinite"])
            if self.collect_histograms and "update_hist" in num:
                rec["update_histograms"] = {
                    l: obs.numerics.sketch_as_histogram(c)
                    for l, c in num["update_hist"].items()}
            if self.collect_histograms and "grad_hist" in num:
                rec["grad_histograms"] = {
                    l: obs.numerics.sketch_as_histogram(c)
                    for l, c in num["grad_hist"].items()}
        if self.collect_histograms:
            rec["histograms"] = {
                name: self._hist(sub) for name, sub in net.params.items()}
        if self.activation_sample is not None:
            # full-distribution histograms on a HELD sample are a
            # separate opt-in (the training pass's activation stats
            # arrive in-step above); the extra forward runs under its
            # own span so it can never masquerade as step device time
            with obs.span("numerics.activations"):
                rec["activation_histograms"] = \
                    self._activation_hists(net)
        self.storage.put_record(self.session_id, rec)

    def _compile_rec(self) -> Optional[Dict[str, Any]]:
        """Compile-subsystem deltas over the recording interval (perf
        sentry + persistent cache): a step that recompiled shows up
        here as nonzero ``traces``/``time_ms`` next to its inflated
        ``step_time_ms`` — the retrace-storm signature the dashboard
        exists to catch. None once compilation has settled."""
        from deeplearning4j_tpu.perf import compile_cache, sentry
        traces = sentry.total_traces()
        tcomp = sentry.total_compile_time_s()
        # counters() not cache_stats(): this runs every recording
        # interval and must not walk the cache dir
        hits = compile_cache.counters()["persistent_hits"]
        prev = self._prev_compile
        self._prev_compile = (traces, tcomp, hits)
        if prev is not None and (traces, tcomp, hits) == prev:
            return None
        d_traces = traces - (prev[0] if prev else 0)
        unplanned = sum(s["unplanned_shapes"]
                        for s in sentry.stats().values())
        return {"traces": d_traces,
                "time_ms": (tcomp - (prev[1] if prev else 0.0)) * 1e3,
                "cache_hits": hits - (prev[2] if prev else 0),
                "total_traces": traces,
                "unplanned_shapes": unplanned}

    def _activation_hists(self, net):
        try:
            acts = net.feed_forward(self.activation_sample)
        except Exception:
            return None
        return {f"layer_{i-1}" if i else "input": self._hist([a])
                for i, a in enumerate(acts)}

    @staticmethod
    def _hist(sub, bins: int = 20):
        import jax
        leaves = [np.asarray(l).ravel() for l in jax.tree.leaves(sub)]
        if not leaves:
            return None
        flat = np.concatenate(leaves)
        finite = flat[np.isfinite(flat)]
        if finite.size == 0:
            # diverged (all NaN/Inf): report emptiness, never crash the
            # training loop the dashboard is meant to diagnose
            return {"counts": [0] * bins, "min": 0.0, "max": 0.0,
                    "nonfinite": int(flat.size)}
        counts, edges = np.histogram(finite, bins=bins)
        out = {"counts": counts.tolist(),
               "min": float(edges[0]), "max": float(edges[-1])}
        if finite.size != flat.size:
            out["nonfinite"] = int(flat.size - finite.size)
        return out


# --- dashboard --------------------------------------------------------------

_DASH_JS = """
const qs = new URLSearchParams(location.search);
let session = qs.get('session');
function line(el, series, opts) {      // series: [{name, pts:[[x,y]]}]
  const w = 640, h = 180, pad = 34;
  let xs = [], ys = [];
  series.forEach(s => s.pts.forEach(p => {
    if (p[1] != null && isFinite(p[1])) { xs.push(p[0]); ys.push(p[1]); }
  }));
  if (!xs.length) { el.innerHTML = ''; return; }
  const mn = a => a.reduce((p, c) => Math.min(p, c), Infinity);
  const mx = a => a.reduce((p, c) => Math.max(p, c), -Infinity);
  const x0 = mn(xs), x1 = mx(xs) || 1;
  const y0 = mn(ys), y1 = mx(ys);
  const sx = (x1 - x0) || 1, sy = (y1 - y0) || 1;
  const colors = ['#2563eb','#dc2626','#16a34a','#9333ea','#ea580c',
                  '#0891b2','#4b5563','#ca8a04'];
  let svg = '';
  series.forEach((s, i) => {
    const pts = s.pts.filter(p => p[1] != null && isFinite(p[1])).map(p =>
      (pad + (p[0]-x0)/sx*(w-pad-4)).toFixed(1) + ',' +
      (h - 18 - (p[1]-y0)/sy*(h-26)).toFixed(1)).join(' ');
    svg += `<polyline fill="none" stroke="${colors[i%8]}"
            stroke-width="1.5" points="${pts}"/>`;
  });
  svg += `<text x="2" y="12" font-size="10">${y1.toPrecision(4)}</text>`;
  svg += `<text x="2" y="${h-6}" font-size="10">${y0.toPrecision(4)}</text>`;
  const legend = series.map((s, i) =>
    `<tspan fill="${colors[i%8]}">&#9644;${s.name}</tspan>`).join(' ');
  svg += `<text x="${pad}" y="12" font-size="10">${legend}</text>`;
  el.innerHTML = svg;
}
function bars(el, hist) {
  const w = 240, h = 80;
  if (!hist || !hist.counts) { el.innerHTML = ''; return; }
  const m = Math.max(...hist.counts) || 1;
  const bw = w / hist.counts.length;
  el.innerHTML = hist.counts.map((c, i) =>
    `<rect x="${(i*bw).toFixed(1)}" y="${(h-c/m*h).toFixed(1)}"
     width="${(bw-1).toFixed(1)}" height="${(c/m*h).toFixed(1)}"
     fill="#2563eb"/>`).join('') +
    `<text x="0" y="${h-2}" font-size="9">${hist.min.toPrecision(3)}
     </text><text x="${w-50}" y="${h-2}" font-size="9">
     ${hist.max.toPrecision(3)}</text>`;
}
function histBlock(containerId, byLayer) {
  const c = document.getElementById(containerId);
  if (!byLayer) { c.innerHTML = ''; return; }
  c.innerHTML = Object.keys(byLayer).map(k =>
    `<div class="hist"><div class="hl">${k}</div>
     <svg viewBox="0 0 240 80" width="240" height="80"
      id="${containerId}-${k}"></svg></div>`).join('');
  Object.keys(byLayer).forEach(k =>
    bars(document.getElementById(`${containerId}-${k}`), byLayer[k]));
}
async function tick() {
  if (!session) {
    const ss = await (await fetch('/sessions')).json();
    if (ss.length) session = ss[ss.length-1]; else return;
  }
  const recs = await (await fetch(
    '/json?session=' + encodeURIComponent(session))).json();
  if (!recs.length) return;
  document.getElementById('sess').textContent = session;
  line(document.getElementById('score'),
       [{name:'score', pts: recs.map(r => [r.iteration, r.score])}]);
  const layers = Object.keys(recs[recs.length-1].update_ratios || {});
  line(document.getElementById('ratios'), layers.map(l => ({
    name: l,
    pts: recs.map(r => [r.iteration,
      r.update_ratios && r.update_ratios[l] > 0 ?
      Math.log10(r.update_ratios[l]) : null])})));
  const glayers = Object.keys(recs[recs.length-1].grad_norms || {});
  line(document.getElementById('gradnorm'), glayers.map(l => ({
    name: l,
    pts: recs.map(r => [r.iteration,
      r.grad_norms && r.grad_norms[l] > 0 ?
      Math.log10(r.grad_norms[l]) : null])})));
  const dlayers = Object.keys(
    recs[recs.length-1].replica_divergence || {});
  line(document.getElementById('divergence'), dlayers.map(l => ({
    name: l,
    pts: recs.map(r => [r.iteration,
      r.replica_divergence ? r.replica_divergence[l] : null])})));
  const nf = recs.map(r => r.nonfinite).filter(Boolean);
  document.getElementById('nf').textContent = nf.length ?
    ('NON-FINITE: layer ' + nf[nf.length-1].layer + ' (' +
     nf[nf.length-1].kind + ')') : '';
  line(document.getElementById('steptime'),
       [{name:'step ms', pts: recs.map(r =>
          [r.iteration, r.sys ? r.sys.step_time_ms : null])},
        {name:'etl ms', pts: recs.map(r =>
          [r.iteration, r.sys ? r.sys.etl_wait_ms : null])}]);
  const last = recs[recs.length-1];
  const sysEl = document.getElementById('sys');
  if (last.sys) sysEl.textContent =
    `host RSS ${last.sys.mem_rss_mb ?
       last.sys.mem_rss_mb.toFixed(0) : '?'} MB · step ` +
    `${last.sys.step_time_ms ?
       last.sys.step_time_ms.toFixed(1) : '?'} ms · ETL wait ` +
    `${last.sys.etl_wait_ms != null ?
       last.sys.etl_wait_ms.toFixed(1) : '–'} ms · iter ` +
    last.iteration;
  histBlock('phist', last.histograms);
  histBlock('uhist', last.update_histograms);
  histBlock('ahist', last.activation_histograms);
}
tick(); setInterval(tick, 2000);
"""

_DASH_HTML = """<html><head><title>deeplearning4j_tpu training UI</title>
<style>body{{font-family:sans-serif;margin:2em;}}h2{{margin-top:1.2em;}}
.hist{{display:inline-block;margin:4px;}}.hl{{font-size:11px;}}
#sys{{color:#4b5563;}}</style></head><body>
<h1>Training dashboard</h1>
<p>Session: <b id="sess">–</b> · sessions: {sessions}</p>
<p id="sys">collecting…</p>
<h2>Score</h2>
<svg id="score" viewBox="0 0 640 180" width="640" height="180"></svg>
<h2>update:param ratio per layer (log10)</h2>
<svg id="ratios" viewBox="0 0 640 180" width="640" height="180"></svg>
<p id="nf" style="color:#dc2626;font-weight:bold;"></p>
<h2>gradient norm per layer (log10)</h2>
<svg id="gradnorm" viewBox="0 0 640 180" width="640" height="180"></svg>
<h2>replica divergence (max−min grad norm)</h2>
<svg id="divergence" viewBox="0 0 640 180" width="640" height="180"></svg>
<h2>step time / ETL wait (ms)</h2>
<svg id="steptime" viewBox="0 0 640 180" width="640" height="180"></svg>
<h2>parameter histograms (latest)</h2><div id="phist"></div>
<h2>update histograms (latest)</h2><div id="uhist"></div>
<h2>activation histograms (latest)</h2><div id="ahist"></div>
<script>{js}</script></body></html>"""


class UIServer:
    """Training dashboard (reference UIServer/VertxUIServer): live
    2-second polling of ``/json``, client-rendered score chart,
    per-layer update:param ratio, gradient-norm and replica-divergence
    charts (numerics observatory), a non-finite alarm line,
    step-time/ETL chart, and parameter/update/activation histograms,
    plus host system metrics. Stdlib-only server, dependency-free
    inline JS.
    """

    _instance = None

    def __init__(self, port: int = 9000):
        self.port = port
        self._storages: List[StatsStorage] = []
        self._httpd = None
        self._thread = None

    @classmethod
    def get_instance(cls, port: int = 9000) -> "UIServer":
        if cls._instance is None:
            cls._instance = cls(port)
        return cls._instance

    def attach(self, storage: StatsStorage):
        self._storages.append(storage)
        return self

    # -- html --------------------------------------------------------------
    def _sessions(self) -> List[str]:
        return [s for st in self._storages
                for s in st.list_session_ids()]

    def _render(self) -> str:
        # session selection happens client-side (the JS reads
        # location.search and polls /json)
        links = " | ".join(
            f'<a href="/?session={s}">{s}</a>' for s in self._sessions())
        return _DASH_HTML.format(sessions=links or "none yet",
                                 js=_DASH_JS)

    # -- server ------------------------------------------------------------
    def start(self):
        import http.server
        import urllib.parse

        ui = self

        class Handler(http.server.BaseHTTPRequestHandler):
            def do_GET(self):
                q = urllib.parse.urlparse(self.path)
                qs = urllib.parse.parse_qs(q.query)
                session = qs.get("session", [None])[0]
                if q.path == "/json":
                    recs = []
                    for st in ui._storages:
                        if session:
                            recs.extend(st.get_records(session))
                    recs.sort(key=lambda r: r.get("iteration", 0))
                    # the dashboard renders histograms only for the
                    # final record — strip them elsewhere so the poll
                    # payload stays O(scalars), not O(layers·bins)
                    bulky = ("histograms", "update_histograms",
                             "grad_histograms",
                             "activation_histograms")
                    recs = [
                        {k: v for k, v in r.items() if k not in bulky}
                        if i < len(recs) - 1 else r
                        for i, r in enumerate(recs)]
                    body = json.dumps(recs).encode()
                    ctype = "application/json"
                elif q.path == "/sessions":
                    body = json.dumps(ui._sessions()).encode()
                    ctype = "application/json"
                else:
                    body = ui._render().encode()
                    ctype = "text/html"
                self.send_response(200)
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def log_message(self, *a):
                pass

        self._httpd = http.server.ThreadingHTTPServer(
            ("127.0.0.1", self.port), Handler)
        self.port = self._httpd.server_address[1]
        self._thread = threading.Thread(target=self._httpd.serve_forever,
                                        daemon=True)
        self._thread.start()
        return self

    def stop(self):
        if self._httpd:
            self._httpd.shutdown()
            self._httpd.server_close()
            self._httpd = None
