"""Training stats collection + storage + dashboard.

Reference: ``deeplearning4j-ui-parent`` —
``org.deeplearning4j.ui.model.stats.StatsListener`` (per-iteration score,
param/update histograms & ratios, system metrics) streaming into a
``StatsStorage`` (``InMemoryStatsStorage`` / ``FileStatsStorage``), and
``org.deeplearning4j.ui.api.UIServer`` (``VertxUIServer``) rendering
score charts + layer histograms.

TPU-native redesign: stats records are plain dicts (JSON lines on disk
instead of the reference's custom binary + MapDB); the dashboard is a
dependency-free stdlib ``http.server`` rendering inline SVG — no Vertx,
no build step. Param/update norms are computed with jitted reductions
on-device, only scalars cross to host.
"""
from __future__ import annotations

import json
import math
import threading
import time
from pathlib import Path
from typing import Any, Dict, List, Optional

import numpy as np

from deeplearning4j_tpu.train.listeners import TrainingListener


# --- storage ----------------------------------------------------------------

class StatsStorage:
    """Reference: org.deeplearning4j.api.storage.StatsStorage."""

    def put_record(self, session_id: str, record: Dict[str, Any]) -> None:
        raise NotImplementedError

    def list_session_ids(self) -> List[str]:
        raise NotImplementedError

    def get_records(self, session_id: str) -> List[Dict[str, Any]]:
        raise NotImplementedError


class InMemoryStatsStorage(StatsStorage):
    def __init__(self):
        self._data: Dict[str, List[Dict[str, Any]]] = {}
        self._lock = threading.Lock()

    def put_record(self, session_id, record):
        with self._lock:
            self._data.setdefault(session_id, []).append(record)

    def list_session_ids(self):
        return list(self._data)

    def get_records(self, session_id):
        return list(self._data.get(session_id, []))


class FileStatsStorage(StatsStorage):
    """JSON-lines per session (reference FileStatsStorage/MapDB)."""

    def __init__(self, path: str):
        self.dir = Path(path)
        self.dir.mkdir(parents=True, exist_ok=True)

    def _file(self, sid):
        return self.dir / f"{sid}.jsonl"

    def put_record(self, session_id, record):
        with open(self._file(session_id), "a") as f:
            f.write(json.dumps(record) + "\n")

    def list_session_ids(self):
        return [p.stem for p in self.dir.glob("*.jsonl")]

    def get_records(self, session_id):
        p = self._file(session_id)
        if not p.exists():
            return []
        with open(p) as f:
            return [json.loads(line) for line in f if line.strip()]


# --- listener ---------------------------------------------------------------

def _tree_norms(tree) -> Dict[str, float]:
    """Per-layer L2 norms, computed on-device, scalars to host."""
    import jax
    import jax.numpy as jnp

    out = {}
    for name, sub in (tree or {}).items():
        leaves = jax.tree.leaves(sub)
        if leaves:
            out[name] = float(jnp.sqrt(sum(jnp.sum(jnp.square(l))
                                           for l in leaves)))
    return out


class StatsListener(TrainingListener):
    """Streams per-iteration stats into a StatsStorage (reference
    StatsListener; update:param ratios are the reference's headline
    training-health diagnostic)."""

    def __init__(self, storage: StatsStorage, frequency: int = 1,
                 session_id: Optional[str] = None,
                 collect_histograms: bool = False):
        self.storage = storage
        self.frequency = max(1, frequency)
        self.session_id = session_id or f"session_{int(time.time())}"
        self.collect_histograms = collect_histograms
        self._prev_params: Optional[Dict[str, Any]] = None
        self._t0 = time.time()

    def iteration_done(self, net, iteration, epoch):
        if iteration % self.frequency:
            return          # keep _prev_params from the last recorded iter
        rec: Dict[str, Any] = {
            "iteration": iteration,
            "epoch": epoch,
            "time": time.time() - self._t0,
            "score": float(net.score_)
            if np.isfinite(net.score_) else None,
            "param_norms": _tree_norms(net.params),
        }
        if self._prev_params is not None:
            import jax
            import jax.numpy as jnp
            ratios = {}
            for name, sub in net.params.items():
                prev = self._prev_params.get(name)
                if prev is None:
                    continue
                upd = jax.tree.map(lambda a, b: a - b, sub, prev)
                un = float(jnp.sqrt(sum(jnp.sum(jnp.square(l))
                                        for l in jax.tree.leaves(upd))))
                pn = rec["param_norms"].get(name, 0.0)
                ratios[name] = un / pn if pn > 0 else 0.0
            rec["update_ratios"] = ratios
        if self.collect_histograms:
            rec["histograms"] = {
                name: self._hist(sub) for name, sub in net.params.items()}
        # keep a COPY — the net's next jitted step donates (deletes) the
        # current param buffers
        import jax
        import jax.numpy as jnp
        self._prev_params = jax.tree.map(jnp.array, net.params)
        self.storage.put_record(self.session_id, rec)

    @staticmethod
    def _hist(sub, bins: int = 20):
        import jax
        leaves = [np.asarray(l).ravel() for l in jax.tree.leaves(sub)]
        if not leaves:
            return None
        flat = np.concatenate(leaves)
        counts, edges = np.histogram(flat, bins=bins)
        return {"counts": counts.tolist(),
                "min": float(edges[0]), "max": float(edges[-1])}


# --- dashboard --------------------------------------------------------------

def _svg_line(points, w=640, h=180, color="#2563eb"):
    if len(points) < 2:
        return "<svg></svg>"
    xs = [p[0] for p in points]
    ys = [p[1] for p in points if p[1] is not None]
    if not ys:
        return "<svg></svg>"
    x0, x1 = min(xs), max(xs) or 1
    y0, y1 = min(ys), max(ys)
    span_x = (x1 - x0) or 1
    span_y = (y1 - y0) or 1
    pts = " ".join(
        f"{(p[0]-x0)/span_x*w:.1f},{h-(p[1]-y0)/span_y*h:.1f}"
        for p in points if p[1] is not None)
    return (f'<svg viewBox="0 0 {w} {h}" width="{w}" height="{h}">'
            f'<polyline fill="none" stroke="{color}" stroke-width="1.5" '
            f'points="{pts}"/></svg>')


class UIServer:
    """Minimal training dashboard (reference UIServer/VertxUIServer):
    score chart, update:param ratio chart, session picker. Stdlib-only.
    """

    _instance = None

    def __init__(self, port: int = 9000):
        self.port = port
        self._storages: List[StatsStorage] = []
        self._httpd = None
        self._thread = None

    @classmethod
    def get_instance(cls, port: int = 9000) -> "UIServer":
        if cls._instance is None:
            cls._instance = cls(port)
        return cls._instance

    def attach(self, storage: StatsStorage):
        self._storages.append(storage)
        return self

    # -- html --------------------------------------------------------------
    def _render(self, session: Optional[str]) -> str:
        sessions = [s for st in self._storages
                    for s in st.list_session_ids()]
        if session is None and sessions:
            session = sessions[-1]
        records = []
        for st in self._storages:
            records.extend(st.get_records(session) if session else [])
        records.sort(key=lambda r: r.get("iteration", 0))
        score = [(r["iteration"], r.get("score")) for r in records]
        parts = [
            "<html><head><title>deeplearning4j_tpu training UI</title>",
            "<style>body{font-family:sans-serif;margin:2em;}"
            "h2{margin-top:1.5em;}</style></head><body>",
            "<h1>Training dashboard</h1>",
            "<p>Sessions: " + " | ".join(
                f'<a href="/?session={s}">{s}</a>' for s in sessions)
            + "</p>",
        ]
        if records:
            parts.append(f"<h2>Score — {session}</h2>")
            parts.append(_svg_line(score))
            last = records[-1]
            if "update_ratios" in last:
                parts.append("<h2>update:param ratio (last iter, "
                             "log10)</h2><ul>")
                for name, v in last["update_ratios"].items():
                    lg = math.log10(v) if v > 0 else float("-inf")
                    parts.append(f"<li>{name}: {lg:.2f}</li>")
                parts.append("</ul>")
            parts.append("<h2>param norms (last iter)</h2><ul>")
            for name, v in last.get("param_norms", {}).items():
                parts.append(f"<li>{name}: {v:.4f}</li>")
            parts.append("</ul>")
        else:
            parts.append("<p>No records yet.</p>")
        parts.append("</body></html>")
        return "".join(parts)

    # -- server ------------------------------------------------------------
    def start(self):
        import http.server
        import urllib.parse

        ui = self

        class Handler(http.server.BaseHTTPRequestHandler):
            def do_GET(self):
                q = urllib.parse.urlparse(self.path)
                qs = urllib.parse.parse_qs(q.query)
                session = qs.get("session", [None])[0]
                if q.path == "/json":
                    recs = []
                    for st in ui._storages:
                        if session:
                            recs.extend(st.get_records(session))
                    body = json.dumps(recs).encode()
                    ctype = "application/json"
                else:
                    body = ui._render(session).encode()
                    ctype = "text/html"
                self.send_response(200)
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def log_message(self, *a):
                pass

        self._httpd = http.server.ThreadingHTTPServer(
            ("127.0.0.1", self.port), Handler)
        self.port = self._httpd.server_address[1]
        self._thread = threading.Thread(target=self._httpd.serve_forever,
                                        daemon=True)
        self._thread.start()
        return self

    def stop(self):
        if self._httpd:
            self._httpd.shutdown()
            self._httpd.server_close()
            self._httpd = None
