"""Legacy full-batch optimizers + Solver driver.

Reference: ``org.deeplearning4j.optimize.Solver`` (+``.Builder``) and
``org.deeplearning4j.optimize.solvers.*`` — StochasticGradientDescent,
LBFGS, ConjugateGradient, LineGradientDescent, all built on
``BackTrackLineSearch`` and driven by ``model.computeGradientAndScore``.

TPU-native design: each optimizer iteration is ONE jitted update —
LBFGS via ``optax.lbfgs`` (two-loop recursion with zoom line search
inside the jitted update), conjugate gradient as Polak-Ribière+ with a
jitted Armijo backtracking line search (``lax.while_loop``, so the
whole search compiles instead of the reference's per-step host loop).
"""
from __future__ import annotations

from typing import Callable, Optional

import jax
import jax.numpy as jnp
import numpy as np
import optax


def backtrack_line_search(loss_fn: Callable, params, direction, *,
                          initial_step: float = 1.0, c1: float = 1e-4,
                          tau: float = 0.5, max_steps: int = 16):
    """Armijo backtracking (reference BackTrackLineSearch.optimize):
    shrink step until f(p + a·d) ≤ f(p) + c1·a·⟨g,d⟩. One jitted
    while_loop. Returns (step_size, new_loss)."""
    f0, g0 = jax.value_and_grad(loss_fn)(params)
    slope = sum(jnp.sum(d * g) for d, g in
                zip(jax.tree.leaves(direction), jax.tree.leaves(g0)))

    def apply_step(a):
        return jax.tree.map(lambda p, d: p + a * d, params, direction)

    def cond(state):
        a, f_new, it = state
        return jnp.logical_and(it < max_steps,
                               f_new > f0 + c1 * a * slope)

    def body(state):
        a, _, it = state
        a = a * tau
        return a, loss_fn(apply_step(a)), it + 1

    a0 = jnp.asarray(initial_step)
    state = (a0, loss_fn(apply_step(a0)), jnp.asarray(0))
    a, f_new, _ = jax.lax.while_loop(cond, body, state)
    return a, f_new


class BaseOptimizer:
    """Full-batch optimizer over a (params → scalar loss) objective."""

    def __init__(self, max_iterations: int = 100, tol: float = 1e-8):
        self.max_iterations = max_iterations
        self.tol = tol
        self.scores_ = []

    def optimize(self, loss_fn, params):
        raise NotImplementedError


class StochasticGradientDescent(BaseOptimizer):
    """Plain gradient step (reference solvers.StochasticGradientDescent).
    """

    def __init__(self, learning_rate: float = 0.1, **kw):
        super().__init__(**kw)
        self.learning_rate = learning_rate

    def optimize(self, loss_fn, params):
        lr = self.learning_rate

        @jax.jit
        def step(p):
            loss, g = jax.value_and_grad(loss_fn)(p)
            return jax.tree.map(lambda pp, gg: pp - lr * gg, p, g), loss

        for _ in range(self.max_iterations):
            params, loss = step(params)
            self.scores_.append(float(loss))
            if len(self.scores_) > 1 and abs(
                    self.scores_[-2] - self.scores_[-1]) < self.tol:
                break
        return params


class LineGradientDescent(BaseOptimizer):
    """Steepest descent with Armijo line search per iteration
    (reference solvers.LineGradientDescent)."""

    def optimize(self, loss_fn, params):
        @jax.jit
        def step(p):
            g = jax.grad(loss_fn)(p)
            d = jax.tree.map(lambda x: -x, g)
            a, loss = backtrack_line_search(loss_fn, p, d)
            return jax.tree.map(lambda pp, dd: pp + a * dd, p, d), loss

        for _ in range(self.max_iterations):
            params, loss = step(params)
            self.scores_.append(float(loss))
            if len(self.scores_) > 1 and abs(
                    self.scores_[-2] - self.scores_[-1]) < self.tol:
                break
        return params


class ConjugateGradient(BaseOptimizer):
    """Polak-Ribière+ nonlinear CG with Armijo line search
    (reference solvers.ConjugateGradient)."""

    def optimize(self, loss_fn, params):
        @jax.jit
        def step(p, d_prev, g_prev, first):
            g = jax.grad(loss_fn)(p)
            num = sum(jnp.sum(gn * (gn - go)) for gn, go in
                      zip(jax.tree.leaves(g), jax.tree.leaves(g_prev)))
            den = sum(jnp.sum(jnp.square(go))
                      for go in jax.tree.leaves(g_prev))
            beta = jnp.maximum(num / jnp.maximum(den, 1e-12), 0.0)
            beta = jnp.where(first, 0.0, beta)
            d = jax.tree.map(lambda gg, dd: -gg + beta * dd, g, d_prev)
            a, loss = backtrack_line_search(loss_fn, p, d)
            new_p = jax.tree.map(lambda pp, dd: pp + a * dd, p, d)
            return new_p, d, g, loss

        d = jax.tree.map(jnp.zeros_like, params)
        g = jax.tree.map(jnp.ones_like, params)
        first = jnp.asarray(True)
        for _ in range(self.max_iterations):
            params, d, g, loss = step(params, d, g, first)
            first = jnp.asarray(False)
            self.scores_.append(float(loss))
            if len(self.scores_) > 1 and abs(
                    self.scores_[-2] - self.scores_[-1]) < self.tol:
                break
        return params


class LBFGS(BaseOptimizer):
    """Limited-memory BFGS (reference solvers.LBFGS) via ``optax.lbfgs``
    — two-loop recursion + zoom line search inside one jitted update."""

    def __init__(self, memory: int = 10, **kw):
        super().__init__(**kw)
        self.memory = memory

    def optimize(self, loss_fn, params):
        opt = optax.lbfgs(memory_size=self.memory)
        opt_state = opt.init(params)
        value_and_grad = optax.value_and_grad_from_state(loss_fn)

        @jax.jit
        def step(p, s):
            value, grad = value_and_grad(p, state=s)
            updates, s = opt.update(grad, s, p, value=value, grad=grad,
                                    value_fn=loss_fn)
            return optax.apply_updates(p, updates), s, value

        for _ in range(self.max_iterations):
            params, opt_state, loss = step(params, opt_state)
            self.scores_.append(float(loss))
            if len(self.scores_) > 1 and abs(
                    self.scores_[-2] - self.scores_[-1]) < self.tol:
                break
        return params


_ALGOS = {
    "STOCHASTIC_GRADIENT_DESCENT": StochasticGradientDescent,
    "LINE_GRADIENT_DESCENT": LineGradientDescent,
    "CONJUGATE_GRADIENT": ConjugateGradient,
    "LBFGS": LBFGS,
}


class Solver:
    """Reference ``Solver.Builder().model(m).build().optimize()``: runs a
    full-batch optimizer over a network's loss on a DataSet."""

    def __init__(self, net, algo: str = "STOCHASTIC_GRADIENT_DESCENT",
                 max_iterations: int = 100, **algo_kwargs):
        self.net = net
        if algo.upper() not in _ALGOS:
            raise ValueError(f"unknown optimization algo {algo!r}; "
                             f"known: {sorted(_ALGOS)}")
        self.optimizer = _ALGOS[algo.upper()](
            max_iterations=max_iterations, **algo_kwargs)

    class Builder:
        def __init__(self):
            self._net = None
            self._algo = "STOCHASTIC_GRADIENT_DESCENT"
            self._max_iter = 100
            self._kw = {}

        def model(self, net):
            self._net = net
            return self

        def optimization_algo(self, algo: str):
            self._algo = algo
            return self

        def max_iterations(self, n: int):
            self._max_iter = n
            return self

        def configure(self, **kw):
            self._kw.update(kw)
            return self

        def build(self) -> "Solver":
            return Solver(self._net, self._algo, self._max_iter,
                          **self._kw)

    @staticmethod
    def builder() -> "Solver.Builder":
        return Solver.Builder()

    def optimize(self, dataset) -> float:
        """Full-batch optimize the network's params on `dataset`;
        returns the final score."""
        net = self.net
        x = jnp.asarray(np.asarray(dataset.features))
        y = jnp.asarray(np.asarray(dataset.labels))
        state = net.state
        rng = jax.random.PRNGKey(net.conf.seed)

        def loss_fn(params):
            loss, _ = net._loss_fn(params, state, x, y, None, None, rng)
            return loss

        net.params = self.optimizer.optimize(loss_fn, net.params)
        net.score_ = self.optimizer.scores_[-1]
        return net.score_

    @property
    def scores(self):
        return self.optimizer.scores_
