"""Training listeners — reference:
``org.deeplearning4j.optimize.api.TrainingListener`` SPI and impls
(ScoreIterationListener, PerformanceListener, CheckpointListener,
EvaluativeListener — SURVEY §5 metrics/observability).

The listener SPI is the universal hook point around the jitted train
step: iteration_done / on_epoch_start / on_epoch_end.
"""
from __future__ import annotations

import logging
import time
from pathlib import Path
from typing import Optional

logger = logging.getLogger("deeplearning4j_tpu")


class TrainingListener:
    def iteration_done(self, net, iteration: int, epoch: int):
        pass

    def on_epoch_start(self, net):
        pass

    def on_epoch_end(self, net):
        pass


def _step_score(net) -> float:
    """The fit loop's already-computed step loss (``net.score_``) —
    listeners must never call ``net.score()`` per iteration: a
    dataset-scoring override would run an extra forward (device sync,
    possible retrace) just to log a number the step already produced."""
    score = getattr(net, "score_", None)
    return net.score() if score is None else score


class ScoreIterationListener(TrainingListener):
    """Logs score every N iterations (reference ScoreIterationListener)."""

    def __init__(self, print_iterations: int = 10):
        self.n = print_iterations

    def iteration_done(self, net, iteration, epoch):
        if iteration % self.n == 0:
            logger.info("Score at iteration %d is %s", iteration,
                        _step_score(net))


class PerformanceListener(TrainingListener):
    """Throughput/ETL timing (reference PerformanceListener)."""

    def __init__(self, frequency: int = 10, report=None,
                 iterator=None):
        """``iterator``: pass the AsyncDataSetIterator feeding fit() to
        include its cumulative ETL-wait in the report (the reference's
        ETL-time column)."""
        self.frequency = frequency
        self._last_time = None
        self._last_iter = None
        self.samples_per_sec = None
        self._report = report or (lambda msg: logger.info("%s", msg))
        self._batch = None
        self._iterator = iterator
        self._last_etl = 0.0

    def iteration_done(self, net, iteration, epoch):
        now = time.perf_counter()
        if self._last_time is not None and \
                iteration % self.frequency == 0:
            dt = now - self._last_time
            iters = iteration - self._last_iter
            if dt > 0 and iters > 0:
                msg = (f"iter {iteration}: {iters / dt:.1f} iter/sec, "
                       f"score {_step_score(net):.5f}")
                etl = getattr(self._iterator, "etl_wait_seconds", None)
                if etl is not None:
                    msg += (f", ETL wait "
                            f"{(etl - self._last_etl) * 1e3:.1f} ms")
                    self._last_etl = etl
                self._report(msg)
        if iteration % self.frequency == 0:
            self._last_time = now
            self._last_iter = iteration


class CheckpointListener(TrainingListener):
    """Periodic checkpoints with keep-last-K (reference
    CheckpointListener: every N iters/epochs, keepLast policies)."""

    def __init__(self, save_dir, save_every_n_iterations: Optional[int]
                 = None, save_every_n_epochs: Optional[int] = None,
                 keep_last: int = 3, sharded: bool = False):
        """``sharded=True`` switches from the zip ModelSerializer to the
        orbax-backed ShardedCheckpointer (async, tensorstore layout) —
        the multi-host/TP-sharded path; saves don't block the step."""
        self.dir = Path(save_dir)
        self.dir.mkdir(parents=True, exist_ok=True)
        self.every_iter = save_every_n_iterations
        self.every_epoch = save_every_n_epochs
        self.keep_last = keep_last
        self.sharded = sharded
        self._ck = None
        self._last_sharded_step = None

    def _save(self, net, tag: str):
        if self.sharded:
            if self._ck is None:
                from deeplearning4j_tpu.serialization import \
                    ShardedCheckpointer
                self._ck = ShardedCheckpointer(self.dir,
                                               keep_last=self.keep_last)
            # steps are net.iteration: an epoch-end save right after an
            # iteration-triggered one would collide — skip duplicates
            if net.iteration != self._last_sharded_step:
                self._ck.save(net.iteration, net)
                self._last_sharded_step = net.iteration
            return
        from deeplearning4j_tpu.serialization import ModelSerializer
        path = self.dir / f"checkpoint_{tag}.zip"
        ModelSerializer.write_model(net, path)
        ckpts = sorted(self.dir.glob("checkpoint_*.zip"),
                       key=lambda p: p.stat().st_mtime)
        for old in ckpts[:-self.keep_last]:
            old.unlink()
            # drop the CRC manifest sidecar with its checkpoint
            from deeplearning4j_tpu.resilience.checkpoint import \
                manifest_path
            manifest_path(old).unlink(missing_ok=True)

    def iteration_done(self, net, iteration, epoch):
        if self.every_iter and iteration % self.every_iter == 0:
            self._save(net, f"iter_{iteration}")

    def on_epoch_end(self, net):
        if self.every_epoch and (net.epoch + 1) % self.every_epoch == 0:
            self._save(net, f"epoch_{net.epoch}")
        # epoch boundary = async barrier: surfaces any background save
        # error here instead of losing the checkpoint silently
        self.flush()

    def flush(self):
        """Block until pending async sharded saves land (call after a
        batch-API training loop that never crosses an epoch end)."""
        if self._ck is not None:
            self._ck.wait_until_finished()


class EvaluativeListener(TrainingListener):
    """Periodic eval during training (reference EvaluativeListener)."""

    def __init__(self, iterator, frequency_iters: int = 0,
                 frequency_epochs: int = 1, callback=None):
        self.iterator = iterator
        self.frequency_iters = frequency_iters
        self.frequency_epochs = frequency_epochs
        self.callback = callback or (
            lambda e: logger.info("\n%s", e.stats()))
        self.last_evaluation = None

    def _eval(self, net):
        e = net.evaluate(self.iterator)
        self.last_evaluation = e
        self.callback(e)

    def iteration_done(self, net, iteration, epoch):
        if self.frequency_iters and iteration % self.frequency_iters == 0:
            self._eval(net)

    def on_epoch_end(self, net):
        if self.frequency_epochs and \
                (net.epoch + 1) % self.frequency_epochs == 0:
            self._eval(net)


class CollectScoresListener(TrainingListener):
    """Collects (iteration, score) pairs (reference
    CollectScoresIterationListener)."""

    def __init__(self):
        self.scores = []

    def iteration_done(self, net, iteration, epoch):
        self.scores.append((iteration, _step_score(net)))
