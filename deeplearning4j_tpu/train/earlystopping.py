"""Early stopping (reference: ``deeplearning4j-core``
``org.deeplearning4j.earlystopping``: ``EarlyStoppingConfiguration``,
``EarlyStoppingTrainer``, termination conditions
(``MaxEpochsTerminationCondition``, ``MaxTimeIterationTerminationCondition``,
``ScoreImprovementEpochTerminationCondition``, ``MaxScoreIterationTerminationCondition``),
savers (``InMemoryModelSaver``, ``LocalFileModelSaver``),
``EarlyStoppingResult``).
"""
from __future__ import annotations

import copy
import os
import time
from dataclasses import dataclass, field
from typing import Any, Callable, List, Optional

import numpy as np


# --- termination conditions -------------------------------------------------

class EpochTerminationCondition:
    def initialize(self):
        pass

    def terminate(self, epoch: int, score: float) -> bool:
        raise NotImplementedError


class IterationTerminationCondition:
    def initialize(self):
        pass

    def terminate(self, iteration: int, score: float) -> bool:
        raise NotImplementedError


@dataclass
class MaxEpochsTerminationCondition(EpochTerminationCondition):
    max_epochs: int = 10

    def terminate(self, epoch, score):
        return epoch + 1 >= self.max_epochs


@dataclass
class ScoreImprovementEpochTerminationCondition(EpochTerminationCondition):
    """Stop after ``patience`` epochs without ≥``min_improvement`` gain."""
    patience: int = 5
    min_improvement: float = 0.0

    def initialize(self):
        self._best = float("inf")
        self._bad = 0

    def terminate(self, epoch, score):
        if score < self._best - self.min_improvement:
            self._best = score
            self._bad = 0
        else:
            self._bad += 1
        return self._bad > self.patience


@dataclass
class MaxTimeIterationTerminationCondition(IterationTerminationCondition):
    max_seconds: float = 3600.0

    def initialize(self):
        self._t0 = time.time()

    def terminate(self, iteration, score):
        return time.time() - self._t0 > self.max_seconds


@dataclass
class MaxScoreIterationTerminationCondition(IterationTerminationCondition):
    """Abort when the score explodes past a bound (diverged run)."""
    max_score: float = 1e9

    def terminate(self, iteration, score):
        return score > self.max_score or not np.isfinite(score)


# --- score calculators ------------------------------------------------------

class ScoreCalculator:
    def calculate_score(self, net) -> float:
        raise NotImplementedError

    # reference: minimizeScore() — False for accuracy-like scores
    minimize = True


class DataSetLossCalculator(ScoreCalculator):
    """Average loss over a held-out iterator (reference
    DataSetLossCalculator)."""

    def __init__(self, iterator):
        self.iterator = iterator

    def calculate_score(self, net):
        total, n = 0.0, 0
        self.iterator.reset()
        for ds in self.iterator:
            b = len(np.asarray(ds.features))
            total += net.score(ds) * b
            n += b
        return total / max(n, 1)


class ClassificationScoreCalculator(ScoreCalculator):
    """Held-out accuracy/F1 (reference ClassificationScoreCalculator)."""
    minimize = False

    def __init__(self, iterator, metric: str = "accuracy"):
        self.iterator = iterator
        self.metric = metric

    def calculate_score(self, net):
        self.iterator.reset()
        ev = net.evaluate(self.iterator)
        return getattr(ev, self.metric)()


# --- model savers -----------------------------------------------------------

class InMemoryModelSaver:
    def __init__(self):
        self._best = None
        self._latest = None

    def save_best_model(self, net, score):
        self._best = (net.clone(), score)

    def save_latest_model(self, net, score):
        self._latest = (net.clone(), score)

    def get_best_model(self):
        return self._best[0] if self._best else None

    def get_latest_model(self):
        return self._latest[0] if self._latest else None


class LocalFileModelSaver:
    """Zip-format persistence of best/latest (reference
    LocalFileModelSaver + ModelSerializer)."""

    def __init__(self, directory: str):
        self.dir = directory
        os.makedirs(directory, exist_ok=True)

    def _path(self, kind):
        return os.path.join(self.dir, f"{kind}Model.zip")

    def save_best_model(self, net, score):
        from deeplearning4j_tpu.serialization import ModelSerializer
        ModelSerializer.write_model(net, self._path("best"))

    def save_latest_model(self, net, score):
        from deeplearning4j_tpu.serialization import ModelSerializer
        ModelSerializer.write_model(net, self._path("latest"))

    def get_best_model(self):
        from deeplearning4j_tpu.serialization import ModelSerializer
        p = self._path("best")
        return ModelSerializer.restore_multi_layer_network(p) \
            if os.path.exists(p) else None

    def get_latest_model(self):
        from deeplearning4j_tpu.serialization import ModelSerializer
        p = self._path("latest")
        return ModelSerializer.restore_multi_layer_network(p) \
            if os.path.exists(p) else None


# --- configuration / result / trainer --------------------------------------

@dataclass
class EarlyStoppingConfiguration:
    score_calculator: Optional[ScoreCalculator] = None
    epoch_terminations: List[EpochTerminationCondition] = field(
        default_factory=list)
    iteration_terminations: List[IterationTerminationCondition] = field(
        default_factory=list)
    model_saver: Any = None
    evaluate_every_n_epochs: int = 1
    save_last_model: bool = False

    def __post_init__(self):
        if self.model_saver is None:
            self.model_saver = InMemoryModelSaver()


@dataclass
class EarlyStoppingResult:
    termination_reason: str          # "EpochTermination" | ...
    termination_details: str
    best_model_epoch: int
    best_model_score: float
    total_epochs: int
    best_model: Any
    score_vs_epoch: dict = field(default_factory=dict)


class EarlyStoppingTrainer:
    """Reference: EarlyStoppingTrainer (BaseEarlyStoppingTrainer.fit)."""

    def __init__(self, config: EarlyStoppingConfiguration, net,
                 train_iterator):
        self.config = config
        self.net = net
        self.iterator = train_iterator

    def fit(self) -> EarlyStoppingResult:
        cfg = self.config
        if not cfg.epoch_terminations and not cfg.iteration_terminations:
            raise ValueError(
                "EarlyStoppingConfiguration has no termination "
                "conditions — training would never stop; add e.g. "
                "MaxEpochsTerminationCondition")
        for c in cfg.epoch_terminations + cfg.iteration_terminations:
            c.initialize()
        sign = 1.0 if (cfg.score_calculator is None
                       or cfg.score_calculator.minimize) else -1.0
        best_score, best_epoch = float("inf"), -1
        scores = {}
        epoch = 0
        reason, details = "EpochTermination", "no condition fired"

        while True:
            self.iterator.reset()
            aborted = False
            for ds in self.iterator:
                self.net.fit(ds)
                it_score = self.net.score_
                for c in cfg.iteration_terminations:
                    if c.terminate(self.net.iteration, it_score):
                        reason = "IterationTermination"
                        details = f"{type(c).__name__} at iteration " \
                                  f"{self.net.iteration}"
                        aborted = True
                        break
                if aborted:
                    break

            if not aborted:
                # score calculation is throttled; termination checks run
                # EVERY epoch with the latest score (reference
                # BaseEarlyStoppingTrainer semantics — MaxEpochs must
                # not overshoot when evaluation is infrequent)
                if epoch % cfg.evaluate_every_n_epochs == 0:
                    score = (cfg.score_calculator.calculate_score(self.net)
                             if cfg.score_calculator else self.net.score_)
                    scores[epoch] = score
                    if sign * score < best_score:
                        best_score = sign * score
                        best_epoch = epoch
                        cfg.model_saver.save_best_model(self.net, score)
                    if cfg.save_last_model:
                        cfg.model_saver.save_latest_model(self.net, score)
                last_score = scores[max(scores)] if scores \
                    else self.net.score_
                for c in cfg.epoch_terminations:
                    if c.terminate(epoch, sign * last_score):
                        reason = "EpochTermination"
                        details = f"{type(c).__name__} at epoch {epoch}"
                        aborted = True
                        break

            epoch += 1
            if aborted:
                break

        best = cfg.model_saver.get_best_model() or self.net
        return EarlyStoppingResult(
            termination_reason=reason, termination_details=details,
            best_model_epoch=best_epoch,
            best_model_score=sign * best_score if best_epoch >= 0
            else float("nan"),
            total_epochs=epoch, best_model=best, score_vs_epoch=scores)
