"""Fault-tolerant training — checkpoint-based automatic restart.

Reference (SURVEY §5 "Failure detection / elastic recovery"): the
reference has no in-framework elasticity; its recovery story is
CheckpointListener + ModelSerializer resume, with Spark-level task
retry re-running failed partitions. On TPU the idiom is the same at
slice level: when a host/chip fails, the jax coordination service
tears the job down and the RESTARTED job resumes from the last
checkpoint. This module packages that idiom, hardened by the
resilience subsystem (ARCHITECTURE.md §10):

- in-process: ``FaultTolerantTrainer.fit`` retries around exceptions
  under a ``resilience.policy.RetryPolicy`` — exponential backoff with
  seeded jitter for transient errors (IO flakes, chip drops), at most
  ONE restore-and-retry for deterministic ones (shape/dtype/NaN —
  re-raised loudly instead of burning every restart), restoring the
  newest *valid* checkpoint (corrupt ones quarantined to ``corrupt/``).
- preemption: SIGTERM (the notice preemptible TPU slices get) is
  honored at the next iteration boundary — checkpoint, persist
  progress, return cleanly (exit code 0; the restarted job resumes).
- mid-epoch continuity: ``progress.json`` carries the iterator
  position (``batch_in_epoch``) alongside the counters, so a resumed
  run skips the batches the checkpoint already trained on and replays
  the exact uninterrupted trajectory (same per-iteration rng folds).
- cross-process: ``resume_or_init`` loads the newest valid checkpoint
  if one exists, so the training script is restart-idempotent (the
  reference's Spark-driver-resubmit pattern without Spark).
"""
from __future__ import annotations

import json
import logging
import time
from pathlib import Path
from typing import Callable, Optional

from deeplearning4j_tpu import obs
from deeplearning4j_tpu.resilience import checkpoint as rck
from deeplearning4j_tpu.resilience.policy import (Preempted,
                                                  PreemptionHandler,
                                                  RetryPolicy, classify,
                                                  describe)

logger = logging.getLogger("deeplearning4j_tpu")


def newest_checkpoint(checkpoint_dir) -> Optional[Path]:
    """Newest *valid* checkpoint: candidates are verified (zip CRC
    sweep + required entries + manifest when present) newest-first;
    corrupt/partial files are quarantined to ``corrupt/`` with a
    warning instead of being handed to the restart loop."""
    return rck.newest_valid_checkpoint(checkpoint_dir)


def _restore_net(ckpt_path, template=None):
    """Restore the right network type for the checkpoint: from the
    template net when one is in hand, else from the checkpoint's own
    configuration.json (a ComputationGraph config carries node/input
    declarations; an MLN config carries a layer list)."""
    import json
    import zipfile
    from deeplearning4j_tpu.serialization import ModelSerializer
    if template is not None:
        is_graph = hasattr(template.conf, "inputs")
    else:
        with zipfile.ZipFile(ckpt_path) as zf:
            cj = json.loads(zf.read("configuration.json").decode())
        is_graph = "nodes" in cj
    if is_graph:
        return ModelSerializer.restore_computation_graph(str(ckpt_path))
    return ModelSerializer.restore_multi_layer_network(str(ckpt_path))


def read_progress(checkpoint_dir) -> dict:
    """``progress.json`` contents (``{}`` when absent/torn — a torn
    progress file must never block a restart)."""
    p = Path(checkpoint_dir) / "progress.json"
    try:
        return json.loads(p.read_text()) if p.exists() else {}
    except (OSError, ValueError):
        return {}


def resume_or_init(net_factory: Callable[[], "object"],
                   checkpoint_dir) -> "object":
    """Restart-idempotent bring-up: newest VALID checkpoint if present,
    else a fresh net from the factory (call this at the top of a
    training script; re-running the script after a slice restart — or
    a preemption — resumes)."""
    ckpt = newest_checkpoint(checkpoint_dir)
    if ckpt is not None:
        logger.info("resuming from %s", ckpt)
        net = _restore_net(ckpt)
        state = read_progress(checkpoint_dir)
        # fast-forward the epoch counter only when progress describes
        # THIS checkpoint (same iteration): a stale file — crash
        # between the checkpoint and progress writes, or a quarantined
        # newer checkpoint — must never desync counters from params
        if state.get("iteration") == net.iteration:
            net.epoch = max(net.epoch, state.get("epoch", net.epoch))
        return net
    return net_factory()


class _SkipBatches:
    """One-epoch iterator view that drops the first ``skip`` batches —
    resuming a mid-epoch restore at its persisted position so the
    replayed epoch matches the uninterrupted one batch-for-batch."""

    def __init__(self, base, skip: int):
        self.base = base
        self.skip = int(skip)

    def __len__(self):
        return max(0, len(self.base) - self.skip)

    def reset(self):
        if hasattr(self.base, "reset"):
            self.base.reset()

    def __iter__(self):
        it = iter(self.base)
        for _ in range(self.skip):
            try:
                next(it)
            except StopIteration:
                return
        yield from it


class _ProgressTracker:
    """Listener that (a) maintains the mid-epoch batch position, (b)
    persists ``progress.json`` at the checkpoint cadence, (c) turns a
    pending preemption notice into control flow at the iteration
    boundary — the only safe place to stop a train loop."""

    def __init__(self, trainer: "FaultTolerantTrainer"):
        self.trainer = trainer
        self._cur_epoch: Optional[int] = None
        self._epoch_start_iter = 0

    def reset_epoch_tracking(self):
        self._cur_epoch = None

    def iteration_done(self, net, iteration, epoch):
        t = self.trainer
        if self._cur_epoch != epoch:
            # first completed batch of this epoch (works for fit loops
            # without epoch hooks, e.g. ParallelWrapper)
            self._cur_epoch = epoch
            self._epoch_start_iter = iteration - 1
        t._batch_in_epoch = t._skip + (iteration - self._epoch_start_iter)
        if t.every_iter and iteration % t.every_iter == 0:
            t._save_progress()
        if t._preemption is not None and t._preemption.requested:
            raise Preempted()

    def on_epoch_start(self, net):
        pass

    def on_epoch_end(self, net):
        pass


class FaultTolerantTrainer:
    """fit() that survives mid-training failures by restoring the last
    valid checkpoint and continuing under a retry policy, and honors
    SIGTERM preemption by checkpointing and returning cleanly
    (reference analog: Spark task retry + CheckpointListener, SURVEY
    §5 — hardened per ARCHITECTURE.md §10).

    ``train_with``: optional trainer object whose ``fit(iterator,
    epochs=...)`` drives the epochs (e.g. a ``ParallelWrapper``);
    defaults to ``net`` itself. ``policy``: a
    ``resilience.policy.RetryPolicy`` (default: ``max_restarts``
    retries, 50 ms base backoff)."""

    def __init__(self, net, checkpoint_dir,
                 save_every_n_iterations: int = 50,
                 keep_last: int = 3, max_restarts: int = 3,
                 policy: Optional[RetryPolicy] = None,
                 handle_preemption: bool = True,
                 train_with=None):
        from deeplearning4j_tpu.train.listeners import CheckpointListener
        self.net = net
        self.dir = Path(checkpoint_dir)
        self.dir.mkdir(parents=True, exist_ok=True)
        self.max_restarts = max_restarts
        self.every_iter = save_every_n_iterations
        self.policy = policy or RetryPolicy(max_retries=max_restarts)
        self.handle_preemption = handle_preemption
        self.train_with = train_with
        self._listener = CheckpointListener(
            self.dir, save_every_n_iterations=save_every_n_iterations,
            keep_last=keep_last)
        self._keep_last = keep_last
        self._sharded = None        # lazy ShardedCheckpointer
        self._tracker = _ProgressTracker(self)
        self._preemption: Optional[PreemptionHandler] = None
        self._skip = 0              # batches to drop in the next epoch
        self._batch_in_epoch = 0    # live mid-epoch position
        self._det_restored = False  # deterministic error: one restore
        self.restarts = 0
        self.preempted = False

    def _save_progress(self):
        rck.atomic_write_bytes(self.dir / "progress.json", json.dumps(
            {"epoch": self.net.epoch,
             "iteration": self.net.iteration,
             "batch_in_epoch": self._batch_in_epoch,
             "time": time.time()}).encode())

    # -- ZeRO sharded-update integration (PR 5 x PR 3 interplay) --------
    def _sharded_wrapper(self):
        """The ``train_with`` wrapper when it carries its optimizer
        state as 1/N ZeRO shards — the case where the replicated zip
        path would have to materialize N× the live footprint just to
        stop cleanly."""
        tw = self.train_with
        return tw if tw is not None and \
            getattr(tw, "sharded_update", False) else None

    def _sharded_ck(self):
        if self._sharded is None:
            from deeplearning4j_tpu.serialization import \
                ShardedCheckpointer
            self._sharded = ShardedCheckpointer(
                self.dir / "sharded", keep_last=self._keep_last,
                async_save=False)
        return self._sharded

    def _newest_sharded_step(self) -> Optional[int]:
        if not (self.dir / "sharded").is_dir():
            return None
        steps = self._sharded_ck().all_steps()
        return max(steps) if steps else None

    def _restore_sharded(self, min_iteration: int = -1) -> bool:
        """Newest-valid sharded restore into the wrapper (quarantining
        corrupt step dirs, resharding onto the wrapper's world size if
        the checkpoint was written at a different one). Returns False
        when nothing restorable remains — OR when the step the
        fallback actually landed on is older than ``min_iteration``
        (the valid zip the caller holds): the newest SHARDED step
        being ahead of the zip says nothing until it verifies, so the
        comparison must be re-made after the fallback resolves and the
        caller must then restore its newer zip over this state."""
        tw = self._sharded_wrapper()
        try:
            self._sharded_ck().restore_latest_valid(wrapper=tw)
        except FileNotFoundError:
            return False
        if self.net.iteration < min_iteration:
            return False
        prog = read_progress(self.dir)
        if prog.get("iteration") == self.net.iteration:
            self.net.epoch = max(self.net.epoch,
                                 prog.get("epoch", self.net.epoch))
            self._skip = prog.get("batch_in_epoch", 0)
        else:
            self._skip = 0
        self._batch_in_epoch = self._skip
        self._tracker.reset_epoch_tracking()
        return True

    def _checkpoint_now(self):
        """Synchronous checkpoint + progress (preemption path). A
        ZeRO sharded-update wrapper publishes through
        ``ShardedCheckpointer.save_wrapper`` — each device writes only
        its 1/N optimizer shard — NOT the replicated zip path, whose
        gather would materialize exactly the N copies the sharded
        mode exists to avoid, in the narrow shutdown window a
        preemption notice leaves."""
        tw = self._sharded_wrapper()
        if tw is not None:
            ck = self._sharded_ck()
            if self.net.iteration not in ck.all_steps():
                # an existing step IS this iteration's state (e.g. a
                # second preemption before any progress) — orbax
                # refuses to overwrite, and there is nothing to add
                ck.save_wrapper(self.net.iteration, tw, wait=True)
            self._save_progress()
            return
        self._listener._save(self.net, f"iter_{self.net.iteration}")
        self._listener.flush()
        self._save_progress()

    @staticmethod
    def _zip_iteration(ckpt_path) -> int:
        """The iteration a zip checkpoint was cut at (its meta.json);
        -1 for anything unreadable — the caller treats it as older
        than any sharded step."""
        import zipfile
        try:
            with zipfile.ZipFile(ckpt_path) as zf:
                return int(json.loads(
                    zf.read("meta.json").decode()).get("iteration", -1))
        except Exception:
            return -1

    def _restore(self, e) -> None:
        """Restore the newest valid checkpoint into ``self.net`` (in
        place) and set the mid-epoch skip; no checkpoint → continue
        from in-memory params (the failed epoch restarts). When the
        trainer drives a ZeRO sharded-update wrapper, the newest
        checkpoint may be a SHARDED one (the preemption path writes
        those): the newer of the two chains wins, and the sharded
        restore reshards onto the current world size if it has to."""
        ckpt = newest_checkpoint(self.dir)
        if self._sharded_wrapper() is not None:
            sh_step = self._newest_sharded_step()
            zip_iter = self._zip_iteration(ckpt) if ckpt is not None \
                else -1
            if sh_step is not None and sh_step >= zip_iter:
                logger.warning(
                    "training failure (%s); restoring sharded "
                    "checkpoint step %d (restart %d/%d)", describe(e),
                    sh_step, self.restarts, self.max_restarts)
                # min_iteration: if the newest sharded steps turn out
                # corrupt and the fallback lands BELOW the valid zip,
                # fall through and let the zip restore win
                if self._restore_sharded(min_iteration=zip_iter):
                    return
        if ckpt is None:
            logger.warning(
                "failure before first checkpoint (%s); "
                "restarting epoch from in-memory params", e)
            self._skip = 0
            self._tracker.reset_epoch_tracking()
            return
        logger.warning("training failure (%s); restoring %s "
                       "(restart %d/%d)", describe(e), ckpt,
                       self.restarts, self.max_restarts)
        t0 = obs.now()
        restored = _restore_net(ckpt, template=self.net)
        net = self.net
        net.params = restored.params
        net.opt_state = restored.opt_state
        net.state = restored.state
        net.epoch = restored.epoch          # rewind counters to
        net.iteration = restored.iteration  # the checkpoint
        net._train_loop_fn = None     # re-jit with fresh buffers
        # resume at the persisted iterator position — only when the
        # progress file describes THIS checkpoint. The epoch max()
        # covers the boundary case: a checkpoint cut at an epoch's
        # last iteration carries the pre-increment epoch in its meta,
        # while progress (written at epoch end) has the completed one —
        # without it the whole epoch would be silently retrained.
        prog = read_progress(self.dir)
        if prog.get("iteration") == net.iteration:
            net.epoch = max(net.epoch, prog.get("epoch", net.epoch))
            self._skip = prog.get("batch_in_epoch", 0)
        else:
            self._skip = 0
        tw = self.train_with
        if tw is not None and getattr(tw, "_dp_state", None) is not None:
            # a ParallelWrapper's mode-specific device state (replica
            # params, residuals, in-flight queues) still reflects the
            # pre-failure run — drop it so _prepare() rebuilds it from
            # the RESTORED params; otherwise AVERAGING/ASYNC would keep
            # training un-restored replicas and _sync_back would
            # overwrite the restore at fit() end
            tw._dp_state = None
        if tw is not None and getattr(tw, "mode", None) in ("averaging",
                                                            "async"):
            # replica modes publish net.params only at _sync_back, so a
            # mid-epoch checkpoint holds epoch-START params: replay the
            # whole epoch instead of skipping batches those params
            # never trained on
            self._skip = 0
        self._batch_in_epoch = self._skip
        self._tracker.reset_epoch_tracking()
        if obs.trace.enabled():
            obs.trace.add_span("resilience/restore", t0, obs.now(),
                               args={"checkpoint": str(ckpt),
                                     "skip_batches": self._skip})

    def fit(self, iterator, epochs: int = 1):
        net = self.net
        trainer = self.train_with if self.train_with is not None else net
        for l in (self._listener, self._tracker):
            if l not in net.listeners:
                net.listeners.append(l)
        if self.handle_preemption and self._preemption is None:
            try:
                self._preemption = PreemptionHandler().install()
            except ValueError:      # not the main thread: poll-only
                self._preemption = None
        # sharded-chain resume: a preemption (or elastic departure)
        # under a ZeRO wrapper published 1/N shards, which the zip
        # scan of resume_or_init cannot see — restore them here when
        # they are newer than whatever the net already carries,
        # resharding onto the current topology if the world size
        # changed between the save and this restart
        if self._sharded_wrapper() is not None:
            sh_step = self._newest_sharded_step()
            if sh_step is not None and sh_step > net.iteration:
                logger.info("resuming from sharded checkpoint step %d",
                            sh_step)
                if not self._restore_sharded(
                        min_iteration=net.iteration):
                    # the sharded fallback landed on a step older than
                    # the state the net already carried (a zip-restored
                    # net, overwritten just now): put the newer zip
                    # state back
                    self._restore(RuntimeError(
                        "sharded chain fell back below the zip state"))
        # cross-process mid-epoch resume: a net brought up by
        # resume_or_init after a preemption/crash carries counters that
        # match progress.json — honor its batch_in_epoch so the resumed
        # epoch skips the batches the checkpoint already trained on.
        # Replica-state wrapper modes (averaging/async) are excluded:
        # they publish net.params only at _sync_back, so a mid-epoch
        # checkpoint holds epoch-START params and the epoch must replay
        # in full (same guard as _restore).
        if self._skip == 0 and net.iteration > 0 and \
                getattr(trainer, "mode", None) not in ("averaging",
                                                       "async"):
            prog = read_progress(self.dir)
            if prog.get("iteration") == net.iteration and \
                    prog.get("epoch", net.epoch) == net.epoch:
                self._skip = prog.get("batch_in_epoch", 0)
                self._batch_in_epoch = self._skip
        target_epoch = net.epoch + epochs
        try:
            while net.epoch < target_epoch:
                try:
                    it = _SkipBatches(iterator, self._skip) \
                        if self._skip else iterator
                    trainer.fit(it, epochs=1)
                    self._skip = 0
                    self._det_restored = False
                    self._batch_in_epoch = 0
                    self._save_progress()
                except Preempted:
                    self.preempted = True
                    obs.metrics.PREEMPTIONS.inc()
                    logger.warning(
                        "preemption: checkpointing at iteration %d and "
                        "stopping cleanly", net.iteration)
                    self._checkpoint_now()
                    break
                except KeyboardInterrupt:
                    raise
                except Exception as e:
                    kind = classify(e)
                    self.restarts += 1
                    obs.metrics.RESILIENCE_RESTARTS.inc()
                    if self.restarts > self.max_restarts:
                        raise RuntimeError(
                            f"training failed {self.restarts} times; "
                            f"last error: {e}") from e
                    if kind == "deterministic":
                        if self._det_restored:
                            raise   # one restore did not clear it
                        self._det_restored = True
                    else:
                        time.sleep(self.policy.delay(self.restarts))
                    self._restore(e)
        finally:
            if self._preemption is not None:
                self._preemption.uninstall()
                self._preemption = None
        return net
