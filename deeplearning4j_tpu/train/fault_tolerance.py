"""Fault-tolerant training — checkpoint-based automatic restart.

Reference (SURVEY §5 "Failure detection / elastic recovery"): the
reference has no in-framework elasticity; its recovery story is
CheckpointListener + ModelSerializer resume, with Spark-level task
retry re-running failed partitions. On TPU the idiom is the same at
slice level: when a host/chip fails, the jax coordination service
tears the job down and the RESTARTED job resumes from the last
checkpoint. This module packages that idiom:

- in-process: ``FaultTolerantTrainer.fit`` retries around exceptions,
  restoring the newest checkpoint (the Spark-task-retry analog).
- cross-process: run the same code after a slice restart —
  ``resume_or_init`` loads the newest checkpoint if one exists, so the
  training script is restart-idempotent (the reference's
  Spark-driver-resubmit pattern without Spark).
"""
from __future__ import annotations

import json
import logging
import time
from pathlib import Path
from typing import Callable, Optional

logger = logging.getLogger("deeplearning4j_tpu")


def newest_checkpoint(checkpoint_dir) -> Optional[Path]:
    ckpts = sorted(Path(checkpoint_dir).glob("checkpoint_*.zip"),
                   key=lambda p: p.stat().st_mtime)
    return ckpts[-1] if ckpts else None


def resume_or_init(net_factory: Callable[[], "object"],
                   checkpoint_dir) -> "object":
    """Restart-idempotent bring-up: newest checkpoint if present, else a
    fresh net from the factory (call this at the top of a training
    script; re-running the script after a slice restart resumes)."""
    ckpt = newest_checkpoint(checkpoint_dir)
    if ckpt is not None:
        from deeplearning4j_tpu.serialization import ModelSerializer
        logger.info("resuming from %s", ckpt)
        net = ModelSerializer.restore_multi_layer_network(str(ckpt))
        meta = Path(checkpoint_dir) / "progress.json"
        if meta.exists():
            state = json.loads(meta.read_text())
            net.epoch = state.get("epoch", net.epoch)
            net.iteration = state.get("iteration", net.iteration)
        return net
    return net_factory()


class FaultTolerantTrainer:
    """fit() that survives mid-training failures by restoring the last
    checkpoint and continuing (reference analog: Spark task retry +
    CheckpointListener, SURVEY §5)."""

    def __init__(self, net, checkpoint_dir,
                 save_every_n_iterations: int = 50,
                 keep_last: int = 3, max_restarts: int = 3):
        from deeplearning4j_tpu.train.listeners import CheckpointListener
        self.net = net
        self.dir = Path(checkpoint_dir)
        self.dir.mkdir(parents=True, exist_ok=True)
        self.max_restarts = max_restarts
        self._listener = CheckpointListener(
            self.dir, save_every_n_iterations=save_every_n_iterations,
            keep_last=keep_last)
        self.restarts = 0

    def _save_progress(self):
        (self.dir / "progress.json").write_text(json.dumps(
            {"epoch": self.net.epoch,
             "iteration": self.net.iteration,
             "time": time.time()}))

    def fit(self, iterator, epochs: int = 1):
        from deeplearning4j_tpu.serialization import ModelSerializer
        if self._listener not in self.net.listeners:
            self.net.listeners.append(self._listener)
        target_epoch = self.net.epoch + epochs
        while self.net.epoch < target_epoch:
            try:
                self.net.fit(iterator,
                             epochs=target_epoch - self.net.epoch)
                self._save_progress()
            except KeyboardInterrupt:
                raise
            except Exception as e:
                self.restarts += 1
                if self.restarts > self.max_restarts:
                    raise RuntimeError(
                        f"training failed {self.restarts} times; "
                        f"last error: {e}") from e
                ckpt = newest_checkpoint(self.dir)
                if ckpt is None:
                    logger.warning(
                        "failure before first checkpoint (%s); "
                        "restarting epoch from in-memory params", e)
                    continue
                logger.warning("training failure (%s); restoring %s "
                               "(restart %d/%d)", e, ckpt,
                               self.restarts, self.max_restarts)
                restored = ModelSerializer.restore_multi_layer_network(
                    str(ckpt))
                net = self.net
                net.params = restored.params
                net.opt_state = restored.opt_state
                net.state = restored.state
                net.epoch = restored.epoch          # rewind counters to
                net.iteration = restored.iteration  # the checkpoint
                net._train_loop_fn = None     # re-jit with fresh buffers
        return self.net
