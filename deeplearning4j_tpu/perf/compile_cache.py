"""Persistent XLA compilation cache — compiles survive the process.

Every fresh process pays full XLA compilation on the first step of
every ``(model, bucket)`` pair; on TPU a big train step is tens of
seconds. JAX ships the fix (``jax_compilation_cache_dir``: serialized
executables keyed by HLO + compile options, shared on disk) and this
module wires it into the tier-2 flag system: :func:`configure_from_env`
runs at package import, so restarts, ``ParallelWrapper`` worker
processes and ``tests/mp_harness.py`` children all reuse each other's
compiles with zero per-callsite code.

Flags (``environment.py``):

- ``DL4J_TPU_COMPILE_CACHE`` — cache dir (default
  ``~/.dl4j_tpu/compile_cache``, applied only when a non-CPU platform
  is configured — see :func:`configure`; '' / '0' / 'off' / 'none'
  disables).
- ``DL4J_TPU_COMPILE_CACHE_MIN_BYTES`` / ``_MIN_SECS`` — eligibility
  floors (both default to "cache everything": first-request latency is
  the target, and small entries are exactly the many-bucket serving
  case).

Hit/miss counters come from ``jax.monitoring`` events and surface in
:func:`cache_stats` (consumed by ``bench.py`` and the perf dossier).
"""
from __future__ import annotations

import os
import threading
from typing import Any, Dict, Optional

_LOCK = threading.Lock()
_state: Dict[str, Any] = {
    "dir": None,             # active cache dir (None -> disabled)
    "store": None,           # CompileStore when routed through one
    "listeners": False,      # monitoring listeners installed
    "requests": 0,           # compile requests eligible for the cache
    "hits": 0,               # persistent-cache hits
}

_DISABLED = {"", "0", "off", "none", "false", "disabled"}


def _on_event(event: str, **kw) -> None:
    if event.endswith("/compilation_cache/compile_requests_use_cache"):
        with _LOCK:
            _state["requests"] += 1
    elif event.endswith("/compilation_cache/cache_hits"):
        with _LOCK:
            _state["hits"] += 1


def _install_listeners() -> None:
    if _state["listeners"]:
        return
    try:
        import jax.monitoring
        jax.monitoring.register_event_listener(_on_event)
        _state["listeners"] = True
    except Exception:       # monitoring API moved/absent: keep serving
        pass


def _accelerator_configured() -> bool:
    """True when the process has a non-CPU platform explicitly
    configured (the TPU box's sitecustomize pins ``axon,cpu``). Read
    from config/env only — never from ``jax.devices()``, which would
    initialize a backend at package import. Auto-detect (nothing
    configured) counts as False: the default-on cache must never reach
    a plain-CPU process."""
    import jax
    plats = (jax.config.jax_platforms
             or os.environ.get("JAX_PLATFORMS", ""))
    names = [p.strip() for p in str(plats).split(",") if p.strip()]
    return any(n != "cpu" for n in names)


def configure(cache_dir: Optional[str] = None,
              min_entry_size_bytes: Optional[int] = None,
              min_compile_time_secs: Optional[float] = None
              ) -> Optional[str]:
    """Point JAX's persistent compilation cache at ``cache_dir``
    (created if missing) and drop the eligibility floors. Arguments
    default to the ``DL4J_TPU_COMPILE_CACHE*`` flags. Returns the
    active dir, or None when disabled. Safe to call repeatedly and
    before/after backends initialize (``jax.config`` updates apply to
    subsequent compiles).

    The DEFAULT dir applies only when a non-CPU platform is configured:
    jaxlib 0.4.x can segfault deserializing some XLA:CPU executables
    from the cache (measured here: the pretrained-zoo forward), so
    CPU processes get caching only via an explicit
    ``DL4J_TPU_COMPILE_CACHE`` env var / ``cache_dir`` argument."""
    from deeplearning4j_tpu import environment
    import jax

    store = None
    if cache_dir is None:
        # the content-addressed fleet store (perf/compile_store.py)
        # supersedes the flat cache dir when configured: its fenced
        # xla/ plane becomes the JAX cache dir, so a jaxlib/topology
        # change can never serve a stale executable. Explicit opt-in,
        # so it works on CPU too (same contract as an explicit
        # DL4J_TPU_COMPILE_CACHE).
        from deeplearning4j_tpu.perf import compile_store
        store = compile_store.from_env()
        if store is not None:
            cache_dir = str(store.xla_dir)
        elif "DL4J_TPU_COMPILE_CACHE" not in os.environ \
                and not _accelerator_configured():
            with _LOCK:
                _state["dir"] = None
                _state["store"] = None
            return None
        else:
            cache_dir = environment.get_flag("DL4J_TPU_COMPILE_CACHE")
    if cache_dir is None or str(cache_dir).strip().lower() in _DISABLED:
        with _LOCK:
            _state["dir"] = None
            _state["store"] = None
        return None
    cache_dir = os.path.expanduser(str(cache_dir))
    os.makedirs(cache_dir, exist_ok=True)
    if min_entry_size_bytes is None:
        min_entry_size_bytes = environment.get_flag(
            "DL4J_TPU_COMPILE_CACHE_MIN_BYTES")
    if min_compile_time_secs is None:
        min_compile_time_secs = environment.get_flag(
            "DL4J_TPU_COMPILE_CACHE_MIN_SECS")
    jax.config.update("jax_compilation_cache_dir", cache_dir)
    # the floors are newer knobs — a missing one must not take the
    # whole cache down with it
    for knob, val in (
            ("jax_persistent_cache_min_entry_size_bytes",
             int(min_entry_size_bytes)),
            ("jax_persistent_cache_min_compile_time_secs",
             float(min_compile_time_secs))):
        try:
            jax.config.update(knob, val)
        except Exception:
            pass
    _install_listeners()
    with _LOCK:
        _state["dir"] = cache_dir
        _state["store"] = store
    return cache_dir


def active_store():
    """The :class:`~deeplearning4j_tpu.perf.compile_store.CompileStore`
    the cache is routed through, or None (flat dir / disabled)."""
    return _state["store"]


def configure_from_env() -> Optional[str]:
    """Import-time entry point (called from the package ``__init__``):
    configure entirely from flags, never raise — an unwritable cache
    dir degrades to no caching, not an import error."""
    try:
        return configure()
    except Exception:
        with _LOCK:
            _state["dir"] = None
            _state["store"] = None
        return None


def cache_dir() -> Optional[str]:
    return _state["dir"]


def counters() -> Dict[str, int]:
    """In-process compile-request/hit counters only — no disk walk, so
    safe on the per-iteration training hot path (``cache_stats`` walks
    the whole cache dir and belongs in once-per-run reporters)."""
    with _LOCK:
        requests, hits = _state["requests"], _state["hits"]
    return {"compile_requests": requests, "persistent_hits": hits,
            "persistent_misses": max(0, requests - hits)}


def cache_stats() -> Dict[str, Any]:
    """On-disk + in-process view of the persistent cache: entry count
    and bytes in the dir, and this process's eligible compile requests
    vs persistent hits (misses = requests - hits; a miss is a compile
    another process can now skip)."""
    d = _state["dir"]
    entries = 0
    size = 0
    if d and os.path.isdir(d):
        for root, _dirs, files in os.walk(d):
            for f in files:
                if f.endswith("-atime"):    # LRU bookkeeping, not entries
                    continue
                entries += 1
                try:
                    size += os.path.getsize(os.path.join(root, f))
                except OSError:
                    pass
    with _LOCK:
        requests, hits = _state["requests"], _state["hits"]
        store = _state["store"]
    out = {
        "dir": d,
        "enabled": d is not None,
        "entries": entries,
        "bytes": size,
        "compile_requests": requests,
        "persistent_hits": hits,
        "persistent_misses": max(0, requests - hits),
    }
    if store is not None:
        out["store_fence"] = store.fence
        out["store"] = store.counters()
    return out


def reset_counters() -> None:
    with _LOCK:
        _state["requests"] = _state["hits"] = 0
