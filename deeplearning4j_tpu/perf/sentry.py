"""Retrace sentry — trace/compile accounting for every jitted hot path.

TPU-native necessity with no reference equivalent (the reference's
eager kernels never compile): on XLA every distinct argument signature
(pytree structure + leaf shapes/dtypes) traced through a ``jax.jit``
entry point costs a full recompile. A retrace storm — e.g. an
unbucketed sequence length slipping past ``BucketedSequenceIterator``,
or a serving queue fed raw request sizes — degrades throughput
silently: every "step" is really a compile.

:func:`jit` is a drop-in for ``jax.jit`` that counts distinct traced
avals per function, records compile wall-time, and warns (or raises
under :func:`strict` / ``DL4J_TPU_RETRACE_STRICT``) once the number of
UNPLANNED signatures exceeds the budget (``DL4J_TPU_RETRACE_BUDGET``).
Shapes registered ahead of traffic through :meth:`SentryJit.warmup`
(see ``perf/warmup.py``) are *planned* and never count against the
budget — the budget meters surprises, not declared buckets.

Metrics surface through :func:`stats` (consumed by
``train.stats.StatsListener``, ``bench.py`` and
``tools/perf_dossier.py``).
"""
from __future__ import annotations

import contextlib
import functools
import logging
import threading
import time
import weakref
from typing import Any, Dict, List, Optional

_log = logging.getLogger("deeplearning4j_tpu.perf")

_LOCK = threading.RLock()
# weakrefs: stats live exactly as long as their SentryJit (and thus the
# net) does — a long-running server constructing models repeatedly must
# not accumulate dead ledgers. Call under _LOCK.
_REGISTRY: List["weakref.ref[FunctionStats]"] = []


def _live_stats() -> List["FunctionStats"]:
    out = [s for s in (r() for r in _REGISTRY) if s is not None]
    if len(out) != len(_REGISTRY):
        _REGISTRY[:] = [r for r in _REGISTRY if r() is not None]
    return out

# strict()/budget() context overrides (None -> read the env flags)
_STRICT_OVERRIDE: Optional[bool] = None
_BUDGET_OVERRIDE: Optional[int] = None


class RetraceBudgetExceeded(RuntimeError):
    """A jitted entry point traced more distinct unplanned shapes than
    its retrace budget allows (retrace storm)."""


def _flag(name):
    from deeplearning4j_tpu import environment
    return environment.get_flag(name)


def _is_strict() -> bool:
    if _STRICT_OVERRIDE is not None:
        return _STRICT_OVERRIDE
    return bool(_flag("DL4J_TPU_RETRACE_STRICT"))


def _default_budget() -> int:
    if _BUDGET_OVERRIDE is not None:
        return _BUDGET_OVERRIDE
    return int(_flag("DL4J_TPU_RETRACE_BUDGET"))


def signature(tree) -> tuple:
    """Hashable aval signature of an argument pytree: treedef + per-leaf
    (shape, dtype). Works on concrete arrays, tracers, and
    ``ShapeDtypeStruct``s alike — the same triple ``jax.jit`` keys its
    trace cache on (sans weak-type/sharding, which never differ along
    our entry points' call paths)."""
    import jax
    leaves, treedef = jax.tree.flatten(tree)
    sig = []
    for leaf in leaves:
        shape = getattr(leaf, "shape", None)
        if shape is not None:
            sig.append((tuple(shape), str(getattr(leaf, "dtype", "?"))))
        else:                       # python scalar / static-ish leaf
            sig.append(("py", type(leaf).__name__))
    return (treedef, tuple(sig))


class FunctionStats:
    """Per-entry-point counters (one per SentryJit instance; sharing a
    name across instances is fine — :func:`stats` merges by name)."""

    def __init__(self, name: str, budget: Optional[int]):
        self.name = name
        self.budget = budget          # None -> global flag/override
        self.traces = 0               # total tracings (incl. planned)
        self.compiles = 0             # compiles observed on live calls
        self.warmed = 0               # compiles done ahead of traffic
        self.aot_hits = 0             # live calls served by a warmed
                                      # executable (zero-compile proof)
        self.compile_time_s = 0.0     # wall-time spent compiling
        self.signatures: set = set()  # every distinct traced aval sig
        self.planned: set = set()     # declared via warmup()

    # -- accounting -----------------------------------------------------
    def note_plan(self, sig):
        with _LOCK:
            self.planned.add(sig)

    def note_trace(self, sig):
        with _LOCK:
            self.traces += 1
            self.signatures.add(sig)
            unplanned = len(self.signatures - self.planned)
            budget = (self.budget if self.budget is not None
                      else _default_budget())
            over = unplanned > budget
        if over:
            msg = (f"retrace sentry: {self.name!r} traced {unplanned} "
                   f"distinct unplanned shapes (budget {budget}) — "
                   "likely a retrace storm; bucket the offending "
                   "shapes (BucketedSequenceIterator / ParallelInference "
                   "buckets) or declare them via warmup()")
            if _is_strict():
                raise RetraceBudgetExceeded(msg)
            _log.warning(msg)

    def unplanned(self) -> int:
        with _LOCK:
            return len(self.signatures - self.planned)

    def snapshot(self) -> Dict[str, Any]:
        with _LOCK:
            return {
                "traces": self.traces,
                "distinct_shapes": len(self.signatures),
                "unplanned_shapes": len(self.signatures - self.planned),
                "planned_shapes": len(self.planned),
                "compiles": self.compiles,
                "warmed": self.warmed,
                "aot_hits": self.aot_hits,
                "compile_time_s": self.compile_time_s,
            }


class SentryJit:
    """``jax.jit`` plus trace accounting and AOT warmup.

    Un-warmed calls dispatch through the wrapped jit exactly as
    before; the only interception is a counter bump at TRACE time (the
    wrapped python fn body runs once per cache miss), so their
    steady-state dispatch overhead is zero. ``warmup(*args)``
    lowers+compiles from (possibly abstract) arguments and KEEPS the
    compiled executable: on this jax the AOT ``.lower().compile()``
    path does not populate jit's own dispatch cache (only the trace
    cache), so a warmed signature is routed straight to its stored
    executable — the first real call on it neither traces nor compiles
    (``aot_hits`` in the stats is the proof).
    """

    def __init__(self, fn, name: Optional[str] = None,
                 budget: Optional[int] = None, **jit_kwargs):
        import jax
        self._fn = fn
        self._aot: Dict[tuple, Any] = {}   # sig -> Compiled
        self.name = name or getattr(fn, "__name__", "jit_fn")
        self.stats = FunctionStats(self.name, budget)
        stats = self.stats

        @functools.wraps(fn)
        def counted(*args, **kwargs):
            stats.note_trace(signature((args, kwargs)))
            return fn(*args, **kwargs)

        self._jitted = jax.jit(counted, **jit_kwargs)
        with _LOCK:
            _REGISTRY.append(weakref.ref(stats))

    def __call__(self, *args, **kwargs):
        st = self.stats
        if self._aot:
            compiled = self._aot.get(signature((args, kwargs)))
            if compiled is not None:
                try:
                    out = compiled(*args, **kwargs)
                except (TypeError, ValueError):
                    # pre-execution arg rejection (layout/sharding
                    # drifted from the warmed executable): fall
                    # through to jit, whose trace/compile the counters
                    # then see. Runtime failures (OOM, debug_nans)
                    # must propagate — donated buffers are gone and
                    # the crash handlers key on the original error
                    pass
                else:
                    with _LOCK:
                        st.aot_hits += 1
                    return out
        before = st.traces
        t0 = time.perf_counter()
        out = self._jitted(*args, **kwargs)
        if st.traces != before:     # this call traced -> it compiled
            dt = time.perf_counter() - t0
            with _LOCK:
                st.compiles += 1
                st.compile_time_s += dt
        return out

    def warmup(self, *args, **kwargs):
        """AOT-compile for the given argument signature (concrete
        arrays and ``ShapeDtypeStruct``s mix freely), keep the
        executable for dispatch, and mark the signature PLANNED.
        Idempotent per signature. Returns compile seconds (0.0 when
        the signature was already traced)."""
        st = self.stats
        sig = signature((args, kwargs))
        st.note_plan(sig)
        with _LOCK:
            if sig in st.signatures:
                return 0.0          # already traced/compiled
        t0 = time.perf_counter()
        self._aot[sig] = self._jitted.lower(*args, **kwargs).compile()
        dt = time.perf_counter() - t0
        with _LOCK:
            st.warmed += 1
            st.compile_time_s += dt
        return dt

    # AOT inspection passthroughs
    def lower(self, *args, **kwargs):
        return self._jitted.lower(*args, **kwargs)

    @property
    def __wrapped__(self):
        return self._fn


def jit(fn, *, name: Optional[str] = None,
        budget: Optional[int] = None, **jit_kwargs) -> SentryJit:
    """Drop-in ``jax.jit`` with retrace accounting (see module doc)."""
    return SentryJit(fn, name=name, budget=budget, **jit_kwargs)


# -- global controls --------------------------------------------------------

@contextlib.contextmanager
def strict(budget: Optional[int] = None):
    """Within the context, blowing a retrace budget RAISES
    :class:`RetraceBudgetExceeded` instead of warning; ``budget``
    optionally overrides every function's budget. The CI tier-1 fence
    runs a tiny fit under ``sentry.strict()`` so a future PR that
    introduces a retrace storm fails loudly."""
    global _STRICT_OVERRIDE, _BUDGET_OVERRIDE
    prev = (_STRICT_OVERRIDE, _BUDGET_OVERRIDE)
    _STRICT_OVERRIDE = True
    if budget is not None:
        _BUDGET_OVERRIDE = budget
    try:
        yield
    finally:
        _STRICT_OVERRIDE, _BUDGET_OVERRIDE = prev


def stats() -> Dict[str, Dict[str, Any]]:
    """Merged per-name counter snapshot for every sentried entry point
    that traced or warmed at least once."""
    with _LOCK:
        recs = [(s.name, s.snapshot()) for s in _live_stats()]
    out: Dict[str, Dict[str, Any]] = {}
    for name, snap in recs:
        if snap["traces"] == 0 and snap["warmed"] == 0:
            continue
        if name not in out:
            out[name] = snap
        else:
            agg = out[name]
            for k, v in snap.items():
                agg[k] = agg[k] + v
    return out


def total_traces() -> int:
    """Total tracings across every sentried entry point — the
    zero-new-compiles assertion anchor for warmup tests."""
    with _LOCK:
        return sum(s.traces for s in _live_stats())


def total_compile_time_s() -> float:
    with _LOCK:
        return sum(s.compile_time_s for s in _live_stats())


def reset() -> None:
    """Zero every counter and forget dead entries (stats of live
    SentryJit instances are zeroed in place — their jit caches and
    warmed executables survive)."""
    with _LOCK:
        for s in _live_stats():
            s.traces = s.compiles = s.warmed = s.aot_hits = 0
            s.compile_time_s = 0.0
            s.signatures.clear()
            s.planned.clear()
