"""AOT warmup — compile every declared shape bucket before traffic.

The serving/training stack bounds its compiled-program count by
snapping shapes to buckets (``BucketedSequenceIterator`` time buckets,
``ParallelInference`` batch buckets, GPT decode's power-of-two prompt
buckets) — but each bucket still pays its compile at FIRST use, i.e.
on a real request/step. This module moves those compiles ahead of
traffic: ``.lower().compile()`` from abstract ``ShapeDtypeStruct``s —
no real data, no device stalls — through the same sentried jit entry
points live calls use, so the first real step/request on a warmed
bucket executes with zero new traces (asserted via
``perf.sentry.total_traces``). With the persistent compile cache
configured, warmup in one process pre-pays every process.

Use::

    specs = warmup_plan(iterator, batch_size=32, feature_dims=(64,),
                        label_dims=(10,))
    net.warmup(specs)                       # train step + output fn
    pi.warmup(feature_shape=(64,))          # every serving bucket
    model.warmup_decode(net, batch_sizes=(1, 8), prompt_lens=(1024,),
                        n_new=128)          # GPT decode buckets
"""
from __future__ import annotations

from dataclasses import dataclass
from typing import Any, Dict, Iterable, Optional, Sequence, Tuple, Union

ShapeLike = Union[Tuple[int, ...], Dict[str, Tuple[int, ...]],
                  Sequence[Tuple[int, ...]]]


def sds(shape, dtype="float32"):
    """Abstract array stand-in (shape+dtype, no buffer)."""
    import jax
    import jax.numpy as jnp
    return jax.ShapeDtypeStruct(tuple(shape), jnp.dtype(dtype))


@dataclass(frozen=True)
class WarmupSpec:
    """One shape bucket to pre-compile. ``features``/``labels`` are
    batch-inclusive shape tuples — or, for ComputationGraph, a dict
    (by input name) / sequence (by output position) of them."""
    features: ShapeLike
    labels: Optional[ShapeLike] = None
    features_mask: Optional[Tuple[int, ...]] = None
    labels_mask: Optional[Tuple[int, ...]] = None
    dtype: str = "float32"
    labels_dtype: Optional[str] = None    # None -> same as dtype
    train: bool = True                    # warm the train step
    serve: bool = True                    # warm the output fn
    steps_per_loop: int = 0               # >0: also warm the scanned loop


def _label_dtype(spec: WarmupSpec) -> str:
    return spec.labels_dtype or spec.dtype


def sharded_sds(tree, sharding):
    """Rewrite a (tree of) ShapeDtypeStruct(s) to carry an explicit
    sharding — warmup must lower from the SAME sharding the live path
    feeds (batch-sharded global batches, ZeRO optimizer shards), or
    jit's sharding-keyed dispatch cache misses and the first real step
    recompiles invisibly."""
    import jax

    return jax.tree.map(
        lambda s: jax.ShapeDtypeStruct(s.shape, s.dtype,
                                       sharding=sharding), tree)


def _feature_sds(spec: WarmupSpec, conf):
    """Spec features -> the network's feed structure."""
    graph_inputs = getattr(conf, "inputs", None)
    f = spec.features
    if graph_inputs is not None:
        if isinstance(f, dict):
            return {n: sds(s, spec.dtype) for n, s in f.items()}
        shapes = [f] if isinstance(f[0], int) else list(f)
        return {n: sds(s, spec.dtype)
                for n, s in zip(graph_inputs, shapes)}
    return sds(f, spec.dtype)


def _label_sds(spec: WarmupSpec, conf):
    graph_inputs = getattr(conf, "inputs", None)
    y = spec.labels
    if y is None:
        raise ValueError("WarmupSpec.labels is required for train "
                         "warmup (set train=False for serve-only "
                         "buckets)")
    dt = _label_dtype(spec)
    if graph_inputs is not None:
        if isinstance(y, dict):
            shapes = list(y.values())
        elif isinstance(y[0], int):
            shapes = [y]            # one output, bare shape tuple
        else:
            shapes = list(y)
        return [sds(s, dt) for s in shapes]
    return sds(y, dt)


def warmup_network(net, specs: Iterable[WarmupSpec]) -> Dict[str, Any]:
    """AOT-compile a ``MultiLayerNetwork``/``ComputationGraph``'s train
    step, scanned loop, and output fn for every spec. Uses the live
    params/opt-state pytrees as structure donors (lowering never
    consumes them) and abstract data shapes. Returns a report:
    ``{"compiled": n_new_executables, "seconds": compile_wall_time}``.
    """
    import jax

    if not getattr(net, "params", None):
        raise RuntimeError("warmup needs an initialized network — "
                           "call init() first")
    graph = hasattr(net.conf, "inputs")
    compiled, seconds = 0, 0.0
    rng = jax.random.fold_in(jax.random.PRNGKey(net.conf.seed), 0)
    for spec in specs:
        x = _feature_sds(spec, net.conf)
        if spec.train:
            if net._train_step_fn is None:
                net._train_step_fn = net._make_train_step()
            y = _label_sds(spec, net.conf)
            fm = (sds(spec.features_mask, spec.dtype)
                  if spec.features_mask else None)
            lm = (sds(spec.labels_mask, spec.dtype)
                  if spec.labels_mask else None)
            if graph:
                masks = {} if fm is None else {net.conf.inputs[0]: fm}
                lmasks = ({} if lm is None
                          else {net.conf.outputs[0]: lm})
                args = (net.params, net.opt_state, net.state, x, y,
                        masks, lmasks, rng)
            else:
                args = (net.params, net.opt_state, net.state, x, y,
                        fm, lm, rng)
            dt = net._train_step_fn.warmup(*args)
            compiled += dt > 0
            seconds += dt
            if getattr(net, "_numerics", None) is not None:
                # numerics observatory attached: the cadence-gated
                # diagnostic step is a second compiled program over
                # the same signature — warm it too or the first
                # diagnostic iteration stalls on its compile
                if net._diag_step_fn is None:
                    net._diag_step_fn = net._make_diag_step()
                dt = net._diag_step_fn.warmup(*args)
                compiled += dt > 0
                seconds += dt
        if spec.train and spec.steps_per_loop > 0 \
                and not spec.features_mask and not spec.labels_mask:
            if net._train_loop_fn is None:
                net._train_loop_fn = net._make_train_loop()
            k = spec.steps_per_loop
            stack = lambda a: sds((k,) + tuple(a.shape), a.dtype)
            rngs = jax.numpy.stack([rng] * k)
            if graph:
                xs = {n: stack(s) for n, s in x.items()}
                ys = [stack(s) for s in _label_sds(spec, net.conf)]
                dt = net._train_loop_fn.warmup(
                    net.params, net.opt_state, net.state, xs, ys,
                    {}, {}, rngs)
            else:
                dt = net._train_loop_fn.warmup(
                    net.params, net.opt_state, net.state, stack(x),
                    stack(_label_sds(spec, net.conf)), rngs)
            compiled += dt > 0
            seconds += dt
        if spec.serve:
            if net._output_fn is None:
                net._output_fn = net._make_output_fn()
            if graph:
                dt = net._output_fn.warmup(net.params, net.state, x)
            else:
                fm = (sds(spec.features_mask, spec.dtype)
                      if spec.features_mask else None)
                dt = net._output_fn.warmup(net.params, net.state, x, fm)
            compiled += dt > 0
            seconds += dt
    return {"compiled": compiled, "seconds": seconds}


def warmup_inference(pi, feature_shape: Tuple[int, ...],
                     dtype: str = "float32") -> Dict[str, Any]:
    """AOT-compile a ``ParallelInference`` queue's forward for every
    declared batch bucket. ``feature_shape`` is ONE example's shape
    (no batch dim)."""
    specs = [WarmupSpec(features=(b,) + tuple(feature_shape),
                        dtype=dtype, train=False, serve=True)
             for b in pi.buckets]
    return warmup_network(pi.net, specs)


def warmup(target, specs: Optional[Iterable[WarmupSpec]] = None,
           **kw) -> Dict[str, Any]:
    """Generic entry: dispatches on target type (network vs
    ParallelInference)."""
    if hasattr(target, "buckets") and hasattr(target, "net"):
        return warmup_inference(target, **kw)
    return warmup_network(target, specs or [])


def warmup_plan(source, *, batch_size: Optional[int] = None,
                feature_dims: Tuple[int, ...] = (),
                label_dims: Optional[Tuple[int, ...]] = None,
                seq_labels: bool = True,
                dtype: str = "float32",
                labels_dtype: Optional[str] = None,
                train: bool = True, serve: bool = True,
                steps_per_loop: int = 0) -> list:
    """Derive the WarmupSpec set from an existing bucket table.

    ``source`` is one of:

    - a ``BucketedSequenceIterator`` (or anything with TIME buckets in
      ``.buckets``): one spec per bucket length, features
      ``[batch_size, T, *feature_dims]`` with the [B, T] masks the
      iterator attaches when it pads; labels ``[B, T, *label_dims]``
      when ``seq_labels`` else ``[B, *label_dims]``;
    - a ``ParallelInference`` (BATCH buckets in ``.buckets``): one
      serve-only spec per bucket, features ``[bucket, *feature_dims]``;
    - a plain iterable of ints: treated as batch buckets.
    """
    from deeplearning4j_tpu.data.iterators import (
        BucketedSequenceIterator)

    time_bucketed = isinstance(source, BucketedSequenceIterator) or (
        hasattr(source, "buckets") and hasattr(source, "base"))
    if not time_bucketed:
        # batch buckets: a ParallelInference or a plain int iterable
        buckets = (source.buckets if hasattr(source, "buckets")
                   else list(source))
        return [WarmupSpec(
            features=(b,) + tuple(feature_dims),
            labels=((b,) + tuple(label_dims)
                    if label_dims is not None else None),
            dtype=dtype, labels_dtype=labels_dtype,
            train=train and label_dims is not None, serve=serve)
            for b in buckets]
    # time-bucketed sequences
    bsz = batch_size or getattr(source, "batch_size", None)
    if not bsz:
        raise ValueError("warmup_plan needs batch_size for "
                         "time-bucketed sources")
    out = []
    for t in source.buckets:
        lab = None
        lmask = None
        if label_dims is not None:
            lab = ((bsz, t) + tuple(label_dims) if seq_labels
                   else (bsz,) + tuple(label_dims))
            lmask = (bsz, t) if seq_labels else None
        out.append(WarmupSpec(
            features=(bsz, t) + tuple(feature_dims),
            labels=lab, features_mask=(bsz, t), labels_mask=lmask,
            dtype=dtype, labels_dtype=labels_dtype,
            train=train and lab is not None, serve=serve,
            steps_per_loop=steps_per_loop))
    return out
