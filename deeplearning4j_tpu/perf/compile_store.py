"""Content-addressed compile store — compilation as a *fleet* asset.

The persistent XLA cache (``compile_cache.py``) makes compiles survive
one process's restarts; this module makes them survive replica churn
across a serving fleet. Two planes share one fenced root:

- **XLA plane** — ``<root>/<fence>/xla/`` is handed to JAX as
  ``jax_compilation_cache_dir`` (via ``compile_cache.configure`` when
  ``DL4J_TPU_COMPILE_STORE`` is set). The *fence* directory name bakes
  in ``(store format, jaxlib version, topology)``, so a jaxlib upgrade
  or a different device topology lands in a disjoint keyspace — a new
  binary can never deserialize a stale executable (the PyGraph
  version-fencing bar, arxiv 2503.19779).
- **Object plane** — ``<root>/<fence>/objects/<sha>.cse`` holds
  first-party content-addressed entries (the serving fleet's warm-plan
  manifests, AOT artifacts): ``sha = sha256(store_version, jaxlib,
  topology, program fingerprint)``. Entries are single files published
  with the ``resilience/checkpoint.py`` atomic idiom (same-dir dotted
  tmp, fsync, ``os.replace``, dir fsync), so a replica killed -9
  mid-``put`` leaves the old entry or no entry — never a truncated
  artifact another replica could load. A torn/corrupt entry found at
  ``get`` time is quarantined to ``<fence>/corrupt/`` and reported as
  a miss (fallback: recompile), mirroring the checkpoint scan.

Entry layout: ``MAGIC + header-JSON + "\\n" + payload`` where the
header carries ``{store_version, jaxlib, topology, fingerprint, size,
crc32}``; ``get`` re-derives the CRC before returning bytes. A header
whose fence fields mismatch the store's is a *fence miss* (wrong
universe, entry left alone); a payload that fails size/CRC is
*corruption* (quarantined).

See ARCHITECTURE.md §20 and the serving-fleet runbook in docs/OPS.md.
"""
from __future__ import annotations

import hashlib
import json
import os
import threading
import zlib
from pathlib import Path
from typing import Any, Dict, Optional

STORE_VERSION = 1
MAGIC = b"DL4JCSE1\n"
ENTRY_SUFFIX = ".cse"
CORRUPT_DIR = "corrupt"


def default_jaxlib() -> str:
    """The jaxlib wheel version — the binary whose serialized
    executables the fence isolates."""
    try:
        import jaxlib
        return str(getattr(jaxlib, "__version__", "") or "unknown")
    except Exception:
        try:
            import jax
            return str(jax.__version__)
        except Exception:
            return "unknown"


def default_topology() -> str:
    """Configured platform string (config/env only — never
    ``jax.devices()``, which would initialize a backend here)."""
    try:
        import jax
        plats = (jax.config.jax_platforms
                 or os.environ.get("JAX_PLATFORMS", ""))
    except Exception:
        plats = os.environ.get("JAX_PLATFORMS", "")
    names = [p.strip() for p in str(plats).split(",") if p.strip()]
    return "-".join(names) if names else "auto"


def _sanitize(part: str) -> str:
    return "".join(c if (c.isalnum() or c in "._-") else "_"
                   for c in str(part)) or "_"


def program_fingerprint(**parts: Any) -> str:
    """Stable fingerprint of a program's identity: sorted-key JSON of
    whatever the caller considers compile-relevant (model config,
    bucket grid, spec widths, block size...). Hash, not the JSON, is
    the key — callers never depend on the encoding."""
    blob = json.dumps(parts, sort_keys=True, default=str)
    return hashlib.sha256(blob.encode()).hexdigest()


class CompileStore:
    """One fence's view of the content-addressed store rooted at
    ``root``. Counters: puts / hits / misses (fence mismatch or
    absent) / quarantined (corrupt entries moved aside)."""

    def __init__(self, root, *, jaxlib: Optional[str] = None,
                 topology: Optional[str] = None):
        self.root = Path(os.path.expanduser(str(root)))
        self.jaxlib = jaxlib if jaxlib is not None else default_jaxlib()
        self.topology = (topology if topology is not None
                         else default_topology())
        self.fence = (f"v{STORE_VERSION}__jaxlib-"
                      f"{_sanitize(self.jaxlib)}__"
                      f"{_sanitize(self.topology)}")
        self.fence_dir = self.root / self.fence
        self.xla_dir = self.fence_dir / "xla"
        self.objects_dir = self.fence_dir / "objects"
        self._lock = threading.Lock()
        self._counters = {"puts": 0, "hits": 0, "misses": 0,
                          "quarantined": 0}
        self.xla_dir.mkdir(parents=True, exist_ok=True)
        self.objects_dir.mkdir(parents=True, exist_ok=True)

    # -- keys -------------------------------------------------------------
    def key(self, fingerprint: str) -> str:
        blob = json.dumps([STORE_VERSION, self.jaxlib, self.topology,
                           fingerprint], sort_keys=True)
        return hashlib.sha256(blob.encode()).hexdigest()

    def entry_path(self, fingerprint: str) -> Path:
        return self.objects_dir / (self.key(fingerprint) + ENTRY_SUFFIX)

    # -- write ------------------------------------------------------------
    def put(self, fingerprint: str, payload: bytes) -> Path:
        """Publish ``payload`` under ``fingerprint`` atomically: a
        reader (or a crash) observes the old entry, no entry, or the
        complete new entry — never a torn one."""
        from deeplearning4j_tpu.resilience.checkpoint import (
            atomic_write_bytes)
        header = {
            "store_version": STORE_VERSION,
            "jaxlib": self.jaxlib,
            "topology": self.topology,
            "fingerprint": fingerprint,
            "size": len(payload),
            "crc32": zlib.crc32(payload) & 0xFFFFFFFF,
        }
        blob = MAGIC + json.dumps(header, sort_keys=True).encode() \
            + b"\n" + payload
        path = atomic_write_bytes(self.entry_path(fingerprint), blob)
        with self._lock:
            self._counters["puts"] += 1
        return Path(path)

    # -- read -------------------------------------------------------------
    def get(self, fingerprint: str) -> Optional[bytes]:
        """Payload bytes, or None (miss). Fence-mismatched entries are
        misses and left in place (they belong to another universe);
        torn/corrupt entries are quarantined and reported as misses —
        the caller's fallback is always "recompile"."""
        path = self.entry_path(fingerprint)
        try:
            blob = path.read_bytes()
        except OSError:
            with self._lock:
                self._counters["misses"] += 1
            return None
        payload = self._validate(path, blob, fingerprint)
        with self._lock:
            self._counters["hits" if payload is not None
                           else "misses"] += 1
        return payload

    def _validate(self, path: Path, blob: bytes,
                  fingerprint: str) -> Optional[bytes]:
        if not blob.startswith(MAGIC):
            self._quarantine(path, "bad magic")
            return None
        rest = blob[len(MAGIC):]
        nl = rest.find(b"\n")
        if nl < 0:
            self._quarantine(path, "truncated header")
            return None
        try:
            header = json.loads(rest[:nl])
        except ValueError:
            self._quarantine(path, "unparseable header")
            return None
        if (header.get("store_version") != STORE_VERSION
                or header.get("jaxlib") != self.jaxlib
                or header.get("topology") != self.topology
                or header.get("fingerprint") != fingerprint):
            # version fence: a different universe's entry, not damage
            return None
        payload = rest[nl + 1:]
        if len(payload) != header.get("size") or \
                (zlib.crc32(payload) & 0xFFFFFFFF) != header.get("crc32"):
            self._quarantine(path, "size/crc mismatch")
            return None
        return payload

    def _quarantine(self, path: Path, reason: str) -> None:
        """Move a damaged entry to ``<fence>/corrupt/`` — out of every
        future ``get``, kept for post-mortems (the checkpoint-scan
        idiom)."""
        import shutil
        dest_dir = self.fence_dir / CORRUPT_DIR
        try:
            dest_dir.mkdir(parents=True, exist_ok=True)
            dest = dest_dir / path.name
            if dest.exists():       # keep prior evidence, don't clobber
                dest = dest_dir / f"{path.name}.{os.getpid()}"
            shutil.move(str(path), str(dest))
        except OSError:
            try:                    # at minimum get it out of the scan
                path.unlink()
            except OSError:
                return
        with self._lock:
            self._counters["quarantined"] += 1

    # -- reporting --------------------------------------------------------
    def counters(self) -> Dict[str, int]:
        with self._lock:
            return dict(self._counters)

    def stats(self) -> Dict[str, Any]:
        """Disk + in-process view (walks the fence dir — once-per-run
        reporters only)."""
        objects = obj_bytes = 0
        for p in self.objects_dir.glob("*" + ENTRY_SUFFIX):
            objects += 1
            try:
                obj_bytes += p.stat().st_size
            except OSError:
                pass
        xla_entries = xla_bytes = 0
        if self.xla_dir.is_dir():
            for root, _dirs, files in os.walk(self.xla_dir):
                for f in files:
                    if f.endswith("-atime"):
                        continue
                    xla_entries += 1
                    try:
                        xla_bytes += os.path.getsize(
                            os.path.join(root, f))
                    except OSError:
                        pass
        fences = sorted(p.name for p in self.root.iterdir()
                        if p.is_dir()) if self.root.is_dir() else []
        out: Dict[str, Any] = {
            "root": str(self.root), "fence": self.fence,
            "fences": fences, "objects": objects,
            "object_bytes": obj_bytes, "xla_entries": xla_entries,
            "xla_bytes": xla_bytes,
        }
        out.update(self.counters())
        return out


def from_env() -> Optional[CompileStore]:
    """Store from ``DL4J_TPU_COMPILE_STORE`` (None when unset/off)."""
    from deeplearning4j_tpu import environment
    root = environment.get_flag("DL4J_TPU_COMPILE_STORE")
    if not root or str(root).strip().lower() in (
            "", "0", "off", "none", "false", "disabled"):
        return None
    return CompileStore(root)
