"""Compile subsystem: persistent XLA cache, AOT warmup, retrace sentry.

Three legs, one goal — compilation is a managed artifact, not an
ambient surprise:

- :mod:`~deeplearning4j_tpu.perf.compile_cache` — JAX's on-disk
  compilation cache wired to the tier-2 flag system; restarts and
  multi-process workers reuse each other's compiles.
- :mod:`~deeplearning4j_tpu.perf.compile_store` — the fleet-shared,
  content-addressed tier above it: (jaxlib, topology,
  program-fingerprint)-keyed entries with version fencing and
  corrupt-entry quarantine (ARCHITECTURE.md §20).
- :mod:`~deeplearning4j_tpu.perf.warmup` — ``.lower().compile()``
  every declared shape bucket from abstract shapes before traffic.
- :mod:`~deeplearning4j_tpu.perf.sentry` — count distinct traced
  avals per jitted entry point, record compile wall-time, warn/raise
  on retrace storms.

See ARCHITECTURE.md "Compilation lifecycle".
"""
from deeplearning4j_tpu.perf import compile_cache as compile_cache
from deeplearning4j_tpu.perf import compile_store as compile_store
from deeplearning4j_tpu.perf import sentry as sentry
from deeplearning4j_tpu.perf import warmup as warmup
from deeplearning4j_tpu.perf.compile_store import (
    CompileStore as CompileStore,
    program_fingerprint as program_fingerprint)
from deeplearning4j_tpu.perf.sentry import (
    RetraceBudgetExceeded as RetraceBudgetExceeded)
from deeplearning4j_tpu.perf.warmup import (
    WarmupSpec as WarmupSpec, warmup_plan as warmup_plan)


def compile_report() -> dict:
    """One-shot compile-subsystem summary for end-of-run reporters
    (``bench.py``'s ``compile`` section, the dossier's
    ``compile_subsystem`` entry): sentry totals + persistent-cache
    state. Walks the cache dir — don't call per iteration
    (``StatsListener`` uses :func:`compile_cache.counters`)."""
    cache = compile_cache.cache_stats()
    return {
        "compile_time_s": round(sentry.total_compile_time_s(), 3),
        "traces": sentry.total_traces(),
        "per_function": sentry.stats(),
        "cache_dir": cache["dir"],
        "cache_entries": cache["entries"],
        "cache_hits": cache["persistent_hits"],
        "cache_misses": cache["persistent_misses"],
    }


__all__ = ["compile_cache", "compile_store", "CompileStore",
           "program_fingerprint", "sentry", "warmup", "WarmupSpec",
           "warmup_plan", "RetraceBudgetExceeded", "compile_report"]
