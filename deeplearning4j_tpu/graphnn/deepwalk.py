"""DeepWalk graph embeddings.

Reference: ``org.deeplearning4j.graph.models.deepwalk.DeepWalk`` —
uniform random walks from every vertex (``RandomWalkIterator``), fed to
skip-gram with window; the reference trains a custom GraphVectors
hierarchy, here the walks reuse the Word2Vec negative-sampling jitted
step (same math, one code path).
"""
from __future__ import annotations

from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from deeplearning4j_tpu.nlp.word2vec import Word2Vec


class Graph:
    """Undirected/directed adjacency graph (reference
    org.deeplearning4j.graph.graph.Graph)."""

    def __init__(self, n_vertices: int, directed: bool = False):
        self.n = n_vertices
        self.directed = directed
        self._adj: List[List[int]] = [[] for _ in range(n_vertices)]

    def add_edge(self, a: int, b: int):
        self._adj[a].append(b)
        if not self.directed:
            self._adj[b].append(a)
        return self

    def neighbors(self, v: int) -> List[int]:
        return self._adj[v]

    def num_vertices(self) -> int:
        return self.n


class DeepWalk:
    """Reference: DeepWalk (+.Builder): windowSize/vectorSize/walkLength/
    walksPerVertex; fit(graph) then getVertexVector/similarity."""

    def __init__(self, vector_size: int = 64, window_size: int = 5,
                 walk_length: int = 40, walks_per_vertex: int = 10,
                 learning_rate: float = 0.1, negative: int = 5,
                 epochs: int = 1, iterations: int = 3, seed: int = 77):
        self.vector_size = vector_size
        self.window_size = window_size
        self.walk_length = walk_length
        self.walks_per_vertex = walks_per_vertex
        self.learning_rate = learning_rate
        self.negative = negative
        self.epochs = epochs
        self.iterations = iterations
        self.seed = seed
        self._w2v: Optional[Word2Vec] = None

    def _random_walks(self, graph: Graph) -> List[List[str]]:
        rng = np.random.default_rng(self.seed)
        walks = []
        for _ in range(self.walks_per_vertex):
            for start in rng.permutation(graph.num_vertices()):
                v = int(start)
                walk = [str(v)]
                for _ in range(self.walk_length - 1):
                    nbrs = graph.neighbors(v)
                    if not nbrs:
                        break
                    v = int(nbrs[rng.integers(len(nbrs))])
                    walk.append(str(v))
                walks.append(walk)
        return walks

    def fit(self, graph: Graph) -> "DeepWalk":
        walks = self._random_walks(graph)
        w2v = Word2Vec(layer_size=self.vector_size,
                       window_size=self.window_size,
                       min_word_frequency=1,
                       negative=self.negative,
                       learning_rate=self.learning_rate,
                       epochs=self.epochs, iterations=self.iterations,
                       seed=self.seed)
        w2v.fit(" ".join(w) for w in walks)
        self._w2v = w2v
        return self

    def get_vertex_vector(self, v: int) -> np.ndarray:
        return self._w2v.get_word_vector(str(v))

    def similarity(self, a: int, b: int) -> float:
        return self._w2v.similarity(str(a), str(b))

    def verts_nearest(self, v: int, top_n: int = 5) -> List[int]:
        return [int(w) for w in
                self._w2v.words_nearest(str(v), top_n)]
