"""Graph embeddings (reference: ``deeplearning4j-graph`` —
``org.deeplearning4j.graph.models.deepwalk.DeepWalk``,
``graph.Graph``, ``iterator.RandomWalkIterator``).
"""
from deeplearning4j_tpu.graphnn.deepwalk import DeepWalk, Graph

__all__ = ["DeepWalk", "Graph"]
