"""Hardened checkpoint pipeline — atomic writes, verification, quarantine.

The reference's ``ModelSerializer`` wrote its zip in place; a crash
mid-save left a truncated newest-by-mtime file that
``resume_or_init``/``FaultTolerantTrainer`` would then loop on forever
(restore → crash → restore the same corrupt file). This module closes
that window for every checkpoint producer and consumer:

- **Atomic publication.** :func:`atomic_write_bytes` /
  the tmp+fsync+``os.replace`` protocol used by
  ``ModelSerializer.write_model``: the final path either holds the old
  complete checkpoint or the new complete checkpoint — ``kill -9`` at
  any byte leaves no observable in-between state (crash-consistency
  test in ``tests/test_resilience.py``).
- **Verification.** :func:`verify_checkpoint` proves a zip checkpoint
  restorable *before* anyone restores it: zip central directory +
  per-entry CRC sweep (``testzip``), required entries present,
  ``meta.json`` parseable, and — when the sidecar manifest exists —
  whole-file CRC32 + size + format version match.
- **Manifest.** :func:`write_manifest` publishes
  ``<ckpt>.manifest.json`` (CRC32, size, format version, counters)
  after the checkpoint itself; a crash between the two leaves a valid
  checkpoint whose verification falls back to the zip-level checks.
- **Quarantine.** :func:`quarantine` moves a corrupt/partial
  checkpoint (and its manifest) to ``<dir>/corrupt/`` — restart loops
  stop tripping over it, the evidence survives for post-mortems, and
  ``dl4j_tpu_checkpoints_quarantined_total`` counts it.
- **Fallback.** :func:`newest_valid_checkpoint` walks newest→oldest
  and returns the first checkpoint that verifies, quarantining the
  invalid ones it skips.

The orbax/tensorstore sharded path gets the same posture via
``ShardedCheckpointer.restore_latest_valid`` (``serialization.py``),
which quarantines unrestorable step dirs to the same ``corrupt/``
location. ZeRO sharded-update training
(``ParallelWrapper(sharded_update=True)``) checkpoints through
``ShardedCheckpointer.save_wrapper``/``restore_wrapper``: each device
saves and restores only its 1/N optimizer shard, onto the same
topology, without ever materializing the replicated state.
"""
from __future__ import annotations

import json
import logging
import os
import shutil
import zipfile
import zlib
from pathlib import Path
from typing import Dict, Optional, Tuple

logger = logging.getLogger("deeplearning4j_tpu")

#: bumped when the checkpoint layout changes incompatibly; recorded in
#: both the zip's meta.json and the sidecar manifest
FORMAT_VERSION = 1

#: subdirectory (under the checkpoint dir) corrupt checkpoints move to
CORRUPT_DIR = "corrupt"

#: entries a ModelSerializer zip must contain to be restorable
REQUIRED_ENTRIES = ("configuration.json", "params.npz", "meta.json")


def fsync_dir(path) -> None:
    """Flush a directory entry table — after ``os.replace`` this makes
    the rename itself durable (best-effort: not every platform/FS
    supports opening a directory)."""
    try:
        fd = os.open(os.fspath(path), os.O_RDONLY)
    except OSError:
        return
    try:
        os.fsync(fd)
    except OSError:
        pass
    finally:
        os.close(fd)


def tmp_path_for(path: Path) -> Path:
    """Same-directory tmp name for the atomic protocol. Dot-prefixed
    and ``.zip``-free so no ``checkpoint_*.zip`` glob (or mtime scan)
    can ever select an in-progress file."""
    return path.with_name(f".{path.name}.tmp-{os.getpid()}")


def atomic_write_bytes(path, data: bytes) -> Path:
    """Write ``data`` to ``path`` atomically: same-dir tmp file, fsync,
    ``os.replace``, directory fsync."""
    path = Path(path)
    tmp = tmp_path_for(path)
    try:
        with open(tmp, "wb") as f:
            f.write(data)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, path)
    except BaseException:
        tmp.unlink(missing_ok=True)
        raise
    fsync_dir(path.parent)
    return path


def file_crc32(path) -> int:
    crc = 0
    with open(path, "rb") as f:
        for chunk in iter(lambda: f.read(1 << 20), b""):
            crc = zlib.crc32(chunk, crc)
    return crc & 0xFFFFFFFF




def manifest_path(ckpt) -> Path:
    ckpt = Path(ckpt)
    return ckpt.with_name(ckpt.name + ".manifest.json")


def write_manifest(ckpt, extra: Optional[Dict] = None,
                   crc32: Optional[int] = None) -> Path:
    """Publish the sidecar manifest for an already-published checkpoint
    (atomic in its own right; losing it only downgrades verification
    to the zip-level checks). ``crc32``: the value accumulated by a
    :class:`CRCWriter` during the write — passing it skips re-reading
    the whole checkpoint."""
    ckpt = Path(ckpt)
    m = {"file": ckpt.name,
         "format_version": FORMAT_VERSION,
         "size": ckpt.stat().st_size,
         "crc32": file_crc32(ckpt) if crc32 is None else int(crc32)}
    if extra:
        m.update(extra)
    return atomic_write_bytes(manifest_path(ckpt),
                              (json.dumps(m, indent=1) + "\n").encode())


def verify_checkpoint(path) -> Tuple[bool, str]:
    """Is this zip checkpoint restorable? Returns ``(ok, reason)`` —
    never raises. Checks, cheapest first: file present/non-empty,
    manifest CRC32+size+version (when the sidecar exists), zip central
    directory, per-entry CRC sweep, required entries, meta.json
    parseable."""
    path = Path(path)
    try:
        if not path.is_file():
            return False, "missing"
        if path.stat().st_size == 0:
            return False, "empty file"
        mpath = manifest_path(path)
        if mpath.is_file():
            try:
                m = json.loads(mpath.read_text())
            except (OSError, ValueError):
                m = None            # torn manifest: fall back to zip checks
            if m is not None:
                if int(m.get("format_version", FORMAT_VERSION)) > \
                        FORMAT_VERSION:
                    return False, (f"format_version "
                                   f"{m.get('format_version')} "
                                   f"> supported {FORMAT_VERSION}")
                if "size" in m and int(m["size"]) != path.stat().st_size:
                    return False, (f"size {path.stat().st_size} != "
                                   f"manifest {m['size']}")
                if "crc32" in m and int(m["crc32"]) != file_crc32(path):
                    return False, "crc32 mismatch vs manifest"
        if not zipfile.is_zipfile(path):
            return False, "not a zip (truncated or partial write)"
        with zipfile.ZipFile(path) as zf:
            bad = zf.testzip()
            if bad is not None:
                return False, f"zip entry {bad!r} fails CRC"
            names = set(zf.namelist())
            missing = [n for n in REQUIRED_ENTRIES if n not in names]
            if missing:
                return False, f"missing entries {missing}"
            try:
                json.loads(zf.read("meta.json").decode())
            except ValueError:
                return False, "meta.json unparseable"
    except (OSError, zipfile.BadZipFile) as e:
        return False, f"unreadable ({e})"
    return True, "ok"


def quarantine(path, reason: str) -> Optional[Path]:
    """Move a corrupt checkpoint (zip or orbax step dir, plus any
    manifest) to ``<dir>/corrupt/`` — out of every newest-first scan,
    kept for post-mortems. Returns the new location (None if the move
    itself failed; the caller's scan must then skip the file)."""
    from deeplearning4j_tpu import obs
    path = Path(path)
    dest_dir = path.parent / CORRUPT_DIR
    t0 = obs.now()
    try:
        dest_dir.mkdir(parents=True, exist_ok=True)
        dest = dest_dir / path.name
        if dest.exists():           # keep prior evidence, don't clobber
            dest = dest_dir / f"{path.name}.{os.getpid()}.{t0:.0f}"
        shutil.move(str(path), str(dest))
        mp = manifest_path(path)
        if mp.is_file():
            shutil.move(str(mp), str(dest_dir / mp.name))
    except OSError as e:
        logger.error("could not quarantine corrupt checkpoint %s: %s",
                     path, e)
        return None
    obs.metrics.CKPT_QUARANTINED.inc()
    if obs.trace.enabled():
        obs.trace.add_span("resilience/quarantine", t0, obs.now(),
                           args={"path": str(path), "reason": reason})
    logger.warning("quarantined corrupt checkpoint %s -> %s (%s)",
                   path.name, dest, reason)
    return dest


def newest_valid_checkpoint(directory, pattern: str = "checkpoint_*.zip",
                            quarantine_invalid: bool = True
                            ) -> Optional[Path]:
    """Newest checkpoint that actually verifies. Invalid ones are
    quarantined (or skipped with a warning) instead of crashing — or
    looping — the restart path."""
    directory = Path(directory)
    ckpts = sorted(directory.glob(pattern),
                   key=lambda p: p.stat().st_mtime, reverse=True)
    for p in ckpts:
        ok, reason = verify_checkpoint(p)
        if ok:
            return p
        logger.warning("skipping invalid checkpoint %s: %s", p, reason)
        if quarantine_invalid:
            quarantine(p, reason)
    return None
