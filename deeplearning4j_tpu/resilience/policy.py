"""Retry, backoff, error classification, preemption — the decision
layer between "something threw" and "restore and continue".

The seed's ``FaultTolerantTrainer`` retried *unconditionally* and
*immediately*: a deterministic shape error burned every restart in
milliseconds, and a flaky filesystem got hammered in a tight loop.
This module packages the policy the TPU job actually needs:

- :func:`classify` — transient (``OSError``/``ConnectionError``/
  ``TimeoutError``/plain ``RuntimeError``: chip drops, network flakes,
  IO hiccups → retry with backoff) vs. deterministic (shape/dtype/
  NaN/Inf messages, ``ValueError``/``TypeError``/``FloatingPointError``:
  the same input will crash the same way → at most ONE
  restore-and-retry, then re-raise loudly).
- :class:`RetryPolicy` — exponential backoff with seeded jitter
  (deterministic in tests, decorrelated in fleets) and a generic
  :meth:`RetryPolicy.call` runner.
- :class:`PreemptionHandler` — SIGTERM (the preemption notice TPU
  slices get) sets a cooperative flag; the training loop observes it
  at the next iteration boundary, checkpoints, and exits cleanly
  (exit code 0 — the restarted job resumes via ``resume_or_init``).
  :class:`Preempted` is the control-flow signal, a ``BaseException``
  so no retry loop mistakes it for a failure.
"""
from __future__ import annotations

import logging
import random
import re
import signal
import threading
import time
from dataclasses import dataclass
from typing import Callable, Optional, Tuple

logger = logging.getLogger("deeplearning4j_tpu")

TRANSIENT = "transient"
DETERMINISTIC = "deterministic"

#: message shapes that mean "same input → same crash": retrying without
#: changing anything cannot help (one restore MAY — a corrupt in-memory
#: buffer or poisoned optimizer state goes away with the rollback)
_DETERMINISTIC_RE = re.compile(
    r"shape|dtype|rank|dimension mismatch|incompatible|"
    r"\bnan\b|\binf\b|not finite|non-finite", re.IGNORECASE)

#: exception types that are transient by nature regardless of message
_TRANSIENT_TYPES: Tuple[type, ...] = (OSError, ConnectionError,
                                      TimeoutError)


def classify(exc: BaseException) -> str:
    """``transient`` → retry with backoff; ``deterministic`` → one
    restore, then re-raise. Message patterns outrank types: a
    RuntimeError carrying "shape mismatch" is deterministic even
    though bare RuntimeErrors (XLA's habitual wrapper for runtime
    faults) default to transient.

    The numerics observatory's structured ``NonFiniteError``
    (``obs.numerics`` — a ``FloatingPointError`` carrying
    ``layer``/``kind``/``iteration``) lands here as deterministic
    through both its type and its "non-finite" message: one restore
    MAY clear it (a poisoned batch or corrupted optimizer state rolls
    back), a second occurrence re-raises with the attribution intact
    (see :func:`describe` for the log line)."""
    if isinstance(exc, (FloatingPointError, ZeroDivisionError)):
        return DETERMINISTIC
    if _DETERMINISTIC_RE.search(str(exc)):
        return DETERMINISTIC
    if isinstance(exc, _TRANSIENT_TYPES):
        return TRANSIENT
    if isinstance(exc, RuntimeError):
        return TRANSIENT
    return DETERMINISTIC


def describe(exc: BaseException) -> str:
    """Human log line for a classified failure — surfaces the numerics
    observatory's structured attribution when present, so the restart
    log reads "layer gpt.h3.attn gradients overflowed at iter 412"
    instead of "loss is NaN"."""
    layer = getattr(exc, "layer", None)
    if layer is not None:
        return (f"layer {layer} {getattr(exc, 'kind', None) or 'values'}"
                f" went non-finite at iteration "
                f"{getattr(exc, 'iteration', '?')}")
    return f"{type(exc).__name__}: {exc}"


@dataclass
class RetryPolicy:
    """Exponential backoff with seeded jitter.

    ``delay(attempt)`` (1-based) = ``base * 2^(attempt-1)`` clamped to
    ``max_delay_s``, scaled by a uniform jitter in ``[1-jitter, 1+jitter]``
    drawn from a per-(seed, attempt) RNG — deterministic for tests,
    decorrelated across a fleet of restarting workers (every worker
    passes its rank as ``seed``)."""

    max_retries: int = 3
    base_delay_s: float = 0.05
    max_delay_s: float = 10.0
    jitter: float = 0.5
    seed: int = 0

    def delay(self, attempt: int) -> float:
        d = min(self.max_delay_s,
                self.base_delay_s * (2.0 ** max(0, attempt - 1)))
        if not self.jitter:
            return d
        r = random.Random(self.seed * 1000003 + attempt)
        return d * (1.0 + self.jitter * (2.0 * r.random() - 1.0))

    def call(self, fn: Callable[[], "object"], *,
             classify_fn: Callable[[BaseException], str] = classify,
             on_retry: Optional[Callable] = None,
             sleep: Callable[[float], None] = time.sleep):
        """Run ``fn()`` under this policy: transient errors retry with
        backoff up to ``max_retries``; a deterministic error is retried
        at most once (immediately), then re-raised."""
        attempt = 0
        det_retried = False
        while True:
            try:
                return fn()
            except KeyboardInterrupt:
                raise
            except Exception as e:
                kind = classify_fn(e)
                attempt += 1
                if attempt > self.max_retries or (
                        kind == DETERMINISTIC and det_retried):
                    raise
                if kind == DETERMINISTIC:
                    det_retried = True
                    d = 0.0
                else:
                    d = self.delay(attempt)
                logger.warning("retry %d/%d after %s error (%s); "
                               "backoff %.3fs", attempt,
                               self.max_retries, kind, e, d)
                if on_retry is not None:
                    on_retry(e, attempt, kind)
                if d:
                    sleep(d)


class Preempted(BaseException):
    """Control flow, not an error: the loop was asked to stop, has
    checkpointed, and is unwinding cleanly. BaseException so generic
    ``except Exception`` retry machinery can never swallow it."""


class PreemptionHandler:
    """Cooperative SIGTERM handling for checkpoint-and-exit.

    ``install()`` registers a handler (main thread only — Python's
    signal contract) that sets a flag and chains any previously
    installed Python-level handler. The training loop polls
    :attr:`requested` at iteration boundaries — the handler itself
    never checkpoints (saving from signal context could tear the very
    file the restart needs)."""

    def __init__(self, signals=(signal.SIGTERM,)):
        self.signals = tuple(signals)
        self._requested = threading.Event()
        self._prev: dict = {}
        self._installed = False

    def install(self) -> "PreemptionHandler":
        for s in self.signals:
            self._prev[s] = signal.signal(s, self._on_signal)
        self._installed = True
        return self

    def uninstall(self) -> None:
        if not self._installed:
            return
        for s, prev in self._prev.items():
            try:
                signal.signal(s, prev if prev is not None
                              else signal.SIG_DFL)
            except (ValueError, TypeError):   # non-main thread/odd prev
                pass
        self._prev.clear()
        self._installed = False

    def _on_signal(self, signum, frame):
        self._requested.set()
        logger.warning("preemption notice (signal %d): will checkpoint "
                       "and exit at the next iteration boundary", signum)
        prev = self._prev.get(signum)
        if callable(prev):
            prev(signum, frame)

    @property
    def requested(self) -> bool:
        return self._requested.is_set()

    def clear(self) -> None:
        self._requested.clear()

    def __enter__(self) -> "PreemptionHandler":
        return self.install()

    def __exit__(self, *exc) -> None:
        self.uninstall()
