"""Elastic multi-host training — membership, mesh epochs, re-formation.

PR 3 made ONE process survive faults and preemption; the ZeRO sharded
update (parallel/zero.py) then spread optimizer state over N replicas.
This module makes the FLEET survive: losing a host no longer strands
every peer in a dead collective and every shard on a topology that no
longer exists (ROADMAP open item 3 — training that rides
spot/preemptible pools).

Three cooperating pieces (ARCHITECTURE.md §13):

- **Membership coordinator** (:class:`MembershipCoordinator`):
  generation-numbered *mesh epochs* over a shared directory (the same
  medium the checkpoints already live on). Hosts hold *leases* —
  atomic JSON files renewed like heartbeats (and mirrored into the
  PR 2 ``obs/health.py`` registry, so ``/healthz`` names dead peers).
  A missed lease (``DL4J_TPU_HOST_LEASE_SECS``) or a SIGTERM
  :meth:`~MembershipCoordinator.leave` evicts the host; survivors run
  the propose→ack→commit round of :meth:`agree_membership` so
  *everyone agrees on the new membership before any collective runs
  again*. Every commit bumps the epoch (``dl4j_tpu_mesh_epoch``) and
  is stamped onto every subsequent step: a straggler from an old
  generation raises :class:`StaleMeshEpoch` instead of silently
  joining (and corrupting) the new generation's allreduce.

- **Bounded-timeout collectives** (:func:`bounded_sync` /
  :class:`ElasticContext`): the blocking host↔device sync of every
  ``ParallelWrapper`` step runs under a watchdog, so the peers of a
  dead host raise :class:`CollectiveTimeoutError` within the lease
  window instead of hanging forever (the runtime's own collective
  error — e.g. a gloo connection reset — surfaces even faster).

- **Re-formation by re-exec** (:meth:`ElasticTrainer.reform`): a
  wedged collective runtime cannot be torn down in-process — on this
  runtime family the coordination client *aborts the process* during
  shutdown once a peer has died — so re-formation replaces the
  process image (``os.execv``), the one teardown that always works.
  The fresh image re-runs mesh bring-up (``parallel/mesh.py``) at the
  agreed world size and *reshard-restores* the newest valid sharded
  checkpoint (``ShardedCheckpointer.restore_wrapper`` gathers by
  manifest and re-scatters through ``FlatShardLayout``), resuming the
  uninterrupted trajectory at the surviving scale.

The coordinator assumes a shared filesystem and crash-stop failures —
the same assumptions the checkpoint pipeline already makes. Leases use
the *wall* clock (``time.time``): lease deadlines must be comparable
across processes, which monotonic clocks are not; hosts of one fleet
are assumed NTP-close relative to the lease window.

Drilled by ``tools/chaos.py --elastic`` on ``tests/mp_harness.py``:
SIGKILL one host mid-epoch → survivors detect within the lease
window, re-form at the reduced world size, reshard-restore, and match
the same-scale uninterrupted baseline bit-for-bit.
"""
from __future__ import annotations

import json
import logging
import os
import sys
import threading
import time
from pathlib import Path
from typing import Callable, Dict, List, Optional

from deeplearning4j_tpu.resilience import checkpoint as _ckpt
from deeplearning4j_tpu.resilience import faults as _faults

logger = logging.getLogger("deeplearning4j_tpu")

#: env passed through ``os.execv`` so the fresh image knows it is a
#: re-formation (join waits for lease expiry instead of a fixed count)
#: and can carry the restart counter across the exec boundary
_REFORM_ENV = "DL4J_TPU_ELASTIC_REFORM"
_RESTARTS_ENV = "DL4J_TPU_ELASTIC_RESTARTS"


class CollectiveTimeoutError(RuntimeError):
    """A collective (or its host-side sync) outlived the watchdog —
    the canonical signature of a dead/wedged peer. Classified
    transient by ``resilience.policy`` (retrying IS the elastic
    answer: re-form and go again)."""


class StaleMeshEpoch(RuntimeError):
    """This host's mesh generation is no longer the committed one —
    it slept through a re-formation (GC pause, SIGSTOP, slow restore)
    and must NOT touch the new generation's collectives."""


class Evicted(RuntimeError):
    """The committed membership no longer includes this host — its
    lease lapsed and the survivors moved on. The only safe action is
    to exit (rejoining means a fresh :meth:`MembershipCoordinator.join`
    at the next epoch)."""


def _read_json(path: Path) -> Optional[dict]:
    """Tolerant read: a missing or torn file is ``None``, never an
    exception — every coordinator file is written atomically, so a
    torn read means 'concurrent writer', i.e. retry."""
    try:
        return json.loads(Path(path).read_text())
    except (OSError, ValueError):
        return None


def _write_json(path: Path, obj: dict) -> None:
    _ckpt.atomic_write_bytes(path, (json.dumps(obj) + "\n").encode())


class _WatchdogThread:
    """One reusable DAEMON worker thread running submitted callables
    under a timeout — the per-step form of :func:`bounded_sync`
    without a thread spawn per step. Daemon on purpose: a worker
    wedged inside a dead collective must never block interpreter
    exit (and after a timeout the caller re-forms by exec anyway)."""

    def __init__(self, name: str = "dl4j-collective-watchdog"):
        import queue
        self._name = name
        self._q: "queue.Queue" = queue.Queue()
        self._thread: Optional[threading.Thread] = None

    def _loop(self):
        while True:
            item = self._q.get()
            if item is None:        # close() sentinel
                return
            fn, box, done = item
            try:
                box["v"] = fn()
            except BaseException as e:
                box["e"] = e
            finally:
                done.set()

    def run(self, fn: Callable[[], object], timeout_s: float,
            what: str = "collective"):
        if not timeout_s or timeout_s <= 0:
            return fn()
        if self._thread is None or not self._thread.is_alive():
            self._thread = threading.Thread(
                target=self._loop, daemon=True, name=self._name)
            self._thread.start()
        box: dict = {}
        done = threading.Event()
        self._q.put((fn, box, done))
        if not done.wait(timeout_s):
            # the worker is stuck in the dead collective; abandon it —
            # the next run() starts a fresh thread if needed (it won't
            # be: the caller's answer to a timeout is re-formation)
            self._thread = None
            raise CollectiveTimeoutError(
                f"{what} did not complete within {timeout_s:.1f}s — a "
                "peer is dead or wedged; tear down and re-form the "
                "mesh")
        if "e" in box:
            raise box["e"]
        return box.get("v")

    def close(self) -> None:
        """Let the worker exit once idle (a worker stuck inside a dead
        collective drains the sentinel whenever — or never — it
        returns; it is a daemon either way)."""
        if self._thread is not None and self._thread.is_alive():
            self._q.put(None)
        self._thread = None


def bounded_sync(fn: Callable[[], object], timeout_s: float,
                 what: str = "collective"):
    """Run a blocking device sync under a watchdog: returns ``fn()``'s
    value, re-raises its exception, or raises
    :class:`CollectiveTimeoutError` after ``timeout_s``. The wedged
    operation itself cannot be cancelled — the caller must treat a
    timeout as fatal to the collective context (re-form, don't retry
    in place). One-shot form of :class:`_WatchdogThread` (which the
    per-step path holds long-lived to avoid a spawn per step); the
    throwaway worker is told to exit so repeated calls don't
    accumulate parked threads."""
    w = _WatchdogThread()
    try:
        return w.run(fn, timeout_s, what)
    finally:
        w.close()


class MembershipCoordinator:
    """File-plane membership with generation-numbered mesh epochs.

    Layout under ``directory``::

        members/<host>.json        live lease (atomic, renewed)
        members/evicted/...        expired leases, moved aside
        proposals/<g>.json         leader's proposed membership
        proposals/<g>.ack.<host>   member acknowledgements
        epoch.json                 the committed mesh epoch record

    The *leader* is simply the lexicographically-first live host —
    deterministic from any coherent view, no election traffic. A
    commit requires every proposed member's ack, so no survivor can
    run a collective against a membership its peers never agreed to.
    """

    def __init__(self, directory, host_id: str, *,
                 n_devices: Optional[int] = None,
                 addr: Optional[str] = None,
                 lease_secs: Optional[float] = None,
                 port_base: Optional[int] = None,
                 clock: Callable[[], float] = time.time):
        from deeplearning4j_tpu import environment
        self.dir = Path(directory)
        self.host = str(host_id)
        self.addr = addr or os.environ.get("DL4J_TPU_HOST_ADDR",
                                           "127.0.0.1")
        self.n_devices = n_devices
        self.lease_secs = float(
            lease_secs if lease_secs is not None
            else environment.get_flag("DL4J_TPU_HOST_LEASE_SECS"))
        self.port_base = int(
            port_base if port_base is not None
            else environment.get_flag("DL4J_TPU_ELASTIC_PORT_BASE"))
        self.clock = clock
        self._members = self.dir / "members"
        self._proposals = self.dir / "proposals"
        self._members.mkdir(parents=True, exist_ok=True)
        self._proposals.mkdir(parents=True, exist_ok=True)
        self._renew_thread: Optional[threading.Thread] = None
        self._renew_stop = threading.Event()
        self._last_renew = 0.0
        # the auto-renew thread and the per-step maybe_renew share one
        # pid-keyed tmp file — serialize them or one replace()s the
        # tmp out from under the other
        self._renew_lock = threading.Lock()

    @classmethod
    def from_env(cls, **kw) -> "MembershipCoordinator":
        """Coordinator from the standing flags: shared directory from
        ``DL4J_TPU_ELASTIC_DIR`` (required), host identity from
        ``DL4J_TPU_HOST_ID`` (default: hostname-pid — stable across
        the exec-based re-formation, which preserves the pid)."""
        import socket
        from deeplearning4j_tpu import environment
        d = environment.get_flag("DL4J_TPU_ELASTIC_DIR")
        if not d:
            raise ValueError(
                "DL4J_TPU_ELASTIC_DIR is not set — the elastic "
                "membership coordinator needs a shared directory")
        host = environment.get_flag("DL4J_TPU_HOST_ID") or \
            f"{socket.gethostname()}-{os.getpid()}"
        return cls(d, host, **kw)

    # -- leases ---------------------------------------------------------
    def _lease_path(self, host: str) -> Path:
        return self._members / f"{host}.json"

    def renew(self) -> None:
        """Refresh this host's lease (the cross-process heartbeat) and
        mirror every known lease age into ``obs/health.py`` so
        ``/healthz`` + ``dl4j_tpu_worker_stale`` name dead peers."""
        _faults.inject("coordinator")
        from deeplearning4j_tpu.obs import health
        with self._renew_lock:
            now = self.clock()
            _write_json(self._lease_path(self.host), {
                "host": self.host, "pid": os.getpid(),
                "addr": self.addr, "n_devices": self.n_devices,
                "t": now, "lease_secs": self.lease_secs})
            self._last_renew = now
        for host, lease in self._leases().items():
            # each lease carries its OWN staleness window into the
            # health table, so /healthz and the eviction logic render
            # one verdict (a host 20s silent under a 15s lease must
            # not read "ok" against the generic 30s worker default)
            health.observe_age(
                f"host:{host}",
                max(0.0, now - lease.get("t", 0.0)),
                stale_after=float(lease.get("lease_secs",
                                            self.lease_secs)))

    def maybe_renew(self, every: Optional[float] = None) -> bool:
        """Renew when more than ``every`` (default: a third of the
        lease) has passed — the per-step hook stays cheap. Returns
        whether a renewal actually happened (the epoch-stamp check
        piggybacks on the same cadence: a host that never went a
        renewal interval without stepping cannot have slept through a
        re-formation)."""
        every = self.lease_secs / 3.0 if every is None else every
        if self.clock() - self._last_renew >= every:
            self.renew()
            return True
        return False

    def start_auto_renew(self) -> None:
        """Background lease renewal — keeps the host live through long
        compiles/restores. Liveness of the *process* is the right
        signal: a wedged-but-alive straggler is fenced by the mesh
        epoch stamp, not by lease expiry."""
        if self._renew_thread is not None:
            return
        self._renew_stop.clear()

        def run():
            while not self._renew_stop.wait(self.lease_secs / 3.0):
                try:
                    self.renew()
                except Exception:   # pragma: no cover - best effort
                    logger.exception("lease auto-renew failed")

        self._renew_thread = threading.Thread(
            target=run, daemon=True, name="dl4j-lease-renew")
        self._renew_thread.start()

    def stop_auto_renew(self) -> None:
        if self._renew_thread is None:
            return
        self._renew_stop.set()
        self._renew_thread.join(timeout=2.0)
        self._renew_thread = None

    def leave(self) -> None:
        """Graceful departure (the SIGTERM path): drop the lease NOW so
        survivors evict this host at the next agreement instead of
        waiting out the lease window. The fleet-plane snapshot is
        retired first (into a ``departed`` bundle) — a stale snapshot
        with no lease would read as a corpse to the skew attribution
        forever."""
        self.stop_auto_renew()
        try:
            from deeplearning4j_tpu import obs
            # now= keeps the bundle in THIS coordinator's clock domain
            # (an injected clock mixed with wall time reads every
            # lease as astronomically stale)
            obs.fleet.record_departure(self.dir, self.host,
                                       now=self.clock())
        except Exception:           # pragma: no cover - best effort
            logger.exception("elastic: departure bundle failed")
        self._lease_path(self.host).unlink(missing_ok=True)

    def _leases(self) -> Dict[str, dict]:
        out = {}
        for p in sorted(self._members.glob("*.json")):
            lease = _read_json(p)
            if lease and "host" in lease:
                out[str(lease["host"])] = lease
        return out

    def live_members(self, now: Optional[float] = None) -> List[str]:
        now = self.clock() if now is None else now
        live = []
        for host, lease in self._leases().items():
            if now - lease.get("t", 0.0) <= lease.get(
                    "lease_secs", self.lease_secs):
                live.append(host)
        return sorted(live)

    def evict_expired(self, now: Optional[float] = None) -> List[str]:
        """Move expired leases to ``members/evicted/`` (kept for
        post-mortems, out of every live scan) and count them in
        ``dl4j_tpu_hosts_evicted_total``."""
        from deeplearning4j_tpu import obs
        now = self.clock() if now is None else now
        evicted = []
        dest = self._members / "evicted"
        for host, lease in self._leases().items():
            age = now - lease.get("t", 0.0)
            if age <= lease.get("lease_secs", self.lease_secs):
                continue
            dest.mkdir(parents=True, exist_ok=True)
            try:
                os.replace(self._lease_path(host),
                           dest / f"{host}.{now:.0f}.json")
            except OSError:
                continue            # a peer moved it first — fine
            evicted.append(host)
            obs.metrics.HOSTS_EVICTED.inc()
            # flight recorder, leader half: exactly one peer wins the
            # os.replace above, and that peer snapshots the corpse's
            # FINAL telemetry into a postmortem bundle (no-op when the
            # fleet plane never published for it)
            try:
                obs.fleet.record_eviction(self.dir, host,
                                          by=self.host, now=now)
            except Exception:       # pragma: no cover - best effort
                logger.exception("elastic: eviction bundle failed")
            logger.warning(
                "elastic: evicted host %r (lease %.1fs overdue)",
                host, age - self.lease_secs)
        return evicted

    # -- mesh epochs ----------------------------------------------------
    def epoch_record(self) -> Optional[dict]:
        """The committed mesh epoch: ``{"epoch", "members",
        "coordinator", "addr", "port"}`` (None before first
        formation)."""
        return _read_json(self.dir / "epoch.json")

    def committed_epoch(self) -> int:
        rec = self.epoch_record()
        return int(rec["epoch"]) if rec else 0

    def check_epoch(self, epoch: int) -> None:
        """Reject a straggler: raises :class:`StaleMeshEpoch` when the
        committed generation has moved past ``epoch`` — this host must
        not touch the new generation's collectives."""
        cur = self.committed_epoch()
        if cur != int(epoch):
            raise StaleMeshEpoch(
                f"host {self.host!r} runs mesh epoch {epoch} but the "
                f"fleet committed epoch {cur} — this process slept "
                "through a re-formation and must re-join, not compute")

    def agree_membership(self, timeout_s: float = 60.0,
                         poll_s: float = 0.05) -> dict:
        """One agreement round: evict expired leases, leader proposes
        the live set at generation ``committed+1``, every proposed
        member acks, leader commits. Idempotent — when the committed
        record already names exactly the live set, it is returned
        as-is (the steady-state fast path). Every survivor returns the
        SAME record; a host that finds itself excluded raises
        :class:`Evicted`."""
        _faults.inject("coordinator")
        deadline = self.clock() + timeout_s
        self.renew()
        last_ack = None             # (g, members) last written
        while True:
            now = self.clock()
            if now > deadline:
                raise TimeoutError(
                    f"membership agreement did not converge within "
                    f"{timeout_s}s (live={self.live_members()})")
            self.evict_expired(now)
            live = self.live_members(now)
            if self.host not in live:
                # our own lease lapsed mid-agreement: re-joining at
                # the NEXT epoch is the elastic semantic for a host
                # that is demonstrably alive (the leader will include
                # the fresh lease in its superseding proposal);
                # :class:`Evicted` fires only when the fleet has
                # ALREADY committed a membership without us (fast
                # path above / :meth:`rank_of`)
                self.renew()
                live = self.live_members()
            cur = self.epoch_record()
            if cur and sorted(cur.get("members", [])) == live:
                if self.host not in live:
                    raise Evicted(
                        f"host {self.host!r} is not in the committed "
                        f"membership {live}")
                return cur
            g = (int(cur["epoch"]) if cur else 0) + 1
            leader = live[0] if live else self.host
            prop_path = self._proposals / f"{g}.json"
            prop = _read_json(prop_path)
            if leader == self.host and prop is not None and \
                    sorted(prop.get("members", [])) != live:
                # SUPERSEDE a stale proposal: a proposed member died
                # before acking (its ack can never arrive), or the
                # proposer itself is gone — without this overwrite,
                # generation g could never commit and the fleet would
                # be permanently unable to form
                prop = None
            if prop is None and leader == self.host:
                prop = {"epoch": g, "members": live,
                        "coordinator": leader,
                        "addr": self._leases().get(leader, {}).get(
                            "addr", self.addr),
                        "port": self.port_base + (g % 1000)}
                _write_json(prop_path, prop)
            if prop is not None and self.host in prop["members"]:
                # the ack names the member set it is FOR, so acks of a
                # superseded proposal cannot count toward the new one;
                # written only when (g, set) changes — not per poll
                ack_key = (g, tuple(sorted(prop["members"])))
                if ack_key != last_ack:
                    _write_json(
                        self._proposals / f"{g}.ack.{self.host}",
                        {"host": self.host, "epoch": g,
                         "members": sorted(prop["members"])})
                    last_ack = ack_key
            if prop is not None and leader == self.host:
                # strip the "<g>.ack." prefix (NOT Path.suffix — host
                # ids may legitimately contain dots, e.g. hostnames)
                ack_prefix = f"{g}.ack."
                acks = set()
                for a in self._proposals.glob(f"{g}.ack.*"):
                    data = _read_json(a)
                    if data and sorted(data.get("members", [])) == \
                            sorted(prop["members"]):
                        acks.add(a.name[len(ack_prefix):])
                if all(m in acks for m in prop["members"]):
                    _write_json(self.dir / "epoch.json", prop)
                    from deeplearning4j_tpu import obs
                    obs.metrics.MESH_EPOCH.set(g)
                    logger.warning(
                        "elastic: committed mesh epoch %d members=%s",
                        g, prop["members"])
            time.sleep(poll_s)

    def join(self, expected: Optional[int] = None,
             timeout_s: float = 120.0,
             settle_s: Optional[float] = None) -> dict:
        """Initial formation / re-join. With ``expected`` the host
        waits for that many live leases (fast, for coordinated
        launches); without it the live set must hold STABLE for
        ``settle_s`` (default: one lease window) — long enough for a
        dead host's lease to expire so a post-failure re-formation
        cannot re-commit the corpse. Then one :meth:`agree_membership`
        round commits (or confirms) the epoch."""
        settle = self.lease_secs if settle_s is None else settle_s
        deadline = self.clock() + timeout_s
        self.renew()
        stable_since = self.clock()
        prev = self.live_members()
        while True:
            now = self.clock()
            if now > deadline:
                raise TimeoutError(
                    f"join did not converge within {timeout_s}s "
                    f"(live={prev}, expected={expected})")
            live = self.live_members(now)
            if expected is not None:
                if len(live) >= expected:
                    break
            else:
                if live != prev:
                    prev, stable_since = live, now
                elif now - stable_since >= settle:
                    break
            time.sleep(min(0.05, self.lease_secs / 10))
            # keep our lease fresh at the normal cadence — a full
            # fsync'd write every 50ms poll would hammer the shared
            # filesystem for nothing
            self.maybe_renew()
        rec = self.agree_membership(
            timeout_s=max(5.0, deadline - self.clock()))
        from deeplearning4j_tpu import obs
        obs.metrics.MESH_EPOCH.set(int(rec["epoch"]))
        return rec

    def rank_of(self, rec: dict) -> int:
        members = sorted(rec["members"])
        if self.host not in members:
            raise Evicted(f"host {self.host!r} not in {members}")
        return members.index(self.host)


class ElasticContext:
    """Per-step elastic hooks installed on a ``ParallelWrapper``
    (``wrapper.elastic = ElasticContext(...)``): stamp + verify the
    mesh epoch before every dispatch, renew the lease, and run the
    blocking loss sync under the collective watchdog. This is where
    the ``host_death`` fault-injection site lives, so membership-change
    paths are drillable like every other failure mode
    (``DL4J_TPU_FAULT_PLAN=host-preempt``)."""

    def __init__(self, coordinator: MembershipCoordinator, record: dict,
                 collective_timeout_s: Optional[float] = None,
                 compile_grace_s: float = 300.0,
                 fleet=None):
        self.coordinator = coordinator
        self.record = record
        self.epoch = int(record["epoch"])
        #: optional ``obs.fleet.FleetTelemetry`` — when set, every
        #: step stamps barrier entry/exit into the published snapshot
        #: (the aggregator's skew-attribution source); None costs one
        #: branch per step
        self.fleet = fleet
        # default: two lease windows — a dead peer's lease expires and
        # is evictable by the time the watchdog fires
        self.collective_timeout_s = (
            2.0 * coordinator.lease_secs
            if collective_timeout_s is None else collective_timeout_s)
        # the FIRST dispatch of a fresh process image compiles the
        # step (tens of seconds on real hardware) — it gets this much
        # headroom before the watchdog calls it a dead peer
        self.compile_grace_s = float(compile_grace_s)
        self.last_step_wall: Optional[float] = None
        self._watchdog = _WatchdogThread()
        self._dispatched_once = False
        self._last_epoch_check = 0.0

    def pre_step(self, iteration: int) -> None:
        from deeplearning4j_tpu import obs
        _faults.inject("host_death")
        now = self.coordinator.clock()
        self.last_step_wall = now
        self.coordinator.maybe_renew()
        # epoch stamp on its OWN lease/3 cadence (NOT gated on
        # maybe_renew's return — the auto-renew thread refreshes the
        # lease at the same interval, which would starve the check):
        # reading the committed record (a shared-FS hit) every single
        # step buys nothing, since a host that never went a third of
        # a lease without stepping cannot have slept through a
        # re-formation
        if now - self._last_epoch_check >= \
                self.coordinator.lease_secs / 3.0:
            self._last_epoch_check = now
            self.coordinator.check_epoch(self.epoch)
            obs.metrics.MESH_EPOCH.set(self.epoch)
        if self.fleet is not None:
            # barrier-ENTRY stamp (wall clock, cross-host comparable):
            # a host that stamps this late every step IS the straggler
            # the fleet aggregator names
            self.fleet.note_enter(iteration, t=now)

    def post_step(self, iteration: int, loss: float) -> None:
        """Barrier-EXIT stamp + flight-recorder ring entry +
        cadence-gated snapshot publish, called by the wrapper once the
        loss sync lands. The off path (no fleet plane) is this one
        branch."""
        if self.fleet is None:
            return
        self.fleet.record_step(iteration, mesh_epoch=self.epoch,
                               loss=loss,
                               t_exit=self.coordinator.clock())

    def run(self, fn: Callable[[], object]):
        """A step dispatch under the watchdog — a dead peer turns an
        indefinite in-dispatch collective hang into a
        :class:`CollectiveTimeoutError` within the window. One
        long-lived daemon worker serves every step (no thread spawn
        on the hot path). The first dispatch of this context runs
        under ``compile_grace_s`` instead: a cold XLA compile is not
        a dead peer."""
        timeout = self.collective_timeout_s
        if not self._dispatched_once:
            timeout = max(timeout, self.compile_grace_s)
        out = self._watchdog.run(fn, timeout,
                                 what=f"step (mesh epoch "
                                      f"{self.epoch})")
        self._dispatched_once = True
        return out

    def sync(self, value):
        """The step's blocking device sync (``float(loss)``) under the
        watchdog — same contract as :meth:`run` for runtimes whose
        dispatch is async and whose block lands on the host read."""
        return self._watchdog.run(
            lambda: float(value), self.collective_timeout_s,
            what=f"step sync (mesh epoch {self.epoch})")


def elastic_env(rec: dict) -> Dict[str, str]:
    """The distributed bring-up env for a committed epoch record —
    what ``parallel/mesh.py::initialize_distributed`` reads. The port
    is epoch-salted so a stale generation's coordination service can
    never capture the new generation's workers."""
    members = sorted(rec["members"])
    return {
        "DL4J_TPU_COORD": f"{rec.get('addr', '127.0.0.1')}"
                          f":{rec['port']}",
        "DL4J_TPU_NPROC": str(len(members)),
    }


def reform_exec(restarts: int, argv: Optional[List[str]] = None) -> None:
    """Re-formation by image replacement: the wedged collective
    runtime cannot be shut down in-process (the coordination client
    aborts the process once a peer died), so survivors ``exec`` a
    fresh image that re-runs bring-up at the new world size. Never
    returns."""
    from deeplearning4j_tpu import obs
    os.environ[_REFORM_ENV] = "1"
    os.environ[_RESTARTS_ENV] = str(restarts)
    obs.metrics.RESILIENCE_RESTARTS.inc()
    argv = list(sys.argv if argv is None else argv)
    logger.warning("elastic: re-forming by exec (restart %d): %s",
                   restarts, [sys.executable] + argv)
    sys.stdout.flush()
    sys.stderr.flush()
    os.execv(sys.executable, [sys.executable] + argv)


def is_reform() -> bool:
    """True in a process image produced by :func:`reform_exec`."""
    return os.environ.get(_REFORM_ENV) == "1"


def prior_restarts() -> int:
    try:
        return int(os.environ.get(_RESTARTS_ENV, "0"))
    except ValueError:
        return 0


class ElasticTrainer:
    """The per-host elastic training loop: bring up membership, form
    the mesh at the agreed world size, reshard-restore the newest
    valid checkpoint, train under bounded-timeout collectives, and
    re-form (by exec) when a peer dies.

    ``net_factory``: builds a fresh initialized net (the restore
    template). Checkpoints go through
    ``ShardedCheckpointer.save_wrapper`` every ``save_every``
    iterations — each device writes only its 1/N optimizer shard, and
    restore reshards onto whatever world size survived
    (``restore_wrapper(..., reshard=True)``).
    """

    def __init__(self, net_factory: Callable[[], object], ckpt_dir, *,
                 coordinator: MembershipCoordinator,
                 sharded_update: bool = True,
                 save_every: int = 2, keep_last: int = 20,
                 collective_timeout_s: Optional[float] = None,
                 max_reforms: int = 5,
                 fleet_telemetry: Optional[bool] = None):
        from deeplearning4j_tpu import environment
        self.net_factory = net_factory
        self.ckpt_dir = Path(ckpt_dir)
        self.coordinator = coordinator
        self.sharded_update = sharded_update
        self.save_every = save_every
        self.keep_last = keep_last
        self.collective_timeout_s = collective_timeout_s
        self.max_reforms = max_reforms
        self.fleet_telemetry = bool(
            environment.get_flag("DL4J_TPU_FLEET_TELEMETRY")
            if fleet_telemetry is None else fleet_telemetry)
        self.fleet = None
        self.wrapper = None
        self.net = None
        self.record: Optional[dict] = None
        self.resumed_step: Optional[int] = None
        self._ck = None

    # -- bring-up -------------------------------------------------------
    def bring_up(self, expected: Optional[int] = None):
        """Join → agree → form the mesh → reshard-restore. Returns the
        (wrapper, epoch record) pair ready to train. ``expected`` is
        the launch-time host count; a re-exec'd image ignores it and
        waits for the live set to settle instead (the dead host's
        lease must expire before the new generation commits)."""
        from deeplearning4j_tpu import obs
        from deeplearning4j_tpu.parallel import mesh as _mesh
        from deeplearning4j_tpu.serialization import ShardedCheckpointer

        co = self.coordinator
        restarts = prior_restarts()
        if restarts:
            # the restart counter crossed the exec boundary in env;
            # fold it back into the fresh image's metrics registry
            obs.metrics.RESILIENCE_RESTARTS.inc(restarts)
        rec = co.join(expected=None if is_reform() else expected)
        co.start_auto_renew()
        members = sorted(rec["members"])
        if len(members) > 1:
            env = elastic_env(rec)
            _mesh.initialize_distributed_elastic(
                env["DL4J_TPU_COORD"],
                num_processes=len(members),
                process_id=co.rank_of(rec))
        import jax
        from deeplearning4j_tpu.parallel.wrapper import ParallelWrapper
        self.net = self.net_factory()
        self.wrapper = ParallelWrapper(
            self.net, sharded_update=self.sharded_update,
            prefetch_buffer=0)
        if self.fleet_telemetry:
            # the fleet observability plane rides the same shared dir
            # as the leases: snapshots under telemetry/, postmortem
            # bundles under postmortem/ (obs/fleet.py)
            self.fleet = obs.fleet.FleetTelemetry(
                co.dir, co.host, clock=co.clock)
            self.fleet.event("mesh_epoch_commit", epoch=rec["epoch"],
                             members=sorted(rec["members"]),
                             restarts=restarts)
            obs.metrics.set_fleet_dir(co.dir)
        self.wrapper.elastic = ElasticContext(
            co, rec, collective_timeout_s=self.collective_timeout_s,
            fleet=self.fleet)
        self.record = rec
        self._ck = ShardedCheckpointer(self.ckpt_dir,
                                       keep_last=self.keep_last,
                                       async_save=False)
        if self._ck.all_steps():
            self._ck.restore_latest_valid(wrapper=self.wrapper)
            self.resumed_step = int(self.net.iteration)
            logger.warning(
                "elastic: host %s resumed step %d at world size %d "
                "(mesh epoch %d, %d device(s))", co.host,
                self.resumed_step, len(members), rec["epoch"],
                len(jax.devices()))
        return self.wrapper, rec

    # -- checkpoint listener -------------------------------------------
    class _SaveListener:
        """Collective sharded save every k iterations — every host
        calls ``save_wrapper`` at the same step (the fit loops run in
        lockstep), so each device publishes exactly its shard."""

        def __init__(self, trainer: "ElasticTrainer"):
            self.t = trainer

        def iteration_done(self, net, iteration, epoch):
            t = self.t
            if t.save_every and iteration % t.save_every == 0:
                t._ck.save_wrapper(
                    iteration, t.wrapper, wait=True,
                    mesh_epoch=int(t.record["epoch"]))

        def on_epoch_start(self, net):
            pass

        def on_epoch_end(self, net):
            pass

    # -- the loop -------------------------------------------------------
    def fit(self, iterator, epochs: int, expected: Optional[int] = None):
        """Train to ``epochs`` total epochs, surviving host loss. On a
        peer failure (collective timeout/error, stale epoch) the host
        re-forms via exec and THIS CALL NEVER RETURNS — the fresh
        image must re-run the same script, whose ``fit`` resumes from
        the reshard-restored checkpoint. Returns ``"done"`` on
        completion, ``"preempted"`` after a clean SIGTERM departure."""
        import jax
        from deeplearning4j_tpu.resilience.policy import (
            Preempted, PreemptionHandler)
        if self.wrapper is None:
            self.bring_up(expected=expected)
        net = self.net
        listener = self._SaveListener(self)
        if listener not in net.listeners:
            net.listeners.append(listener)
        handler = None
        try:
            handler = PreemptionHandler().install()
        except ValueError:          # not the main thread
            handler = None

        class _PreemptGate:
            def iteration_done(self, _net, _it, _ep):
                if handler is not None and handler.requested:
                    raise Preempted()

            def on_epoch_start(self, _net):
                pass

            def on_epoch_end(self, _net):
                pass

        gate = _PreemptGate()
        net.listeners.append(gate)
        try:
            while net.epoch < epochs:
                self.wrapper.fit(iterator, epochs=1)
            # final save — unless the per-k listener already published
            # this exact step (orbax refuses to overwrite a step)
            if self.save_every and \
                    net.iteration not in self._ck.all_steps():
                self._ck.save_wrapper(net.iteration, self.wrapper,
                                      wait=True,
                                      mesh_epoch=int(
                                          self.record["epoch"]))
            if self.fleet is not None:
                # final telemetry: a run shorter than the publish
                # cadence must still leave its last step in the fleet
                # view (the same reason the dump paths force-publish).
                # Best-effort — a telemetry write failure on a
                # FINISHED run must not classify transient and burn
                # reform() exec cycles on a job that already succeeded
                try:
                    self.fleet.publish(force=True)
                except Exception:   # pragma: no cover - disk gone
                    logger.exception("elastic: final telemetry "
                                     "publish failed")
            return "done"
        except Preempted:
            # graceful departure: drop the lease so survivors evict us
            # at the next agreement; a single-host world checkpoints
            # first (no peers are needed for that save)
            from deeplearning4j_tpu import obs
            obs.metrics.PREEMPTIONS.inc()
            if len(self.record["members"]) == 1 and self.save_every \
                    and net.iteration not in self._ck.all_steps():
                # skip when the per-k listener already published this
                # exact step (orbax refuses to overwrite a step)
                self._ck.save_wrapper(net.iteration, self.wrapper,
                                      wait=True,
                                      mesh_epoch=int(
                                          self.record["epoch"]))
            self._flight_dump("preemption")
            self.coordinator.leave()
            return "preempted"
        except Evicted as e:
            # no republish: the leader's eviction bundle already
            # retired this host's snapshot — rewriting it would leave
            # a lease-less "corpse" in the fleet view forever
            self._flight_dump(e, republish=False)
            raise
        except (CollectiveTimeoutError, StaleMeshEpoch) as e:
            # dead-peer / stale-straggler signals: re-forming (exec →
            # join the new epoch) is the designed answer for both
            self.reform(e)          # never returns
        except Exception as e:
            from deeplearning4j_tpu.resilience.policy import (
                TRANSIENT, classify)
            # XlaRuntimeError = the collective runtime itself failed
            # (gloo reset, ICI fault): ALWAYS a re-formation matter,
            # whatever keywords its message happens to carry
            if type(e).__name__ == "XlaRuntimeError" or \
                    classify(e) == TRANSIENT:
                self.reform(e)      # never returns
            # deterministic failures (shape bugs, NonFiniteError...)
            # would recur identically after every reform — surface
            # them, with the flight recorder carrying the last-N
            # steps, instead of burning max_reforms fleet-wide
            # exec/restore cycles on an error no re-formation can fix
            self._flight_dump(e)
            raise
        finally:
            for l in (listener, gate):
                if l in net.listeners:
                    net.listeners.remove(l)
            if handler is not None:
                handler.uninstall()

    def _flight_dump(self, cause, republish: bool = True) -> None:
        """Best-effort flight-recorder bundle — the black box must
        never turn one failure into two."""
        if self.fleet is None:
            return
        try:
            self.fleet.dump(cause, republish=republish)
        except Exception:           # pragma: no cover - disk gone
            logger.exception("elastic: flight-recorder dump failed")

    def reform(self, cause: BaseException) -> None:
        """Peer-failure answer: record the cause (flight-recorder
        bundle first — the postmortem must survive the exec), stop
        renewing from this doomed image, and exec a fresh one.
        Membership agreement happens in the NEW image's
        :meth:`bring_up` — the old image still hosts the wedged
        runtime, whose distributed client may abort the process at
        any moment; the file plane work must not race against that."""
        self._flight_dump(cause)
        restarts = prior_restarts() + 1
        if restarts > self.max_reforms:
            raise RuntimeError(
                f"elastic: {restarts} re-formations exceed the budget "
                f"({self.max_reforms}); last cause: {cause!r}") \
                from cause
        ctx = getattr(self.wrapper, "elastic", None)
        detect_s = -1.0
        if ctx is not None and ctx.last_step_wall is not None:
            detect_s = self.coordinator.clock() - ctx.last_step_wall
        # structured breadcrumb the chaos drill parses: the bounded-
        # timeout raise happened, this long after the last dispatch
        logger.warning(
            "ELASTIC_REFORM host=%s epoch=%s cause=%s detect_s=%.2f",
            self.coordinator.host,
            self.record and self.record.get("epoch"),
            type(cause).__name__, detect_s)
        self.coordinator.stop_auto_renew()
        reform_exec(restarts)
