"""Deterministic fault injection — failure as a testable artifact.

The reference's recovery story (CheckpointListener + ModelSerializer
resume + Spark task retry, SURVEY §5) was only ever exercised by real
outages; none of its failure paths had a switch a test could flip.
This module gives the port one: seedable fault *plans* whose rules
fire at named *sites* threaded through the real code paths —

========================  ===================================================
site                      where it fires
========================  ===================================================
``ckpt_write``            ``ModelSerializer.write_model`` before the tmp
                          file is written (checkpoint IO refused)
``ckpt_commit``           after the tmp zip is fully written, before
                          ``os.replace`` publishes it (crash-mid-save: the
                          window atomic writes must make unobservable)
``step``                  ``MultiLayerNetwork``/``ComputationGraph`` fit,
                          before the jitted step dispatch
``iterator``              ``DataSetIterator._apply_pp`` — every batch any
                          iterator yields
``worker_step``           ``ParallelWrapper.fit`` per-worker loop body
``serving``               ``ParallelInference`` dispatch worker, per batch
``host_death``            ``elastic.ElasticContext.pre_step`` — every
                          elastic step on every host (``error=exit`` is
                          the in-process kill -9 analog, ``sigterm`` a
                          preemption notice for ONE host of a fleet)
``coordinator``           ``elastic.MembershipCoordinator`` lease renewal
                          and agreement rounds (coordination-plane IO
                          flakes)
``router``                ``serving.fleet.ServingRouter.submit`` — every
                          request the front-end router forwards to a
                          replica (routing-plane flakes)
``replica_spawn``         ``serving.fleet.ServingReplica.start`` and the
                          supervisor's respawn path — a replica dying
                          during bring-up (before it takes its lease)
========================  ===================================================

Plans are env-gated (``DL4J_TPU_FAULT_PLAN``) and the **off path is one
branch**: :func:`inject` returns after a single module-global ``None``
check — no callback runs, no counter moves (the same contract as the
span tracer's off path, counter-asserted by ``tests/test_resilience.py``).

Plan syntax — ``;``-separated rules, each ``site[:key=value]...``::

    DL4J_TPU_FAULT_PLAN="ckpt_*:error=OSError:p=0.5:seed=3:max=2;step:nth=6"

``site`` may be an ``fnmatch`` glob. Keys: ``error`` (exception class
name from :data:`ERRORS`, or ``sigterm``/``exit`` for process-level
faults), ``p`` (per-evaluation probability, seeded → deterministic),
``nth`` (fire on exactly the nth evaluation), ``every`` (every kth),
``max`` (max fires), ``seed``. Named plans (:data:`NAMED_PLANS`) give
``tools/chaos.py`` and the docs a shared vocabulary.

Every fire increments ``dl4j_tpu_faults_injected_total{site=...}`` so
an injected-fault run is self-describing in ``/metrics``.
"""
from __future__ import annotations

import fnmatch
import logging
import os
import random
import signal
import threading
from contextlib import contextmanager
from typing import Dict, List, Optional, Union

logger = logging.getLogger("deeplearning4j_tpu")


class InjectedFault(RuntimeError):
    """Default exception a fault rule raises (classified transient by
    ``resilience.policy`` — retry paths see it as a real failure)."""


#: exception classes a rule may raise by name (`error=` key), plus the
#: process-level kinds ``sigterm`` (self-delivered preemption notice)
#: and ``exit`` (hard crash via ``os._exit`` — no finally blocks, the
#: closest in-process analog of kill -9)
ERRORS = {
    "InjectedFault": InjectedFault,
    "RuntimeError": RuntimeError,
    "OSError": OSError,
    "IOError": OSError,
    "ConnectionError": ConnectionError,
    "TimeoutError": TimeoutError,
    "ValueError": ValueError,
    "MemoryError": MemoryError,
    "FloatingPointError": FloatingPointError,
}

#: error kinds resolved lazily at fire time (importing them here would
#: couple this module's import to theirs); validated by name like the
#: ERRORS entries
_LAZY_ERRORS = {"NonFiniteError"}


def _error_class(name: str):
    if name == "NonFiniteError":
        # the numerics observatory's structured NaN sentinel — lets a
        # step-site rule drill the whole attribute-classify-restore
        # path (classified deterministic by resilience.policy)
        from deeplearning4j_tpu.obs.numerics import NonFiniteError
        return NonFiniteError
    return ERRORS[name]

#: every site threaded into the codebase (the table above) — literal
#: rule sites are validated against this at parse time so a typo'd
#: plan fails loudly instead of silently never firing
KNOWN_SITES = frozenset({"ckpt_write", "ckpt_commit", "step",
                         "iterator", "worker_step", "serving",
                         "host_death", "coordinator", "router",
                         "replica_spawn"})

#: the chaos vocabulary: plan names accepted by ``FaultPlan.parse``,
#: ``tools/chaos.py --plan`` and ``DL4J_TPU_FAULT_PLAN`` itself
NAMED_PLANS = {
    # checkpoint IO flakes: refuse some writes, kill one commit window
    "ckpt-io-flake": "ckpt_write:error=OSError:p=0.5:seed=3:max=3;"
                     "ckpt_commit:error=OSError:nth=2:max=1",
    # one mid-training step failure (the chip-drop analog)
    "worker-crash": "step:error=ConnectionError:nth=6:max=1",
    # data pipeline flake mid-epoch
    "etl-flake": "iterator:error=OSError:nth=9:max=1",
    # serving dispatch worker takes a poisoned batch
    "serving-crash": "serving:error=RuntimeError:nth=2:max=1",
    # self-delivered SIGTERM mid-fit (preemption notice)
    "preempt": "step:error=sigterm:nth=5:max=1",
    # one host of an elastic fleet gets its preemption notice mid-run
    # (elastic step site): graceful leave -> survivors evict + re-form
    "host-preempt": "host_death:error=sigterm:nth=4:max=1",
    # coordination-plane IO flakes: lease renewals / agreement rounds
    # hit a flaky shared filesystem
    "coord-flake": "coordinator:error=OSError:p=0.4:seed=9:max=2",
    # one serving replica hard-dies mid-trace (`error=exit` = the
    # in-process kill -9 analog, fired at the gateway worker's per-
    # iteration serving site): the router must stop routing to it
    # within a lease window and the supervisor respawns capacity
    "replica-crash": "serving:error=exit:nth=25:max=1",
    # the routing plane itself flakes: one forwarded request hits a
    # connection error -> re-route, shed only within budget
    "router-flake": "router:error=ConnectionError:nth=3:max=1",
    # a replica dies during bring-up, before its first lease: the
    # supervisor must observe the missing lease and spawn again
    "spawn-crash": "replica_spawn:error=exit:nth=1:max=1",
}

_EXIT_CODE = 17         # `error=exit` status — distinguishable from crashes


class FaultRule:
    """One parsed rule: a site pattern plus deterministic firing state."""

    def __init__(self, site: str, error: str = "InjectedFault",
                 p: float = 1.0, nth: int = 0, every: int = 0,
                 max_fires: int = 1 << 30, seed: int = 0):
        if error not in ERRORS and error not in _LAZY_ERRORS \
                and error not in ("sigterm", "exit"):
            raise ValueError(
                f"fault rule {site!r}: unknown error kind {error!r} "
                f"(one of {sorted(ERRORS) + sorted(_LAZY_ERRORS)} "
                "| sigterm | exit)")
        self.site = site
        self.error = error
        self.p = float(p)
        self.nth = int(nth)
        self.every = int(every)
        self.max_fires = int(max_fires)
        self.seed = int(seed)
        self.evals = 0
        self.fires = 0
        self._rng = random.Random(self.seed)

    def matches(self, site: str) -> bool:
        return self.site == site or fnmatch.fnmatchcase(site, self.site)

    def should_fire(self) -> bool:
        """Evaluate once (call with the plan lock held) — deterministic
        for a given (seed, evaluation-ordinal) pair."""
        self.evals += 1
        if self.fires >= self.max_fires:
            return False
        if self.nth:
            return self.evals == self.nth
        if self.every:
            return self.evals % self.every == 0
        if self.p >= 1.0:
            return True
        return self._rng.random() < self.p

    def describe(self) -> str:
        parts = [self.site, f"error={self.error}"]
        if self.nth:
            parts.append(f"nth={self.nth}")
        elif self.every:
            parts.append(f"every={self.every}")
        elif self.p < 1.0:
            parts.append(f"p={self.p}:seed={self.seed}")
        if self.max_fires < (1 << 30):
            parts.append(f"max={self.max_fires}")
        return ":".join(parts)


class FaultPlan:
    """An ordered list of :class:`FaultRule` — the unit of activation."""

    def __init__(self, rules: List[FaultRule]):
        self.rules = list(rules)

    @staticmethod
    def parse(spec: Union[str, "FaultPlan"]) -> "FaultPlan":
        if isinstance(spec, FaultPlan):
            return spec
        spec = NAMED_PLANS.get(spec.strip(), spec)
        rules = []
        for chunk in spec.split(";"):
            chunk = chunk.strip()
            if not chunk:
                continue
            fields = chunk.split(":")
            kwargs: Dict[str, object] = {}
            for f in fields[1:]:
                if "=" not in f:
                    raise ValueError(
                        f"fault plan field {f!r} (rule {chunk!r}) is "
                        "not key=value")
                k, v = f.split("=", 1)
                k = {"max": "max_fires"}.get(k, k)
                if k == "error":
                    kwargs[k] = v
                elif k == "p":
                    kwargs[k] = float(v)
                elif k in ("nth", "every", "max_fires", "seed"):
                    kwargs[k] = int(v)
                else:
                    raise ValueError(
                        f"fault plan key {k!r} (rule {chunk!r}) unknown")
            site = fields[0]
            # a literal (non-glob) site that matches nothing would arm
            # a plan that silently never fires — reject it here; globs
            # stay free-form for forward compatibility
            if not any(c in site for c in "*?[") and \
                    site not in KNOWN_SITES:
                raise ValueError(
                    f"fault plan site {site!r} unknown "
                    f"(one of {sorted(KNOWN_SITES)}, or a glob)")
            rules.append(FaultRule(site, **kwargs))
        if not rules:
            raise ValueError(f"fault plan {spec!r} has no rules")
        return FaultPlan(rules)

    def describe(self) -> str:
        return ";".join(r.describe() for r in self.rules)


_lock = threading.Lock()
_plan: Optional[FaultPlan] = None   # the one branch the off path pays
_evaluations = 0                    # bumps ONLY while a plan is active


def inject(site: str) -> None:
    """Hot-path hook. With no plan active this returns after a single
    module-global check — the whole cost of shipping fault injection in
    production code paths."""
    if _plan is None:
        return
    _inject_active(site)


def _inject_active(site: str) -> None:
    global _evaluations
    fire_rule = None
    with _lock:
        plan = _plan
        if plan is None:            # deactivated between check and lock
            return
        _evaluations += 1
        for rule in plan.rules:
            if rule.matches(site) and rule.should_fire():
                rule.fires += 1
                fire_rule = rule
                break
    if fire_rule is None:
        return
    from deeplearning4j_tpu import obs
    obs.metrics.FAULTS_INJECTED.labels(site=site).inc()
    logger.warning("fault injection: firing %r at site %r (fire %d)",
                   fire_rule.error, site, fire_rule.fires)
    if fire_rule.error == "exit":
        os._exit(_EXIT_CODE)
    if fire_rule.error == "sigterm":
        os.kill(os.getpid(), signal.SIGTERM)
        return                      # the preemption handler takes over
    raise _error_class(fire_rule.error)(
        f"injected fault at site {site!r} "
        f"(rule {fire_rule.describe()}, fire {fire_rule.fires})")


def activate(plan: Union[str, FaultPlan]) -> FaultPlan:
    """Install ``plan`` (a spec string, plan name, or FaultPlan) as the
    process-wide active plan."""
    global _plan
    plan = FaultPlan.parse(plan)
    with _lock:
        _plan = plan
    logger.warning("fault injection ACTIVE: %s", plan.describe())
    return plan


def deactivate() -> None:
    global _plan
    with _lock:
        _plan = None


@contextmanager
def active(plan: Union[str, FaultPlan]):
    """``with faults.active("step:nth=3"):`` — scoped activation for
    tests and the chaos harness."""
    p = activate(plan)
    try:
        yield p
    finally:
        deactivate()


def plan() -> Optional[FaultPlan]:
    return _plan


def evaluations() -> int:
    """Total site evaluations while a plan was active — stays 0 for the
    whole process lifetime when ``DL4J_TPU_FAULT_PLAN`` is unset (the
    off-path zero-overhead assertion)."""
    return _evaluations


def stats() -> Dict[str, Dict[str, int]]:
    """Per-rule ``{pattern: {evals, fires}}`` of the active plan."""
    with _lock:
        if _plan is None:
            return {}
        return {r.describe(): {"evals": r.evals, "fires": r.fires}
                for r in _plan.rules}


def reset() -> None:
    """Tests only: drop the plan and zero the evaluation counter."""
    global _plan, _evaluations
    with _lock:
        _plan = None
        _evaluations = 0


def configure_from_env() -> Optional[FaultPlan]:
    """Activate the plan named by ``DL4J_TPU_FAULT_PLAN`` (called by
    ``environment.apply_startup_flags`` at package import; unset/empty
    → no plan, and the import path never even reaches this module)."""
    from deeplearning4j_tpu import environment
    raw = str(environment.get_flag("DL4J_TPU_FAULT_PLAN") or "").strip()
    if not raw:
        return None
    return activate(raw)
