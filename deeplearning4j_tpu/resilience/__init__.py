"""Resilience subsystem — failure as a managed, testable artifact.

PR 1 made compilation a managed artifact, PR 2 made runtime behavior
observable; this package does the same for *failure* (ARCHITECTURE.md
§10): the reference's recovery idiom (CheckpointListener +
ModelSerializer resume + Spark task retry, SURVEY §5) is rebuilt
robust-by-construction and verified by injected faults — the posture
PyGraph (PAPERS.md) takes for CUDA-graph capture.

- :mod:`~deeplearning4j_tpu.resilience.faults` — deterministic,
  seedable fault injection at named sites threaded through the real
  code paths (checkpoint IO, step dispatch, iterator, worker loop,
  serving worker); env-gated by ``DL4J_TPU_FAULT_PLAN``, one-branch
  off path.
- :mod:`~deeplearning4j_tpu.resilience.checkpoint` — atomic
  tmp+fsync+replace checkpoint publication, CRC32 manifests,
  :func:`~deeplearning4j_tpu.resilience.checkpoint.verify_checkpoint`,
  quarantine of corrupt files to ``corrupt/``, newest-*valid* fallback.
- :mod:`~deeplearning4j_tpu.resilience.policy` — error classification
  (transient vs deterministic), :class:`RetryPolicy` exponential
  backoff with seeded jitter, SIGTERM :class:`PreemptionHandler` for
  checkpoint-and-exit-cleanly.
- :mod:`~deeplearning4j_tpu.resilience.elastic` — the fleet-level
  layer (ARCHITECTURE.md §13): membership coordinator with
  generation-numbered mesh epochs (lease files +
  ``DL4J_TPU_HOST_LEASE_SECS``), bounded-timeout collectives so the
  peers of a dead host raise instead of hanging, exec-based mesh
  re-formation at the surviving world size, and reshard-on-restore
  through ``ShardedCheckpointer``/``FlatShardLayout``.

Consumers: ``ModelSerializer``/``ShardedCheckpointer``
(``serialization.py``), ``FaultTolerantTrainer``
(``train/fault_tolerance.py``), ``ParallelWrapper`` elastic hooks
(``parallel/wrapper.py``), ``ParallelInference`` load-shedding
(``parallel/inference.py``), and ``tools/chaos.py``.
"""
from deeplearning4j_tpu.resilience import checkpoint as checkpoint
from deeplearning4j_tpu.resilience import faults as faults
from deeplearning4j_tpu.resilience import policy as policy
from deeplearning4j_tpu.resilience import elastic as elastic
from deeplearning4j_tpu.resilience.checkpoint import (
    newest_valid_checkpoint, quarantine, verify_checkpoint,
    write_manifest)
from deeplearning4j_tpu.resilience.faults import (FaultPlan, FaultRule,
                                                  InjectedFault,
                                                  NAMED_PLANS)
from deeplearning4j_tpu.resilience.policy import (Preempted,
                                                  PreemptionHandler,
                                                  RetryPolicy, classify)

__all__ = [
    "checkpoint", "elastic", "faults", "policy",
    "newest_valid_checkpoint", "quarantine", "verify_checkpoint",
    "write_manifest", "FaultPlan", "FaultRule", "InjectedFault",
    "NAMED_PLANS", "Preempted", "PreemptionHandler", "RetryPolicy",
    "classify",
]
