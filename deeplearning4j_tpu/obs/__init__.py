"""Telemetry spine — spans, metrics, worker health, one merged report.

Replaces the three ad-hoc timing mechanisms that grew around the stack
(``train/stats.py`` wall clocks, ``utils/profiler.py`` sections,
per-tool private formats) with one layer (ARCHITECTURE.md §9):

- :mod:`~deeplearning4j_tpu.obs.trace` — process-wide span tracer
  writing Chrome-trace/Perfetto JSONL (``DL4J_TPU_TRACE``); nesting,
  explicit t0/t1, thread/worker ids, bounded ring; the off path is one
  branch.
- :mod:`~deeplearning4j_tpu.obs.metrics` — counters/gauges/histograms
  with Prometheus text exposition on a stdlib ``/metrics`` +
  ``/healthz`` endpoint; the retrace sentry and persistent compile
  cache join as pull-time collector families.
- :mod:`~deeplearning4j_tpu.obs.health` — worker heartbeats + stale
  detection.
- :mod:`~deeplearning4j_tpu.obs.numerics` — in-step per-layer
  gradient/activation health with NaN attribution (cadence-gated
  diagnostic steps; ARCHITECTURE.md §11).
- :mod:`~deeplearning4j_tpu.obs.fleet` — cross-host telemetry
  aggregation, collective-skew straggler attribution, and the crash
  flight recorder riding the elastic file plane (ARCHITECTURE.md
  §14).
- :mod:`~deeplearning4j_tpu.obs.devtime` — per-layer DEVICE-time
  attribution: short ``jax.profiler.trace`` windows joined with the
  ``named_scope``-annotated programs' HLO into per-scope device-time
  totals, roofline utilization, and the Pallas-gap report
  (ARCHITECTURE.md §16).
- :mod:`~deeplearning4j_tpu.obs.commtime` — the comm sibling: a
  static per-collective wire ledger for any compiled program plus
  per-scope collective device time and interconnect-roofline
  utilization from the same capture pipeline (ARCHITECTURE.md §19).
- :func:`report` — the merged JSON snapshot consumed by
  ``StatsListener`` records, ``bench.py``'s ``obs`` section,
  ``tools/perf_dossier.py``, and ``utils/crashreport.py``.

Hot-path contract: instrumented loops call :func:`record_step` /
:func:`record_etl` with explicit :func:`now` timestamps — metrics are
always on (a few dict lookups + float adds per step), spans cost one
branch when tracing is off (asserted by ``tests/test_obs.py`` and
measured as the ``obs`` section of ``bench.py``).
"""
from __future__ import annotations

from typing import Any, Dict, Optional

from deeplearning4j_tpu.obs import devtime as devtime
from deeplearning4j_tpu.obs import commtime as commtime
from deeplearning4j_tpu.obs import health as health
from deeplearning4j_tpu.obs import metrics as metrics
from deeplearning4j_tpu.obs import numerics as numerics
from deeplearning4j_tpu.obs import trace as trace
from deeplearning4j_tpu.obs import fleet as fleet
from deeplearning4j_tpu.obs.trace import now as now, span as span


def record_step(entry: str, t0: float, t1: float, t2: float,
                t3: float, args: Optional[Dict[str, Any]] = None
                ) -> None:
    """One completed train/serve step with phase attribution:
    ``t0→t1`` host→device feed, ``t1→t2`` dispatch (async on TPU),
    ``t2→t3`` blocking device sync. Metrics always; spans when
    tracing."""
    metrics.observe_step(entry, t3 - t0, t1 - t0, t3 - t2)
    if trace.enabled():
        trace.add_span(entry + "/step", t0, t3, args)
        trace.add_span(entry + "/h2d", t0, t1)
        trace.add_span(entry + "/dispatch", t1, t2)
        trace.add_span(entry + "/sync", t2, t3)


def record_etl(entry: str, t0: float, t1: float) -> None:
    """Fit-loop wait on its data iterator."""
    metrics.FIT_ETL_SECONDS.labels(entry=entry).inc(t1 - t0)
    if trace.enabled():
        trace.add_span(entry + "/etl", t0, t1)


def record_worker_step(worker: str, t0: float, t1: float, t2: float,
                       t3: float) -> None:
    """ParallelWrapper worker loop: per-worker latency histogram,
    collective-sync wall time, liveness heartbeat, spans."""
    metrics.WORKER_STEP.labels(worker=worker).observe(t3 - t0)
    metrics.WORKER_SYNC.labels(worker=worker).inc(t3 - t2)
    health.heartbeat(worker)
    if trace.enabled():
        w = {"worker": worker}
        trace.add_span("ParallelWrapper.fit/step", t0, t3, w)
        trace.add_span("ParallelWrapper.fit/h2d", t0, t1)
        trace.add_span("ParallelWrapper.fit/dispatch", t1, t2)
        trace.add_span("ParallelWrapper.fit/collective_sync", t2, t3)


def summary() -> Dict[str, Any]:
    """Compact per-interval view (embedded in every ``StatsListener``
    record — scalars only, never the full family dump)."""
    return {
        "tracing": trace.enabled(),
        "trace_events": trace.events_recorded(),
        "stale_workers": health.stale_workers(),
        "step": metrics.step_summary(),
    }


def report(spans: int = 20) -> Dict[str, Any]:
    """The merged telemetry snapshot: tracer state + last ``spans``
    ring events, every metric family (sentry/compile-cache collector
    families included), and worker health. Crash dumps call this with
    a larger ``spans`` so the last moments of a dying run survive."""
    return {
        "trace": {
            "enabled": trace.enabled(),
            "path": trace.trace_path(),
            "events_recorded": trace.events_recorded(),
        },
        "spans": trace.events(last=spans) if spans else [],
        "metrics": metrics.snapshot(),
        "health": health.check(),
    }


def overhead_report(step_seconds: Optional[float] = None,
                    iters: int = 2000) -> Dict[str, Any]:
    """Measure the tracing-OFF per-step cost of the instrumentation
    (the exact calls ``record_step``+``record_etl`` make on the off
    path) and express it as a fraction of ``step_seconds`` — the
    ``obs`` section of ``bench.py`` / the dossier. Restores the
    tracer's enabled state."""
    was_enabled = trace.enabled()
    # flip the gate only (file/ring untouched) so the off path is what
    # gets timed even mid-trace
    trace._enabled = False
    try:
        t0 = now()
        for _ in range(iters):
            a = now()
            record_step("obs_overhead_probe", a, a, a, now())
            b = now()
            record_etl("obs_overhead_probe", b, now())
        per_step = (now() - t0) / iters
    finally:
        trace._enabled = was_enabled
        # scrub the probe's synthetic samples — they measured the off
        # path but must not masquerade as real telemetry in /metrics,
        # step_summary(), or StatsListener records
        metrics.drop_entry("obs_overhead_probe")
    out: Dict[str, Any] = {
        "tracing": was_enabled,
        "off_path_cost_us": round(per_step * 1e6, 3),
    }
    if step_seconds:
        out["step_ms"] = round(step_seconds * 1e3, 3)
        out["overhead_pct_of_step"] = round(
            100.0 * per_step / step_seconds, 4)
    return out


# snapshot() convenience re-export used by reporters
def snapshot() -> Dict[str, Any]:
    return metrics.snapshot()


__all__ = ["trace", "metrics", "health", "numerics", "fleet",
           "devtime", "commtime", "span", "now", "record_step",
           "record_etl",
           "record_worker_step", "summary", "report",
           "overhead_report", "snapshot"]
