"""Numerics observatory — in-step gradient/activation health with
per-layer NaN attribution.

Reference: the DL4J Training UI's headline diagnostic is per-layer
training health (``StatsListener`` update:param ratios, gradient and
activation distributions — SURVEY §5), but the reference collects all
of it host-side AFTER the step: a second forward pass for activations,
a full previous-parameters copy for update deltas, and a NaN that
surfaces only as a scoreless iteration with zero attribution.

TPU-native redesign: the statistics are auxiliary outputs of the SAME
XLA program that computes the update. A cadence-gated *diagnostic
step* (a second ``sentry.jit``-wrapped compile of the net's update,
AOT-warmable like every other bucket — ``perf/warmup.py``) returns,
next to the new params, a ``diag`` pytree of per-layer scalars:

- gradient / update / parameter L2 norms (update:param ratio follows
  from two scalars on host),
- activation mean/std/absmax from the REAL training forward (no extra
  forward pass — ``_forward(stats_out=...)`` taps each layer's output
  as it is traced),
- per-layer non-finite counts for gradients and activations — the NaN
  sentinel: the first layer (forward order) with non-finite
  activations, or the last layer (backward order) with non-finite
  gradients, names the origin,
- optional fixed-bucket log2-scale histogram sketches (``HIST_BINS``
  buckets over ``2**HIST_LO .. 2**HIST_HI``) for gradients and
  updates,
- on the ``ParallelWrapper`` SPMD path, per-layer replica divergence
  (``pmax − pmin`` of the per-replica gradient norms), and — under
  the ZeRO sharded weight update — per-layer ``pmax − pmin`` of the
  per-replica POST-GATHER param norms (the lockstep fence: exactly 0
  while every replica reassembles identical params).

Only these scalars cross to host, and only at cadence. The off path
is one attribute check in the fit loop: with no monitor attached the
default compiled step is byte-identical and :func:`diag_dispatches` /
:func:`host_pulls` stay 0 for the process lifetime (the same
counter-asserted contract as the span tracer's and fault injector's
off paths).

A non-finite origin raises :class:`NonFiniteError` — a structured
``FloatingPointError`` carrying ``layer``/``kind``/``iteration`` that
``resilience.policy.classify`` routes as deterministic (one
restore-and-retry, then re-raise): "loss is NaN" becomes "layer
gpt.h3.attn gradients overflowed at iter 412, restored from iter 400".
"""
from __future__ import annotations

import math
import threading
from typing import Any, Dict, List, Optional, Tuple

from deeplearning4j_tpu.obs import metrics as _metrics
from deeplearning4j_tpu.obs import trace as _trace

#: log2-scale sketch geometry: HIST_BINS buckets of 2 exponents each
#: over |v| in [2**HIST_LO, 2**HIST_HI); zeros are excluded, out-of-
#: range magnitudes clamp into the edge buckets
HIST_BINS = 16
HIST_LO = -24.0
HIST_HI = 8.0
_HIST_STEP = (HIST_HI - HIST_LO) / HIST_BINS


class NonFiniteError(FloatingPointError):
    """Structured NaN/Inf sentinel. ``FloatingPointError`` + a
    "non-finite" message so ``resilience.policy.classify`` routes it
    deterministic (one restore, then re-raise) through both its type
    and message rules."""

    def __init__(self, message: Optional[str] = None, *,
                 layer: Optional[str] = None,
                 kind: Optional[str] = None,
                 iteration: Optional[int] = None):
        self.layer = layer
        self.kind = kind
        self.iteration = iteration
        if message is None:
            message = (f"non-finite {kind or 'values'} detected in "
                       f"layer {layer!r} at iteration {iteration}")
        super().__init__(message)


# -- metric families (scraped as dl4j_tpu_numerics_* on /metrics) ------------

GRAD_NORM = _metrics.REGISTRY.gauge(
    "dl4j_tpu_numerics_grad_norm",
    "per-layer gradient L2 norm at the last diagnostic step",
    ("layer",))
UPDATE_RATIO = _metrics.REGISTRY.gauge(
    "dl4j_tpu_numerics_update_ratio",
    "per-layer update:param norm ratio at the last diagnostic step",
    ("layer",))
ACT_ABSMAX = _metrics.REGISTRY.gauge(
    "dl4j_tpu_numerics_activation_absmax",
    "per-layer activation |max| from the training forward",
    ("layer",))
REPLICA_DIVERGENCE = _metrics.REGISTRY.gauge(
    "dl4j_tpu_numerics_replica_divergence",
    "per-layer max-min spread of per-replica gradient norms "
    "(ParallelWrapper SPMD path)", ("layer",))
PARAM_REPLICA_DIVERGENCE = _metrics.REGISTRY.gauge(
    "dl4j_tpu_numerics_param_replica_divergence",
    "per-layer max-min spread of per-replica PARAM norms after the "
    "ZeRO sharded-update all-gather — the lockstep invariant: "
    "exactly 0 while every replica reassembles identical params",
    ("layer",))
NONFINITE = _metrics.REGISTRY.counter(
    "dl4j_tpu_numerics_nonfinite_total",
    "non-finite origins pinpointed by the NaN sentinel",
    ("layer", "kind"))
DIAG_STEPS = _metrics.REGISTRY.counter(
    "dl4j_tpu_numerics_diag_steps_total",
    "diagnostic steps dispatched (cadence-gated)")

# -- off-path fence counters (tests assert both stay 0 with no monitor) ------

_lock = threading.Lock()
_counters = {"diag_dispatches": 0, "host_pulls": 0}


def diag_dispatches() -> int:
    """Diagnostic steps processed since the last reset — stays 0 for
    the whole process lifetime when no monitor is attached (the
    off-path zero-overhead assertion)."""
    return _counters["diag_dispatches"]


def host_pulls() -> int:
    """Device→host diag transfers — the scalars-only-at-cadence
    assertion anchor (one pull per diagnostic step, 0 otherwise)."""
    return _counters["host_pulls"]


def reset_counters() -> None:
    """Tests only."""
    with _lock:
        _counters["diag_dispatches"] = 0
        _counters["host_pulls"] = 0


# -- in-program stat builders (traced inside the diagnostic step) ------------

_TAP_FN = None


def _tap_barrier():
    """Lazy ``optimization_barrier`` wrapper (module keeps jax imports
    inside functions). The barrier pins the tapped tensor to the ONE
    buffer the real computation produced: without it XLA happily
    re-materialises the producer chain into the tap's consumer — on
    the CPU smoke LeNet it duplicated the pooling reduce-windows into
    every activation tap, which was most of the residual diag-on cost
    after the reduction fusion (measured +60 ms → +10 ms). The
    ``custom_jvp`` with a zero tangent exists because this jaxlib has
    no differentiation rule for the barrier primitive — diagnostics
    are never differentiated, so zero is exact."""
    global _TAP_FN
    if _TAP_FN is None:
        import jax
        import jax.numpy as jnp
        from jax import lax

        @jax.custom_jvp
        def tap(v):
            return lax.optimization_barrier(v)

        @tap.defjvp
        def _tap_jvp(primals, tangents):
            (v,) = primals
            return lax.optimization_barrier(v), jnp.zeros_like(v)

        _TAP_FN = tap
    return _TAP_FN


def fused_moments(v, barrier: bool = False):
    """``(Σx, Σx², max|x|, finite-count)`` of one tensor in ONE
    variadic ``lax.reduce`` — the fused-tap primitive of the ISSUE 15
    diag-cost work. The old form issued four separate XLA reductions
    over the masked tensor; XLA:CPU does not multi-output-fuse
    reductions, so every diagnostic tap re-walked the activation four
    to six times (measured 18.8 ms vs 1.25 ms for this form on a 4M-
    element f32 — most of the old ~17% diag-on overhead). A single
    variadic reduce walks the tensor once and the elementwise
    mask/square/abs fuse into the reduce loop on every backend.
    ``barrier=True`` (the mid-forward activation taps) additionally
    pins the tap to the buffer the real forward produced — see
    :func:`_tap_barrier`; leave it off for tensors that are already
    materialised program outputs/operands (grads, updates, params),
    where the barrier only costs scheduling freedom (measured +50 ms
    on the smoke LeNet's grad taps). ``stop_gradient`` keeps autodiff
    from asking the reduce for a JVP rule (diagnostics are never
    differentiated; without it linearize trips over the int operand's
    symbolic-zero tangent)."""
    import jax.numpy as jnp
    from jax import lax

    v = lax.stop_gradient(
        v if v.dtype == jnp.float32 else v.astype(jnp.float32))
    if barrier:
        v = _tap_barrier()(v)
    if v.ndim == 0:
        v = v.reshape(1)
    finite = jnp.isfinite(v)
    safe = jnp.where(finite, v, 0.0)

    def comp(acc, op):
        s1, s2, mx, c = acc
        a, b, m, f = op
        return (s1 + a, s2 + b, jnp.maximum(mx, m), c + f)

    return lax.reduce(
        (safe, jnp.square(safe), jnp.abs(safe),
         finite.astype(jnp.int32)),
        (jnp.float32(0), jnp.float32(0), jnp.float32(0), jnp.int32(0)),
        comp, tuple(range(v.ndim)))


def act_summary(x) -> Dict[str, Any]:
    """Scalar summary of one layer's activation tensor, traced inside
    the training forward: mean/std/absmax over the finite mask plus a
    non-finite count (the attribution signal — masking keeps the
    summary stats themselves finite even mid-divergence).

    ONE pass (ISSUE 15 tentpole b): all four stats come out of a
    single :func:`fused_moments` reduce, and the variance is assembled
    from the moments (E[x²] − E[x]², clamped ≥ 0) instead of a second
    full ``(x − mean)²`` walk. The moment form accumulates in f32 over
    a masked tensor; for |mean| ≫ std it loses the same low-order
    variance bits the one-pass BatchNorm trade (ARCHITECTURE §5)
    already accepts — these are diagnostics, the signal is orders of
    magnitude. The pre-fusion two-pass form is kept as
    :func:`act_summary_twopass` — the baseline the diag-cost
    regression fence beats."""
    import jax.numpy as jnp

    s1, s2, mx, n_ok = fused_moments(x, barrier=True)
    n = jnp.maximum(n_ok, 1)
    mean = s1 / n
    var = jnp.maximum(s2 / n - jnp.square(mean), 0.0)
    return {"mean": mean, "std": jnp.sqrt(var), "absmax": mx,
            "nonfinite": jnp.asarray(x.size, jnp.int32) - n_ok}


def act_summary_twopass(x) -> Dict[str, Any]:
    """The PR 4 two-pass form (shifted variance: a second full
    ``(x − mean)²`` walk over the activation). Kept ONLY as the
    measured baseline for the fused-tap regression fence
    (tests/test_fused_kernels.py) — production diag steps trace
    :func:`act_summary`."""
    import jax.numpy as jnp

    v = x.astype(jnp.float32)
    finite = jnp.isfinite(v)
    n_bad = jnp.asarray(v.size, jnp.int32) - jnp.sum(
        finite, dtype=jnp.int32)
    safe = jnp.where(finite, v, 0.0)
    n = jnp.maximum(jnp.sum(finite, dtype=jnp.int32), 1)
    mean = jnp.sum(safe) / n
    var = jnp.sum(jnp.where(finite, jnp.square(v - mean), 0.0)) / n
    return {"mean": mean, "std": jnp.sqrt(var),
            "absmax": jnp.max(jnp.abs(safe)), "nonfinite": n_bad}


def _zero_act_summary():
    import jax.numpy as jnp
    z = jnp.float32(0.0)
    return {"mean": z, "std": z, "absmax": z,
            "nonfinite": jnp.int32(0)}


def _flat_layer(leaves):
    """One layer's leaves as a single flat f32 vector (a concat is one
    cheap copy; the payoff is ONE reduce per layer instead of one per
    leaf — at smoke batch sizes the diag program's cost is its HLO op
    COUNT, ~3-6 µs of XLA:CPU thunk dispatch per op, not its bytes)."""
    import jax.numpy as jnp

    flat = [jnp.ravel(l).astype(jnp.float32) for l in leaves]
    return flat[0] if len(flat) == 1 else jnp.concatenate(flat)


def layer_summary(sub) -> Tuple[Any, Any, Any]:
    """(l2_norm, absmax, nonfinite_count) over one layer's leaves —
    norms over the finite mask (the count carries the NaN signal).
    ONE :func:`fused_moments` reduce over the layer's concatenated
    leaves (was four separate reductions per leaf — the same
    fused-tap trade as ``act_summary``; the concat reassociates the
    float sum across leaf boundaries, an at-most-ulps change in a
    diagnostic)."""
    import jax
    import jax.numpy as jnp

    leaves = jax.tree.leaves(sub)
    if not leaves:
        return jnp.float32(0.0), jnp.float32(0.0), jnp.int32(0)
    v = _flat_layer(leaves)
    _, s2, mx, n_ok = fused_moments(v)
    nf = jnp.asarray(v.size, jnp.int32) - n_ok
    return jnp.sqrt(s2), mx, nf


def layer_norm(sub):
    """Plain (unmasked) L2 norm over one layer's leaves — the cheap
    reduction for trees that don't need attribution counts (updates,
    post-update params): a non-finite leaf simply propagates into the
    norm, which is itself diagnostic. One reduce over the
    concatenated leaves (op-count trade, see :func:`_flat_layer`)."""
    import jax
    import jax.numpy as jnp

    leaves = jax.tree.leaves(sub)
    if not leaves:
        return jnp.float32(0.0)
    v = _flat_layer(leaves)
    return jnp.sqrt(jnp.sum(jnp.square(v)))


def log2_sketch(sub):
    """Fixed-bucket log2-magnitude histogram over one layer's leaves:
    ``HIST_BINS`` int32 counts, zeros excluded, magnitudes clamped to
    the edge buckets. Fixed buckets make sketches comparable across
    layers, steps, and runs (no data-dependent edges to recompute)."""
    import jax
    import jax.numpy as jnp

    counts = jnp.zeros((HIST_BINS,), jnp.int32)
    for leaf in jax.tree.leaves(sub):
        v = jnp.abs(leaf.astype(jnp.float32)).ravel()
        ok = jnp.isfinite(v) & (v > 0)
        e = jnp.log2(jnp.where(ok, v, 1.0))
        idx = jnp.clip(((e - HIST_LO) / _HIST_STEP).astype(jnp.int32),
                       0, HIST_BINS - 1)
        counts = counts + jnp.bincount(
            idx, weights=ok.astype(jnp.int32),
            length=HIST_BINS).astype(jnp.int32)
    return counts


def pack_diag(diag: Dict[str, Any]) -> Dict[str, Any]:
    """Concatenate the diag dict's arrays into ONE f32 and ONE i32
    vector, key names encoded in the packed dict's KEYS (static pytree
    structure, so nothing but the two buffers crosses to host).
    Shrinks the diag program's output surface and turns the per-step
    host pull from ~10 small transfers into 2 — at tiny smoke batches
    the per-transfer sync was a visible slice of the whole diag
    overhead. Inverse: :func:`unpack_diag`."""
    import jax.numpy as jnp

    f32_keys = sorted(k for k, v in diag.items()
                      if v.dtype != jnp.int32)
    i32_keys = sorted(k for k, v in diag.items()
                      if v.dtype == jnp.int32)
    out: Dict[str, Any] = {}
    if f32_keys:
        out["f32:" + ";".join(f32_keys)] = jnp.concatenate(
            [jnp.ravel(diag[k]).astype(jnp.float32)
             for k in f32_keys])
    if i32_keys:
        out["i32:" + ";".join(i32_keys)] = jnp.concatenate(
            [jnp.ravel(diag[k]) for k in i32_keys])
    return out


def unpack_diag(host: Dict[str, Any], n_layers: int) -> Dict[str, Any]:
    """Rebuild the per-key diag dict from :func:`pack_diag` output
    (host-side numpy). Every entry is ``[L]`` except the ``*_hist``
    sketches (``[L, HIST_BINS]``). Un-packed dicts pass through, so
    hand-built diag trees in tests keep working."""
    import numpy as np

    if not any(":" in k for k in host):
        return host
    out: Dict[str, Any] = {}
    for key, vec in host.items():
        if ":" not in key:
            out[key] = vec
            continue
        _, names = key.split(":", 1)
        vec = np.asarray(vec)
        off = 0
        for name in names.split(";"):
            n = (n_layers * HIST_BINS if name.endswith("_hist")
                 else n_layers)
            chunk = vec[off:off + n]
            off += n
            out[name] = (chunk.reshape(n_layers, HIST_BINS)
                         if name.endswith("_hist") else chunk)
    return out


def layer_norms_vector(tree, layers: List[str]):
    """Per-layer L2 norms stacked into one [L] vector (the shape the
    SPMD divergence pmax/pmin reduces over)."""
    import jax.numpy as jnp
    return jnp.stack([layer_summary(tree.get(l, {}))[0]
                      for l in layers])


def build_diag(params, grads, updates, act_stats,
               layers: List[str], histograms: bool = False
               ) -> Dict[str, Any]:
    """Assemble the diagnostic aux pytree — stacked [L] scalar vectors
    (plus [L, HIST_BINS] sketches when requested), traced inside the
    diagnostic step so the whole thing is aux outputs of the one XLA
    program. ``params`` are the POST-update params (the ratio's
    denominator, matching the reference's current-param semantics)."""
    import jax.numpy as jnp

    g = [layer_summary(grads.get(l, {})) for l in layers]
    a = [act_stats.get(l) or _zero_act_summary() for l in layers]
    diag: Dict[str, Any] = {
        "grad_norm": jnp.stack([t[0] for t in g]),
        "grad_absmax": jnp.stack([t[1] for t in g]),
        "grad_nonfinite": jnp.stack([t[2] for t in g]),
        "update_norm": jnp.stack(
            [layer_norm(updates.get(l, {})) for l in layers]),
        "param_norm": jnp.stack(
            [layer_norm(params.get(l, {})) for l in layers]),
        "act_mean": jnp.stack([s["mean"] for s in a]),
        "act_std": jnp.stack([s["std"] for s in a]),
        "act_absmax": jnp.stack([s["absmax"] for s in a]),
        "act_nonfinite": jnp.stack([s["nonfinite"] for s in a]),
    }
    if histograms:
        diag["grad_hist"] = jnp.stack(
            [log2_sketch(grads.get(l, {})) for l in layers])
        diag["update_hist"] = jnp.stack(
            [log2_sketch(updates.get(l, {})) for l in layers])
    return diag


def reduce_act_stats(act_stats, axis_name: str):
    """Cross-replica reduction of per-layer activation summaries on
    the SPMD path: means/stds pmean, absmax pmax, non-finite counts
    psum (a NaN on ANY replica must attribute)."""
    import jax

    out = {}
    for name, s in act_stats.items():
        out[name] = {
            "mean": jax.lax.pmean(s["mean"], axis_name),
            "std": jax.lax.pmean(s["std"], axis_name),
            "absmax": jax.lax.pmax(s["absmax"], axis_name),
            "nonfinite": jax.lax.psum(s["nonfinite"], axis_name),
        }
    return out


# -- host-side helpers -------------------------------------------------------

_TREE_NORMS_FN = None


def tree_norms(tree) -> Dict[str, float]:
    """Per-layer L2 norms of a params-like tree in ONE jitted fused
    reduction — the sanctioned replacement for listener-side
    per-layer ``jnp`` loops (``tools/lint_instrumentation.py`` flags
    those in listener/stats paths; this module is the allowlisted
    home). One device→host transfer of L scalars per call."""
    global _TREE_NORMS_FN
    import jax

    if _TREE_NORMS_FN is None:
        def impl(t):
            return {name: layer_summary(sub)[0]
                    for name, sub in t.items()}
        _TREE_NORMS_FN = jax.jit(impl)
    host = jax.device_get(_TREE_NORMS_FN(tree or {}))
    return {k: float(v) for k, v in host.items()}


def sketch_as_histogram(counts) -> Dict[str, Any]:
    """Render a log2 sketch in the dashboard's ``{counts, min, max}``
    histogram shape (bucket-range bounds as the edges)."""
    return {"counts": [int(c) for c in counts],
            "min": float(2.0 ** HIST_LO), "max": float(2.0 ** HIST_HI),
            "log2": True}


def first_nonfinite(num: Dict[str, Any], layers: List[str]
                    ) -> Optional[Tuple[str, str]]:
    """Pinpoint the origin layer of a non-finite event from the
    per-layer counts. Forward activations propagate a NaN/Inf from
    its origin ONWARD, so the first layer (forward order) with
    non-finite activations is the origin; backward gradients
    propagate it toward EARLIER layers, so absent an activation
    signal the origin is the last layer (forward order) with
    non-finite gradients."""
    act = num.get("act_nonfinite") or {}
    for l in layers:
        if act.get(l, 0) > 0:
            return l, "activations"
    grad = num.get("grad_nonfinite") or {}
    hits = [l for l in layers if grad.get(l, 0) > 0]
    if hits:
        return hits[-1], "gradients"
    return None


def measure_diag_overhead(net, p, o, s, feed, rng, k: int = 10,
                          rounds: int = 3) -> Dict[str, Any]:
    """Time plain steps vs diagnostic steps (cadence=1, per-step loss
    sync, scalars-only diag pull) on a live (params, opt_state, state)
    tree — the shared harness behind ``bench.py``'s ``numerics``
    section and the dossier's ``numerics_observatory`` entry. ``feed``
    is the net's step feed after (p, o, s): e.g. ``(x, y, None,
    None)`` for a MultiLayerNetwork, ``({name: x}, [y], {}, {})`` for
    a ComputationGraph. Attaches a non-raising monitor when none is
    present; consumes/returns nothing from the passed trees (donated
    buffers are replaced step over step).

    Protocol: the two arms run as INTERLEAVED ``k``-step bursts and
    each arm reports its median burst (the ``_timeit`` rationale from
    ``tools/perf_dossier.py``, applied to an A/B: on a shared CI box
    the machine's throughput drifts ±10% over the tens of seconds one
    arm takes, so timing arm A then arm B folds that drift straight
    into the overhead column — the round-5 ~17% reading carried more
    box drift than diagnostics; interleaving samples both arms under
    the same drift)."""
    import jax

    if getattr(net, "_numerics", None) is None:
        net.monitor_numerics(every=1, raise_on_nonfinite=False)
    plain = net._make_train_step()
    diag = net._make_diag_step()

    def burst(step, with_diag, n):
        nonlocal p, o, s
        t0 = _trace.now()
        for _ in range(n):
            out = step(p, o, s, *feed, rng)
            p, o, s = out[0], out[1], out[2]
            float(out[3])                  # per-step loss sync
            if with_diag:
                jax.device_get(out[4])     # the scalars-only pull
        return (_trace.now() - t0) / n

    burst(plain, False, 1)                 # compile + warm both arms
    burst(diag, True, 1)
    offs, ons = [], []
    for _ in range(max(1, rounds)):
        offs.append(burst(plain, False, k))
        ons.append(burst(diag, True, k))
    t_off = sorted(offs)[len(offs) // 2]
    t_on = sorted(ons)[len(ons) // 2]
    return {
        "step_ms_off": round(t_off * 1e3, 3),
        "step_ms_on": round(t_on * 1e3, 3),
        "overhead_pct": round(100.0 * (t_on - t_off) / t_off, 2)
        if t_off > 0 else None,
    }


class NumericsMonitor:
    """Cadence config + host-side processing for a network's
    diagnostic steps. Attach with ``net.monitor_numerics(...)``; the
    fit loops consult :meth:`due` per iteration (one attribute check
    plus a modulo when attached, one ``is None`` check otherwise).

    ``due`` fires when the POST-step iteration lands on the cadence
    (``(iteration + 1) % every == 0``) so a diagnostic record aligns
    with ``StatsListener``'s ``iteration % frequency == 0`` records,
    and unconditionally on the step after a non-finite score
    (:meth:`note_score` escalation — attribution arrives one step
    after a NaN even at a sparse cadence)."""

    def __init__(self, every: int = 1, histograms: bool = False,
                 raise_on_nonfinite: bool = True):
        self.every = max(1, int(every))
        self.histograms = bool(histograms)
        self.raise_on_nonfinite = bool(raise_on_nonfinite)
        self.force = False
        self._warned_group_split = False

    def due(self, iteration: int) -> bool:
        return self.force or ((iteration + 1) % self.every == 0)

    def note_score(self, score: float) -> None:
        """Called by the fit loops after NON-diagnostic steps: a
        non-finite loss escalates the next step to a diagnostic one."""
        if not math.isfinite(score):
            self.force = True

    def note_group_split(self, group_len: int) -> None:
        """Called when a diagnostic-due iteration forces a scanned
        ``steps_per_loop`` group to run per-batch — warn ONCE so the
        trade (per-step diagnostics vs scan amortization) is visible;
        raise ``every`` above ``steps_per_loop`` to keep most groups
        scanned."""
        if self._warned_group_split:
            return
        self._warned_group_split = True
        import logging
        logging.getLogger("deeplearning4j_tpu").warning(
            "numerics observatory: diagnostic cadence (every=%d) falls "
            "inside a steps_per_loop=%d group — such groups run "
            "per-batch instead of as one scanned executable. Use a "
            "cadence larger than steps_per_loop (or detach the "
            "monitor) to keep the device loop.", self.every, group_len)

    def process(self, net, diag, layers: List[str], *,
                entry: str = "net") -> Dict[str, Any]:
        """Pull the diag scalars (ONE device→host transfer), publish
        them (``net.last_numerics``, metric gauges, Perfetto counter
        tracks), and raise :class:`NonFiniteError` naming the origin
        layer when the sentinel fired."""
        import jax
        import numpy as np

        t0 = _trace.now()
        host = unpack_diag(jax.device_get(diag), len(layers))
        with _lock:
            _counters["diag_dispatches"] += 1
            _counters["host_pulls"] += 1
        DIAG_STEPS.inc()
        it = net.iteration

        def per_layer(key, cast=float):
            return {l: cast(host[key][i]) for i, l in enumerate(layers)}

        num: Dict[str, Any] = {
            "iteration": it, "entry": entry,
            "grad_norm": per_layer("grad_norm"),
            "grad_absmax": per_layer("grad_absmax"),
            "grad_nonfinite": per_layer("grad_nonfinite", int),
            "update_norm": per_layer("update_norm"),
            "param_norm": per_layer("param_norm"),
            "act_mean": per_layer("act_mean"),
            "act_std": per_layer("act_std"),
            "act_absmax": per_layer("act_absmax"),
            "act_nonfinite": per_layer("act_nonfinite", int),
        }
        num["update_ratio"] = {
            l: (num["update_norm"][l] / num["param_norm"][l]
                if math.isfinite(num["param_norm"][l])
                and math.isfinite(num["update_norm"][l])
                and num["param_norm"][l] > 0 else 0.0)
            for l in layers}
        for dkey in ("replica_divergence", "param_replica_divergence"):
            if dkey in host:
                num[dkey] = {l: float(host[dkey][i])
                             for i, l in enumerate(layers)}
        for key in ("grad_hist", "update_hist"):
            if key in host:
                num[key] = {l: np.asarray(host[key][i]).tolist()
                            for i, l in enumerate(layers)}
        net.last_numerics = num

        for l in layers:
            GRAD_NORM.labels(layer=l).set(num["grad_norm"][l])
            UPDATE_RATIO.labels(layer=l).set(num["update_ratio"][l])
            ACT_ABSMAX.labels(layer=l).set(num["act_absmax"][l])
        if "replica_divergence" in num:
            for l in layers:
                REPLICA_DIVERGENCE.labels(layer=l).set(
                    num["replica_divergence"][l])
        if "param_replica_divergence" in num:
            for l in layers:
                PARAM_REPLICA_DIVERGENCE.labels(layer=l).set(
                    num["param_replica_divergence"][l])
        if _trace.enabled():
            _trace.counter("numerics/grad_norm", num["grad_norm"])
            _trace.counter("numerics/update_ratio",
                           num["update_ratio"])
            if "replica_divergence" in num:
                _trace.counter("numerics/replica_divergence",
                               num["replica_divergence"])
            _trace.add_span("numerics/process", t0, _trace.now(),
                            args={"iteration": it})

        self.force = False
        origin = first_nonfinite(num, layers)
        if origin is not None:
            layer, kind = origin
            num["nonfinite"] = {"layer": layer, "kind": kind}
            NONFINITE.labels(layer=layer, kind=kind).inc()
            if self.raise_on_nonfinite:
                raise NonFiniteError(layer=layer, kind=kind,
                                     iteration=it)
        return num


__all__ = ["NonFiniteError", "NumericsMonitor", "act_summary",
           "act_summary_twopass",
           "layer_summary", "log2_sketch", "layer_norms_vector",
           "build_diag", "reduce_act_stats", "tree_norms",
           "sketch_as_histogram", "first_nonfinite",
           "diag_dispatches", "host_pulls", "reset_counters",
           "HIST_BINS", "HIST_LO", "HIST_HI"]
