"""Numerics observatory — in-step gradient/activation health with
per-layer NaN attribution.

Reference: the DL4J Training UI's headline diagnostic is per-layer
training health (``StatsListener`` update:param ratios, gradient and
activation distributions — SURVEY §5), but the reference collects all
of it host-side AFTER the step: a second forward pass for activations,
a full previous-parameters copy for update deltas, and a NaN that
surfaces only as a scoreless iteration with zero attribution.

TPU-native redesign: the statistics are auxiliary outputs of the SAME
XLA program that computes the update. A cadence-gated *diagnostic
step* (a second ``sentry.jit``-wrapped compile of the net's update,
AOT-warmable like every other bucket — ``perf/warmup.py``) returns,
next to the new params, a ``diag`` pytree of per-layer scalars:

- gradient / update / parameter L2 norms (update:param ratio follows
  from two scalars on host),
- activation mean/std/absmax from the REAL training forward (no extra
  forward pass — ``_forward(stats_out=...)`` taps each layer's output
  as it is traced),
- per-layer non-finite counts for gradients and activations — the NaN
  sentinel: the first layer (forward order) with non-finite
  activations, or the last layer (backward order) with non-finite
  gradients, names the origin,
- optional fixed-bucket log2-scale histogram sketches (``HIST_BINS``
  buckets over ``2**HIST_LO .. 2**HIST_HI``) for gradients and
  updates,
- on the ``ParallelWrapper`` SPMD path, per-layer replica divergence
  (``pmax − pmin`` of the per-replica gradient norms), and — under
  the ZeRO sharded weight update — per-layer ``pmax − pmin`` of the
  per-replica POST-GATHER param norms (the lockstep fence: exactly 0
  while every replica reassembles identical params).

Only these scalars cross to host, and only at cadence. The off path
is one attribute check in the fit loop: with no monitor attached the
default compiled step is byte-identical and :func:`diag_dispatches` /
:func:`host_pulls` stay 0 for the process lifetime (the same
counter-asserted contract as the span tracer's and fault injector's
off paths).

A non-finite origin raises :class:`NonFiniteError` — a structured
``FloatingPointError`` carrying ``layer``/``kind``/``iteration`` that
``resilience.policy.classify`` routes as deterministic (one
restore-and-retry, then re-raise): "loss is NaN" becomes "layer
gpt.h3.attn gradients overflowed at iter 412, restored from iter 400".
"""
from __future__ import annotations

import math
import threading
from typing import Any, Dict, List, Optional, Tuple

from deeplearning4j_tpu.obs import metrics as _metrics
from deeplearning4j_tpu.obs import trace as _trace

#: log2-scale sketch geometry: HIST_BINS buckets of 2 exponents each
#: over |v| in [2**HIST_LO, 2**HIST_HI); zeros are excluded, out-of-
#: range magnitudes clamp into the edge buckets
HIST_BINS = 16
HIST_LO = -24.0
HIST_HI = 8.0
_HIST_STEP = (HIST_HI - HIST_LO) / HIST_BINS


class NonFiniteError(FloatingPointError):
    """Structured NaN/Inf sentinel. ``FloatingPointError`` + a
    "non-finite" message so ``resilience.policy.classify`` routes it
    deterministic (one restore, then re-raise) through both its type
    and message rules."""

    def __init__(self, message: Optional[str] = None, *,
                 layer: Optional[str] = None,
                 kind: Optional[str] = None,
                 iteration: Optional[int] = None):
        self.layer = layer
        self.kind = kind
        self.iteration = iteration
        if message is None:
            message = (f"non-finite {kind or 'values'} detected in "
                       f"layer {layer!r} at iteration {iteration}")
        super().__init__(message)


# -- metric families (scraped as dl4j_tpu_numerics_* on /metrics) ------------

GRAD_NORM = _metrics.REGISTRY.gauge(
    "dl4j_tpu_numerics_grad_norm",
    "per-layer gradient L2 norm at the last diagnostic step",
    ("layer",))
UPDATE_RATIO = _metrics.REGISTRY.gauge(
    "dl4j_tpu_numerics_update_ratio",
    "per-layer update:param norm ratio at the last diagnostic step",
    ("layer",))
ACT_ABSMAX = _metrics.REGISTRY.gauge(
    "dl4j_tpu_numerics_activation_absmax",
    "per-layer activation |max| from the training forward",
    ("layer",))
REPLICA_DIVERGENCE = _metrics.REGISTRY.gauge(
    "dl4j_tpu_numerics_replica_divergence",
    "per-layer max-min spread of per-replica gradient norms "
    "(ParallelWrapper SPMD path)", ("layer",))
PARAM_REPLICA_DIVERGENCE = _metrics.REGISTRY.gauge(
    "dl4j_tpu_numerics_param_replica_divergence",
    "per-layer max-min spread of per-replica PARAM norms after the "
    "ZeRO sharded-update all-gather — the lockstep invariant: "
    "exactly 0 while every replica reassembles identical params",
    ("layer",))
NONFINITE = _metrics.REGISTRY.counter(
    "dl4j_tpu_numerics_nonfinite_total",
    "non-finite origins pinpointed by the NaN sentinel",
    ("layer", "kind"))
DIAG_STEPS = _metrics.REGISTRY.counter(
    "dl4j_tpu_numerics_diag_steps_total",
    "diagnostic steps dispatched (cadence-gated)")

# -- off-path fence counters (tests assert both stay 0 with no monitor) ------

_lock = threading.Lock()
_counters = {"diag_dispatches": 0, "host_pulls": 0}


def diag_dispatches() -> int:
    """Diagnostic steps processed since the last reset — stays 0 for
    the whole process lifetime when no monitor is attached (the
    off-path zero-overhead assertion)."""
    return _counters["diag_dispatches"]


def host_pulls() -> int:
    """Device→host diag transfers — the scalars-only-at-cadence
    assertion anchor (one pull per diagnostic step, 0 otherwise)."""
    return _counters["host_pulls"]


def reset_counters() -> None:
    """Tests only."""
    with _lock:
        _counters["diag_dispatches"] = 0
        _counters["host_pulls"] = 0


# -- in-program stat builders (traced inside the diagnostic step) ------------

def act_summary(x) -> Dict[str, Any]:
    """Scalar summary of one layer's activation tensor, traced inside
    the training forward: mean/std/absmax over the finite mask plus a
    non-finite count (the attribution signal — masking keeps the
    summary stats themselves finite even mid-divergence)."""
    import jax.numpy as jnp

    v = x.astype(jnp.float32)
    finite = jnp.isfinite(v)
    n_bad = jnp.asarray(v.size, jnp.int32) - jnp.sum(
        finite, dtype=jnp.int32)
    safe = jnp.where(finite, v, 0.0)
    n = jnp.maximum(jnp.sum(finite, dtype=jnp.int32), 1)
    mean = jnp.sum(safe) / n
    var = jnp.sum(jnp.where(finite, jnp.square(v - mean), 0.0)) / n
    return {"mean": mean, "std": jnp.sqrt(var),
            "absmax": jnp.max(jnp.abs(safe)), "nonfinite": n_bad}


def _zero_act_summary():
    import jax.numpy as jnp
    z = jnp.float32(0.0)
    return {"mean": z, "std": z, "absmax": z,
            "nonfinite": jnp.int32(0)}


def layer_summary(sub) -> Tuple[Any, Any, Any]:
    """(l2_norm, absmax, nonfinite_count) over one layer's leaves —
    norms over the finite mask (the count carries the NaN signal)."""
    import jax
    import jax.numpy as jnp

    leaves = jax.tree.leaves(sub)
    if not leaves:
        return jnp.float32(0.0), jnp.float32(0.0), jnp.int32(0)
    sq = jnp.float32(0.0)
    am = jnp.float32(0.0)
    nf = jnp.int32(0)
    for leaf in leaves:
        v = leaf.astype(jnp.float32)
        finite = jnp.isfinite(v)
        nf = nf + jnp.asarray(v.size, jnp.int32) - jnp.sum(
            finite, dtype=jnp.int32)
        safe = jnp.where(finite, v, 0.0)
        sq = sq + jnp.sum(jnp.square(safe))
        am = jnp.maximum(am, jnp.max(jnp.abs(safe)))
    return jnp.sqrt(sq), am, nf


def layer_norm(sub):
    """Plain (unmasked) L2 norm over one layer's leaves — the cheap
    reduction for trees that don't need attribution counts (updates,
    post-update params): a non-finite leaf simply propagates into the
    norm, which is itself diagnostic."""
    import jax
    import jax.numpy as jnp

    leaves = jax.tree.leaves(sub)
    if not leaves:
        return jnp.float32(0.0)
    sq = jnp.float32(0.0)
    for leaf in leaves:
        sq = sq + jnp.sum(jnp.square(leaf.astype(jnp.float32)))
    return jnp.sqrt(sq)


def log2_sketch(sub):
    """Fixed-bucket log2-magnitude histogram over one layer's leaves:
    ``HIST_BINS`` int32 counts, zeros excluded, magnitudes clamped to
    the edge buckets. Fixed buckets make sketches comparable across
    layers, steps, and runs (no data-dependent edges to recompute)."""
    import jax
    import jax.numpy as jnp

    counts = jnp.zeros((HIST_BINS,), jnp.int32)
    for leaf in jax.tree.leaves(sub):
        v = jnp.abs(leaf.astype(jnp.float32)).ravel()
        ok = jnp.isfinite(v) & (v > 0)
        e = jnp.log2(jnp.where(ok, v, 1.0))
        idx = jnp.clip(((e - HIST_LO) / _HIST_STEP).astype(jnp.int32),
                       0, HIST_BINS - 1)
        counts = counts + jnp.bincount(
            idx, weights=ok.astype(jnp.int32),
            length=HIST_BINS).astype(jnp.int32)
    return counts


def layer_norms_vector(tree, layers: List[str]):
    """Per-layer L2 norms stacked into one [L] vector (the shape the
    SPMD divergence pmax/pmin reduces over)."""
    import jax.numpy as jnp
    return jnp.stack([layer_summary(tree.get(l, {}))[0]
                      for l in layers])


def build_diag(params, grads, updates, act_stats,
               layers: List[str], histograms: bool = False
               ) -> Dict[str, Any]:
    """Assemble the diagnostic aux pytree — stacked [L] scalar vectors
    (plus [L, HIST_BINS] sketches when requested), traced inside the
    diagnostic step so the whole thing is aux outputs of the one XLA
    program. ``params`` are the POST-update params (the ratio's
    denominator, matching the reference's current-param semantics)."""
    import jax.numpy as jnp

    g = [layer_summary(grads.get(l, {})) for l in layers]
    a = [act_stats.get(l) or _zero_act_summary() for l in layers]
    diag: Dict[str, Any] = {
        "grad_norm": jnp.stack([t[0] for t in g]),
        "grad_absmax": jnp.stack([t[1] for t in g]),
        "grad_nonfinite": jnp.stack([t[2] for t in g]),
        "update_norm": jnp.stack(
            [layer_norm(updates.get(l, {})) for l in layers]),
        "param_norm": jnp.stack(
            [layer_norm(params.get(l, {})) for l in layers]),
        "act_mean": jnp.stack([s["mean"] for s in a]),
        "act_std": jnp.stack([s["std"] for s in a]),
        "act_absmax": jnp.stack([s["absmax"] for s in a]),
        "act_nonfinite": jnp.stack([s["nonfinite"] for s in a]),
    }
    if histograms:
        diag["grad_hist"] = jnp.stack(
            [log2_sketch(grads.get(l, {})) for l in layers])
        diag["update_hist"] = jnp.stack(
            [log2_sketch(updates.get(l, {})) for l in layers])
    return diag


def reduce_act_stats(act_stats, axis_name: str):
    """Cross-replica reduction of per-layer activation summaries on
    the SPMD path: means/stds pmean, absmax pmax, non-finite counts
    psum (a NaN on ANY replica must attribute)."""
    import jax

    out = {}
    for name, s in act_stats.items():
        out[name] = {
            "mean": jax.lax.pmean(s["mean"], axis_name),
            "std": jax.lax.pmean(s["std"], axis_name),
            "absmax": jax.lax.pmax(s["absmax"], axis_name),
            "nonfinite": jax.lax.psum(s["nonfinite"], axis_name),
        }
    return out


# -- host-side helpers -------------------------------------------------------

_TREE_NORMS_FN = None


def tree_norms(tree) -> Dict[str, float]:
    """Per-layer L2 norms of a params-like tree in ONE jitted fused
    reduction — the sanctioned replacement for listener-side
    per-layer ``jnp`` loops (``tools/lint_instrumentation.py`` flags
    those in listener/stats paths; this module is the allowlisted
    home). One device→host transfer of L scalars per call."""
    global _TREE_NORMS_FN
    import jax

    if _TREE_NORMS_FN is None:
        def impl(t):
            return {name: layer_summary(sub)[0]
                    for name, sub in t.items()}
        _TREE_NORMS_FN = jax.jit(impl)
    host = jax.device_get(_TREE_NORMS_FN(tree or {}))
    return {k: float(v) for k, v in host.items()}


def sketch_as_histogram(counts) -> Dict[str, Any]:
    """Render a log2 sketch in the dashboard's ``{counts, min, max}``
    histogram shape (bucket-range bounds as the edges)."""
    return {"counts": [int(c) for c in counts],
            "min": float(2.0 ** HIST_LO), "max": float(2.0 ** HIST_HI),
            "log2": True}


def first_nonfinite(num: Dict[str, Any], layers: List[str]
                    ) -> Optional[Tuple[str, str]]:
    """Pinpoint the origin layer of a non-finite event from the
    per-layer counts. Forward activations propagate a NaN/Inf from
    its origin ONWARD, so the first layer (forward order) with
    non-finite activations is the origin; backward gradients
    propagate it toward EARLIER layers, so absent an activation
    signal the origin is the last layer (forward order) with
    non-finite gradients."""
    act = num.get("act_nonfinite") or {}
    for l in layers:
        if act.get(l, 0) > 0:
            return l, "activations"
    grad = num.get("grad_nonfinite") or {}
    hits = [l for l in layers if grad.get(l, 0) > 0]
    if hits:
        return hits[-1], "gradients"
    return None


def measure_diag_overhead(net, p, o, s, feed, rng, k: int = 10
                          ) -> Dict[str, Any]:
    """Time ``k`` plain steps vs ``k`` diagnostic steps (cadence=1,
    per-step loss sync, scalars-only diag pull) on a live
    (params, opt_state, state) tree — the shared harness behind
    ``bench.py``'s ``numerics`` section and the dossier's
    ``numerics_observatory`` entry. ``feed`` is the net's step feed
    after (p, o, s): e.g. ``(x, y, None, None)`` for a
    MultiLayerNetwork, ``({name: x}, [y], {}, {})`` for a
    ComputationGraph. Attaches a non-raising monitor when none is
    present; consumes/returns nothing from the passed trees (donated
    buffers are replaced step over step)."""
    import jax

    if getattr(net, "_numerics", None) is None:
        net.monitor_numerics(every=1, raise_on_nonfinite=False)
    plain = net._make_train_step()
    diag = net._make_diag_step()

    def timed(step, with_diag):
        nonlocal p, o, s
        out = step(p, o, s, *feed, rng)          # compile + warm
        p, o, s = out[0], out[1], out[2]
        float(out[3])
        t0 = _trace.now()
        for _ in range(k):
            out = step(p, o, s, *feed, rng)
            p, o, s = out[0], out[1], out[2]
            float(out[3])                  # per-step loss sync
            if with_diag:
                jax.device_get(out[4])     # the scalars-only pull
        return (_trace.now() - t0) / k

    t_off = timed(plain, False)
    t_on = timed(diag, True)
    return {
        "step_ms_off": round(t_off * 1e3, 3),
        "step_ms_on": round(t_on * 1e3, 3),
        "overhead_pct": round(100.0 * (t_on - t_off) / t_off, 2)
        if t_off > 0 else None,
    }


class NumericsMonitor:
    """Cadence config + host-side processing for a network's
    diagnostic steps. Attach with ``net.monitor_numerics(...)``; the
    fit loops consult :meth:`due` per iteration (one attribute check
    plus a modulo when attached, one ``is None`` check otherwise).

    ``due`` fires when the POST-step iteration lands on the cadence
    (``(iteration + 1) % every == 0``) so a diagnostic record aligns
    with ``StatsListener``'s ``iteration % frequency == 0`` records,
    and unconditionally on the step after a non-finite score
    (:meth:`note_score` escalation — attribution arrives one step
    after a NaN even at a sparse cadence)."""

    def __init__(self, every: int = 1, histograms: bool = False,
                 raise_on_nonfinite: bool = True):
        self.every = max(1, int(every))
        self.histograms = bool(histograms)
        self.raise_on_nonfinite = bool(raise_on_nonfinite)
        self.force = False
        self._warned_group_split = False

    def due(self, iteration: int) -> bool:
        return self.force or ((iteration + 1) % self.every == 0)

    def note_score(self, score: float) -> None:
        """Called by the fit loops after NON-diagnostic steps: a
        non-finite loss escalates the next step to a diagnostic one."""
        if not math.isfinite(score):
            self.force = True

    def note_group_split(self, group_len: int) -> None:
        """Called when a diagnostic-due iteration forces a scanned
        ``steps_per_loop`` group to run per-batch — warn ONCE so the
        trade (per-step diagnostics vs scan amortization) is visible;
        raise ``every`` above ``steps_per_loop`` to keep most groups
        scanned."""
        if self._warned_group_split:
            return
        self._warned_group_split = True
        import logging
        logging.getLogger("deeplearning4j_tpu").warning(
            "numerics observatory: diagnostic cadence (every=%d) falls "
            "inside a steps_per_loop=%d group — such groups run "
            "per-batch instead of as one scanned executable. Use a "
            "cadence larger than steps_per_loop (or detach the "
            "monitor) to keep the device loop.", self.every, group_len)

    def process(self, net, diag, layers: List[str], *,
                entry: str = "net") -> Dict[str, Any]:
        """Pull the diag scalars (ONE device→host transfer), publish
        them (``net.last_numerics``, metric gauges, Perfetto counter
        tracks), and raise :class:`NonFiniteError` naming the origin
        layer when the sentinel fired."""
        import jax
        import numpy as np

        t0 = _trace.now()
        host = jax.device_get(diag)
        with _lock:
            _counters["diag_dispatches"] += 1
            _counters["host_pulls"] += 1
        DIAG_STEPS.inc()
        it = net.iteration

        def per_layer(key, cast=float):
            return {l: cast(host[key][i]) for i, l in enumerate(layers)}

        num: Dict[str, Any] = {
            "iteration": it, "entry": entry,
            "grad_norm": per_layer("grad_norm"),
            "grad_absmax": per_layer("grad_absmax"),
            "grad_nonfinite": per_layer("grad_nonfinite", int),
            "update_norm": per_layer("update_norm"),
            "param_norm": per_layer("param_norm"),
            "act_mean": per_layer("act_mean"),
            "act_std": per_layer("act_std"),
            "act_absmax": per_layer("act_absmax"),
            "act_nonfinite": per_layer("act_nonfinite", int),
        }
        num["update_ratio"] = {
            l: (num["update_norm"][l] / num["param_norm"][l]
                if math.isfinite(num["param_norm"][l])
                and math.isfinite(num["update_norm"][l])
                and num["param_norm"][l] > 0 else 0.0)
            for l in layers}
        for dkey in ("replica_divergence", "param_replica_divergence"):
            if dkey in host:
                num[dkey] = {l: float(host[dkey][i])
                             for i, l in enumerate(layers)}
        for key in ("grad_hist", "update_hist"):
            if key in host:
                num[key] = {l: np.asarray(host[key][i]).tolist()
                            for i, l in enumerate(layers)}
        net.last_numerics = num

        for l in layers:
            GRAD_NORM.labels(layer=l).set(num["grad_norm"][l])
            UPDATE_RATIO.labels(layer=l).set(num["update_ratio"][l])
            ACT_ABSMAX.labels(layer=l).set(num["act_absmax"][l])
        if "replica_divergence" in num:
            for l in layers:
                REPLICA_DIVERGENCE.labels(layer=l).set(
                    num["replica_divergence"][l])
        if "param_replica_divergence" in num:
            for l in layers:
                PARAM_REPLICA_DIVERGENCE.labels(layer=l).set(
                    num["param_replica_divergence"][l])
        if _trace.enabled():
            _trace.counter("numerics/grad_norm", num["grad_norm"])
            _trace.counter("numerics/update_ratio",
                           num["update_ratio"])
            if "replica_divergence" in num:
                _trace.counter("numerics/replica_divergence",
                               num["replica_divergence"])
            _trace.add_span("numerics/process", t0, _trace.now(),
                            args={"iteration": it})

        self.force = False
        origin = first_nonfinite(num, layers)
        if origin is not None:
            layer, kind = origin
            num["nonfinite"] = {"layer": layer, "kind": kind}
            NONFINITE.labels(layer=layer, kind=kind).inc()
            if self.raise_on_nonfinite:
                raise NonFiniteError(layer=layer, kind=kind,
                                     iteration=it)
        return num


__all__ = ["NonFiniteError", "NumericsMonitor", "act_summary",
           "layer_summary", "log2_sketch", "layer_norms_vector",
           "build_diag", "reduce_act_stats", "tree_norms",
           "sketch_as_histogram", "first_nonfinite",
           "diag_dispatches", "host_pulls", "reset_counters",
           "HIST_BINS", "HIST_LO", "HIST_HI"]
