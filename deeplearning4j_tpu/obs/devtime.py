"""Device-time observatory — per-layer *device* attribution + roofline.

The PR 2/4/7 spine measures host wall-clock: `obs.record_step` can say
a step took 46 ms, but on an asynchronously-dispatched backend it
cannot say which LAYER the device spent those milliseconds in — the
dispatch returns before the device runs, and XLA fuses the program
into op soup whose names (``fusion.7``, ``dot.5``) carry no model
structure. ROADMAP item "Pallas only where XLA has a gap" is blocked
on exactly that attribution: the cuDNN-primitives shape of the win
(PAPERS.md: arxiv 1410.0759) is a SMALL library of tuned kernels
chosen from measured hot spots, so the hot spots must first be
*named*. This module is the naming instrument:

1. **Scopes.** :func:`scope` wraps ``jax.named_scope`` with a
   recognizable ``dl4j.`` prefix. The fit forwards annotate every
   layer (``nn/multilayer.py``/``nn/graph.py`` ``_forward``), the
   hand-rolled zoo transformer annotates its blocks (``zoo/gpt.py``),
   the serving scheduler its paged decode blocks, and the ZeRO layout
   its collective phases (``parallel/zero.py``). ``named_scope`` is
   trace-time only — zero bytes and zero branches in the compiled
   step; jax carries the scope into the backward program as
   ``transpose(jvp(dl4j.<scope>))`` so gradients attribute too.

2. **Capture.** :func:`capture` (on demand) or the env-gated
   :class:`Observatory` (cadence, ``DL4J_TPU_DEVTIME``) runs a short
   ``jax.profiler.trace`` window around real steps and parses the
   resulting ``*.xplane.pb`` with a dependency-free protobuf
   wire-format reader (:func:`read_xspace` — ``jax.profiler
   .ProfileData`` does not exist on the pinned jaxlib, and the
   tensorboard plugin's proto module is absent from the wheel).
   XLA-op execution events carry ``hlo_op``/``hlo_module`` stats and
   picosecond durations — the device's own account of where time
   went; ``tools/xprof_summary.py`` reads captures through the same
   parser.

3. **Attribution.** The post-optimization HLO of the executed
   programs (``Compiled.as_text()`` — the retrace sentry keeps its
   AOT executables, :func:`sentry_executables`) maps each timed op
   name to its ``metadata={op_name="...dl4j.<scope>..."}`` scope;
   per-op FLOP/byte estimates parsed from the HLO shapes give each
   scope an achieved-vs-roofline utilization (:func:`roofline`,
   peaks from ``DL4J_TPU_PEAK_TFLOPS`` / ``DL4J_TPU_PEAK_HBM_GBS``),
   and ``Compiled.cost_analysis()`` program totals provide the
   per-module cross-check (the ``modules`` section: XLA's own
   FLOPs/bytes against measured device time, independent of the
   shape-regex estimates).

4. **Gap report.** :func:`gap_report` ranks scopes by device-time
   share with utilization, fusion count, and a ``pallas_candidate``
   flag — the structured answer to "which kernel should the Pallas
   library fill next". It lands in ``tools/perf_dossier.py``
   (``hot_path_gaps``), ``bench.py`` (``devtime``), the
   ``dl4j_tpu_devtime_*`` metric families, and the ``tpu_watch``
   devtime view. Every entry carries exactly :data:`GAP_KEYS` —
   ``tools/lint_instrumentation.py`` rule 8 keeps the keys OPS.md and
   tpu_watch reference resolvable against that tuple.

Off path: with ``DL4J_TPU_DEVTIME`` unset the fit-loop hooks
(:func:`step_started`/:func:`step_ended`) are one module-global
``is None`` branch — zero profiler sessions, zero captures, counter-
fenced by ``tests/test_devtime.py`` (the PR 2 contract).
"""
from __future__ import annotations

import math
import os
import re
import shutil
import struct
import tempfile
import threading
from pathlib import Path
from typing import Any, Dict, Iterable, List, Optional, Tuple

from deeplearning4j_tpu.obs import metrics as _metrics
from deeplearning4j_tpu.obs import trace as _trace

_TRUTHY = {"1", "true", "on", "yes"}

#: every scope emitted through :func:`scope` carries this prefix, so
#: attribution can find the innermost model scope anywhere in an
#: ``op_name`` path (``jit(f)/transpose(jvp(dl4j.layer_0.Dense))/...``)
SCOPE_PREFIX = "dl4j."

_SCOPE_RE = re.compile(r"dl4j\.([\w.:\-]+)")

_lock = threading.Lock()
_counters = {"captures": 0, "sessions": 0}

#: the env-gated cadence monitor (None = off: the one branch every
#: un-observed step pays in the fit loops)
_MONITOR: Optional["Observatory"] = None

#: the last completed capture's gap report (tools / obs.report tail)
_last_report: Optional[Dict[str, Any]] = None


def captures() -> int:
    """Completed capture-and-attribute pipelines since reset — with
    ``DL4J_TPU_DEVTIME`` unset and no explicit :func:`capture` call
    this stays 0 (the off-path fence)."""
    return _counters["captures"]


def profiler_sessions() -> int:
    """``jax.profiler`` sessions started by this module since reset."""
    return _counters["sessions"]


def reset_counters() -> None:
    global _last_report
    with _lock:
        _counters["captures"] = 0
        _counters["sessions"] = 0
    _last_report = None


def last_report() -> Optional[Dict[str, Any]]:
    return _last_report


# ---------------------------------------------------------------------------
# scope annotation (trace-time only — nothing survives into the step)
# ---------------------------------------------------------------------------

def scope(name: str):
    """``with devtime.scope("layer_0.DenseLayer"): ...`` around the
    layer math AS TRACED: the compiled program's ops carry the scope
    in their HLO metadata, the compiled step itself is byte-identical
    (metadata never feeds codegen). Use anywhere a device-time total
    should have a model-level name."""
    import jax
    return jax.named_scope(SCOPE_PREFIX + str(name))


# ---------------------------------------------------------------------------
# xplane.pb reader — protobuf wire format, no proto deps
# ---------------------------------------------------------------------------
# Field numbers from tsl/profiler/protobuf/xplane.proto (stable):
#   XSpace.planes=1; XPlane{id=1,name=2,lines=3,event_metadata=4(map),
#   stat_metadata=5(map),stats=6}; XLine{id=1,name=2,timestamp_ns=3,
#   events=4,duration_ps=9,display_name=11}; XEvent{metadata_id=1,
#   offset_ps=2,duration_ps=3,stats=4,timestamp_ns=7};
#   XStat{metadata_id=1,double=2,uint64=3,int64=4,str=5,bytes=6,ref=7};
#   XEventMetadata{id=1,name=2,display_name=4};
#   XStatMetadata{id=1,name=2}; map entry{key=1,value=2}.

def _varint(buf: bytes, i: int) -> Tuple[int, int]:
    out = shift = 0
    while True:
        b = buf[i]
        i += 1
        out |= (b & 0x7F) << shift
        if not b & 0x80:
            return out, i
        shift += 7


def _fields(buf: bytes):
    """Yield ``(field_no, wire_type, value)`` over one message body.
    Length-delimited values come back as the raw bytes slice."""
    i, end = 0, len(buf)
    while i < end:
        tag, i = _varint(buf, i)
        fno, wt = tag >> 3, tag & 7
        if wt == 0:
            v, i = _varint(buf, i)
        elif wt == 1:
            v = buf[i:i + 8]
            i += 8
        elif wt == 2:
            ln, i = _varint(buf, i)
            v = buf[i:i + ln]
            i += ln
        elif wt == 5:
            v = buf[i:i + 4]
            i += 4
        else:                       # group wire types never appear here
            raise ValueError(f"unsupported wire type {wt} in xplane.pb")
        yield fno, wt, v


def _map_entry(buf: bytes) -> Tuple[int, bytes]:
    key, val = 0, b""
    for fno, _wt, v in _fields(buf):
        if fno == 1:
            key = v
        elif fno == 2:
            val = v
    return key, val


def _stat(buf: bytes, stat_names: Dict[int, str]) -> Tuple[str, Any]:
    mid, val = 0, None
    for fno, wt, v in _fields(buf):
        if fno == 1:
            mid = v
        elif fno == 2:
            val = struct.unpack("<d", v)[0]
        elif fno in (3, 4):
            val = v
        elif fno == 5:
            val = v.decode("utf-8", "replace")
        elif fno == 6:
            val = v
        elif fno == 7:              # ref into stat_metadata names
            val = stat_names.get(v, str(v))
    return stat_names.get(mid, str(mid)), val


def read_xspace(path) -> Dict[str, Any]:
    """Parse one ``*.xplane.pb`` into plain dicts::

        {"planes": [{"name", "lines": [{"name", "timestamp_ns",
                     "events": [{"name", "dur_ps", "offset_ps",
                                 "stats": {...}}]}]}]}

    Event names and ref-valued stats are resolved through the plane's
    metadata tables."""
    buf = Path(path).read_bytes()
    planes = []
    for fno, _wt, pbuf in _fields(buf):
        if fno != 1:
            continue
        name = ""
        line_bufs: List[bytes] = []
        ev_names: Dict[int, str] = {}
        stat_names: Dict[int, str] = {}
        for pf, _pw, pv in _fields(pbuf):
            if pf == 2:
                name = pv.decode("utf-8", "replace")
            elif pf == 3:
                line_bufs.append(pv)
            elif pf == 4:
                k, v = _map_entry(pv)
                em_name = ""
                for ef, _ew, evv in _fields(v):
                    if ef == 2:
                        em_name = evv.decode("utf-8", "replace")
                ev_names[k] = em_name
            elif pf == 5:
                k, v = _map_entry(pv)
                sm_name = ""
                for sf, _sw, svv in _fields(v):
                    if sf == 2:
                        sm_name = svv.decode("utf-8", "replace")
                stat_names[k] = sm_name
        lines = []
        for lbuf in line_bufs:
            lname, ts_ns = "", 0
            events = []
            for lf, _lw, lv in _fields(lbuf):
                if lf == 2:
                    lname = lv.decode("utf-8", "replace")
                elif lf == 3:
                    ts_ns = lv
                elif lf == 11 and not lname:
                    lname = lv.decode("utf-8", "replace")
                elif lf == 4:
                    mid = off_ps = dur_ps = 0
                    stats: Dict[str, Any] = {}
                    for ef, _ew, ev in _fields(lv):
                        if ef == 1:
                            mid = ev
                        elif ef == 2:
                            off_ps = ev
                        elif ef == 3:
                            dur_ps = ev
                        elif ef == 4:
                            k, v = _stat(ev, stat_names)
                            stats[k] = v
                    events.append({"name": ev_names.get(mid, str(mid)),
                                   "offset_ps": off_ps,
                                   "dur_ps": dur_ps, "stats": stats})
            lines.append({"name": lname, "timestamp_ns": ts_ns,
                          "events": events})
        planes.append({"name": name, "lines": lines})
    return {"planes": planes}


def xplane_paths(path) -> List[str]:
    """Resolve a capture argument to the xplane file set: an explicit
    ``*.xplane.pb`` file is read alone; a directory resolves to EVERY
    plane file of the NEWEST capture session under it (one session dir
    holds one ``<host>.xplane.pb`` per host — merging them is what
    keeps a multi-host capture from silently dropping hosts)."""
    p = Path(path)
    if p.is_file():
        return [str(p)]
    planes = list(p.rglob("*.xplane.pb"))
    if not planes:
        raise FileNotFoundError(f"no *.xplane.pb under {path}")
    by_session: Dict[Path, List[Path]] = {}
    for q in planes:
        by_session.setdefault(q.parent, []).append(q)
    newest = max(by_session,
                 key=lambda d: max(q.stat().st_mtime
                                   for q in by_session[d]))
    return [str(q) for q in sorted(by_session[newest])]


def op_events(xspace: Dict[str, Any]) -> List[Dict[str, Any]]:
    """XLA-op *execution* events from one parsed xplane: device planes
    contribute their "XLA Ops" lines; the CPU thunk executor (this
    jaxlib's XLA:CPU) reports per-op events on host lines whose stats
    carry ``hlo_op``/``hlo_module``. Returns
    ``[{"op", "module", "dur_ns", "plane"}, ...]``."""
    out = []
    for plane in xspace["planes"]:
        device = "/device:" in plane["name"]
        for line in plane["lines"]:
            dev_line = device and line["name"] in ("XLA Ops",
                                                   "XLA Modules")
            if dev_line and line["name"] == "XLA Modules":
                continue            # per-op granularity only
            for e in line["events"]:
                mod = e["stats"].get("hlo_module")
                if not (dev_line or mod is not None):
                    continue
                op = e["stats"].get("hlo_op") or e["name"]
                if not e["dur_ps"]:
                    continue
                rec = {"op": str(op), "module": str(mod or ""),
                       "dur_ns": e["dur_ps"] / 1e3,
                       "plane": plane["name"]}
                # TPU device planes stamp the framework op path on the
                # event itself ("tf_op") — a scope source that needs
                # no compiled-HLO join at all
                tf_op = e["stats"].get("tf_op")
                if tf_op:
                    rec["op_name"] = str(tf_op)
                out.append(rec)
    return out


def step_durations_ns(xspace: Dict[str, Any]) -> List[float]:
    """Device "Steps" line durations (TPU captures; absent on CPU)."""
    out = []
    for plane in xspace["planes"]:
        if "/device:" not in plane["name"]:
            continue
        for line in plane["lines"]:
            if line["name"] == "Steps":
                out.extend(e["dur_ps"] / 1e3 for e in line["events"])
    return out


# ---------------------------------------------------------------------------
# HLO scope map + per-op cost estimates
# ---------------------------------------------------------------------------

_HLO_MODULE_RE = re.compile(r"^HloModule (\S+?)[,\s]", re.M)
_HLO_OP_RE = re.compile(
    r"^\s*(?:ROOT )?%?([\w.\-]+) = (.*)$", re.M)
_OP_NAME_RE = re.compile(r'op_name="([^"]+)"')
_SHAPE_RE = re.compile(r"\b([a-z]+\d*)\[([0-9,]*)\]")
_KIND_RE = re.compile(r"^(?:\([^=]*?\)|\S+(?:\{[^}]*\})?)\s+"
                      r"([\w\-]+)\(")
_LHS_CONTRACT_RE = re.compile(r"lhs_contracting_dims=\{([0-9,]*)\}")
_DIM_LABELS_RE = re.compile(r"dim_labels=(\w+)_(\w+)->(\w+)")

_DTYPE_BYTES = {"pred": 1, "s8": 1, "u8": 1, "s16": 2, "u16": 2,
                "f16": 2, "bf16": 2, "s32": 4, "u32": 4, "f32": 4,
                "s64": 8, "u64": 8, "f64": 8, "c64": 8, "c128": 16,
                "s4": 1, "u4": 1, "f8e4m3fn": 1, "f8e5m2": 1}


def _shape_bytes(dtype: str, dims: str) -> Tuple[int, int]:
    elems = 1
    for d in dims.split(","):
        if d:
            elems *= int(d)
    return elems, elems * _DTYPE_BYTES.get(dtype, 4)


def _op_cost(kind: str, rhs: str,
             shapes: List[Tuple[str, str]]) -> Tuple[float, float]:
    """(flops, bytes) estimate for one optimized-HLO op line: exact
    2·M·N·K math for dots, kernel-volume math for convolutions, one
    flop per output element for everything else; bytes are the sum of
    every shape on the line (result + operands — the traffic an ideal
    cache-less execution moves). Estimates, labeled as such — they
    rank roofline gaps, they are not a simulator."""
    if not shapes:
        return 0.0, 0.0
    bytes_ = float(sum(_shape_bytes(dt, dm)[1] for dt, dm in shapes))
    out_elems = _shape_bytes(*shapes[0])[0]
    flops = float(out_elems)
    if kind == "dot" and len(shapes) >= 2:
        m = _LHS_CONTRACT_RE.search(rhs)
        lhs_dims = [int(x) for x in
                    (m.group(1).split(",") if m and m.group(1) else [])]
        lhs_shape = [int(x) for x in shapes[1][1].split(",") if x]
        k = 1
        for d in lhs_dims:
            if d < len(lhs_shape):
                k *= lhs_shape[d]
        flops = 2.0 * out_elems * k
    elif kind == "convolution" and len(shapes) >= 3:
        kern_elems = _shape_bytes(*shapes[2])[0]
        out_ch = 1
        m = _DIM_LABELS_RE.search(rhs)
        if m and "o" in m.group(2):
            kern_dims = [int(x) for x in shapes[2][1].split(",") if x]
            oi = m.group(2).index("o")
            if oi < len(kern_dims):
                out_ch = kern_dims[oi]
        flops = 2.0 * out_elems * kern_elems / max(1, out_ch)
    return flops, bytes_


_CALLEE_RE = re.compile(
    r"(?:calls|body|condition|to_apply)=%?([\w.\-]+)")


def hlo_scope_map(hlo_text: str) -> Dict[str, Any]:
    """Map one executable's post-optimization HLO to attribution data:
    ``{"module": name, "ops": {op_name: {"scope", "backward", "kind",
    "flops", "bytes"}}}``. ``scope`` is the INNERMOST ``dl4j.`` scope
    on the op's ``metadata op_name`` path; ops with no metadata of
    their own (while-loop bookkeeping, region bodies — XLA:CPU's
    scatter loops are made of these) INHERIT the scope of the op that
    calls their computation, so a conv-backward scatter's thousands of
    body iterations attribute to the conv layer, not to noise. None
    when no caller on the chain is annotated (optimizer update,
    loss, ...)."""
    m = _HLO_MODULE_RE.search(hlo_text)
    module = m.group(1) if m else ""
    ops: Dict[str, Dict[str, Any]] = {}
    comp_of: Dict[str, str] = {}       # op -> enclosing computation
    caller_of: Dict[str, str] = {}     # computation -> calling op
    current_comp = ""
    for raw in hlo_text.splitlines():
        line = raw.strip()
        # computation header: `%name (params...) -> result {`
        if line.endswith("{") and ") -> " in line and " = " not in line:
            head = line.split(" ", 1)[0]
            if head == "ENTRY":
                head = line.split(" ", 2)[1]
            current_comp = head.lstrip("%")
            continue
        om = _HLO_OP_RE.match(line)
        if om is None:
            continue
        op, rhs = om.group(1), om.group(2)
        km = _KIND_RE.match(rhs)
        if km:
            kind = km.group(1)
        else:
            head = rhs.split("(")[0].split()
            kind = head[-1] if head else ""
        if not kind or kind == "parameter":
            continue
        for callee in _CALLEE_RE.findall(rhs):
            caller_of.setdefault(callee, op)
        nm = _OP_NAME_RE.search(rhs)
        scope_ = None
        backward = False
        if nm:
            hits = _SCOPE_RE.findall(nm.group(1))
            scope_ = hits[-1] if hits else None
            backward = "transpose(" in nm.group(1)
        shapes = _SHAPE_RE.findall(rhs)
        flops, bytes_ = _op_cost(kind, rhs, shapes)
        comp_of[op] = current_comp
        ops[op] = {"scope": scope_, "backward": backward,
                   "kind": kind, "flops": flops, "bytes": bytes_,
                   "has_meta": nm is not None}
    # scope inheritance: un-annotated ops take their calling op's
    # resolved scope (bounded walk — call graphs are shallow)
    def resolve(op: str, depth: int = 0) -> Tuple[Optional[str], bool]:
        info = ops.get(op)
        if info is None or depth > 8:
            return None, False
        if info["scope"] is not None:
            return info["scope"], info["backward"]
        caller = caller_of.get(comp_of.get(op, ""))
        if caller is None or caller == op:
            return None, info["backward"]
        sc, bwd = resolve(caller, depth + 1)
        return sc, (info["backward"] or bwd) if sc is not None \
            else info["backward"]

    for op, info in ops.items():
        if info["scope"] is None:
            sc, bwd = resolve(op)
            info["scope"], info["backward"] = sc, bwd
        info.pop("has_meta", None)
    return {"module": module, "ops": ops}


def sentry_executables(*fns) -> List[Any]:
    """The AOT ``Compiled`` executables a set of ``sentry.jit`` entry
    points keeps after warmup — the zero-recompile source of HLO text
    and ``cost_analysis()`` for attribution. Non-sentried / un-warmed
    arguments contribute nothing (attribution then falls back to
    op-class scopes)."""
    out = []
    for fn in fns:
        aot = getattr(fn, "_aot", None)
        if isinstance(aot, dict):
            out.extend(aot.values())
    return out


def executable_maps(executables: Iterable[Any]) -> Dict[str, Any]:
    """Scope maps keyed by HLO module name, plus merged
    ``cost_analysis()`` program totals per module."""
    maps: Dict[str, Any] = {}
    for ex in executables or ():
        try:
            text = ex.as_text()
        except Exception:
            continue
        sm = hlo_scope_map(text)
        try:
            ca = ex.cost_analysis()
            ca = ca[0] if isinstance(ca, (list, tuple)) else ca
            sm["program_flops"] = float(ca.get("flops", 0.0))
            sm["program_bytes"] = float(ca.get("bytes accessed", 0.0))
        except Exception:
            sm["program_flops"] = sm["program_bytes"] = 0.0
        maps[sm["module"]] = sm
    return maps


# ---------------------------------------------------------------------------
# roofline
# ---------------------------------------------------------------------------

def peaks_from_env() -> Tuple[float, float]:
    """(peak FLOP/s, peak bytes/s) — ``DL4J_TPU_PEAK_TFLOPS`` /
    ``DL4J_TPU_PEAK_HBM_GBS``, defaulting to the v5e chip (197 bf16
    TFLOP/s, 819 GB/s). On a CPU smoke run the utilization numbers are
    wiring-validation only (reports carry the peaks used)."""
    from deeplearning4j_tpu import environment
    return (float(environment.get_flag("DL4J_TPU_PEAK_TFLOPS")) * 1e12,
            float(environment.get_flag("DL4J_TPU_PEAK_HBM_GBS")) * 1e9)


def roofline(flops: float, bytes_: float, seconds: float,
             peak_flops: float, peak_bytes_per_s: float
             ) -> Dict[str, Any]:
    """Achieved-vs-roofline utilization for one measured region: which
    resource bounds it (arithmetic intensity vs the ridge point) and
    how close the measured rate comes to that resource's peak.
    ``utilization`` is the binding-resource fraction — a 0.9 means
    "this region already runs at 90% of what the roofline allows; a
    custom kernel buys little", a 0.1 names a gap."""
    if seconds <= 0 or peak_flops <= 0 or peak_bytes_per_s <= 0:
        return {"achieved_tflops": 0.0, "achieved_gbs": 0.0,
                "compute_utilization": 0.0, "memory_utilization": 0.0,
                "utilization": 0.0, "bound": "unknown"}
    achieved_fs = flops / seconds
    achieved_bs = bytes_ / seconds
    cu = achieved_fs / peak_flops
    mu = achieved_bs / peak_bytes_per_s
    ridge = peak_flops / peak_bytes_per_s        # flops per byte
    intensity = flops / bytes_ if bytes_ > 0 else math.inf
    bound = "compute" if intensity >= ridge else "memory"
    return {"achieved_tflops": round(achieved_fs / 1e12, 6),
            "achieved_gbs": round(achieved_bs / 1e9, 6),
            "compute_utilization": round(cu, 6),
            "memory_utilization": round(mu, 6),
            "utilization": round(cu if bound == "compute" else mu, 6),
            "bound": bound}


# ---------------------------------------------------------------------------
# attribution + gap report
# ---------------------------------------------------------------------------

_CLASS_NAME_RE = re.compile(r"^([a-zA-Z0-9_\-]+?)(?:\.\d+)?$")

#: control-flow containers whose children report their own time —
#: counting both would double-book every loop body (the
#: ``xprof_summary`` skip list, shared rationale)
_CONTAINER_KINDS = {"while", "conditional", "call", "async-start",
                    "async-done", "async-update"}


def _op_class(op: str) -> str:
    m = _CLASS_NAME_RE.match(op)
    return m.group(1) if m else op


#: the five HLO collective opcodes — the comm axis of the gap report
#: and the event filter of ``obs/commtime.py`` (which layers the wire
#: ledger + interconnect roofline on top of this classification)
COLLECTIVE_KINDS = ("all-reduce", "all-gather", "reduce-scatter",
                    "collective-permute", "all-to-all")

_COLLECTIVE_RE = re.compile(
    r"^(all-reduce|all-gather|reduce-scatter|collective-permute|"
    r"all-to-all)(?:-start)?(?:\.\d+)?$")


def collective_kind(op_or_kind: str) -> Optional[str]:
    """Base collective kind of an HLO op name/opcode, or None. The
    async ``-start`` form classifies (its device event carries the
    transfer duration); ``-done`` does not (a sync point — counting
    both would double-book every async collective)."""
    m = _COLLECTIVE_RE.match(op_or_kind)
    return m.group(1) if m else None


def attribute(paths: Iterable[str],
              maps: Optional[Dict[str, Any]] = None,
              peaks: Optional[Tuple[float, float]] = None
              ) -> Dict[str, Any]:
    """Join timed op events from ``paths`` (xplane files — every host
    of one session) with the executables' scope maps into per-scope
    device-time totals. Ops outside every annotated region aggregate
    under ``op:<class>`` scopes (the xprof class view), so the report
    always accounts for 100% of measured device time."""
    maps = maps or {}
    peak_f, peak_b = peaks or peaks_from_env()
    scopes: Dict[str, Dict[str, Any]] = {}
    module_ns: Dict[str, float] = {}
    module_op_count: Dict[Tuple[str, str], int] = {}
    total_ns = 0.0
    attributed_ns = 0.0
    steps: List[float] = []
    n_planes = 0
    for p in paths:
        xs = read_xspace(p)
        n_planes += len(xs["planes"])
        steps.extend(step_durations_ns(xs))
        for ev in op_events(xs):
            mod_map = maps.get(ev["module"])
            if mod_map is None and ev["module"]:
                # module-name fingerprint suffixes: accept a UNIQUE
                # prefix match, never a blind any-module scan —
                # default HLO names (fusion.1, broadcast.4) collide
                # across programs and would book one program's time
                # to another's scope
                cands = [m for k, m in maps.items()
                         if k and (ev["module"].startswith(k)
                                   or k.startswith(ev["module"]))]
                if len(cands) == 1:
                    mod_map = cands[0]
            info = mod_map["ops"].get(ev["op"]) \
                if mod_map is not None else None
            kind_ = info["kind"] if info else _op_class(ev["op"])
            if kind_ in _CONTAINER_KINDS:
                continue            # children report their own time
            sc = info["scope"] if info and info["scope"] else None
            if sc is None and "op_name" in ev:
                hits = _SCOPE_RE.findall(ev["op_name"])
                sc = hits[-1] if hits else None
            key = sc if sc is not None else f"op:{_op_class(ev['op'])}"
            e = scopes.get(key)
            if e is None:
                e = scopes[key] = {
                    "device_ns": 0.0, "ops": 0, "fusions": 0,
                    "backward_ns": 0.0, "custom_call_ns": 0.0,
                    "collective_ns": 0.0,
                    "flops": 0.0, "bytes": 0.0, "kinds": {}}
            dur = ev["dur_ns"]
            total_ns += dur
            if mod_map is not None:
                module_ns[mod_map["module"]] = \
                    module_ns.get(mod_map["module"], 0.0) + dur
                mk = (mod_map["module"], ev["op"])
                module_op_count[mk] = module_op_count.get(mk, 0) + 1
            e["device_ns"] += dur
            e["ops"] += 1
            kind = info["kind"] if info else _op_class(ev["op"])
            e["kinds"][kind] = e["kinds"].get(kind, 0) + 1
            if "fusion" in kind or "fusion" in ev["op"]:
                e["fusions"] += 1
            if "custom-call" in kind or "custom-call" in ev["op"]:
                e["custom_call_ns"] += dur
            if collective_kind(kind) or collective_kind(ev["op"]):
                e["collective_ns"] += dur
            if info is not None:
                e["flops"] += info["flops"]
                e["bytes"] += info["bytes"]
                if info["backward"]:
                    e["backward_ns"] += dur
            if sc is not None:
                attributed_ns += dur
    out_scopes: Dict[str, Dict[str, Any]] = {}
    for key, e in scopes.items():
        sec = e["device_ns"] / 1e9
        rec: Dict[str, Any] = {
            "device_ms": round(e["device_ns"] / 1e6, 6),
            "share": round(e["device_ns"] / total_ns, 6)
            if total_ns else 0.0,
            "ops": e["ops"], "fusions": e["fusions"],
            "backward_ms": round(e["backward_ns"] / 1e6, 6),
            "custom_call_ms": round(e["custom_call_ns"] / 1e6, 6),
            "comm_ms": round(e["collective_ns"] / 1e6, 6),
            "flops": e["flops"], "bytes": e["bytes"],
            "kinds": dict(sorted(e["kinds"].items(),
                                 key=lambda kv: -kv[1])),
        }
        if e["flops"] or e["bytes"]:
            rec["roofline"] = roofline(e["flops"], e["bytes"], sec,
                                       peak_f, peak_b)
        out_scopes[key] = rec
    # program-level cross-check: XLA's OWN cost_analysis() totals per
    # executed module against its measured device time — the roofline
    # number that does not depend on the regex shape estimates.
    # Executions per module = the MIN occurrence count over its
    # mapped non-container ops in the window: every top-level op runs
    # exactly once per execution (count == executions), loop-body ops
    # run more — min is robust to loop overcount and only
    # underestimates for conditional arms, which merely makes the
    # per-execution roofline conservative.
    modules: Dict[str, Dict[str, Any]] = {}
    for mod, ns in module_ns.items():
        mm = maps.get(mod)
        if mm is None:
            continue
        counts = [c for (m, op), c in module_op_count.items()
                  if m == mod and op in mm["ops"]
                  and mm["ops"][op]["kind"] not in _CONTAINER_KINDS]
        execs = min(counts) if counts else 1
        rec: Dict[str, Any] = {
            "device_ms": round(ns / 1e6, 6),
            "executions": max(1, execs),
            "program_flops": mm.get("program_flops", 0.0),
            "program_bytes": mm.get("program_bytes", 0.0),
        }
        if rec["program_flops"] or rec["program_bytes"]:
            rec["roofline"] = roofline(
                rec["program_flops"] * rec["executions"],
                rec["program_bytes"] * rec["executions"],
                ns / 1e9, peak_f, peak_b)
        modules[mod] = rec
    return {
        "total_device_ms": round(total_ns / 1e6, 6),
        "attributed_ms": round(attributed_ns / 1e6, 6),
        "scope_coverage": round(attributed_ns / total_ns, 6)
        if total_ns else 0.0,
        "device_steps": len(steps),
        "planes": n_planes,
        "peaks": {"flops": peak_f, "bytes_per_s": peak_b},
        "modules": modules,
        "scopes": out_scopes,
    }


#: the gap-report entry schema. ``tools/lint_instrumentation.py``
#: rule 8 resolves every ``gap.<key>`` token in docs/OPS.md and
#: tools/tpu_watch.py against THIS tuple — extend it here first.
#: ``closed_by`` (ISSUE 15): the registered fused kernel
#: (``ops/kernel_registry.py``) this scope now dispatches to, or None
#: while the gap is open — a closed scope is never a candidate and its
#: ``dl4j_tpu_devtime_scope_pallas_candidate`` gauge reads 0.
#: ``comm_ms`` (ISSUE 17): device time the scope spent inside
#: collective ops — when it dominates, ``bound`` reads ``"wire"`` (the
#: interconnect, not a kernel, is the ceiling) and the scope is never
#: a Pallas candidate.
GAP_KEYS = ("scope", "device_ms", "share", "ops", "fusions",
            "backward_ms", "comm_ms", "flops", "bytes", "utilization",
            "bound", "pallas_candidate", "closed_by")

#: a scope whose collective time exceeds this fraction of its device
#: time is wire-bound (the gap report + commtime WIRE_BOUND alarm)
WIRE_BOUND_SHARE = 0.5


def _is_pallas_candidate(share: float, util: Optional[float],
                         custom_ms: float, device_ms: float) -> bool:
    """A scope is worth a Pallas kernel when it is a real share of the
    step AND the roofline says XLA left performance on the table — and
    it is not already dominated by a custom call (an existing Pallas
    kernel re-flagging itself forever)."""
    if device_ms > 0 and custom_ms > 0.5 * device_ms:
        return False
    if util is None:                # no cost info: share alone decides
        return share >= 0.10
    return share >= 0.05 and util < 0.35


def gap_report(capture_: Dict[str, Any], top: int = 12
               ) -> List[Dict[str, Any]]:
    """Rank the capture's scopes by device-time share; every entry
    carries exactly :data:`GAP_KEYS`. A scope covered by a registered
    (gate-active) fused kernel reports that kernel as ``closed_by``
    and is never a ``pallas_candidate`` — the loop-closing half of the
    observatory: the report that NAMED the gap is also the proof the
    gap was filled (``tools/perf_dossier.py`` ``hot_path_gaps`` prints
    the closed/open split)."""
    from deeplearning4j_tpu.ops import kernel_registry
    rows = []
    for name, e in capture_["scopes"].items():
        rl = e.get("roofline")
        util = rl["utilization"] if rl else None
        bound = rl["bound"] if rl else "unknown"
        comm_ms = e.get("comm_ms", 0.0)
        # the comm axis: collective-dominated scopes are WIRE-bound —
        # the interconnect is the ceiling, so no kernel closes them
        wire = (e["device_ms"] > 0
                and comm_ms > WIRE_BOUND_SHARE * e["device_ms"])
        if wire:
            bound = "wire"
        closed = kernel_registry.closed_by(name)
        rows.append({
            "scope": name,
            "device_ms": e["device_ms"],
            "share": e["share"],
            "ops": e["ops"],
            "fusions": e["fusions"],
            "backward_ms": e["backward_ms"],
            "comm_ms": comm_ms,
            "flops": e["flops"],
            "bytes": e["bytes"],
            "utilization": util,
            "bound": bound,
            "pallas_candidate": closed is None and not wire
            and _is_pallas_candidate(
                e["share"], util, e["custom_call_ms"], e["device_ms"]),
            "closed_by": closed,
        })
    rows.sort(key=lambda r: -r["share"])
    assert all(tuple(r) == GAP_KEYS for r in rows)
    return rows[:top]


def _publish(capture_: Dict[str, Any],
             gaps: List[Dict[str, Any]]) -> None:
    """Export the last capture as ``dl4j_tpu_devtime_*`` gauges.
    Scope-label cardinality is bounded by the gap report's ``top``;
    stale labels from the previous capture are dropped so the scrape
    always shows ONE capture's ranking."""
    for fam in (_metrics.DEVTIME_SCOPE_SECONDS,
                _metrics.DEVTIME_SCOPE_SHARE,
                _metrics.DEVTIME_SCOPE_UTILIZATION,
                _metrics.DEVTIME_SCOPE_CANDIDATE):
        with fam._lock:
            fam._children.clear()
    for g in gaps:
        lab = g["scope"]
        _metrics.DEVTIME_SCOPE_SECONDS.labels(scope=lab).set(
            g["device_ms"] / 1e3)
        _metrics.DEVTIME_SCOPE_SHARE.labels(scope=lab).set(g["share"])
        if g["utilization"] is not None:
            _metrics.DEVTIME_SCOPE_UTILIZATION.labels(scope=lab).set(
                g["utilization"])
        _metrics.DEVTIME_SCOPE_CANDIDATE.labels(scope=lab).set(
            int(g["pallas_candidate"]))
    _metrics.DEVTIME_PALLAS_CANDIDATES.set(
        sum(1 for g in gaps if g["pallas_candidate"]))


# ---------------------------------------------------------------------------
# capture pipelines: on demand + cadence
# ---------------------------------------------------------------------------

def capture(run, *, executables: Iterable[Any] = (),
            label: str = "on_demand", top: int = 12,
            keep_dir: Optional[str] = None) -> Dict[str, Any]:
    """The on-demand pipeline: run ``run()`` (real steps — the capture
    measures whatever the caller dispatches) under a
    ``jax.profiler.trace`` window, attribute the device time against
    ``executables``' scope maps, publish the gauges, and return
    ``{"capture": ..., "gaps": [...]}``. ``keep_dir`` preserves the
    raw xplane session for ``tools/xprof_summary.py``."""
    import jax

    d = keep_dir or tempfile.mkdtemp(prefix="dl4j_devtime_")
    t0 = _trace.now()
    with _lock:
        _counters["sessions"] += 1
    try:
        with jax.profiler.trace(d):
            run()
    except Exception:
        if keep_dir is None:
            shutil.rmtree(d, ignore_errors=True)
        raise
    try:
        att = attribute(xplane_paths(d),
                        maps=executable_maps(executables))
    finally:
        if keep_dir is None:
            shutil.rmtree(d, ignore_errors=True)
    wall = _trace.now() - t0
    gaps = gap_report(att, top=top)
    with _lock:
        _counters["captures"] += 1
    _metrics.DEVTIME_CAPTURES.inc()
    _metrics.DEVTIME_CAPTURE_SECONDS.inc(wall)
    _publish(att, gaps)
    global _last_report
    _last_report = {"label": label, "capture_wall_s": round(wall, 6),
                    "capture": att, "gaps": gaps}
    if _trace.enabled():
        _trace.instant("devtime/capture",
                       {"label": label, "wall_s": round(wall, 4)})
    return _last_report


class Observatory:
    """Cadence-gated capture windows inside the fit loops: every
    ``every``-th iteration opens a ``jax.profiler.trace`` window that
    stays open for ``steps`` fit steps, then attributes and publishes.
    Instantiated from ``DL4J_TPU_DEVTIME`` — never on the default
    path."""

    def __init__(self, every: int = 100, steps: int = 3,
                 top: int = 12):
        self.every = max(1, int(every))
        self.steps = max(1, int(steps))
        self.top = int(top)
        self._dir: Optional[str] = None
        self._steps_in = 0
        self._t0 = 0.0

    def capturing(self) -> bool:
        return self._dir is not None

    def due(self, iteration: int) -> bool:
        return iteration % self.every == 0

    def on_step_start(self, iteration: int) -> None:
        if self._dir is not None or not self.due(iteration):
            return
        import jax
        d = tempfile.mkdtemp(prefix="dl4j_devtime_")
        try:
            jax.profiler.start_trace(d)
        except Exception:
            # another profiler session owns the process (e.g. the
            # dossier's --trace wrapper): skip this window, never
            # break the step
            shutil.rmtree(d, ignore_errors=True)
            return
        with _lock:
            _counters["sessions"] += 1
        self._dir = d
        self._steps_in = 0
        self._t0 = _trace.now()

    def on_step_end(self, *step_fns) -> None:
        if self._dir is None:
            return
        self._steps_in += 1
        if self._steps_in < self.steps:
            return
        import jax
        d, self._dir = self._dir, None
        try:
            jax.profiler.stop_trace()
        except Exception:
            shutil.rmtree(d, ignore_errors=True)
            return
        try:
            att = attribute(
                xplane_paths(d),
                maps=executable_maps(
                    sentry_executables(*[f for f in step_fns
                                         if f is not None])))
        except FileNotFoundError:
            shutil.rmtree(d, ignore_errors=True)
            return
        finally:
            shutil.rmtree(d, ignore_errors=True)
        wall = _trace.now() - self._t0
        gaps = gap_report(att, top=self.top)
        with _lock:
            _counters["captures"] += 1
        _metrics.DEVTIME_CAPTURES.inc()
        _metrics.DEVTIME_CAPTURE_SECONDS.inc(wall)
        _publish(att, gaps)
        global _last_report
        _last_report = {"label": "cadence",
                        "capture_wall_s": round(wall, 6),
                        "capture": att, "gaps": gaps}


def configure(every: int = 100, steps: int = 3,
              top: int = 12) -> Observatory:
    """Install the cadence monitor programmatically (tests/tools)."""
    global _MONITOR
    _MONITOR = Observatory(every=every, steps=steps, top=top)
    return _MONITOR


def disable() -> None:
    global _MONITOR
    if _MONITOR is not None and _MONITOR.capturing():
        import jax
        try:
            jax.profiler.stop_trace()
        except Exception:
            pass
        if _MONITOR._dir:
            shutil.rmtree(_MONITOR._dir, ignore_errors=True)
    _MONITOR = None


def configure_from_env() -> Optional[Observatory]:
    """Install the monitor from ``DL4J_TPU_DEVTIME`` (called by
    ``environment.apply_startup_flags``; the unset path never reaches
    here)."""
    from deeplearning4j_tpu import environment
    raw = str(environment.get_flag("DL4J_TPU_DEVTIME") or "").strip()
    if raw.lower() not in _TRUTHY:
        return None
    return configure(
        every=int(environment.get_flag("DL4J_TPU_DEVTIME_EVERY")),
        steps=int(environment.get_flag("DL4J_TPU_DEVTIME_STEPS")))


# -- fit-loop hooks (the counter-fenced off path) ---------------------------

def step_started(iteration: int) -> None:
    """Called by the fit loops before dispatching a step. Off path
    (``DL4J_TPU_DEVTIME`` unset): one module-global ``is None``
    branch — zero profiler sessions, zero allocations."""
    m = _MONITOR
    if m is None:
        return
    m.on_step_start(iteration)


def step_ended(*step_fns) -> None:
    """Called by the fit loops after the step's blocking sync, passing
    the step's (possibly warmed) ``sentry.jit`` entry points so the
    attribution can read their compiled HLO. Same one-branch off
    path."""
    m = _MONITOR
    if m is None:
        return
    m.on_step_end(*step_fns)


# ---------------------------------------------------------------------------
# bench probe
# ---------------------------------------------------------------------------

def measure_capture_overhead(step_seconds: Optional[float] = None,
                             iters: int = 20000) -> Dict[str, Any]:
    """The ``devtime`` section of ``bench.py``/the dossier: the OFF
    path (the two fit-loop hook branches every un-observed step pays)
    and the capture counters — synthetic probe state restored so the
    off-path fences stay honest."""
    global _MONITOR
    saved, _MONITOR = _MONITOR, None
    c0 = dict(_counters)
    try:
        t0 = _trace.now()
        for i in range(iters):
            step_started(i)
            step_ended(None)
        off = (_trace.now() - t0) / iters
    finally:
        _MONITOR = saved
        with _lock:
            _counters.update(c0)
    out: Dict[str, Any] = {
        "off_path_cost_us": round(off * 1e6, 4),
        "monitor_enabled": _MONITOR is not None,
        "captures": captures(),
        "profiler_sessions": profiler_sessions(),
    }
    if step_seconds:
        out["step_ms"] = round(step_seconds * 1e3, 3)
        out["off_path_pct_of_step"] = round(
            100.0 * off / step_seconds, 5)
    lr = _last_report
    if lr is not None:
        out["last_capture"] = {"label": lr["label"],
                               "wall_s": lr["capture_wall_s"],
                               "top_gap": (lr["gaps"][0]["scope"]
                                           if lr["gaps"] else None)}
    return out


__all__ = ["scope", "capture", "attribute", "gap_report", "roofline",
           "read_xspace", "xplane_paths", "op_events",
           "step_durations_ns", "hlo_scope_map", "executable_maps",
           "sentry_executables", "peaks_from_env", "Observatory",
           "configure", "configure_from_env", "disable",
           "step_started", "step_ended", "captures",
           "profiler_sessions", "reset_counters", "last_report",
           "measure_capture_overhead", "GAP_KEYS", "SCOPE_PREFIX",
           "COLLECTIVE_KINDS", "collective_kind", "WIRE_BOUND_SHARE"]
