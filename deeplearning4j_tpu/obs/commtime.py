"""Communication observatory — per-collective wire-byte and
interconnect-time attribution across every parallelism mode.

The devtime observatory (PR 9) answers "which LAYER is the device
computing in"; nothing answered "which PHASE is the interconnect
moving bytes for". ROADMAP item 4 (encoded-gradient collectives) is
blocked on exactly that measurement — "nothing measures wire bytes" —
and `tools/collective_volume.py` only projected volume statically for
three hand-written configs. This module is the comm sibling of
:mod:`~deeplearning4j_tpu.obs.devtime` (ARCHITECTURE.md §19):

1. **Static wire ledger.** :func:`collective_records` walks one
   optimized-HLO module (the collective walker factored out of
   ``tools/collective_volume.py``, which now delegates here) and
   yields one record per collective op: kind, result tensor bytes,
   ring-model wire bytes (sized by the op's PARSED replica groups,
   not a global device count), replica groups, and the ``dl4j.*``
   scope joined through the same ``metadata``/call-graph inheritance
   devtime uses (:func:`~deeplearning4j_tpu.obs.devtime
   .hlo_scope_map`). :func:`wire_ledger` aggregates records across
   any set of sentry-registered executables — so EVERY jitted
   program (DP, ZeRO scatter/gather, gather-overlap, composed
   DP×TP/SP/PP/EP, the serving fleet paths) gets a per-scope wire
   account, not just the hand-picked configs.

2. **Runtime attribution.** :func:`attribute` rides devtime's xplane
   capture pipeline: per-scope device time spent inside collective
   ops (``all-reduce``/``reduce-scatter``/``all-gather``/
   ``collective-permute``/``all-to-all``; async ``-start`` events
   carry the transfer, ``-done`` sync points are excluded), joined
   with the static ledger into an interconnect roofline — measured
   wire GB/s over ``DL4J_TPU_PEAK_ICI_GBS``. Off-TPU captures
   (CPU/gloo) are labeled ``estimate_only``: thunk timings are host
   copies, not ICI transfers, so only the LEDGER numbers are load-
   bearing there. ``devtime.gap_report`` entries carry the same axis
   (``gap.comm_ms``; ``bound == "wire"`` when collectives dominate).

3. **Live plane.** :func:`capture` / the env-gated
   :class:`Observatory` (``DL4J_TPU_COMMTIME``) publish
   ``dl4j_tpu_comm_*`` gauges through the standing registry — which
   the PR 7 fleet snapshots embed verbatim, so ``/fleet`` re-labels
   per-scope wire bytes and link utilization with host/mesh-epoch:
   per-host link health is routable state. ``tpu_watch --comm``
   renders the table + WIRE_BOUND alarm; ``bench.py`` carries the
   ``comm`` section (the PR 5 ZeRO byte gates, measured); the
   dossier carries the ``comm_observatory`` row.

Off path: with ``DL4J_TPU_COMMTIME`` unset the fit-loop hooks
(:func:`step_started`/:func:`step_ended`) are one module-global
``is None`` branch — zero profiler sessions, zero captures, zero
publishes, counter-fenced by ``tests/test_commtime.py``.
"""
from __future__ import annotations

import re
import shutil
import tempfile
import threading
from typing import Any, Dict, Iterable, List, Optional, Tuple

from deeplearning4j_tpu.obs import devtime as _devtime
from deeplearning4j_tpu.obs import metrics as _metrics
from deeplearning4j_tpu.obs import trace as _trace
from deeplearning4j_tpu.obs.devtime import (COLLECTIVE_KINDS,
                                            WIRE_BOUND_SHARE,
                                            collective_kind)

_TRUTHY = {"1", "true", "on", "yes"}

_lock = threading.Lock()
_counters = {"captures": 0, "sessions": 0}

#: the env-gated cadence monitor (None = off: the one branch every
#: un-observed step pays in the fit loops)
_MONITOR: Optional["Observatory"] = None

#: the last completed comm capture (tools / dossier tail)
_last_report: Optional[Dict[str, Any]] = None


def captures() -> int:
    """Completed comm capture-and-attribute pipelines since reset —
    with ``DL4J_TPU_COMMTIME`` unset and no explicit :func:`capture`
    call this stays 0 (the off-path fence)."""
    return _counters["captures"]


def profiler_sessions() -> int:
    """``jax.profiler`` sessions started by this module since reset."""
    return _counters["sessions"]


def reset_counters() -> None:
    global _last_report
    with _lock:
        _counters["captures"] = 0
        _counters["sessions"] = 0
    _last_report = None


def last_report() -> Optional[Dict[str, Any]]:
    return _last_report


# ---------------------------------------------------------------------------
# static wire ledger: the HLO collective walker (factored out of
# tools/collective_volume.py — that tool now delegates here)
# ---------------------------------------------------------------------------

# HLO line shape: `%name = <shape-or-tuple> <opcode>(...), ...` — the
# result may be a TUPLE (XLA fuses many gradients into one all-reduce)
_COLLECTIVE_LINE_RE = re.compile(
    r"=\s*(\(?[^(=]*?(?:\([^)]*\))?)\s*"
    r"(all-reduce|all-gather|reduce-scatter|collective-permute|"
    r"all-to-all)(?:-start)?\(")
_SHAPE_RE = re.compile(r"([a-z0-9]+)\[([0-9,]*)\]")
_LHS_RE = re.compile(r"^\s*(?:ROOT\s+)?%?([\w.\-]+)\s*=")

_DTYPE_BYTES = {"f64": 8, "f32": 4, "bf16": 2, "f16": 2, "s32": 4,
                "u32": 4, "s64": 8, "u64": 8, "s16": 2, "u16": 2,
                "s8": 1, "u8": 1, "pred": 1, "c64": 8, "c128": 16}

_GROUPS_LITERAL_RE = re.compile(r"replica_groups=\{(\{[0-9,]+\}"
                                r"(?:,\{[0-9,]+\})*)\}")
_GROUPS_IOTA_RE = re.compile(
    r"replica_groups=\[(\d+),(\d+)\]<=\[([0-9,]+)\]"
    r"(?:T\(([0-9,]+)\))?")
_PAIRS_RE = re.compile(r"source_target_pairs=\{([0-9,{} ]*)\}")


def _tensor_bytes(dtype: str, dims: str) -> int:
    n = 1
    for d in dims.split(",") if dims else []:
        n *= int(d)
    return n * _DTYPE_BYTES.get(dtype, 4)


def parse_replica_groups(line: str):
    """Replica groups of one HLO collective line, as a frozenset of
    frozensets of device ids — handles both the literal
    ``{{0,2},{1,3}}`` and the iota ``[G,S]<=[dims]T(perm)`` forms.
    None for the empty/absent form (all devices one group)."""
    m = _GROUPS_LITERAL_RE.search(line)
    if m:
        return frozenset(
            frozenset(int(d) for d in g.split(","))
            for g in m.group(1)[1:-1].split("},{"))
    m = _GROUPS_IOTA_RE.search(line)
    if m:
        import numpy as np
        g, s = int(m.group(1)), int(m.group(2))
        dims = [int(d) for d in m.group(3).split(",")]
        arr = np.arange(int(np.prod(dims))).reshape(dims)
        if m.group(4):
            arr = arr.transpose([int(p) for p in m.group(4).split(",")])
        arr = arr.reshape(g, s)
        return frozenset(frozenset(int(d) for d in row) for row in arr)
    return None


def parse_source_target_pairs(line: str
                              ) -> Optional[List[Tuple[int, int]]]:
    """``source_target_pairs`` of a collective-permute line."""
    m = _PAIRS_RE.search(line)
    if not m or not m.group(1):
        return None
    return [tuple(int(x) for x in p.split(","))
            for p in m.group(1)[1:-1].split("},{")]


def ring_wire_bytes(kind: str, tensor_bytes: float,
                    group_size: int) -> float:
    """Per-device ring-algorithm wire bytes for one collective whose
    HLO RESULT is ``tensor_bytes`` over a ``group_size`` ring:

    - all-reduce: ``2·N·(n−1)/n`` (reduce-scatter + all-gather)
    - all-gather: ``N/n·(n−1)`` (result is the FULL gathered tensor;
      each device sends its shard to n−1 peers)
    - reduce-scatter: ``N·(n−1)`` (result is the shard)
    - collective-permute: ``N`` (one neighbor hop)
    - all-to-all: ``N·(n−1)/n``
    """
    n = int(group_size)
    if n <= 1:
        return 0.0      # a one-device group moves nothing
    nb = float(tensor_bytes)
    return {"all-reduce": 2.0 * nb * (n - 1) / n,
            "all-gather": nb / n * (n - 1),
            "reduce-scatter": nb * (n - 1),
            "collective-permute": nb,
            "all-to-all": nb * (n - 1) / n}[kind]


def collective_records(hlo_text: str, n_devices: Optional[int] = None,
                       uniform_ring: Optional[int] = None
                       ) -> List[Dict[str, Any]]:
    """Walk one optimized-HLO module → one ledger record per
    collective op (async ``-done`` halves excluded): ``{"module",
    "op", "kind", "tensor_bytes", "wire_bytes", "group_size",
    "replica_groups", "source_target_pairs", "scope", "backward",
    "in_while", "trips"}``.

    The ring model is sized by the op's PARSED replica groups (the
    largest group — a DP×TP program's tensor-axis all-reduce rings
    over 2 devices, not 8), falling back to ``n_devices`` when the
    groups are absent/empty. ``uniform_ring`` overrides the group
    size for every op — the legacy ``collective_volume.py`` knob its
    analytic rows are pinned to. ``scope`` is the innermost ``dl4j.``
    scope via :func:`devtime.hlo_scope_map` (metadata + call-graph
    inheritance), or None for an anonymous collective. Collectives
    inside a ``while`` body (the ring-attention fori_loop) execute
    once per trip; the ring's trip count is its group size."""
    smap = _devtime.hlo_scope_map(hlo_text)
    ops = smap["ops"]
    out: List[Dict[str, Any]] = []
    for line in hlo_text.splitlines():
        head = line.split("metadata=")[0]
        m = _COLLECTIVE_LINE_RE.search(head)
        if not m or "-done" in head:
            continue
        shapes, kind = m.groups()
        nb = sum(_tensor_bytes(d, dims)
                 for d, dims in _SHAPE_RE.findall(shapes))
        groups = parse_replica_groups(line)
        if uniform_ring:
            g = int(uniform_ring)
        elif groups:
            g = max(len(grp) for grp in groups)
        elif n_devices:
            g = int(n_devices)
        else:
            g = 2
        lhs = _LHS_RE.match(line)
        op = lhs.group(1) if lhs else ""
        info = ops.get(op)
        scope = info["scope"] if info and info["scope"] else None
        in_while = "/while/" in line
        trips = g if in_while else 1
        out.append({
            "module": smap["module"], "op": op, "kind": kind,
            "tensor_bytes": nb,
            "wire_bytes": ring_wire_bytes(kind, nb, g) * trips,
            "group_size": g, "replica_groups": groups,
            "source_target_pairs": parse_source_target_pairs(line),
            "scope": scope,
            "backward": bool(info and info["backward"]),
            "in_while": in_while, "trips": trips})
    return out


def wire_ledger(executables: Iterable[Any] = (), *,
                n_devices: Optional[int] = None) -> Dict[str, Any]:
    """The static half of the observatory: aggregate
    :func:`collective_records` across ``executables`` (anything with
    ``.as_text()`` — ``devtime.sentry_executables`` output, or
    ``.lower().compile()`` results) into per-scope and per-kind wire
    accounts, assuming each program executes once per step. Anonymous
    collectives (no ``dl4j.`` scope on the op or any caller)
    aggregate under ``op:<kind>`` keys — lint rule 11 keeps the
    in-repo collective emitters scoped so those stay empty."""
    ex = [e for e in executables if e is not None]
    if n_devices is None:
        import jax
        n_devices = jax.device_count()
    records: List[Dict[str, Any]] = []
    for c in ex:
        try:
            text = c.as_text()
        except Exception:
            continue
        records.extend(collective_records(text, n_devices=n_devices))
    by_scope: Dict[str, Dict[str, Any]] = {}
    by_kind: Dict[str, Dict[str, Any]] = {}
    total = 0.0
    for r in records:
        key = r["scope"] if r["scope"] else f"op:{r['kind']}"
        s = by_scope.setdefault(key, {"wire_bytes": 0.0,
                                      "tensor_bytes": 0.0,
                                      "kinds": {}})
        s["wire_bytes"] += r["wire_bytes"]
        s["tensor_bytes"] += r["tensor_bytes"] * r["trips"]
        s["kinds"][r["kind"]] = s["kinds"].get(r["kind"], 0) + 1
        k = by_kind.setdefault(r["kind"], {"count": 0,
                                           "wire_bytes": 0.0})
        k["count"] += 1
        k["wire_bytes"] += r["wire_bytes"]
        total += r["wire_bytes"]
    return {"n_devices": int(n_devices), "programs": len(ex),
            "records": records, "by_scope": by_scope,
            "by_kind": by_kind, "wire_bytes": total}


# ---------------------------------------------------------------------------
# runtime attribution + interconnect roofline
# ---------------------------------------------------------------------------

def peak_ici_from_env() -> float:
    """Interconnect roofline peak in bytes/s (``DL4J_TPU_PEAK_ICI_GBS``,
    default the public v5e figure: 45 GB/s per link per direction)."""
    from deeplearning4j_tpu import environment
    return float(environment.get_flag("DL4J_TPU_PEAK_ICI_GBS")) * 1e9


def _estimate_only() -> bool:
    """CPU/gloo captures time host-side thunk copies, not ICI
    transfers — their utilization numbers are wiring-validation only
    (the ledger bytes remain exact)."""
    try:
        import jax
        return jax.devices()[0].platform != "tpu"
    except Exception:
        return True


def comm_view(att: Dict[str, Any],
              ledger: Optional[Dict[str, Any]] = None,
              peak_ici: Optional[float] = None) -> Dict[str, Any]:
    """Project a ``devtime.attribute`` capture onto the comm axis and
    join the static ``ledger``: per-scope collective seconds, share of
    device time, wire bytes/step, and achieved-vs-peak interconnect
    utilization (``wire GB/s / DL4J_TPU_PEAK_ICI_GBS``)."""
    peak = peak_ici or peak_ici_from_env()
    total_ms = att["total_device_ms"]
    execs = [m.get("executions", 1) for m in att["modules"].values()]
    steps = att["device_steps"] or (max(execs) if execs else 1) or 1
    by_scope = (ledger or {}).get("by_scope", {})
    scopes: Dict[str, Dict[str, Any]] = {}
    by_kind: Dict[str, int] = {}
    total_comm = 0.0
    for name, e in att["scopes"].items():
        kinds: Dict[str, int] = {}
        for k, c in e.get("kinds", {}).items():
            base = collective_kind(k)
            if base:
                kinds[base] = kinds.get(base, 0) + c
        comm_ms = e.get("comm_ms", 0.0)
        led = by_scope.get(name)
        if comm_ms <= 0 and not kinds and led is None:
            continue
        total_comm += comm_ms
        for k, c in kinds.items():
            by_kind[k] = by_kind.get(k, 0) + c
        rec: Dict[str, Any] = {
            "collective_ms": comm_ms,
            "device_ms": e["device_ms"],
            "share": round(comm_ms / total_ms, 6) if total_ms else 0.0,
            "wire_bound": bool(
                e["device_ms"] > 0
                and comm_ms > WIRE_BOUND_SHARE * e["device_ms"]),
            "kinds": kinds,
        }
        if led is not None:
            rec["wire_bytes_per_step"] = led["wire_bytes"]
            rec["tensor_bytes_per_step"] = led["tensor_bytes"]
            if comm_ms > 0:
                gbs = (led["wire_bytes"] * steps
                       / (comm_ms / 1e3)) / 1e9
                rec["achieved_gbs"] = round(gbs, 6)
                rec["link_utilization"] = round(gbs * 1e9 / peak, 6)
        scopes[name] = rec
    return {
        "total_device_ms": total_ms,
        "collective_ms": round(total_comm, 6),
        "comm_share": round(total_comm / total_ms, 6)
        if total_ms else 0.0,
        "device_steps": att["device_steps"],
        "planes": att["planes"],
        "peak_ici_gbs": peak / 1e9,
        "estimate_only": _estimate_only(),
        "by_kind": dict(sorted(by_kind.items(), key=lambda kv: -kv[1])),
        "wire_bytes_per_step": (ledger or {}).get("wire_bytes"),
        "wire_bound_scopes": sorted(
            n for n, r in scopes.items() if r["wire_bound"]),
        "scopes": scopes,
    }


def attribute(paths: Iterable[str],
              maps: Optional[Dict[str, Any]] = None,
              ledger: Optional[Dict[str, Any]] = None,
              peak_ici: Optional[float] = None) -> Dict[str, Any]:
    """Runtime half over raw xplane ``paths``: one
    ``devtime.attribute`` pass (scope join through the same maps),
    projected onto the comm axis via :func:`comm_view`. With
    ``maps=None`` the scope join falls back to each event's
    ``op_name`` metadata (``tools/xprof_summary.py --comm``)."""
    return comm_view(_devtime.attribute(paths, maps=maps),
                     ledger=ledger, peak_ici=peak_ici)


def _publish(view: Dict[str, Any], top: int = 12) -> None:
    """Export the last comm capture as ``dl4j_tpu_comm_*`` gauges.
    Scope-label cardinality bounded by ``top``; stale labels dropped
    so the scrape always shows ONE capture's ranking. The fleet
    snapshot embeds the registry exposition verbatim, so these ride
    into ``/fleet`` with host labels for free."""
    for fam in (_metrics.COMM_SCOPE_WIRE_BYTES,
                _metrics.COMM_SCOPE_SECONDS,
                _metrics.COMM_SCOPE_SHARE,
                _metrics.COMM_SCOPE_LINK_UTILIZATION,
                _metrics.COMM_OP_COUNT,
                _metrics.COMM_WIRE_BOUND_SCOPES):
        with fam._lock:
            fam._children.clear()
    ranked = sorted(view["scopes"].items(),
                    key=lambda kv: -kv[1]["collective_ms"])[:top]
    for name, r in ranked:
        _metrics.COMM_SCOPE_SECONDS.labels(scope=name).set(
            r["collective_ms"] / 1e3)
        _metrics.COMM_SCOPE_SHARE.labels(scope=name).set(r["share"])
        if "wire_bytes_per_step" in r:
            _metrics.COMM_SCOPE_WIRE_BYTES.labels(scope=name).set(
                r["wire_bytes_per_step"])
        if "link_utilization" in r:
            _metrics.COMM_SCOPE_LINK_UTILIZATION.labels(
                scope=name).set(r["link_utilization"])
    for kind, count in view["by_kind"].items():
        _metrics.COMM_OP_COUNT.labels(kind=kind).set(count)
    for name in view["wire_bound_scopes"]:
        _metrics.COMM_WIRE_BOUND_SCOPES.labels(scope=name).set(1)


# ---------------------------------------------------------------------------
# capture pipelines: on demand + cadence
# ---------------------------------------------------------------------------

def capture(run, *, executables: Iterable[Any] = (),
            label: str = "on_demand", top: int = 12,
            keep_dir: Optional[str] = None) -> Dict[str, Any]:
    """The on-demand pipeline: run ``run()`` under a
    ``jax.profiler.trace`` window, build the static wire ledger from
    ``executables``, attribute the collective device time, publish the
    ``dl4j_tpu_comm_*`` gauges, and return ``{"comm": ...,
    "ledger": ...}``. ``keep_dir`` preserves the raw xplane session
    for ``tools/xprof_summary.py --comm``."""
    import jax

    ex = [e for e in executables if e is not None]
    d = keep_dir or tempfile.mkdtemp(prefix="dl4j_commtime_")
    t0 = _trace.now()
    with _lock:
        _counters["sessions"] += 1
    try:
        with jax.profiler.trace(d):
            run()
    except Exception:
        if keep_dir is None:
            shutil.rmtree(d, ignore_errors=True)
        raise
    try:
        led = wire_ledger(ex)
        view = attribute(_devtime.xplane_paths(d),
                         maps=_devtime.executable_maps(ex),
                         ledger=led)
    finally:
        if keep_dir is None:
            shutil.rmtree(d, ignore_errors=True)
    wall = _trace.now() - t0
    with _lock:
        _counters["captures"] += 1
    _metrics.COMM_CAPTURES.inc()
    _metrics.COMM_CAPTURE_SECONDS.inc(wall)
    _publish(view, top=top)
    global _last_report
    _last_report = {"label": label, "capture_wall_s": round(wall, 6),
                    "comm": view,
                    "ledger": {"wire_bytes": led["wire_bytes"],
                               "by_kind": led["by_kind"],
                               "programs": led["programs"]}}
    if _trace.enabled():
        _trace.instant("commtime/capture",
                       {"label": label, "wall_s": round(wall, 4)})
    return _last_report


class Observatory:
    """Cadence-gated comm capture windows inside the fit loops —
    instantiated from ``DL4J_TPU_COMMTIME``, never on the default
    path. Shares the process profiler politely: if another session
    owns it (devtime's window, the dossier's ``--trace``), the window
    is skipped, never breaking the step."""

    def __init__(self, every: int = 100, steps: int = 3,
                 top: int = 12):
        self.every = max(1, int(every))
        self.steps = max(1, int(steps))
        self.top = int(top)
        self._dir: Optional[str] = None
        self._steps_in = 0
        self._t0 = 0.0

    def capturing(self) -> bool:
        return self._dir is not None

    def due(self, iteration: int) -> bool:
        return iteration % self.every == 0

    def on_step_start(self, iteration: int) -> None:
        if self._dir is not None or not self.due(iteration):
            return
        import jax
        d = tempfile.mkdtemp(prefix="dl4j_commtime_")
        try:
            jax.profiler.start_trace(d)
        except Exception:
            shutil.rmtree(d, ignore_errors=True)
            return
        with _lock:
            _counters["sessions"] += 1
        self._dir = d
        self._steps_in = 0
        self._t0 = _trace.now()

    def on_step_end(self, *step_fns) -> None:
        if self._dir is None:
            return
        self._steps_in += 1
        if self._steps_in < self.steps:
            return
        import jax
        d, self._dir = self._dir, None
        try:
            jax.profiler.stop_trace()
        except Exception:
            shutil.rmtree(d, ignore_errors=True)
            return
        try:
            ex = _devtime.sentry_executables(
                *[f for f in step_fns if f is not None])
            led = wire_ledger(ex)
            view = attribute(_devtime.xplane_paths(d),
                             maps=_devtime.executable_maps(ex),
                             ledger=led)
        except FileNotFoundError:
            shutil.rmtree(d, ignore_errors=True)
            return
        finally:
            shutil.rmtree(d, ignore_errors=True)
        wall = _trace.now() - self._t0
        with _lock:
            _counters["captures"] += 1
        _metrics.COMM_CAPTURES.inc()
        _metrics.COMM_CAPTURE_SECONDS.inc(wall)
        _publish(view, top=self.top)
        global _last_report
        _last_report = {"label": "cadence",
                        "capture_wall_s": round(wall, 6),
                        "comm": view,
                        "ledger": {"wire_bytes": led["wire_bytes"],
                                   "by_kind": led["by_kind"],
                                   "programs": led["programs"]}}


def configure(every: int = 100, steps: int = 3,
              top: int = 12) -> Observatory:
    """Install the cadence monitor programmatically (tests/tools)."""
    global _MONITOR
    _MONITOR = Observatory(every=every, steps=steps, top=top)
    return _MONITOR


def disable() -> None:
    global _MONITOR
    if _MONITOR is not None and _MONITOR.capturing():
        import jax
        try:
            jax.profiler.stop_trace()
        except Exception:
            pass
        if _MONITOR._dir:
            shutil.rmtree(_MONITOR._dir, ignore_errors=True)
    _MONITOR = None


def configure_from_env() -> Optional[Observatory]:
    """Install the monitor from ``DL4J_TPU_COMMTIME`` (called by
    ``environment.apply_startup_flags``; the unset path never reaches
    here)."""
    from deeplearning4j_tpu import environment
    raw = str(environment.get_flag("DL4J_TPU_COMMTIME") or "").strip()
    if raw.lower() not in _TRUTHY:
        return None
    return configure(
        every=int(environment.get_flag("DL4J_TPU_COMMTIME_EVERY")),
        steps=int(environment.get_flag("DL4J_TPU_COMMTIME_STEPS")))


# -- fit-loop hooks (the counter-fenced off path) ---------------------------

def step_started(iteration: int) -> None:
    """Called by the fit loops next to ``devtime.step_started``. Off
    path (``DL4J_TPU_COMMTIME`` unset): one module-global ``is None``
    branch — zero profiler sessions, zero allocations."""
    m = _MONITOR
    if m is None:
        return
    m.on_step_start(iteration)


def step_ended(*step_fns) -> None:
    """Called by the fit loops after the step's blocking sync, passing
    the step's ``sentry.jit`` entry points so the ledger can read
    their compiled HLO. Same one-branch off path."""
    m = _MONITOR
    if m is None:
        return
    m.on_step_end(*step_fns)


# ---------------------------------------------------------------------------
# bench probes
# ---------------------------------------------------------------------------

def measure_capture_overhead(step_seconds: Optional[float] = None,
                             iters: int = 20000) -> Dict[str, Any]:
    """The off-path half of the bench ``comm`` section: the two
    fit-loop hook branches every un-observed step pays, and the
    counter fence — synthetic probe state restored."""
    global _MONITOR
    saved, _MONITOR = _MONITOR, None
    c0 = dict(_counters)
    try:
        t0 = _trace.now()
        for i in range(iters):
            step_started(i)
            step_ended(None)
        off = (_trace.now() - t0) / iters
    finally:
        _MONITOR = saved
        with _lock:
            _counters.update(c0)
    out: Dict[str, Any] = {
        "off_path_cost_us": round(off * 1e6, 4),
        "monitor_enabled": _MONITOR is not None,
        "captures": captures(),
        "profiler_sessions": profiler_sessions(),
    }
    if step_seconds:
        out["step_ms"] = round(step_seconds * 1e3, 3)
        out["off_path_pct_of_step"] = round(
            100.0 * off / step_seconds, 5)
    lr = _last_report
    if lr is not None:
        out["last_capture"] = {"label": lr["label"],
                               "wall_s": lr["capture_wall_s"],
                               "comm_share": lr["comm"]["comm_share"]}
    return out


def comm_report(n_devices: int = 8, hidden: int = 256,
                features: int = 64, classes: int = 16
                ) -> Dict[str, Any]:
    """The ``comm`` section of ``bench.py`` / the dossier
    ``comm_observatory`` row: the ZeRO sharded-update step's wire
    ledger on the live device set, gated against the PR 5 HLO byte
    model — reduce-scatter result bytes ≈ grad_bytes/N under the
    ``zero.reduce_scatter`` scope, all-gather result bytes ≈
    param_bytes under ``zero.all_gather``. Plus the off-path fence
    numbers."""
    import jax
    import jax.numpy as jnp
    import numpy as np
    from jax.sharding import NamedSharding, PartitionSpec as P

    from deeplearning4j_tpu.parallel.zero import supports_psum_scatter

    n = int(n_devices)
    if len(jax.devices()) < n or n < 2:
        return {"skipped": True,
                "reason": f"needs {n} devices, have {len(jax.devices())}"}
    if not supports_psum_scatter():
        return {"skipped": True, "reason": "no lax.psum_scatter"}
    from deeplearning4j_tpu.nn import (MultiLayerNetwork,
                                       NeuralNetConfiguration)
    from deeplearning4j_tpu.nn.config import InputType
    from deeplearning4j_tpu.nn.layers import DenseLayer, OutputLayer
    from deeplearning4j_tpu.nn import updaters as upd
    from deeplearning4j_tpu.parallel import ParallelWrapper

    conf = (NeuralNetConfiguration.builder().seed(5)
            .updater(upd.Adam(learning_rate=1e-3)).list()
            .layer(DenseLayer(n_out=hidden, activation="relu"))
            .layer(DenseLayer(n_out=hidden, activation="relu"))
            .layer(OutputLayer(n_out=classes, activation="softmax",
                               loss="mcxent"))
            .set_input_type(InputType.feed_forward(features)).build())
    net = MultiLayerNetwork(conf).init()
    w = ParallelWrapper(net, workers=n, sharded_update=True)
    w._prepare()
    dshard = NamedSharding(w.mesh, P("data"))
    b = 8 * n
    x = jax.device_put(jnp.zeros((b, features), jnp.float32), dshard)
    y = jax.device_put(jnp.zeros((b, classes), jnp.float32), dshard)
    args = (net.params, w._dp_state, net.state, x, y,
            jax.random.PRNGKey(0))
    compiled = w._step.lower(*args).compile()
    led = wire_ledger([compiled], n_devices=n)
    p_bytes = sum(int(np.prod(p.shape)) * p.dtype.itemsize
                  for p in jax.tree_util.tree_leaves(net.params))
    peak = peak_ici_from_env()
    rs = led["by_scope"].get("zero.reduce_scatter",
                             {"tensor_bytes": 0.0, "wire_bytes": 0.0})
    ag = led["by_scope"].get("zero.all_gather",
                             {"tensor_bytes": 0.0, "wire_bytes": 0.0})
    return {
        "n_devices": n,
        "platform": jax.devices()[0].platform,
        "model": f"mlp {features}-{hidden}-{hidden}-{classes} adam "
                 "(ZeRO sharded update)",
        "param_bytes": p_bytes,
        "grad_bytes": p_bytes,     # f32 grads mirror f32 params
        "scopes": {k: {"tensor_bytes": v["tensor_bytes"],
                       "wire_bytes": v["wire_bytes"],
                       "kinds": v["kinds"]}
                   for k, v in sorted(led["by_scope"].items())},
        "wire_bytes_per_step": led["wire_bytes"],
        "t_ici_ms": round(led["wire_bytes"] / peak * 1e3, 4),
        "peak_ici_gbs": peak / 1e9,
        # the PR 5 HLO gates, through the ledger's scope join
        "gates": {
            "reduce_scatter_tensor_over_grad_shard": round(
                rs["tensor_bytes"] / (p_bytes / n), 4)
            if p_bytes else None,
            "all_gather_tensor_over_params": round(
                ag["tensor_bytes"] / p_bytes, 4) if p_bytes else None,
        },
        "off_path": measure_capture_overhead(iters=2000),
    }


def subprocess_report(timeout: int = 420,
                      n_devices: int = 8) -> Dict[str, Any]:
    """Run :func:`comm_report` in a fresh process on ``n_devices``
    forced CPU host devices — callable from single-device bench runs
    (bench.py, perf_dossier) without touching their backend. Returns
    the report dict, or ``{"skipped": True, ...}`` on any failure."""
    import json
    import os
    import subprocess
    import sys

    env = dict(os.environ)
    flags = env.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in flags:
        flags = (flags + " --xla_force_host_platform_device_count="
                 f"{n_devices}").strip()
    env["XLA_FLAGS"] = flags
    env["JAX_PLATFORMS"] = "cpu"
    try:
        proc = subprocess.run(
            [sys.executable, "-m",
             "deeplearning4j_tpu.obs.commtime"],
            capture_output=True, text=True, timeout=timeout, env=env,
            cwd=os.path.dirname(os.path.dirname(
                os.path.dirname(os.path.abspath(__file__)))))
    except (subprocess.TimeoutExpired, OSError) as e:
        return {"skipped": True, "reason": f"comm child: {e}"}
    parsed = None
    for line in proc.stdout.splitlines():
        line = line.strip()
        if line.startswith("{"):
            try:
                parsed = json.loads(line)
            except ValueError:
                pass
    if proc.returncode != 0 or parsed is None:
        tail = (proc.stderr or proc.stdout or "").strip()
        return {"skipped": True,
                "reason": "comm child rc=%d: %s"
                          % (proc.returncode, tail.splitlines()[-1]
                             if tail else "no output")}
    return parsed


def _main() -> None:
    # sitecustomize forces the axon TPU platform and overrides
    # JAX_PLATFORMS; pin CPU before any device query so the
    # measurement never waits on the TPU tunnel
    import json

    import jax

    try:
        jax.config.update("jax_platforms", "cpu")
    except RuntimeError:
        pass
    print(json.dumps(comm_report()))


if __name__ == "__main__":
    _main()


__all__ = ["COLLECTIVE_KINDS", "collective_kind", "collective_records",
           "wire_ledger", "ring_wire_bytes", "parse_replica_groups",
           "parse_source_target_pairs", "peak_ici_from_env",
           "comm_view", "attribute", "capture", "Observatory",
           "configure", "configure_from_env", "disable",
           "step_started", "step_ended", "captures",
           "profiler_sessions", "reset_counters", "last_report",
           "measure_capture_overhead", "comm_report",
           "subprocess_report", "WIRE_BOUND_SHARE"]
