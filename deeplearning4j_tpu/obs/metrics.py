"""Metrics registry — counters/gauges/histograms with Prometheus text
exposition served from a stdlib HTTP ``/metrics`` + ``/healthz``.

Reference: ``StatsListener``'s system/score metrics and
``PerformanceListener`` throughput lines (SURVEY §5) — but those are
per-listener, per-training-run views. This registry is *process-wide*:
the fit loops, data iterators, ``ParallelWrapper``,
``ParallelInference``, the retrace sentry, and the persistent compile
cache all publish into one namespace, scraped over HTTP in the
standard Prometheus text format (the serving-fleet story the north
star needs) and snapshotted into ``obs.report()`` for bench/dossier/
crash dumps.

Naming scheme (``dl4j_tpu_<subsystem>_<name>_<unit>``):

- ``dl4j_tpu_step_latency_seconds{entry=...}`` — per-entry-point step
  histogram (``MultiLayerNetwork.fit``, ``ComputationGraph.fit``, ...)
- ``dl4j_tpu_h2d_seconds_total`` / ``dl4j_tpu_device_sync_seconds_total``
  — where the step went (host→device feed vs blocking device sync)
- ``dl4j_tpu_fit_etl_seconds_total`` / ``dl4j_tpu_prefetch_*`` — ETL
- ``dl4j_tpu_worker_*{worker=...}`` — ParallelWrapper per-worker step
  latency, collective-sync wall time, heartbeat age / staleness
- ``dl4j_tpu_inference_*`` — ParallelInference queue depth, request
  latency, batch sizes
- ``dl4j_tpu_retrace_*`` / ``dl4j_tpu_compile_*`` — the perf
  subsystem's sentry and persistent-cache counters, re-exported as
  first-class families by a pull-time collector (no double counting:
  ``perf/`` stays the source of truth).

The server reuses the ``train/stats.py`` pattern: stdlib
``ThreadingHTTPServer``, ephemeral-port friendly, daemon thread.
"""
from __future__ import annotations

import json
import re
import threading
from typing import Any, Callable, Dict, Iterable, List, Optional, Tuple

from deeplearning4j_tpu.obs import trace as _trace

# latency buckets (seconds): sub-ms dispatch floors through multi-s
# compiles
LATENCY_BUCKETS = (0.0005, 0.001, 0.0025, 0.005, 0.01, 0.025, 0.05,
                   0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0)
SIZE_BUCKETS = (1, 2, 4, 8, 16, 32, 64, 128, 256)

#: THE metric-family registry: every ``dl4j_tpu_*`` family name in the
#: package — registered families, pull-time collector families, and
#: the fleet aggregator's computed families — declared ONCE here.
#: ``tools/lint_instrumentation.py`` rule 6 keeps this table, the emit
#: sites, ``tools/tpu_watch.py``, and ``docs/OPS.md`` in lockstep so a
#: family can't drift into three spellings across producers and
#: consumers. Add the name here FIRST when introducing a family.
FAMILIES = {
    # fit/serve hot paths (this module)
    "dl4j_tpu_step_latency_seconds": "histogram",
    "dl4j_tpu_steps_total": "counter",
    "dl4j_tpu_h2d_seconds_total": "counter",
    "dl4j_tpu_device_sync_seconds_total": "counter",
    "dl4j_tpu_fit_etl_seconds_total": "counter",
    "dl4j_tpu_prefetch_wait_seconds_total": "counter",
    "dl4j_tpu_prefetch_depth": "gauge",
    "dl4j_tpu_worker_step_latency_seconds": "histogram",
    "dl4j_tpu_worker_collective_sync_seconds_total": "counter",
    "dl4j_tpu_inference_requests_total": "counter",
    "dl4j_tpu_inference_request_latency_seconds": "histogram",
    "dl4j_tpu_inference_queue_depth": "gauge",
    "dl4j_tpu_inference_batch_size": "histogram",
    # resilience + elastic membership
    "dl4j_tpu_resilience_restarts_total": "counter",
    "dl4j_tpu_inference_requests_shed_total": "counter",
    "dl4j_tpu_checkpoints_quarantined_total": "counter",
    "dl4j_tpu_faults_injected_total": "counter",
    "dl4j_tpu_preemptions_total": "counter",
    "dl4j_tpu_mesh_epoch": "gauge",
    "dl4j_tpu_hosts_evicted_total": "counter",
    # parallel training
    "dl4j_tpu_opt_state_bytes_per_device": "gauge",
    # perf collector (retrace sentry + persistent compile cache)
    "dl4j_tpu_retrace_traces_total": "counter",
    "dl4j_tpu_retrace_unplanned_shapes": "gauge",
    "dl4j_tpu_retrace_compiles_total": "counter",
    "dl4j_tpu_aot_hits_total": "counter",
    "dl4j_tpu_compile_time_seconds_total": "counter",
    "dl4j_tpu_compile_cache_requests_total": "counter",
    "dl4j_tpu_compile_cache_hits_total": "counter",
    # worker/host health collector
    "dl4j_tpu_worker_heartbeat_age_seconds": "gauge",
    "dl4j_tpu_worker_stale": "gauge",
    # numerics observatory (obs/numerics.py)
    "dl4j_tpu_numerics_grad_norm": "gauge",
    "dl4j_tpu_numerics_update_ratio": "gauge",
    "dl4j_tpu_numerics_activation_absmax": "gauge",
    "dl4j_tpu_numerics_replica_divergence": "gauge",
    "dl4j_tpu_numerics_param_replica_divergence": "gauge",
    "dl4j_tpu_numerics_nonfinite_total": "counter",
    "dl4j_tpu_numerics_diag_steps_total": "counter",
    # continuous-batching serving gateway (serving/)
    "dl4j_tpu_serving_requests_total": "counter",
    "dl4j_tpu_serving_requests_shed_total": "counter",
    "dl4j_tpu_serving_tokens_total": "counter",
    "dl4j_tpu_serving_ttft_seconds": "histogram",
    "dl4j_tpu_serving_step_seconds": "histogram",
    "dl4j_tpu_serving_prefill_seconds": "histogram",
    "dl4j_tpu_serving_active_slots": "gauge",
    "dl4j_tpu_serving_queue_depth": "gauge",
    "dl4j_tpu_serving_kv_pages_free": "gauge",
    "dl4j_tpu_serving_kv_page_occupancy": "gauge",
    "dl4j_tpu_serving_kv_pages_reserved": "gauge",
    # speculative multi-token decode (serving/scheduler.py)
    "dl4j_tpu_serving_spec_accept_rate": "histogram",
    "dl4j_tpu_serving_spec_drafted_total": "counter",
    "dl4j_tpu_serving_spec_accepted_total": "counter",
    # copy-on-write prefix sharing (serving/kv_pager.py)
    "dl4j_tpu_serving_prefix_hits_total": "counter",
    "dl4j_tpu_serving_prefix_prefill_tokens_saved_total": "counter",
    "dl4j_tpu_serving_prefix_shared_pages": "gauge",
    "dl4j_tpu_serving_prefix_cow_copies_total": "counter",
    # device-time observatory (obs/devtime.py)
    "dl4j_tpu_devtime_captures_total": "counter",
    "dl4j_tpu_devtime_capture_seconds_total": "counter",
    "dl4j_tpu_devtime_scope_seconds": "gauge",
    "dl4j_tpu_devtime_scope_share": "gauge",
    "dl4j_tpu_devtime_scope_utilization": "gauge",
    "dl4j_tpu_devtime_scope_pallas_candidate": "gauge",
    "dl4j_tpu_devtime_pallas_candidates": "gauge",
    # communication observatory (obs/commtime.py)
    "dl4j_tpu_comm_captures_total": "counter",
    "dl4j_tpu_comm_capture_seconds_total": "counter",
    "dl4j_tpu_comm_scope_wire_bytes_per_step": "gauge",
    "dl4j_tpu_comm_scope_collective_seconds": "gauge",
    "dl4j_tpu_comm_scope_step_share": "gauge",
    "dl4j_tpu_comm_scope_link_utilization": "gauge",
    "dl4j_tpu_comm_op_count": "gauge",
    "dl4j_tpu_comm_wire_bound_scopes": "gauge",
    # fleet observability plane (obs/fleet.py)
    "dl4j_tpu_fleet_snapshots_published_total": "counter",
    "dl4j_tpu_flight_recorder_dumps_total": "counter",
    "dl4j_tpu_collective_skew_seconds": "gauge",
    "dl4j_tpu_collective_straggler": "gauge",
    "dl4j_tpu_fleet_hosts": "gauge",
    "dl4j_tpu_fleet_snapshot_age_seconds": "gauge",
    # elastic serving fleet: front-end router (serving/fleet.py)
    "dl4j_tpu_router_requests_total": "counter",
    "dl4j_tpu_router_sheds_total": "counter",
    "dl4j_tpu_router_reroutes_total": "counter",
    "dl4j_tpu_router_replicas_ready": "gauge",
    # elastic serving fleet: replica lifecycle (serving/fleet.py +
    # obs/fleet.py serving aggregation)
    "dl4j_tpu_serving_fleet_spawns_total": "counter",
    "dl4j_tpu_serving_fleet_evictions_total": "counter",
    "dl4j_tpu_serving_fleet_warm_buckets": "gauge",
    "dl4j_tpu_serving_fleet_replica_ready": "gauge",
}


def _escape(v: str) -> str:
    return str(v).replace("\\", r"\\").replace("\n", r"\n") \
        .replace('"', r'\"')


def _label_str(labels: Dict[str, str]) -> str:
    if not labels:
        return ""
    inner = ",".join(f'{k}="{_escape(v)}"'
                     for k, v in sorted(labels.items()))
    return "{" + inner + "}"


class _Child:
    """One labelset's state. ``inc``/``set``/``observe`` are the hot
    path — a lock, a float add, and (histograms) one linear bucket
    scan over ~14 bounds."""

    __slots__ = ("_m", "value", "counts", "sum", "count", "fn")

    def __init__(self, metric: "Metric"):
        self._m = metric
        self.value = 0.0
        self.fn: Optional[Callable[[], float]] = None
        if metric.kind == "histogram":
            self.counts = [0] * len(metric.buckets)
            self.sum = 0.0
            self.count = 0

    def inc(self, amount: float = 1.0):
        with self._m._lock:
            self.value += amount

    def dec(self, amount: float = 1.0):
        self.inc(-amount)

    def set(self, value: float):
        with self._m._lock:
            self.value = float(value)

    def set_function(self, fn: Callable[[], float]):
        """Gauge evaluated at scrape time (queue depths, ages)."""
        self.fn = fn

    def observe(self, value: float):
        m = self._m
        with m._lock:
            self.sum += value
            self.count += 1
            for i, b in enumerate(m.buckets):
                if value <= b:
                    self.counts[i] += 1
                    break

    def get(self) -> float:
        if self.fn is not None:
            try:
                return float(self.fn())
            except Exception:
                return float("nan")
        return self.value


class Metric:
    """One metric family (counter | gauge | histogram), optionally
    labelled. ``labels(**kv)`` returns the cached per-labelset child;
    un-labelled families proxy the operations directly."""

    def __init__(self, kind: str, name: str, doc: str,
                 labelnames: Tuple[str, ...] = (),
                 buckets: Tuple[float, ...] = LATENCY_BUCKETS):
        self.kind = kind
        self.name = name
        self.doc = doc
        self.labelnames = tuple(labelnames)
        self.buckets = tuple(sorted(buckets))
        self._lock = threading.Lock()
        self._children: Dict[Tuple[str, ...], _Child] = {}
        if not self.labelnames:
            self._children[()] = _Child(self)

    def labels(self, **kv: str) -> _Child:
        key = tuple(str(kv[n]) for n in self.labelnames)
        child = self._children.get(key)
        if child is None:
            with self._lock:
                child = self._children.setdefault(key, _Child(self))
        return child

    # un-labelled convenience
    def inc(self, amount: float = 1.0):
        self._children[()].inc(amount)

    def dec(self, amount: float = 1.0):
        self._children[()].dec(amount)

    def set(self, value: float):
        self._children[()].set(value)

    def set_function(self, fn: Callable[[], float]):
        self._children[()].set_function(fn)

    def observe(self, value: float):
        self._children[()].observe(value)

    # -- exposition ------------------------------------------------------
    def _samples(self) -> Iterable[Tuple[str, Dict[str, str], float]]:
        with self._lock:
            items = list(self._children.items())
        for key, child in items:
            labels = dict(zip(self.labelnames, key))
            if self.kind == "histogram":
                cum = 0
                for b, c in zip(self.buckets, child.counts):
                    cum += c
                    yield (self.name + "_bucket",
                           {**labels, "le": repr(float(b))}, cum)
                yield (self.name + "_bucket",
                       {**labels, "le": "+Inf"}, child.count)
                yield (self.name + "_sum", labels, child.sum)
                yield (self.name + "_count", labels, child.count)
            else:
                yield (self.name, labels, child.get())

    def snapshot(self) -> Dict[str, Any]:
        out: Dict[str, Any] = {}
        with self._lock:
            items = list(self._children.items())
        for key, child in items:
            lk = _label_str(dict(zip(self.labelnames, key))) or ""
            if self.kind == "histogram":
                out[lk] = {"count": child.count, "sum": child.sum}
            else:
                out[lk] = child.get()
        return out


class MetricsRegistry:
    def __init__(self):
        self._lock = threading.Lock()
        self._metrics: Dict[str, Metric] = {}
        self._collectors: List[Callable[[], Iterable]] = []

    def _get_or_create(self, kind, name, doc, labelnames, buckets
                       ) -> Metric:
        with self._lock:
            m = self._metrics.get(name)
            if m is None:
                m = Metric(kind, name, doc, labelnames, buckets)
                self._metrics[name] = m
            elif m.kind != kind:
                raise ValueError(
                    f"metric {name!r} already registered as {m.kind}")
            return m

    def counter(self, name, doc, labelnames=()) -> Metric:
        return self._get_or_create("counter", name, doc, labelnames,
                                   LATENCY_BUCKETS)

    def gauge(self, name, doc, labelnames=()) -> Metric:
        return self._get_or_create("gauge", name, doc, labelnames,
                                   LATENCY_BUCKETS)

    def histogram(self, name, doc, labelnames=(),
                  buckets=LATENCY_BUCKETS) -> Metric:
        return self._get_or_create("histogram", name, doc, labelnames,
                                   buckets)

    def register_collector(self, fn: Callable[[], Iterable]) -> None:
        """``fn()`` → iterable of ``(name, kind, doc, samples)`` with
        ``samples = [(labels_dict, value), ...]``, evaluated at scrape
        time — how external counter sources (retrace sentry, compile
        cache, worker health) join the namespace without double
        bookkeeping."""
        with self._lock:
            self._collectors.append(fn)

    def _collected(self) -> List[Tuple[str, str, str, list]]:
        with self._lock:
            collectors = list(self._collectors)
        out = []
        for fn in collectors:
            try:
                out.extend(fn())
            except Exception:
                continue            # a broken collector never breaks /metrics
        return out

    def exposition(self) -> str:
        """Prometheus text exposition format 0.0.4."""
        lines: List[str] = []
        with self._lock:
            metrics = list(self._metrics.values())
        for m in sorted(metrics, key=lambda m: m.name):
            lines.append(f"# HELP {m.name} {_escape(m.doc)}")
            lines.append(f"# TYPE {m.name} {m.kind}")
            for name, labels, value in m._samples():
                lines.append(f"{name}{_label_str(labels)} {value}")
        for name, kind, doc, samples in sorted(self._collected()):
            lines.append(f"# HELP {name} {_escape(doc)}")
            lines.append(f"# TYPE {name} {kind}")
            for labels, value in samples:
                lines.append(f"{name}{_label_str(labels)} {value}")
        return "\n".join(lines) + "\n"

    def snapshot(self) -> Dict[str, Any]:
        """Plain-dict view of every family (registry metrics + collector
        families) — the ``metrics`` section of ``obs.report()``."""
        with self._lock:
            metrics = dict(self._metrics)
        out: Dict[str, Any] = {
            name: {"type": m.kind, "values": m.snapshot()}
            for name, m in metrics.items()}
        for name, kind, _doc, samples in self._collected():
            out[name] = {"type": kind, "values": {
                _label_str(labels) or "": value
                for labels, value in samples}}
        return out

    def reset(self) -> None:
        """Tests only: zero every family IN PLACE (collectors kept).
        The family objects stay registered — module-level handles like
        ``STEP_SECONDS`` keep working — only their labelsets/values are
        dropped; clearing ``_metrics`` instead would orphan every
        standing handle and silently swallow later instrumentation."""
        with self._lock:
            metrics = list(self._metrics.values())
        for m in metrics:
            with m._lock:
                m._children.clear()
                if not m.labelnames:
                    m._children[()] = _Child(m)


REGISTRY = MetricsRegistry()


def snapshot() -> Dict[str, Any]:
    return REGISTRY.snapshot()


def exposition() -> str:
    return REGISTRY.exposition()

# -- the package's standing instrumentation families -------------------------

STEP_SECONDS = REGISTRY.histogram(
    "dl4j_tpu_step_latency_seconds",
    "end-to-end train/serve step latency (h2d + dispatch + sync)",
    ("entry",))
STEPS = REGISTRY.counter(
    "dl4j_tpu_steps_total", "completed steps per entry point", ("entry",))
H2D_SECONDS = REGISTRY.counter(
    "dl4j_tpu_h2d_seconds_total",
    "host->device feed time (array conversion/stacking)", ("entry",))
SYNC_SECONDS = REGISTRY.counter(
    "dl4j_tpu_device_sync_seconds_total",
    "blocking device sync time (loss/result to host)", ("entry",))
FIT_ETL_SECONDS = REGISTRY.counter(
    "dl4j_tpu_fit_etl_seconds_total",
    "time the fit loop waited on its data iterator", ("entry",))
PREFETCH_WAIT = REGISTRY.counter(
    "dl4j_tpu_prefetch_wait_seconds_total",
    "consumer wait on the AsyncDataSetIterator queue")
PREFETCH_DEPTH = REGISTRY.gauge(
    "dl4j_tpu_prefetch_depth",
    "AsyncDataSetIterator queue depth after the last get")
WORKER_STEP = REGISTRY.histogram(
    "dl4j_tpu_worker_step_latency_seconds",
    "ParallelWrapper per-worker step latency", ("worker",))
WORKER_SYNC = REGISTRY.counter(
    "dl4j_tpu_worker_collective_sync_seconds_total",
    "ParallelWrapper wait for step + averaging/all-reduce completion",
    ("worker",))
INFER_REQS = REGISTRY.counter(
    "dl4j_tpu_inference_requests_total",
    "ParallelInference requests enqueued")
INFER_LATENCY = REGISTRY.histogram(
    "dl4j_tpu_inference_request_latency_seconds",
    "enqueue->result latency per request")
INFER_QUEUE = REGISTRY.gauge(
    "dl4j_tpu_inference_queue_depth",
    "ParallelInference request queue depth")
INFER_BATCH = REGISTRY.histogram(
    "dl4j_tpu_inference_batch_size",
    "examples per dispatched serving batch", buckets=SIZE_BUCKETS)

# resilience subsystem (resilience/ + train/fault_tolerance.py)
RESILIENCE_RESTARTS = REGISTRY.counter(
    "dl4j_tpu_resilience_restarts_total",
    "restore-and-continue restarts by FaultTolerantTrainer")
REQS_SHED = REGISTRY.counter(
    "dl4j_tpu_inference_requests_shed_total",
    "serving requests shed instead of served", ("reason",))
CKPT_QUARANTINED = REGISTRY.counter(
    "dl4j_tpu_checkpoints_quarantined_total",
    "corrupt/partial checkpoints moved to corrupt/")
FAULTS_INJECTED = REGISTRY.counter(
    "dl4j_tpu_faults_injected_total",
    "faults fired by the DL4J_TPU_FAULT_PLAN harness", ("site",))
PREEMPTIONS = REGISTRY.counter(
    "dl4j_tpu_preemptions_total",
    "SIGTERM preemption notices honored (checkpoint-and-exit)")

# elastic multi-host training (resilience/elastic.py): the committed
# membership generation every step is stamped with, and the hosts the
# coordinator has evicted (missed lease / SIGTERM departure)
MESH_EPOCH = REGISTRY.gauge(
    "dl4j_tpu_mesh_epoch",
    "committed mesh-membership generation this host trains under")
HOSTS_EVICTED = REGISTRY.counter(
    "dl4j_tpu_hosts_evicted_total",
    "hosts forcibly evicted from the fleet after a missed lease "
    "(graceful SIGTERM departures count preemptions_total instead)")

# continuous-batching serving gateway (serving/): in-flight batched
# decode over the paged KV cache — TTFT is the serving SLO metric
# (queue wait + prefill), step_seconds is the per-token latency every
# active slot pays per decode iteration, kv_pages_free is the
# admission-control currency
SERVING_REQS = REGISTRY.counter(
    "dl4j_tpu_serving_requests_total",
    "gateway requests submitted (per tenant)", ("tenant",))
SERVING_SHED = REGISTRY.counter(
    "dl4j_tpu_serving_requests_shed_total",
    "gateway requests shed instead of served", ("reason",))
SERVING_TOKENS = REGISTRY.counter(
    "dl4j_tpu_serving_tokens_total",
    "tokens streamed by the continuous-batching gateway")
SERVING_TTFT = REGISTRY.histogram(
    "dl4j_tpu_serving_ttft_seconds",
    "submit -> first streamed token (queue wait + paged prefill)")
SERVING_STEP = REGISTRY.histogram(
    "dl4j_tpu_serving_step_seconds",
    "one fixed-shape continuous-batching decode iteration (== the "
    "per-token latency of every active slot)")
SERVING_PREFILL = REGISTRY.histogram(
    "dl4j_tpu_serving_prefill_seconds",
    "prompt prefill-into-pages wall time per admission")
SERVING_SLOTS = REGISTRY.gauge(
    "dl4j_tpu_serving_active_slots",
    "decode slots occupied by in-flight sequences")
SERVING_QUEUE = REGISTRY.gauge(
    "dl4j_tpu_serving_queue_depth",
    "requests queued awaiting admission (all tenants)")
SERVING_PAGES_FREE = REGISTRY.gauge(
    "dl4j_tpu_serving_kv_pages_free",
    "free pages in the paged KV-cache pool")
SERVING_KV_OCCUPANCY = REGISTRY.gauge(
    "dl4j_tpu_serving_kv_page_occupancy",
    "fraction of usable KV pages currently reserved by live "
    "sequences (1.0 = admission-control full)")
SERVING_KV_RESERVED = REGISTRY.gauge(
    "dl4j_tpu_serving_kv_pages_reserved",
    "KV pages reserved per tenant (whole-life reservations, the "
    "admission-control currency)", ("tenant",))

# speculative multi-token decode + copy-on-write prefix sharing
# (serving/scheduler.py + serving/kv_pager.py): accept rate is the
# fraction of drafted tokens the verify step confirmed (1.0 = every
# draft landed — the k-for-one win), prefix counters record admissions
# that rode an existing page chain and the prefill tokens that saved
SERVING_SPEC_ACCEPT = REGISTRY.histogram(
    "dl4j_tpu_serving_spec_accept_rate",
    "per-slot fraction of drafted tokens accepted by one verify step",
    buckets=(0.125, 0.25, 0.375, 0.5, 0.625, 0.75, 0.875, 1.0))
SERVING_SPEC_DRAFTED = REGISTRY.counter(
    "dl4j_tpu_serving_spec_drafted_total",
    "tokens drafted by the host-side prompt-lookup draft")
SERVING_SPEC_ACCEPTED = REGISTRY.counter(
    "dl4j_tpu_serving_spec_accepted_total",
    "drafted tokens accepted by the batched verify step")
SERVING_PREFIX_HITS = REGISTRY.counter(
    "dl4j_tpu_serving_prefix_hits_total",
    "admissions that mapped a shared prompt prefix onto an existing "
    "page chain (prefill ran only on the novel suffix)")
SERVING_PREFIX_SAVED = REGISTRY.counter(
    "dl4j_tpu_serving_prefix_prefill_tokens_saved_total",
    "prompt tokens NOT prefilled because their pages were shared")
SERVING_PREFIX_SHARED = REGISTRY.gauge(
    "dl4j_tpu_serving_prefix_shared_pages",
    "KV pages currently referenced by more than one live sequence")
SERVING_PREFIX_COW = REGISTRY.counter(
    "dl4j_tpu_serving_prefix_cow_copies_total",
    "copy-on-write page copies (a write hit a shared page)")

# elastic serving fleet (serving/fleet.py): the front-end router's
# admission/shed/re-route ledger plus the replica-lifecycle counters
# the autoscale drill asserts against (ARCHITECTURE.md §20)
ROUTER_REQS = REGISTRY.counter(
    "dl4j_tpu_router_requests_total",
    "requests the front-end router forwarded, by replica",
    ("replica",))
ROUTER_SHEDS = REGISTRY.counter(
    "dl4j_tpu_router_sheds_total",
    "in-flight streams structurally shed by the router (every one "
    "surfaced as SequenceAborted — never a hung client)", ("reason",))
ROUTER_REROUTES = REGISTRY.counter(
    "dl4j_tpu_router_reroutes_total",
    "requests re-submitted to a different replica after their first "
    "replica died or refused")
ROUTER_READY = REGISTRY.gauge(
    "dl4j_tpu_router_replicas_ready",
    "replicas the router currently considers routable (lease live "
    "AND warmup-ready)")
FLEET_SPAWNS = REGISTRY.counter(
    "dl4j_tpu_serving_fleet_spawns_total",
    "replicas spawned by the fleet supervisor to restore target "
    "capacity after an eviction")
FLEET_EVICTIONS = REGISTRY.counter(
    "dl4j_tpu_serving_fleet_evictions_total",
    "serving replicas evicted from the membership plane (lease "
    "expired)")
FLEET_WARM_BUCKETS = REGISTRY.gauge(
    "dl4j_tpu_serving_fleet_warm_buckets",
    "warmup buckets this replica has AOT-compiled (readiness = all "
    "declared buckets warm)")

# device-time observatory (obs/devtime.py): short profiler windows
# attributed to the named_scope'd layers — the instrument that names
# the Pallas gaps (ARCHITECTURE.md §16)
DEVTIME_CAPTURES = REGISTRY.counter(
    "dl4j_tpu_devtime_captures_total",
    "completed device-time capture-and-attribute pipelines")
DEVTIME_CAPTURE_SECONDS = REGISTRY.counter(
    "dl4j_tpu_devtime_capture_seconds_total",
    "wall time spent inside capture windows (profiler session + "
    "xplane parse + attribution) — the capture-cost budget meter")
DEVTIME_SCOPE_SECONDS = REGISTRY.gauge(
    "dl4j_tpu_devtime_scope_seconds",
    "device seconds per scope over the LAST capture window",
    ("scope",))
DEVTIME_SCOPE_SHARE = REGISTRY.gauge(
    "dl4j_tpu_devtime_scope_share",
    "share of measured device time per scope (last capture)",
    ("scope",))
DEVTIME_SCOPE_UTILIZATION = REGISTRY.gauge(
    "dl4j_tpu_devtime_scope_utilization",
    "achieved-vs-roofline utilization of the binding resource per "
    "scope (last capture; DL4J_TPU_PEAK_TFLOPS/_PEAK_HBM_GBS peaks)",
    ("scope",))
DEVTIME_SCOPE_CANDIDATE = REGISTRY.gauge(
    "dl4j_tpu_devtime_scope_pallas_candidate",
    "1 when the last gap report flagged this scope as a Pallas "
    "candidate (the AUTHORITATIVE flag — consumers must read it, "
    "not re-derive the rule)", ("scope",))
DEVTIME_PALLAS_CANDIDATES = REGISTRY.gauge(
    "dl4j_tpu_devtime_pallas_candidates",
    "scopes the last gap report flagged as Pallas-kernel candidates "
    "(high share, low utilization, not already a custom call)")

# communication observatory (obs/commtime.py): the wire sibling of the
# devtime plane — per-scope collective time, static HLO wire bytes,
# and interconnect-roofline utilization (ARCHITECTURE.md §19)
COMM_CAPTURES = REGISTRY.counter(
    "dl4j_tpu_comm_captures_total",
    "completed communication capture-and-attribute pipelines")
COMM_CAPTURE_SECONDS = REGISTRY.counter(
    "dl4j_tpu_comm_capture_seconds_total",
    "wall time spent inside comm capture windows (profiler session + "
    "xplane parse + ledger join)")
COMM_SCOPE_WIRE_BYTES = REGISTRY.gauge(
    "dl4j_tpu_comm_scope_wire_bytes_per_step",
    "ring-model wire bytes per step per scope from the static HLO "
    "ledger of the captured executables (last capture)", ("scope",))
COMM_SCOPE_SECONDS = REGISTRY.gauge(
    "dl4j_tpu_comm_scope_collective_seconds",
    "device seconds spent inside collective ops per scope over the "
    "LAST capture window", ("scope",))
COMM_SCOPE_SHARE = REGISTRY.gauge(
    "dl4j_tpu_comm_scope_step_share",
    "share of total measured device time this scope spent in "
    "collectives (last capture) — the WIRE_BOUND alarm input",
    ("scope",))
COMM_SCOPE_LINK_UTILIZATION = REGISTRY.gauge(
    "dl4j_tpu_comm_scope_link_utilization",
    "achieved interconnect GB/s over DL4J_TPU_PEAK_ICI_GBS per scope "
    "(last capture; estimate-only off TPU)", ("scope",))
COMM_OP_COUNT = REGISTRY.gauge(
    "dl4j_tpu_comm_op_count",
    "collective op executions per kind over the last capture window",
    ("kind",))
COMM_WIRE_BOUND_SCOPES = REGISTRY.gauge(
    "dl4j_tpu_comm_wire_bound_scopes",
    "scopes the last comm capture flagged wire-bound (collective time "
    "dominates the scope's device time) — 1 per flagged scope, the "
    "AUTHORITATIVE flag set tpu_watch --comm renders", ("scope",))

# parallel training (parallel/wrapper.py): the optimizer-state HBM
# footprint the ZeRO sharded update divides by N — layout is
# "replicated" (every device holds full moments) or "sharded" (1/N)
OPT_STATE_BYTES = REGISTRY.gauge(
    "dl4j_tpu_opt_state_bytes_per_device",
    "optimizer-state bytes resident per device for the active "
    "ParallelWrapper training layout", ("layout",))


def drop_entry(entry: str) -> None:
    """Remove one ``entry`` labelset from every per-entry family —
    used by ``obs.overhead_report`` to scrub its probe iterations so
    synthetic samples never reach /metrics or step summaries."""
    for fam in (STEP_SECONDS, STEPS, H2D_SECONDS, SYNC_SECONDS,
                FIT_ETL_SECONDS):
        with fam._lock:
            fam._children.pop((entry,), None)


def observe_step(entry: str, dt: float, h2d: float = 0.0,
                 sync: float = 0.0) -> None:
    """One call per completed step — the always-on metrics half of
    ``obs.record_step`` (a handful of dict lookups and float adds)."""
    STEP_SECONDS.labels(entry=entry).observe(dt)
    STEPS.labels(entry=entry).inc()
    if h2d:
        H2D_SECONDS.labels(entry=entry).inc(h2d)
    if sync:
        SYNC_SECONDS.labels(entry=entry).inc(sync)


def step_summary() -> Dict[str, Dict[str, float]]:
    """Per-entry {count, mean_ms} — the compact step view embedded in
    StatsListener records."""
    out = {}
    for lk, s in STEP_SECONDS.snapshot().items():
        if not s["count"]:
            continue
        entry = lk[len('{entry="'):-2] if lk.startswith('{entry="') \
            else lk
        out[entry] = {"count": s["count"],
                      "mean_ms": s["sum"] / s["count"] * 1e3}
    return out


# -- pull-time collectors: perf subsystem + worker health --------------------

def _perf_collector():
    """Re-export the retrace sentry and persistent compile cache as
    metric families (read at scrape; ``perf/`` owns the counters)."""
    from deeplearning4j_tpu.perf import compile_cache, sentry
    st = sentry.stats()
    rows = list(st.items())
    yield ("dl4j_tpu_retrace_traces_total", "counter",
           "distinct tracings per sentried jit entry point",
           [({"function": n}, s["traces"]) for n, s in rows])
    yield ("dl4j_tpu_retrace_unplanned_shapes", "gauge",
           "distinct UNPLANNED traced shapes (the retrace budget meter)",
           [({"function": n}, s["unplanned_shapes"]) for n, s in rows])
    yield ("dl4j_tpu_retrace_compiles_total", "counter",
           "compiles observed on live calls per entry point",
           [({"function": n}, s["compiles"]) for n, s in rows])
    yield ("dl4j_tpu_aot_hits_total", "counter",
           "live calls served by a warmed AOT executable",
           [({"function": n}, s["aot_hits"]) for n, s in rows])
    yield ("dl4j_tpu_compile_time_seconds_total", "counter",
           "wall time XLA spent compiling sentried entry points",
           [({}, sentry.total_compile_time_s())])
    c = compile_cache.counters()
    yield ("dl4j_tpu_compile_cache_requests_total", "counter",
           "compile requests eligible for the persistent XLA cache",
           [({}, c["compile_requests"])])
    yield ("dl4j_tpu_compile_cache_hits_total", "counter",
           "persistent XLA cache hits", [({}, c["persistent_hits"])])


def _health_collector():
    from deeplearning4j_tpu.obs import health
    chk = health.check()
    yield ("dl4j_tpu_worker_heartbeat_age_seconds", "gauge",
           "seconds since each worker's last heartbeat",
           [({"worker": w}, round(s["age_s"], 3))
            for w, s in chk.items()])
    yield ("dl4j_tpu_worker_stale", "gauge",
           "1 when a worker's heartbeat is older than "
           "DL4J_TPU_STALE_WORKER_SECS",
           [({"worker": w}, int(s["stale"])) for w, s in chk.items()])


REGISTRY.register_collector(_perf_collector)
REGISTRY.register_collector(_health_collector)


# -- scrape-side parser (tpu_watch + tests) ----------------------------------

_SAMPLE_RE = re.compile(
    r'^([a-zA-Z_:][a-zA-Z0-9_:]*)(\{[^}]*\})?\s+([^\s]+)$')
_LABEL_RE = re.compile(r'([a-zA-Z_][a-zA-Z0-9_]*)="((?:[^"\\]|\\.)*)"')


def parse_exposition(text: str) -> Dict[Tuple[str, Tuple], float]:
    """Parse Prometheus text exposition into
    ``{(name, ((label, value), ...)): float}`` — used by
    ``tools/tpu_watch.py`` when scraping a live run and by the tests
    that assert the exposition is well-formed."""
    out: Dict[Tuple[str, Tuple], float] = {}
    for line in text.splitlines():
        line = line.strip()
        if not line or line.startswith("#"):
            continue
        m = _SAMPLE_RE.match(line)
        if not m:
            raise ValueError(f"unparseable exposition line: {line!r}")
        name, labelblob, value = m.groups()
        labels = tuple(sorted(
            (k, v.replace(r'\"', '"').replace(r"\n", "\n")
             .replace(r"\\", "\\"))
            for k, v in _LABEL_RE.findall(labelblob or "")))
        out[(name, labels)] = float(value)
    return out


# -- /metrics + /healthz server ----------------------------------------------

#: readiness probes consulted by ``/healthz``: name -> zero-arg
#: callable returning truthy when ready. Readiness ≠ liveness — a
#: replica that is alive but still AOT-compiling its warmup buckets
#: answers 503 with status "warming", so a router never routes a
#: request that would cold-trace (serving/fleet.py registers one per
#: gateway; empty registry = always ready, the pre-fleet behavior)
_readiness: Dict[str, Any] = {}


def register_readiness(name: str, probe) -> None:
    """Add/replace a named readiness probe (None removes it)."""
    if probe is None:
        _readiness.pop(name, None)
    else:
        _readiness[name] = probe


def readiness() -> Dict[str, bool]:
    """Evaluate every registered probe (a raising probe reads as not
    ready — never as a dropped healthz)."""
    out = {}
    for name, probe in sorted(_readiness.items()):
        try:
            out[name] = bool(probe())
        except Exception:
            out[name] = False
    return out


#: shared elastic dir the ``/fleet`` path aggregates over (None = 404)
_fleet_dir: Optional[str] = None


def set_fleet_dir(directory) -> None:
    """Point the standing server's ``/fleet`` path at a fleet plane's
    shared directory: the endpoint then serves the MERGED fleet
    exposition (every host's families with ``host=``/``mesh_epoch=``
    labels plus collective-skew attribution) next to the per-process
    ``/metrics`` — one server, both altitudes."""
    global _fleet_dir
    _fleet_dir = None if directory is None else str(directory)


class MetricsServer:
    """Stdlib HTTP endpoint: ``/metrics`` (Prometheus text),
    ``/healthz`` (JSON liveness: 200 when no worker is stale, 503
    otherwise), ``/fleet`` (merged fleet exposition when
    :func:`set_fleet_dir` configured one). Pattern shared with
    ``train.stats.UIServer``."""

    def __init__(self, port: int = 0, registry: MetricsRegistry = None):
        self.port = port
        self.registry = registry or REGISTRY
        self._httpd = None
        self._thread = None
        self._t_start = _trace.now()

    def healthz(self) -> Dict[str, Any]:
        from deeplearning4j_tpu.obs import health
        chk = health.check()
        stale = sorted(w for w, s in chk.items() if s["stale"])
        ready = readiness()
        warming = sorted(n for n, ok in ready.items() if not ok)
        status = "ok"
        if warming:
            status = "warming"
        if stale:
            status = "stale_workers"
        return {
            "status": status,
            # readiness gate (serving fleet): probes registered via
            # register_readiness — 503/"warming" until every one is
            # true (a cold replica must not take traffic)
            "ready": not warming,
            "warming": warming,
            # ONE staleness table: worker heartbeats and elastic host
            # leases (mirrored in via health.observe_age with their
            # own lease window) — stale_hosts is the host: subset with
            # the prefix stripped, so a 503 names dying PEERS next to
            # wedged local workers with no divergent verdicts
            "stale_workers": stale,
            "stale_hosts": [w[len("host:"):] for w in stale
                            if w.startswith("host:")],
            "workers": {w: round(s["age_s"], 3)
                        for w, s in chk.items()},
            "uptime_s": round(_trace.now() - self._t_start, 3),
            "tracing": _trace.enabled(),
        }

    def start(self) -> "MetricsServer":
        import http.server

        srv = self

        class Handler(http.server.BaseHTTPRequestHandler):
            def do_GET(self):
                path = self.path.split("?", 1)[0]
                if path == "/metrics":
                    body = srv.registry.exposition().encode()
                    code, ctype = 200, \
                        "text/plain; version=0.0.4; charset=utf-8"
                elif path == "/healthz":
                    h = srv.healthz()
                    body = json.dumps(h).encode()
                    code = 200 if h["status"] == "ok" else 503
                    ctype = "application/json"
                elif path == "/fleet":
                    if _fleet_dir is None:
                        body = b"no fleet dir configured "\
                               b"(obs.metrics.set_fleet_dir)\n"
                        code, ctype = 404, "text/plain"
                    else:
                        try:
                            from deeplearning4j_tpu.obs import fleet
                            body = fleet.aggregate(_fleet_dir)\
                                .exposition().encode()
                            code, ctype = 200, \
                                "text/plain; version=0.0.4; " \
                                "charset=utf-8"
                        except Exception as e:
                            # a shared-FS hiccup must answer 500, not
                            # drop the socket mid-request
                            body = f"fleet aggregation failed: " \
                                   f"{e!r}\n".encode()
                            code, ctype = 500, "text/plain"
                else:
                    body = (b"deeplearning4j_tpu telemetry: "
                            b"/metrics /healthz /fleet\n")
                    code, ctype = 200, "text/plain"
                self.send_response(code)
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def log_message(self, *a):
                pass

        self._httpd = http.server.ThreadingHTTPServer(
            ("127.0.0.1", self.port), Handler)
        self.port = self._httpd.server_address[1]
        self._thread = threading.Thread(
            target=self._httpd.serve_forever, daemon=True)
        self._thread.start()
        return self

    def stop(self):
        if self._httpd:
            self._httpd.shutdown()
            self._httpd.server_close()
            self._httpd = None


_server: Optional[MetricsServer] = None


def start_server(port: Optional[int] = None) -> MetricsServer:
    """Start (or return) the process-wide telemetry endpoint. ``port``
    defaults to ``DL4J_TPU_METRICS_PORT`` (0 → ephemeral)."""
    global _server
    if _server is not None:
        return _server
    if port is None:
        from deeplearning4j_tpu import environment
        port = environment.get_flag("DL4J_TPU_METRICS_PORT")
    _server = MetricsServer(port=int(port)).start()
    return _server


def stop_server() -> None:
    global _server
    if _server is not None:
        _server.stop()
        _server = None
