"""Fleet observability plane — cross-host telemetry aggregation,
straggler attribution, and a crash flight recorder.

Reference: DL4J's ``StatsListener`` + training UI aggregated
per-worker ``ParallelWrapper`` stats into ONE fleet-visible view
(SURVEY §5); our PR 2/4 spine is strictly per-process — ``/metrics``,
spans, and numerics all stop at the process boundary, so after the
elastic layer (PR 6) made training multi-host, "which host stalled
the collective" and "what happened in the 50 steps before the
eviction" were unanswerable. This module answers both by riding the
PR 6 file plane (the shared elastic directory the leases already live
on):

- **Telemetry publishing** (:class:`FleetTelemetry`): each host
  atomically publishes a compact, versioned snapshot — metrics
  exposition, heartbeat ages, a numerics tail, mesh epoch, step, and
  per-step barrier-entry/exit wall timestamps — into
  ``<elastic_dir>/telemetry/<host>.json`` on a cadence
  (``DL4J_TPU_FLEET_PUBLISH_SECS``). Publication is the same
  tmp+fsync+``os.replace`` idiom as the lease files: a reader sees
  old-or-new, never torn.

- **Aggregation** (:func:`aggregate` → :class:`FleetView`): merge
  every host's snapshot into ONE fleet-level Prometheus exposition —
  each sample re-labelled with ``host=`` and ``mesh_epoch=`` via
  ``metrics.parse_exposition`` — plus aggregator-computed families:
  per-host collective skew (``dl4j_tpu_collective_skew_seconds``),
  the named straggler (``dl4j_tpu_collective_straggler``), snapshot
  ages, and the live host count. Served on the existing stdlib
  server's ``/fleet`` path (``metrics.set_fleet_dir``) or rendered by
  ``tools/tpu_watch.py --fleet-dir``.

- **Straggler attribution** (:meth:`FleetView.skew_report`): the
  elastic context stamps barrier entry/exit per step; the aggregator
  turns "the allreduce is slow" into "host C enters 40ms late every
  step". A host MISSING from the newest entered step is ranked by its
  lease age — the authoritative liveness signal — so a corpse is
  named the final-step straggler even when every survivor is wedged
  at the same barrier.

- **Crash flight recorder** (:meth:`FleetTelemetry.dump`): a bounded
  black-box ring (last-N steps: barrier stamps, loss, numerics
  scalars, mesh-epoch events) dumped as a *versioned* postmortem
  bundle on ``NonFiniteError`` / ``StaleMeshEpoch`` /
  ``CollectiveTimeoutError`` / SIGTERM preemption, carrying
  ``obs.report()`` tail spans and the fleet skew view at the moment
  of death. On eviction the surviving leader snapshots the dead
  host's FINAL telemetry into a bundle too
  (:func:`record_eviction`) — diagnostics survive the failure they
  explain (PyGraph's robust-versioning bar, PAPERS.md 2503.19779:
  every snapshot and bundle carries a schema version and readers skip
  incompatible files instead of crashing).

Clock: barrier stamps and snapshot ages use the *wall* clock
(``time.time``) for the same reason leases do — they must be
comparable across hosts; fleet hosts are assumed NTP-close relative
to the skew scales of interest (the lease window bounds the error).

Off-path contract (the PR 2/4 bar): with no fleet plane installed the
training step pays ONE branch (``if ... is None``) and
:func:`publishes` / :func:`dumps` stay 0 for the process lifetime —
counter-asserted by ``tests/test_fleet_obs.py``.
"""
from __future__ import annotations

import json
import logging
import os
import threading
import time
from collections import deque
from pathlib import Path
from typing import Any, Callable, Dict, List, Optional

from deeplearning4j_tpu.obs import metrics as _metrics
from deeplearning4j_tpu.obs import trace as _trace

logger = logging.getLogger("deeplearning4j_tpu")

#: schema versions — bump on any incompatible layout change; readers
#: SKIP (never crash on) files from another version
SNAPSHOT_VERSION = 1
BUNDLE_VERSION = 1

#: barrier stamps kept per snapshot (per-step entry/exit pairs — the
#: skew window the aggregator can attribute over)
BARRIER_KEEP = 16

_UNSET = object()   # memoization sentinel (skew_report may be None)

# -- metric families ---------------------------------------------------------

FLEET_PUBLISHES = _metrics.REGISTRY.counter(
    "dl4j_tpu_fleet_snapshots_published_total",
    "telemetry snapshots this host published into the fleet plane")
FLIGHT_DUMPS = _metrics.REGISTRY.counter(
    "dl4j_tpu_flight_recorder_dumps_total",
    "flight-recorder postmortem bundles written", ("cause",))

#: families the AGGREGATOR computes (they exist only in the merged
#: fleet exposition, never in a single process's registry) — declared
#: here AND in ``metrics.FAMILIES`` so ``lint_instrumentation`` rule 6
#: keeps emit sites, tpu_watch, and OPS.md in lockstep
AGGREGATE_FAMILIES = {
    "dl4j_tpu_collective_skew_seconds": "gauge",
    "dl4j_tpu_collective_straggler": "gauge",
    "dl4j_tpu_fleet_hosts": "gauge",
    "dl4j_tpu_fleet_snapshot_age_seconds": "gauge",
    "dl4j_tpu_serving_fleet_replica_ready": "gauge",
}

# -- off-path fence counters (tests assert both stay 0 with no plane) --------

_lock = threading.Lock()
_counters = {"publishes": 0, "dumps": 0}
_bundle_seq = 0


def publishes() -> int:
    """Snapshots published since the last reset — stays 0 for the
    process lifetime when no fleet plane is installed (the off-path
    zero-overhead assertion)."""
    return _counters["publishes"]


def dumps() -> int:
    """Postmortem bundles written since the last reset."""
    return _counters["dumps"]


def reset_counters() -> None:
    """Tests only."""
    with _lock:
        _counters["publishes"] = 0
        _counters["dumps"] = 0


def _atomic_write_json(path: Path, obj: dict) -> None:
    """Atomic JSON publication via the resilience layer's hardened
    writer (tmp+fsync+``os.replace``+directory fsync, tmp cleaned on
    failure) — the postmortem bundle must be durable through the very
    crash it explains. Imported lazily: ``obs`` loads before
    ``resilience`` at package import, so a module-level import here
    would cycle."""
    from deeplearning4j_tpu.resilience.checkpoint import \
        atomic_write_bytes
    atomic_write_bytes(Path(path), (json.dumps(obj) + "\n").encode())


def _read_json(path: Path) -> Optional[dict]:
    """Tolerant read: missing/torn → None (writers are atomic, so a
    failed parse means a concurrent writer — retry next sample)."""
    try:
        return json.loads(Path(path).read_text())
    except (OSError, ValueError):
        return None


def _numerics_tail() -> Dict[str, Any]:
    """Compact numerics-observatory tail for the snapshot: the
    per-layer grad-norm gauges and any nonzero non-finite counters —
    scalar values already on host (no device traffic)."""
    from deeplearning4j_tpu.obs import numerics as _num
    tail: Dict[str, Any] = {}
    grads = _num.GRAD_NORM.snapshot()
    if grads:
        tail["grad_norm"] = {k: round(float(v), 6)
                             for k, v in grads.items()}
    nf = {k: int(v) for k, v in _num.NONFINITE.snapshot().items() if v}
    if nf:
        tail["nonfinite"] = nf
    return tail


class FleetTelemetry:
    """Per-host half of the plane: the publisher + the flight
    recorder. ``directory`` is the shared elastic dir (snapshots go
    under ``telemetry/``, bundles under ``postmortem/``)."""

    def __init__(self, directory, host: str, *,
                 every_s: Optional[float] = None,
                 ring: Optional[int] = None,
                 clock: Callable[[], float] = time.time):
        from deeplearning4j_tpu import environment
        self.dir = Path(directory)
        self.host = str(host)
        self.every_s = float(
            every_s if every_s is not None
            else environment.get_flag("DL4J_TPU_FLEET_PUBLISH_SECS"))
        self.clock = clock
        n = int(ring if ring is not None
                else environment.get_flag("DL4J_TPU_FLEET_RING"))
        self._ring: deque = deque(maxlen=max(1, n))
        self._barriers: deque = deque(maxlen=BARRIER_KEEP)
        self._pending: Dict[int, float] = {}
        self._last_publish = float("-inf")
        self._io_lock = threading.Lock()
        self.step = -1
        self.mesh_epoch = 0
        self.serving: Optional[Dict[str, Any]] = None

    @property
    def telemetry_path(self) -> Path:
        return self.dir / "telemetry" / f"{self.host}.json"

    # -- recording ------------------------------------------------------
    def note_enter(self, step: int, t: Optional[float] = None) -> None:
        """Barrier-entry stamp: this host is about to dispatch ``step``
        (the collective's rendezvous point — a late entry here IS the
        skew the aggregator attributes)."""
        self._pending[int(step)] = self.clock() if t is None else t

    def record_step(self, step: int, *, mesh_epoch: Optional[int] = None,
                    t_enter: Optional[float] = None,
                    t_exit: Optional[float] = None,
                    loss: Optional[float] = None,
                    extra: Optional[Dict[str, Any]] = None) -> None:
        """One completed step: barrier-exit stamp + flight-recorder
        ring entry + cadence-gated publish."""
        step = int(step)
        t_exit = self.clock() if t_exit is None else t_exit
        if t_enter is None:
            t_enter = self._pending.pop(step, t_exit)
        else:
            self._pending.pop(step, None)
        self.step = step
        if mesh_epoch is not None:
            self.mesh_epoch = int(mesh_epoch)
        self._barriers.append((step, t_enter, t_exit))
        rec: Dict[str, Any] = {"step": step, "t_enter": t_enter,
                               "t_exit": t_exit,
                               "mesh_epoch": self.mesh_epoch}
        if loss is not None:
            rec["loss"] = float(loss)
        if extra:
            rec.update(extra)
        self._ring.append(rec)
        self.maybe_publish()

    def event(self, kind: str, **info: Any) -> None:
        """A membership-plane event (mesh-epoch commit, eviction
        observed, preemption notice) — ringed and published
        immediately: these are exactly the breadcrumbs a postmortem
        needs and they are rare enough to skip the cadence."""
        rec = {"event": str(kind), "t_wall": self.clock(), **info}
        if "epoch" in info:
            self.mesh_epoch = int(info["epoch"])
        self._ring.append(rec)
        self.publish(force=True)

    def update_serving(self, **info: Any) -> None:
        """Attach/refresh this host's serving section (queue depth,
        KV-page occupancy, readiness, port...) — it rides the next
        snapshot, so the router's eligibility read and the training
        skew view share one publication plane. Serving replicas call
        this every tick; non-serving hosts never carry the section."""
        if self.serving is None:
            self.serving = {}
        self.serving.update(info)

    # -- publishing -----------------------------------------------------
    def snapshot(self) -> Dict[str, Any]:
        """The compact host snapshot: everything a fleet aggregator
        needs to merge this process into the fleet view."""
        from deeplearning4j_tpu.obs import health as _health
        snap = {
            "version": SNAPSHOT_VERSION,
            "host": self.host,
            "pid": os.getpid(),
            "t_wall": self.clock(),
            "step": self.step,
            "mesh_epoch": self.mesh_epoch,
            "barriers": [list(b) for b in self._barriers] + [
                [s, t, None] for s, t in sorted(self._pending.items())],
            "health": {w: round(s["age_s"], 3)
                       for w, s in _health.check().items()},
            "numerics": _numerics_tail(),
            "exposition": _metrics.exposition(),
        }
        if self.serving is not None:
            snap["serving"] = dict(self.serving)
        return snap

    def maybe_publish(self) -> bool:
        """Publish when more than ``every_s`` has passed — the
        per-step hook stays a clock read + compare (the cadence gate
        lives in :meth:`publish`, once)."""
        return self.publish()

    def publish(self, force: bool = False) -> bool:
        if not force and \
                self.clock() - self._last_publish < self.every_s:
            return False
        snap = self.snapshot()
        with self._io_lock:
            path = self.telemetry_path
            path.parent.mkdir(parents=True, exist_ok=True)
            _atomic_write_json(path, snap)
            self._last_publish = self.clock()
        with _lock:
            _counters["publishes"] += 1
        FLEET_PUBLISHES.inc()
        return True

    # -- the flight recorder --------------------------------------------
    def dump(self, cause, extra: Optional[Dict[str, Any]] = None,
             republish: bool = True) -> Optional[str]:
        """Write the versioned postmortem bundle: the step ring, the
        obs report tail (spans + metric families + health), and the
        fleet skew view at the moment of death. ``cause`` is an
        exception or a string. ``republish=False`` skips the final
        snapshot publish — the EVICTED path must not resurrect the
        telemetry file the leader's eviction bundle just retired (a
        lease-less snapshot reads as a corpse forever). Best-effort by
        construction — a dump must never turn one failure into two."""
        global _bundle_seq
        from deeplearning4j_tpu import obs
        t = self.clock()
        if republish:
            try:
                self.publish(force=True)  # final telemetry for peers
            except Exception:            # pragma: no cover - disk gone
                logger.exception("fleet: final publish failed")
        cause_name = (type(cause).__name__
                      if isinstance(cause, BaseException) else str(cause))
        bundle: Dict[str, Any] = {
            "version": BUNDLE_VERSION,
            "host": self.host,
            "pid": os.getpid(),
            "t_wall": t,
            "cause": cause_name,
            "message": str(cause),
            "step": self.step,
            "mesh_epoch": self.mesh_epoch,
            "ring": list(self._ring),
        }
        if isinstance(cause, BaseException):
            for attr in ("layer", "kind", "iteration"):
                v = getattr(cause, attr, None)
                if v is not None:
                    bundle.setdefault("origin", {})[attr] = v
        if extra:
            bundle.update(extra)
        try:
            bundle["report"] = obs.report(spans=50)
        except Exception:                # pragma: no cover
            logger.exception("fleet: obs.report failed in dump")
        try:
            # aggregate in THIS publisher's clock domain — mixing an
            # injected clock's stamps with wall time would mark every
            # lease/snapshot astronomically stale
            view = aggregate(self.dir, now=t)
            bundle["fleet"] = {"hosts": view.table(),
                               "skew": view.skew_report()}
        except Exception:                # pragma: no cover
            logger.exception("fleet: skew aggregation failed in dump")
        with _lock:
            _bundle_seq += 1
            seq = _bundle_seq
        path = (self.dir / "postmortem" /
                f"{self.host}.{cause_name}.{os.getpid()}.{seq}.json")
        try:
            path.parent.mkdir(parents=True, exist_ok=True)
            _atomic_write_json(path, bundle)
        except Exception:                # pragma: no cover - disk gone
            logger.exception("fleet: postmortem write failed")
            return None
        with _lock:
            _counters["dumps"] += 1
        FLIGHT_DUMPS.labels(cause=cause_name).inc()
        logger.warning("FLIGHT_RECORDER host=%s cause=%s step=%d -> %s",
                       self.host, cause_name, self.step, path)
        return str(path)


def record_eviction(directory, dead_host: str, *, by: str,
                    now: Optional[float] = None,
                    cause: str = "Evicted") -> Optional[str]:
    """Surviving-leader half of the flight recorder: snapshot the dead
    host's FINAL telemetry into a postmortem bundle (named for the
    corpse, recorded by the evictor) and retire its live snapshot so
    the fleet view stops counting it. No-op when the dead host never
    published (fleet plane off). Called by
    ``MembershipCoordinator.evict_expired`` — only the winner of the
    lease ``os.replace`` race calls it, so exactly one bundle. A
    graceful departure takes the same path with ``cause="Departed"``
    (``record_departure``), recorded by the departing host itself —
    without the retirement, a long-gone peer's stale snapshot would
    read lease-less, i.e. dead, and be named straggler forever."""
    d = Path(directory)
    live = d / "telemetry" / f"{dead_host}.json"
    snap = _read_json(live)
    if snap is None:
        return None
    now = time.time() if now is None else now
    bundle = {
        "version": BUNDLE_VERSION,
        "host": str(dead_host),
        "cause": str(cause),
        "recorded_by": str(by),
        "t_wall": now,
        "step": snap.get("step"),
        "mesh_epoch": snap.get("mesh_epoch"),
        "final_telemetry": snap,
    }
    try:
        # the ADJUDICATED skew view: computed at eviction time, while
        # the corpse's snapshot is still live but its lease is gone —
        # survivor dumps race an instant transport error and can
        # misattribute; this one cannot (the lease verdict is in)
        view = aggregate(d, now=now)
        bundle["fleet"] = {"hosts": view.table(),
                           "skew": view.skew_report()}
    except Exception:                    # pragma: no cover
        logger.exception("fleet: eviction skew aggregation failed")
    path = d / "postmortem" / \
        f"{dead_host}.{str(cause).lower()}.{int(now)}.json"
    try:
        path.parent.mkdir(parents=True, exist_ok=True)
        _atomic_write_json(path, bundle)
        live.unlink(missing_ok=True)
    except OSError:                      # pragma: no cover
        logger.exception("fleet: eviction bundle write failed")
        return None
    with _lock:
        _counters["dumps"] += 1
    FLIGHT_DUMPS.labels(cause=str(cause)).inc()
    logger.warning("FLIGHT_RECORDER host=%s cause=%s by=%s -> %s",
                   dead_host, cause, by, path)
    return str(path)


def record_departure(directory, host: str,
                     now: Optional[float] = None) -> Optional[str]:
    """Graceful-departure retirement: the LEAVING host moves its own
    final telemetry into a ``<host>.departed.<ts>.json`` bundle so
    the fleet view stops counting it (a lingering snapshot with no
    lease reads as a corpse and would be named straggler forever)."""
    return record_eviction(directory, host, by=host, now=now,
                           cause="Departed")


# -- aggregation -------------------------------------------------------------

def read_snapshots(directory) -> Dict[str, dict]:
    """Every parseable, version-compatible snapshot under
    ``<directory>/telemetry/`` (or ``directory`` itself when pointed
    straight at a telemetry dir). Incompatible versions are skipped,
    not fatal — a mixed-version fleet mid-rollout must still
    aggregate what it can."""
    d = Path(directory)
    if not (d / "telemetry").is_dir() and d.name == "telemetry":
        tdir = d
    else:
        tdir = d / "telemetry"
    out: Dict[str, dict] = {}
    if not tdir.is_dir():
        return out
    for p in sorted(tdir.glob("*.json")):
        snap = _read_json(p)
        if not snap or "host" not in snap:
            continue
        if snap.get("version") != SNAPSHOT_VERSION:
            logger.warning("fleet: skipping %s (snapshot version %r, "
                           "want %d)", p.name, snap.get("version"),
                           SNAPSHOT_VERSION)
            continue
        out[str(snap["host"])] = snap
    return out


def _read_leases(directory, now: float) -> Dict[str, Dict[str, float]]:
    """Lease evidence from the elastic members/ dir — the
    authoritative liveness signal straggler attribution anchors on:
    ``{host: {"age_s", "lease_secs"}}``. Read directly (tolerantly)
    so the aggregator needs no coordinator instance."""
    out: Dict[str, Dict[str, float]] = {}
    mdir = Path(directory) / "members"
    if not mdir.is_dir():
        return out
    for p in mdir.glob("*.json"):
        lease = _read_json(p)
        if lease and "host" in lease:
            out[str(lease["host"])] = {
                "age_s": now - float(lease.get("t", 0.0)),
                "lease_secs": float(lease.get("lease_secs", 0.0)),
            }
    return out


class FleetView:
    """One merged view over every host's snapshot: the per-host table,
    the collective-skew report, and the fleet-level exposition."""

    def __init__(self, snapshots: Dict[str, dict], *,
                 directory=None, now: Optional[float] = None):
        self.snapshots = snapshots
        self.dir = Path(directory) if directory is not None else None
        # "now" for age math: never run ahead of the snapshots' own
        # clock domain (tests drive fake clocks), never behind it
        t_max = max([s.get("t_wall", 0.0)
                     for s in snapshots.values()] or [0.0])
        self.now = max(t_max, time.time() if now is None else now)
        self.leases = (_read_leases(self.dir, self.now)
                       if self.dir is not None else {})
        # whether a membership plane exists at all: when it does, a
        # host with NO live lease file is presumed dead (evicted,
        # expired-and-moved, or gracefully departed) — the strongest
        # lateness signal there is
        self._has_lease_plane = (
            self.dir is not None and (self.dir / "members").is_dir())
        # a view is a point-in-time read — memoize the derived
        # products so exposition() (which needs both) and its callers
        # (which often also want them) compute each once
        self._table: Optional[Dict[str, Dict[str, Any]]] = None
        self._skew: Any = _UNSET

    def _dead_hosts(self) -> List[str]:
        """Hosts whose LEASE evidence says they are gone: no live
        lease file (while a membership plane exists) or a lease older
        than its own window. Snapshot staleness alone is NOT death —
        at a slow publish cadence every healthy peer looks stale."""
        if not self._has_lease_plane:
            return []
        dead = []
        for h in self.snapshots:
            lease = self.leases.get(h)
            if lease is None:
                dead.append(h)
            elif lease["lease_secs"] > 0 and \
                    lease["age_s"] > lease["lease_secs"]:
                dead.append(h)
        return sorted(dead)

    def table(self) -> Dict[str, Dict[str, Any]]:
        """{host: {step, mesh_epoch, age_s}} — the tpu_watch table."""
        if self._table is None:
            self._table = {
                h: {"step": s.get("step"),
                    "mesh_epoch": s.get("mesh_epoch"),
                    "age_s": round(self.now - s.get("t_wall", 0.0), 3)}
                for h, s in sorted(self.snapshots.items())}
        return self._table

    def serving_table(self) -> Dict[str, Dict[str, Any]]:
        """{host: serving section + lease/liveness columns} for every
        snapshot carrying a ``serving`` section — the router's
        eligibility read and ``tpu_watch --fleet-dir``'s replica
        columns. ``live`` is lease evidence (the same verdict
        ``_dead_hosts`` renders); ``ready`` comes from the replica's
        own published readiness gate."""
        dead = set(self._dead_hosts())
        out: Dict[str, Dict[str, Any]] = {}
        for h, s in sorted(self.snapshots.items()):
            serving = s.get("serving")
            if not isinstance(serving, dict):
                continue
            row = dict(serving)
            row["ready"] = bool(serving.get("ready", False))
            row["live"] = h not in dead
            row["age_s"] = round(self.now - s.get("t_wall", 0.0), 3)
            lease = self.leases.get(h)
            row["lease_age_s"] = (round(lease["age_s"], 3)
                                  if lease else None)
            row["mesh_epoch"] = s.get("mesh_epoch", 0)
            out[h] = row
        return out

    def evicted(self) -> List[str]:
        """Hosts with an eviction bundle under ``postmortem/``."""
        if self.dir is None:
            return []
        pdir = self.dir / "postmortem"
        if not pdir.is_dir():
            return []
        return sorted({p.name.split(".evicted.")[0]
                       for p in pdir.glob("*.evicted.*.json")})

    # -- straggler attribution -----------------------------------------
    def _enters(self) -> Dict[int, Dict[str, float]]:
        """{step: {host: barrier_enter}} across every snapshot."""
        out: Dict[int, Dict[str, float]] = {}
        for host, snap in self.snapshots.items():
            for b in snap.get("barriers", []):
                try:
                    step, t_enter = int(b[0]), float(b[1])
                except (TypeError, ValueError, IndexError):
                    continue
                out.setdefault(step, {})[host] = t_enter
        return out

    def skew_report(self) -> Optional[Dict[str, Any]]:
        """Per-step collective skew + the named straggler.

        For each step, skew = spread of barrier-entry stamps across
        the hosts that entered it; ``last_in`` is the latest entrant.

        Attribution anchors on LEASE evidence, never on snapshot
        staleness: with hosts publishing on a cadence, every healthy
        peer's snapshot lags the newest one by up to the cadence, so
        "missing from the newest step" is normal, not a verdict.

        - When some host is lease-dead (no live lease while a
          membership plane exists, or its lease outlived its own
          window), THAT is the straggler — the stalest-evidence corpse
          first. A SIGKILLed host is named even though it never
          stamped the final step (every survivor wedges at the same
          barrier, so entry times cannot tell corpse from
          victim-of-corpse). With an INSTANT transport error the
          leases are still fresh at dump time, which is why the
          eviction-time bundle — written after the lease verdict — is
          the adjudicated naming and survivor dumps are best-effort.
        - With every lease live, the anchor is the newest step COMMON
          to every live host's published window (falling back to the
          newest step anywhere when windows don't overlap), and the
          straggler is simply the last entrant there."""
        if self._skew is not _UNSET:
            return self._skew
        self._skew = self._skew_report()
        return self._skew

    def _skew_report(self) -> Optional[Dict[str, Any]]:
        enters = self._enters()
        if not enters or not self.snapshots:
            return None
        dead = self._dead_hosts()
        live = [h for h in self.snapshots if h not in dead]
        live_steps = [
            {s for s, ts in enters.items() if h in ts} for h in live]
        common = set.intersection(*live_steps) \
            if live_steps and all(live_steps) else set()
        if common:
            step = max(common)
        else:
            # disjoint windows (steps much faster than the cadence):
            # anchor on the newest step with >= 2 entrants — a
            # single-entrant anchor has no cross-host spread to read
            multi = [s for s, ts in enters.items() if len(ts) >= 2]
            step = max(multi) if multi else max(enters)
        at_step = enters[step]
        min_enter = min(at_step.values())
        skew = {h: round(t - min_enter, 6)
                for h, t in at_step.items()}
        # only lease-dead hosts are "missing" — their lateness is a
        # lower bound, not a stamp
        missing = [h for h in dead if h not in at_step]
        est = round(max(0.0, self.now - min_enter), 6)
        for h in missing:
            skew[h] = est

        def lateness(h):
            snap_age = self.now - self.snapshots[h].get("t_wall", 0.0)
            lease = self.leases.get(h)
            if lease is None:       # no lease at all: deadest evidence
                return (1, 0.0, snap_age)
            return (0, lease["age_s"], snap_age)

        if dead:
            straggler = max(dead, key=lateness)
        elif len(at_step) >= 2:
            straggler = max(at_step, key=at_step.get)
        else:
            # one entrant and nobody dead: there is no straggler to
            # name — naming the lone (often the FASTEST) publisher
            # would be pure noise
            straggler = None
        series = []
        for s in sorted(enters)[-BARRIER_KEEP:]:
            ts = enters[s]
            if len(ts) < 2:
                continue
            lo, hi = min(ts.values()), max(ts.values())
            series.append([s, round(hi - lo, 6),
                           max(ts, key=ts.get)])
        return {"step": step, "skew_s": skew, "missing": missing,
                "dead": dead, "straggler": straggler,
                "max_skew_s": max(skew.values()) if skew else 0.0,
                "series": series}

    # -- fleet-level exposition ----------------------------------------
    def exposition(self) -> str:
        """Fleet-level Prometheus text: every host's samples
        re-labelled with ``host=`` / ``mesh_epoch=``, grouped per
        family with TYPE from the ``metrics.FAMILIES`` registry, plus
        the aggregator-computed skew/straggler/age/host-count
        families."""
        fam_kind = dict(_metrics.FAMILIES)
        by_family: Dict[str, List[str]] = {}

        def base_family(name: str) -> str:
            for suffix in ("_bucket", "_sum", "_count"):
                if name.endswith(suffix) and \
                        fam_kind.get(name[:-len(suffix)]) == "histogram":
                    return name[:-len(suffix)]
            return name

        for host, snap in sorted(self.snapshots.items()):
            epoch = str(snap.get("mesh_epoch", 0))
            try:
                fams = _metrics.parse_exposition(
                    snap.get("exposition", ""))
            except ValueError:
                logger.warning("fleet: unparseable exposition from "
                               "host %r — skipped", host)
                continue
            for (name, labels), value in fams.items():
                merged = dict(labels)
                merged["host"] = host
                merged["mesh_epoch"] = epoch
                by_family.setdefault(base_family(name), []).append(
                    f"{name}{_metrics._label_str(merged)} {value}")
        agg: Dict[str, List[str]] = {
            "dl4j_tpu_fleet_hosts":
                [f"dl4j_tpu_fleet_hosts {len(self.snapshots)}"],
            "dl4j_tpu_fleet_snapshot_age_seconds": [
                f"dl4j_tpu_fleet_snapshot_age_seconds"
                f"{_metrics._label_str({'host': h})} {v['age_s']}"
                for h, v in self.table().items()],
        }
        srv = self.serving_table()
        if srv:
            # the autoscale drill's post-drill assertion target: one
            # sample per serving replica, 1 only when lease-live AND
            # warmup-ready (the router's own eligibility predicate)
            agg["dl4j_tpu_serving_fleet_replica_ready"] = [
                f"dl4j_tpu_serving_fleet_replica_ready"
                f"{_metrics._label_str({'host': h})} "
                f"{int(row['ready'] and row['live'])}"
                for h, row in sorted(srv.items())]
        rep = self.skew_report()
        if rep:
            agg["dl4j_tpu_collective_skew_seconds"] = [
                f"dl4j_tpu_collective_skew_seconds"
                f"{_metrics._label_str({'host': h})} {v}"
                for h, v in sorted(rep["skew_s"].items())]
            agg["dl4j_tpu_collective_straggler"] = [
                f"dl4j_tpu_collective_straggler"
                f"{_metrics._label_str({'host': h})} "
                f"{int(h == rep['straggler'])}"
                for h in sorted(self.snapshots)]
        by_family.update(agg)
        lines: List[str] = []
        for fam in sorted(by_family):
            kind = fam_kind.get(fam) or AGGREGATE_FAMILIES.get(fam)
            if kind:
                lines.append(f"# TYPE {fam} {kind}")
            lines.extend(by_family[fam])
        return "\n".join(lines) + "\n"


def aggregate(directory, now: Optional[float] = None) -> FleetView:
    """Read every snapshot under ``directory`` (the shared elastic
    dir) and return the merged :class:`FleetView`."""
    return FleetView(read_snapshots(directory), directory=directory,
                     now=now)


# -- bench/dossier harness ---------------------------------------------------

def measure_publish_overhead(step_seconds: Optional[float] = None,
                             iters: int = 2000,
                             every_s: float = 1.0) -> Dict[str, Any]:
    """Measure the fleet plane's per-step costs: the OFF path (the one
    ``is None`` branch every non-fleet step pays), the ON-path
    ``record_step`` (ring append + cadence check; publishes amortized
    at ``every_s``), and one full snapshot publish — the ``fleet_obs``
    section of ``bench.py`` / the dossier. Probe counters are scrubbed
    so the synthetic samples never reach the off-path fences."""
    import tempfile

    pubs0, dumps0 = _counters["publishes"], _counters["dumps"]
    fam0 = FLEET_PUBLISHES._children[()].value
    ft = None
    t0 = _trace.now()
    for i in range(iters):
        if ft is not None:           # the exact branch the step pays
            ft.record_step(i)
    off = (_trace.now() - t0) / iters
    with tempfile.TemporaryDirectory(prefix="fleet_bench_") as d:
        ft = FleetTelemetry(d, "bench-probe", every_s=every_s)
        base = time.time()
        t1 = _trace.now()
        for i in range(iters):
            ft.record_step(i, mesh_epoch=1, t_enter=base,
                           t_exit=base, loss=0.0)
        on = (_trace.now() - t1) / iters
        t2 = _trace.now()
        ft.publish(force=True)
        publish_s = _trace.now() - t2
        published = _counters["publishes"] - pubs0
    with _lock:                      # scrub the probe's counters
        _counters["publishes"] = pubs0
        _counters["dumps"] = dumps0
    with FLEET_PUBLISHES._lock:
        FLEET_PUBLISHES._children[()].value = fam0
    out: Dict[str, Any] = {
        "off_path_cost_us": round(off * 1e6, 3),
        "on_path_record_us": round(on * 1e6, 3),
        "publish_ms": round(publish_s * 1e3, 3),
        "publish_interval_s": every_s,
        "publishes": published,
    }
    if step_seconds:
        # at cadence: one publish per every_s, record cost per step
        per_step = on + publish_s * step_seconds / max(every_s, 1e-9)
        out["step_ms"] = round(step_seconds * 1e3, 3)
        out["overhead_pct_of_step"] = round(
            100.0 * per_step / step_seconds, 4)
        out["off_path_pct_of_step"] = round(
            100.0 * off / step_seconds, 4)
    return out


__all__ = ["FleetTelemetry", "FleetView", "aggregate",
           "read_snapshots", "record_eviction", "publishes", "dumps",
           "reset_counters", "measure_publish_overhead",
           "SNAPSHOT_VERSION", "BUNDLE_VERSION", "AGGREGATE_FAMILIES"]
