"""Process-wide span tracer — Chrome-trace/Perfetto timelines.

Reference observability (SURVEY §5) times the step from the *outside*
(StatsListener wall clocks, PerformanceListener iter/sec); a compiled
stack needs the *inside* view too: where a step's wall time went —
ETL wait vs. host→device transfer vs. async dispatch vs. the blocking
device sync — across every thread (fit loop, prefetch worker, serving
worker). PyGraph (PAPERS.md) makes the same argument for compiled
execution: opaque compiled regions must export structured runtime
evidence or regressions hide inside them.

Design:

- **One clock.** :func:`now` (``time.perf_counter``) is the only step
  clock in the package — ``tools/lint_instrumentation.py`` enforces
  that no module outside ``obs/`` calls ``time.time()`` for timing.
- **One branch when off.** Tracing is gated by ``DL4J_TPU_TRACE``;
  disabled, :func:`span` returns a shared no-op context manager and
  :func:`add_span` returns after a single module-global check — zero
  event allocations on the step path (asserted by a counter in
  ``tests/test_obs.py``).
- **Chrome-trace JSONL.** Events are complete-span ``"ph": "X"``
  records (``ts``/``dur`` in microseconds, ``pid``/``tid``), held in a
  bounded ring (``DL4J_TPU_TRACE_RING``) and streamed to a JSONL file:
  first line ``[``, then one event object per line with a trailing
  comma — the Chrome trace "JSON array format", which explicitly
  tolerates the missing ``]``, so the file drops straight into
  ``chrome://tracing`` / Perfetto *and* stays line-parseable
  (:func:`read_trace`). Nesting needs no explicit parent ids: the
  viewers nest spans of one ``tid`` by interval containment.

Flags (``environment.py``): ``DL4J_TPU_TRACE`` — '' (off, default),
truthy ('1'/'true'/'on') for a default ``dl4j_tpu_trace_<pid>.jsonl``
in the cwd, or an explicit output path. ``DL4J_TPU_TRACE_RING`` —
in-memory ring size (crash dumps read the tail from here).
"""
from __future__ import annotations

import atexit
import json
import os
import threading
import time
from collections import deque
from typing import Any, Dict, List, Optional

now = time.perf_counter     #: the package's step clock (monotonic s)

_TRUTHY = {"1", "true", "on", "yes"}
_FALSEY = {"", "0", "off", "none", "false", "no"}

_lock = threading.Lock()
_enabled = False            # the one branch the off path pays
_ring: Optional[deque] = None
_fh = None                  # open JSONL handle (None -> ring only)
_path: Optional[str] = None
_events_recorded = 0
_seen_tids: set = set()
_tls = threading.local()    # .name: worker label for this thread


def enabled() -> bool:
    return _enabled


def enable(path: Optional[str] = None,
           ring: Optional[int] = None) -> Optional[str]:
    """Turn the tracer on. ``path`` (optional) streams events to a
    Chrome-trace JSONL file; events always land in the in-memory ring
    (``ring`` entries, default ``DL4J_TPU_TRACE_RING``). Returns the
    active file path (None when ring-only)."""
    global _enabled, _ring, _fh, _path
    if ring is None:
        from deeplearning4j_tpu import environment
        ring = environment.get_flag("DL4J_TPU_TRACE_RING")
    with _lock:
        if _fh is not None:
            _close_locked()
        _ring = deque(maxlen=max(1, int(ring)))
        _seen_tids.clear()
        if path is not None:
            _path = os.fspath(path)
            _fh = open(_path, "w")
            _fh.write("[\n")    # Chrome JSON array format (']' optional)
        else:
            _path = None
        _enabled = True
    return _path


def disable() -> None:
    """Stop tracing and close the output file (ring kept for
    inspection until the next :func:`enable`/:func:`reset`)."""
    global _enabled
    with _lock:
        _enabled = False
        _close_locked()


def _close_locked() -> None:
    global _fh
    if _fh is not None:
        try:
            _fh.flush()
            _fh.close()
        except OSError:
            pass
        _fh = None


def configure_from_env() -> Optional[str]:
    """Start the tracer from ``DL4J_TPU_TRACE`` (called by
    ``environment.apply_startup_flags`` at package import). Truthy →
    default per-pid file; any other non-falsey value → output path."""
    from deeplearning4j_tpu import environment
    raw = str(environment.get_flag("DL4J_TPU_TRACE")).strip()
    if raw.lower() in _FALSEY:
        return None
    if raw.lower() in _TRUTHY:
        return enable(f"dl4j_tpu_trace_{os.getpid()}.jsonl")
    return enable(raw)


def reset() -> None:
    """Tests only: disable, drop the ring, zero the counter."""
    global _ring, _path, _events_recorded
    disable()
    with _lock:
        _ring = None
        _path = None
        _events_recorded = 0
        _seen_tids.clear()


atexit.register(disable)    # flush + close the JSONL on exit


# -- recording ---------------------------------------------------------------

def set_thread_name(name: str) -> None:
    """Label the calling thread in the timeline (worker id — e.g.
    ``proc0``, ``prefetch``, ``serving``). Emitted as a Chrome ``M``
    metadata event on the thread's first recorded span."""
    _tls.name = str(name)
    if _enabled:
        with _lock:
            _seen_tids.discard(threading.get_ident())   # re-announce


def _emit(ev: Dict[str, Any]) -> None:
    """Append one event to ring+file. Caller checked ``_enabled``."""
    global _events_recorded
    tid = ev["tid"]
    with _lock:
        if _ring is None:
            return
        if tid not in _seen_tids:
            _seen_tids.add(tid)
            name = getattr(_tls, "name", None) or \
                threading.current_thread().name
            meta = {"ph": "M", "name": "thread_name", "pid": ev["pid"],
                    "tid": tid, "args": {"name": name}}
            _ring.append(meta)
            if _fh is not None:
                _fh.write(json.dumps(meta, separators=(",", ":"))
                          + ",\n")
        _ring.append(ev)
        _events_recorded += 1
        if _fh is not None:
            _fh.write(json.dumps(ev, separators=(",", ":")) + ",\n")


def add_span(name: str, t0: float, t1: float,
             args: Optional[Dict[str, Any]] = None) -> None:
    """Record a completed span from explicit :func:`now` timestamps —
    the zero-context-manager-overhead API the fit loops use."""
    if not _enabled:        # the off path: one branch, no allocation
        return
    ev: Dict[str, Any] = {
        "ph": "X", "name": name,
        "ts": round(t0 * 1e6, 3), "dur": round((t1 - t0) * 1e6, 3),
        "pid": os.getpid(), "tid": threading.get_ident(),
    }
    if args:
        ev["args"] = args
    _emit(ev)


def counter(name: str, values: Dict[str, Any],
            t: Optional[float] = None) -> None:
    """Record one sample on a Perfetto counter track (Chrome ``C``
    event): ``values`` maps series name → number, so e.g. per-layer
    gradient norms render as stacked counter series alongside the
    span timeline. Same off-path contract as :func:`add_span`."""
    if not _enabled:
        return
    ev: Dict[str, Any] = {
        "ph": "C", "name": name,
        "ts": round((now() if t is None else t) * 1e6, 3),
        "pid": os.getpid(), "tid": threading.get_ident(),
        "args": {k: float(v) for k, v in values.items()},
    }
    _emit(ev)


def async_span(name: str, aid, t0: float, t1: float,
               args: Optional[Dict[str, Any]] = None,
               cat: str = "request") -> None:
    """Record one phase of an ASYNC track (Chrome nestable async
    ``b``/``e`` event pair sharing ``id``): request-scoped spans live
    here because a request's life overlaps other requests on the same
    worker thread — complete-span (``X``) nesting by interval
    containment would interleave them into garbage, while async
    tracks render one lane per ``id``. Same off-path contract as
    :func:`add_span` (one branch, zero events)."""
    if not _enabled:
        return
    base = {"cat": cat, "id": format(int(aid), "x"),
            "pid": os.getpid(), "tid": threading.get_ident()}
    b: Dict[str, Any] = {"ph": "b", "name": name,
                         "ts": round(t0 * 1e6, 3), **base}
    if args:
        b["args"] = args
    _emit(b)
    _emit({"ph": "e", "name": name, "ts": round(t1 * 1e6, 3), **base})


def instant(name: str, args: Optional[Dict[str, Any]] = None) -> None:
    """Record a point-in-time marker (Chrome ``i`` event)."""
    if not _enabled:
        return
    ev: Dict[str, Any] = {
        "ph": "i", "name": name, "s": "t",
        "ts": round(now() * 1e6, 3),
        "pid": os.getpid(), "tid": threading.get_ident(),
    }
    if args:
        ev["args"] = args
    _emit(ev)


class _NullSpan:
    """Shared no-op context manager — the disabled :func:`span` path."""
    __slots__ = ()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False


_NULL_SPAN = _NullSpan()


class _Span:
    __slots__ = ("name", "args", "t0")

    def __init__(self, name, args):
        self.name = name
        self.args = args

    def __enter__(self):
        self.t0 = now()
        return self

    def __exit__(self, *exc):
        add_span(self.name, self.t0, now(), self.args)
        return False


def span(name: str, args: Optional[Dict[str, Any]] = None):
    """``with obs.span("fit/step"): ...`` — nested spans build the
    timeline; when tracing is off this returns a shared no-op context
    manager (one branch, nothing allocated per call)."""
    if not _enabled:
        return _NULL_SPAN
    return _Span(name, args)


# -- inspection --------------------------------------------------------------

def events_recorded() -> int:
    """Total span/instant events recorded since the last reset — the
    zero-overhead-when-disabled assertion anchor."""
    return _events_recorded


def events(last: Optional[int] = None) -> List[Dict[str, Any]]:
    """Snapshot of the in-memory ring (most recent ``last``, or all)."""
    with _lock:
        evs = list(_ring) if _ring is not None else []
    return evs[-last:] if last else evs


def trace_path() -> Optional[str]:
    return _path


def flush() -> None:
    with _lock:
        if _fh is not None:
            _fh.flush()


def read_trace(path: str) -> List[Dict[str, Any]]:
    """Parse a trace JSONL written by this module (or any Chrome-trace
    JSON array file) back into a list of event dicts."""
    out: List[Dict[str, Any]] = []
    with open(path) as f:
        text = f.read()
    stripped = text.strip()
    if stripped.startswith("[") and stripped.endswith("]"):
        try:                        # complete JSON array / traceEvents
            doc = json.loads(stripped)
            return doc.get("traceEvents", doc) \
                if isinstance(doc, dict) else doc
        except ValueError:
            pass
    for line in text.splitlines():
        line = line.strip().rstrip(",")
        if not line or line in ("[", "]"):
            continue
        try:
            out.append(json.loads(line))
        except ValueError:
            continue                # partial last line of a live file
    return out
