"""Worker heartbeats + stale-worker detection.

Reference: ``ParallelWrapper``'s per-GPU trainer threads died loudly
(a worker thread exception surfaced in fit); here the failure mode is
quieter — a mesh collective can wedge one process of a multi-host job,
a serving worker can stall on a poisoned batch — so liveness is an
explicit, scrapeable signal: every worker loop calls
:func:`heartbeat` once per step, ``/healthz`` (and the
``dl4j_tpu_worker_stale`` metric family) flags any worker whose last
beat is older than ``DL4J_TPU_STALE_WORKER_SECS``.

Timestamps are :func:`obs.trace.now` (monotonic); :func:`heartbeat`
and :func:`check` take explicit time arguments so tests can flag a
deliberately-stalled worker without sleeping.
"""
from __future__ import annotations

import threading
from typing import Dict, List, Optional

from deeplearning4j_tpu.obs import trace as _trace

_lock = threading.Lock()
_beats: Dict[str, float] = {}
#: per-worker staleness threshold overrides — how host LEASES keep
#: their own window (DL4J_TPU_HOST_LEASE_SECS) inside this one table:
#: a host the coordinator would evict at 15s must not read "ok" on
#: /healthz until the generic 30s worker default (no divergent
#: staleness verdicts between the membership plane and the scrape
#: surface)
_stale_after: Dict[str, float] = {}


def heartbeat(worker: str, t: Optional[float] = None) -> None:
    """Record that ``worker`` is alive at ``t`` (default: now)."""
    with _lock:
        _beats[str(worker)] = _trace.now() if t is None else t


def observe_age(worker: str, age_s: float,
                stale_after: Optional[float] = None) -> None:
    """Record a beat whose AGE is known instead of its timestamp —
    how the elastic membership coordinator mirrors cross-process lease
    files (wall-clock deadlines) into this monotonic registry: a peer
    whose lease is ``age_s`` stale shows the same staleness on
    ``/healthz`` and ``dl4j_tpu_worker_stale``, so a dying host is
    named by the scrape surface before the fleet even re-forms.
    ``stale_after`` pins THIS worker's staleness threshold (the lease
    window for hosts) so both planes render one verdict."""
    heartbeat(worker, _trace.now() - max(0.0, float(age_s)))
    if stale_after is not None:
        with _lock:
            _stale_after[str(worker)] = float(stale_after)


def retire(worker: str) -> None:
    """Forget ``worker``'s heartbeat — called when a worker loop exits
    NORMALLY (``ParallelWrapper.fit`` completing its epochs). Without
    this a finished training loop reads as a permanently stale worker
    in a long-lived train-then-serve process. A crashed loop never
    retires, so the stale alarm still fires for real wedges."""
    with _lock:
        _beats.pop(str(worker), None)
        _stale_after.pop(str(worker), None)


def check(stale_after: Optional[float] = None,
          now: Optional[float] = None) -> Dict[str, Dict]:
    """``{worker: {"age_s", "stale"}}`` for every known worker. A
    per-worker threshold recorded via :func:`observe_age` wins over
    the default (it is that worker's authoritative liveness window —
    e.g. a host's lease)."""
    if stale_after is None:
        from deeplearning4j_tpu import environment
        stale_after = environment.get_flag("DL4J_TPU_STALE_WORKER_SECS")
    now = _trace.now() if now is None else now
    with _lock:
        beats = dict(_beats)
        overrides = dict(_stale_after)
    return {w: {"age_s": now - t,
                "stale": (now - t) > overrides.get(w, stale_after)}
            for w, t in beats.items()}


def stale_workers(stale_after: Optional[float] = None,
                  now: Optional[float] = None) -> List[str]:
    return sorted(w for w, s in check(stale_after, now).items()
                  if s["stale"])


def reset() -> None:
    with _lock:
        _beats.clear()
        _stale_after.clear()
