"""Model serialization — reference:
``org.deeplearning4j.util.ModelSerializer`` (zip of configuration.json +
coefficients.bin + updaterState.bin + normalizer.bin).

TPU-native format: a zip of
  configuration.json   — full MultiLayerConfiguration JSON
  params.npz           — one entry per param leaf (path-keyed). The
                         reference's single flattened coefficient buffer
                         deliberately does NOT carry over: sharded
                         checkpointing wants per-leaf arrays (SURVEY §5).
  state.npz            — non-trainable state (BN running stats, centers)
  updater.npz          — optax state leaves (resume-exact)
  normalizer.json      — optional fitted normalizer statistics
  meta.json            — iteration/epoch counters
"""
from __future__ import annotations

import io
import json
import logging
import os
import shutil
import zipfile
from pathlib import Path
from typing import Any, Optional

import jax
import numpy as np

from deeplearning4j_tpu.resilience import checkpoint as _ckpt
from deeplearning4j_tpu.resilience import faults as _faults

logger = logging.getLogger("deeplearning4j_tpu")


def _flatten_with_paths(tree):
    flat, _ = jax.tree_util.tree_flatten_with_path(tree)
    out = {}
    for path, leaf in flat:
        key = "/".join(str(p) for p in path)
        out[key] = np.asarray(leaf)
    return out


def _writestr_det(zf: zipfile.ZipFile, name: str, data) -> None:
    """Deterministic zip entry: fixed DOS epoch timestamp so identical
    content always produces an identical archive (checksum-stable
    goldens; plain writestr stamps the current time)."""
    info = zipfile.ZipInfo(name, date_time=(1980, 1, 1, 0, 0, 0))
    info.compress_type = zipfile.ZIP_DEFLATED
    zf.writestr(info, data)


def _save_npz(zf: zipfile.ZipFile, name: str, tree) -> None:
    buf = io.BytesIO()
    np.savez(buf, **_flatten_with_paths(tree))
    _writestr_det(zf, name, buf.getvalue())


def _load_npz_into(zf: zipfile.ZipFile, name: str, tree):
    """Restore leaves into an existing pytree structure (template from a
    freshly init()ed model — mirrors the reference's approach of
    building the net from config then setting params)."""
    with zf.open(name) as f:
        data = np.load(io.BytesIO(f.read()))
        flat, treedef = jax.tree_util.tree_flatten_with_path(tree)
        leaves = []
        for path, leaf in flat:
            key = "/".join(str(p) for p in path)
            if key not in data:
                raise ValueError(f"checkpoint missing leaf {key}")
            import jax.numpy as jnp
            leaves.append(jnp.asarray(data[key]))
        return jax.tree_util.tree_unflatten(treedef, leaves)


class ModelSerializer:
    @staticmethod
    def write_model(net, path, save_updater: bool = True,
                    normalizer=None) -> None:
        """Atomic publication: the zip is assembled in a same-directory
        tmp file, fsync'd, and ``os.replace``d into place — a crash at
        any byte leaves either the previous complete checkpoint or the
        new complete checkpoint, never a truncated newest-by-mtime file
        for the restart loop to trip on (resilience/checkpoint.py)."""
        import zlib
        path = Path(path)
        path.parent.mkdir(parents=True, exist_ok=True)
        _faults.inject("ckpt_write")
        meta = {"iteration": net.iteration, "epoch": net.epoch,
                "format_version": _ckpt.FORMAT_VERSION}
        # assemble the zip in memory (this is the single-host exchange
        # format — the GB-scale path is the orbax ShardedCheckpointer);
        # the buffer is what gets CRC'd for the manifest, so the file
        # is never re-read after its fsync
        buf = io.BytesIO()
        with zipfile.ZipFile(buf, "w", zipfile.ZIP_DEFLATED) as zf:
            _writestr_det(zf, "configuration.json", net.conf.to_json())
            _save_npz(zf, "params.npz", net.params)
            _save_npz(zf, "state.npz", net.state)
            if save_updater:
                opt_state = net.opt_state
                # a ZeRO sharded-update wrapper (parallel/wrapper.py)
                # carries the LIVE optimizer moments as 1/N shards;
                # net.opt_state is the stale init copy. Fold the
                # shards into the replicated layout for the zip —
                # export is the one place that materialization is the
                # point — so listener/trainer checkpoints taken during
                # sharded training stay resume-exact.
                wref = getattr(net, "_zero_wrapper", None)
                w = wref() if wref is not None else None
                if w is not None and w.sharded_update and \
                        w._dp_state is not None and \
                        opt_state is getattr(w, "_evicted_opt", None):
                    # identity check = ownership: anything else (a
                    # later replicated wrapper, direct net.fit, a
                    # restore) reassigns net.opt_state and thereby
                    # reclaims it from the sharded wrapper
                    opt_state = w.gather_opt_state()
                if opt_state is not None:
                    _save_npz(zf, "updater.npz", opt_state)
            if normalizer is not None:
                _writestr_det(zf, "normalizer.json",
                              json.dumps(normalizer.state_dict()))
            ishape = getattr(net, "_input_shape", None)
            if ishape:
                meta["input_shape"] = list(ishape)
            # ComputationGraph: persist per-input shapes so restore can
            # init() graphs built without input_types
            shapes = getattr(net, "_shapes", None)
            if shapes and hasattr(net.conf, "inputs"):
                meta["input_shapes"] = {
                    n: list(shapes[n]) for n in net.conf.inputs}
            _writestr_det(zf, "meta.json", json.dumps(meta))
        data = buf.getvalue()
        tmp = _ckpt.tmp_path_for(path)
        try:
            with open(tmp, "wb") as f:
                f.write(data)
                f.flush()
                os.fsync(f.fileno())
            _faults.inject("ckpt_commit")
            os.replace(tmp, path)
        except BaseException:
            tmp.unlink(missing_ok=True)
            raise
        _ckpt.fsync_dir(path.parent)
        # sidecar manifest (CRC32 + size + counters) AFTER the replace:
        # losing it to a crash only downgrades verification to the
        # zip-level checks
        _ckpt.write_manifest(path, {"iteration": net.iteration,
                                    "epoch": net.epoch},
                             crc32=zlib.crc32(data) & 0xFFFFFFFF)

    @staticmethod
    def _restore(zf: zipfile.ZipFile, net, meta: dict,
                 load_updater: bool):
        net.params = _load_npz_into(zf, "params.npz", net.params)
        net.state = _load_npz_into(zf, "state.npz", net.state)
        if load_updater and "updater.npz" in zf.namelist():
            net.opt_state = _load_npz_into(zf, "updater.npz",
                                           net.opt_state)
        net.iteration = meta.get("iteration", 0)
        net.epoch = meta.get("epoch", 0)
        return net

    @staticmethod
    def restore_multi_layer_network(path, load_updater: bool = True):
        from deeplearning4j_tpu.nn.config import MultiLayerConfiguration
        from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork
        with zipfile.ZipFile(Path(path)) as zf:
            conf = MultiLayerConfiguration.from_json(
                zf.read("configuration.json").decode())
            meta = json.loads(zf.read("meta.json").decode())
            net = MultiLayerNetwork(conf)
            ishape = tuple(meta.get("input_shape") or ()) or None
            net.init(input_shape=ishape)
            return ModelSerializer._restore(zf, net, meta, load_updater)

    @staticmethod
    def restore_computation_graph(path, load_updater: bool = True):
        from deeplearning4j_tpu.nn.graph import (
            ComputationGraph, ComputationGraphConfiguration)
        with zipfile.ZipFile(Path(path)) as zf:
            conf = ComputationGraphConfiguration.from_json(
                zf.read("configuration.json").decode())
            meta = json.loads(zf.read("meta.json").decode())
            net = ComputationGraph(conf)
            ishapes = meta.get("input_shapes")
            net.init(input_shapes={k: tuple(v)
                                   for k, v in ishapes.items()}
                     if ishapes else None)
            return ModelSerializer._restore(zf, net, meta, load_updater)

    @staticmethod
    def restore_normalizer(path):
        from deeplearning4j_tpu.data.normalizers import \
            normalizer_from_state
        with zipfile.ZipFile(Path(path)) as zf:
            if "normalizer.json" not in zf.namelist():
                return None
            return normalizer_from_state(
                json.loads(zf.read("normalizer.json").decode()))


class ShardedCheckpointer:
    """Orbax-backed sharded (optionally async) checkpointing for
    distributed training — the TPU-native checkpoint path (SURVEY §5:
    "orbax-style sharded async checkpoint of a params pytree + optax
    state; the flattened-single-buffer design does NOT carry over").

    Each host writes only its shards (tensorstore layout); restore
    honors a target sharding, so a TP/DP-sharded model round-trips
    without ever materialising full arrays on one host. Keep-last-K and
    step numbering mirror the reference CheckpointListener policies.

    The zip-based ``ModelSerializer`` remains the single-host exchange
    format; this is the scale path.
    """

    def __init__(self, directory, keep_last: int = 3,
                 async_save: bool = True):
        import orbax.checkpoint as ocp
        self._ocp = ocp
        self.directory = Path(directory).absolute()
        self.directory.mkdir(parents=True, exist_ok=True)
        self._keep_last = keep_last
        self._async_save = async_save
        self.mngr = ocp.CheckpointManager(
            self.directory,
            options=ocp.CheckpointManagerOptions(
                max_to_keep=keep_last,
                enable_async_checkpointing=async_save))

    @staticmethod
    def _net_tree(net):
        """The one checkpoint structure (save and restore must agree)."""
        return {"params": net.params, "opt_state": net.opt_state,
                "state": net.state,
                "meta": {"iteration": net.iteration,
                         "epoch": net.epoch}}

    def save(self, step: int, net=None, *, tree=None, wait: bool = False):
        """Save a network's full training state (params + optimizer +
        layer state + counters) or an explicit pytree."""
        if tree is None:
            tree = self._net_tree(net)
        _faults.inject("ckpt_write")
        self.mngr.save(step, args=self._ocp.args.StandardSave(tree))
        if wait:
            self.mngr.wait_until_finished()
        return self

    def restore(self, step: Optional[int] = None, net=None, *,
                target=None):
        """Restore into ``net`` (in place) or return the raw tree.
        ``target``: a pytree of ShapeDtypeStruct/arrays (possibly with
        shardings) guiding placement; defaults to the net's current
        structure so shards land where the live arrays live."""
        step = self.latest_step() if step is None else step
        if step is None:
            raise FileNotFoundError(
                f"no checkpoints under {self.directory}")
        if net is not None and target is None:
            target = self._net_tree(net)
        args = (self._ocp.args.StandardRestore(target)
                if target is not None
                else self._ocp.args.StandardRestore())
        tree = self.mngr.restore(step, args=args)
        if net is not None:
            net.params = tree["params"]
            net.opt_state = tree["opt_state"]
            net.state = tree["state"]
            net.iteration = int(tree["meta"]["iteration"])
            net.epoch = int(tree["meta"]["epoch"])
            return net
        return tree

    # -- world manifests (elastic resharded restore) --------------------
    def _world_manifest_path(self, step: int) -> Path:
        return self.directory / f"world_{int(step)}.json"

    def world_manifest(self, step: int) -> Optional[dict]:
        """The sidecar written by :meth:`save_wrapper`: the world size
        (shard count) and optimizer layout the step was written under
        — what a restore onto a DIFFERENT world size gathers by."""
        try:
            return json.loads(self._world_manifest_path(step)
                              .read_text())
        except (OSError, ValueError):
            return None

    def save_wrapper(self, step: int, wrapper, *, wait: bool = False,
                     mesh_epoch: Optional[int] = None):
        """Checkpoint a ``ParallelWrapper``'s full training state —
        including the ZeRO sharded optimizer shards, which each device
        writes as its own 1/N (tensorstore layout): the replicated
        optimizer state is never materialized, not even to save. A
        ``world_<step>.json`` sidecar records the shard count and
        layout so a later restore onto M≠N devices knows how to
        gather and re-scatter (elastic fleets: hosts may die between
        save and restore). The manifest is published BEFORE the step
        itself: a crash in between leaves a manifest naming a step
        that never committed (harmless, pruned on the next save),
        while the reverse order would leave a committed step whose
        world size nobody can recover."""
        if jax.process_index() == 0:
            _ckpt.atomic_write_bytes(
                self._world_manifest_path(step),
                (json.dumps({
                    "step": int(step), "n_shards": int(wrapper.n),
                    "layout": ("zero-flat" if wrapper.sharded_update
                               else "replicated"),
                    "mesh_epoch": mesh_epoch}) + "\n").encode())
        self.save(step, tree=wrapper.checkpoint_tree(), wait=wait)
        if jax.process_index() == 0:
            # prune manifests whose step dirs keep-last already dropped
            steps = set(self.all_steps()) | {int(step)}
            for p in self.directory.glob("world_*.json"):
                try:
                    s = int(p.stem.split("_", 1)[1])
                except (IndexError, ValueError):
                    continue
                if s not in steps:
                    p.unlink(missing_ok=True)
        return self

    def restore_wrapper(self, wrapper, step: Optional[int] = None, *,
                        reshard: bool = True):
        """Restore a ``save_wrapper`` checkpoint into ``wrapper``.

        Same topology (checkpoint shard count == ``wrapper.n`` and
        same layout): the wrapper's live state tree (with its
        shardings) is the restore target, so ZeRO optimizer shards
        land directly back on their devices.

        Different topology (``reshard=True``, the default): the
        elastic-restore path — *gather by manifest, re-scatter by
        layout*. The ``world_<step>.json`` manifest names the source
        shard count N; a fully-replicated restore target is built
        analytically from the wrapper's own net (the padded flat
        shapes are a pure function of (params, N)), orbax gathers the
        saved shards into whole leaves, and
        ``ParallelWrapper.load_gathered_tree`` re-pads them through
        ``FlatShardLayout`` onto the surviving M devices — bit-exact
        on the real content (the zero pad is a training invariant;
        see ``parallel/zero.py::repad_flat_leaves``)."""
        step = self.latest_step() if step is None else step
        if step is None:
            raise FileNotFoundError(
                f"no checkpoints under {self.directory}")
        wm = self.world_manifest(step)
        want_layout = ("zero-flat" if wrapper.sharded_update
                       else "replicated")
        n_src = int(wm["n_shards"]) if wm else int(wrapper.n)
        src_layout = (wm or {}).get("layout", want_layout)
        if n_src == wrapper.n and src_layout == want_layout:
            tree = self.restore(step,
                                target=wrapper.checkpoint_target())
            wrapper.load_checkpoint_tree(tree)
            return wrapper
        if not reshard:
            raise ValueError(
                f"checkpoint step {step} was written at "
                f"{n_src} shards ({src_layout}) but the wrapper runs "
                f"{wrapper.n} ({want_layout}); pass reshard=True to "
                "gather and re-scatter")
        tree = self._restore_gathered(step, wrapper, n_src, src_layout)
        wrapper.load_gathered_tree(tree, src_layout)
        logger.warning(
            "resharded restore: step %d (%d shards, %s) -> %d shards",
            step, n_src, src_layout, wrapper.n)
        return wrapper

    def _restore_gathered(self, step: int, wrapper, n_src: int,
                          src_layout: str):
        """Gather-by-manifest: restore every leaf fully replicated on
        the wrapper's (new) mesh. The target is built analytically —
        params/state shapes from the live net, optimizer shapes from
        ``optimizer.init`` over the SOURCE flat layout — because the
        checkpoint's own sharding metadata names devices that no
        longer exist."""
        from jax.sharding import NamedSharding, PartitionSpec as P
        from deeplearning4j_tpu.parallel.zero import FlatShardLayout
        net = wrapper.net
        repl = NamedSharding(wrapper.mesh, P())

        def sds(leaf):
            return jax.ShapeDtypeStruct(tuple(leaf.shape), leaf.dtype,
                                        sharding=repl)

        if src_layout == "zero-flat":
            opt_ref = jax.eval_shape(
                lambda p: net._optimizer.init(
                    FlatShardLayout(p, n_src).flatten(p)), net.params)
        else:
            opt_ref = jax.eval_shape(net._optimizer.init, net.params)
        target = {
            "params": jax.tree.map(sds,
                                   jax.eval_shape(lambda: net.params)),
            "opt": jax.tree.map(sds, opt_ref),
            "state": jax.tree.map(sds,
                                  jax.eval_shape(lambda: net.state)),
            "meta": {"iteration": 0, "epoch": 0},
        }
        return self.mngr.restore(
            step, args=self._ocp.args.StandardRestore(target))

    def restore_latest_valid(self, net=None, *, target=None,
                             wrapper=None):
        """Restore the newest step that actually restores, walking
        newest→oldest; an unrestorable (corrupt/partial) step dir is
        quarantined to ``corrupt/`` and the scan falls back — the
        sharded-path analog of
        ``resilience.checkpoint.newest_valid_checkpoint``. With
        ``wrapper=`` each candidate goes through
        :meth:`restore_wrapper` instead, so the fallback chain keeps
        its reshard-onto-M≠N capability: a corrupt newest written at
        8 devices quarantines, and the next-newest valid one still
        reshards onto the surviving 4."""
        from deeplearning4j_tpu.parallel.zero import LayoutMismatch
        last_err: Optional[Exception] = None
        while True:
            steps = sorted(self.all_steps(), reverse=True)
            if not steps:
                raise FileNotFoundError(
                    f"no restorable checkpoints under {self.directory}"
                ) from last_err
            step = steps[0]
            try:
                if wrapper is not None:
                    return self.restore_wrapper(wrapper, step)
                return self.restore(step, net=net, target=target)
            except (KeyboardInterrupt, SystemExit):
                raise
            except LayoutMismatch:
                # configuration error (wrong net for this checkpoint
                # dir), NOT corruption: fail fast — quarantining would
                # walk the chain and move aside every valid step
                raise
            except Exception as e:
                last_err = e
                logger.warning("sharded checkpoint step %d unrestorable "
                               "(%s); quarantining and falling back",
                               step, e)
                if not self._quarantine_step(step, str(e)):
                    # the corrupt step could not be moved aside (e.g.
                    # read-only mount): the next scan would retry the
                    # SAME step forever — fail loudly instead
                    raise

    def _quarantine_step(self, step: int, reason: str) -> bool:
        """Move a step dir to ``corrupt/``; returns False when nothing
        moved (caller must not loop on the same step)."""
        from deeplearning4j_tpu.resilience import checkpoint as _rck
        step_dir = self.directory / str(step)
        # the manager caches its step list (and may hold handles into
        # the dir): close, move, re-open
        self.mngr.close()
        if step_dir.is_dir():
            moved = _rck.quarantine(step_dir, reason) is not None
            if not moved and not step_dir.is_dir():
                # a concurrently-restoring peer won the move race —
                # the step is out of the scan either way
                moved = True
        else:
            # already moved aside (a peer, or a prior attempt): the
            # goal — this step out of every scan — is achieved
            moved = True
        if moved:
            # the world sidecar goes with its step (evidence stays
            # paired; a later save at the same step number must not
            # inherit a stale manifest)
            wm = self._world_manifest_path(step)
            if wm.is_file():
                try:
                    shutil.move(str(wm),
                                str(step_dir.parent / _rck.CORRUPT_DIR
                                    / wm.name))
                except OSError:
                    wm.unlink(missing_ok=True)
        self.mngr = self._ocp.CheckpointManager(
            self.directory,
            options=self._ocp.CheckpointManagerOptions(
                max_to_keep=self._keep_last,
                enable_async_checkpointing=self._async_save))
        return moved

    def latest_step(self) -> Optional[int]:
        return self.mngr.latest_step()

    def all_steps(self):
        return sorted(self.mngr.all_steps())

    def wait_until_finished(self):
        self.mngr.wait_until_finished()

    def close(self):
        self.mngr.close()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
