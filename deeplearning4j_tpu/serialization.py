"""Model serialization — reference:
``org.deeplearning4j.util.ModelSerializer`` (zip of configuration.json +
coefficients.bin + updaterState.bin + normalizer.bin).

TPU-native format: a zip of
  configuration.json   — full MultiLayerConfiguration JSON
  params.npz           — one entry per param leaf (path-keyed). The
                         reference's single flattened coefficient buffer
                         deliberately does NOT carry over: sharded
                         checkpointing wants per-leaf arrays (SURVEY §5).
  state.npz            — non-trainable state (BN running stats, centers)
  updater.npz          — optax state leaves (resume-exact)
  normalizer.json      — optional fitted normalizer statistics
  meta.json            — iteration/epoch counters
"""
from __future__ import annotations

import io
import json
import zipfile
from pathlib import Path
from typing import Any, Optional

import jax
import numpy as np


def _flatten_with_paths(tree):
    flat, _ = jax.tree_util.tree_flatten_with_path(tree)
    out = {}
    for path, leaf in flat:
        key = "/".join(str(p) for p in path)
        out[key] = np.asarray(leaf)
    return out


def _save_npz(zf: zipfile.ZipFile, name: str, tree) -> None:
    buf = io.BytesIO()
    np.savez(buf, **_flatten_with_paths(tree))
    zf.writestr(name, buf.getvalue())


def _load_npz_into(zf: zipfile.ZipFile, name: str, tree):
    """Restore leaves into an existing pytree structure (template from a
    freshly init()ed model — mirrors the reference's approach of
    building the net from config then setting params)."""
    with zf.open(name) as f:
        data = np.load(io.BytesIO(f.read()))
        flat, treedef = jax.tree_util.tree_flatten_with_path(tree)
        leaves = []
        for path, leaf in flat:
            key = "/".join(str(p) for p in path)
            if key not in data:
                raise ValueError(f"checkpoint missing leaf {key}")
            import jax.numpy as jnp
            leaves.append(jnp.asarray(data[key]))
        return jax.tree_util.tree_unflatten(treedef, leaves)


class ModelSerializer:
    @staticmethod
    def write_model(net, path, save_updater: bool = True,
                    normalizer=None) -> None:
        path = Path(path)
        path.parent.mkdir(parents=True, exist_ok=True)
        with zipfile.ZipFile(path, "w", zipfile.ZIP_DEFLATED) as zf:
            zf.writestr("configuration.json", net.conf.to_json())
            _save_npz(zf, "params.npz", net.params)
            _save_npz(zf, "state.npz", net.state)
            if save_updater and net.opt_state is not None:
                _save_npz(zf, "updater.npz", net.opt_state)
            if normalizer is not None:
                zf.writestr("normalizer.json",
                            json.dumps(normalizer.state_dict()))
            meta = {"iteration": net.iteration, "epoch": net.epoch,
                    "format_version": 1}
            ishape = getattr(net, "_input_shape", None)
            if ishape:
                meta["input_shape"] = list(ishape)
            # ComputationGraph: persist per-input shapes so restore can
            # init() graphs built without input_types
            shapes = getattr(net, "_shapes", None)
            if shapes and hasattr(net.conf, "inputs"):
                meta["input_shapes"] = {
                    n: list(shapes[n]) for n in net.conf.inputs}
            zf.writestr("meta.json", json.dumps(meta))

    @staticmethod
    def _restore(zf: zipfile.ZipFile, net, meta: dict,
                 load_updater: bool):
        net.params = _load_npz_into(zf, "params.npz", net.params)
        net.state = _load_npz_into(zf, "state.npz", net.state)
        if load_updater and "updater.npz" in zf.namelist():
            net.opt_state = _load_npz_into(zf, "updater.npz",
                                           net.opt_state)
        net.iteration = meta.get("iteration", 0)
        net.epoch = meta.get("epoch", 0)
        return net

    @staticmethod
    def restore_multi_layer_network(path, load_updater: bool = True):
        from deeplearning4j_tpu.nn.config import MultiLayerConfiguration
        from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork
        with zipfile.ZipFile(Path(path)) as zf:
            conf = MultiLayerConfiguration.from_json(
                zf.read("configuration.json").decode())
            meta = json.loads(zf.read("meta.json").decode())
            net = MultiLayerNetwork(conf)
            ishape = tuple(meta.get("input_shape") or ()) or None
            net.init(input_shape=ishape)
            return ModelSerializer._restore(zf, net, meta, load_updater)

    @staticmethod
    def restore_computation_graph(path, load_updater: bool = True):
        from deeplearning4j_tpu.nn.graph import (
            ComputationGraph, ComputationGraphConfiguration)
        with zipfile.ZipFile(Path(path)) as zf:
            conf = ComputationGraphConfiguration.from_json(
                zf.read("configuration.json").decode())
            meta = json.loads(zf.read("meta.json").decode())
            net = ComputationGraph(conf)
            ishapes = meta.get("input_shapes")
            net.init(input_shapes={k: tuple(v)
                                   for k, v in ishapes.items()}
                     if ishapes else None)
            return ModelSerializer._restore(zf, net, meta, load_updater)

    @staticmethod
    def restore_normalizer(path):
        from deeplearning4j_tpu.data.normalizers import \
            normalizer_from_state
        with zipfile.ZipFile(Path(path)) as zf:
            if "normalizer.json" not in zf.namelist():
                return None
            return normalizer_from_state(
                json.loads(zf.read("normalizer.json").decode()))
