"""NDArrayIndex — the reference's indexing DSL.

Reference: ``org.nd4j.linalg.indexing.NDArrayIndex`` (+
``INDArrayIndex`` impls: ``interval``, ``point``, ``all``,
``newAxis``) used as ``arr.get(NDArrayIndex.point(0),
NDArrayIndex.interval(1, 3))``.

TPU-native: each index resolves to a numpy-style basic index, so
``get`` stays a pure (jit-traceable, zero-copy view) gather and
``put`` is one functional ``.at[...].set``."""
from __future__ import annotations

from typing import Any, Tuple


class _Index:
    def resolve(self):
        raise NotImplementedError


class _Interval(_Index):
    def __init__(self, start, end, step=1, inclusive=False):
        self.start, self.end, self.step = start, end, step
        self.inclusive = inclusive

    def resolve(self):
        end = self.end + 1 if self.inclusive else self.end
        return slice(self.start, end, self.step)


class _Point(_Index):
    def __init__(self, i):
        self.i = i

    def resolve(self):
        return int(self.i)


class _All(_Index):
    def resolve(self):
        return slice(None)


class _NewAxis(_Index):
    def resolve(self):
        return None


class NDArrayIndex:
    """Factory (reference NDArrayIndex static methods)."""

    @staticmethod
    def interval(start: int, end: int, step: int = 1,
                 inclusive: bool = False) -> _Index:
        return _Interval(start, end, step, inclusive)

    @staticmethod
    def point(i: int) -> _Index:
        return _Point(i)

    @staticmethod
    def all() -> _Index:
        return _All()

    @staticmethod
    def new_axis() -> _Index:
        return _NewAxis()


def resolve_indices(indices: Tuple[Any, ...]):
    # _NewAxis.resolve() is None, which IS numpy's new-axis index
    return tuple(ix.resolve() if isinstance(ix, _Index) else ix
                 for ix in indices)
