"""Eager ndarray façade — the ``INDArray`` / ``Nd4j`` equivalent.

Reference: ``org.nd4j.linalg.api.ndarray.INDArray`` (~700 methods) and the
``org.nd4j.linalg.factory.Nd4j`` static factory. Here the heavy lifting is
``jax.Array`` + XLA: every method is a thin call into ``jax.numpy``, which
jit-caches compiled kernels per shape/dtype, so eager UX costs O(cache
lookup) instead of a JNI crossing per op (reference call stack SURVEY §3.2).

Design notes (TPU-first):
 - No strides/views/TAD machinery — XLA owns layout. ``i``-suffixed
   "in-place" methods from the reference (``addi``, ``subi``…) exist for
   API parity but are functional underneath (they rebind the wrapped
   buffer; jax.Array is immutable).
 - dtype promotion follows jnp; default float dtype from ``dtypes``.
"""
from __future__ import annotations

from typing import Any, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from deeplearning4j_tpu import dtypes

#: process-wide open-workspace count (hint only — the authoritative
#: scope lookup in utils.workspace is thread-local): the hot eager path
#: pays one int check when no workspace is open anywhere
_WS_DEPTH = 0
import threading as _threading  # noqa: E402
_WS_HINT_LOCK = _threading.Lock()


def _unwrap(x):
    return x.jax() if isinstance(x, NDArray) else x


class NDArray:
    """Thin eager wrapper over a ``jax.Array``.

    Reference parity: org.nd4j.linalg.api.ndarray.BaseNDArray.
    """

    __slots__ = ("_a", "__weakref__")
    __array_priority__ = 100  # beat numpy in mixed expressions

    def __init__(self, value, dtype=None):
        if isinstance(value, NDArray):
            value = value._a
        if dtype is not None:
            self._a = jnp.asarray(value, dtype=dtypes.resolve(dtype))
        else:
            self._a = jnp.asarray(value)
        if _WS_DEPTH:                    # workspace tracking (utils.workspace)
            from deeplearning4j_tpu.utils.workspace import \
                register_allocation
            register_allocation(self)

    # -- interop ----------------------------------------------------------
    def jax(self) -> jax.Array:
        return self._a

    def numpy(self) -> np.ndarray:
        return np.asarray(self._a)

    def __jax_array__(self):
        return self._a

    def item(self):
        return self._a.item()

    # -- properties -------------------------------------------------------
    @property
    def shape(self):
        return tuple(self._a.shape)

    @property
    def dtype(self):
        return self._a.dtype

    @property
    def ndim(self) -> int:
        return self._a.ndim

    def rank(self) -> int:
        return self._a.ndim

    def length(self) -> int:
        return int(self._a.size)

    @property
    def size(self) -> int:
        return int(self._a.size)

    def size_at(self, dim: int) -> int:
        return self._a.shape[dim]

    def is_scalar(self) -> bool:
        return self._a.ndim == 0

    def is_vector(self) -> bool:
        return self._a.ndim == 1

    def is_matrix(self) -> bool:
        return self._a.ndim == 2

    # -- shape ops --------------------------------------------------------
    def reshape(self, *shape) -> "NDArray":
        if len(shape) == 1 and isinstance(shape[0], (tuple, list)):
            shape = tuple(shape[0])
        return NDArray(jnp.reshape(self._a, shape))

    def ravel(self) -> "NDArray":
        return NDArray(jnp.ravel(self._a))

    def transpose(self, *axes) -> "NDArray":
        if not axes:
            return NDArray(jnp.transpose(self._a))
        if len(axes) == 1 and isinstance(axes[0], (tuple, list)):
            axes = tuple(axes[0])
        return NDArray(jnp.transpose(self._a, axes))

    def permute(self, *axes) -> "NDArray":
        return self.transpose(*axes)

    @property
    def T(self) -> "NDArray":
        return NDArray(self._a.T)

    def swap_axes(self, a: int, b: int) -> "NDArray":
        return NDArray(jnp.swapaxes(self._a, a, b))

    def broadcast_to(self, shape) -> "NDArray":
        return NDArray(jnp.broadcast_to(self._a, tuple(shape)))

    def expand_dims(self, axis: int) -> "NDArray":
        return NDArray(jnp.expand_dims(self._a, axis))

    def squeeze(self, axis=None) -> "NDArray":
        return NDArray(jnp.squeeze(self._a, axis))

    def repeat(self, repeats, axis=None) -> "NDArray":
        return NDArray(jnp.repeat(self._a, repeats, axis))

    def tile(self, reps) -> "NDArray":
        return NDArray(jnp.tile(self._a, reps))

    def dup(self) -> "NDArray":
        """Reference: INDArray.dup(). jax.Array is immutable; copy is free."""
        return NDArray(self._a)

    def cast(self, dtype) -> "NDArray":
        return NDArray(self._a.astype(dtypes.resolve(dtype)))

    astype = cast

    # -- indexing ---------------------------------------------------------
    def __getitem__(self, idx) -> "NDArray":
        return NDArray(self._a[_unwrap(idx) if not isinstance(idx, tuple)
                               else tuple(_unwrap(i) for i in idx)])

    def put(self, idx, value) -> "NDArray":
        """Functional scatter (reference putScalar/put are mutating)."""
        if isinstance(idx, tuple):
            idx = tuple(_unwrap(i) for i in idx)
        else:
            idx = _unwrap(idx)
        return NDArray(self._a.at[idx].set(_unwrap(value)))

    def get_scalar(self, *idx):
        return self._a[tuple(idx)].item()

    def slice_along(self, i: int, axis: int = 0) -> "NDArray":
        return NDArray(jnp.take(self._a, i, axis=axis))

    # -- arithmetic (functional + reference "i"-parity names) -------------
    def _binop(self, other, fn) -> "NDArray":
        return NDArray(fn(self._a, _unwrap(other)))

    def add(self, o): return self._binop(o, jnp.add)
    def sub(self, o): return self._binop(o, jnp.subtract)
    def mul(self, o): return self._binop(o, jnp.multiply)
    def div(self, o): return self._binop(o, jnp.divide)
    def rsub(self, o): return NDArray(jnp.subtract(_unwrap(o), self._a))
    def rdiv(self, o): return NDArray(jnp.divide(_unwrap(o), self._a))
    def pow(self, o): return self._binop(o, jnp.power)
    def fmod(self, o): return self._binop(o, jnp.fmod)

    # In-place spellings rebind the buffer (functional underneath).
    def addi(self, o): self._a = jnp.add(self._a, _unwrap(o)); return self
    def subi(self, o): self._a = jnp.subtract(self._a, _unwrap(o)); return self
    def muli(self, o): self._a = jnp.multiply(self._a, _unwrap(o)); return self
    def divi(self, o): self._a = jnp.divide(self._a, _unwrap(o)); return self
    def assign(self, o):
        self._a = jnp.broadcast_to(jnp.asarray(_unwrap(o), self._a.dtype),
                                   self._a.shape)
        return self

    __add__ = add
    __radd__ = add
    __sub__ = sub
    def __rsub__(self, o): return self.rsub(o)
    __mul__ = mul
    __rmul__ = mul
    __truediv__ = div
    def __rtruediv__(self, o): return self.rdiv(o)
    __pow__ = pow
    def __neg__(self): return NDArray(-self._a)
    def __abs__(self): return NDArray(jnp.abs(self._a))
    def __matmul__(self, o): return self.mmul(o)

    # -- comparisons ------------------------------------------------------
    def __lt__(self, o): return self._binop(o, jnp.less)
    def __le__(self, o): return self._binop(o, jnp.less_equal)
    def __gt__(self, o): return self._binop(o, jnp.greater)
    def __ge__(self, o): return self._binop(o, jnp.greater_equal)
    def eq(self, o): return self._binop(o, jnp.equal)
    def neq(self, o): return self._binop(o, jnp.not_equal)

    def __eq__(self, o):
        """Elementwise equality (numpy semantics — safe under jit
        tracing). For the reference's INDArray.equals whole-array
        boolean, use :meth:`equals`."""
        if isinstance(o, (NDArray, jax.Array, np.ndarray, int, float,
                          bool)):
            return self._binop(o, jnp.equal)
        return NotImplemented

    def equals(self, o) -> bool:
        """Whole-array value equality (reference INDArray.equals).
        Eager-only: do not call inside jit."""
        a, b = self._a, _unwrap(o)
        return a.shape == b.shape and bool(jnp.all(a == b))

    # Elementwise __eq__ ⇒ unhashable, same stance as np.ndarray.
    __hash__ = None

    # -- linalg -----------------------------------------------------------
    def mmul(self, other) -> "NDArray":
        return NDArray(jnp.matmul(self._a, _unwrap(other)))

    def dot(self, other) -> "NDArray":
        return NDArray(jnp.dot(self._a, _unwrap(other)))

    def tensordot(self, other, axes) -> "NDArray":
        return NDArray(jnp.tensordot(self._a, _unwrap(other), axes))

    # -- reductions -------------------------------------------------------
    def _reduce(self, fn, axis, keepdims=False) -> "NDArray":
        return NDArray(fn(self._a, axis=axis, keepdims=keepdims))

    def sum(self, axis=None, keepdims=False):
        return self._reduce(jnp.sum, axis, keepdims)

    def mean(self, axis=None, keepdims=False):
        return self._reduce(jnp.mean, axis, keepdims)

    def std(self, axis=None, keepdims=False, ddof=1):
        return NDArray(jnp.std(self._a, axis=axis, keepdims=keepdims,
                               ddof=ddof))

    def var(self, axis=None, keepdims=False, ddof=1):
        return NDArray(jnp.var(self._a, axis=axis, keepdims=keepdims,
                               ddof=ddof))

    def max(self, axis=None, keepdims=False):
        return self._reduce(jnp.max, axis, keepdims)

    def min(self, axis=None, keepdims=False):
        return self._reduce(jnp.min, axis, keepdims)

    def prod(self, axis=None, keepdims=False):
        return self._reduce(jnp.prod, axis, keepdims)

    def argmax(self, axis=None):
        return NDArray(jnp.argmax(self._a, axis=axis))

    def argmin(self, axis=None):
        return NDArray(jnp.argmin(self._a, axis=axis))

    def cumsum(self, axis=None):
        return NDArray(jnp.cumsum(self._a, axis=axis))

    def norm1(self, axis=None):
        return NDArray(jnp.sum(jnp.abs(self._a), axis=axis))

    def norm2(self, axis=None):
        return NDArray(jnp.sqrt(jnp.sum(jnp.square(self._a), axis=axis)))

    def norm_max(self, axis=None):
        return NDArray(jnp.max(jnp.abs(self._a), axis=axis))

    def any(self): return bool(jnp.any(self._a))
    def all(self): return bool(jnp.all(self._a))

    # -- elementwise math (reference Transforms.*) ------------------------
    def _map(self, fn) -> "NDArray":
        return NDArray(fn(self._a))

    def abs(self): return self._map(jnp.abs)
    def neg(self): return self._map(jnp.negative)
    def exp(self): return self._map(jnp.exp)
    def log(self): return self._map(jnp.log)
    def sqrt(self): return self._map(jnp.sqrt)
    def square(self): return self._map(jnp.square)
    def sin(self): return self._map(jnp.sin)
    def cos(self): return self._map(jnp.cos)
    def tanh(self): return self._map(jnp.tanh)
    def sigmoid(self): return self._map(jax.nn.sigmoid)
    def relu(self): return self._map(jax.nn.relu)
    def softmax(self, axis=-1):
        return NDArray(jax.nn.softmax(self._a, axis=axis))
    def floor(self): return self._map(jnp.floor)
    def ceil(self): return self._map(jnp.ceil)
    def round(self): return self._map(jnp.round)
    def sign(self): return self._map(jnp.sign)
    def clip(self, lo, hi): return NDArray(jnp.clip(self._a, lo, hi))

    # -- misc -------------------------------------------------------------
    def isnan(self): return self._map(jnp.isnan)
    def isinf(self): return self._map(jnp.isinf)

    def __len__(self):
        return self._a.shape[0]

    def __repr__(self):
        return f"NDArray({np.asarray(self._a)!r})"

    def __format__(self, spec):
        return format(np.asarray(self._a), spec)



    # -- row/column vector broadcasting (reference addRowVector etc.) ---
    def _rowvec(self, other, op):
        o = jnp.asarray(_unwrap(other)).reshape(1, -1)
        return NDArray(op(self._a, o))

    def _colvec(self, other, op):
        o = jnp.asarray(_unwrap(other)).reshape(-1, 1)
        return NDArray(op(self._a, o))

    def add_row_vector(self, v):
        return self._rowvec(v, jnp.add)

    def sub_row_vector(self, v):
        return self._rowvec(v, jnp.subtract)

    def mul_row_vector(self, v):
        return self._rowvec(v, jnp.multiply)

    def div_row_vector(self, v):
        return self._rowvec(v, jnp.divide)

    def add_column_vector(self, v):
        return self._colvec(v, jnp.add)

    def sub_column_vector(self, v):
        return self._colvec(v, jnp.subtract)

    def mul_column_vector(self, v):
        return self._colvec(v, jnp.multiply)

    def div_column_vector(self, v):
        return self._colvec(v, jnp.divide)

    # -- row/column access (reference getRow/putRow/getColumn…) ---------
    def get_row(self, i):
        return NDArray(self._a[i])

    def get_rows(self, *idx):
        return NDArray(self._a[jnp.asarray(idx)])

    def get_column(self, i):
        return NDArray(self._a[:, i])

    def get_columns(self, *idx):
        return NDArray(self._a[:, jnp.asarray(idx)])

    def put_row(self, i, v):
        self._a = self._a.at[i].set(jnp.asarray(_unwrap(v)))
        return self

    def put_column(self, i, v):
        self._a = self._a.at[:, i].set(jnp.asarray(_unwrap(v)))
        return self

    def put_scalar(self, idx, value):
        if isinstance(idx, int):
            idx = (idx,)
        self._a = self._a.at[tuple(idx)].set(value)
        return self

    def get_double(self, *idx):
        return float(self._a[tuple(idx)])

    def get_int(self, *idx):
        return int(self._a[tuple(idx)])

    # -- number-returning reductions (reference sumNumber() etc.) -------
    def sum_number(self):
        return float(jnp.sum(self._a))

    def mean_number(self):
        return float(jnp.mean(self._a))

    def max_number(self):
        return float(jnp.max(self._a))

    def min_number(self):
        return float(jnp.min(self._a))

    def std_number(self):
        # Bessel-corrected like std() and the reference stdNumber()
        return float(jnp.std(self._a, ddof=1))

    def amax(self, axis=None, keepdims=False):
        return NDArray(jnp.max(jnp.abs(self._a), axis=axis,
                               keepdims=keepdims))

    def amin(self, axis=None, keepdims=False):
        return NDArray(jnp.min(jnp.abs(self._a), axis=axis,
                               keepdims=keepdims))

    def amean(self, axis=None, keepdims=False):
        return NDArray(jnp.mean(jnp.abs(self._a), axis=axis,
                                keepdims=keepdims))

    # -- named comparisons (reference gt/lt/gte/lte return masks) -------
    def gt(self, o):
        return NDArray(self._a > jnp.asarray(_unwrap(o)))

    def gte(self, o):
        return NDArray(self._a >= jnp.asarray(_unwrap(o)))

    def lt(self, o):
        return NDArray(self._a < jnp.asarray(_unwrap(o)))

    def lte(self, o):
        return NDArray(self._a <= jnp.asarray(_unwrap(o)))

    # -- distances (reference distance1/distance2/cosineSim) ------------
    def distance1(self, o):
        return float(jnp.sum(jnp.abs(self._a - _unwrap(o))))

    def distance2(self, o):
        return float(jnp.sqrt(jnp.sum(jnp.square(
            self._a - _unwrap(o)))))

    def cosine_sim(self, o):
        b = jnp.asarray(_unwrap(o))
        return float(jnp.sum(self._a * b)
                     / (jnp.linalg.norm(self._a)
                        * jnp.linalg.norm(b) + 1e-12))

    # -- NDArrayIndex DSL (reference get(INDArrayIndex...)/put) ----------
    def get(self, *indices) -> "NDArray":
        """arr.get(NDArrayIndex.point(0), NDArrayIndex.interval(1, 3))
        (reference INDArray.get with the indexing DSL)."""
        from deeplearning4j_tpu.ndarray_index import resolve_indices
        return NDArray(self._a[resolve_indices(indices)])

    def put_indices(self, indices, value) -> "NDArray":
        """Functional put at DSL indices (reference INDArray.put(
        INDArrayIndex[], INDArray)) — returns the updated array."""
        from deeplearning4j_tpu.ndarray_index import resolve_indices
        return NDArray(self._a.at[resolve_indices(tuple(indices))]
                       .set(jnp.asarray(_unwrap(value))))

    # -- shape predicates / host exports (reference INDArray) ------------
    def rows(self) -> int:
        return int(self._a.shape[0])

    def columns(self) -> int:
        return int(self._a.shape[1])

    def is_row_vector(self) -> bool:
        return self._a.ndim == 1 or (self._a.ndim == 2
                                     and self._a.shape[0] == 1)

    def is_column_vector(self) -> bool:
        return self._a.ndim == 2 and self._a.shape[1] == 1

    def is_square(self) -> bool:
        return (self._a.ndim == 2
                and self._a.shape[0] == self._a.shape[1])

    def to_int_vector(self):
        return [int(v) for v in np.asarray(self._a).ravel()]

    def to_double_vector(self):
        return [float(v) for v in np.asarray(self._a).ravel()]

    def to_float_matrix(self):
        return np.asarray(self._a, np.float32).tolist()

    # -- number reductions missing from the commit-fae4081 set -----------
    def median_number(self) -> float:
        return float(jnp.median(self._a))

    def percentile_number(self, q) -> float:
        return float(jnp.percentile(self._a, q))

    def entropy_number(self) -> float:
        import jax.scipy.special as jsp
        return float(-jnp.sum(jsp.xlogy(self._a, self._a)))

    def var_number(self) -> float:
        return float(jnp.var(self._a))

    def prod_number(self) -> float:
        return float(jnp.prod(self._a))

    # -- conditional replace (reference replaceWhere/getWhere/cond) ------
    def replace_where(self, replacement, condition) -> "NDArray":
        """Elements matching ``condition`` replaced from ``replacement``
        (reference BooleanIndexing.replaceWhere)."""
        m = condition(self._a) if callable(condition) else condition
        return NDArray(jnp.where(jnp.asarray(_unwrap(m)),
                                 jnp.asarray(_unwrap(replacement)),
                                 self._a))

    def get_where(self, comp, condition):
        """Eager boolean select (reference getWhere) — returns the
        matching elements as a flat NDArray."""
        m = condition(self._a) if callable(condition) else condition
        return NDArray(self._a[jnp.asarray(_unwrap(m))])

    def cond(self, condition) -> "NDArray":
        """Boolean mask of elements matching condition (reference
        MatchConditionTransform)."""
        m = condition(self._a) if callable(condition) else condition
        return NDArray(jnp.asarray(_unwrap(m)).astype(self._a.dtype))

    # -- tensor-along-dimension (reference TAD API) ----------------------
    def tensors_along_dimension(self, *dims) -> int:
        n = self._a.size
        for d in dims:
            n //= self._a.shape[d]
        return int(n)

    def tensor_along_dimension(self, index, *dims) -> "NDArray":
        """The index-th sub-tensor spanning ``dims`` (reference
        tensorAlongDimension): iterate the remaining axes C-order."""
        other = [d for d in range(self._a.ndim) if d not in dims]
        moved = jnp.moveaxis(self._a, other,
                             list(range(len(other))))
        lead = 1
        for d in other:
            lead *= self._a.shape[d]
        flat = moved.reshape((lead,) + moved.shape[len(other):])
        return NDArray(flat[index])

    def vector_along_dimension(self, index, dim) -> "NDArray":
        return self.tensor_along_dimension(index, dim)

    def vectors_along_dimension(self, dim) -> int:
        return self.tensors_along_dimension(dim)


def _ndarray_unflatten(_, children):
    # Rebind the leaf directly: transforms (eval_shape, jit tracing) pass
    # tracer/ShapeDtypeStruct leaves that jnp.asarray would reject.
    obj = object.__new__(NDArray)
    obj._a = children[0]
    return obj


jax.tree_util.register_pytree_node(
    NDArray,
    lambda x: ((x._a,), None),
    _ndarray_unflatten,
)


class Nd4j:
    """Static factory — reference: ``org.nd4j.linalg.factory.Nd4j``."""

    @staticmethod
    def exec(op_name: str, *args, **kwargs):
        """Run any registered declarable op eagerly on NDArrays
        (reference ``Nd4j.exec(DynamicCustomOp)`` — name + args into the
        op registry instead of a JNI dispatch). Returns NDArray(s)."""
        from deeplearning4j_tpu.autodiff.ops_registry import get_op
        from deeplearning4j_tpu.utils.profiler import OpProfiler
        fn = get_op(op_name)
        prof = OpProfiler.get_instance()
        if prof.verbose or prof.enabled:
            prof.op_executed(op_name, args, kwargs)
        out = fn(*[_unwrap(a) for a in args], **kwargs)
        if isinstance(out, tuple):
            return tuple(NDArray(o) if hasattr(o, "dtype") else o
                         for o in out)
        return NDArray(out) if hasattr(out, "dtype") else out

    @staticmethod
    def create(data=None, shape=None, dtype=None) -> NDArray:
        if data is None:
            return Nd4j.zeros(shape, dtype)
        arr = NDArray(data, dtype=dtype or dtypes.default_dtype())
        if shape is not None:
            arr = arr.reshape(shape)
        return arr

    @staticmethod
    def zeros(shape, dtype=None) -> NDArray:
        return NDArray(jnp.zeros(_shape(shape), dtypes.resolve(dtype)))

    @staticmethod
    def ones(shape, dtype=None) -> NDArray:
        return NDArray(jnp.ones(_shape(shape), dtypes.resolve(dtype)))

    @staticmethod
    def full(shape, value, dtype=None) -> NDArray:
        return NDArray(jnp.full(_shape(shape), value, dtypes.resolve(dtype)))

    value_array_of = full

    @staticmethod
    def eye(n, dtype=None) -> NDArray:
        return NDArray(jnp.eye(n, dtype=dtypes.resolve(dtype)))

    @staticmethod
    def arange(*args, dtype=None) -> NDArray:
        return NDArray(jnp.arange(*args, dtype=dtype and dtypes.resolve(dtype)))

    @staticmethod
    def linspace(lo, hi, num, dtype=None) -> NDArray:
        return NDArray(jnp.linspace(lo, hi, num,
                                    dtype=dtypes.resolve(dtype)))

    @staticmethod
    def rand(shape, seed: Optional[int] = None) -> NDArray:
        """Uniform [0,1). Without ``seed``, draws from an advancing
        global stream (reference Nd4j.rand semantics — successive calls
        differ); with ``seed``, deterministic."""
        return NDArray(jax.random.uniform(_next_key(seed), _shape(shape),
                                          dtypes.default_dtype()))

    @staticmethod
    def randn(shape, seed: Optional[int] = None) -> NDArray:
        return NDArray(jax.random.normal(_next_key(seed), _shape(shape),
                                         dtypes.default_dtype()))

    @staticmethod
    def set_random_seed(seed: int) -> None:
        """Reset the global stream (reference Nd4j.getRandom().setSeed)."""
        _GLOBAL_KEY[0] = jax.random.PRNGKey(seed)

    @staticmethod
    def concat(axis: int, *arrays) -> NDArray:
        return NDArray(jnp.concatenate([_unwrap(a) for a in arrays],
                                       axis=axis))

    @staticmethod
    def stack(axis: int, *arrays) -> NDArray:
        return NDArray(jnp.stack([_unwrap(a) for a in arrays], axis=axis))

    @staticmethod
    def hstack(*arrays) -> NDArray:
        return NDArray(jnp.hstack([_unwrap(a) for a in arrays]))

    @staticmethod
    def vstack(*arrays) -> NDArray:
        return NDArray(jnp.vstack([_unwrap(a) for a in arrays]))

    @staticmethod
    def where(cond, x, y) -> NDArray:
        return NDArray(jnp.where(_unwrap(cond), _unwrap(x), _unwrap(y)))

    @staticmethod
    def sort(arr, axis=-1, descending=False) -> NDArray:
        out = jnp.sort(_unwrap(arr), axis=axis)
        if descending:
            out = jnp.flip(out, axis=axis)
        return NDArray(out)


    @staticmethod
    def zeros_like(a):
        return NDArray(jnp.zeros_like(_unwrap(a)))

    @staticmethod
    def ones_like(a):
        return NDArray(jnp.ones_like(_unwrap(a)))

    @staticmethod
    def scalar(value):
        return NDArray(jnp.asarray(value))

    @staticmethod
    def empty(dtype=None):
        return NDArray(jnp.zeros(
            (0,), dtypes.resolve(dtype) if dtype is not None
            else dtypes.default_dtype()))

    @staticmethod
    def diag(v):
        return NDArray(jnp.diag(jnp.asarray(_unwrap(v))))

    @staticmethod
    def pile(*arrs):
        """Stack along a new leading axis (reference Nd4j.pile)."""
        return Nd4j.stack(0, *arrs)

    @staticmethod
    def rot90(a, k: int = 1):
        return NDArray(jnp.rot90(jnp.asarray(_unwrap(a)), k))

    @staticmethod
    def pad(a, pad_width, mode="constant", value=0.0):
        kw = {"constant_values": value} if mode == "constant" else {}
        return NDArray(jnp.pad(jnp.asarray(_unwrap(a)), pad_width,
                               mode=mode, **kw))

    @staticmethod
    def shuffle(a, seed=None):
        """Permute rows (reference Nd4j.shuffle; functional here)."""
        arr = jnp.asarray(_unwrap(a))
        perm = jax.random.permutation(_next_key(seed), arr.shape[0])
        return NDArray(arr[perm])

    @staticmethod
    def argsort(a, axis=-1):
        return NDArray(jnp.argsort(jnp.asarray(_unwrap(a)), axis=axis))

    @staticmethod
    def to_flattened(*arrs):
        """Concatenate raveled arrays (reference Nd4j.toFlattened)."""
        return NDArray(jnp.concatenate(
            [jnp.ravel(jnp.asarray(_unwrap(a))) for a in arrs]))


class Transforms:
    """Reference ``org.nd4j.linalg.ops.transforms.Transforms`` — the
    eager math-helper namespace users reach for first."""

    @staticmethod
    def _wrap1(fn, a):
        return NDArray(fn(jnp.asarray(_unwrap(a))))

    sigmoid = staticmethod(lambda a: Transforms._wrap1(jax.nn.sigmoid, a))
    tanh = staticmethod(lambda a: Transforms._wrap1(jnp.tanh, a))
    relu = staticmethod(lambda a: Transforms._wrap1(jax.nn.relu, a))
    leaky_relu = staticmethod(
        lambda a, alpha=0.01: NDArray(jax.nn.leaky_relu(
            jnp.asarray(_unwrap(a)), alpha)))
    softmax = staticmethod(
        lambda a, axis=-1: NDArray(jax.nn.softmax(
            jnp.asarray(_unwrap(a)), axis=axis)))
    exp = staticmethod(lambda a: Transforms._wrap1(jnp.exp, a))
    log = staticmethod(lambda a: Transforms._wrap1(jnp.log, a))
    sqrt = staticmethod(lambda a: Transforms._wrap1(jnp.sqrt, a))
    abs = staticmethod(lambda a: Transforms._wrap1(jnp.abs, a))
    sign = staticmethod(lambda a: Transforms._wrap1(jnp.sign, a))
    floor = staticmethod(lambda a: Transforms._wrap1(jnp.floor, a))
    ceil = staticmethod(lambda a: Transforms._wrap1(jnp.ceil, a))
    round = staticmethod(lambda a: Transforms._wrap1(jnp.round, a))
    sin = staticmethod(lambda a: Transforms._wrap1(jnp.sin, a))
    cos = staticmethod(lambda a: Transforms._wrap1(jnp.cos, a))
    asin = staticmethod(lambda a: Transforms._wrap1(jnp.arcsin, a))
    acos = staticmethod(lambda a: Transforms._wrap1(jnp.arccos, a))
    atan = staticmethod(lambda a: Transforms._wrap1(jnp.arctan, a))
    hard_tanh = staticmethod(
        lambda a: NDArray(jnp.clip(jnp.asarray(_unwrap(a)), -1, 1)))
    soft_plus = staticmethod(
        lambda a: Transforms._wrap1(jax.nn.softplus, a))
    elu = staticmethod(lambda a: Transforms._wrap1(jax.nn.elu, a))

    @staticmethod
    def pow(a, p):
        return NDArray(jnp.power(jnp.asarray(_unwrap(a)), _unwrap(p)))

    @staticmethod
    def max(a, b):
        return NDArray(jnp.maximum(jnp.asarray(_unwrap(a)),
                                   jnp.asarray(_unwrap(b))))

    @staticmethod
    def min(a, b):
        return NDArray(jnp.minimum(jnp.asarray(_unwrap(a)),
                                   jnp.asarray(_unwrap(b))))

    @staticmethod
    def unit_vec(a):
        arr = jnp.asarray(_unwrap(a))
        return NDArray(arr / (jnp.linalg.norm(arr) + 1e-12))

    @staticmethod
    def normalize_zero_mean_and_unit_variance(a):
        arr = jnp.asarray(_unwrap(a))
        return NDArray((arr - jnp.mean(arr, 0)) / (jnp.std(arr, 0)
                                                   + 1e-12))

    @staticmethod
    def cosine_sim(a, b):
        x = jnp.asarray(_unwrap(a)).ravel()
        y = jnp.asarray(_unwrap(b)).ravel()
        return float(jnp.dot(x, y) / (jnp.linalg.norm(x)
                                      * jnp.linalg.norm(y) + 1e-12))

    @staticmethod
    def euclidean_distance(a, b):
        return float(jnp.linalg.norm(jnp.asarray(_unwrap(a)).ravel()
                                     - jnp.asarray(_unwrap(b)).ravel()))

    @staticmethod
    def manhattan_distance(a, b):
        return float(jnp.sum(jnp.abs(
            jnp.asarray(_unwrap(a)).ravel()
            - jnp.asarray(_unwrap(b)).ravel())))

    @staticmethod
    def all_cosine_similarities(a, b):
        """Pairwise cosine similarities between rows of a and b
        (reference allCosineSimilarities)."""
        x = jnp.asarray(_unwrap(a))
        y = jnp.asarray(_unwrap(b))
        xn = x / (jnp.linalg.norm(x, axis=1, keepdims=True) + 1e-12)
        yn = y / (jnp.linalg.norm(y, axis=1, keepdims=True) + 1e-12)
        # analytics helper, not a hot path: full-precision matmul (the
        # TPU default bf16 MXU precision is visible at 1e-4 here)
        return NDArray(jnp.matmul(xn, yn.T,
                                  precision=jax.lax.Precision.HIGHEST))


def _shape(shape) -> tuple:
    if shape is None:
        raise ValueError("shape required")
    if isinstance(shape, int):
        return (shape,)
    return tuple(shape)


# lazily seeded: creating a PRNGKey materialises a device array, and
# importing the library must NEVER initialise a backend (with the axon
# tunnel down, a device touch at import time hangs every consumer)
_GLOBAL_KEY = [None]


def _next_key(seed: Optional[int] = None):
    if seed is not None:
        return jax.random.PRNGKey(seed)
    if _GLOBAL_KEY[0] is None:
        _GLOBAL_KEY[0] = jax.random.PRNGKey(0)
    _GLOBAL_KEY[0], sub = jax.random.split(_GLOBAL_KEY[0])
    return sub
