"""In-process TF graph execution — reference: ``nd4j-tensorflow``
``org.nd4j.tensorflow.conversion.graphrunner.GraphRunner`` (SURVEY
§2.2), which runs real TensorFlow GraphDefs through the TF C API with
casting rules and named feeds/fetches.

TPU-native design: the installed TensorFlow runtime executes the graph
(mirroring the reference's in-process libtensorflow), arrays cross the
boundary zero-copy via numpy. For graphs the importer supports,
``TFImporter`` (tf_import.py) is the faster path — it retraces to JAX
and jits; GraphRunner is the conformance/eval tool that runs the
ORIGINAL graph, e.g. to produce goldens the import path is tested
against.
"""
from __future__ import annotations

from typing import Dict, List, Optional, Sequence

import numpy as np


class GraphRunner:
    """Run a frozen TF GraphDef with named inputs/outputs.

    Reference API mirrored: construct with graph bytes/path + input and
    output op names; ``run({name: array})`` returns ``{name: array}``.
    """

    def __init__(self, graph_def=None, *, path: Optional[str] = None,
                 input_names: Optional[Sequence[str]] = None,
                 output_names: Optional[Sequence[str]] = None,
                 cast_inputs: Optional[Dict[str, str]] = None):
        import tensorflow as tf  # local: heavy dep, only when used
        self._tf = tf
        if graph_def is None:
            if path is None:
                raise ValueError("need graph_def or path")
            graph_def = tf.compat.v1.GraphDef()
            with open(path, "rb") as f:
                graph_def.ParseFromString(f.read())
        elif isinstance(graph_def, (bytes, bytearray)):
            gd = tf.compat.v1.GraphDef()
            gd.ParseFromString(bytes(graph_def))
            graph_def = gd
        self.graph_def = graph_def
        node_names = [n.name for n in graph_def.node]
        self.input_names = list(input_names) if input_names else [
            n.name for n in graph_def.node if n.op == "Placeholder"]
        self.cast_inputs = cast_inputs or {}

        graph = tf.Graph()
        with graph.as_default():
            tf.graph_util.import_graph_def(graph_def, name="")
        self._graph = graph

        if output_names:
            self.output_names = list(output_names)
        else:
            # terminal nodes: consumed by nobody AND producing at least
            # one tensor (frozen graphs often carry NoOp/Assert leaves)
            consumed = {i.split(":")[0].lstrip("^")
                        for n in graph_def.node for i in n.input}
            self.output_names = [
                n for n in node_names
                if n not in consumed
                and graph.get_operation_by_name(n).outputs]

        feeds = [graph.get_tensor_by_name(f"{n}:0")
                 for n in self.input_names]
        self._feed_dtypes = {n: t.dtype.as_numpy_dtype
                             for n, t in zip(self.input_names, feeds)}

        # wrap as a ConcreteFunction once; repeated run() calls are
        # then a single in-process executor invocation (the reference
        # keeps one TF_Session for the same reason)
        self._fn = tf.compat.v1.wrap_function(
            lambda *args: tf.graph_util.import_graph_def(
                graph_def, name="",
                input_map=dict(zip(self.input_names, args)),
                return_elements=[f"{n}:0" for n in self.output_names]),
            [tf.TensorSpec(t.shape, t.dtype) for t in feeds])

    def run(self, inputs: Dict[str, np.ndarray]) -> Dict[str, np.ndarray]:
        args = []
        for n in self.input_names:
            a = np.asarray(inputs[n])
            # reference GraphRunner casting rules: explicit cast map
            # first, else coerce to the placeholder dtype (numpy's
            # float64 default would otherwise fail against f32 graphs)
            a = a.astype(self.cast_inputs.get(n, self._feed_dtypes[n]))
            args.append(self._tf.constant(a))
        outs = self._fn(*args)
        return {n: np.asarray(o) for n, o in zip(self.output_names, outs)}

    # reference API aliases
    def run_list(self, inputs: List[np.ndarray]) -> List[np.ndarray]:
        out = self.run(dict(zip(self.input_names, inputs)))
        return [out[n] for n in self.output_names]
